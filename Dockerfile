# Deploy image, the role of the reference's Dockerfile (reference
# Dockerfile:1-5, which ships a maturin builder + protoc). This image
# builds the C++ control plane, installs the package, and can run any of
# the entry points — the example trainer on CPU JAX by default:
#
#   docker build -t torchft-tpu .
#   docker run torchft-tpu                                    # demo trainer
#   docker run torchft-tpu torchft-tpu-lighthouse --bind [::]:29510
#   docker run torchft-tpu torchft-tpu-launcher --num-replica-groups 2 \
#       -- python examples/train_ddp.py
#
# For real TPU hosts, base on a TPU-enabled JAX image instead and drop
# JAX_PLATFORMS (libtpu discovers the chips).
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make protobuf-compiler libprotobuf-dev \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY . /app

RUN pip install --no-cache-dir "jax[cpu]" optax ml_dtypes \
    && pip install --no-cache-dir -e . -v

ENV JAX_PLATFORMS=cpu NUM_STEPS=30
# One-process demo: in-process lighthouse, single replica group. Multi-group
# deployments run one container per replica group pointed at a shared
# lighthouse via TORCHFT_LIGHTHOUSE (docs/OPERATIONS.md).
CMD ["torchft-tpu-launcher", "--num-replica-groups", "1", \
     "python", "examples/train_ddp.py"]
