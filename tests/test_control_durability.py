"""Durable control plane: write-ahead quorum log, warm-standby root
failover, and the fencing/monotonicity contracts.

Four layers of proof:

1. **Kill-at-every-byte property sweep** (pure): a WAL is authored
   through the same native ``DurableLog`` the live root writes, then
   recovered from EVERY byte-truncation prefix — the recovered
   ``quorum_id`` watermark must be monotone in the prefix length, never
   exceed what was durably appended, and torn tail records must be
   DROPPED (detected by length/CRC), never partially applied.
2. **Seeded ``wal_write`` seam** (live): the PR-11 fault machinery tears
   an append mid-record inside a live lighthouse; the root must freeze
   NEW promises (frozen beats regressed) and a restart must recover
   exactly the pre-tear watermark.
3. **Restart + takeover continuity** (live): leases renewed within TTL
   before a root crash are still live after replay and after a warm
   standby's takeover; explicit departs stay departed; the deposed
   primary fences itself behind the takeover epoch.
4. **Manager-facing failover**: endpoint lists rotate onto the active
   root, and the demoted manager's bounded region re-probe gives up
   after ``region_probe_max`` failures instead of probing forever.
"""

import os
import shutil
import time
from datetime import timedelta

import pytest

from torchft_tpu import _native
from torchft_tpu._native import (
    Lighthouse,
    Manager,
    ManagerClient,
    RegionLighthouse,
    Store,
    WalLog,
    wal_recover,
)

TIMEOUT = timedelta(seconds=20)


def member(replica_id, step=1):
    return {
        "replica_id": replica_id,
        "address": f"addr_{replica_id}",
        "store_address": f"store_{replica_id}",
        "step": step,
        "world_size": 1,
        "shrink_only": False,
        "force_reconfigure": False,
    }


def wal_entry(replica_id, ttl_ms=60000, participating=True, age_ms=0,
              joined_age_ms=0):
    e = {
        "replica_id": replica_id,
        "age_ms": age_ms,
        "ttl_ms": ttl_ms,
        "participating": participating,
    }
    if participating:
        e["joined_age_ms"] = joined_age_ms
        e["member"] = member(replica_id)
    return e


def lease_entry(replica_id, ttl_ms=60000, participating=True):
    return {
        "replica_id": replica_id,
        "ttl_ms": ttl_ms,
        "participating": participating,
        "member": member(replica_id),
    }


def quorum(qid, ids, created_ms=1000):
    return {
        "quorum_id": qid,
        "created_ms": created_ms,
        "participants": [member(i) for i in ids],
    }


def wait_until(pred, deadline_s=10.0, msg="condition"):
    deadline = time.monotonic() + deadline_s
    while True:
        v = pred()
        if v:
            return v
        assert time.monotonic() < deadline, f"timed out waiting for {msg}"
        time.sleep(0.05)


class TestWalRoundTrip:
    def test_records_replay_to_watermark(self, tmp_path):
        d = str(tmp_path / "wal")
        w = WalLog(d)
        w.log_epoch(1)
        w.log_lease([wal_entry("g0"), wal_entry("g1")], unix_ms=5000)
        w.log_quorum(quorum(1, ["g0", "g1"]), quorum_gen=1, root_epoch=1)
        w.log_lease([wal_entry("g0")], unix_ms=5100)
        w.log_depart("g1")
        w.log_quorum(quorum(2, ["g0"]), quorum_gen=2, root_epoch=1)
        w.log_lease([wal_entry("g0")], unix_ms=5200)
        w.close()
        rec = wal_recover(d, 6000, 6000)
        assert rec["replayed"] and rec["records_replayed"] == 7
        assert rec["dropped_tail_bytes"] == 0
        st = rec["state"]
        assert st["quorum_id"] == 2 and rec["root_epoch"] == 1
        assert rec["quorum_gen"] == 2
        # identity rebase at mono == unix: g0's last grant was at 5200
        assert st["heartbeats"]["g0"] == 5200
        # the explicit depart stays departed
        assert "g1" not in st["heartbeats"]
        assert [m["replica_id"] for m in st["prev_quorum"]["participants"]] \
            == ["g0"]
        # quorum replay mirrors quorum_step's participant clear; the later
        # lease record re-registered g0
        assert "g0" in st["participants"]

    def test_snapshot_compacts_and_replays(self, tmp_path):
        d = str(tmp_path / "wal")
        w = WalLog(d)
        w.log_lease([wal_entry("g0")], unix_ms=1000)
        state = {
            "quorum_id": 7,
            "participants": {"g0": {"joined_ms": 900, "member": member("g0")}},
            "heartbeats": {"g0": 1000},
            "lease_ttls": {"g0": 60000},
            "prev_quorum": quorum(7, ["g0"]),
        }
        w.snapshot(state, quorum_gen=5, root_epoch=3, mono_now=1000,
                   unix_now=1000)
        # post-snapshot records replay on top
        w.log_lease([wal_entry("g1")], unix_ms=1200)
        w.close()
        assert os.path.exists(os.path.join(d, "snapshot.json"))
        rec = wal_recover(d, 1300, 1300)
        st = rec["state"]
        assert st["quorum_id"] == 7 and rec["root_epoch"] == 3
        assert rec["quorum_gen"] == 5
        assert st["heartbeats"]["g0"] == 1000
        assert st["heartbeats"]["g1"] == 1200
        assert st["participants"]["g0"]["joined_ms"] == 900
        # only the post-compaction record remains in the log
        assert rec["records_replayed"] == 1

    def test_clock_rebase_across_restart(self, tmp_path):
        # The recovering process's monotonic clock restarted: a lease
        # granted at unix 10_000 must land at (mono_now - elapsed).
        d = str(tmp_path / "wal")
        w = WalLog(d)
        w.log_lease([wal_entry("g0", ttl_ms=5000)], unix_ms=10_000)
        w.close()
        rec = wal_recover(d, 50, 11_000)  # 1s elapsed, fresh mono clock
        assert rec["state"]["heartbeats"]["g0"] == 50 - 1000

    def test_empty_dir_is_cold(self, tmp_path):
        d = str(tmp_path / "nothing")
        rec = wal_recover(d, 0, 0)
        assert not rec["replayed"]
        assert rec["state"]["quorum_id"] == 0 and rec["root_epoch"] == 0


class TestKillAtEveryByte:
    """The scripted kill-at-every-record property: recovery from every
    byte-truncation prefix of the log is (a) crash-free, (b) monotone in
    the prefix (more bytes never recover a SMALLER watermark), and (c)
    exact at record boundaries — a torn tail is dropped, never applied."""

    def test_truncation_sweep_monotone(self, tmp_path):
        d = str(tmp_path / "wal")
        w = WalLog(d)
        logged_qids = [0]
        w.log_epoch(1)
        for qid, ids in ((1, ["g0"]), (2, ["g0", "g1"]), (3, ["g0"])):
            w.log_lease([wal_entry(i) for i in ids], unix_ms=1000 + qid)
            w.log_quorum(quorum(qid, ids), quorum_gen=qid, root_epoch=1)
            logged_qids.append(qid)
        w.log_depart("g1")
        w.close()
        raw = open(os.path.join(d, "wal.log"), "rb").read()
        assert len(raw) > 100

        sweep_dir = str(tmp_path / "sweep")
        prev_qid = -1
        prev_records = -1
        for cut in range(len(raw) + 1):
            shutil.rmtree(sweep_dir, ignore_errors=True)
            os.makedirs(sweep_dir)
            with open(os.path.join(sweep_dir, "wal.log"), "wb") as f:
                f.write(raw[:cut])
            rec = wal_recover(sweep_dir, 2000, 2000)
            qid = rec["state"]["quorum_id"]
            # (a) only promised watermarks ever appear
            assert qid in logged_qids, (cut, qid)
            # (b) monotone in the prefix
            assert qid >= prev_qid, (cut, qid, prev_qid)
            assert rec["records_replayed"] >= prev_records - 7
            # (c) anything after the last whole record is dropped tail
            if cut < len(raw):
                assert rec["dropped_tail_bytes"] >= 0
            prev_qid = qid
            prev_records = rec["records_replayed"]
        # the full log recovers the full watermark
        assert prev_qid == 3

    def test_corrupt_tail_bits_are_dropped_not_applied(self, tmp_path):
        d = str(tmp_path / "wal")
        w = WalLog(d)
        w.log_quorum(quorum(1, ["g0"]), quorum_gen=1, root_epoch=1)
        w.log_quorum(quorum(2, ["g0"]), quorum_gen=2, root_epoch=1)
        w.close()
        path = os.path.join(d, "wal.log")
        raw = bytearray(open(path, "rb").read())
        # flip one payload bit inside the LAST record: its CRC must fail
        # and recovery must fall back to the first record's watermark
        raw[-3] ^= 0x10
        open(path, "wb").write(bytes(raw))
        rec = wal_recover(d, 1000, 1000)
        assert rec["state"]["quorum_id"] == 1
        assert rec["dropped_tail_bytes"] > 0


class TestWalWriteSeam:
    """The PR-11 seeded fault machinery on the new ``wal_write`` seam: a
    torn append inside a LIVE root freezes new promises, and restart
    recovers exactly the pre-tear watermark."""

    def teardown_method(self):
        _native.fault_disarm()

    def test_torn_append_freezes_promises_and_recovers(self, tmp_path):
        d = str(tmp_path / "wal")
        lh = Lighthouse(bind="[::]:0", min_replicas=1, join_timeout_ms=100,
                        wal_dir=d)
        try:
            c = _native.LeaseClient(lh.address())
            c.renew([lease_entry("g0")])
            wait_until(lambda: lh.status_json()["quorum_id"] >= 1,
                       msg="first quorum")
            qid = lh.status_json()["quorum_id"]

            # Arm: the NEXT wal append tears mid-record (crash-mid-write).
            _native.fault_arm({
                "seed": 1,
                "rules": [{"seam": "wal_write", "kind": "truncate",
                           "member": -1, "permille": 1000, "max_fires": 1}],
            })
            # A new member would bump quorum_id — but the promise cannot
            # be made durable, so it must never be published.
            c.renew([lease_entry("g0"), lease_entry("g1")])
            time.sleep(0.5)
            st = lh.status_json()
            assert st["quorum_id"] == qid, "promise published past a torn WAL"
            assert st["wal"]["dead"] is True
            stats = _native.fault_stats()
            assert stats["fired"].get("wal_write:truncate", 0) >= 1
            _native.fault_disarm()
        finally:
            lh.shutdown()
        # Restart: the torn tail is dropped; the watermark is exactly the
        # last PUBLISHED promise.
        lh2 = Lighthouse(bind="[::]:0", min_replicas=1, join_timeout_ms=100,
                         wal_dir=d)
        try:
            st = lh2.status_json()
            assert st["wal_replayed"] is True
            assert st["quorum_id"] == qid
            assert st["wal"]["dropped_tail_bytes"] > 0
        finally:
            lh2.shutdown()


class TestRestartContinuity:
    def test_lease_continuity_and_departs_across_restart(self, tmp_path):
        d = str(tmp_path / "wal")
        lh = Lighthouse(bind="[::]:0", min_replicas=1, join_timeout_ms=100,
                        wal_dir=d)
        c = _native.LeaseClient(lh.address())
        c.renew([lease_entry("gA"), lease_entry("gB"),
                 lease_entry("gC", participating=False)])
        wait_until(lambda: lh.status_json()["quorum_id"] >= 1, msg="quorum")
        c.depart("gB")
        wait_until(
            lambda: all(m["replica_id"] != "gB"
                        for m in lh.status_json()["members"]),
            msg="depart applied",
        )
        qid = lh.status_json()["quorum_id"]
        epoch = lh.root_epoch()
        lh.shutdown()
        del lh, c

        lh2 = Lighthouse(bind="[::]:0", min_replicas=1, join_timeout_ms=100,
                         wal_dir=d)
        try:
            st = lh2.status_json()
            # amnesia stamps: replayed, epoch bumped, watermark intact
            assert st["wal_replayed"] is True
            assert st["root_epoch"] == epoch + 1
            assert st["quorum_id"] == qid
            members = {m["replica_id"]: m for m in st["members"]}
            # renewed-within-TTL members are still LIVE after replay
            assert members["gA"]["lease_remaining_ms"] > 0
            assert members["gC"]["lease_remaining_ms"] > 0
            # the explicit depart stayed departed
            assert "gB" not in members
            # and the root keeps serving: a fresh registration bumps the
            # quorum PAST the replayed watermark, never below it
            c2 = _native.LeaseClient(lh2.address())
            c2.renew([lease_entry("gA"), lease_entry("gD")])
            wait_until(lambda: lh2.status_json()["quorum_id"] > qid,
                       msg="post-replay quorum")
        finally:
            lh2.shutdown()

    def test_fresh_wal_root_is_cold_not_amnesiac(self, tmp_path):
        lh = Lighthouse(bind="[::]:0", min_replicas=1, join_timeout_ms=100,
                        wal_dir=str(tmp_path / "fresh"))
        try:
            st = lh.status_json()
            assert st["wal_enabled"] is True
            assert st["wal_replayed"] is False  # cold, nothing to remember
            assert st["root_epoch"] == 1
        finally:
            lh.shutdown()

    def test_non_wal_root_stamps(self):
        lh = Lighthouse(bind="[::]:0", min_replicas=1, join_timeout_ms=100)
        try:
            st = lh.status_json()
            assert st["wal_enabled"] is False
            assert st["wal_replayed"] is False
            assert "wal" not in st
            assert st["active"] is True
        finally:
            lh.shutdown()


class TestStandbyTakeover:
    def test_takeover_preserves_watermark_and_leases(self, tmp_path):
        dp, ds = str(tmp_path / "p"), str(tmp_path / "s")
        primary = Lighthouse(bind="[::]:0", min_replicas=1,
                             join_timeout_ms=100, wal_dir=dp)
        paddr = primary.address()
        standby = Lighthouse(bind="[::]:0", min_replicas=1,
                             join_timeout_ms=100, wal_dir=ds, peers=paddr,
                             standby=True, takeover_ms=800)
        saddr = standby.address()
        try:
            assert primary.active() and not standby.active()
            assert standby.status_json()["role"] == "standby"

            c = _native.LeaseClient(paddr)
            c.renew([lease_entry("gA"), lease_entry("gB")])
            wait_until(lambda: primary.status_json()["quorum_id"] >= 1,
                       msg="primary quorum")
            qid = primary.status_json()["quorum_id"]
            # The commit was PUSH-replicated: the standby holds the
            # watermark synchronously, not a sync interval later.
            wait_until(lambda: standby.status_json()["quorum_id"] >= qid,
                       deadline_s=3, msg="standby mirror")

            primary.shutdown()
            wait_until(standby.active, msg="takeover")
            st = standby.status_json()
            assert st["quorum_id"] >= qid  # never regresses across epochs
            assert st["root_epoch"] == 2
            members = {m["replica_id"]: m for m in st["members"]}
            # lease continuity across the takeover
            assert members["gA"]["lease_remaining_ms"] > 0
            assert members["gB"]["lease_remaining_ms"] > 0

            # the new active root actually serves: quorum advances there
            c2 = _native.LeaseClient(saddr)
            c2.renew([lease_entry("gA"), lease_entry("gB"),
                      lease_entry("gC")])
            wait_until(lambda: standby.status_json()["quorum_id"] > qid,
                       msg="post-takeover quorum")
        finally:
            standby.shutdown()
            primary.shutdown()

    def test_deposed_primary_fences_on_restart(self, tmp_path):
        dp, ds = str(tmp_path / "p"), str(tmp_path / "s")
        primary = Lighthouse(bind="[::]:0", min_replicas=1,
                             join_timeout_ms=100, wal_dir=dp)
        pport = primary.address().rsplit(":", 1)[1]
        standby = Lighthouse(bind="[::]:0", min_replicas=1,
                             join_timeout_ms=100, wal_dir=ds,
                             peers=primary.address(), standby=True,
                             takeover_ms=800)
        try:
            c = _native.LeaseClient(primary.address())
            c.renew([lease_entry("gA")])
            wait_until(lambda: primary.status_json()["quorum_id"] >= 1,
                       msg="quorum")
            primary.shutdown()
            wait_until(standby.active, msg="takeover")
            takeover_epoch = standby.root_epoch()

            # the deposed incarnation returns on its own WAL: it must
            # find the higher-epoch active peer and start FENCED
            p2 = Lighthouse(bind=f"[::]:{pport}", min_replicas=1,
                            join_timeout_ms=100, wal_dir=dp,
                            peers=standby.address())
            try:
                assert not p2.active()
                assert p2.root_epoch() < takeover_epoch
                assert p2.status_json()["role"] == "standby"
                # and it now TAILS the new active root (watermark flows)
                c2 = _native.LeaseClient(standby.address())
                c2.renew([lease_entry("gA"), lease_entry("gNew")])
                wait_until(
                    lambda: p2.status_json()["quorum_id"]
                    >= standby.status_json()["quorum_id"],
                    msg="fenced primary mirrors the new active",
                )
            finally:
                p2.shutdown()
        finally:
            standby.shutdown()
            primary.shutdown()


class TestEpochCollisionTieBreak:
    def test_two_equal_epoch_actives_resolve_to_one(self, tmp_path):
        # The collided-claim case: two roots activate at the SAME epoch
        # (here: both start unflagged, each probing before the other is
        # active — the restarted-primary-during-standby-partition race
        # in miniature). Strictly-greater epoch fencing alone would
        # leave BOTH active forever; the per-claim nonce tie-break must
        # demote exactly one within a probe round.
        # In-process Lighthouses can't be mutually peered (peers are ctor
        # state and ephemeral ports are unknown until bound), so use the
        # fixed-port subprocess roots.
        from torchft_tpu.chaos import RootProcess, free_port

        ports = [free_port(), free_port()]
        addrs = [f"http://localhost:{p}" for p in ports]
        # BOTH unflagged: each starts, probes the other (not yet serving
        # or serving-inactive), and claims epoch 1 — the collision.
        r0 = RootProcess(ports[0], wal_dir=str(tmp_path / "p0"),
                         peers=addrs[1], takeover_ms=600)
        r1 = RootProcess(ports[1], wal_dir=str(tmp_path / "p1"),
                         peers=addrs[0], takeover_ms=600)
        try:
            r0.wait_serving()
            r1.wait_serving()

            def exactly_one_active():
                st0, st1 = r0.status(), r1.status()
                if st0 is None or st1 is None:
                    return False
                return (st0["active"] + st1["active"]) == 1

            # within a fence-probe round (<= max(500, takeover/2) + rpc)
            wait_until(exactly_one_active, deadline_s=15,
                       msg="nonce tie-break to a single active root")
            # and it STAYS resolved (no demote flapping)
            time.sleep(1.5)
            assert exactly_one_active()
        finally:
            r0.stop()
            r1.stop()


class TestStallSelfFence:
    """The stalled-not-dead primary (SIGSTOP past the takeover bound):
    the standby takes over; the RESUMED primary must detect its own tick
    stall, probe peers BEFORE serving again, and fence itself behind the
    takeover epoch — the split-brain path clean kills never exercise.
    Needs subprocess roots (SIGSTOP of an in-process lighthouse would
    stop the test runner with it)."""

    def test_resumed_primary_fences(self, tmp_path):
        from torchft_tpu.chaos import RootProcess, free_port

        ports = [free_port(), free_port()]
        addrs = [f"http://localhost:{p}" for p in ports]
        primary = RootProcess(
            ports[0], wal_dir=str(tmp_path / "p"), peers=addrs[1],
            takeover_ms=800,
        )
        standby = RootProcess(
            ports[1], wal_dir=str(tmp_path / "s"), peers=addrs[0],
            standby=True, takeover_ms=800,
        )
        try:
            primary.wait_serving()
            standby.wait_serving()
            stall = primary.partition(3.0)  # ~4x the takeover bound
            wait_until(
                lambda: (standby.status() or {}).get("active", False),
                deadline_s=15,
                msg="takeover during the stall",
            )
            stall.join()
            # the resumed primary must end up PASSIVE at a lower epoch
            def fenced():
                st = primary.status()
                return st is not None and not st.get("active", True)

            wait_until(fenced, deadline_s=15, msg="resumed-primary fence")
            pst, sst = primary.status(), standby.status()
            assert sst["active"] and sst["root_epoch"] > pst["root_epoch"]
            assert pst["role"] == "standby"
        finally:
            primary.stop()
            standby.stop()


class TestManagerEndpointList:
    def test_manager_rotates_past_standby_to_active(self, tmp_path):
        # The endpoint list leads with the STANDBY: the manager must
        # rotate onto the active root and form a quorum anyway.
        primary = Lighthouse(bind="[::]:0", min_replicas=1,
                             join_timeout_ms=200)
        standby = Lighthouse(bind="[::]:0", min_replicas=1,
                             join_timeout_ms=200, peers=primary.address(),
                             standby=True, takeover_ms=60000)
        store = Store()
        m = Manager(
            "repL", f"{standby.address()},{primary.address()}", "localhost",
            "[::]:0", store.address(), 1,
            heartbeat_interval=timedelta(milliseconds=50),
        )
        client = ManagerClient(m.address())
        try:
            res = client.quorum(0, 1, "ck", timeout=TIMEOUT)
            assert res.replica_world_size == 1
            assert res.quorum_id >= 1
        finally:
            m.shutdown()
            standby.shutdown()
            primary.shutdown()
            store.shutdown()

    def test_region_tier_follows_takeover(self, tmp_path):
        # Region tier pointed at the (primary, standby) list: after the
        # primary dies and the standby takes over, digests/polls drift to
        # the standby and quorums keep forming through the region.
        dp, ds = str(tmp_path / "p"), str(tmp_path / "s")
        primary = Lighthouse(bind="[::]:0", min_replicas=1,
                             join_timeout_ms=200, wal_dir=dp)
        standby = Lighthouse(bind="[::]:0", min_replicas=1,
                             join_timeout_ms=200, wal_dir=ds,
                             peers=primary.address(), standby=True,
                             takeover_ms=800)
        roots = f"{primary.address()},{standby.address()}"
        ra = RegionLighthouse(roots, "ra", digest_interval_ms=50)
        try:
            c = _native.LeaseClient(ra.address())
            c.renew([lease_entry("g0")])
            wait_until(lambda: ra.status_json()["quorum_id"] >= 1,
                       msg="quorum via region")
            qid = ra.status_json()["quorum_id"]

            primary.shutdown()
            wait_until(standby.active, msg="takeover")
            # a NEW member must reach a quorum through region -> standby
            deadline = time.monotonic() + 20
            while True:
                c.renew([lease_entry("g0"), lease_entry("g1")])
                st = ra.status_json()
                q = st.get("quorum") or {}
                ids = [p["replica_id"] for p in q.get("participants", [])]
                if "g1" in ids:
                    break
                assert time.monotonic() < deadline, st
                time.sleep(0.1)
            assert st["quorum_id"] >= qid
        finally:
            ra.shutdown()
            standby.shutdown()
            primary.shutdown()


class TestRegionProbeGiveUp:
    def _wait(self, pred, deadline_s, msg):
        wait_until(pred, deadline_s, msg)

    def test_bounded_give_up_stops_probing(self):
        root = Lighthouse(min_replicas=1, join_timeout_ms=200)
        ra = RegionLighthouse(root.address(), "ra", digest_interval_ms=50)
        ra_port = int(ra.address().rsplit(":", 1)[1])
        store = Store()
        m = Manager(
            "repG", ra.address(), "localhost", "[::]:0", store.address(), 1,
            heartbeat_interval=timedelta(milliseconds=50),
            root_addr=root.address(),
            lease_ttl=timedelta(milliseconds=300),
            region_probe_max=3,
        )
        try:
            assert not m.region_probe_given_up()
            ra.shutdown()
            self._wait(m.using_root_fallback, 10, "demotion")
            # 3 probes at one per 300 ms TTL -> given up within ~2 s
            self._wait(m.region_probe_given_up, 15, "probe give-up")
            # region returns on the SAME port: the manager must NOT drift
            # back — it stopped probing for good
            ra2 = RegionLighthouse(
                root.address(), "ra", bind=f"[::]:{ra_port}",
                digest_interval_ms=50,
            )
            try:
                time.sleep(1.5)  # several TTLs of would-be probes
                assert m.using_root_fallback()
                assert m.region_probe_given_up()
            finally:
                ra2.shutdown()
        finally:
            m.shutdown()
            root.shutdown()
            store.shutdown()

    def test_probe_max_zero_probes_forever(self):
        # The pre-bound behavior stays available: probe_max=0 keeps
        # probing and the revived region wins the manager back.
        root = Lighthouse(min_replicas=1, join_timeout_ms=200)
        ra = RegionLighthouse(root.address(), "ra", digest_interval_ms=50)
        ra_port = int(ra.address().rsplit(":", 1)[1])
        store = Store()
        m = Manager(
            "repF", ra.address(), "localhost", "[::]:0", store.address(), 1,
            heartbeat_interval=timedelta(milliseconds=50),
            root_addr=root.address(),
            lease_ttl=timedelta(milliseconds=300),
            region_probe_max=0,
        )
        try:
            ra.shutdown()
            self._wait(m.using_root_fallback, 10, "demotion")
            time.sleep(1.5)  # more failed probes than any small bound
            assert not m.region_probe_given_up()
            ra2 = RegionLighthouse(
                root.address(), "ra", bind=f"[::]:{ra_port}",
                digest_interval_ms=50,
            )
            try:
                self._wait(lambda: not m.using_root_fallback(), 10,
                           "drift back")
            finally:
                ra2.shutdown()
        finally:
            m.shutdown()
            root.shutdown()
            store.shutdown()
