"""Mixture-of-Experts transformer: the expert-parallel (EP) model family.

TPU-first MoE in the GShard/Switch mold — everything is static-shaped and
einsum-dispatched so XLA can tile it onto the MXU and insert the
all-to-alls from sharding annotations alone:

- router: top-k gating over ``n_experts`` with a capacity cap per expert
  (tokens over capacity are dropped — their combine weight is zero — the
  standard static-shape TPU trade),
- dispatch/combine are dense one-hot einsums (no gather/scatter, no
  dynamic shapes),
- expert weights are stacked ``(E, ...)`` and sharded over an ``expert``
  mesh axis (P("expert", ...)); the dispatched activations are
  sharding-constrained to the same axis, so GSPMD materializes the
  token->expert all-to-all over ICI — no hand-written collectives,
- the load-balance auxiliary loss (mean gate fraction x mean routed
  fraction, scaled by E) keeps routing from collapsing.

Composes with the rest of the parallel stack: the ``expert`` axis lives
inside a replica group's slice mesh next to ``data``/``model`` axes, and
the cross-replica-group fault-tolerance dimension stays host-side exactly
as for the dense flagship (SURVEY.md §2.3: the intra-group mesh is opaque
to the FT layer — reference process_group.py:1310-1341 leaves intra-group
dims to the user; here EP is a first-class intra-group option).

The reference has no MoE/EP anywhere (SURVEY.md §2.3 "EP: absent").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .transformer import (
    TransformerConfig,
    _attention,
    _dense_init,
    _rmsnorm,
    attn_sublayer_init,
    attn_sublayer_specs,
    backbone_init,
    backbone_specs,
    embed_tokens,
    mlp_apply,
    mlp_init,
    mlp_specs,
    next_token_loss,
    readout,
    remat_wrap,
)


@dataclass(frozen=True)
class MoEConfig(TransformerConfig):
    n_experts: int = 8
    router_k: int = 2          # experts per token
    capacity_factor: float = 1.25
    aux_coef: float = 1e-2     # load-balance loss weight
    # every block's MLP is an MoE layer when True; else alternate blocks
    # (dense, moe, dense, ...) like most production MoE stacks
    moe_every_block: bool = False

    def capacity(self, n_tokens: int) -> int:
        cap = int(self.capacity_factor * self.router_k * n_tokens
                  / self.n_experts)
        return max(1, min(cap, n_tokens))

    def is_moe_block(self, i: int) -> bool:
        return self.moe_every_block or (i % 2 == 1)


def tiny_moe_config() -> MoEConfig:
    return MoEConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=128, n_experts=4, router_k=2,
    )


def init_params(cfg: MoEConfig, key: jax.Array) -> Dict[str, Any]:
    """Same skeleton as the dense flagship; MoE blocks carry stacked
    expert weights + a router instead of a single MLP."""
    keys = jax.random.split(key, 2 + cfg.n_layers)
    scale = cfg.d_model ** -0.5

    blocks = []
    for i in range(cfg.n_layers):
        bk = jax.random.split(keys[2 + i], 5)
        block = attn_sublayer_init(cfg, bk[0], bk[1])
        if cfg.is_moe_block(i):
            block["moe"] = {
                "router": _dense_init(
                    bk[4], (cfg.d_model, cfg.n_experts), scale
                ),
                "wi": _dense_init(
                    bk[2], (cfg.n_experts, cfg.d_model, cfg.d_ff), scale
                ),
                "wo": _dense_init(
                    bk[3], (cfg.n_experts, cfg.d_ff, cfg.d_model),
                    cfg.d_ff ** -0.5,
                ),
            }
        else:
            block["mlp"] = mlp_init(cfg, bk[2], bk[3])
        blocks.append(block)
    params = backbone_init(cfg, keys[0], keys[1])
    params["blocks"] = blocks
    return params


def param_sharding_rules(cfg: MoEConfig) -> Dict[str, Any]:
    """Experts over the ``expert`` axis, their inner dims over ``model``
    (EP x TP); dense layers Megatron-style as in the flagship."""
    blocks = []
    for i in range(cfg.n_layers):
        block = attn_sublayer_specs()
        if cfg.is_moe_block(i):
            block["moe"] = {
                "router": P(),
                "wi": P("expert", None, "model"),
                "wo": P("expert", "model", None),
            }
        else:
            block["mlp"] = mlp_specs()
        blocks.append(block)
    rules = backbone_specs()
    rules["blocks"] = blocks
    return rules


def _constraint(x: jax.Array, cfg: MoEConfig, spec: P) -> jax.Array:
    # cp_mesh doubles as the EP mesh, but it may be a CP/TP-only mesh
    # (flash/ring attention) with no "expert" axis — then EP constraints
    # are skipped and the experts stay replicated.
    if cfg.cp_mesh is not None and all(
        ax is None or ax in cfg.cp_mesh.axis_names for ax in spec
    ):
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(cfg.cp_mesh, spec)
        )
    return x


def moe_layer(
    cfg: MoEConfig, p: Dict[str, Any], x: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Top-k routed expert MLP.

    Args:
        x: (B, S, D) activations.
    Returns:
        ((B, S, D) output, scalar load-balance aux loss).
    """
    B, S, D = x.shape
    N = B * S
    E, K = cfg.n_experts, cfg.router_k
    C = cfg.capacity(N)
    tokens = x.reshape(N, D)

    # Router in f32 for a stable softmax.
    logits = (tokens.astype(jnp.float32)
              @ p["router"].astype(jnp.float32))  # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, K)  # (N, K)

    # Position of each (token, k) routing choice within its expert's
    # capacity buffer: running count of earlier claims on that expert.
    # one_hot: (N, K, E); claims are ordered token-major then k.
    one_hot = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)
    flat = one_hot.reshape(N * K, E)
    pos = (jnp.cumsum(flat, axis=0) - flat).reshape(N, K, E)
    pos_in_expert = jnp.sum(pos * one_hot, axis=-1)  # (N, K)
    keep = pos_in_expert < C  # over-capacity claims dropped

    # Renormalize the kept gates so each token's weights sum to 1.
    gates = gate_vals * keep
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)

    # combine[n, e, c] = gate weight of token n in slot c of expert e
    slot_oh = jax.nn.one_hot(
        pos_in_expert.astype(jnp.int32), C, dtype=jnp.float32
    ) * keep[..., None]  # (N, K, C)
    combine = jnp.einsum("nke,nkc->nec", one_hot * gates[..., None], slot_oh)
    dispatch = jnp.einsum(
        "nke,nkc->nec", one_hot, slot_oh
    )  # 0/1 dispatch mask

    # Token -> expert all-to-all: the dispatched activations are
    # constrained onto the expert axis; GSPMD inserts the collective.
    xe = jnp.einsum(
        "nec,nd->ecd", dispatch.astype(cfg.dtype), tokens.astype(cfg.dtype)
    )
    xe = _constraint(xe, cfg, P("expert", None, None))
    h = jnp.einsum("ecd,edf->ecf", xe, p["wi"].astype(cfg.dtype))
    h = jax.nn.gelu(h)
    ye = jnp.einsum("ecf,efd->ecd", h, p["wo"].astype(cfg.dtype))
    ye = _constraint(ye, cfg, P("expert", None, None))
    out = jnp.einsum("nec,ecd->nd", combine.astype(cfg.dtype), ye)

    # Switch-style load balance: E * sum_e (token fraction routed to e) *
    # (mean router prob of e); minimized by the uniform router.
    frac_routed = jnp.mean(one_hot[:, 0, :], axis=0)  # top-1 assignment
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac_routed * mean_prob)
    return out.reshape(B, S, D).astype(x.dtype), aux


def _block(
    cfg: MoEConfig, i: int, p: Dict[str, Any], x: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    x = x + _attention(cfg, p["attn"], _rmsnorm(x, p["ln1"]["scale"]))
    h = _rmsnorm(x, p["ln2"]["scale"])
    if cfg.is_moe_block(i):
        y, aux = moe_layer(cfg, p["moe"], h)
        return x + y, aux
    return x + mlp_apply(cfg, p["mlp"], h), jnp.float32(0.0)


def forward(
    cfg: MoEConfig, params: Dict[str, Any], tokens: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """tokens (B, S) int32 -> (logits (B, S, vocab) f32, aux loss)."""
    x = embed_tokens(cfg, params, tokens)
    aux_total = jnp.float32(0.0)
    block = remat_wrap(cfg, _block, static_argnums=(0, 1))
    for i, p in enumerate(params["blocks"]):
        x, aux = block(cfg, i, p, x)
        aux_total = aux_total + aux
    return readout(cfg, params, x), aux_total


def loss_fn(
    cfg: MoEConfig, params: Dict[str, Any], tokens: jax.Array
) -> jax.Array:
    """Next-token cross entropy + load-balance aux."""
    logits, aux = forward(cfg, params, tokens[:, :-1])
    return next_token_loss(logits, tokens[:, 1:]) + cfg.aux_coef * aux
