"""Durable checkpoints v2: asynchronous, sharded, wire-compressed
snapshots behind a WAL-fenced manifest — and no-donor fleet restore.

The v1 tier was a synchronous, full-state, per-member local pickle: the
trainer stalled for the whole d2h + serialize + fsync while every member
redundantly wrote W copies, and a whole-fleet preemption left nothing a
cold fleet could heal from unless every member's local disk survived.
v2 rebuilds the tier around four ideas:

**Zero-stall capture.** At the commit boundary (a ``Manager`` commit
hook, or an explicit :meth:`DurableCheckpointer.maybe_save`) the state
dict is captured into a :class:`~.checkpointing._StreamStaging` in
snapshot mode: async d2h dispatched for every leaf up front, every
captured buffer owning its bytes (the donation/aliasing guard — the
writer reads the staging while the trainer runs steps N+1..N+k), and
opt-state downcast to bf16 on the wire under the protect-params
discipline (params always raw). The trainer pays ONLY this capture;
serialize + CRC + write + fsync happen on a background writer thread.

**1/W sharded writes.** The packed stream splits into W contiguous byte
ranges — the same floor split the streamed-heal range readers use — and
the member with participating rank r durably writes only bytes
``[total*r/W, total*(r+1)/W)`` plus a tiny marker carrying its range CRC.
Per-member durable bytes scale as 1/W instead of W redundant copies.

**WAL-fenced manifest.** A snapshot becomes restorable only when a
``commit`` record lands in the manifest log — an append-only,
CRC32C-framed log with the PR-13 ``DurableLog`` replay discipline (a
torn tail is dropped, never trusted). Rank 0 appends the commit record
only after ALL W shard markers are durably present and mutually
consistent, so a torn or partially-written snapshot set can never win a
restore. Quorum changes mid-snapshot abort the in-flight set.

**No-donor restore.** :meth:`DurableCheckpointer.restore_latest` replays
the manifest, takes the newest committed snapshot whose objects verify,
parallel range-fetches the W_old shards into one preallocated buffer
(per-shard CRC checked against the manifest), and rebuilds the full tree
via :func:`~.checkpointing.rebuild_from_packed`. Every member rebuilds
the FULL state, so restore works across a different fleet width
(W_new != W_old) — sharded-optimizer engines re-shard on the next quorum
exactly as after any membership change. Restore precedence in a running
fleet is live donor first (the streamed heal), durable tier only when no
donor holds the state.

Storage is pluggable behind :class:`CheckpointStore`
(:class:`LocalDirStore` default — point it at the shared durable mount;
an S3/GCS backend drops in by implementing the same ABC).

Knobs (see docs/OPERATIONS.md "Durable checkpointing"):
``TORCHFT_DURABLE_EVERY``, ``TORCHFT_DURABLE_WIRE``,
``TORCHFT_DURABLE_MODE``, ``TORCHFT_DURABLE_STORE``,
``TORCHFT_DURABLE_STAGING_MB``, ``TORCHFT_DURABLE_COMMIT_TIMEOUT_S``.
"""

from __future__ import annotations

import io
import json
import logging
import os
import queue
import struct
import threading
import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ._native import crc32c as _crc32c
from .checkpointing import (
    _StreamStaging,
    deserialize_state_dict,
    load_packed_meta,
    rebuild_from_packed,
    serialize_state_dict,
)

logger = logging.getLogger(__name__)

MANIFEST_NAME = "MANIFEST.log"
_SNAP_PREFIX = "snap/"
# [u32 payload_len][u32 crc32c(payload)] — the DurableLog frame shape.
_FRAME = struct.Struct("<II")


def shard_bounds(total: int, world: int) -> List[int]:
    """The W+1 byte boundaries splitting a packed stream into W
    contiguous shards — the same floor split (``total*i//W``) the
    streamed-heal range readers tile a donor stream with, so shard r of
    a snapshot is byte-identical to range r/W of a live heal."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    return [total * i // world for i in range(world + 1)]


# ---------------------------------------------------------------------------
# storage backends


class CheckpointStore(ABC):
    """Durable object storage for snapshots and the manifest log.

    Implementations must make :meth:`put` atomic-and-durable (a name is
    either absent or holds the complete fsynced bytes — presence implies
    durability) and :meth:`append` durable before returning. Names are
    ``/``-separated keys. The default local-directory backend is
    :class:`LocalDirStore`; an object store (S3/GCS) drops in by
    implementing this ABC — ``append`` may be emulated with versioned
    record objects as long as replay order is preserved."""

    @abstractmethod
    def put(self, name: str, data: bytes) -> None:
        """Atomically publishes ``data`` under ``name`` (fsynced)."""

    def put_from(self, name: str, write_fn: Callable[[Any], None]) -> int:
        """Streams a writer callback into ``name`` (atomic, fsynced).
        Returns the byte count. Default buffers through memory; backends
        with real streaming override."""
        buf = io.BytesIO()
        write_fn(buf)
        data = buf.getvalue()
        self.put(name, data)
        return len(data)

    @abstractmethod
    def get(self, name: str) -> bytes:
        """Reads the full object (KeyError/OSError when absent)."""

    @abstractmethod
    def read_range(self, name: str, offset: int, nbytes: int) -> bytes:
        """Reads ``nbytes`` starting at ``offset`` of the object."""

    @abstractmethod
    def append(self, name: str, data: bytes) -> None:
        """Durably appends ``data`` to the (possibly absent) object."""

    @abstractmethod
    def list(self, prefix: str) -> List[str]:
        """All object names under ``prefix`` (sorted)."""

    @abstractmethod
    def delete(self, name: str) -> None:
        """Removes an object (no-op when absent)."""

    @abstractmethod
    def exists(self, name: str) -> bool:
        """True when ``name`` holds a published object."""

    def delete_prefix(self, prefix: str) -> None:
        for name in self.list(prefix):
            self.delete(name)


class LocalDirStore(CheckpointStore):
    """Filesystem-backed store rooted at a directory (point it at the
    shared durable mount so every member and any future cold fleet see
    one namespace). ``put`` is tmp + fsync + atomic rename + directory
    fsync; ``append`` is O_APPEND + fsync — the exact publish discipline
    the control-plane WAL uses."""

    def __init__(self, root: str) -> None:
        self._root = os.path.abspath(root)
        os.makedirs(self._root, exist_ok=True)

    @property
    def root(self) -> str:
        return self._root

    def _path(self, name: str) -> str:
        parts = [p for p in name.split("/") if p]
        if not parts or any(p in ("..", ".") for p in parts):
            raise ValueError(f"bad store name: {name!r}")
        return os.path.join(self._root, *parts)

    @staticmethod
    def _fsync_dir(path: str) -> None:
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:  # pragma: no cover - platform without dir fds
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def put(self, name: str, data: bytes) -> None:
        self.put_from(name, lambda f: f.write(data))

    def put_from(self, name: str, write_fn: Callable[[Any], None]) -> int:
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                write_fn(f)
                f.flush()
                os.fsync(f.fileno())
                size = f.tell()
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        # Rename durability: the new directory entry must itself survive
        # a crash, or a committed manifest could reference a shard whose
        # name vanished with the dirent.
        self._fsync_dir(os.path.dirname(path))
        return size

    def get(self, name: str) -> bytes:
        with open(self._path(name), "rb") as f:
            return f.read()

    def read_range(self, name: str, offset: int, nbytes: int) -> bytes:
        with open(self._path(name), "rb") as f:
            f.seek(offset)
            return f.read(nbytes)

    def append(self, name: str, data: bytes) -> None:
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "ab") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    def list(self, prefix: str) -> List[str]:
        out: List[str] = []
        for dirpath, _, files in os.walk(self._root):
            rel = os.path.relpath(dirpath, self._root)
            for fname in files:
                if fname.endswith(".tmp") or ".tmp." in fname:
                    continue
                name = fname if rel == "." else f"{rel}/{fname}".replace(
                    os.sep, "/"
                )
                if name.startswith(prefix):
                    out.append(name)
        return sorted(out)

    def delete(self, name: str) -> None:
        path = self._path(name)
        try:
            os.unlink(path)
        except FileNotFoundError:
            return
        # prune now-empty parents up to (not including) the root
        d = os.path.dirname(path)
        while d != self._root:
            try:
                os.rmdir(d)
            except OSError:
                break
            d = os.path.dirname(d)

    def exists(self, name: str) -> bool:
        return os.path.exists(self._path(name))


def store_from_env(default_dir: str) -> CheckpointStore:
    """Resolves the durable store backend: ``TORCHFT_DURABLE_STORE``
    (``file:/path`` or a bare path) when set, else a
    :class:`LocalDirStore` at ``default_dir``."""
    spec = os.environ.get("TORCHFT_DURABLE_STORE", "").strip()
    if not spec:
        return LocalDirStore(default_dir)
    if spec.startswith("file:"):
        return LocalDirStore(spec[len("file:"):])
    if "://" in spec or ":" in spec.split("/", 1)[0]:
        raise ValueError(f"unsupported TORCHFT_DURABLE_STORE: {spec!r}")
    return LocalDirStore(spec)


# ---------------------------------------------------------------------------
# manifest log


class ManifestLog:
    """Append-only CRC32C-framed record log over a store object — the
    DurableLog frame/replay discipline applied to snapshot publication.
    Each record is ``[u32 len][u32 crc32c(json)]json``; replay walks
    frames and DROPS the tail at the first short or corrupt frame (a
    crash mid-append, or the chaos truncate seam, can tear at any byte —
    a torn record never yields a committed snapshot). Compaction
    rewrites the log atomically through :meth:`CheckpointStore.put` with
    only live records, so a crash mid-compaction leaves either the old
    or the new log, both valid."""

    def __init__(self, store: CheckpointStore, name: str = MANIFEST_NAME):
        self._store = store
        self._name = name
        self._lock = threading.Lock()

    @staticmethod
    def frame(record: Dict[str, Any]) -> bytes:
        payload = json.dumps(
            record, separators=(",", ":"), sort_keys=True
        ).encode()
        return _FRAME.pack(len(payload), _crc32c(payload)) + payload

    def append(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._store.append(self._name, self.frame(record))

    def replay(self) -> Tuple[List[Dict[str, Any]], int]:
        """All intact records in append order, plus the dropped torn-tail
        byte count (0 on a clean log)."""
        try:
            raw = (
                self._store.get(self._name)
                if self._store.exists(self._name)
                else b""
            )
        except OSError:
            raw = b""
        records: List[Dict[str, Any]] = []
        pos = 0
        while pos + _FRAME.size <= len(raw):
            ln, want = _FRAME.unpack_from(raw, pos)
            begin = pos + _FRAME.size
            if begin + ln > len(raw):
                break  # torn: frame promised more bytes than exist
            payload = raw[begin:begin + ln]
            if _crc32c(payload) != want:
                break  # torn or corrupt: nothing after it is trusted
            try:
                rec = json.loads(payload)
            except ValueError:
                break
            records.append(rec)
            pos = begin + ln
        return records, len(raw) - pos

    def compact(self, live: List[Dict[str, Any]]) -> None:
        with self._lock:
            self._store.put(
                self._name, b"".join(self.frame(r) for r in live)
            )


# ---------------------------------------------------------------------------
# snapshots


@dataclass
class _Snapshot:
    """One in-flight capture: the staged bytes plus everything the
    writer and committer need. ``abort`` flips when the quorum moved
    mid-flight (the set can no longer complete: W changed under it)."""

    step: int
    quorum_id: int
    rank: int
    world: int
    staging: _StreamStaging
    local_state: Optional[bytes]  # per-member blob (loader position)
    replica_id: str
    stats: Dict[str, Any]
    abort: threading.Event = field(default_factory=threading.Event)
    done: threading.Event = field(default_factory=threading.Event)

    @property
    def directory(self) -> str:
        return snapshot_dir(self.step, self.quorum_id, self.world)


def snapshot_dir(step: int, quorum_id: int, world: int) -> str:
    return (
        f"{_SNAP_PREFIX}step{step:08d}_q{max(quorum_id, 0):08d}"
        f"_w{world:04d}"
    )


def _member_id(replica_id: str) -> str:
    """Stable per-member identity for local-state blobs. The native
    Manager suffixes the configured replica id with a per-session UUID
    (``repA:3f2c...``) — that suffix changes on every restart, so the
    durable name must key on the stable prefix or a restarted member
    could never find its own loader position."""
    stable = str(replica_id).split(":", 1)[0]
    return "".join(
        c if c.isalnum() or c in "._-" else "_" for c in stable
    ) or "member"


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    return float(raw) if raw else default


def inconsistent_marker(
    markers: Dict[int, Dict[str, Any]],
    *,
    step: int,
    quorum_id: int,
    world: int,
    total: int,
    wire: str,
) -> Optional[Tuple[int, Optional[Dict[str, Any]]]]:
    """The commit fence's consistency predicate, extracted pure (PR-7
    pattern): all W shard markers must be present and agree with the
    snapshot's identity before a commit record may be appended.  Returns
    the first offending ``(rank, marker_or_None)`` or ``None`` when the
    set is commit-eligible.  graftcheck's ``durable`` model verifies the
    fence; the conformance suite pins this exact predicate to it."""
    for r in range(world):
        m = markers.get(r)
        if m is None:
            return (r, None)
        ok = (
            m.get("step") == step
            and m.get("quorum_id") == quorum_id
            and m.get("world") == world
            and m.get("total") == total
            and m.get("wire") == wire
            and m.get("rank") == r
        )
        if not ok:
            return (r, m)
    return None


def live_commits(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Committed, non-retired manifest records in commit order — the
    restorable candidates.  Pure: shared by the committer's retention
    pass and the no-donor restore so both see the same live set, and by
    the graftcheck conformance suite."""
    retired = {r["dir"] for r in records if r.get("t") == "retire"}
    return [
        r
        for r in records
        if r.get("t") == "commit" and r["dir"] not in retired
    ]


class DurableCheckpointer:
    """Asynchronous sharded durable checkpoints of (user state, manager
    state, loader position) behind a WAL-fenced manifest.

    Usage (same loop shape as v1)::

        ckpt = DurableCheckpointer(dir_, manager, state, loader=loader,
                                   every=100, keep=3)
        ckpt.restore_latest()          # before the first quorum
        while ...:
            ...; optimizer.step(avg)
            ckpt.maybe_save()          # capture-only stall on the
                                       # every-th COMMITTED step
        ckpt.close()

    or hook-driven (``register_hook=True``): the capture fires inside
    ``Manager.should_commit`` with no per-step call in the loop.

    ``mode="async"`` (default): ``maybe_save`` pays only the snapshot
    capture; a background writer serializes, CRC-frames, writes and
    fsyncs the member's 1/W shard, and rank 0 commits the manifest once
    all W shards are durable. ``mode="sync"`` runs the v1-shaped
    blocking pipeline inline (full-state write + fsync + commit on the
    trainer thread) — kept as the stall baseline and for tooling that
    must not return before durability."""

    def __init__(
        self,
        directory: str,
        manager: Any,
        state: Any,
        *,
        loader: Any = None,
        every: Optional[int] = None,
        keep: int = 3,
        store: Optional[CheckpointStore] = None,
        wire: Optional[str] = "__env__",
        mode: Optional[str] = None,
        commit_timeout_s: Optional[float] = None,
        max_staging_mb: Optional[float] = None,
        zero_copy: Optional[bool] = None,
        register_hook: bool = False,
    ) -> None:
        """
        Args:
            directory: durable root (shared mount) — used when ``store``
                is not given (``TORCHFT_DURABLE_STORE`` overrides).
            manager: the Manager; supplies ``{step, batches_committed}``,
                the participating rank/world at the commit boundary, and
                the quorum id that fences in-flight sets.
            state: object with ``state_dict()``/``load_state_dict()``
                for USER state.
            loader: optional stateful loader; its position is saved as
                PER-MEMBER local state keyed by replica id (a restored
                fleet with different replica ids starts loaders fresh).
            every: snapshot every ``every``-th committed step
                (``TORCHFT_DURABLE_EVERY``, default 100).
            keep: committed snapshots retained (older sets are retired
                from the manifest and their objects deleted).
            store: explicit backend; default from env/``directory``.
            wire: ``"bf16"`` (default via ``TORCHFT_DURABLE_WIRE``,
                bf16 opt-state / raw params) or ``None`` for raw f32.
            mode: ``"async"`` | ``"sync"`` (``TORCHFT_DURABLE_MODE``).
            commit_timeout_s: how long rank 0 waits for all W shard
                markers before abandoning the set
                (``TORCHFT_DURABLE_COMMIT_TIMEOUT_S``, default 120).
            max_staging_mb: cap on in-flight staged snapshot bytes; a
                capture that would exceed it is SKIPPED (backpressure
                never stalls the trainer; ``TORCHFT_DURABLE_STAGING_MB``,
                0 = unlimited).
            zero_copy: pin immutable uncompressed jax leaves instead of
                copying them at capture (``TORCHFT_DURABLE_ZEROCOPY``,
                default off) — the snapshot holds the Array alive and
                the stall drops to the layout walk. ONLY sound when the
                trainer never donates these buffers to a jit; numpy
                leaves are still copied.
            register_hook: wire ``manager.add_commit_hook`` so captures
                fire at every committed ``every``-boundary step without
                a ``maybe_save`` call in the loop.
        """
        self._manager = manager
        self._state = state
        self._loader = loader
        self._every = max(
            int(every if every is not None
                else _env_int("TORCHFT_DURABLE_EVERY", 100)),
            1,
        )
        self._keep = max(int(keep), 1)
        self._store = store if store is not None else store_from_env(directory)
        if wire == "__env__":
            wire = os.environ.get("TORCHFT_DURABLE_WIRE", "bf16").strip()
            wire = None if wire.lower() in ("", "none", "f32", "raw") else wire
        if wire not in (None, "bf16"):
            raise ValueError(f"unsupported durable wire: {wire!r}")
        self._wire = wire
        mode = (
            mode
            or os.environ.get("TORCHFT_DURABLE_MODE", "async").strip()
            or "async"
        )
        if mode not in ("async", "sync"):
            raise ValueError(f"unsupported durable mode: {mode!r}")
        self._mode = mode
        self._commit_timeout_s = (
            commit_timeout_s
            if commit_timeout_s is not None
            else _env_float("TORCHFT_DURABLE_COMMIT_TIMEOUT_S", 120.0)
        )
        self._max_staging = int(
            (
                max_staging_mb
                if max_staging_mb is not None
                else _env_float("TORCHFT_DURABLE_STAGING_MB", 0.0)
            )
            * 1024
            * 1024
        )
        self._zero_copy = bool(
            zero_copy
            if zero_copy is not None
            else os.environ.get("TORCHFT_DURABLE_ZEROCOPY", "").strip()
            .lower() in ("1", "true", "yes", "on")
        )
        self._manifest = ManifestLog(self._store)
        self._last_saved: Optional[int] = None
        self._inflight: List[_Snapshot] = []
        self._inflight_lock = threading.Lock()
        self._queue: "queue.Queue[Optional[_Snapshot]]" = queue.Queue()
        self._writer: Optional[threading.Thread] = None
        self._closed = False
        # bench/test observability: one row per capture attempt, plus
        # the last restore's bucket breakdown
        self.snapshots: List[Dict[str, Any]] = []
        self.last_restore_stats: Optional[Dict[str, Any]] = None
        if register_hook:
            manager.add_commit_hook(self._on_commit)
        # Restore-time donor/durable arbitration: hand the manager the
        # cold-start fallback, so a cold fleet's FIRST start_quorum
        # restores the latest committed checkpoint when no live donor
        # exists — the trainer no longer has to call restore_latest()
        # before its loop (it still may: the manager's consult is
        # one-shot and disarmed by a nonzero step). Guarded so stub
        # managers without the hook keep working.
        register_restore = getattr(manager, "set_durable_restore", None)
        if callable(register_restore):
            register_restore(self.restore_latest)

    # -- capture (trainer thread) --

    def _on_commit(self, step: int, quorum_id: int, committed: bool) -> None:
        """Manager commit hook: fences in-flight sets against quorum
        moves, then captures on committed ``every``-boundary steps."""
        self._fence_inflight(quorum_id)
        if not committed:
            return
        if step == 0 or step % self._every or step == self._last_saved:
            return
        self._capture(step, quorum_id)

    def maybe_save(self) -> Optional[str]:
        """Captures iff the manager just committed an ``every``-boundary
        step; call right after ``should_commit``/``optimizer.step``.
        Returns the snapshot directory name when a capture was taken
        (async: durability follows once the manifest commit lands)."""
        step = self._manager.current_step()
        # step only advances on COMMIT: after an aborted step the loop
        # lands here again at the same step — re-capturing would publish
        # a loader position that already consumed the aborted batch
        if step == 0 or step % self._every or step == self._last_saved:
            return None
        return self.save()

    def save(self) -> Optional[str]:
        """Unconditional capture of the current committed state."""
        step = self._manager.current_step()
        quorum_id = self._manager.quorum_id()
        self._fence_inflight(quorum_id)
        return self._capture(step, quorum_id)

    def _fence_inflight(self, quorum_id: int) -> None:
        """A quorum move invalidates every in-flight set captured under
        the old membership: its W no longer tiles the fleet, so peers
        will never produce the missing shards. Abort them; the writer
        deletes whatever partial objects already landed."""
        with self._inflight_lock:
            self._inflight = [s for s in self._inflight if not s.done.is_set()]
            for snap in self._inflight:
                if snap.quorum_id != quorum_id:
                    snap.abort.set()

    def _capture(self, step: int, quorum_id: int) -> Optional[str]:
        rank = self._manager.participating_rank()
        if rank is None:
            return None  # spare/healing member: no shard duty this set
        world = max(int(self._manager.num_participants()), 1)
        t0 = time.perf_counter()
        payload = {
            "user": self._state.state_dict(),
            "torchft": self._manager.state_dict(),
        }
        row: Dict[str, Any] = {
            "step": step, "quorum_id": quorum_id, "rank": rank,
            "world": world, "mode": self._mode, "wire": self._wire or "none",
            "committed": False, "aborted": False, "skipped": False,
        }
        if self._max_staging > 0:
            with self._inflight_lock:
                pending = sum(
                    s.staging.captured_bytes
                    for s in self._inflight
                    if not s.done.is_set()
                )
            if pending > self._max_staging:
                # Backpressure without a stall: dropping a snapshot only
                # widens the restore gap; blocking the trainer on disk
                # is exactly what v2 exists to remove.
                row["skipped"] = True
                row["stall_s"] = time.perf_counter() - t0
                self.snapshots.append(row)
                logger.warning(
                    "durable snapshot at step %d skipped: %d staged bytes "
                    "in flight exceed TORCHFT_DURABLE_STAGING_MB", step,
                    pending,
                )
                return None
        # Range-limited capture: this member's durable duty is only its
        # ~1/W shard, so it only pays d2h + owning copies for the leaves
        # that shard touches — the trainer-visible stall scales as 1/W
        # while the skeleton (layout math, no bytes) stays complete for
        # rank 0's meta.
        staging = _StreamStaging(
            payload, self._wire, seq=step, snapshot=True,
            shard_of=(rank, world), pin_leaves=self._zero_copy,
        )
        local = (
            serialize_state_dict(self._loader.state_dict())
            if self._loader is not None
            else None
        )
        snap = _Snapshot(
            step=step, quorum_id=quorum_id, rank=rank, world=world,
            staging=staging, local_state=local,
            replica_id=_member_id(self._manager.replica_id()), stats=row,
        )
        row["total_bytes"] = staging.total
        row["captured_bytes"] = staging.captured_bytes
        bounds = shard_bounds(staging.total, world)
        row["shard_bytes"] = bounds[rank + 1] - bounds[rank]
        # The trainer's whole stall: the capture above (d2h + owning
        # host copies + skeleton pickle). Everything after this line is
        # off the training path in async mode.
        row["stall_s"] = time.perf_counter() - t0
        self._last_saved = step
        self.snapshots.append(row)
        with self._inflight_lock:
            self._inflight.append(snap)
        if self._mode == "sync":
            t1 = time.perf_counter()
            self._write_snapshot(snap)
            if rank == 0 and not snap.abort.is_set():
                self._commit_snapshot(snap)
            snap.done.set()
            # sync mode stalls for the full pipeline — the baseline the
            # async stall is benched against
            row["stall_s"] += time.perf_counter() - t1
        else:
            self._ensure_writer()
            self._queue.put(snap)
        return snap.directory

    # -- writer (background thread) --

    def _ensure_writer(self) -> None:
        if self._writer is None or not self._writer.is_alive():
            self._writer = threading.Thread(
                target=self._writer_loop, name="durable_writer", daemon=True
            )
            self._writer.start()

    def _writer_loop(self) -> None:
        while True:
            snap = self._queue.get()
            if snap is None:
                return
            try:
                self._write_snapshot(snap)
                if snap.rank == 0 and not snap.abort.is_set():
                    self._commit_snapshot(snap)
            except Exception:
                logger.exception(
                    "durable snapshot at step %d failed", snap.step
                )
            finally:
                snap.done.set()

    def _write_snapshot(self, snap: _Snapshot) -> None:
        d = snap.directory
        bounds = shard_bounds(snap.staging.total, snap.world)
        begin, end = bounds[snap.rank], bounds[snap.rank + 1]
        row = snap.stats
        t0 = time.perf_counter()
        if snap.abort.is_set():
            row["aborted"] = True
            return
        crc = snap.staging.range_crc32c(begin, end)
        shard_name = f"{d}/shard_{snap.rank:04d}.bin"
        self._store.put_from(
            shard_name,
            lambda f: snap.staging.write_range(f, begin, end),
        )
        marker: Dict[str, Any] = {
            "v": 1, "step": snap.step, "quorum_id": snap.quorum_id,
            "rank": snap.rank, "world": snap.world,
            "begin": begin, "end": end, "nbytes": end - begin,
            "crc": f"{crc:08x}", "wire": self._wire or "none",
            "total": snap.staging.total, "name": shard_name,
        }
        if snap.rank == 0:
            meta = snap.staging.meta
            self._store.put(f"{d}/meta.pkl", meta)
            marker["meta_nbytes"] = len(meta)
            marker["meta_crc"] = f"{_crc32c(meta):08x}"
        if snap.local_state is not None:
            self._store.put(
                f"{d}/member_{snap.replica_id}.local", snap.local_state
            )
        if snap.abort.is_set():
            row["aborted"] = True
            self._cleanup_member(snap)
            return
        # Marker publication is the member's durability vote: it lands
        # (atomic, fsynced) strictly AFTER the shard payload is durable,
        # so the committer polling markers can never commit over a shard
        # still in flight.
        self._store.put(
            f"{d}/shard_{snap.rank:04d}.json",
            json.dumps(marker, sort_keys=True).encode(),
        )
        row["write_s"] = time.perf_counter() - t0
        row["durable_bytes"] = (end - begin) + (
            marker.get("meta_nbytes", 0)
            + (len(snap.local_state) if snap.local_state else 0)
        )

    def _cleanup_member(self, snap: _Snapshot) -> None:
        d = snap.directory
        for name in (
            f"{d}/shard_{snap.rank:04d}.bin",
            f"{d}/shard_{snap.rank:04d}.json",
            f"{d}/member_{snap.replica_id}.local",
            *((f"{d}/meta.pkl",) if snap.rank == 0 else ()),
        ):
            try:
                self._store.delete(name)
            except OSError:  # pragma: no cover - best-effort cleanup
                pass

    # -- committer (rank 0, background thread) --

    def _commit_snapshot(self, snap: _Snapshot) -> bool:
        """Polls the store until all W shard markers are durably present
        and mutually consistent, then appends the manifest commit record
        — the ONLY thing that makes the set restorable."""
        d = snap.directory
        deadline = time.monotonic() + self._commit_timeout_s
        t0 = time.perf_counter()
        markers: Dict[int, Dict[str, Any]] = {}
        while len(markers) < snap.world:
            for r in range(snap.world):
                if r in markers:
                    continue
                name = f"{d}/shard_{r:04d}.json"
                if not self._store.exists(name):
                    continue
                try:
                    markers[r] = json.loads(self._store.get(name))
                except (OSError, ValueError):
                    continue
            if len(markers) >= snap.world:
                break
            if snap.abort.is_set() or time.monotonic() > deadline:
                snap.stats["aborted"] = True
                logger.warning(
                    "durable snapshot %s abandoned: %d/%d shard markers "
                    "after %.1fs", d, len(markers), snap.world,
                    time.monotonic() - (deadline - self._commit_timeout_s),
                )
                return False
            time.sleep(0.02)
        bad = inconsistent_marker(
            markers,
            step=snap.step,
            quorum_id=snap.quorum_id,
            world=snap.world,
            total=snap.staging.total,
            wire=self._wire or "none",
        )
        if bad is not None:
            logger.warning(
                "durable snapshot %s abandoned: shard %d marker "
                "inconsistent (%s)", d, bad[0], bad[1],
            )
            snap.stats["aborted"] = True
            return False
        if snap.abort.is_set():
            snap.stats["aborted"] = True
            return False
        record = {
            "t": "commit", "step": snap.step, "quorum_id": snap.quorum_id,
            "world": snap.world, "wire": self._wire or "none",
            "total": snap.staging.total, "dir": d,
            "meta": {
                "name": f"{d}/meta.pkl",
                "nbytes": markers[0]["meta_nbytes"],
                "crc": markers[0]["meta_crc"],
            },
            "shards": [
                {
                    "rank": r, "name": markers[r]["name"],
                    "begin": markers[r]["begin"], "end": markers[r]["end"],
                    "nbytes": markers[r]["nbytes"], "crc": markers[r]["crc"],
                }
                for r in range(snap.world)
            ],
            "unix_ms": int(time.time() * 1000),
        }
        self._manifest.append(record)
        snap.stats["committed"] = True
        snap.stats["commit_s"] = time.perf_counter() - t0
        self._retire_old()
        return True

    def _retire_old(self) -> None:
        """Retention: keep the newest ``keep`` committed sets; retire the
        rest (a ``retire`` record fences them from restore BEFORE their
        objects disappear) and compact the log when it accumulates."""
        records, _ = self._manifest.replay()
        retired = {r["dir"] for r in records if r.get("t") == "retire"}
        commits = live_commits(records)
        for rec in commits[: -self._keep] if len(commits) > self._keep else []:
            self._manifest.append({"t": "retire", "dir": rec["dir"]})
            retired.add(rec["dir"])
            try:
                self._store.delete_prefix(rec["dir"] + "/")
            except OSError:  # pragma: no cover - best-effort retention
                pass
        if len(records) > max(8 * self._keep, 64):
            live = [
                r
                for r in records
                if r.get("t") == "commit" and r["dir"] not in retired
            ]
            self._manifest.compact(live)

    # -- restore (no-donor path) --

    def restore_latest(self, device_put: bool = False) -> Optional[int]:
        """Reassembles the newest COMMITTED snapshot from the durable
        tier and applies it; returns its step, or None when the manifest
        holds no restorable set. Call BEFORE the first quorum so the
        member joins at the restored step instead of 0.

        This is the no-donor path: in a running fleet the live streamed
        heal always takes precedence (the quorum routes a joining member
        at a donor); this runs when there is no donor left — a cold
        fleet after whole-fleet preemption. Works across a different
        fleet width: every member rebuilds the FULL tree from all W_old
        shards, and width-dependent engine state re-shards on the next
        quorum. A set that fails validation (missing object, CRC
        mismatch) falls back to the next older committed set — a torn
        snapshot can never win."""
        t_replay = time.perf_counter()
        records, dropped = self._manifest.replay()
        commits = live_commits(records)
        replay_s = time.perf_counter() - t_replay
        for rec in reversed(commits):
            try:
                payload, local, stats = self._fetch_committed(
                    rec, device_put
                )
            except Exception as e:  # noqa: BLE001 - older set may be whole
                logger.warning(
                    "durable restore: committed set %s unusable (%s); "
                    "trying older", rec.get("dir"), e,
                )
                continue
            stats["manifest_read_s"] += replay_s
            stats["dropped_tail_bytes"] = dropped
            self._state.load_state_dict(payload["user"])
            self._manager.load_state_dict(payload["torchft"])
            if self._loader is not None and local is not None:
                self._loader.load_state_dict(local)
                stats["loader_restored"] = True
            step = int(payload["torchft"]["step"])
            # Arm the same-step guard: an aborted first post-restore step
            # must not re-capture over this set with a drifted loader.
            self._last_saved = step
            self.last_restore_stats = stats
            logger.info(
                "restored durable snapshot %s (step %d, %d shards, "
                "%d bytes)", rec["dir"], step, rec["world"], rec["total"],
            )
            return step
        return None

    def _fetch_committed(
        self, rec: Dict[str, Any], device_put: bool
    ) -> Tuple[Any, Optional[Any], Dict[str, Any]]:
        stats: Dict[str, Any] = {
            "dir": rec["dir"], "step": rec["step"], "world": rec["world"],
            "bytes": rec["total"], "wire": rec["wire"],
            "h2d_s": 0.0, "compile_s": 0.0,
        }
        t0 = time.perf_counter()
        meta_raw = self._store.get(rec["meta"]["name"])
        if len(meta_raw) != rec["meta"]["nbytes"] or (
            f"{_crc32c(meta_raw):08x}" != rec["meta"]["crc"]
        ):
            raise ValueError("meta blob CRC/size mismatch")
        meta = load_packed_meta(meta_raw)
        if int(meta["total"]) != int(rec["total"]):
            raise ValueError("meta/manifest total mismatch")
        stats["manifest_read_s"] = time.perf_counter() - t0

        # Parallel range-fetch: each shard IS one contiguous range of the
        # packed stream, so W readers fill one preallocated buffer with
        # no reassembly pass — the streamed-heal receiver shape against
        # the durable tier instead of a donor.
        t1 = time.perf_counter()
        total = int(rec["total"])
        buf = bytearray(total)
        view = memoryview(buf)
        errors: List[BaseException] = []

        def fetch(shard: Dict[str, Any]) -> None:
            try:
                begin, end = int(shard["begin"]), int(shard["end"])
                data = self._store.read_range(
                    shard["name"], 0, end - begin
                )
                if len(data) != end - begin:
                    raise ValueError(
                        f"shard {shard['rank']} short read "
                        f"({len(data)}/{end - begin})"
                    )
                if f"{_crc32c(data):08x}" != shard["crc"]:
                    raise ValueError(
                        f"shard {shard['rank']} CRC32C mismatch"
                    )
                view[begin:end] = data
            except BaseException as e:  # noqa: BLE001 - surface to caller
                errors.append(e)

        threads = [
            threading.Thread(target=fetch, args=(s,), daemon=True)
            for s in rec["shards"]
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        covered = sorted(
            (int(s["begin"]), int(s["end"])) for s in rec["shards"]
        )
        pos = 0
        for begin, end in covered:
            if begin != pos:
                raise ValueError("shard ranges do not tile the stream")
            pos = end
        if pos != total:
            raise ValueError("shard ranges do not cover the stream")
        stats["shard_fetch_s"] = time.perf_counter() - t1

        t2 = time.perf_counter()
        payload = rebuild_from_packed(meta, buf, device_put=False)
        stats["reshard_s"] = time.perf_counter() - t2
        if device_put:
            import jax
            import jax.numpy as jnp
            import numpy as np

            t3 = time.perf_counter()

            def up(leaf: Any) -> Any:
                if isinstance(leaf, np.ndarray) and (
                    jax.dtypes.canonicalize_dtype(leaf.dtype) == leaf.dtype
                ):
                    return jnp.asarray(leaf)
                return leaf

            payload = jax.tree_util.tree_map(up, payload)
            jax.block_until_ready(
                [l for l in jax.tree_util.tree_leaves(payload)]
            )
            stats["h2d_s"] = time.perf_counter() - t3

        local = None
        local_name = (
            f"{rec['dir']}/member_"
            f"{_member_id(self._manager.replica_id())}.local"
        )
        if self._loader is not None and self._store.exists(local_name):
            local = deserialize_state_dict(self._store.get(local_name))
        return payload, local, stats

    # -- lifecycle / introspection --

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Blocks until every in-flight snapshot finished (written +
        committed/aborted). Returns False on timeout."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._inflight_lock:
            pending = list(self._inflight)
        for snap in pending:
            remain = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remain is not None and remain <= 0:
                return False
            if not snap.done.wait(remain):
                return False
        return True

    def committed_steps(self) -> List[int]:
        """Steps of currently restorable (committed, unretired) sets."""
        records, _ = self._manifest.replay()
        retired = {r["dir"] for r in records if r.get("t") == "retire"}
        return [
            int(r["step"])
            for r in records
            if r.get("t") == "commit" and r["dir"] not in retired
        ]

    def latest_path(self) -> Optional[str]:
        """Directory name of the newest committed set (None when empty)."""
        records, _ = self._manifest.replay()
        retired = {r["dir"] for r in records if r.get("t") == "retire"}
        commits = [
            r
            for r in records
            if r.get("t") == "commit" and r["dir"] not in retired
        ]
        return commits[-1]["dir"] if commits else None

    @property
    def store(self) -> CheckpointStore:
        return self._store

    @property
    def manifest(self) -> ManifestLog:
        return self._manifest

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Drains the writer thread (in-flight snapshots finish)."""
        if self._closed:
            return
        self._closed = True
        if self._writer is not None and self._writer.is_alive():
            self._queue.put(None)
            self._writer.join(timeout)

    def __enter__(self) -> "DurableCheckpointer":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
