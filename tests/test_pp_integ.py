"""Pipeline parallelism composed with the fault-tolerance layer, end to
end: each replica group runs the flagship blocks GPipe-pipelined over its
OWN {data:2, pipe:2} mesh, gradients average across groups through a REAL
2-member host TCP ring, with kill + heal and the bit-identical oracle.

Same claim as test_hsdp_integ (reference analog fsdp_test.py:38-74) with
the intra-group dimension being the pipeline instead of tp.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import HAS_SHARD_MAP, SHARD_MAP_SKIP

if not HAS_SHARD_MAP:
    # the pipelined group step shard_maps over the pipe axis
    pytest.skip(SHARD_MAP_SKIP, allow_module_level=True)

from torchft_tpu.models.transformer import (
    _block,
    embed_tokens,
    init_params,
    next_token_loss,
    readout,
    tiny_config,
)
from torchft_tpu.parallel import make_mesh
from torchft_tpu.pipeline import pipeline_blocks, stack_blocks, stage_specs

from sharded_integ import (
    DEVICES_PER_GROUP,
    GroupSetup,
    assert_bitwise_identical,
    run_kill_and_heal,
    run_sharded_groups,
)


def _setup(gid: int) -> GroupSetup:
    from jax.sharding import PartitionSpec as P

    devices = jax.devices()[
        gid * DEVICES_PER_GROUP : (gid + 1) * DEVICES_PER_GROUP
    ]
    mesh = make_mesh({"data": 2, "pipe": 2}, devices=devices)
    cfg = tiny_config()  # n_layers=2 -> one layer per stage

    def fresh_params():
        raw = init_params(cfg, jax.random.PRNGKey(42))
        return {
            "backbone": {k: v for k, v in raw.items() if k != "blocks"},
            "stacked": stack_blocks(raw["blocks"]),
        }

    raw = fresh_params()
    rules = {
        "backbone": jax.tree_util.tree_map(lambda _l: P(), raw["backbone"]),
        "stacked": stage_specs(raw["stacked"]),
    }

    def loss_fn(params, tokens):
        x = embed_tokens(cfg, params["backbone"], tokens[:, :-1])
        x = pipeline_blocks(
            functools.partial(_block, cfg),
            params["stacked"],
            x,
            mesh=mesh,
            microbatches=2,
            data_axis="data",
        )
        return next_token_loss(
            readout(cfg, params["backbone"], x), tokens[:, 1:]
        )

    def batch_fn(step: int):
        rng = np.random.default_rng(9000 + step)
        return jnp.asarray(
            rng.integers(0, cfg.vocab_size, size=(4, 33), dtype=np.int32)
        )

    return GroupSetup(
        devices=devices,
        mesh=mesh,
        rules=rules,
        grad_step=jax.jit(jax.value_and_grad(loss_fn)),
        fresh_params=fresh_params,
        batch_fn=batch_fn,
        check_subtree="stacked",
    )


class TestPipelineUnderFaults:
    def test_pipelined_groups_stay_identical(self):
        results = run_sharded_groups("pp", _setup, num_steps=4)
        for r in results:
            assert r["manager_state"]["step"] == 4
        assert_bitwise_identical(results)

    def test_pipelined_group_kill_and_heal(self):
        run_kill_and_heal("pp", _setup)

    def test_zero_sharded_groups_stay_identical(self):
        # Per-step ZeRO engine (rs grads, ~1/W opt shard, param ag)
        # composed with the dp x pipe sharding.
        results = run_sharded_groups(
            "pp", _setup, num_steps=4, engine="zero"
        )
        for r in results:
            assert r["manager_state"]["step"] == 4
        assert_bitwise_identical(results)
