"""TPU cost ablation at the big shape: where does the non-matmul time go?
Run ALONE on the chip. Modes via EXP_ABL env:
  layers  — n_layers in {0,2,4,8} dense B8: slope = per-layer, intercept =
            embed+readout+loss+optimizer
  blocks  — in-model flash block sweep at B8 + dense baseline
"""
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from torchft_tpu.models import TransformerConfig, init_params, loss_fn

B = int(os.environ.get("EXP_B", "8"))
MODE = os.environ.get("EXP_ABL", "layers")


def drain(x):
    jax.block_until_ready(x)
    np.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[0:1])


def run(cfg, batch, label, warm=2, iters=8):
    tx = optax.adamw(1e-3)
    try:
        params = init_params(cfg, jax.random.PRNGKey(0))
        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(params))
        opt_state = tx.init(params)

        def one_step(p, o, b):
            loss, grads = jax.value_and_grad(
                lambda pp: loss_fn(cfg, pp, b)
            )(p)
            u, o2 = tx.update(grads, o, p)
            return optax.apply_updates(p, u), o2, loss

        step = jax.jit(one_step, donate_argnums=(0, 1))
        for _ in range(warm):
            params, opt_state, loss = step(params, opt_state, batch)
        drain(params)
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, batch)
        drain(params)
        dt = (time.perf_counter() - t0) / iters
        tf = 6 * n_params * batch.size / 1e12
        print(f"{label:28s} {dt*1000:8.1f} ms/step  "
              f"{tf/dt:6.1f} param-TFLOP/s", flush=True)
        del params, opt_state
        return dt
    except Exception as e:
        print(f"{label}: FAIL {type(e).__name__}: {str(e)[:150]}", flush=True)
        return None


def main():
    assert jax.devices()[0].platform == "tpu"
    rng = np.random.default_rng(0)
    batch = jnp.asarray(rng.integers(0, 8192, size=(B, 2048), dtype=np.int32))
    base = dict(vocab_size=8192, d_model=1024, n_heads=16, d_ff=4096,
                max_seq_len=2048)

    if MODE == "layers":
        for L in (0, 2, 4, 8):
            run(TransformerConfig(n_layers=L, use_flash=True, **base), batch,
                f"flash L={L} B={B}")
    elif MODE == "fused":
        # dispatch-overhead probe: same compute, fewer program launches
        import optax as _ox
        from torchft_tpu.models import init_params as ip, loss_fn as lf

        for L in (0, 8):
            cfg = TransformerConfig(n_layers=L, **base)
            tx = _ox.adamw(1e-3)
            params = ip(cfg, jax.random.PRNGKey(0))
            n_params = sum(int(np.prod(l.shape))
                           for l in jax.tree_util.tree_leaves(params))
            opt_state = tx.init(params)

            def one_step(p, o, b):
                loss, grads = jax.value_and_grad(
                    lambda pp: lf(cfg, pp, b)
                )(p)
                u, o2 = tx.update(grads, o, p)
                return _ox.apply_updates(p, u), o2, loss

            merged = jax.jit(one_step, donate_argnums=(0, 1))

            def scan8(p, o, b):
                def body(carry, _):
                    p, o = carry
                    p2, o2, loss = one_step(p, o, b)
                    return (p2, o2), loss
                (p, o), losses = jax.lax.scan(
                    body, (p, o), None, length=8
                )
                return p, o, losses
            scanned = jax.jit(scan8, donate_argnums=(0, 1))

            for label, fn, per_call in (
                (f"merged L={L}", merged, 1),
                (f"scan8 L={L}", scanned, 8),
            ):
                for _ in range(2):
                    out = fn(params, opt_state, batch)
                    params, opt_state = out[0], out[1]
                drain(params)
                t0 = time.perf_counter()
                iters = 16 if per_call == 1 else 2
                for _ in range(iters):
                    out = fn(params, opt_state, batch)
                    params, opt_state = out[0], out[1]
                drain(params)
                dt = (time.perf_counter() - t0) / (iters * per_call)
                tf = 6 * n_params * batch.size / 1e12
                print(f"{label:20s} {dt*1000:8.1f} ms/step  "
                      f"{tf/dt:6.1f} param-TFLOP/s", flush=True)
            del params, opt_state
    elif MODE == "loss":
        # isolate the head: L=0 model, loss variants
        from torchft_tpu.models import transformer as T

        def loss_v(variant):
            def nt_loss(logits, targets):
                if variant == "take":
                    logp = jax.nn.log_softmax(logits, axis=-1)
                    ll = jnp.take_along_axis(
                        logp, targets[..., None], axis=-1
                    )[..., 0]
                    return -jnp.mean(ll)
                if variant == "mask":
                    logz = jax.scipy.special.logsumexp(logits, axis=-1)
                    V = logits.shape[-1]
                    tgt = jax.lax.broadcasted_iota(
                        jnp.int32, logits.shape, logits.ndim - 1
                    ) == targets[..., None]
                    picked = jnp.sum(
                        jnp.where(tgt, logits, 0.0), axis=-1
                    )
                    return jnp.mean(logz - picked)
                if variant == "onehot":
                    logz = jax.scipy.special.logsumexp(logits, axis=-1)
                    oh = jax.nn.one_hot(
                        targets, logits.shape[-1], dtype=logits.dtype
                    )
                    picked = jnp.einsum("bsv,bsv->bs", logits, oh)
                    return jnp.mean(logz - picked)
                raise ValueError(variant)
            return nt_loss

        for variant in ("take", "mask", "onehot"):
            cfg0 = TransformerConfig(n_layers=0, **base)
            orig = T.next_token_loss
            T.next_token_loss = loss_v(variant)
            try:
                run(cfg0, batch, f"head loss={variant} B={B}")
            finally:
                T.next_token_loss = orig
    else:
        cfg8 = TransformerConfig(n_layers=8, **base)
        run(cfg8, batch, f"dense B={B}")
        blocks = [(128, 128), (256, 256), (512, 256), (512, 512),
                  (1024, 1024), (2048, 512), (256, 2048)]
        if os.environ.get("EXP_BLOCKS_SHORT"):
            blocks = [(512, 512), (1024, 1024)]
        for bq, bk in blocks:
            c = dataclasses.replace(
                cfg8, use_flash=True, flash_block_q=bq, flash_block_k=bk
            )
            run(c, batch, f"flash B={B} bq={bq} bk={bk}")


if __name__ == "__main__":
    main()
