"""Deterministic chaos plane — the Python layer.

The architecture's one invariant is that every training step is a
transaction: an error anywhere latches, the commit vote discards the
step, and the fleet heals. This module makes faults FIRST-CLASS so that
invariant can be exercised (and replayed) from a single seed instead of
ad-hoc SIGKILLs:

- :class:`FaultPlan` is a declarative seeded schedule — *at attempted
  step N, inject fault F at seam S on member M* — generated
  deterministically from ``(seed, config)`` by :meth:`FaultPlan.random`
  and serialized as JSON, so any failing schedule reproduces
  byte-for-byte from the ``(seed, plan)`` printed in a failure message.
- :class:`ChaosInjector` drives a plan against a live member: native
  seams (``ring_send``/``ring_hdr``/``net_send``/``shm_ring``) arm
  one-shot rules in
  the C++ fault engine per step (see native/src/fault.h); Python seams
  (``store``/``heal``/``child``/``shm``) are realized by the injector
  wrappers below.
- Seam injectors: :class:`FaultyStoreClient` (drop / delay / stale
  read), :class:`HealFaultProxy` (truncated body, slow-loris range,
  connection reset, 5xx, blackhole — in front of a real
  CheckpointServer), :func:`kill_process` / :class:`ProcessStall`
  (SIGKILL and SIGSTOP — the stalled-not-dead child or lighthouse), and
  :func:`tear_shm` (torn segment on attach).

The seeded hash (splitmix64) mirrors the native engine bit-for-bit, so
Python- and C-side decisions derive from one stream.
"""

from __future__ import annotations

import http.server
import json
import os
import signal
import socket
import socketserver
import threading
import time
import urllib.request
from dataclasses import asdict, dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import _native

_MASK = (1 << 64) - 1

# Seams a plan may name. The native engine owns the first group; the
# rest are realized Python-side by the injectors in this module.
NATIVE_SEAMS = ("ring_send", "ring_hdr", "net_send", "shm_ring", "wal_write")
PYTHON_SEAMS = ("store", "heal", "child", "shm", "lighthouse", "root",
                "serving")
SEAMS = NATIVE_SEAMS + PYTHON_SEAMS

# Kinds per seam (what a random plan may draw). Native ring kinds map
# 1:1 onto native/src/fault.h; Python seams define their own vocabulary.
SEAM_KINDS: Dict[str, Tuple[str, ...]] = {
    "ring_send": ("drop", "delay", "truncate", "duplicate", "bit_flip",
                  "partition"),
    "ring_hdr": ("bit_flip", "drop"),
    # The host tier's shared-memory rings (native/src/collectives.cc
    # shm_duplex): drop = drop-doorbell (every publish of the op
    # silently vanishes — an asymmetric partition; the consumer stalls
    # to its op deadline), bit_flip = stale-payload (a replayed frame
    # sequence, detected as WireCorruption), truncate = torn-segment
    # (half a frame + poisoned ring magic).
    "shm_ring": ("drop", "delay", "truncate", "bit_flip"),
    "net_send": ("drop", "delay", "truncate", "bit_flip"),
    # The root lighthouse's write-ahead quorum log (native/src/wal.cc):
    # truncate = crash mid-append (half a record on disk — recovery must
    # detect + drop the torn tail), drop = crash before any byte lands,
    # delay = slow disk. Both crash kinds kill the log; the root then
    # refuses NEW quorum promises (frozen beats regressed) until restart.
    "wal_write": ("truncate", "drop", "delay"),
    "store": ("drop", "delay", "stale"),
    "heal": ("truncate_body", "reset_mid_range", "slow_loris", "error_500",
             "blackhole"),
    "child": ("sigkill", "sigstop"),
    "shm": ("tear",),
    "lighthouse": ("stall", "kill"),
    # The ROOT lighthouse process (a RootProcess subprocess): kill =
    # SIGKILL the active root mid-promise, restart = kill + respawn on
    # the same port + WAL dir (the replay path), partition = SIGSTOP for
    # `param` ms then SIGCONT (unreachable-but-alive — the takeover +
    # deposed-primary fencing path).
    "root": ("kill", "restart", "partition"),
    # The weight-distribution serving plane (serving.py): kill = SIGKILL
    # the publisher subprocess MID-range (TORCHFT_PS_DRIP_MS throttles
    # the body so the kill reliably lands inside a transfer — the
    # short-body + CRC + nonce ladder must avert the install), restart =
    # kill + respawn on the same port (fresh nonces over reused version
    # numbers: the torn-republish 400 path), partition = cut one relay
    # from its upstream (it keeps serving with honestly growing age_ms),
    # churn = subscriber join/leave storm (lease table pruning under
    # load).
    "serving": ("kill", "restart", "partition", "churn"),
}


def splitmix64(x: int) -> int:
    """The exact mixer the native fault engine uses (fault.cc mix64), so
    Python-side decisions derive from the same stream."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK
    return x ^ (x >> 31)


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: at attempted step ``step``, inject ``kind``
    at ``seam`` on ``member`` (-1 = any member). ``param`` is the kind's
    knob (delay/stall milliseconds, ...)."""

    step: int
    seam: str
    kind: str
    member: int = -1
    param: int = 0


@dataclass(frozen=True)
class FaultPlan:
    """A seeded, replayable fault schedule. Pure data: the same
    ``(seed, events)`` always realizes the same faults, and
    :meth:`random` derives events deterministically from the seed — so a
    failure message carrying ``(seed, plan_json)`` IS the reproducer."""

    seed: int
    events: Tuple[FaultEvent, ...] = ()

    @classmethod
    def random(
        cls,
        seed: int,
        steps: int,
        members: int,
        seams: Sequence[str] = ("ring_send",),
        events_target: int = 3,
        max_delay_ms: int = 200,
    ) -> "FaultPlan":
        """Draws ~``events_target`` events over ``steps`` attempted steps
        across ``members`` members and the given seams — deterministic in
        every argument. Step 0 is left fault-free (the fleet must form
        once before the storm starts)."""
        if steps < 2:
            raise ValueError("need >= 2 steps (step 0 stays clean)")
        events: List[FaultEvent] = []
        n_draws = max(events_target, 1)
        h = splitmix64(seed)
        for draw in range(n_draws):
            h = splitmix64(h ^ draw)
            step = 1 + (h % (steps - 1))
            h = splitmix64(h)
            seam = seams[h % len(seams)]
            kinds = SEAM_KINDS[seam]
            h = splitmix64(h)
            kind = kinds[h % len(kinds)]
            h = splitmix64(h)
            # net_send has no member identity at the native call site
            # (Socket::send_all passes -1): a targeted member would be a
            # lie in the replay stamp, so the plan says "any" honestly.
            member = (
                -1
                if seam == "net_send"
                else (h % members if members > 0 else -1)
            )
            h = splitmix64(h)
            param = (h % max_delay_ms) + 1 if kind in ("delay",) else 0
            if kind in ("sigstop", "stall", "partition"):
                param = 300 + (h % 700)  # ms stopped before SIGCONT
            events.append(FaultEvent(step, seam, kind, member, param))
        events.sort(key=lambda e: (e.step, e.seam, e.kind, e.member))
        return cls(seed=seed, events=tuple(events))

    def events_at(self, step: int, member: Optional[int] = None) -> List[FaultEvent]:
        return [
            e
            for e in self.events
            if e.step == step
            and (member is None or e.member < 0 or e.member == member)
        ]

    def native_rules(self, step: int) -> List[dict]:
        """The native fault-engine rules for this step's native-seam
        events: one-shot (max_fires=1), always-fire (permille=1000) —
        the step axis is driven by the injector's arm/disarm cadence, the
        frame hit is the first matching send of the step."""
        rules = []
        for e in self.events_at(step):
            if e.seam not in NATIVE_SEAMS:
                continue
            rules.append(
                {
                    "seam": e.seam,
                    "kind": e.kind,
                    # net_send call sites carry no member identity, so a
                    # targeted member would silently mean "any" in the
                    # engine; ship the honest -1 instead.
                    "member": -1 if e.seam == "net_send" else e.member,
                    "permille": 1000,
                    "max_fires": 1,
                    "param": e.param,
                }
            )
        return rules

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "events": [asdict(e) for e in self.events]}
        )

    @classmethod
    def from_json(cls, raw: str) -> "FaultPlan":
        d = json.loads(raw)
        return cls(
            seed=int(d["seed"]),
            events=tuple(FaultEvent(**e) for e in d.get("events", [])),
        )

    def fingerprint(self) -> dict:
        """The replay stamp bench artifacts carry (``fault_plan`` key):
        enough to re-run ``scripts/chaos_run.py --seed <seed>
        --plan '<json>'`` byte-for-byte."""
        return {
            "seed": self.seed,
            "n_events": len(self.events),
            "plan": self.to_json(),
        }


class ChaosInjector:
    """Drives one :class:`FaultPlan` in one process.

    Call :meth:`begin_step` at the top of every attempted step: native
    rules for that step's native-seam events are armed (one-shot), and
    each Python-seam event is dispatched to the handler registered for
    its seam via :meth:`on`. :meth:`finish` disarms and returns the
    cumulative native injection stats — the harness's injected-fault
    ledger."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self._handlers: Dict[str, Callable[[FaultEvent], None]] = {}
        self._python_fired: List[dict] = []

    def on(self, seam: str, handler: Callable[[FaultEvent], None]) -> "ChaosInjector":
        if seam not in PYTHON_SEAMS:
            raise ValueError(f"{seam!r} is not a Python-side seam")
        self._handlers[seam] = handler
        return self

    def begin_step(self, step: int, member: Optional[int] = None) -> None:
        rules = self.plan.native_rules(step)
        # (Re-)arming replaces the rule set; stats accumulate across
        # re-arms. An empty step disarms — a clean step costs the ring
        # its one relaxed load per frame, nothing more.
        _native.fault_arm({"seed": self.plan.seed, "rules": rules})
        for e in self.plan.events_at(step, member):
            if e.seam in NATIVE_SEAMS:
                continue
            handler = self._handlers.get(e.seam)
            if handler is not None:
                handler(e)
                self._python_fired.append(asdict(e))

    def finish(self) -> dict:
        stats = _native.fault_stats()
        _native.fault_disarm()
        stats["python_fired"] = list(self._python_fired)
        return stats


# -- Python seam injectors ---------------------------------------------------


class FaultyStoreClient:
    """A :class:`~torchft_tpu._native.StoreClient` wrapper realizing the
    ``store`` seam: per-op seeded decisions to DROP (raise a timeout, the
    client-visible face of a flaky KV service), DELAY, or serve a STALE
    read (the last value this wrapper saw for the key — a lagging
    replica). Deterministic in ``(seed, op index)``."""

    def __init__(
        self,
        inner: Any,
        seed: int,
        drop_permille: int = 0,
        delay_permille: int = 0,
        stale_permille: int = 0,
        delay_ms: int = 100,
    ) -> None:
        self._inner = inner
        self._seed = seed
        self._drop = drop_permille
        self._delay = delay_permille
        self._stale = stale_permille
        self._delay_ms = delay_ms
        self._op = 0
        self._cache: Dict[str, bytes] = {}
        self.fired: List[str] = []

    def _decide(self) -> Optional[str]:
        h = splitmix64(self._seed ^ (self._op * 0xC2B2AE3D))
        self._op += 1
        gate = h % 1000
        if gate < self._drop:
            return "drop"
        if gate < self._drop + self._delay:
            return "delay"
        if gate < self._drop + self._delay + self._stale:
            return "stale"
        return None

    def _apply(self, op: str) -> Optional[str]:
        verdict = self._decide()
        if verdict == "drop":
            self.fired.append(f"{op}:drop")
            raise TimeoutError(f"chaos injected: store {op} dropped")
        if verdict == "delay":
            self.fired.append(f"{op}:delay")
            time.sleep(self._delay_ms / 1e3)
            return None
        return verdict

    def set(self, key: str, value: Any, **kw: Any) -> None:
        self._apply("set")
        self._inner.set(key, value, **kw)
        self._cache[key] = value if isinstance(value, bytes) else str(value).encode()

    def get(self, key: str, **kw: Any) -> bytes:
        verdict = self._apply("get")
        if verdict == "stale" and key in self._cache:
            self.fired.append("get:stale")
            return self._cache[key]
        out = self._inner.get(key, **kw)
        self._cache[key] = out
        return out

    def add(self, key: str, delta: int, **kw: Any) -> int:
        self._apply("add")
        return self._inner.add(key, delta, **kw)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


class HealFaultProxy:
    """An HTTP proxy in front of a checkpoint donor realizing the
    ``heal`` seam. ``mode`` (mutable between fetches) selects the fault:

    - ``"truncate_body"``: correct headers, half the body, then close —
      the torn-response case the receiver must detect and fall back from
      without double-charging its timeout budget.
    - ``"reset_mid_range"``: connection reset halfway through the body.
    - ``"slow_loris"``: trickle the body a few bytes per second (the
      receiver's deadline, not patience, must end it).
    - ``"error_500"``: a flaky-donor 5xx.
    - ``"blackhole"``: accept, read the request, never answer.
    - ``"bit_flip"``: forward the body with ONE byte corrupted while
      preserving the donor's integrity header — the receiver's CRC
      check, not luck, must catch it (the zero-silent-commits contract
      applied to heal traffic).
    - ``None``: transparent pass-through.

    ``only_paths`` (substring match) limits faults to matching request
    paths — e.g. fault ``/stream/`` ranges while leaving the layout
    fetch clean. ``max_faults`` bounds how many requests are faulted
    (later ones pass through, so fallbacks can succeed)."""

    def __init__(
        self,
        upstream: str,
        mode: Optional[str] = None,
        only_paths: Sequence[str] = (),
        max_faults: int = 1 << 30,
    ) -> None:
        self.upstream = upstream.rstrip("/")
        self.mode = mode
        self.only_paths = tuple(only_paths)
        self.max_faults = max_faults
        self.faults_fired = 0
        self.requests: List[str] = []
        proxy = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self) -> None:  # noqa: N802 - stdlib naming
                proxy.requests.append(self.path)
                mode = proxy.mode
                if (
                    mode is not None
                    and proxy.faults_fired < proxy.max_faults
                    and (
                        not proxy.only_paths
                        or any(p in self.path for p in proxy.only_paths)
                    )
                ):
                    proxy.faults_fired += 1
                    if mode == "blackhole":
                        # hold the socket open, never answer; the client's
                        # timeout is the only way out
                        time.sleep(3600)
                        return
                    if mode == "error_500":
                        self.send_error(500, "chaos injected: donor error")
                        return
                    try:
                        with urllib.request.urlopen(
                            proxy.upstream + self.path, timeout=30
                        ) as resp:
                            body = resp.read()
                            upstream_headers = dict(resp.headers.items())
                    except Exception:
                        self.send_error(502, "upstream failed")
                        return
                    if mode == "bit_flip":
                        corrupted = bytearray(body)
                        if corrupted:
                            h = splitmix64(len(body) ^ 0xC0FFEE)
                            corrupted[h % len(corrupted)] ^= 1 << (h % 8)
                        self.send_response(200)
                        self.send_header("Content-Length", str(len(corrupted)))
                        crc = upstream_headers.get("X-Tft-Crc32c") or (
                            upstream_headers.get("X-TFT-Crc32c")
                        )
                        if crc:
                            self.send_header("X-TFT-Crc32c", crc)
                        self.end_headers()
                        self.wfile.write(bytes(corrupted))
                        return
                    if mode == "truncate_body":
                        self.send_response(200)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body[: len(body) // 2])
                        self.wfile.flush()
                        # close underneath the declared length: the
                        # receiver sees a short read, not a clean EOF
                        self.connection.close()
                        return
                    if mode == "reset_mid_range":
                        self.send_response(200)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body[: max(1, len(body) // 2)])
                        self.wfile.flush()
                        # RST, not FIN: SO_LINGER 0 + close
                        import struct

                        self.connection.setsockopt(
                            socket.SOL_SOCKET,
                            socket.SO_LINGER,
                            struct.pack("ii", 1, 0),
                        )
                        self.connection.close()
                        return
                    if mode == "slow_loris":
                        self.send_response(200)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        for i in range(0, len(body), 16):
                            self.wfile.write(body[i : i + 16])
                            self.wfile.flush()
                            time.sleep(0.5)
                        return
                # transparent pass-through (headers included — the CRC
                # header must survive the proxy)
                try:
                    with urllib.request.urlopen(
                        proxy.upstream + self.path, timeout=30
                    ) as resp:
                        body = resp.read()
                        self.send_response(resp.status)
                        for k, v in resp.headers.items():
                            if k.lower() in ("content-length", "x-tft-crc32c"):
                                self.send_header(k, v)
                        if "Content-Length" not in resp.headers:
                            self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                except urllib.error.HTTPError as e:
                    self.send_error(e.code, str(e.reason))
                except Exception:
                    self.send_error(502, "upstream failed")

            def log_message(self, fmt: str, *args: Any) -> None:
                pass

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="heal_chaos"
        )
        self._thread.start()

    def address(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def kill_process(pid: int) -> None:
    """SIGKILL — the classic clean-death fault (child seam ``sigkill``)."""
    try:
        os.kill(pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


class ProcessStall:
    """SIGSTOP a process for ``duration_s``, then SIGCONT — the
    stalled-not-dead fault (child seam ``sigstop``, lighthouse seam
    ``stall``): the victim is alive to every liveness poll while doing
    nothing, the long-tail failure mode clean deaths never exercise.
    ``start()`` returns immediately; ``join()`` waits for the CONT."""

    def __init__(self, pid: int, duration_s: float) -> None:
        self.pid = pid
        self.duration_s = duration_s
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ProcessStall":
        try:
            os.kill(self.pid, signal.SIGSTOP)
        except (ProcessLookupError, PermissionError):
            return self

        def cont() -> None:
            time.sleep(self.duration_s)
            try:
                os.kill(self.pid, signal.SIGCONT)
            except (ProcessLookupError, PermissionError):
                pass

        self._thread = threading.Thread(target=cont, daemon=True)
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


class RootProcess:
    """A root lighthouse hosted in a SUBPROCESS — the ``root`` seam's
    substrate. In-process lighthouses cannot be SIGKILLed without taking
    the harness down with them; this wrapper runs ``python -m
    torchft_tpu.lighthouse`` on a FIXED port (so managers' endpoint lists
    and a restart's address both survive the kill) with an optional WAL
    dir, peer list and standby role, and exposes the three root
    injectors:

    - :meth:`kill` — SIGKILL (the mid-promise crash; with a WAL dir the
      next :meth:`restart` replays to the pre-crash watermark).
    - :meth:`restart` — kill + respawn with the same port/WAL/peers (the
      recovery path; a deposed primary fences itself at startup when a
      peer took over meanwhile).
    - :meth:`partition` — SIGSTOP for ``duration_s`` then SIGCONT: the
      root is unreachable but ALIVE, the takeover + stall-self-fence
      path clean deaths never exercise.
    """

    def __init__(
        self,
        port: int,
        wal_dir: str = "",
        peers: str = "",
        standby: bool = False,
        takeover_ms: int = 0,
        min_replicas: int = 1,
        join_timeout_ms: int = 200,
        heartbeat_timeout_ms: int = 5000,
        extra_env: Optional[Dict[str, str]] = None,
    ) -> None:
        self.port = port
        self.wal_dir = wal_dir
        self.peers = peers
        self.standby = standby
        self.takeover_ms = takeover_ms
        self.min_replicas = min_replicas
        self.join_timeout_ms = join_timeout_ms
        self.heartbeat_timeout_ms = heartbeat_timeout_ms
        self.extra_env = dict(extra_env or {})
        self.proc: Optional[Any] = None
        self.restarts = 0
        self.spawn()

    def address(self) -> str:
        return f"http://localhost:{self.port}"

    @property
    def pid(self) -> Optional[int]:
        return None if self.proc is None else self.proc.pid

    def _argv(self) -> List[str]:
        import sys

        argv = [
            sys.executable,
            "-m",
            "torchft_tpu.lighthouse",
            "--role",
            "root",
            "--bind",
            f"[::]:{self.port}",
            "--min_replicas",
            str(self.min_replicas),
            "--join_timeout_ms",
            str(self.join_timeout_ms),
            "--heartbeat_timeout_ms",
            str(self.heartbeat_timeout_ms),
        ]
        if self.wal_dir:
            argv += ["--wal-dir", self.wal_dir]
        if self.peers:
            argv += ["--peers", self.peers]
        if self.standby:
            argv += ["--standby"]
        if self.takeover_ms:
            argv += ["--takeover-ms", str(self.takeover_ms)]
        return argv

    def spawn(self) -> None:
        import subprocess

        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # The child resolves `-m torchft_tpu.lighthouse` via PYTHONPATH,
        # not the harness's cwd.
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        env.update(self.extra_env)
        self.proc = subprocess.Popen(self._argv(), env=env)

    def status(self, timeout: float = 2.0) -> Optional[dict]:
        """One /status.json read, or None while unreachable."""
        try:
            with urllib.request.urlopen(
                self.address() + "/status.json", timeout=timeout
            ) as r:
                return json.loads(r.read().decode("utf-8"))
        except Exception:  # noqa: BLE001 - down/partitioned is the point
            return None

    def wait_serving(self, deadline_s: float = 20.0) -> dict:
        """Blocks until /status.json answers (any role); returns it."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            st = self.status()
            if st is not None:
                return st
            time.sleep(0.05)
        raise TimeoutError(f"root on port {self.port} never served status")

    def kill(self) -> None:
        """SIGKILL — the root seam's clean-crash fault."""
        if self.proc is not None and self.proc.poll() is None:
            kill_process(self.proc.pid)
            self.proc.wait(timeout=10)

    def restart(self) -> None:
        """kill + respawn on the same port/WAL/peers: the replay path."""
        self.kill()
        self.restarts += 1
        self.spawn()

    def partition(self, duration_s: float) -> ProcessStall:
        """SIGSTOP for ``duration_s`` then SIGCONT (started; join() the
        returned stall to wait for the CONT)."""
        assert self.proc is not None
        return ProcessStall(self.proc.pid, duration_s).start()

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                self.proc.kill()


class PublisherProcess:
    """A demo weight publisher hosted in a SUBPROCESS — the ``serving``
    seam's substrate (``python -m torchft_tpu.serving`` on a FIXED port,
    so relays keep dialing the same upstream across kills). The chaos
    point: ``TORCHFT_PS_DRIP_MS`` makes the publisher stream range
    bodies in 64 KiB dribbles, so :meth:`kill` reliably lands MID-range
    — the subscriber-side short-body/CRC ladder must avert the install,
    never tear it. :meth:`restart` respawns on the same port with a
    FRESH version history (new nonces over reused version numbers),
    which is exactly the torn-republish case the 400-nonce contract and
    the downstream regression-resync guard.

    The deterministic ``seed`` means every incarnation publishes the
    same weight trees (:func:`torchft_tpu.serving.demo_params`), so the
    harness can verify any subscriber's installed tree bit-for-bit
    without talking to the (possibly dead) publisher."""

    def __init__(
        self,
        port: int,
        wire: str = "q8",
        leaves: int = 4,
        elems: int = 16384,
        seed: int = 0,
        publish_every_ms: int = 250,
        snapshot_every: int = 4,
        keep: int = 16,
        drip_ms: int = 0,
        extra_env: Optional[Dict[str, str]] = None,
    ) -> None:
        self.port = port
        self.wire = wire
        self.leaves = leaves
        self.elems = elems
        self.seed = seed
        self.publish_every_ms = publish_every_ms
        self.snapshot_every = snapshot_every
        self.keep = keep
        self.drip_ms = drip_ms
        self.extra_env = dict(extra_env or {})
        self.proc: Optional[Any] = None
        self.restarts = 0
        self.spawn()

    def address(self) -> str:
        return f"http://[::1]:{self.port}"

    @property
    def pid(self) -> Optional[int]:
        return None if self.proc is None else self.proc.pid

    def _argv(self) -> List[str]:
        import sys

        return [
            sys.executable,
            "-m",
            "torchft_tpu.serving",
            "--port", str(self.port),
            "--wire", self.wire,
            "--leaves", str(self.leaves),
            "--elems", str(self.elems),
            "--seed", str(self.seed),
            "--publish-every-ms", str(self.publish_every_ms),
            "--snapshot-every", str(self.snapshot_every),
            "--keep", str(self.keep),
        ]

    def spawn(self) -> None:
        import subprocess

        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        if self.drip_ms > 0:
            env["TORCHFT_PS_DRIP_MS"] = str(self.drip_ms)
        env.update(self.extra_env)
        self.proc = subprocess.Popen(self._argv(), env=env)

    def status(self, timeout: float = 2.0) -> Optional[dict]:
        """One /ps/status read, or None while unreachable."""
        try:
            with urllib.request.urlopen(
                self.address() + "/ps/status", timeout=timeout
            ) as r:
                return json.loads(r.read().decode("utf-8"))
        except Exception:  # noqa: BLE001 - down IS a state here
            return None

    def wait_serving(self, deadline_s: float = 30.0, min_version: int = 0) -> dict:
        """Blocks until /ps/status answers with ``latest >=
        min_version``; returns the status."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            st = self.status()
            if st is not None and int(st.get("latest", -1)) >= min_version:
                return st
            time.sleep(0.05)
        raise TimeoutError(
            f"publisher on port {self.port} never reached v{min_version}"
        )

    def kill(self) -> None:
        """SIGKILL — with ``drip_ms`` set, this lands mid-range on any
        in-flight transfer (the serving seam's signature fault)."""
        if self.proc is not None and self.proc.poll() is None:
            kill_process(self.proc.pid)
            self.proc.wait(timeout=10)

    def restart(self) -> None:
        """kill + respawn on the same port: version numbers restart at 0
        under fresh nonces — the torn-republish path."""
        self.kill()
        self.restarts += 1
        self.spawn()

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                self.proc.kill()


def free_port() -> int:
    """Reserves an ephemeral port and releases it (the usual bind-probe;
    RootProcess needs FIXED ports so kills and restarts keep the
    address). The close-to-spawn window is racy in principle; harness
    fleets allocate their ports up front, back to back, so collisions
    would need an outside writer."""
    s = socket.socket(socket.AF_INET6, socket.SOCK_STREAM)
    try:
        s.bind(("::", 0))
        return s.getsockname()[1]
    finally:
        s.close()


def tear_shm(name: str) -> None:
    """Realizes the ``shm`` seam's ``tear``: unlinks the segment NAME so
    the next attach fails (the torn-segment-on-attach lifecycle fault;
    existing mappings stay valid, exactly like a crashed creator that
    never finished publishing)."""
    try:
        _native.shm_unlink(name)
    except RuntimeError:
        pass


# -- bench artifact stamping -------------------------------------------------


def bench_fault_stamp(plan: Optional[FaultPlan] = None, **bench_fields: Any) -> dict:
    """The ``fault_plan`` key every bench artifact carries: the seeded
    schedule that produced the run (explicit ``plan``, else the
    ``TORCHFT_CHAOS_SEED`` / ``TORCHFT_CHAOS_PLAN`` env contract), plus
    the bench's OWN fault knobs (kill cadence etc.) so a bench-observed
    anomaly replays via ``scripts/chaos_run.py --seed``."""
    out: Dict[str, Any] = dict(bench_fields)
    env_plan = os.environ.get("TORCHFT_CHAOS_PLAN")
    env_seed = os.environ.get("TORCHFT_CHAOS_SEED")
    if plan is not None:
        out.update(plan.fingerprint())
    elif env_plan:
        try:
            out.update(FaultPlan.from_json(env_plan).fingerprint())
        except (ValueError, KeyError, json.JSONDecodeError):
            out["plan_parse_error"] = True
            out["plan"] = env_plan
    elif env_seed:
        # Degrade, never raise: the stamp runs at artifact-write time,
        # the very last step of a potentially hour-long bench — a typo'd
        # seed must not discard the run's results.
        try:
            out["seed"] = int(env_seed)
        except ValueError:
            out["seed_parse_error"] = True
            out["seed"] = env_seed
    else:
        out["seed"] = None
    return out
