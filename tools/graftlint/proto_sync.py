"""Wire-contract drift check: torchft.proto <-> pb_fallback header.

When the real protobuf toolchain is absent the native layer serializes
with the handwritten ``native/src/pb_fallback/torchft.pb.h``.  Nothing
compiles the two against each other: a field renamed or renumbered in
``native/torchft.proto`` (the contract the Rust/protoc side speaks)
silently desynchronizes the fallback wire format — messages parse, the
drifted field just reads as its default.  This rule parses both and
diffs them two ways:

- every ``message`` in the proto must have a matching ``class`` in the
  header, and vice versa;
- within a message, every proto field name must be serialized by the
  header's ``AppendTo`` (members follow the ``<field_name>_``
  convention) and every member the header serializes must exist in the
  proto — with the *same field number* on both sides;
- internally, every field number ``AppendTo`` writes must have a
  ``case N:`` handler in ``Field`` (a write-only field round-trips to
  its default through the fallback parser).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Tuple

from . import Violation, relpath

RULE = "proto_sync"

PROTO = Path("native/torchft.proto")
HEADER = Path("native/src/pb_fallback/torchft.pb.h")


class Field(NamedTuple):
    number: int
    line: int


_MSG_RE = re.compile(r"^message\s+(\w+)\s*\{", re.M)
_CLASS_RE = re.compile(r"^class\s+(\w+)\s*\{", re.M)
# "repeated int64 step = 4;" — two identifier tokens before '=' keeps
# enum values ("UNKNOWN = 0;") and reserved/option lines from matching.
_PROTO_FIELD_RE = re.compile(
    r"^\s*(?:repeated\s+|optional\s+)?[A-Za-z_][\w.]*\s+([A-Za-z_]\w*)"
    r"\s*=\s*(\d+)\s*;",
    re.M,
)
_PUT_RE = re.compile(
    r"tft_pb::put_(?!tag\b|varint\b)\w+\(\s*out\s*,\s*(\d+)\s*,\s*(.*)"
)
# Raw-encoded fields write put_tag(out, N, wire) then put_varint(out, m_).
_PUT_TAG_RE = re.compile(r"tft_pb::put_tag\(\s*out\s*,\s*(\d+)\s*,")
_PUT_VARINT_RE = re.compile(r"tft_pb::put_varint\(\s*out\s*,\s*(.*)")
_FOR_RE = re.compile(r"for\s*\(.*?:\s*(\w+)_\s*\)")
_MEMBER_RE = re.compile(r"([A-Za-z]\w*)_(?![\w])")
# Single-field messages use "if (f == 1 && ...)" instead of a switch.
_CASE_RE = re.compile(r"case\s+(\d+)\s*:|\bf\s*==\s*(\d+)")


def _block(text: str, open_brace: int) -> str:
    """Text of a brace-balanced block starting at ``open_brace``
    (inclusive of the braces)."""
    depth = 0
    for i in range(open_brace, len(text)):
        c = text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return text[open_brace : i + 1]
    return text[open_brace:]


def _strip_nested(body: str) -> str:
    """Blanks nested enum blocks (their values would otherwise shadow
    field lines) while preserving line offsets."""
    out = body
    for m in re.finditer(r"\benum\s+\w+\s*\{", out):
        nested = _block(out, m.end() - 1)
        blank = "".join(c if c == "\n" else " " for c in nested)
        out = out[: m.end() - 1] + blank + out[m.end() - 1 + len(nested) :]
    return out


def parse_proto(text: str) -> Dict[str, Dict[str, Field]]:
    """{message: {field_name: Field}} of every top-level message."""
    out: Dict[str, Dict[str, Field]] = {}
    for m in _MSG_RE.finditer(text):
        body = _strip_nested(_block(text, m.end() - 1))
        base_line = text[: m.start()].count("\n") + 1
        fields: Dict[str, Field] = {}
        for fm in _PROTO_FIELD_RE.finditer(body):
            line = base_line + body[: fm.start()].count("\n")
            fields[fm.group(1)] = Field(int(fm.group(2)), line)
        out[m.group(1)] = fields
    return out


class HeaderMsg(NamedTuple):
    fields: Dict[str, Field]  # member name (sans trailing _) -> Field
    cases: frozenset  # field numbers Field() can parse
    line: int


def _method_body(cls_body: str, signature: str) -> Tuple[str, int]:
    """(body text, offset) of a method inside a class body, or ("", 0)."""
    m = re.search(signature, cls_body)
    if not m:
        return "", 0
    return _block(cls_body, cls_body.index("{", m.start())), m.start()


def parse_header(
    text: str, rel: str
) -> Tuple[Dict[str, HeaderMsg], List[Violation]]:
    out: Dict[str, HeaderMsg] = {}
    problems: List[Violation] = []
    for m in _CLASS_RE.finditer(text):
        cls_body = _block(text, m.end() - 1)
        base_line = text[: m.start()].count("\n") + 1
        append, aoff = _method_body(cls_body, r"void\s+AppendTo\s*\(")
        fields: Dict[str, Field] = {}
        loop_member: Optional[str] = None
        pending_tag: Optional[Field] = None
        pos = 0
        for raw in append.splitlines(keepends=True):
            fm = _FOR_RE.search(raw)
            if fm:
                loop_member = fm.group(1)
            tm = _PUT_TAG_RE.search(raw)
            if tm:
                pending_tag = Field(
                    int(tm.group(1)),
                    base_line
                    + cls_body[:aoff].count("\n")
                    + append[:pos].count("\n"),
                )
            vm = _PUT_VARINT_RE.search(raw)
            if vm and pending_tag is not None:
                members = _MEMBER_RE.findall(vm.group(1))
                if members:
                    fields[members[-1]] = pending_tag
                pending_tag = None
            pm = _PUT_RE.search(raw)
            if pm:
                line = (
                    base_line
                    + cls_body[:aoff].count("\n")
                    + append[:pos].count("\n")
                )
                members = _MEMBER_RE.findall(pm.group(2))
                name = members[-1] if members else loop_member
                if name is None:
                    problems.append(
                        Violation(
                            RULE,
                            rel,
                            line,
                            "%s.AppendTo writes field %s from an "
                            "unrecognized member expression"
                            % (m.group(1), pm.group(1)),
                        )
                    )
                else:
                    fields[name] = Field(int(pm.group(1)), line)
                if not fm:
                    loop_member = None
            pos += len(raw)
        parse, _ = _method_body(cls_body, r"bool\s+Field\s*\(")
        cases = frozenset(int(a or b) for a, b in _CASE_RE.findall(parse))
        out[m.group(1)] = HeaderMsg(fields, cases, base_line)
    return out, problems


def check(
    root: Path,
    proto_path: Optional[Path] = None,
    header_path: Optional[Path] = None,
) -> List[Violation]:
    proto_path = proto_path or root / PROTO
    header_path = header_path or root / HEADER
    proto_rel = relpath(root, proto_path)
    header_rel = relpath(root, header_path)

    messages = parse_proto(proto_path.read_text())
    classes, out = parse_header(header_path.read_text(), header_rel)

    if not messages:
        out.append(Violation(RULE, proto_rel, 1, "no messages parsed"))
    if not classes:
        out.append(Violation(RULE, header_rel, 1, "no classes parsed"))

    for name, fields in messages.items():
        cls = classes.get(name)
        if cls is None:
            out.append(
                Violation(
                    RULE,
                    header_rel,
                    1,
                    "message %s has no class in the pb_fallback header"
                    % name,
                )
            )
            continue
        for fname, f in fields.items():
            h = cls.fields.get(fname)
            if h is None:
                out.append(
                    Violation(
                        RULE,
                        proto_rel,
                        f.line,
                        "%s.%s (field %d) is not serialized by the "
                        "pb_fallback header" % (name, fname, f.number),
                    )
                )
            elif h.number != f.number:
                out.append(
                    Violation(
                        RULE,
                        header_rel,
                        h.line,
                        "%s.%s is field %d in the header but %d in the "
                        "proto" % (name, fname, h.number, f.number),
                    )
                )
        for fname, h in cls.fields.items():
            if fname not in fields:
                out.append(
                    Violation(
                        RULE,
                        header_rel,
                        h.line,
                        "%s.%s (field %d) serialized by the header but "
                        "absent from the proto" % (name, fname, h.number),
                    )
                )
        for fname, h in cls.fields.items():
            if h.number not in cls.cases:
                out.append(
                    Violation(
                        RULE,
                        header_rel,
                        h.line,
                        "%s.AppendTo writes field %d (%s) but Field() has "
                        "no case for it: the fallback parser drops it"
                        % (name, h.number, fname),
                    )
                )

    for name, cls in classes.items():
        if name not in messages:
            out.append(
                Violation(
                    RULE,
                    header_rel,
                    cls.line,
                    "class %s has no message in the proto" % name,
                )
            )
    return out
