"""Pallas wire-compression kernels: quantize/dequantize/cast ON DEVICE.

Every compressed wire used to pack on the HOST after a full-f32
device-to-host transfer, so compression saved network bytes but never the
device-link leg — the leg ``pop_op_stats`` flags as dominant on tunneled
TPU runtimes. These kernels emit the packed wire buffer on the
accelerator, so d2h bytes scale with the WIRE size, not the f32 size:

- :func:`quantize_q8` / :func:`quantize_q8_ef`: symmetric per-leaf int8
  quantization (absmax/127 scale, floored at 1e-12), the EF variant with
  the error-feedback residual carried as a DEVICE-RESIDENT f32 array that
  never crosses the link. The (q, scale) pair is the pre-packed leaf
  payload the native CommPlan decodes into its f32 staging
  (``plan_execute_pre``), replacing both the host-side
  ``quantize.quantize_with_feedback`` jit and the native
  ``plan_pack_ef`` on the hot path.
- :func:`cast_bf16`: round-to-nearest-even f32 -> bf16, the bf16 wire's
  pack cast (bit-identical to the native ``f32_to_bf16``; the existing
  plan tests pin jax's cast == the native cast).
- :func:`dequantize_q8`: the exact inverse decode (q * scale), for the
  allgather-transport payloads and the kernel round-trip oracle.

Numerics contract (the bit-identity oracle in tests/test_device_pack.py):
``quantize_q8_ef`` reproduces the FMA-free numpy EF reference — and
therefore the native ``plan_pack_ef`` — BIT FOR BIT: ``d = x + res``;
``scale = max(max|d|/127, 1e-12)``; ``q = clip(round_half_even(d/scale))``;
``dq = q * scale``; ``res' = d - dq``. The residual subtraction is wrapped
in ``jax.lax.optimization_barrier`` so XLA cannot contract ``d - q*scale``
into an fma (the documented last-ulp divergence of the jitted jax EF).
A non-finite leaf poisons its ENTIRE payload and carry — scale and the
new residual become NaN while the int8 codes zero, so the decode
``0 * NaN`` reproduces the host EF's whole-leaf NaN propagation.

Off-TPU the kernels run under ``interpret=True`` (the flash-attention
discipline), so CPU tier-1 exercises the identical code path; on TPU the
same bodies compile to Mosaic. Shapes are arbitrary: inputs flatten and
zero-pad to (rows, 128) lane tiles — padding is absmax-neutral (|0| never
raises a finite absmax) and its residual stays exactly 0.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128
# Rows per grid block: 256x128 f32 = 128 KiB per VMEM buffer, and a
# multiple of every dtype's sublane tile floor (f32 8, bf16 16, int8 32).
_BLOCK_ROWS = 256
# Scale floor, shared with quantize.quantize_with_feedback and the native
# plan_pack_ef: an all-zero leaf stays representable.
_SCALE_FLOOR = 1e-12


def _pick_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _grid_shape(n: int, interpret: bool) -> Tuple[int, int]:
    """(padded rows, block rows) for an n-element flat payload.

    Compiled (TPU): _BLOCK_ROWS-row VMEM blocks once the payload
    outgrows one (rows padded to the block multiple; the 32-row floor
    covers the int8 sublane tile). Interpret mode: ALWAYS one block —
    the interpreter's grid loop carries the full output through a
    dynamic_update_slice per step, so a multi-block grid costs
    O(grid x payload) copying while a single block has no VMEM ceiling
    to respect."""
    rows = _cdiv(max(n, 1), _LANES)
    if interpret or rows <= _BLOCK_ROWS:
        rows_pad = _cdiv(rows, 32) * 32
        return rows_pad, rows_pad
    return _cdiv(rows, _BLOCK_ROWS) * _BLOCK_ROWS, _BLOCK_ROWS


def _to_tiles(x: jax.Array, rows_pad: int) -> jax.Array:
    flat = x.reshape(-1).astype(jnp.float32)
    total = rows_pad * _LANES
    return jnp.pad(flat, (0, total - flat.size)).reshape(rows_pad, _LANES)


def _absmax_kernel(x_ref, out_ref):
    # Revisited (1, 1) output block: the TPU grid is sequential, so the
    # running max is deterministic; max() propagates NaN/Inf, which is the
    # non-finite signal the scale computation turns into a NaN scale.
    i = pl.program_id(0)
    m = jnp.max(jnp.abs(x_ref[...]))

    @pl.when(i == 0)
    def _init():
        out_ref[0, 0] = m

    @pl.when(i > 0)
    def _acc():
        out_ref[0, 0] = jnp.maximum(out_ref[0, 0], m)


def _absmax(tiles: jax.Array, block: int, interpret: bool) -> jax.Array:
    rows = tiles.shape[0]
    return pl.pallas_call(
        _absmax_kernel,
        grid=(rows // block,),
        in_specs=[pl.BlockSpec((block, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        interpret=interpret,
    )(tiles)


def _round32_mul(qf, s):
    """round_f32(qf * s), immune to fma contraction — the decode the ring
    peers run is a plain single-rounded f32 multiply, and the residual
    needs ``d - round32(qf*s)`` with TWO roundings; a compiler-contracted
    ``fma(-qf, s, d)`` rounds once and drifts the carry at the last ulp
    (XLA's loop fusion contracts straight through optimization_barrier on
    CPU). Split ``s`` into 12-bit mantissa halves by masking (exact);
    both partial products are EXACT (|qf| <= 127 has <= 7 significand
    bits, each half <= 12), so the single f32 add performs the one
    rounding — and contracting either multiply into an fma cannot change
    an exact product's value."""
    bits = jax.lax.bitcast_convert_type(s, jnp.uint32)
    s_hi = jax.lax.bitcast_convert_type(
        bits & jnp.uint32(0xFFFFF000), jnp.float32
    )
    s_lo = s - s_hi  # exact: the masked-off low mantissa bits
    return qf * s_hi + qf * s_lo


def _quant_kernel(d_ref, scale_ref, q_ref, res_out_ref):
    # d_ref already holds the EF-adjusted payload (x + res, one exact
    # elementwise add). scale_ref holds the RAW scale max(absmax/127,
    # floor): finite for a finite leaf, NaN/Inf when the leaf diverged.
    # On the poison path the codes zero and the caller's NaN scale
    # carries the signal (0 * NaN decodes to NaN on every element — the
    # host EF's whole-leaf propagation); the residual poisons here.
    s = scale_ref[0, 0]
    d = d_ref[...]
    v = jnp.clip(jnp.round(d / s), -127.0, 127.0)
    qf = jnp.where(jnp.isfinite(v), v, 0.0)
    q_ref[...] = qf.astype(jnp.int8)
    res_out_ref[...] = jnp.where(
        jnp.isfinite(s), d - _round32_mul(qf, s), jnp.nan
    )


def _quantize_tiles(
    tiles: jax.Array, res_tiles: jax.Array, block: int, interpret: bool
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(q tiles int8, scale (), res tiles f32). `scale` is the FINAL wire
    scale: NaN when the leaf is non-finite."""
    rows = tiles.shape[0]
    # The EF-adjusted payload, computed ONCE and fed to both passes — the
    # absmax (and therefore the scale) is over d = x + res, not x. One
    # exact elementwise f32 add, identical to the oracle's.
    d = tiles + res_tiles
    absmax = _absmax(d, block, interpret)[0, 0]
    # The denominator is made DATA-DEPENDENT (0*x cannot be folded away
    # for floats — x may be NaN/Inf) because XLA compiles division by a
    # LITERAL constant into a reciprocal multiply under jit, which
    # mis-rounds ~1/3 of scales by one ulp and would break bit-identity
    # with the native EF's true `absmax / 127.0f` division. As a bonus a
    # non-finite absmax NaNs the denominator, which NaNs the scale — the
    # poison signal either way.
    denom = jnp.float32(127.0) + 0.0 * absmax
    scale_raw = jnp.maximum(absmax / denom, _SCALE_FLOOR)  # NaN if hot
    q, res_out = pl.pallas_call(
        _quant_kernel,
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), jnp.int8),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(d, scale_raw.reshape(1, 1))
    scale = jnp.where(jnp.isfinite(scale_raw), scale_raw, jnp.nan)
    return q, scale, res_out


def quantize_q8_ef(
    x: jax.Array, res: jax.Array, *, interpret: Optional[bool] = None
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Symmetric int8 quantization with error feedback, on device.

    ``x``: any float leaf (upcast to f32 like the native EF); ``res``: the
    f32 carry, same shape. Returns ``(q int8, scale f32 scalar, new_res
    f32)``, each shaped like ``x`` (scale a 0-d array). The caller owns
    the carry: keep it on device, restore/zero it under the same
    heal/abort discipline as ``plan_reset_feedback``. Traceable — callers
    jit it (the device packer does)."""
    interpret = _pick_interpret(interpret)
    n = x.size
    if n == 0:
        return (jnp.zeros(x.shape, jnp.int8), jnp.float32(_SCALE_FLOOR),
                jnp.zeros(x.shape, jnp.float32))
    rows_pad, block = _grid_shape(n, interpret)
    q, scale, res_out = _quantize_tiles(
        _to_tiles(x, rows_pad), _to_tiles(res, rows_pad), block, interpret
    )
    return (
        q.reshape(-1)[:n].reshape(x.shape),
        scale,
        res_out.reshape(-1)[:n].reshape(x.shape),
    )


def quantize_q8(
    x: jax.Array, *, interpret: Optional[bool] = None
) -> Tuple[jax.Array, jax.Array]:
    """EF-free symmetric int8 quantization: ``(q, scale)`` for payloads
    with no carry (e.g. the int8 allgather transport). Same scale/round/
    poison semantics as :func:`quantize_q8_ef` with a zero residual."""
    q, scale, _ = quantize_q8_ef(
        x, jnp.zeros(x.shape, jnp.float32), interpret=interpret
    )
    return q, scale


def _dequant_kernel(q_ref, scale_ref, o_ref):
    o_ref[...] = q_ref[...].astype(jnp.float32) * scale_ref[0, 0]


def dequantize_q8(
    q: jax.Array, scale: jax.Array, *, interpret: Optional[bool] = None
) -> jax.Array:
    """Exact decode ``q * scale`` (the native plan_pack_pre_range's
    arithmetic), on device. A NaN scale poisons the whole leaf."""
    interpret = _pick_interpret(interpret)
    n = q.size
    if n == 0:
        return jnp.zeros(q.shape, jnp.float32)
    rows_pad, block = _grid_shape(n, interpret)
    flat = q.reshape(-1)
    tiles = jnp.pad(flat, (0, rows_pad * _LANES - n)).reshape(
        rows_pad, _LANES
    )
    out = pl.pallas_call(
        _dequant_kernel,
        grid=(rows_pad // block,),
        in_specs=[
            pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_pad, _LANES), jnp.float32),
        interpret=interpret,
    )(tiles, jnp.asarray(scale, jnp.float32).reshape(1, 1))
    return out.reshape(-1)[:n].reshape(q.shape)


def _cast_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...].astype(jnp.bfloat16)


def cast_bf16(
    x: jax.Array, *, interpret: Optional[bool] = None
) -> jax.Array:
    """f32 -> bf16 with round-to-nearest-even, on device: the bf16 wire's
    pack cast, emitting the 2-byte words that cross the device link."""
    interpret = _pick_interpret(interpret)
    n = x.size
    if n == 0:
        return jnp.zeros(x.shape, jnp.bfloat16)
    rows_pad, block = _grid_shape(n, interpret)
    out = pl.pallas_call(
        _cast_kernel,
        grid=(rows_pad // block,),
        in_specs=[pl.BlockSpec((block, _LANES), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_pad, _LANES), jnp.bfloat16),
        interpret=interpret,
    )(_to_tiles(x, rows_pad))
    return out.reshape(-1)[:n].reshape(x.shape)
