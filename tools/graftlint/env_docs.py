"""Every TORCHFT_* knob the product code reads is documented.

An undocumented env knob is an operational trap: it changes ring behavior
(and sometimes wire schedules every member must agree on) with no
discoverable contract. The rule scans the shipped surfaces — Python under
``torchft_tpu/`` and C++ under ``native/src/`` — for environment READS of
``TORCHFT_*`` names and requires each to appear in ``docs/OPERATIONS.md``.
Tests and benches that read a knob exercise the same documented surface,
so only the product tree is scanned.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from . import Violation, relpath

RULE = "env_docs"

DOCS = Path("docs/OPERATIONS.md")
# scripts/ joined the scan when the chaos harness grew operator-facing
# TORCHFT_CHAOS_* knobs: an undocumented replay knob defeats the whole
# "reproduce any failure from its printed seed" contract.
SCAN_DIRS = (Path("torchft_tpu"), Path("native/src"), Path("scripts"))

# Read forms only (setting an env var for a child process is the caller's
# business): os.environ.get("X"), os.getenv("X"), os.environ["X"] in
# Python; getenv("X") / std::getenv("X") in C++. Two indirect Python
# forms also count as reads — the typed helpers durable.py/serving.py
# grew (``_env_int("TORCHFT_X", d)``) and the ``_ENV_FOO = "TORCHFT_X"``
# module constants profiling.py routes its reads through; both are how
# a knob escapes a literal-only scan.
_PY_READ = re.compile(
    r"(?:os\.getenv\(|os\.environ\.get\(|os\.environ\[)\s*"
    r"[\"'](TORCHFT_[A-Z0-9_]+)[\"']",
    re.S,
)
_PY_HELPER_READ = re.compile(
    r"\b_env_[a-z_]+\(\s*[\"'](TORCHFT_[A-Z0-9_]+)[\"']"
)
_PY_CONST_DEF = re.compile(
    r"^(_ENV_[A-Z0-9_]+)\s*=\s*[\"'](TORCHFT_[A-Z0-9_]+)[\"']", re.M
)
_CC_READ = re.compile(r"getenv\(\s*\"(TORCHFT_[A-Z0-9_]+)\"")


def _py_const_reads(text: str):
    """(knob, match_start) for each env read routed through an ``_ENV_*``
    module constant. Only constants actually passed to a read form count
    (the definition alone is not a read)."""
    consts = dict(_PY_CONST_DEF.findall(text))
    out = []
    for name, knob in consts.items():
        for m in re.finditer(
            r"(?:os\.getenv\(|os\.environ\.get\(|os\.environ\[)\s*"
            + re.escape(name) + r"\b",
            text,
        ):
            out.append((knob, m.start()))
    return out


def collect_reads(root: Path, dirs: Sequence[Path]) -> Dict[str, List[str]]:
    """{knob: ["file:line", ...]} across the scanned trees."""
    reads: Dict[str, List[str]] = {}
    for d in dirs:
        base = root / d
        if not base.exists():
            continue
        for path in sorted(base.rglob("*")):
            if path.suffix == ".py":
                pattern = _PY_READ
            elif path.suffix in (".cc", ".h"):
                pattern = _CC_READ
            else:
                continue
            text = path.read_text()
            rel = str(path.relative_to(root))
            hits = [
                (m.group(1), m.start()) for m in pattern.finditer(text)
            ]
            if pattern is _PY_READ:
                hits += [
                    (m.group(1), m.start())
                    for m in _PY_HELPER_READ.finditer(text)
                ]
                hits += _py_const_reads(text)
            for knob, start in sorted(hits, key=lambda h: h[1]):
                line = text[:start].count("\n") + 1
                reads.setdefault(knob, []).append(f"{rel}:{line}")
    return reads


def check(
    root: Path,
    docs_path: Optional[Path] = None,
    scan_dirs: Optional[Sequence[Path]] = None,
) -> List[Violation]:
    docs_path = docs_path or root / DOCS
    documented = set(
        re.findall(r"TORCHFT_[A-Z0-9_]+", docs_path.read_text())
    )
    docs_rel = relpath(root, docs_path)

    out: List[Violation] = []
    for knob, sites in sorted(
        collect_reads(root, scan_dirs or SCAN_DIRS).items()
    ):
        if knob not in documented:
            first = sites[0]
            rel, _, line = first.rpartition(":")
            out.append(
                Violation(
                    RULE,
                    rel,
                    int(line),
                    f"{knob} is read here (and at "
                    f"{len(sites) - 1} other site(s)) but not documented "
                    f"in {docs_rel}",
                )
            )
    return out
