"""Pipeline parallelism: GPipe schedule numerics + gradient parity.

The pipelined program must be bit-for-bit a reordering of the sequential
layer stack — same outputs, same grads — with stage weights sharded over
the ``pipe`` axis.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import HAS_SHARD_MAP, SHARD_MAP_SKIP

if not HAS_SHARD_MAP:
    # pipeline_blocks resolves jax.shard_map at trace time: every test
    # here drives it, so skip the module wholesale
    pytest.skip(SHARD_MAP_SKIP, allow_module_level=True)

from torchft_tpu.parallel import make_mesh, shard_pytree
from torchft_tpu.pipeline import pipeline_blocks, stack_blocks, stage_specs


def _mk_blocks(n_layers, d, key):
    ks = jax.random.split(key, n_layers)
    return [
        {
            "w": jax.random.normal(k, (d, d)) * (d ** -0.5),
            "b": jax.random.normal(k, (d,)) * 0.1,
        }
        for k in ks
    ]


def _block_fn(p, x):
    return x + jnp.tanh(x @ p["w"] + p["b"])


def _sequential(blocks, x):
    for p in blocks:
        x = _block_fn(p, x)
    return x


@pytest.mark.parametrize("n_stages,microbatches", [(2, 4), (4, 2), (4, 8)])
def test_pipeline_matches_sequential(n_stages, microbatches):
    d, n_layers = 16, 8
    blocks = _mk_blocks(n_layers, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, d))
    mesh = make_mesh(
        {"pipe": n_stages}, devices=jax.devices()[:n_stages]
    )
    stacked = stack_blocks(blocks)
    out = pipeline_blocks(
        _block_fn, stacked, x, mesh=mesh, microbatches=microbatches
    )
    ref = _sequential(blocks, x)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_pipeline_grads_match_sequential():
    d, n_layers, n_stages = 8, 4, 4
    blocks = _mk_blocks(n_layers, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, d))
    mesh = make_mesh(
        {"pipe": n_stages}, devices=jax.devices()[:n_stages]
    )
    stacked = stack_blocks(blocks)

    def loss_pp(stacked, x):
        return jnp.sum(
            pipeline_blocks(
                _block_fn, stacked, x, mesh=mesh, microbatches=2
            ) ** 2
        )

    def loss_seq(blocks, x):
        return jnp.sum(_sequential(blocks, x) ** 2)

    g_pp = jax.grad(loss_pp)(stacked, x)
    g_seq = stack_blocks(
        [g for g in jax.grad(loss_seq)(blocks, x)]
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(g_pp), jax.tree_util.tree_leaves(g_seq)
    ):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_pipeline_composes_with_dp_and_sharded_stage_weights():
    d, n_layers = 8, 4
    blocks = _mk_blocks(n_layers, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, d))
    mesh = make_mesh({"data": 2, "pipe": 4})
    stacked = shard_pytree(
        stack_blocks(blocks), stage_specs(stack_blocks(blocks)), mesh
    )
    out = jax.jit(
        functools.partial(
            pipeline_blocks, _block_fn, mesh=mesh, microbatches=2,
            data_axis="data",
        )
    )(stacked, x)
    np.testing.assert_allclose(
        out, _sequential(blocks, x), atol=1e-5, rtol=1e-5
    )


def test_pipeline_under_jit_and_remat():
    d, n_layers, n_stages = 8, 4, 2
    blocks = _mk_blocks(n_layers, d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, d))
    mesh = make_mesh(
        {"pipe": n_stages}, devices=jax.devices()[:n_stages]
    )
    stacked = stack_blocks(blocks)
    block = jax.checkpoint(_block_fn)

    @jax.jit
    def loss(stacked, x):
        return jnp.sum(
            pipeline_blocks(
                block, stacked, x, mesh=mesh, microbatches=2
            )
        )

    g = jax.grad(loss)(stacked, x)
    assert np.isfinite(
        np.asarray(jax.tree_util.tree_leaves(g)[0])
    ).all()


def test_bad_divisibility_raises():
    d = 8
    blocks = _mk_blocks(3, d, jax.random.PRNGKey(0))
    mesh = make_mesh({"pipe": 2}, devices=jax.devices()[:2])
    x = jnp.ones((4, d))
    with pytest.raises(ValueError, match="divisible"):
        pipeline_blocks(
            _block_fn, stack_blocks(blocks), x, mesh=mesh, microbatches=2
        )
    blocks4 = _mk_blocks(4, d, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_blocks(
            _block_fn, stack_blocks(blocks4), jnp.ones((3, d)), mesh=mesh,
            microbatches=2,
        )
    # with a data axis the split happens on the PER-SHARD batch: global
    # B=8 divides by 8 microbatches but the per-shard batch of 4 does not
    mesh_dp = make_mesh({"data": 2, "pipe": 4})
    with pytest.raises(ValueError, match="per-shard"):
        pipeline_blocks(
            _block_fn, stack_blocks(blocks4), jnp.ones((8, d)),
            mesh=mesh_dp, microbatches=8, data_axis="data",
        )
