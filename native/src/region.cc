#include "region.h"

#include <sys/socket.h>

#include <algorithm>
#include <cctype>
#include <chrono>
#include <functional>

#include "http_util.h"
#include "log.h"
#include "wire.h"

namespace tft {

using torchft_tpu::ErrorResponse;
using torchft_tpu::QuorumMember;

RegionLighthouse::RegionLighthouse(const std::string& bind_addr,
                                   const std::string& root_addr,
                                   const std::string& region_id,
                                   const RegionOpt& opt)
    : root_addr_(root_addr),
      root_endpoints_(split_addr_list(root_addr)),
      region_id_(region_id),
      opt_(opt),
      listener_(std::make_unique<Listener>(bind_addr)),
      hostname_(local_hostname()) {
  if (root_endpoints_.empty()) {
    throw std::runtime_error("region lighthouse: empty root address");
  }
  lh_opt_.heartbeat_timeout_ms = opt_.heartbeat_timeout_ms;
  accept_thread_ = std::thread([this] { accept_loop(); });
  digest_thread_ = std::thread([this] { digest_loop(); });
  poll_thread_ = std::thread([this] { poll_loop(); });
  LOG_INFO("Region lighthouse " << region_id_ << " listening on " << address()
                                << " (root " << root_addr_ << ")");
}

RegionLighthouse::~RegionLighthouse() { shutdown(); }

std::string RegionLighthouse::address() const {
  return "http://" + hostname_ + ":" + std::to_string(listener_->port());
}

uint16_t RegionLighthouse::port() const { return listener_->port(); }

void RegionLighthouse::shutdown() {
  {
    // Flag + notify under the cv's mutex so waiters can't miss the wakeup.
    MutexLock lock(mu_);
    if (shutting_down_.exchange(true)) return;
    quorum_cv_.notify_all();
    digest_cv_.notify_all();
  }
  // Wake the root-connection threads out of any blocking IO.
  int fd = digest_fd_.exchange(-1);
  if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  fd = poll_fd_.exchange(-1);
  if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  listener_->close();
  if (accept_thread_.joinable()) accept_thread_.join();
  if (digest_thread_.joinable()) digest_thread_.join();
  if (poll_thread_.joinable()) poll_thread_.join();
  conns_.shutdown_all();
}

void RegionLighthouse::accept_loop() {
  while (!shutting_down_) {
    Socket sock = listener_->accept();
    if (!sock.valid()) return;
    conns_.spawn(std::move(sock), [this](Socket& s) { handle_conn(s); });
  }
}

namespace {

// Shutdown-aware backoff nap for the root-connection loops (they cannot
// park on a condvar while holding no state worth waking for, but must not
// stall shutdown behind a multi-second backoff either).
void nap_ms(int64_t total, const std::atomic<bool>& stop) {
  while (total > 0 && !stop) {
    int64_t chunk = total < 100 ? total : 100;
    struct timespec ts;
    ts.tv_sec = chunk / 1000;
    ts.tv_nsec = (chunk % 1000) * 1000000;
    nanosleep(&ts, nullptr);
    total -= chunk;
  }
}

} // namespace

void RegionLighthouse::digest_loop() {
  Socket sock;
  int failures = 0;
  size_t endpoint = 0;
  uint64_t seed = std::hash<std::string>{}(region_id_);
  while (!shutting_down_) {
    torchft_tpu::RegionDigestRequest req;
    req.set_region_id(region_id_);
    std::vector<std::string> departed;
    int64_t built_ms;
    {
      UniqueMutexLock lock(mu_);
      if (!digest_urgent_ && !shutting_down_)
        digest_cv_.wait_for(lock,
                            std::chrono::milliseconds(opt_.digest_interval_ms));
      if (shutting_down_) break;
      digest_urgent_ = false;
      prune_expired(state_, now_ms(), lh_opt_);
      built_ms = now_ms();
      digest_to_pb(make_digest(state_, built_ms, lh_opt_), &req);
      departed.swap(departed_pending_);
    }
    for (const auto& d : departed) req.add_departed(d);

    try {
      if (!sock.valid()) {
        sock = connect_with_retry(
            root_endpoints_[endpoint % root_endpoints_.size()],
            std::min<int64_t>(2000, opt_.connect_timeout_ms));
        digest_fd_ = sock.fd();
        if (shutting_down_) break;
      }
      int64_t deadline = now_ms() + opt_.connect_timeout_ms;
      send_msg(sock, MsgType::kRegionDigestReq, req, deadline);
      recv_expect<torchft_tpu::RegionDigestResponse>(
          sock, MsgType::kRegionDigestResp, deadline);
      failures = 0;
      MutexLock lock(mu_);
      root_connected_ = true;
      digests_sent_ += 1;
      last_digest_ms_ = now_ms();
      digest_built_ms_ = built_ms;
    } catch (const std::exception& e) {
      sock.close();
      digest_fd_ = -1;
      failures += 1;
      // Rotate through the root failover set: a standby answers its
      // UNAVAILABLE rejection (an RpcError landing here), a dead root
      // fails to connect — either way the next attempt tries the next
      // endpoint, finding a fresh active root within one walk.
      endpoint = (endpoint + 1) % root_endpoints_.size();
      {
        MutexLock lock(mu_);
        root_connected_ = false;
        // Departs must not be lost to a root outage; re-queue them.
        for (const auto& d : departed) departed_pending_.push_back(d);
      }
      if (failures == 1) LOG_WARN("digest push to root failed: " << e.what());
      nap_ms(backoff_ms(failures, 100, 5000, seed), shutting_down_);
    }
  }
  digest_fd_ = -1;
}

void RegionLighthouse::poll_loop() {
  Socket sock;
  int failures = 0;
  size_t endpoint = 0;
  uint64_t seed = std::hash<std::string>{}(region_id_) ^ 0x5eedULL;
  while (!shutting_down_) {
    int64_t gen;
    {
      MutexLock lock(mu_);
      gen = root_gen_;
    }
    try {
      if (!sock.valid()) {
        sock = connect_with_retry(
            root_endpoints_[endpoint % root_endpoints_.size()],
            std::min<int64_t>(2000, opt_.connect_timeout_ms));
        poll_fd_ = sock.fd();
        if (shutting_down_) break;
        // Fresh connection: the broadcast generation belongs to a root
        // INCARNATION. After a root restart its counter starts over, so a
        // carried-over min_gen would park every poll forever. Resetting
        // costs at worst one duplicate republish of a quorum we already
        // saw (waiters re-check membership; harmless).
        MutexLock lock(mu_);
        root_gen_ = 0;
        gen = 0;
      }
      torchft_tpu::RegionPollRequest req;
      req.set_min_gen(gen);
      req.set_timeout_ms(10000);
      int64_t deadline = now_ms() + 15000;
      send_msg(sock, MsgType::kRegionPollReq, req, deadline);
      auto resp = recv_expect<torchft_tpu::RegionPollResponse>(
          sock, MsgType::kRegionPollResp, deadline);
      failures = 0;
      MutexLock lock(mu_);
      root_gen_ = resp.gen();
      latest_quorum_ = resp.quorum();
      quorum_refresh_ms_ = now_ms();
      // The root consumed every registered participant when it formed this
      // quorum; mirror that clear so waiters not in the quorum re-register
      // — exactly the flat flow. EXCEPT registrations newer than the last
      // forwarded digest: the root never saw those, so clearing them would
      // silently drop quorum intent for up to a renewal period.
      for (auto it = state_.participants.begin();
           it != state_.participants.end();) {
        auto hb = state_.heartbeats.find(it->first);
        int64_t touched = hb == state_.heartbeats.end() ? 0 : hb->second;
        if (touched > digest_built_ms_) {
          ++it; // never forwarded; keep its registration live
        } else {
          it = state_.participants.erase(it);
        }
      }
      quorum_gen_ += 1;
      quorum_cv_.notify_all();
    } catch (const RpcError& e) {
      if (e.code == ErrorResponse::DEADLINE_EXCEEDED) {
        // No new quorum inside the poll window; the error frame was fully
        // consumed, so the connection is still in sync. Just re-poll.
        continue;
      }
      // Any other error frame — a standby root's UNAVAILABLE rejection
      // included — walks to the next endpoint of the failover set.
      sock.close();
      poll_fd_ = -1;
      failures += 1;
      endpoint = (endpoint + 1) % root_endpoints_.size();
      nap_ms(backoff_ms(failures, 100, 5000, seed), shutting_down_);
    } catch (const std::exception&) {
      sock.close();
      poll_fd_ = -1;
      failures += 1;
      endpoint = (endpoint + 1) % root_endpoints_.size();
      nap_ms(backoff_ms(failures, 100, 5000, seed), shutting_down_);
    }
  }
  poll_fd_ = -1;
}

void RegionLighthouse::register_participant_locked(const QuorumMember& member) {
  state_.heartbeats[member.replica_id()] = now_ms();
  state_.participants[member.replica_id()] =
      ParticipantDetails{now_ms(), member};
  digest_urgent_ = true;
  digest_cv_.notify_all();
}

void RegionLighthouse::handle_conn(Socket& sock) {
  try {
    std::string req_head;
    if (sniff_http(sock, req_head)) {
      handle_http(sock, req_head);
      return;
    }

    while (true) {
      auto [type, payload] = recv_frame(sock);
      switch (type) {
        case MsgType::kLighthouseQuorumReq:
          handle_quorum_req(sock, payload);
          break;
        case MsgType::kLighthouseHeartbeatReq: {
          torchft_tpu::LighthouseHeartbeatRequest req;
          req.ParseFromString(payload);
          {
            MutexLock lock(mu_);
            // A first-seen member must reach the root promptly: another
            // region's urgent quorum could otherwise form without it and
            // the split-brain guard would then park that quorum's
            // stragglers for a whole digest interval.
            if (!state_.heartbeats.count(req.replica_id())) {
              digest_urgent_ = true;
              digest_cv_.notify_all();
            }
            state_.heartbeats[req.replica_id()] = now_ms();
          }
          send_msg(sock, MsgType::kLighthouseHeartbeatResp,
                   torchft_tpu::LighthouseHeartbeatResponse());
          break;
        }
        case MsgType::kLeaseRenewReq: {
          torchft_tpu::LeaseRenewRequest req;
          if (!req.ParseFromString(payload)) {
            send_error(sock, ErrorResponse::INVALID_ARGUMENT,
                       "bad lease renew request");
            return;
          }
          std::vector<LeaseEntry> entries = lease_entries_from_pb(req);
          bool urgent = false;
          for (const auto& e : entries) urgent |= e.participating;
          torchft_tpu::LeaseRenewResponse resp;
          {
            MutexLock lock(mu_);
            // First-seen members propagate urgently too (see heartbeat).
            for (const auto& e : entries)
              urgent |= !state_.heartbeats.count(e.replica_id);
            apply_lease_batch(state_, entries, now_ms());
            if (urgent) {
              // Quorum intent must reach the root promptly, not on the
              // next periodic digest.
              digest_urgent_ = true;
              digest_cv_.notify_all();
            }
            resp.set_quorum_id(latest_quorum_.quorum_id());
          }
          send_msg(sock, MsgType::kLeaseRenewResp, resp);
          break;
        }
        case MsgType::kDepartReq: {
          torchft_tpu::DepartRequest req;
          if (!req.ParseFromString(payload) || req.replica_id().empty()) {
            send_error(sock, ErrorResponse::INVALID_ARGUMENT,
                       "missing replica_id");
            return;
          }
          {
            MutexLock lock(mu_);
            apply_depart(state_, req.replica_id());
            departed_pending_.push_back(req.replica_id());
            digest_urgent_ = true;
            digest_cv_.notify_all();
          }
          send_msg(sock, MsgType::kDepartResp, torchft_tpu::DepartResponse());
          break;
        }
        default:
          send_error(sock, ErrorResponse::INVALID_ARGUMENT,
                     "unexpected message type");
          return;
      }
    }
  } catch (const std::exception&) {
    // peer went away
  }
}

void RegionLighthouse::handle_quorum_req(Socket& sock, const std::string& payload) {
  torchft_tpu::LighthouseQuorumRequest req;
  if (!req.ParseFromString(payload) || !req.has_requester()) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "missing requester");
    return;
  }
  const QuorumMember& requester = req.requester();
  LOG_INFO("region " << region_id_ << ": quorum request for replica "
                     << requester.replica_id());

  int64_t deadline = req.timeout_ms() <= 0 ? -1 : now_ms() + req.timeout_ms();

  UniqueMutexLock lock(mu_);
  register_participant_locked(requester);
  int64_t gen = quorum_gen_;

  while (true) {
    // Wait for a root quorum newer than our subscription point.
    while (quorum_gen_ == gen && !shutting_down_) {
      if (deadline < 0) {
        quorum_cv_.wait(lock);
      } else {
        int64_t remain = deadline - now_ms();
        if (remain <= 0) {
          lock.unlock();
          send_error(sock, ErrorResponse::DEADLINE_EXCEEDED,
                     "region lighthouse quorum timed out");
          return;
        }
        quorum_cv_.wait_for(lock, std::chrono::milliseconds(remain));
      }
    }
    if (shutting_down_) {
      lock.unlock();
      send_error(sock, ErrorResponse::CANCELLED,
                 "region lighthouse shutting down");
      return;
    }
    gen = quorum_gen_;
    bool in_quorum = false;
    for (const auto& p : latest_quorum_.participants()) {
      if (p.replica_id() == requester.replica_id()) {
        in_quorum = true;
        break;
      }
    }
    if (in_quorum) {
      torchft_tpu::LighthouseQuorumResponse resp;
      *resp.mutable_quorum() = latest_quorum_;
      lock.unlock();
      send_msg(sock, MsgType::kLighthouseQuorumResp, resp);
      return;
    }
    // A quorum formed without us; re-register (urgent digest) and wait on.
    register_participant_locked(requester);
  }
}

std::string RegionLighthouse::status_json() {
  Json j;
  {
    MutexLock lock(mu_);
    int64_t now = now_ms();
    JsonObject o;
    o["role"] = std::string("region");
    o["region_id"] = region_id_;
    o["root_addr"] = root_addr_;
    o["root_connected"] = root_connected_;
    o["quorum_id"] = latest_quorum_.quorum_id();
    o["quorum_gen"] = quorum_gen_;
    if (quorum_refresh_ms_ >= 0) {
      o["quorum_age_ms"] = now - quorum_refresh_ms_;
    } else {
      o["quorum_age_ms"] = Json();
    }
    if (latest_quorum_.participants_size() > 0) {
      o["quorum"] = quorum_to_json(latest_quorum_);
    } else {
      o["quorum"] = Json();
    }
    JsonArray members;
    for (const auto& [replica_id, last] : state_.heartbeats) {
      JsonObject m;
      m["replica_id"] = replica_id;
      int64_t ttl = lease_ttl_for(state_, replica_id, lh_opt_);
      m["ttl_ms"] = ttl;
      m["lease_remaining_ms"] = last + ttl - now;
      m["participating"] = state_.participants.count(replica_id) > 0;
      auto st = state_.member_status.find(replica_id);
      if (st != state_.member_status.end()) {
        try {
          m["status"] = Json::parse(st->second);
        } catch (const std::exception&) {
          m["status"] = st->second; // unparseable digest: surface raw
        }
      }
      members.push_back(Json(std::move(m)));
    }
    o["members"] = Json(std::move(members));
    o["digests_sent"] = digests_sent_;
    if (last_digest_ms_ >= 0) {
      o["last_digest_age_ms"] = now - last_digest_ms_;
    } else {
      o["last_digest_age_ms"] = Json();
    }
    j = Json(std::move(o));
  }
  JsonObject& o = j.as_object();
  o["open_conns"] = static_cast<int64_t>(conns_.size());
  o["address"] = address();
  return j.dump();
}

std::string RegionLighthouse::quorum_json() {
  JsonObject o;
  {
    MutexLock lock(mu_);
    o["cached"] = true;
    o["quorum_id"] = latest_quorum_.quorum_id();
    o["root_connected"] = root_connected_;
    if (quorum_refresh_ms_ >= 0) {
      o["age_ms"] = now_ms() - quorum_refresh_ms_;
      o["quorum"] = latest_quorum_.participants_size() > 0
                        ? quorum_to_json(latest_quorum_)
                        : Json();
    } else {
      o["age_ms"] = Json(); // no root quorum ever seen
      o["quorum"] = Json();
    }
  }
  o["region_id"] = region_id_;
  return Json(std::move(o)).dump();
}

void RegionLighthouse::handle_http(Socket& sock, const std::string& head) {
  std::istringstream is(head);
  std::string method, path;
  is >> method >> path;

  if (method == "GET" && path == "/status.json") {
    http_respond(sock, 200, "application/json", status_json());
  } else if (method == "GET" && path == "/quorum.json") {
    // Served from the region-side cache: no root traffic per request.
    http_respond(sock, 200, "application/json", quorum_json());
  } else if (method == "GET" && (path == "/" || path.empty())) {
    http_respond(sock, 200, "text/html",
                 "<html><body><h1>torchft_tpu region lighthouse " +
                     html_escape(region_id_) +
                     "</h1><p>See <a href='/status.json'>/status.json</a>"
                     "</p></body></html>");
  } else {
    http_respond(sock, 404, "text/plain", "not found");
  }
}

} // namespace tft
