"""Three-way C-API bridge check: capi.cc <-> _native.py <-> _native.pyi.

The ctypes bridge has no compiler between its three layers: a C export
whose signature drifts from its ``argtypes`` declaration corrupts the call
frame silently (wrong-width ints, missing pointers), and a stub file that
drifts lies to every type-checked consumer. This rule parses all three and
diffs them:

- every ``tft_*`` function defined in ``native/src/capi.cc`` must have an
  ``argtypes`` declaration in ``_load_lib`` with the same parameter count
  (when it takes parameters) and a ``restype`` whenever the C return type
  is not ``int``/``void`` (ctypes' default return of c_int silently
  truncates an ``int64_t`` and mangles pointers);
- every ``lib.tft_*`` declared in ``_native.py`` must exist in capi.cc
  (stale bindings dangle);
- every export must appear as a method of the ``_NativeLib`` class in
  ``_native.pyi`` with the same parameter count (plus ``self``), and the
  stub must not invent functions.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, NamedTuple, Optional, Tuple

from . import Violation, relpath

RULE = "capi_sync"

CAPI = Path("native/src/capi.cc")
NATIVE_PY = Path("torchft_tpu/_native.py")
NATIVE_PYI = Path("torchft_tpu/_native.pyi")


class CExport(NamedTuple):
    name: str
    nparams: int
    ret: str  # normalized return type text
    line: int


_FUNC_RE = re.compile(
    # return type (may span words and '*'), name, params up to the first
    # ')' (no function-pointer params in this API), then the body brace.
    r"^([A-Za-z_][\w]*(?:\s+[\w]+)*[\s\*]+)(tft_\w+)\s*\(([^)]*)\)\s*\{",
    re.M | re.S,
)


def parse_capi(text: str) -> List[CExport]:
    m = re.search(r'extern\s+"C"\s*\{', text)
    region = text[m.end():] if m else text
    offset_line = text[: m.end()].count("\n") + 1 if m else 1
    out = []
    for fm in _FUNC_RE.finditer(region):
        ret = " ".join(fm.group(1).replace("*", " * ").split())
        params = fm.group(3).strip()
        if params in ("", "void"):
            n = 0
        else:
            n = params.count(",") + 1
        line = offset_line + region[: fm.start()].count("\n")
        out.append(CExport(fm.group(2), n, ret, line))
    return out


def _needs_restype(ret: str) -> bool:
    return ret not in ("int", "void")


def _list_len(node: ast.expr) -> int:
    """Length of a ctypes argtypes list expression ([..], list+list,
    list*N)."""
    if isinstance(node, ast.List):
        return len(node.elts)
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Add):
            return _list_len(node.left) + _list_len(node.right)
        if isinstance(node.op, ast.Mult):
            if isinstance(node.right, ast.Constant) and isinstance(
                node.right.value, int
            ):
                return _list_len(node.left) * node.right.value
            if isinstance(node.left, ast.Constant) and isinstance(
                node.left.value, int
            ):
                return node.left.value * _list_len(node.right)
    raise ValueError("unsupported argtypes expression")


class PyDecl(NamedTuple):
    argtypes: Optional[int]  # parameter count, None if never declared
    restype: bool
    line: int


def parse_native_py(text: str) -> Tuple[Dict[str, PyDecl], List[Violation]]:
    tree = ast.parse(text)
    decls: Dict[str, PyDecl] = {}
    problems: List[Violation] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (
            isinstance(tgt, ast.Attribute)
            and tgt.attr in ("argtypes", "restype")
            and isinstance(tgt.value, ast.Attribute)
            and isinstance(tgt.value.value, ast.Name)
            and tgt.value.value.id == "lib"
            and tgt.value.attr.startswith("tft_")
        ):
            continue
        name = tgt.value.attr
        prev = decls.get(name, PyDecl(None, False, node.lineno))
        if tgt.attr == "restype":
            decls[name] = PyDecl(prev.argtypes, True, prev.line)
        else:
            try:
                n = _list_len(node.value)
            except ValueError:
                problems.append(
                    Violation(
                        RULE,
                        str(NATIVE_PY),
                        node.lineno,
                        f"{name}.argtypes is not a statically countable "
                        "list expression",
                    )
                )
                continue
            decls[name] = PyDecl(n, prev.restype, node.lineno)
    return decls, problems


def parse_pyi(text: str) -> Optional[Dict[str, Tuple[int, int]]]:
    """{name: (nparams excluding self, line)} of the _NativeLib class, or
    None when the class is missing entirely."""
    tree = ast.parse(text)
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "_NativeLib":
            out = {}
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    out[item.name] = (len(item.args.args) - 1, item.lineno)
            return out
    return None


def check(
    root: Path,
    capi_path: Optional[Path] = None,
    native_py_path: Optional[Path] = None,
    pyi_path: Optional[Path] = None,
) -> List[Violation]:
    capi_path = capi_path or root / CAPI
    native_py_path = native_py_path or root / NATIVE_PY
    pyi_path = pyi_path or root / NATIVE_PYI

    exports = parse_capi(capi_path.read_text())
    decls, out = parse_native_py(native_py_path.read_text())
    stubs = parse_pyi(pyi_path.read_text())

    capi_rel = relpath(root, capi_path)
    py_rel = relpath(root, native_py_path)
    pyi_rel = relpath(root, pyi_path)

    by_name = {e.name: e for e in exports}
    if not exports:
        out.append(Violation(RULE, capi_rel, 1, "no tft_* exports parsed"))

    for e in exports:
        d = decls.get(e.name)
        if d is None:
            out.append(
                Violation(
                    RULE,
                    py_rel,
                    1,
                    f"{e.name} exported by capi.cc but has no ctypes "
                    "declaration in _load_lib",
                )
            )
            continue
        if e.nparams > 0 and d.argtypes is None:
            out.append(
                Violation(
                    RULE,
                    py_rel,
                    d.line,
                    f"{e.name} takes {e.nparams} parameters but declares "
                    "no argtypes",
                )
            )
        elif d.argtypes is not None and d.argtypes != e.nparams:
            out.append(
                Violation(
                    RULE,
                    py_rel,
                    d.line,
                    f"{e.name} argtypes length {d.argtypes} != "
                    f"{e.nparams} parameters in capi.cc",
                )
            )
        if _needs_restype(e.ret) and not d.restype:
            out.append(
                Violation(
                    RULE,
                    py_rel,
                    d.line,
                    f"{e.name} returns {e.ret!r} but declares no restype "
                    "(ctypes defaults to c_int: truncated int64 / mangled "
                    "pointer)",
                )
            )

    for name, d in decls.items():
        if name not in by_name:
            out.append(
                Violation(
                    RULE,
                    py_rel,
                    d.line,
                    f"{name} declared in _native.py but not exported by "
                    "capi.cc",
                )
            )

    if stubs is None:
        out.append(
            Violation(
                RULE,
                pyi_rel,
                1,
                "_native.pyi has no _NativeLib class stubbing the raw "
                "tft_* surface",
            )
        )
        return out
    for e in exports:
        s = stubs.get(e.name)
        if s is None:
            out.append(
                Violation(
                    RULE,
                    pyi_rel,
                    1,
                    f"{e.name} exported by capi.cc but missing from "
                    "_NativeLib in _native.pyi",
                )
            )
        elif s[0] != e.nparams:
            out.append(
                Violation(
                    RULE,
                    pyi_rel,
                    s[1],
                    f"{e.name} stub takes {s[0]} parameters but capi.cc "
                    f"takes {e.nparams}",
                )
            )
    for name, (_, line) in stubs.items():
        if name not in by_name:
            out.append(
                Violation(
                    RULE,
                    pyi_rel,
                    line,
                    f"{name} stubbed in _NativeLib but not exported by "
                    "capi.cc",
                )
            )
    return out
