// graftlint fixture: a fault-engine Seam enum drifted against
// bad_chaos.py's registry.
#pragma once

namespace tft::fault {

enum Seam {
  kSeamRingSend = 0,  // reachable: bad_fault.cc's TFT_FAULT_CHECK site
  kSeamWalWrite = 1,  // no call site in the fixture tree -> unreachable
  kSeamStore = 2,     // reserved for the Python-side injector: ok
  kSeamPhantom = 3,   // no seam in bad_chaos.py -> orphan enumerator
  // bad_chaos.py's "ghost_seam" has no enumerator -> sync violation
};

}  // namespace tft::fault
