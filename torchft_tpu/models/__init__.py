from torchft_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    param_sharding_rules,
    tiny_config,
)

__all__ = [
    "TransformerConfig",
    "forward",
    "init_params",
    "loss_fn",
    "param_sharding_rules",
    "tiny_config",
]
