"""Fault-tolerant data parallelism across replica groups.

Reference: torchft/ddp.py — there, a comm-hook routes each gradient bucket
through ``Manager.allreduce`` during backward. JAX has no backward hooks;
gradients materialize as one pytree from ``jax.grad``, which is *better* for
this transport: the whole tree is packed into one ring pass per dtype by the
collectives layer (the bucketing DDP's reducer approximates).

Intra-replica-group sharding (FSDP/TP-style) stays in user pjit code over
the slice mesh — this wrapper only averages across groups, mirroring the
reference's division of labor (torchft owns the replicate dim only,
process_group.py:1067-1341).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

from .collectives import Work
from .manager import Manager
from .train_state import FTTrainState


class DistributedDataParallel:
    """Averages gradient pytrees across replica groups, fault-tolerantly.

    Usage::

        ddp = DistributedDataParallel(manager)
        grads = grad_fn(params, batch)
        grads = ddp.allreduce_grads(grads).wait()   # async; overlap-friendly

    or wrap a grad function so the average happens on call::

        value_and_avg_grads = ddp.wrap_grad_fn(jax.value_and_grad(loss_fn))
    """

    def __init__(self, manager: Manager) -> None:
        self._manager = manager

    def allreduce_grads(self, grads: Any) -> Work:
        """Starts the async cross-group average of ``grads``; the Work
        resolves to the averaged pytree (input unchanged on error, with the
        error latched for ``should_commit`` — reference ddp.py:67-71)."""
        return self._manager.allreduce(grads)

    def wrap_grad_fn(
        self, grad_fn: Callable[..., Tuple[Any, Any]]
    ) -> Callable[..., Tuple[Any, Any]]:
        """Wraps a ``jax.value_and_grad``-style fn so returned grads are
        already averaged across replica groups (blocking)."""

        def wrapped(*args: Any, **kwargs: Any) -> Tuple[Any, Any]:
            value, grads = grad_fn(*args, **kwargs)
            return value, self.allreduce_grads(grads).wait()

        return wrapped


class PipelinedDDP:
    """Per-step DDP with the cross-group ring overlapped with compute.

    The reference hides its allreduce behind backward via bucket hooks
    (reference ddp.py:47-71): bucket ``b``'s ring pass overlaps computing
    bucket ``b+1``'s gradients. JAX materializes the whole gradient pytree
    from one jitted program, so the equivalent overlap is across the *step*
    boundary instead: step ``i``'s ring pass runs while the device computes
    step ``i+1``'s forward/backward (a one-step-stale gradient schedule,
    the standard pipelined-SGD delay-1 discipline). Device dispatch is
    async, so the host thread that would otherwise idle in ``wait()``
    instead settles the previous step's transaction.

    Per call, the full manager transaction still runs for every step —
    quorum, managed allreduce, AND-vote commit — just one iteration behind
    the compute. Recovery is handled: when a heal lands at the commit safe
    point, the already-dispatched gradients were computed from pre-heal
    weights, so they are recomputed from the recovered state before being
    contributed (a fresh restart otherwise pollutes the cohort average
    with init-weight gradients).

    ``compress="bf16"`` casts float32 gradients to bfloat16 for the wire
    (half the cross-group bytes; ring hops accumulate in f32) and restores
    the original dtypes on return — the JAX analog of torch DDP's
    ``bf16_compress_hook``.

    Quantized modes (both: per-leaf int8 quantization with ERROR
    FEEDBACK — the per-step quantization error carries into the next
    step's gradients, the standard EF-SGD recipe, reset on heal along
    with the rest of the local trajectory; the analog of torch DDP's
    compressed comm hooks). Two transports for two bottlenecks:

    - ``compress="int8"``: the int8 payload itself ({q, scale} leaves)
      rides a managed device-packed ALLGATHER and is dequantize-averaged
      on settle. The DEVICE<->HOST link carries int8 bytes — the mode for
      hosts where that link (PCIe / a tunneled runtime) is the
      bottleneck. Allgather traffic grows with cohort size; intended for
      small cohorts.
    - ``compress="q8"``: the dequantized (f32, int8-gridded) gradients
      ride the native ring's quantized wire (int8 chunks + per-chunk
      scales, dequant-accumulated per hop): TCP bytes are ~4x below f32
      and CONSTANT in cohort size, but the device link carries f32 — the
      mode for real DCN deployments where the network is the bottleneck
      and cohorts are larger.

    Usage::

        ddp = PipelinedDDP(manager, state, grad_fn)  # grad_fn: (params, batch) -> (loss, grads)
        for batch in batches:
            loss = ddp.step(batch)
        ddp.flush()      # settle the final in-flight step
    """

    def __init__(
        self,
        manager: Manager,
        state: FTTrainState,
        grad_fn: Callable[..., Tuple[Any, Any]],
        compress: Optional[str] = None,
    ) -> None:
        if compress not in (None, "bf16", "int8", "q8"):
            raise ValueError(f"unsupported compress: {compress!r}")
        self._manager = manager
        self._state = state
        self._grad_fn = grad_fn
        self._compress_mode = compress
        self._inflight: Optional[Work] = None
        self._inflight_dtypes: Any = None  # grad dtype TUPLE at dispatch
        #                                    (may change across restores)
        self._compress_jit: Optional[Any] = None
        self._decompress_jit: Optional[Any] = None
        self._quant_jit: Optional[Any] = None
        self._combine_fns: dict = {}     # int8: per-cohort dequant-avg
        self._residual: Any = None       # int8/q8: error-feedback carry
        self._prev_residual: Any = None  # pre-dispatch carry (non-commit
        #                                  settles roll back to it)

    def _compress(self, grads: Any) -> Any:
        """Returns the wire payload for ``grads`` and records the dtype
        tree the settle-side decompress restores (recomputed every step —
        a restore can change the gradient pytree's dtypes mid-run)."""
        import jax

        # hashable tuple (leaf order = tree_flatten order): doubles as
        # the static arg of the jitted decompress cast
        self._inflight_dtypes = tuple(
            l.dtype for l in jax.tree_util.tree_leaves(grads)
        )
        if self._compress_mode is None:
            return grads
        import jax.numpy as jnp

        if self._compress_mode in ("int8", "q8"):
            if self._quant_jit is None:
                from .quantize import quantize_with_feedback

                self._quant_jit = jax.jit(quantize_with_feedback)
            if self._residual is None:
                self._residual = jax.tree_util.tree_map(
                    lambda l: jnp.zeros(l.shape, jnp.float32), grads
                )
            self._prev_residual = self._residual  # restored on non-commit
            out = self._quant_jit(grads, self._residual)
            self._residual = out["res"]
            if self._compress_mode == "int8":
                # int8 BYTES cross the device link (device-packed
                # allgather); settle dequantize-averages
                return {"q": out["q"], "scale": out["scale"]}
            # q8: f32 on the device link, int8 on the TCP ring
            return out["dq"]

        if self._compress_jit is None:

            def down(t: Any) -> Any:
                return jax.tree_util.tree_map(
                    lambda l: l.astype(jnp.bfloat16)
                    if l.dtype == jnp.float32
                    else l,
                    t,
                )

            self._compress_jit = jax.jit(down)
        return self._compress_jit(grads)

    def _decompress(self, avg: Any) -> Any:
        if self._compress_mode in (None, "int8", "q8"):
            return avg
        import jax

        # restore the dtypes recorded AT dispatch (not a forever-cached
        # tree: a restore may legitimately change grad dtypes mid-run).
        # Jitted with the dtype tuple STATIC: one fused cast program per
        # distinct dtype signature instead of per-leaf eager dispatches
        # on the per-step hot path.
        if self._decompress_jit is None:

            def up(t: Any, dts: Any) -> Any:
                leaves, treedef = jax.tree_util.tree_flatten(t)
                return jax.tree_util.tree_unflatten(
                    treedef, [l.astype(d) for l, d in zip(leaves, dts)]
                )

            self._decompress_jit = jax.jit(up, static_argnums=(1,))
        return self._decompress_jit(avg, self._inflight_dtypes)

    def _dispatch(self, grads: Any) -> Work:
        payload = self._compress(grads)
        if self._compress_mode == "int8":
            return self._manager.allgather(payload)
        if self._compress_mode == "q8":
            # the quantized ring returns the averaged f32 tree directly
            # (FTTrainState harmonizes dtypes against the master params)
            return self._manager.allreduce(payload, wire="q8")
        return self._manager.allreduce(payload)

    def _settle(self) -> bool:
        """Waits the in-flight ring pass, votes, applies on commit."""
        assert self._inflight is not None
        result = self._inflight.wait()
        self._inflight = None
        committed = self._manager.should_commit()
        if committed:
            if self._compress_mode == "int8":
                # member-wise dequantize, average over PARTICIPANTS
                # (healing/spare entries arrive zeroed and must not
                # dilute the divisor — Manager.allgather discipline)
                import jax
                import jax.numpy as jnp

                cohort = len(result)
                combine = self._combine_fns.get(cohort)
                if combine is None:
                    from .quantize import make_dequant_average

                    combine = self._combine_fns[cohort] = \
                        make_dequant_average()
                avg = combine(
                    result,
                    float(max(self._manager.num_participants(), 1)),
                )
            else:
                avg = self._decompress(result)
            self._state.apply_gradients(avg)
        elif self._compress_mode in ("int8", "q8"):
            # The step was discarded: its gradients were never applied, so
            # carrying ITS quantization error forward would inject signal
            # from an abandoned payload into the next step — roll the EF
            # carry back to the pre-dispatch value (AsyncDiLoCo's
            # restored-on-abort discipline).
            self._residual = self._prev_residual
        return committed

    def step(self, *batch: Any) -> Any:
        """One pipelined step: dispatches this batch's gradient program,
        settles the PREVIOUS step's transaction while the device computes,
        then contributes these gradients to a newly-started quorum. Returns
        the loss (a device value; don't block on it in the hot loop)."""
        loss, grads = self._grad_fn(self._state.params, *batch)
        if self._inflight is not None:
            healed = self._manager.is_healing()
            self._settle()
            if healed:
                # The dispatched grads came from pre-heal weights; recompute
                # from the recovered (and just-updated) state. The EF carry
                # belongs to the abandoned trajectory — drop it.
                loss, grads = self._grad_fn(self._state.params, *batch)
                self._residual = None
        self._manager.start_quorum()
        self._inflight = self._dispatch(grads)
        return loss

    def flush(self) -> bool:
        """Settles the final in-flight step; returns whether it committed.
        Call once after the loop (and before reading ``state`` as the
        final model)."""
        if self._inflight is None:
            return False
        return self._settle()
