"""Fused causal attention as a pallas TPU kernel (FlashAttention-2 style).

The dense attention path in ``models/transformer.py`` materializes the
(B, H, S, S) score matrix in HBM — at seq 2048 that is the single largest
activation of the step and a pure HBM-bandwidth tax. This kernel keeps
each (query-block × key-block) score tile in VMEM, runs the online-softmax
recurrence (the same one ``context_parallel.ring_attention`` uses across
devices, here across VMEM tiles within one device), and writes only the
(S, D) output plus an (S,) logsumexp residual for the backward pass.

Backward is ONE fused kernel (not the two-kernel FlashAttention-2 split):
the grid walks key blocks; dk/dv accumulate in VMEM per key block, and dq
accumulates into a full-row f32 output block that pallas keeps resident
across the sequential TPU grid (revisited index map — grid steps on TPU
execute in order, so read-modify-write accumulation is deterministic).
Fusing matters because this shape is VPU-bound, not MXU-bound (head_dim
64: each S×S exp pass costs more than the matmuls it feeds): the split
design recomputes probabilities twice per tile pair — once for dq, once
for dk/dv — and the fused kernel computes them once, cutting the
dominant exp/elementwise work ~in half and the matmul count 7→5 per
tile. The softmax scale is folded into q OUTSIDE the kernel (exact for
power-of-two scales, e.g. head_dim 64 → 0.125), removing the per-tile
S×S scale multiplies; autodiff of the fold rescales dq automatically.

The causal path splits every tile loop into UNMASKED interior tiles plus
one masked diagonal tile (requires block_q == block_k, the auto default):
strictly-below-diagonal tiles are fully live, so the interior body skips
the iota/compare/select mask passes entirely — measured 57% of the
flagship step was attention, and the mask/guard VPU passes were a third
of the kernel (experiments/mfu_breakdown.py). The fast path also uses a
finite -1e30 mask value instead of -inf, which removes every
``isfinite`` guard from the online-softmax recurrence: with at least one
live key per query row (guaranteed on the causal path — every row
attends at least its own position; padded query rows attend earlier live
keys), ``exp(-1e30 - m)`` underflows to exactly 0 and the recurrence
needs no special cases. The backward kernels apply NO padding mask at
all: padded k/v rows are zeros, so padded-column score/probability
garbage contributes exactly 0 to dq (``ds @ k`` hits zero rows) and only
to dk/dv rows that are sliced off; padded query rows carry zero
cotangents. The general path (sliding window, unequal blocks,
non-causal) keeps per-tile masks.

Design notes (pallas_guide.md):
- all matmuls request ``preferred_element_type=float32`` so the MXU
  accumulates in f32 regardless of the bf16 inputs;
- iota is always 2D (``broadcasted_iota``) — 1D iota does not lower;
- blocks always span the full head dim, satisfying Mosaic's "divisible by
  128 OR equal to the array dim" lane rule without padding D (padding to
  128 lanes would double the QK FLOPs at the flagship head_dim of 64);
  arbitrary sequence lengths ARE padded — up to the block multiple, with
  padded keys masked in-kernel and padded queries carrying zero
  cotangents;
- causal kernels bound their inner ``fori_loop`` by the block diagonal so
  masked-out tiles are never computed (dynamic trip counts lower to
  ``while_loop``).

Off-TPU the same kernels run under ``interpret=True`` so CPU tests and the
virtual-device dryrun exercise the identical code path.

Reference parity: none — the reference has no fused kernels (SURVEY.md
§2.3: its compute path is plain torch ops + NCCL). This is the
"pallas kernels for the hot ops" part of the TPU-first mandate.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

_NEG_INF = float("-inf")
# Finite mask value for the fast (split-diagonal) path: large enough that
# exp(_NEG_LARGE - m) underflows to exactly 0 for any live row max m
# (|m| <= ~1e4 in practice), small enough to stay exact in f32.
_NEG_LARGE = -1e30


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _dot_f32(a: jax.Array, b: jax.Array) -> jax.Array:
    """MXU matmul keeping the inputs' (bf16) dtype, f32 accumulation —
    casting inputs to f32 first would run the MXU at f32 rate (~8x slower
    on v5e)."""
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_nt(a: jax.Array, b: jax.Array) -> jax.Array:
    """a @ b.T via dot_general dimension numbers — Mosaic contracts the
    shared minor dim directly instead of materializing b.T (an explicit
    .T is a per-tile VMEM relayout pass)."""
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_tn(a: jax.Array, b: jax.Array) -> jax.Array:
    """a.T @ b without materializing a.T (contract the major dims)."""
    return jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _tile_mask(
    q_start, k_start, block_q: int, block_k: int, kv_len: int,
    causal: bool, padded: bool, window: Optional[int] = None,
):
    """Validity mask for one (block_q, block_k) score tile, or None when
    every position is live. Shared by the forward and both backward
    kernels so the mask semantics cannot drift apart. ``window`` w keeps
    only keys with q_pos - k_pos < w (sliding-window / local attention)."""
    if not (causal or padded or window is not None):
        return None
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    ok = k_pos < kv_len if padded else True
    if causal or window is not None:
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        if causal:
            ok = (q_pos >= k_pos) & ok
        if window is not None:
            ok = (q_pos - k_pos < window) & ok
    return ok


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _split_diag(causal: bool, window, block_q: int, block_k: int) -> bool:
    """True when the tile loops may run as unmasked-interior + one masked
    diagonal tile (see module docstring). Requires equal blocks so the
    diagonal tile of query block qi is exactly key block qi."""
    return causal and window is None and block_q == block_k


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *,
    causal: bool, block_q: int, block_k: int, num_k: int,
    kv_len: int, window,
):
    # q arrives PRE-SCALED by sm_scale (folded outside the kernel), so
    # s = q @ k.T is the final score with no per-tile S x S multiply.
    qi = pl.program_id(1)
    q = q_ref[0]  # (block_q, D), input dtype
    D = q.shape[-1]
    padded = kv_len < num_k * block_k
    fast = _split_diag(causal, window, block_q, block_k)
    neg = _NEG_LARGE if fast else _NEG_INF

    def tile(j, carry, masked: bool):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot_nt(q, k_blk)  # (block_q, block_k) f32
        if masked:
            ok = _tile_mask(
                qi * block_q, j * block_k, block_q, block_k, kv_len,
                causal, padded, window,
            )
            if ok is not None:
                s = jnp.where(ok, s, neg)
        m_new = jnp.maximum(m, s.max(axis=-1))
        if fast:
            # every query row has >= 1 live key (causal: its own position,
            # or for zero-padded query rows any earlier live key), so
            # m_new is finite after the first processed tile and the
            # -inf/isfinite guards of the general path are dead weight:
            # exp(_NEG_LARGE - m_new) underflows to exactly 0.
            p = jnp.exp(s - m_new[:, None])
            corr = jnp.exp(m - m_new)
        else:
            # rows with every key masked keep m = -inf; guard
            # exp(-inf - -inf)
            safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.where(
                jnp.isfinite(s), jnp.exp(s - safe_m[:, None]), 0.0
            )
            corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[:, None] + _dot_f32(
            p.astype(v_blk.dtype), v_blk
        )
        return m_new, l_new, acc_new

    init = (
        jnp.full((block_q,), neg, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
        jnp.zeros((block_q, D), jnp.float32),
    )
    if fast:
        # interior tiles j < qi are fully below the causal diagonal (and
        # never reach padded key columns: cols < qi*block_k < kv_len), so
        # they run with no mask at all; the diagonal tile j == qi carries
        # the causal mask and (in the last row block) the padding mask.
        m, l, acc = jax.lax.fori_loop(
            0, qi, lambda j, c: tile(j, c, False), init
        )
        m, l, acc = tile(qi, (m, l, acc), True)
    else:
        num_k_live = _cdiv(kv_len, block_k)  # skip fully-padded key blocks
        if causal:
            # key blocks strictly above the block diagonal are fully masked
            hi = jnp.minimum(
                num_k_live, ((qi + 1) * block_q + block_k - 1) // block_k
            )
        else:
            hi = num_k_live
        lo = 0
        if window is not None:
            # key blocks fully left of the sliding window are masked for
            # every query row in this block
            lo = jnp.maximum(0, (qi * block_q - window + 1) // block_k)
        m, l, acc = jax.lax.fori_loop(
            lo, hi, lambda j, c: tile(j, c, True), init
        )
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse rides a full-row (1, 1, S) block revisited across the sequential
    # qi grid dim (a (1, block_q) 2D block violates Mosaic's (8, 128) tile
    # floor); each step writes its slice
    if fast:
        # m is finite for every row (see tile()); no -inf bookkeeping
        lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = m + jnp.log(l_safe)
    else:
        lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = jnp.where(
            jnp.isfinite(m), m + jnp.log(l_safe), _NEG_INF
        )


def _flash_fwd_call(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    causal: bool, block_q: int, block_k: int,
    interpret: bool, kv_len: int, window,
):
    """q (pre-scaled)/k/v: (BH, S_pad, D) -> out (BH, S_pad, D),
    lse (BH, 1, S_pad) f32. Positions >= kv_len are zero padding, masked
    out of every softmax."""
    BH, S, D = q.shape
    num_q, num_k = _cdiv(S, block_q), _cdiv(S, block_k)
    kernel = functools.partial(
        _fwd_kernel, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k, kv_len=kv_len,
        window=window,
    )
    row = pl.BlockSpec((1, S, D), lambda bh, qi: (bh, 0, 0))
    qspec = pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0))
    return pl.pallas_call(
        kernel,
        grid=(BH, num_q),
        in_specs=[qspec, row, row],
        out_specs=[
            qspec,
            pl.BlockSpec((1, 1, S), lambda bh, qi: (bh, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dk_ref, dv_ref, *,
    causal: bool, block_q: int, block_k: int, num_q: int,
    kv_len: int, window,
):
    ki = pl.program_id(1)
    k_blk = k_ref[0]  # (block_k, D), input dtype
    v_blk = v_ref[0]
    D = k_blk.shape[-1]
    # Padded QUERY rows need no mask here: their cotangent (do) and delta
    # are zero, so ds and p.T @ do vanish (their lse is finite on both
    # paths — causal padded query rows attend earlier live keys — so p
    # stays finite and 0 * p cannot produce NaN). On the general path,
    # padded KEY columns are masked; the fast path drops that mask too:
    # p/ds garbage in padded columns lands only in dk/dv ROWS that the
    # caller slices off (each dk/dv row is a column-wise independent sum),
    # so masking them buys nothing.
    padded = kv_len < q_ref.shape[1]  # static: S_pad > kv_len
    fast = _split_diag(causal, window, block_q, block_k)

    # dq accumulates into a REVISITED full-row f32 output block: the TPU
    # grid is sequential, so every ki step of one bh row sees the same
    # resident VMEM block; zero it on the first step.
    @pl.when(ki == 0)
    def _init_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    def tile(i, carry, masked: bool):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)]
        s = _dot_nt(q_blk, k_blk)  # q pre-scaled by sm_scale
        p = jnp.exp(s - lse[:, None])
        if masked:
            ok = _tile_mask(
                i * block_q, ki * block_k, block_q, block_k, kv_len,
                causal, padded and not fast, window,
            )
            if ok is not None:
                p = jnp.where(ok, p, 0.0)
        dv_new = dv + _dot_tn(p.astype(do_blk.dtype), do_blk)
        dp = _dot_nt(do_blk, v_blk)
        ds = (p * (dp - delta[:, None])).astype(q_blk.dtype)  # one cast,
        dk_new = dk + _dot_tn(ds, q_blk)                      # used twice
        dq_ref[0, pl.ds(i * block_q, block_q), :] += _dot_f32(ds, k_blk)
        return dk_new, dv_new

    init = (
        jnp.zeros((block_k, D), jnp.float32),
        jnp.zeros((block_k, D), jnp.float32),
    )
    if fast:
        # diagonal tile i == ki carries the causal mask; query blocks
        # i > ki are fully below the diagonal (every q_pos >= every
        # k_pos), so they run unmasked.
        dk, dv = tile(ki, init, True)
        dk, dv = jax.lax.fori_loop(
            ki + 1, num_q, lambda i, c: tile(i, c, False), (dk, dv)
        )
    else:
        if causal:
            # query blocks strictly below the block diagonal see none of
            # this key block
            lo = (ki * block_k) // block_q
        else:
            lo = 0
        hi = num_q
        if window is not None:
            # query blocks fully right of the window (q_min - k_max >= w)
            # see none of this key block
            hi = jnp.minimum(
                num_q, ((ki + 1) * block_k - 1 + window) // block_q + 1
            )
        dk, dv = jax.lax.fori_loop(
            lo, hi, lambda i, c: tile(i, c, True), init
        )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_call(
    q, k, v, o, lse, do, *,
    causal: bool, block_q: int, block_k: int,
    interpret: bool, kv_len: int, window,
):
    BH, S, D = q.shape
    num_q, num_k = _cdiv(S, block_q), _cdiv(S, block_k)
    # delta_i = sum_d do_id * o_id — one fused elementwise+reduce, not worth
    # a kernel
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )[:, None, :]  # (BH, 1, S) — same full-row layout as lse

    row3 = pl.BlockSpec((1, S, D), lambda bh, i: (bh, 0, 0))
    row2 = pl.BlockSpec((1, 1, S), lambda bh, i: (bh, 0, 0))
    kblk3 = pl.BlockSpec((1, block_k, D), lambda bh, i: (bh, i, 0))

    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_kernel, causal=causal,
            block_q=block_q, block_k=block_k, num_q=num_q, kv_len=kv_len,
            window=window,
        ),
        grid=(BH, num_k),
        in_specs=[row3, kblk3, kblk3, row3, row2, row2],
        out_specs=[row3, kblk3, kblk3],
        out_shape=[
            # dq is the revisited f32 accumulator (cast to q.dtype below)
            jax.ShapeDtypeStruct((BH, S, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq.astype(q.dtype), dk, dv


# ---------------------------------------------------------------------------
# custom-vjp plumbing on the (BH, S, D) canonical layout
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(cfg, q, k, v):
    out, _ = _flash_fwd_res(cfg, q, k, v)
    return out


def _flash_fwd_res(cfg, q, k, v):
    causal, block_q, block_k, interpret, kv_len, window = cfg
    out, lse = _flash_fwd_call(
        q, k, v, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        kv_len=kv_len, window=window,
    )
    # Name the kernel outputs so a jax.checkpoint policy can SAVE them:
    # the vjp needs (out, lse) as residuals, and with both saved the remat
    # backward's forward replay prunes the fwd pallas launch entirely
    # (q/k/v are re-derived from the cheap qkv projection instead).
    # checkpoint_name is the identity outside a policy-remat context.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _flash_bwd_res(cfg, res, g):
    causal, block_q, block_k, interpret, kv_len, window = cfg
    q, k, v, out, lse = res
    return _flash_bwd_call(
        q, k, v, out, lse, g, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
        kv_len=kv_len, window=window,
    )


_flash.defvjp(_flash_fwd_res, _flash_bwd_res)


def _pick_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    mesh: Any = None,
    batch_axis: Optional[str] = "data",
    head_axis: Optional[str] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Fused multi-head causal attention.

    Args:
        q, k, v: (B, S, H, head_dim), any float dtype.
        causal: apply the autoregressive mask.
        window: sliding-window (local) attention — each query attends
            only the most recent ``window`` keys (q_pos - k_pos < window);
            tiles fully outside the window are skipped by the loop
            bounds, so computed tiles scale with S*window instead of
            S^2/2 (wall-clock gains show once S/window is large).
            Requires ``causal``.
        sm_scale: score scale; default ``head_dim ** -0.5``. The scale
            is folded into ``q`` OUTSIDE the kernel as one f32 multiply
            rounded back to the input dtype (it removes a per-tile
            (S_q, S_k) multiply from every kernel). For POWER-OF-TWO
            scales — any power-of-two head_dim, e.g. 64 -> 0.125 — the
            fold is exact in every float dtype. CAVEAT: a
            non-power-of-two ``sm_scale`` with bf16/f16 inputs rounds
            each scaled q element once (<= 1/2 ulp; ~0.4% relative at
            bf16) BEFORE the scores are formed, so scores are not
            bit-equal to an unfused baseline that scales the f32
            logits. Numerically benign for training; pass f32 q/k/v or
            a power-of-two scale when exactness matters.
        block_q, block_k: VMEM tile sizes; clamped to S, and on real TPU
            rounded UP to 128-multiples (Mosaic's lane-aligned store
            requirement — a requested 64 runs as 128 on hardware;
            interpret mode honors small blocks exactly). Default auto:
            (512, 512) when the sublane-padded sequence length reaches
            2048, else (128, 128). Measured IN-MODEL on v5e (8-layer
            111M-param LM at padded S 2048, fused train step, head_dim
            64; FLASH_ABLATION.json): at B8 the (512, 512) kernel runs
            the step at 64.6 param-TFLOP/s vs 47.5 dense and 38.3 for
            (128, 128); at B4 58.0 vs 40.8 dense; at B16 70.0 (dense
            fails to compile). Standalone kernel sweeps rank tiles
            differently (fusion/VMEM interactions dominate) — trust
            whole-step timings.
        interpret: force pallas interpret mode; default: on iff the backend
            is not TPU (CPU tests / virtual-device dryruns).
        mesh/batch_axis/head_axis: when ``mesh`` is given the kernel runs
            per shard under ``shard_map`` with batch split over
            ``batch_axis`` and heads over ``head_axis`` (a pallas call is a
            single custom op XLA cannot partition on its own).
    Returns:
        (B, S, H, head_dim) attention output, dtype of q.
    """
    B, S, H, D = q.shape
    if sm_scale is None:
        sm_scale = D ** -0.5
    if window is not None:
        if not causal:
            raise ValueError(
                "window requires causal=True (one-sided local attention)"
            )
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")

    if mesh is not None:
        spec = P(batch_axis, None, head_axis, None)
        local = functools.partial(
            flash_attention, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
            window=window,
        )
        # check_vma=False: pallas out_shapes carry no varying-mesh-axes
        # annotation, which the new shard_map VMA typing would reject
        return shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )(q, k, v)

    interp = _pick_interpret(interpret)
    # Auto tile sizes (v5e-measured, see docstring); arbitrary S is
    # handled by zero-padding the sequence up to the block multiple —
    # padded keys are masked in-kernel, padded queries carry zero
    # cotangents, so numerics are exact.
    # Tile choice keys on the PADDED sublane length, not raw S:
    # language-model training slices the last token off (tokens[:, :-1]),
    # so the flagship in-model sequence is 2047 — a raw-S `>= 2048` test
    # once dropped it onto the 128-tile path and cost 1.7x whole-step
    # throughput, while sequences just over a power of two would pay ~50%
    # padding on the large-tile path. s8 >= 2048 admits exactly the
    # 2048-class shapes the measurements cover (FLASH_ABLATION.json at
    # padded S 2048; standalone 512-tile win at S 8192).
    # On hardware the lse row is sliced along the LANE dim in block_q-wide
    # stores, so blocks must be 128-multiples (Mosaic rejects misaligned
    # vector stores — observed at S=99 on v5e); interpret mode only needs
    # the 8-sublane floor, and the CPU tests use small blocks.
    unit = 8 if interp else 128
    s8 = _cdiv(S, unit) * unit
    if s8 >= 2048:
        auto_q, auto_k = 512, 512
    else:
        auto_q, auto_k = 128, 128
    block_q = min(block_q or auto_q, s8)
    block_k = min(block_k or auto_k, s8)
    if not interp:
        block_q = _cdiv(block_q, 128) * 128
        block_k = _cdiv(block_k, 128) * 128
    base = block_q * block_k // math.gcd(block_q, block_k)
    S_pad = _cdiv(S, base) * base

    # (B, S, H, D) -> (B*H, S_pad, D). Blocks always span the full head
    # dim, so Mosaic's "divisible by 128 OR equal to the array dim" lane
    # rule is satisfied without padding D (padding to 128 lanes would
    # double the QK FLOPs at the flagship head_dim of 64).
    def to_rows(x):
        x = x.transpose(0, 2, 1, 3).reshape(B * H, S, D)
        if S_pad != S:
            x = jnp.pad(x, ((0, 0), (0, S_pad - S), (0, 0)))
        return x

    cfg = (
        bool(causal), block_q, block_k, interp, S,
        None if window is None else int(window),
    )
    # sm_scale folded into q OUTSIDE the custom_vjp: one cheap (S, D)
    # multiply replaces a per-tile (S_q, S_k) multiply in every kernel,
    # and autodiff of this fold rescales dq automatically (exact for
    # power-of-two scales — head_dim 64 gives 0.125). The product is
    # computed with an f32 scalar so the scale itself is never quantized
    # to bf16; only the single product rounding remains.
    q_scaled = (q * jnp.float32(sm_scale)).astype(q.dtype)
    out = _flash(cfg, to_rows(q_scaled), to_rows(k), to_rows(v))
    return out[:, :S].reshape(B, H, S, D).transpose(0, 2, 1, 3)
