import os
import subprocess
import sys

# JAX on a virtual 8-device CPU mesh: multi-chip sharding paths are tested
# without TPU hardware (the driver's dryrun uses the same trick). Must be set
# before the first `import jax` anywhere in the test session.
# Force CPU even when a real TPU is tunneled in: the unit suite needs 8
# virtual devices (and TPU jit compiles are 20-40s each); the driver runs
# bench.py / dryrun on real hardware separately. The axon sitecustomize
# pins the TPU backend via jax.config at startup, so the env var alone is
# not enough — override the config after import too.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

_LIB = os.path.join(REPO_ROOT, "torchft_tpu", "_libtorchft.so")
if not os.path.exists(_LIB):
    subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "native")], check=True)
