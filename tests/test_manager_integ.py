"""End-to-end integration: replica groups as threads, real lighthouse +
managers + host collectives, fault injection, recovery.

Mirrors the reference harness (reference manager_integ_test.py): each replica
group is a thread with its own Store and Manager against one in-process
Lighthouse; ``FailureInjector.fail_at(rank, step)`` raises inside the train
loop; ``Runner.run_replica`` catches it and re-enters (simulating
torchelastic restart, manager_integ_test.py:113-126). Correctness oracle:
after recovery all replicas' state dicts are **bit-identical**
(manager_integ_test.py:279-282).
"""

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from dataclasses import dataclass, field
from datetime import timedelta
from typing import Any, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import (
    FTTrainState,
    HostCollectives,
    Lighthouse,
    Manager,
    OptimizerWrapper,
    PipelinedDDP,
    Store,
)

logger = logging.getLogger(__name__)


class InjectedFailure(Exception):
    pass


class FailureInjector:
    """Raises at a (local rank, step) once; one injector per replica group.
    Reference manager_integ_test.py:43-61."""

    def __init__(self) -> None:
        self._failures: Set[Tuple[int, int]] = set()
        self._lock = threading.Lock()
        self.count = 0

    def fail_at(self, rank: int, step: int) -> "FailureInjector":
        with self._lock:
            self._failures.add((rank, step))
        return self

    def check(self, rank: int, step: int) -> None:
        with self._lock:
            if (rank, step) in self._failures:
                self._failures.remove((rank, step))
                self.count += 1
                logger.info(f"injecting failure rank={rank} step={step}")
                raise InjectedFailure(f"injected at {rank=} {step=}")


def _init_state(seed: int = 42):
    """Tiny deterministic MLP + SGD state; identical on every replica."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    params = {
        "w1": jax.random.normal(k1, (4, 8), jnp.float32) * 0.1,
        "b1": jnp.zeros((8,), jnp.float32),
        "w2": jax.random.normal(k2, (8, 2), jnp.float32) * 0.1,
        "b2": jnp.zeros((2,), jnp.float32),
    }
    return params


def _loss_fn(params, x, y):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    return jnp.mean((logits - y) ** 2)


_grad_fn = jax.jit(jax.grad(_loss_fn))
_value_and_grad_fn = jax.jit(jax.value_and_grad(_loss_fn))


def _batch(step: int):
    """Deterministic per-step batch, identical across replicas (pure DP over
    identical data keeps the oracle simple, like the reference's all-ones
    inputs)."""
    rng = np.random.default_rng(1000 + step)
    x = jnp.asarray(rng.standard_normal((8, 4)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((8, 2)).astype(np.float32))
    return x, y


@dataclass
class Runner:
    """One replica group of ``world_size`` local-rank threads sharing a
    Store, mirroring the reference's nested-executor harness (reference
    manager_integ_test.py:64-126). An InjectedFailure in any rank takes the
    whole group down (torchelastic restarts groups, not ranks); the group
    then re-enters with a fresh Store/Managers."""

    replica_id: int
    lighthouse_address: str
    failure_injector: FailureInjector
    num_steps: int = 5
    use_async_quorum: bool = True
    attempts: int = 3
    world_size: int = 1
    # Deterministic overlap gate. With only 2 replicas the split-brain guard
    # blocks the survivor until the dead peer's heartbeat expires, but with
    # >= 3 the surviving majority can finish (and exit) before the victim
    # restarts — after which the joiner legitimately trains alone and the
    # bit-identical oracle no longer applies. Survivors therefore wait at
    # `gate_step` until `gate_event` is set; the restarting replica sets
    # `announce_restart` once its new Manager is up.
    gate_step: Optional[int] = None
    gate_event: Optional[threading.Event] = None
    announce_restart: Optional[threading.Event] = None
    # None = blocking OptimizerWrapper loop; "plain"/"bf16" = PipelinedDDP
    # (step i's ring overlapped with step i+1's grads; see
    # torchft_tpu/ddp.py). The pipelined loop settles one step late, so
    # its exit overshoots num_steps by exactly one committed step.
    pipelined: Optional[str] = None

    def run_replica(self) -> List[Dict[str, Any]]:
        for attempt in range(self.attempts):
            try:
                return self._replica_main(attempt)
            except InjectedFailure:
                logger.info(
                    f"replica {self.replica_id} died (attempt {attempt}); "
                    "restarting"
                )
                continue
        raise RuntimeError(f"replica {self.replica_id} exhausted attempts")

    def _replica_main(self, attempt: int) -> List[Dict[str, Any]]:
        store = Store()  # the group's rendezvous store, shared by its ranks
        try:
            with ThreadPoolExecutor(
                max_workers=self.world_size,
                thread_name_prefix=f"replica{self.replica_id}",
            ) as ex:
                futures = [
                    ex.submit(self._train_loop, rank, store.address(), attempt)
                    for rank in range(self.world_size)
                ]
                results: List[Dict[str, Any]] = []
                errors: List[BaseException] = []
                for f in as_completed(futures):
                    e = f.exception()
                    if e is not None:
                        errors.append(e)
                    else:
                        results.append(f.result())
                if errors:
                    # One rank's injected death cascades to its peers as
                    # connection errors when the group's manager goes down;
                    # the injected failure is the root cause to surface.
                    for e in errors:
                        if isinstance(e, InjectedFailure):
                            raise e
                    raise errors[0]
                return sorted(results, key=lambda r: r["rank"])
        finally:
            store.shutdown()

    def _train_loop(
        self, rank: int, store_addr: str, attempt: int = 0
    ) -> Dict[str, Any]:
        # 30 s (not 10): these are correctness tests, not latency tests;
        # on the 1-core CI host a loaded machine can stall a worker past
        # a 10 s op timeout and flake the run (observed under concurrent
        # suite + bench load).
        collectives = HostCollectives(timeout=timedelta(seconds=30))
        state = FTTrainState(_init_state(), optax.sgd(0.1))

        manager = Manager(
            collectives=collectives,
            load_state_dict=state.load_state_dict,
            state_dict=state.state_dict,
            min_replica_size=1,
            use_async_quorum=self.use_async_quorum,
            timeout=timedelta(seconds=30),
            quorum_timeout=timedelta(seconds=30),
            connect_timeout=timedelta(seconds=30),
            rank=rank,
            world_size=self.world_size,
            store_addr=store_addr,
            lighthouse_addr=self.lighthouse_address,
            replica_id=f"replica_{self.replica_id}",
        )
        optimizer = OptimizerWrapper(manager, state)
        if attempt > 0 and rank == 0 and self.announce_restart is not None:
            self.announce_restart.set()
        try:
            if self.pipelined is not None:
                self._pipelined_loop(rank, manager, state)
            else:
                self._blocking_loop(rank, manager, state, optimizer)
            return {
                "replica_id": self.replica_id,
                "rank": rank,
                "state_dict": jax.tree_util.tree_map(
                    np.asarray, state.state_dict()
                ),
                "manager_state": manager.state_dict(),
                "metrics": manager.metrics().snapshot(),
            }
        finally:
            manager.shutdown()
            collectives.shutdown()

    def _blocking_loop(self, rank, manager, state, optimizer) -> None:
        while manager.current_step() < self.num_steps:
            if (
                self.gate_event is not None
                and manager.current_step() == self.gate_step
            ):
                assert self.gate_event.wait(timeout=180)
            self.failure_injector.check(rank, manager.current_step())
            optimizer.zero_grad()  # start_quorum
            x, y = _batch(manager.current_step())
            grads = _grad_fn(state.params, x, y)
            avg_grads = manager.allreduce(grads).wait()
            optimizer.step(avg_grads)

    def _pipelined_loop(self, rank, manager, state) -> None:
        ddp = PipelinedDDP(
            manager,
            state,
            lambda p, x, y: _value_and_grad_fn(p, x, y),
            compress=None if self.pipelined == "plain" else self.pipelined,
        )
        # Local dispatch counter, not manager.current_step(): the settle
        # runs one iteration behind, and a non-committed batch is consumed
        # rather than replayed (the reference's sampler is lossy under
        # faults too, reference data.py:33-36). Batch choice only affects
        # this group's contribution — the averaged update every group
        # applies is shared, so the bitwise oracle is unaffected.
        i = 0
        while manager.current_step() < self.num_steps:
            self.failure_injector.check(rank, manager.current_step())
            x, y = _batch(i)
            i += 1
            ddp.step(x, y)
        # Every group exits its loop at the same settle (the shared ring
        # paces iterations), each holding one in-flight step; flushing
        # commits it jointly, overshooting num_steps by one everywhere.
        ddp.flush()


def _run_replicas(
    num_replicas: int,
    num_steps: int,
    injectors: Optional[List[FailureInjector]] = None,
    use_async_quorum: bool = True,
    min_replicas_lighthouse: int = 1,
    gates: Optional[Dict[int, Dict[str, Any]]] = None,
    world_size: int = 1,
    pipelined: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """Runs ``num_replicas`` groups of ``world_size`` ranks; returns the flat
    list of per-rank results (group-major order)."""
    lighthouse = Lighthouse(
        bind="[::]:0",
        min_replicas=min_replicas_lighthouse,
        join_timeout_ms=200,
        quorum_tick_ms=50,
        # Wide enough that a loaded CI host (the full suite runs many
        # thread-per-replica tests back to back) can't age out a LIVE
        # member between 100 ms heartbeats; failure detection latency is
        # not what these tests assert.
        heartbeat_timeout_ms=4000,
    )
    injectors = injectors or [FailureInjector() for _ in range(num_replicas)]
    try:
        with ThreadPoolExecutor(max_workers=num_replicas) as ex:
            futures = [
                ex.submit(
                    Runner(
                        **{
                            "replica_id": i,
                            "lighthouse_address": lighthouse.address(),
                            "failure_injector": injectors[i],
                            "num_steps": num_steps,
                            "use_async_quorum": use_async_quorum,
                            "world_size": world_size,
                            "pipelined": pipelined,
                            **(gates or {}).get(i, {}),
                        }
                    ).run_replica
                )
                for i in range(num_replicas)
            ]
            return [r for f in futures for r in f.result(timeout=120)]
    finally:
        lighthouse.shutdown()


def _assert_bitwise_identical(results: List[Dict[str, Any]]) -> None:
    ref = results[0]["state_dict"]
    for other in results[1:]:
        leaves_a, td_a = jax.tree_util.tree_flatten(ref)
        leaves_b, td_b = jax.tree_util.tree_flatten(other["state_dict"])
        assert td_a == td_b
        for a, b in zip(leaves_a, leaves_b):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), (
                "state dicts diverged"
            )


class TestManagerInteg:
    def test_happy_path_two_replicas(self):
        results = _run_replicas(num_replicas=2, num_steps=5)
        assert len(results) == 2
        for r in results:
            assert r["manager_state"]["step"] == 5
        _assert_bitwise_identical(results)

    def test_ddp_recovery_async(self):
        injectors = [FailureInjector(), FailureInjector().fail_at(0, 2)]
        results = _run_replicas(
            num_replicas=2, num_steps=6, injectors=injectors
        )
        assert injectors[1].count == 1
        for r in results:
            assert r["manager_state"]["step"] == 6
        _assert_bitwise_identical(results)
        # Observability: the restarted replica's manager recorded its heal
        # and both sides timed the transaction phases.
        healed = next(r for r in results if r["replica_id"] == 1)
        assert healed["metrics"]["counters"]["heals"] >= 1
        for r in results:
            c, t = r["metrics"]["counters"], r["metrics"]["timers_s"]
            assert c["commits"] >= 1 and c["reconfigures"] >= 1
            for phase in ("quorum", "reconfigure", "allreduce", "commit_vote"):
                assert t[phase]["n"] >= 1, phase

    def test_ddp_recovery_sync_quorum(self):
        injectors = [FailureInjector(), FailureInjector().fail_at(0, 2)]
        results = _run_replicas(
            num_replicas=2,
            num_steps=6,
            injectors=injectors,
            use_async_quorum=False,
        )
        assert injectors[1].count == 1
        _assert_bitwise_identical(results)

    def test_ddp_recovery_multiple_failures(self):
        injectors = [
            FailureInjector().fail_at(0, 4),
            FailureInjector().fail_at(0, 2),
        ]
        results = _run_replicas(
            num_replicas=2, num_steps=7, injectors=injectors
        )
        assert injectors[0].count == 1
        assert injectors[1].count == 1
        _assert_bitwise_identical(results)

    def test_three_replicas_one_death(self):
        injectors = [
            FailureInjector(),
            FailureInjector(),
            FailureInjector().fail_at(0, 1),
        ]
        # Survivors hold at step 3 until replica 2's restart is live, so the
        # heal deterministically overlaps their run (see Runner.gate_step).
        rejoined = threading.Event()
        results = _run_replicas(
            num_replicas=3,
            num_steps=8,
            injectors=injectors,
            gates={
                0: {"gate_step": 3, "gate_event": rejoined},
                1: {"gate_step": 3, "gate_event": rejoined},
                2: {"announce_restart": rejoined},
            },
        )
        assert injectors[2].count == 1
        for r in results:
            assert r["manager_state"]["step"] == 8
        _assert_bitwise_identical(results)

    def test_happy_path_multi_rank(self):
        # 2 groups x 2 local ranks: exercises the C++ local-rank quorum
        # barrier (one lighthouse request per group), the per-rank ring
        # namespacing ({store}/torchft/{quorum_id}/{rank}), and the
        # AND-vote across local ranks in should_commit.
        results = _run_replicas(num_replicas=2, num_steps=4, world_size=2)
        assert len(results) == 4
        assert [(r["replica_id"], r["rank"]) for r in results] == [
            (0, 0), (0, 1), (1, 0), (1, 1),
        ]
        for r in results:
            assert r["manager_state"]["step"] == 4
        _assert_bitwise_identical(results)

    def test_ddp_recovery_multi_rank(self):
        # Reference manager_integ_test.py:284-323: both ranks of group 1 die
        # at step 2; the whole group restarts, rejoins, heals from group 0,
        # and every rank of every group converges bit-identically.
        injectors = [
            FailureInjector(),
            FailureInjector().fail_at(0, 2).fail_at(1, 2),
        ]
        results = _run_replicas(
            num_replicas=2, num_steps=6, injectors=injectors, world_size=2
        )
        assert injectors[1].count >= 1  # rank races: >=1 of the 2 fires
        assert len(results) == 4
        for r in results:
            assert r["manager_state"]["step"] == 6
        _assert_bitwise_identical(results)

    def test_pipelined_happy_path(self):
        # PipelinedDDP: step i's ring overlaps step i+1's gradient program.
        # The settle runs one step behind, so both groups exit the loop
        # holding one in-flight step and flush() commits it jointly.
        results = _run_replicas(num_replicas=2, num_steps=5, pipelined="plain")
        for r in results:
            assert r["manager_state"]["step"] == 6  # 5 + the flushed step
        _assert_bitwise_identical(results)

    def test_pipelined_bf16_compress(self):
        # bf16 wire compression (the torch-DDP bf16_compress_hook analog):
        # both members compress identically, so the averaged update is
        # still bit-identical across groups.
        results = _run_replicas(num_replicas=2, num_steps=4, pipelined="bf16")
        _assert_bitwise_identical(results)

    def test_pipelined_int8_compress(self):
        # int8+error-feedback, ALLGATHER transport (device-link-optimal
        # mode): the {q, scale} payload is dequantize-averaged on settle.
        # Both members quantize identically, so groups still agree
        # bit-for-bit; training correctness (loss actually falls under
        # quantization) is covered by the convergence assert.
        results = _run_replicas(num_replicas=2, num_steps=4, pipelined="int8")
        _assert_bitwise_identical(results)
        for r in results:
            assert r["manager_state"]["step"] == 5  # 4 + the flushed step

    def test_pipelined_q8_compress(self):
        # int8+error-feedback, QUANTIZED-RING transport (TCP-optimal
        # mode, wire bytes constant in cohort size): the native ring
        # circulates owner-quantized codes verbatim in phase 2, so both
        # groups decode identical averages — bitwise oracle holds.
        results = _run_replicas(num_replicas=2, num_steps=4, pipelined="q8")
        _assert_bitwise_identical(results)
        for r in results:
            assert r["manager_state"]["step"] == 5  # 4 + the flushed step

    def test_pipelined_recovery(self):
        # Group 1 dies at step 2 mid-pipeline (an in-flight ring op is
        # abandoned), restarts, heals; the heal path recomputes the
        # pre-dispatched gradients from the recovered weights
        # (PipelinedDDP.step's is_healing branch).
        injectors = [FailureInjector(), FailureInjector().fail_at(0, 2)]
        results = _run_replicas(
            num_replicas=2, num_steps=6, injectors=injectors,
            pipelined="plain",
        )
        assert injectors[1].count == 1
        steps = {r["manager_state"]["step"] for r in results}
        assert len(steps) == 1 and steps.pop() >= 6
        _assert_bitwise_identical(results)
        healed = next(r for r in results if r["replica_id"] == 1)
        assert healed["metrics"]["counters"]["heals"] >= 1

    def test_pipelined_mixed_with_blocking(self):
        # Protocol interop: a pipelined group and a blocking group share a
        # cohort. The pipelined member runs one fewer loop step (its flush
        # settles the last) so both dispatch exactly 5 ring ops and end at
        # step 5 — and since every group applies the same averaged update,
        # states match bit-for-bit even though the pipelined member
        # contributes one-step-stale gradients.
        results = _run_replicas(
            num_replicas=2,
            num_steps=5,
            gates={1: {"pipelined": "plain", "num_steps": 4}},
        )
        for r in results:
            assert r["manager_state"]["step"] == 5
        _assert_bitwise_identical(results)

    def test_quorum_timeout_fast_fail(self):
        # A quorum that cannot complete (min_replicas=2, one participant)
        # must fail fast with TimeoutError, not hang
        # (reference manager_integ_test.py:356-368).
        import time

        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=2, join_timeout_ms=60000
        )
        store = Store()
        collectives = HostCollectives()
        manager = Manager(
            collectives=collectives,
            load_state_dict=lambda sd: None,
            state_dict=lambda: {},
            min_replica_size=2,
            rank=0,
            world_size=1,
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="lonely",
            use_async_quorum=False,
        )
        try:
            start = time.monotonic()
            with pytest.raises(TimeoutError):
                manager.start_quorum(timeout=timedelta(milliseconds=250))
            assert time.monotonic() - start < 2.0
        finally:
            manager.shutdown()
            collectives.shutdown()
            store.shutdown()
            lighthouse.shutdown()


class TestPipelinedDDPUnit:
    """Mock-manager unit tests for PipelinedDDP's int8 wire details
    (review findings r4): structure-safe quantize splitting and the
    error-feedback rollback on a discarded step."""

    def _mock(self, commits):
        from unittest.mock import create_autospec

        from torchft_tpu.manager import Manager as RealManager

        manager = create_autospec(RealManager, instance=True)
        manager.allreduce.side_effect = (
            lambda tree, op=None, wire=None: _completed_work(tree)
        )
        manager.is_healing.return_value = False
        manager.should_commit.side_effect = list(commits)
        return manager

    def test_int8_handles_tuple_structured_grads(self):
        # A gradient pytree CONTAINING a 2-tuple node: the dq/res split
        # must be structure-driven (tree_transpose), not tuple-sniffing —
        # a naive is_leaf=isinstance(tuple) silently ships residuals as
        # gradients for such trees.
        import jax.numpy as jnp
        import numpy as np

        manager = self._mock([True, True, True])
        state = FTTrainState(
            {"w": (jnp.ones((3,)), jnp.full((2,), 2.0))}, optax.sgd(1.0)
        )

        def grad_fn(p, _):
            return 0.0, jax.tree_util.tree_map(lambda l: l * 0.5, p)

        ddp = PipelinedDDP(manager, state, grad_fn, compress="q8")
        ddp.step(None)
        ddp.flush()
        # grads = 0.5*w quantize exactly (single-scale leaves); sgd(1.0)
        # applies them: w = w - 0.5*w
        np.testing.assert_allclose(
            np.asarray(state.params["w"][0]), 0.5, atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(state.params["w"][1]), 1.0, atol=1e-3
        )

    def test_int8_residual_rolls_back_on_discarded_step(self):
        # A non-committed settle must restore the pre-dispatch EF carry:
        # the abandoned payload's quantization error belongs to gradients
        # nobody applied.
        import jax.numpy as jnp
        import numpy as np

        manager = self._mock([False, True])
        state = FTTrainState({"w": jnp.ones((4,))}, optax.sgd(1.0))
        # gradient that does NOT quantize exactly -> nonzero residual
        g = jnp.asarray([0.1, 0.0333, 0.00777, 0.0001])

        def grad_fn(p, _):
            return 0.0, {"w": g}

        ddp = PipelinedDDP(manager, state, grad_fn, compress="q8")
        ddp.step(None)           # dispatch #1
        ddp.step(None)           # settles #1 -> NOT committed
        res_after_abort = jax.tree_util.tree_map(
            np.asarray, ddp._residual
        )
        ddp.flush()              # settles #2 -> committed
        # after the aborted settle the carry equals the value BEFORE
        # dispatch #2 consumed it... i.e. dispatch #2 ran quantize on the
        # rolled-back (zero) carry, so the live residual equals the
        # single-step quantization error, not a double-accumulated one
        scale = float(jnp.max(jnp.abs(g))) / 127
        assert np.all(np.abs(res_after_abort["w"]) <= scale / 2 + 1e-9)


def _completed_work(tree):
    from torchft_tpu.collectives import _completed

    return _completed(tree)
