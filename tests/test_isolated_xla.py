"""IsolatedXLACollectives: the compiled data plane in a disposable child.

The subsystem's contracts, layered:

- shm segments: native lifecycle (creator unlinks, attachments don't),
  cross-process visibility, the live-handle leak oracle;
- layout: the native ``tft_shm_layout_json`` authority matches the Python
  ``_plan_groups`` mirror positionally (the invariant that lets parent
  and child lay out the same bytes independently);
- monitored channel: a dead child surfaces within a liveness interval,
  child exceptions re-raise in the parent with the child traceback;
- the backend end-to-end ON THIS HOST via the store-fallback reduction
  (the capability probe's measured verdict where CPU jax has no compiled
  multi-process path): multi-member ops in threads, bit-identity against
  the host ring, kill-and-respawn reconfigure, mid-op child SIGKILL;
- manager + AdaptiveDDP integration: managed ``None``-default latching,
  the ``xla_iso`` candidate, and never-beat-by-crash (an un-spawnable
  child records sentinels, the cohort locks a runnable schedule).

The compiled-psum path itself (bit-identity vs the in-process
``XLACollectives``) needs a CPU multiprocess collectives backend and is
gated like every other gloo test.
"""

import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import numpy as np
import pytest

from conftest import CPU_MULTIPROCESS_SKIP, HAS_CPU_MULTIPROCESS

from torchft_tpu import _native
from torchft_tpu.collectives import (
    HostCollectives,
    ReduceOp,
    _plan_groups,
)
from torchft_tpu.isolated_xla import (
    ChildDiedError,
    IsolatedXLACollectives,
    _MonitoredChannel,
    _apply_child_env,
    _child_env,
    _sig_layout,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def store():
    s = _native.Store()
    yield s
    s.shutdown()


def _run_all(cols, fn):
    results = [None] * len(cols)
    errors = []

    def run(r):
        try:
            results[r] = fn(r, cols[r])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=run, args=(r,)) for r in range(len(cols))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


def _iso_ring(store, prefix, world, timeout_s=30):
    cols = [
        IsolatedXLACollectives(
            timeout=timedelta(seconds=timeout_s),
            connect_timeout=timedelta(seconds=30),
        )
        for _ in range(world)
    ]
    addr = f"{store.address()}/{prefix}"
    _run_all(cols, lambda r, c: c.configure(addr, r, world))
    return cols


class TestShmSegments:
    def test_create_attach_visibility_and_leak_oracle(self):
        base = _native.shm_live_count()
        seg = _native.ShmSegment.create("tft_test_seg_a", 8192)
        view = np.frombuffer(seg.buffer(), np.float32)
        view[:3] = [1.5, 2.5, 3.5]
        att = _native.ShmSegment.attach("tft_test_seg_a", 8192)
        got = np.frombuffer(att.buffer(), np.float32)
        np.testing.assert_array_equal(got[:3], [1.5, 2.5, 3.5])
        # writes travel the other way too (same kernel pages)
        got[3] = 9.0
        assert view[3] == 9.0
        assert _native.shm_live_count() == base + 2
        del view, got
        att.close()
        seg.close()
        assert _native.shm_live_count() == base

    def test_attach_missing_and_short_segment_fail(self):
        with pytest.raises(RuntimeError, match="shm_open"):
            _native.ShmSegment.attach("tft_test_never_created", 4096)
        seg = _native.ShmSegment.create("tft_test_seg_small", 4096)
        try:
            # attaching at a LARGER size must fail loudly, not SIGBUS
            with pytest.raises(RuntimeError, match="smaller"):
                _native.ShmSegment.attach("tft_test_seg_small", 8192)
        finally:
            seg.close()

    def test_creator_unlinks_attacher_does_not(self):
        seg = _native.ShmSegment.create("tft_test_seg_own", 4096)
        att = _native.ShmSegment.attach("tft_test_seg_own", 4096)
        att.close()  # attachment close must NOT remove the name
        att2 = _native.ShmSegment.attach("tft_test_seg_own", 4096)
        att2.close()
        seg.close()  # creator close unlinks
        with pytest.raises(RuntimeError, match="shm_open"):
            _native.ShmSegment.attach("tft_test_seg_own", 4096)

    def test_unlink_is_idempotent(self):
        _native.shm_unlink("tft_test_seg_gone")  # never created: no error
        seg = _native.ShmSegment.create("tft_test_seg_unl", 4096)
        _native.shm_unlink("tft_test_seg_unl")
        _native.shm_unlink("tft_test_seg_unl")
        seg.close()  # creator's unlink finds the name gone: still fine


class TestShmLayout:
    def _sig(self, specs):
        return tuple((shape, np.dtype(dt)) for shape, dt in specs)

    @pytest.mark.parametrize("wire_name,wire_code", [
        (None, 0), ("bf16", 1), ("q8", 2), ("q8ef", 3),
    ])
    def test_native_layout_matches_python_plan_groups(
        self, wire_name, wire_code
    ):
        # The invariant both sides of the shm boundary depend on: native
        # tft_shm_layout_json groups leaves exactly like the Python
        # _plan_groups mirror (plan_build's first-appearance order), so
        # parent-built views and child-built views address one layout.
        import ml_dtypes

        sig = self._sig([
            ((7, 3), np.float32),
            ((5,), ml_dtypes.bfloat16 if wire_name in (None, "bf16")
             else np.float32),
            ((2, 2), np.float32),
        ])
        counts = [int(np.prod(s)) for s, _ in sig]
        from torchft_tpu.collectives import _NATIVE_DTYPES

        codes = [_NATIVE_DTYPES[dt] for _, dt in sig]
        native = _native.shm_layout(counts, codes, wire_code)
        groups = _plan_groups(sig, wire_name)
        assert len(native["groups"]) == len(groups)
        for ng, (gdt, idxs) in zip(native["groups"], groups):
            assert ng["dtype"] == _NATIVE_DTYPES[gdt]
            assert ng["count"] == sum(counts[i] for i in idxs)
        # per-leaf group assignment and elem offsets match the mirror
        for i, nl in enumerate(native["leaves"]):
            gdt, idxs = groups[nl["group"]]
            assert i in idxs
            expect_off = sum(counts[j] for j in idxs[: idxs.index(i)])
            assert nl["off"] == expect_off

    def test_group_bases_are_64_aligned_and_total_covers(self):
        lay = _native.shm_layout([3, 5, 7], [2, 0, 2], 0)  # i32,f32,i32
        for g in lay["groups"]:
            assert g["offset"] % 64 == 0
        last = lay["groups"][-1]
        dt = {0: 4, 1: 8, 2: 4, 3: 8, 4: 2}[last["dtype"]]
        assert lay["total_bytes"] >= last["offset"] + last["count"] * dt

    def test_q8_wire_rejects_int_leaves(self):
        with pytest.raises(RuntimeError, match="q8"):
            _native.shm_layout([4], [2], 2)  # i32 leaf on the q8 wire

    def test_empty_and_bad_inputs(self):
        with pytest.raises(RuntimeError, match="empty"):
            _native.shm_layout([], [], 0)
        with pytest.raises(RuntimeError, match="wire"):
            _native.shm_layout([4], [0], 9)


class _FakeChild:
    """Socketpair-backed stand-in for the child side of the channel."""

    def __init__(self):
        self.parent_sock, self.child_sock = socket.socketpair()
        self.rc = None

    def alive(self):
        return self.rc

    def reply(self, payload: bytes):
        self.child_sock.sendall(payload)

    def die(self, rc=-9):
        self.rc = rc
        self.child_sock.close()


class TestMonitoredChannel:
    def test_roundtrip_and_child_error_reraise(self):
        fake = _FakeChild()
        ch = _MonitoredChannel(fake.parent_sock, fake.alive)
        ch.send({"cmd": "x"})
        fake.reply(b'{"ok": true}\n')
        assert ch.recv(5.0) == {"ok": True}
        fake.reply(
            b'{"error": "ValueError: boom", "tb": "Traceback...child"}\n'
        )
        with pytest.raises(RuntimeError, match="boom") as ei:
            ch.recv(5.0)
        assert "child traceback" in str(ei.value)
        ch.close()
        fake.child_sock.close()

    def test_child_death_beats_the_op_timeout(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_ISO_LIVENESS_MS", "20")
        fake = _FakeChild()
        ch = _MonitoredChannel(fake.parent_sock, fake.alive)
        threading.Timer(0.1, fake.die).start()
        t0 = time.perf_counter()
        with pytest.raises(ChildDiedError):
            ch.recv(30.0)  # would be a 30 s hang without liveness polling
        assert time.perf_counter() - t0 < 5.0
        ch.close()

    def test_timeout_without_death(self):
        fake = _FakeChild()
        ch = _MonitoredChannel(fake.parent_sock, fake.alive)
        with pytest.raises(TimeoutError):
            ch.recv(0.3)
        ch.close()
        fake.child_sock.close()


class TestChildEnvContract:
    def test_child_env_is_parent_env_plus_repo_pythonpath(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_ENV_PROBE", "x1")
        env = _child_env()
        assert env["TORCHFT_ENV_PROBE"] == "x1"
        assert REPO in env["PYTHONPATH"].split(os.pathsep)

    def test_apply_child_env_replaces_not_merges(self):
        # Regression: zygote-forked children used to MERGE the shipped
        # env on top of the zygote's startup snapshot, so a variable
        # unset in the parent since the zygote started (JAX_PLATFORMS,
        # TORCHFT_*) still reached the child — diverging from the
        # classic-spawn semantics _spawn_child promises.
        snap = dict(os.environ)
        try:
            os.environ["TORCHFT_STALE_VAR"] = "zombie"
            desired = dict(snap)
            desired.pop("TORCHFT_STALE_VAR", None)
            desired["TORCHFT_FRESH_VAR"] = "new"
            _apply_child_env(desired)
            assert "TORCHFT_STALE_VAR" not in os.environ
            assert os.environ.get("TORCHFT_FRESH_VAR") == "new"
        finally:
            os.environ.clear()
            os.environ.update(snap)


class TestIsolatedBackendStorePath:
    """End-to-end on this host: the capability probe lands on the store
    fallback (no compiled CPU multiprocess path), which exercises the
    whole parent half — shm staging, monitored channel, kill/respawn —
    against real children."""

    def test_allreduce_tree_sum_avg_int_and_host_ring_identity(self, store):
        import jax.numpy as jnp

        cols = _iso_ring(store, "q0", 2)
        try:
            assert all(c.reduction_path() == "store" or
                       c.reduction_path() == "psum" for c in cols)
            tree = lambda r: {  # noqa: E731
                "w": jnp.arange(33, dtype=jnp.float32) * (r + 1) * 0.37,
                "b": np.arange(5, dtype=np.int32) * (r + 1),
            }
            outs = _run_all(
                cols,
                lambda r, c: c.allreduce(tree(r), ReduceOp.SUM).wait(),
            )
            # members agree bitwise
            np.testing.assert_array_equal(
                np.asarray(outs[0]["w"]), np.asarray(outs[1]["w"])
            )
            np.testing.assert_array_equal(
                np.asarray(outs[0]["b"]), np.asarray(outs[1]["b"])
            )
            # ... and match the HOST RING bitwise on W=2 (two-operand
            # sums are order-free in IEEE, so the oracle is exact)
            hcs = [HostCollectives(timeout=timedelta(seconds=15))
                   for _ in range(2)]
            addr = f"{store.address()}/hr0"
            _run_all(hcs, lambda r, c: c.configure(addr, r, 2))
            houts = _run_all(
                hcs, lambda r, c: c.allreduce(tree(r), ReduceOp.SUM).wait()
            )
            np.testing.assert_array_equal(
                np.asarray(outs[0]["w"]), np.asarray(houts[0]["w"])
            )
            np.testing.assert_array_equal(
                np.asarray(outs[0]["b"]), np.asarray(houts[0]["b"])
            )
            for c in hcs:
                c.shutdown()
            # AVG: int leaves floor-divide in their own dtype
            avg = _run_all(
                cols,
                lambda r, c: c.allreduce(
                    jnp.full((3,), 3.0 + r), ReduceOp.AVG
                ).wait(),
            )
            assert np.allclose(np.asarray(avg[0]), 3.5)
            iavg = _run_all(
                cols,
                lambda r, c: c.allreduce(
                    np.full((2,), 3 + r, np.int32), ReduceOp.AVG
                ).wait(),
            )
            assert iavg[0].dtype == np.int32 and int(iavg[0][0]) == 3
        finally:
            for c in cols:
                c.shutdown()

    def test_world3_members_identical_and_close_to_ring(self, store):
        import jax.numpy as jnp

        cols = _iso_ring(store, "q3", 3)
        try:
            rng = np.random.default_rng(7)
            base = rng.standard_normal(257).astype(np.float32)
            outs = _run_all(
                cols,
                lambda r, c: c.allreduce(
                    jnp.asarray(base * (r + 1)), ReduceOp.AVG
                ).wait(),
            )
            np.testing.assert_array_equal(
                np.asarray(outs[0]), np.asarray(outs[1])
            )
            np.testing.assert_array_equal(
                np.asarray(outs[0]), np.asarray(outs[2])
            )
            np.testing.assert_allclose(
                np.asarray(outs[0]), base * 2.0, rtol=1e-6
            )
        finally:
            for c in cols:
                c.shutdown()

    def test_slot_recycling_never_serves_stale_payloads(self, store):
        # Regression: store.get only waits for key EXISTENCE, so once the
        # payload slots recycle (op n and op n-window share keys) a
        # member one op ahead of a laggy peer could read the peer's
        # window-old payload and silently corrupt the reduction. The
        # per-(slot, rank) version key forbids it: run 3x the window of
        # sequential ops with per-op distinct values, one member lagging
        # so the other is always ahead at the version poll, and assert
        # every single op's value.
        import jax.numpy as jnp

        from torchft_tpu.isolated_xla import _STORE_SLOTS

        cols = _iso_ring(store, "qstale", 2)
        try:
            nops = 3 * _STORE_SLOTS
            def run(r, c):
                outs = []
                for op in range(nops):
                    if r == 1:
                        time.sleep(0.03)  # the laggy member
                    outs.append(
                        np.asarray(c.allreduce(
                            jnp.full((64,), float((op + 1) * (r + 1))),
                            ReduceOp.SUM,
                        ).wait())
                    )
                return outs

            results = _run_all(cols, run)
            for op in range(nops):
                want = float((op + 1) * 3)  # (op+1)*1 + (op+1)*2
                for r in range(2):
                    assert np.allclose(results[r][op], want), (
                        op, r, results[r][op][0], want
                    )
        finally:
            for c in cols:
                c.shutdown()

    def test_allgather_broadcast_barrier(self, store):
        import jax.numpy as jnp

        cols = _iso_ring(store, "q1", 2)
        try:
            def ops(r, c):
                g = c.allgather(jnp.full((4,), float(r * 10 + 1))).wait()
                b = c.broadcast(jnp.full((2,), float(r)), root=1).wait()
                c.barrier().wait()
                return g, b

            outs = _run_all(cols, ops)
            for g, b in outs:
                assert np.allclose(np.asarray(g[0]), 1.0)
                assert np.allclose(np.asarray(g[1]), 11.0)
                assert np.allclose(np.asarray(b), 1.0)
        finally:
            for c in cols:
                c.shutdown()

    def test_reconfigure_is_kill_and_respawn(self, store):
        import jax.numpy as jnp

        cols = _iso_ring(store, "q2", 2)
        try:
            pids = [c.child_pid() for c in cols]
            assert all(p is not None for p in pids)
            # parent-side device arrays survive untouched (no in-process
            # runtime teardown happens): hold one across the reconfigure
            keep = jnp.arange(16, dtype=jnp.float32) * 1.25
            keep_host = np.asarray(keep).copy()
            addr = f"{store.address()}/q2b"
            _run_all(cols, lambda r, c: c.configure(addr, r, 2))
            new_pids = [c.child_pid() for c in cols]
            assert all(
                n is not None and n != p for n, p in zip(new_pids, pids)
            ), (pids, new_pids)
            # the old children are really gone — SIGKILLed children stay
            # kill(0)-visible zombies until the zygote's reaper tick
            # collects them, so poll with a deadline instead of asserting
            # instantaneous disappearance
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and any(
                _pid_alive(p) for p in pids
            ):
                time.sleep(0.05)
            assert all(not _pid_alive(p) for p in pids), pids
            np.testing.assert_array_equal(np.asarray(keep), keep_host)
            outs = _run_all(
                cols,
                lambda r, c: c.allreduce(
                    jnp.full((8,), 2.0), ReduceOp.SUM
                ).wait(),
            )
            assert np.allclose(np.asarray(outs[0]), 4.0)
        finally:
            for c in cols:
                c.shutdown()

    def test_mid_op_child_kill_fails_fast_then_respawn_recovers(self, store):
        import jax.numpy as jnp

        cols = _iso_ring(store, "q4", 2, timeout_s=6)
        try:
            victim_pid = cols[1].child_pid()
            os.kill(victim_pid, signal.SIGKILL)
            t0 = time.perf_counter()
            errors = [None, None]

            def op(r, c):
                try:
                    c.allreduce(jnp.ones((4,)), ReduceOp.SUM).wait()
                except Exception as e:  # noqa: BLE001
                    errors[r] = e

            _run_all(cols, op)
            elapsed = time.perf_counter() - t0
            # the killed member fails within a liveness interval; the
            # survivor within one op deadline — never the runtime
            # heartbeat's minutes
            assert isinstance(errors[1], ChildDiedError), errors
            assert errors[0] is not None, "survivor must not hang"
            assert elapsed < 15.0, elapsed
            # step-granularity recovery: the next configure respawns and
            # the cohort reduces again
            addr = f"{store.address()}/q4b"
            _run_all(cols, lambda r, c: c.configure(addr, r, 2))
            outs = _run_all(
                cols,
                lambda r, c: c.allreduce(
                    jnp.full((4,), 1.0), ReduceOp.SUM
                ).wait(),
            )
            assert np.allclose(np.asarray(outs[0]), 2.0)
        finally:
            for c in cols:
                c.shutdown()

    @pytest.mark.parametrize("fresh_rank", [0, 1])
    def test_elastic_join_fresh_member_configures_uniformly(
        self, store, fresh_rank
    ):
        # Regression: the capability probe and the /child rendezvous are
        # cohort-wide, so a cohort with MIXED path hints — an elastic
        # joiner's fresh parent sends none while incumbents hint the
        # known verdict — used to strand one side alone in a collective
        # the other never joins (the joiner wedged for the full
        # connect+op deadline, and its parent's configure failed on
        # every retry since _path never locked). Rank 0 now rendezvouses
        # ONE decision through the store; both orderings must configure
        # cleanly and land on the same path.
        import jax.numpy as jnp

        cols = _iso_ring(store, f"qjoin{fresh_rank}", 2, timeout_s=8)
        old = None
        try:
            # the member at fresh_rank "restarts": a brand-new backend
            # with no memory of the locked path (path_hint=None)
            old = cols[fresh_rank]
            cols[fresh_rank] = IsolatedXLACollectives(
                timeout=timedelta(seconds=8),
                connect_timeout=timedelta(seconds=20),
            )
            addr = f"{store.address()}/qjoin{fresh_rank}b"
            _run_all(cols, lambda r, c: c.configure(addr, r, 2))
            assert cols[0].reduction_path() == cols[1].reduction_path()
            outs = _run_all(
                cols,
                lambda r, c: c.allreduce(
                    jnp.full((4,), float(r + 1)), ReduceOp.SUM
                ).wait(),
            )
            assert np.allclose(np.asarray(outs[0]), 3.0)
            assert np.allclose(np.asarray(outs[1]), 3.0)
        finally:
            if old is not None:
                old.shutdown()
            for c in cols:
                c.shutdown()

    def test_superseded_configure_never_installs_its_child(self):
        # Regression: a configure whose caller already gave up (outer
        # timeout -> the next quorum's configure ran its entry kill)
        # used to keep running, install its late child, flip
        # _aborted=False, and leak the child untracked on the stale
        # quorum prefix. The generation token makes the stale install
        # kill the child and raise instead.
        c = IsolatedXLACollectives(
            timeout=timedelta(seconds=10),
            connect_timeout=timedelta(seconds=20),
        )
        real_spawn = c._spawn_and_connect_detached
        try:
            gate = threading.Event()
            release = threading.Event()
            spawned_pids = []

            def slow_spawn():
                gate.set()
                assert release.wait(timeout=30)
                out = real_spawn()
                spawned_pids.append(out[0].pid)
                return out

            c._spawn_and_connect_detached = slow_spawn
            with c._child_lock:
                c._cfg_gen += 1
                gen = c._cfg_gen
            errors = []

            def stale_configure():
                try:
                    c._take_or_spawn_child(gen)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            t = threading.Thread(target=stale_configure)
            t.start()
            assert gate.wait(timeout=10)
            with c._child_lock:
                c._cfg_gen += 1  # the newer configure's entry kill ran
            release.set()
            t.join(timeout=60)
            assert not t.is_alive()
            assert errors and "superseded" in str(errors[0]), errors
            assert c.child_pid() is None, "stale child must not install"
            # ... and the late child is really reaped, not leaked
            assert spawned_pids
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and _pid_alive(
                spawned_pids[0]
            ):
                time.sleep(0.05)
            assert not _pid_alive(spawned_pids[0]), spawned_pids
        finally:
            c._spawn_and_connect_detached = real_spawn
            c.shutdown()

    def test_outer_configure_timeout_covers_inner_deadlines(self):
        # Regression: the outer configure bound was connect+op while the
        # inner work can legitimately take spawn accept (<= connect) +
        # hello (<= connect) + configure reply (<= connect+op) — a slow
        # but healthy configure was abandoned mid-flight.
        c = IsolatedXLACollectives(
            timeout=timedelta(seconds=7),
            connect_timeout=timedelta(seconds=11),
        )
        try:
            assert c._outer_configure_timeout_s() >= 3 * 11 + 7
        finally:
            c.shutdown()

    def test_segment_regrow_evicts_all_staging_views(self):
        # Regression: regenerating a segment unmapped the old pages
        # while _staging still held OTHER signatures' numpy views into
        # them (use-after-unmap; the generation check only rejected the
        # entries on their next lookup, it did not drop the views).
        c = IsolatedXLACollectives()
        try:
            c._staging_for((((8,), np.dtype(np.float32)),), 1)
            c._staging_for((((4,), np.dtype(np.int32)),), 1)
            assert len(c._staging) == 2
            # a signature larger than the segment forces regeneration
            c._staging_for((((1 << 15,), np.dtype(np.float32)),), 1)
            assert len(c._staging) == 1, (
                "stale-generation staging (dangling views into the "
                "unmapped segment) must be evicted, not retained"
            )
            assert all(g == c._seg_gen for g, _ in c._staging.values())
            c.shutdown()
            assert c._staging == {}
        finally:
            c.shutdown()

    def test_shutdown_reaps_children_and_segments(self, store):
        base = _native.shm_live_count()
        cols = _iso_ring(store, "q5", 2)
        import jax.numpy as jnp

        _run_all(
            cols,
            lambda r, c: c.allreduce(jnp.ones((4,)), ReduceOp.SUM).wait(),
        )
        pids = [c.child_pid() for c in cols]
        for c in cols:
            c.shutdown()
        assert _native.shm_live_count() == base
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(not _pid_alive(p) for p in pids):
                break
            time.sleep(0.05)
        assert all(not _pid_alive(p) for p in pids), pids

    def test_solo_world_short_circuits_without_child(self, store):
        import jax.numpy as jnp

        c = IsolatedXLACollectives(timeout=timedelta(seconds=10))
        try:
            c.configure(f"{store.address()}/solo", 0, 1)
            assert c.reduction_path() == "solo"
            assert c.child_pid() is None
            out = c.allreduce(jnp.full((3,), 4.0), ReduceOp.AVG).wait()
            assert np.allclose(np.asarray(out), 4.0)
            assert c.allgather({"x": jnp.ones(2)}).wait()[0]["x"].shape == (2,)
        finally:
            c.shutdown()

    def test_op_stats_parity_keys(self, store):
        import jax.numpy as jnp

        cols = _iso_ring(store, "q6", 2)
        try:
            _run_all(
                cols,
                lambda r, c: c.allreduce(
                    {"w": jnp.ones(100, jnp.float32)}, ReduceOp.SUM
                ).wait(),
            )
            stats = cols[0].pop_op_stats()
            cfg = [s for s in stats if s["op"] == "configure"]
            ar = [s for s in stats if s["op"] == "allreduce"]
            assert cfg and ar
            assert cfg[0]["backend"] == "iso"
            for key in ("spawn_s", "child_init_s", "rendezvous_s", "path"):
                assert key in cfg[0]
            st = ar[-1]
            # the cross-backend accounting contract: op/bytes/d2h_bytes
            assert st["bytes"] >= 400
            assert st["d2h_bytes"] == 400  # one f32 jax leaf crossed d2h
            for key in ("pack", "d2h", "ring", "h2d", "child_s", "path"):
                assert key in st
            assert cols[0].pop_op_stats() == []  # drained
        finally:
            for c in cols:
                c.shutdown()


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False


class TestManagerIso:
    def _managers(self, n, store_list, lighthouse, iso=True):
        from torchft_tpu.manager import Manager

        managers = []
        for i in range(n):
            managers.append(
                Manager(
                    collectives=HostCollectives(
                        timeout=timedelta(seconds=15)
                    ),
                    iso_collectives=IsolatedXLACollectives(
                        timeout=timedelta(seconds=15),
                        connect_timeout=timedelta(seconds=20),
                    ) if iso else None,
                    load_state_dict=lambda s: None,
                    state_dict=lambda: {},
                    min_replica_size=n,
                    rank=0,
                    world_size=1,
                    use_async_quorum=False,
                    timeout=timedelta(seconds=15),
                    quorum_timeout=timedelta(seconds=30),
                    store_addr=store_list[i].address(),
                    lighthouse_addr=lighthouse.address(),
                    replica_id=f"iso_integ_{i}",
                )
            )
        return managers

    def test_iso_allreduce_through_managers(self):
        import jax.numpy as jnp

        from torchft_tpu import Lighthouse

        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=2, join_timeout_ms=2000,
            quorum_tick_ms=50, heartbeat_timeout_ms=5000,
        )
        stores = [_native.Store() for _ in range(2)]
        managers = self._managers(2, stores, lighthouse)
        try:
            def step(i, m):
                m.start_quorum()
                out = m.iso_allreduce(
                    {"g": jnp.full((6,), float(i + 1))}
                ).wait()
                committed = m.should_commit()
                return out, committed

            with ThreadPoolExecutor(max_workers=2) as ex:
                results = list(
                    ex.map(lambda im: step(*im), enumerate(managers))
                )
            for out, committed in results:
                assert committed, "clean iso step must commit"
                assert np.allclose(np.asarray(out["g"]), 1.5), out
        finally:
            for m in managers:
                m.shutdown()
            for s in stores:
                s.shutdown()
            lighthouse.shutdown()

    def test_child_death_latches_none_and_next_step_recovers(self):
        # The managed discipline the tentpole names: child death -> None
        # + latch -> vote discards -> forced reconfigure respawns -> the
        # NEXT step commits. No parent process restarts.
        import jax.numpy as jnp

        from torchft_tpu import Lighthouse

        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=2, join_timeout_ms=2000,
            quorum_tick_ms=50, heartbeat_timeout_ms=5000,
        )
        stores = [_native.Store() for _ in range(2)]
        managers = self._managers(2, stores, lighthouse)
        try:
            barrier = threading.Barrier(2)

            def run(i, m):
                outcomes = []
                for step in range(3):
                    m.start_quorum()
                    if step == 1 and i == 0:
                        # murder our own child mid-step, pre-dispatch
                        pid = m.iso_collectives().child_pid()
                        if pid is not None:
                            os.kill(pid, signal.SIGKILL)
                    work = m.iso_allreduce(
                        {"g": jnp.full((4,), float(i + 1))}
                    )
                    out = work.wait()
                    committed = m.should_commit()
                    outcomes.append((out is None, committed))
                    barrier.wait(timeout=60)
                return outcomes

            with ThreadPoolExecutor(max_workers=2) as ex:
                futs = [
                    ex.submit(run, i, m) for i, m in enumerate(managers)
                ]
                res = [f.result(timeout=120) for f in futs]
            # step 0: clean commit everywhere
            assert res[0][0] == (False, True)
            assert res[1][0] == (False, True)
            # step 1: the killed member resolves None and the COHORT
            # discards (AND-vote)
            assert res[0][1][0] is True, "dead child must default to None"
            assert res[0][1][1] is False and res[1][1][1] is False
            # step 2: forced reconfigure respawned the child; commits
            assert res[0][2] == (False, True), res[0]
            assert res[1][2] == (False, True), res[1]
        finally:
            for m in managers:
                m.shutdown()
            for s in stores:
                s.shutdown()
            lighthouse.shutdown()


class TestAdaptiveIsoCandidate:
    def _solo_manager(self, iso):
        from torchft_tpu import Lighthouse
        from torchft_tpu.manager import Manager

        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=1, join_timeout_ms=200,
            quorum_tick_ms=50, heartbeat_timeout_ms=2000,
        )
        store = _native.Store()
        manager = Manager(
            collectives=HostCollectives(timeout=timedelta(seconds=10)),
            iso_collectives=iso,
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=1,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            timeout=timedelta(seconds=10),
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="adaptive_iso",
        )
        return manager, store, lighthouse

    def _grad_fn(self, params, x):
        import jax
        import jax.numpy as jnp

        def loss(p):
            return jnp.mean((x @ p["w"]) ** 2)

        value, grads = jax.value_and_grad(loss)(params)
        return value, grads

    def _state(self):
        import jax.numpy as jnp
        import optax

        from torchft_tpu.train_state import FTTrainState

        return FTTrainState({"w": jnp.ones((8, 8), jnp.float32)}, optax.sgd(0.1))

    def test_candidate_joins_only_with_iso_plane(self):
        from torchft_tpu.ddp import AdaptiveDDP

        iso = IsolatedXLACollectives(timeout=timedelta(seconds=10))
        manager, store, lighthouse = self._solo_manager(iso)
        try:
            ddp = AdaptiveDDP(
                manager, self._state(), self._grad_fn, device_pack="off"
            )
            assert "xla_iso" in ddp._candidates
            # int8 compress has no iso transport: candidate dropped
            ddp8 = AdaptiveDDP(
                manager, self._state(), self._grad_fn, compress="int8",
                device_pack="off",
            )
            assert "xla_iso" not in ddp8._candidates
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()

    def test_no_iso_plane_no_candidate(self):
        from torchft_tpu.ddp import AdaptiveDDP

        manager, store, lighthouse = self._solo_manager(None)
        try:
            ddp = AdaptiveDDP(
                manager, self._state(), self._grad_fn, device_pack="off"
            )
            assert "xla_iso" not in ddp._candidates
            with pytest.raises(ValueError, match="iso_collectives"):
                AdaptiveDDP(
                    manager, self._state(), self._grad_fn, mode="xla_iso"
                )
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()

    def test_probe_with_iso_locks_and_trains(self):
        import jax.numpy as jnp

        from torchft_tpu.ddp import AdaptiveDDP

        iso = IsolatedXLACollectives(timeout=timedelta(seconds=10))
        manager, store, lighthouse = self._solo_manager(iso)
        try:
            state = self._state()
            ddp = AdaptiveDDP(
                manager, state, self._grad_fn, probe_steps=2,
                device_pack="off",
            )
            x = jnp.ones((4, 8), jnp.float32)
            for _ in range(10):
                ddp.step(x)
            ddp.flush()
            assert ddp.mode is not None
            assert "xla_iso" in ddp.decision["probe_s"]
            assert manager.current_step() == 10
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()

    def test_unspawnable_child_never_wins(self, monkeypatch):
        # The never-beat-by-crash acceptance: spawning is broken, every
        # xla_iso probe step errors (configure failure -> unusable plane
        # -> latch), the candidate records sentinels, and the cohort
        # locks a RUNNABLE schedule. The primary plane is unaffected.
        import jax.numpy as jnp

        from torchft_tpu import isolated_xla
        from torchft_tpu.ddp import AdaptiveDDP

        def no_spawn(connect):
            raise RuntimeError("injected: no child for you")

        monkeypatch.setattr(isolated_xla, "_spawn_child", no_spawn)
        iso = IsolatedXLACollectives(
            timeout=timedelta(seconds=5),
            connect_timeout=timedelta(seconds=5),
        )

        # world_size 1 takes the solo path (no child) and would never
        # exercise the spawn: force the child path by pretending the
        # world is bigger at the iso plane only. Patch configure to
        # always raise instead — the un-spawnable-child presentation the
        # manager actually sees.
        def broken_configure(store_addr, rank, world_size):
            raise RuntimeError("injected: child unspawnable")

        monkeypatch.setattr(iso, "configure", broken_configure)
        manager, store, lighthouse = self._solo_manager(iso)
        try:
            state = self._state()
            ddp = AdaptiveDDP(
                manager, state, self._grad_fn, probe_steps=2,
                device_pack="off",
            )
            x = jnp.ones((4, 8), jnp.float32)
            for _ in range(14):
                ddp.step(x)
            ddp.flush()
            assert ddp.mode is not None, "probe must terminate"
            assert ddp.mode != "xla_iso", (
                "a candidate whose child cannot spawn must never win"
            )
            assert ddp.decision["probe_s"]["xla_iso"] >= 1e8
            # the primary plane kept training through it
            assert manager.current_step() >= 8
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()


# ---------------------------------------------------------------------------
# compiled-psum path: needs the CPU multiprocess collectives backend
# ---------------------------------------------------------------------------

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
    sys.path.insert(0, {repo!r})
    import numpy as np
    from datetime import timedelta
    rank = int(sys.argv[1]); store_addr = sys.argv[2]
    from torchft_tpu import IsolatedXLACollectives
    from torchft_tpu.collectives import ReduceOp

    iso = IsolatedXLACollectives(timeout=timedelta(seconds=60),
                                 connect_timeout=timedelta(seconds=60))
    iso.configure(store_addr + "/iso0", rank, 2)
    assert iso.reduction_path() == "psum", iso.reduction_path()

    import jax, jax.numpy as jnp
    tree = {{"a": jnp.arange(1000, dtype=jnp.float32) * (rank + 1) * 0.31,
            "b": jnp.ones((7, 3), jnp.float32) * (rank + 1)}}
    got = iso.allreduce(tree, ReduceOp.AVG).wait()

    # in-process XLACollectives oracle over the SAME cohort (fresh
    # prefix): bit-identity is structural (the child RUNS XLACollectives)
    from torchft_tpu import XLACollectives
    xc = XLACollectives(timeout=timedelta(seconds=60),
                        connect_timeout=timedelta(seconds=60))
    xc.configure(store_addr + "/xla0", rank, 2)
    want = xc.allreduce(tree, ReduceOp.AVG).wait()
    for k in tree:
        assert np.array_equal(np.asarray(got[k]), np.asarray(want[k])), k

    # membership change mid-run: kill-and-respawn, then identical again
    iso.configure(store_addr + "/iso1", rank, 2)
    got2 = iso.allreduce(tree, ReduceOp.SUM).wait()
    want2 = xc.allreduce(tree, ReduceOp.SUM).wait()
    for k in tree:
        assert np.array_equal(np.asarray(got2[k]), np.asarray(want2[k])), k
    print("PSUM-OK")
    iso.shutdown(); xc.shutdown()
    """
).format(repo=REPO)


@pytest.mark.skipif(not HAS_CPU_MULTIPROCESS, reason=CPU_MULTIPROCESS_SKIP)
class TestIsolatedPsumPath:
    def test_psum_bit_identity_vs_inprocess_xla(self):
        store = _native.Store()
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _WORKER, str(r), store.address()],
                env=dict(os.environ, JAX_PLATFORMS="cpu"),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            for r in range(2)
        ]
        try:
            outs = [p.communicate(timeout=240)[0] for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            store.shutdown()
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out
            assert "PSUM-OK" in out
