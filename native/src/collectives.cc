#include "collectives.h"

#include <poll.h>
#include <string.h>

#include <algorithm>
#include <cstdlib>
#include <chrono>
#include <cmath>
#include <exception>
#include <limits>
#include <thread>
#include <vector>

#include "log.h"
#include "store.h"

namespace tft {

size_t dtype_size(Dtype d) {
  switch (d) {
    case Dtype::kF32:
    case Dtype::kI32:
      return 4;
    case Dtype::kF64:
    case Dtype::kI64:
      return 8;
    case Dtype::kBF16:
      return 2;
  }
  throw SocketError("bad dtype");
}

namespace {

// Hello magic, versioned: the low byte is the ring wire-protocol revision.
// History: the original "tftc" magic (0x74667463) spanned BOTH the
// pre-op-header wire and the build that added check_op_header, so the
// magic alone could not distinguish them; a ring mixing those desyncs
// mid-op (the old side consumes the 24-byte op header as payload). This
// versioned magic makes any mix of revisions — including byte-compatible
// "tftc" builds that already spoke op headers — fail AT CONNECT with a
// clear error; that over-rejection is the price of screening out the
// truly incompatible older builds sharing the old magic. Bump the low
// byte on any future wire change.
// rev 3: hello grew from {magic, rank} to {magic, rank, stripe, nstripes}
// for the striped multi-connection ring.
constexpr uint32_t kHelloMagic = 0x74667403; // "tft" + proto rev 3
// "tftp": per-op header magic (part of the wire protocol).
constexpr uint32_t kOpMagic = 0x74667470;

// Floor on bytes a stripe must carry before an extra connection/thread is
// worth waking: below this, per-op thread dispatch costs more than the
// wire. The effective stripe count derived from it depends only on
// (payload, configured stripes) — identical on every member, preserving
// the schedule agreement.
constexpr size_t kMinStripeBytes = 64 << 10;

int64_t effective_stripes(size_t payload_bytes, int64_t configured) {
  int64_t by_size = static_cast<int64_t>(payload_bytes / kMinStripeBytes);
  return std::max<int64_t>(1, std::min(configured, std::max<int64_t>(by_size, 1)));
}

template <typename T>
void reduce_typed(T* dst, const T* src, size_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::kSum:
      for (size_t i = 0; i < n; i++) dst[i] += src[i];
      return;
    case ReduceOp::kProduct:
      for (size_t i = 0; i < n; i++) dst[i] *= src[i];
      return;
    case ReduceOp::kMin:
      for (size_t i = 0; i < n; i++) dst[i] = std::min(dst[i], src[i]);
      return;
    case ReduceOp::kMax:
      for (size_t i = 0; i < n; i++) dst[i] = std::max(dst[i], src[i]);
      return;
  }
  throw SocketError("bad reduce op");
}

inline float bf16_to_f32(uint16_t h) {
  uint32_t bits = static_cast<uint32_t>(h) << 16;
  float f;
  memcpy(&f, &bits, sizeof(f));
  return f;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  memcpy(&bits, &f, sizeof(bits));
  // Round to nearest even (NaN payloads preserved by the +0x7FFF carry-free
  // path since NaN mantissas survive truncation of the low half).
  uint32_t lsb = (bits >> 16) & 1;
  bits += 0x7FFF + lsb;
  return static_cast<uint16_t>(bits >> 16);
}

void reduce_bf16(uint16_t* dst, const uint16_t* src, size_t n, ReduceOp op) {
  for (size_t i = 0; i < n; i++) {
    float a = bf16_to_f32(dst[i]);
    float b = bf16_to_f32(src[i]);
    float r;
    switch (op) {
      case ReduceOp::kSum: r = a + b; break;
      case ReduceOp::kProduct: r = a * b; break;
      case ReduceOp::kMin: r = std::min(a, b); break;
      case ReduceOp::kMax: r = std::max(a, b); break;
      default: throw SocketError("bad reduce op");
    }
    dst[i] = f32_to_bf16(r);
  }
}

void reduce_into(void* dst, const void* src, size_t n, Dtype dtype, ReduceOp op) {
  switch (dtype) {
    case Dtype::kF32:
      reduce_typed(static_cast<float*>(dst), static_cast<const float*>(src), n, op);
      return;
    case Dtype::kF64:
      reduce_typed(static_cast<double*>(dst), static_cast<const double*>(src), n,
                   op);
      return;
    case Dtype::kI32:
      reduce_typed(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src), n,
                   op);
      return;
    case Dtype::kI64:
      reduce_typed(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src), n,
                   op);
      return;
    case Dtype::kBF16:
      reduce_bf16(static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
                  n, op);
      return;
  }
  throw SocketError("bad dtype");
}

// Element range of ring chunk `c` when `count` elements are split into `ws`
// near-equal chunks (first `count % ws` chunks get one extra element).
std::pair<size_t, size_t> chunk_range(size_t count, int64_t ws, int64_t c) {
  size_t q = count / ws;
  size_t r = count % ws;
  size_t start = c * q + std::min<size_t>(c, r);
  size_t len = q + (static_cast<size_t>(c) < r ? 1 : 0);
  return {start, len};
}

}  // namespace

std::pair<size_t, size_t> HostCollectives::stripe_range(size_t count,
                                                        int64_t n, int64_t s) {
  return chunk_range(count, n, s);
}

HostCollectives::~HostCollectives() {
  abort();
  {
    std::lock_guard<std::mutex> lock(pool_mu_);
    pool_stop_ = true;
  }
  pool_cv_.notify_all();
  for (auto& w : pool_) w.join();
}

void HostCollectives::abort() {
  std::lock_guard<std::mutex> lock(cfg_mu_);
  aborted_ = true;
  abort_epoch_++;
  if (listener_) listener_->close();
  for (auto& s : next_) s.shutdown_rdwr();
  for (auto& s : prev_) s.shutdown_rdwr();
}

void HostCollectives::shutdown_sockets() {
  std::lock_guard<std::mutex> lock(cfg_mu_);
  for (auto& s : next_) s.shutdown_rdwr();
  for (auto& s : prev_) s.shutdown_rdwr();
}

namespace {

// Remaining budget before `deadline`; throws once it is exhausted (a
// non-positive timeout must never leak into a blocking call, where some
// callees read <0 as "wait forever").
int64_t remain_or_throw(int64_t deadline) {
  int64_t r = deadline - now_ms();
  if (r <= 0) throw TimeoutError("configure timed out");
  return r;
}

} // namespace

void HostCollectives::configure(const std::string& store_addr, int64_t rank,
                                int64_t world_size, int64_t timeout_ms,
                                int64_t stripes) {
  if (rank < 0 || world_size <= 0 || rank >= world_size)
    throw SocketError("bad rank/world_size");
  if (stripes < 1 || stripes > kMaxStripes)
    throw SocketError("bad stripe count (want 1.." +
                      std::to_string(kMaxStripes) + ")");
  abort(); // unblock any op stuck on the old ring
  std::lock_guard<std::mutex> op_lock(op_mu_); // wait for it to drain

  // Phase 1 (under cfg_mu_, non-blocking): retire the old ring, stand up the
  // new listener so a concurrent abort() can close it and wake phase 2.
  int64_t epoch;
  {
    std::lock_guard<std::mutex> lock(cfg_mu_);
    next_.clear();
    prev_.clear();
    listener_.reset();
    rank_ = rank;
    world_size_ = world_size;
    stripes_ = stripes;
    const char* cap = std::getenv("TORCHFT_HC_WIRE_CAP_MBPS");
    wire_cap_bps_ =
        cap ? static_cast<int64_t>(std::atof(cap) * (1 << 20)) : 0;
    scratch_.assign(stripes, StripeScratch{});  // fresh pace state per ring
    aborted_ = true;
    epoch = abort_epoch_;
    if (world_size == 1) {
      aborted_ = false;
      return;
    }
    listener_ = std::make_unique<Listener>("[::]:0");
  }

  // Phase 2 (no locks held, every step deadline-bounded): rendezvous through
  // the store and wire the ring. Both neighbors dial concurrently; connect()
  // lands in the peer's listen backlog, so no accept ordering is needed.
  int64_t deadline = now_ms() + timeout_ms;
  auto [kv_addr, prefix] = split_store_addr(store_addr);
  StoreClient store(kv_addr, remain_or_throw(deadline));

  std::string my_addr =
      local_hostname() + ":" + std::to_string(listener_->port());
  store.set(prefix + "/hc_addr_" + std::to_string(rank), my_addr,
            remain_or_throw(deadline));

  int64_t next_rank = (rank + 1) % world_size;
  std::string next_addr =
      store.get(prefix + "/hc_addr_" + std::to_string(next_rank),
                remain_or_throw(deadline));

  // Dial the next rank once per stripe; the hello names the stripe slot so
  // the peer can place accepted connections regardless of arrival order,
  // and carries the stripe COUNT so a config mismatch that slipped past the
  // store-level negotiation still fails at connect, not mid-op.
  std::vector<Socket> next_socks(stripes);
  for (int64_t s = 0; s < stripes; s++) {
    next_socks[s] = connect_with_retry(next_addr, remain_or_throw(deadline));
    uint32_t hello[4] = {kHelloMagic, static_cast<uint32_t>(rank),
                         static_cast<uint32_t>(s),
                         static_cast<uint32_t>(stripes)};
    next_socks[s].send_all(hello, sizeof(hello), deadline);
  }

  std::vector<Socket> prev_socks(stripes);
  int64_t prev_rank = (rank - 1 + world_size) % world_size;
  for (int64_t i = 0; i < stripes; i++) {
    Socket sock = listener_->accept(deadline);
    if (!sock.valid()) throw SocketError("listener closed during configure");
    uint32_t peer_hello[4];
    sock.recv_all(peer_hello, sizeof(peer_hello), deadline);
    if (peer_hello[0] != kHelloMagic)
      throw SocketError(
          "ring handshake: wire-protocol mismatch (peer binary speaks a "
          "different ring protocol revision)");
    if (peer_hello[1] != static_cast<uint32_t>(prev_rank))
      throw SocketError("ring handshake: unexpected peer rank");
    if (peer_hello[3] != static_cast<uint32_t>(stripes))
      throw SocketError(
          "ring handshake: stripe-count mismatch (this rank " +
          std::to_string(stripes) + ", prev rank " +
          std::to_string(peer_hello[3]) +
          " — all members must configure the same stripes)");
    uint32_t slot = peer_hello[2];
    if (slot >= static_cast<uint32_t>(stripes) || prev_socks[slot].valid())
      throw SocketError("ring handshake: bad or duplicate stripe index");
    prev_socks[slot] = std::move(sock);
  }

  // Phase 3: publish the new ring unless an abort raced in.
  std::lock_guard<std::mutex> lock(cfg_mu_);
  if (abort_epoch_ != epoch) throw SocketError("aborted during configure");
  next_ = std::move(next_socks);
  prev_ = std::move(prev_socks);
  aborted_ = false;
}

void HostCollectives::duplex(Socket& next, Socket& prev, const char* send_buf,
                             size_t send_len, char* recv_buf, size_t recv_len,
                             int64_t deadline_ms, PaceState* pace) {
  const double bps = static_cast<double>(wire_cap_bps_);
  // Burst = 20 ms of credit (floor 64 KB): small enough that the realized
  // rate tracks the cap within any measurement window, large enough that a
  // chunk-sized write needs one send call.
  const double burst = std::max(65536.0, bps / 50.0);
  size_t sent = 0, got = 0;
  while (sent < send_len || got < recv_len) {
    // Refill the token bucket and decide whether this pass may send; when
    // token-dry, the send fd leaves the poll set and the poll timeout
    // shrinks to the refill time, so receives still drain at full speed.
    int64_t pace_wait_ms = -1;
    bool may_send = sent < send_len;
    if (may_send && pace && bps > 0) {
      auto now = std::chrono::steady_clock::now();
      if (!pace->init) {
        pace->init = true;
        pace->tokens = burst;
      } else {
        pace->tokens +=
            std::chrono::duration<double>(now - pace->last).count() * bps;
        if (pace->tokens > burst) pace->tokens = burst;
      }
      pace->last = now;
      if (pace->tokens < 1.0) {
        may_send = false;
        pace_wait_ms =
            static_cast<int64_t>((1.0 - pace->tokens) / bps * 1000.0) + 1;
      }
    }
    struct pollfd pfds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    if (may_send) {
      send_idx = n;
      pfds[n].fd = next.fd();
      pfds[n].events = POLLOUT;
      n++;
    }
    if (got < recv_len) {
      recv_idx = n;
      pfds[n].fd = prev.fd();
      pfds[n].events = POLLIN;
      n++;
    }
    int timeout = poll_timeout_or_throw(deadline_ms, "collective timed out");
    if (pace_wait_ms >= 0 && (timeout < 0 || pace_wait_ms < timeout))
      timeout = static_cast<int>(pace_wait_ms);
    int prc = ::poll(pfds, n, timeout);
    if (prc == 0) {
      if (pace_wait_ms >= 0) continue;  // token refill elapsed, not a stall
      throw TimeoutError("collective timed out");
    }
    if (prc < 0) {
      if (errno == EINTR) continue;
      throw SocketError(std::string("poll: ") + strerror(errno));
    }
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR | POLLHUP))) {
      size_t allow = send_len - sent;
      if (pace && bps > 0 && static_cast<double>(allow) > pace->tokens)
        allow = static_cast<size_t>(pace->tokens);
      ssize_t w = ::send(next.fd(), send_buf + sent, allow,
                         MSG_NOSIGNAL | MSG_DONTWAIT);
      if (w > 0) {
        sent += static_cast<size_t>(w);
        if (pace && bps > 0) pace->tokens -= static_cast<double>(w);
      } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                 errno != EINTR) {
        throw SocketError(std::string("ring send: ") + strerror(errno));
      }
    }
    if (recv_idx >= 0 &&
        (pfds[recv_idx].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t r = ::recv(prev.fd(), recv_buf + got, recv_len - got, MSG_DONTWAIT);
      if (r > 0) {
        got += static_cast<size_t>(r);
      } else if (r == 0) {
        throw SocketError("ring peer closed connection");
      } else if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
        throw SocketError(std::string("ring recv: ") + strerror(errno));
      }
    }
  }
}

void HostCollectives::check_op_header(uint32_t kind, uint64_t count,
                                      uint32_t dtype, uint32_t op,
                                      int64_t deadline_ms) {
  // One tiny duplex exchange describing the op each neighbor is about to
  // run. A mismatched op (different tree sizes, dtypes, or op kinds on
  // different members) otherwise DEADLOCKS silently: the small member
  // finishes, stops reading, and the large member blocks forever once
  // kernel buffers fill. ~20 bytes per collective — noise next to any
  // payload — converts that into an immediate, descriptive error. Runs on
  // stripe 0 (the stripe COUNT is already pinned at connect time by the
  // hello, so one stripe's agreement covers the schedule).
  struct Header {
    uint32_t magic, kind;
    uint64_t count;
    uint32_t dtype, op;
  } mine{kOpMagic, kind, count, dtype, op}, theirs{};
  duplex(next_[0], prev_[0], reinterpret_cast<const char*>(&mine),
         sizeof(mine), reinterpret_cast<char*>(&theirs), sizeof(theirs),
         deadline_ms);
  if (theirs.magic != kOpMagic)
    throw SocketError("ring op header corrupt (protocol desync)");
  if (theirs.kind != mine.kind || theirs.count != mine.count ||
      theirs.dtype != mine.dtype || theirs.op != mine.op)
    throw SocketError(
        "ring op mismatch: this rank kind=" + std::to_string(kind) +
        " count=" + std::to_string(count) + " dtype=" +
        std::to_string(dtype) + " op=" + std::to_string(op) +
        ", prev rank kind=" + std::to_string(theirs.kind) + " count=" +
        std::to_string(theirs.count) + " dtype=" +
        std::to_string(theirs.dtype) + " op=" + std::to_string(theirs.op) +
        " (members must reduce identical trees)");
}

void HostCollectives::run_striped(const std::function<void(int64_t)>& fn) {
  int64_t n = static_cast<int64_t>(last_stripe_ns_.size());
  std::vector<std::exception_ptr> errs(n);

  auto body = [&](int64_t s) {
    auto t0 = std::chrono::steady_clock::now();
    try {
      fn(s);
    } catch (...) {
      errs[s] = std::current_exception();
      // Wake every sibling stripe immediately: they share the op's fate,
      // and letting them block until their timeout would stall the abort
      // path the whole design exists to keep fast.
      shutdown_sockets();
    }
    last_stripe_ns_[s] =
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - t0)
            .count();
  };

  if (n <= 1) {
    body(0);
  } else {
    // Publish the job to the persistent workers (a thread per stripe per
    // native op would cost more than the stripe's transport at pipelined
    // chunk sizes), run stripe 0 here, then wait for the drain. The drain
    // wait is unconditional-bounded: failing stripes shut down every
    // socket, so no sibling can block past its IO wakeup.
    std::function<void(int64_t)> body_fn = body;
    ensure_pool(n - 1);
    {
      std::lock_guard<std::mutex> lock(pool_mu_);
      pool_body_ = &body_fn;
      pool_n_ = n;
      pool_pending_ = n - 1;
      pool_gen_++;
    }
    pool_cv_.notify_all();
    body(0);
    {
      std::unique_lock<std::mutex> lock(pool_mu_);
      pool_done_cv_.wait(lock, [&] { return pool_pending_ == 0; });
      pool_body_ = nullptr;
    }
  }
  for (auto& e : errs)
    if (e) std::rethrow_exception(e);  // ONE error: lowest stripe wins
}

void HostCollectives::ensure_pool(int64_t workers) {
  std::lock_guard<std::mutex> lock(pool_mu_);
  while (static_cast<int64_t>(pool_.size()) < workers) {
    // Seed each worker with the CURRENT generation (stable under pool_mu_):
    // a fresh thread must not mistake an already-running or past job for
    // its first wakeup.
    pool_.emplace_back(&HostCollectives::pool_main, this,
                       static_cast<int64_t>(pool_.size()), pool_gen_);
  }
}

void HostCollectives::pool_main(int64_t idx, int64_t start_gen) {
  int64_t seen_gen = start_gen;
  for (;;) {
    const std::function<void(int64_t)>* body;
    int64_t n;
    {
      std::unique_lock<std::mutex> lock(pool_mu_);
      pool_cv_.wait(lock,
                    [&] { return pool_stop_ || pool_gen_ != seen_gen; });
      if (pool_stop_) return;
      seen_gen = pool_gen_;
      body = pool_body_;
      n = pool_n_;
    }
    // Worker idx owns stripe idx+1; jobs narrower than the pool (fewer
    // effective stripes) don't count the spare workers in pool_pending_.
    if (idx + 1 < n) {
      (*body)(idx + 1);
      std::lock_guard<std::mutex> lock(pool_mu_);
      if (--pool_pending_ == 0) pool_done_cv_.notify_all();
    }
  }
}

void HostCollectives::rs_phase_stripe(int64_t s, char* bytes, size_t count,
                                      size_t esize, Dtype dtype, ReduceOp op,
                                      int64_t deadline) {
  size_t max_chunk = count / world_size_ + 1;
  std::vector<char>& recv_tmp = scratch_[s].recv;
  if (recv_tmp.size() < max_chunk * esize) recv_tmp.resize(max_chunk * esize);

  // Reduce-scatter: after step t, chunk (rank - t) has accumulated the
  // values of ranks rank-t..rank. After ws-1 steps chunk (rank+1) holds the
  // full reduction at this rank — computed in the identical rank order
  // everywhere.
  for (int64_t t = 0; t < world_size_ - 1; t++) {
    int64_t send_c = ((rank_ - t) % world_size_ + world_size_) % world_size_;
    int64_t recv_c =
        ((rank_ - t - 1) % world_size_ + world_size_) % world_size_;
    auto [s_start, s_len] = chunk_range(count, world_size_, send_c);
    auto [r_start, r_len] = chunk_range(count, world_size_, recv_c);
    duplex(next_[s], prev_[s], bytes + s_start * esize, s_len * esize,
           recv_tmp.data(), r_len * esize, deadline, &scratch_[s].pace);
    reduce_into(bytes + r_start * esize, recv_tmp.data(), r_len, dtype, op);
  }
}

void HostCollectives::ag_phase_stripe(int64_t s, char* bytes, size_t count,
                                      size_t esize, int64_t deadline) {
  // Allgather: circulate the owned chunks, starting from (rank + 1) —
  // the chunk the reduce-scatter phase leaves fully reduced here.
  for (int64_t t = 0; t < world_size_ - 1; t++) {
    int64_t send_c =
        ((rank_ + 1 - t) % world_size_ + world_size_) % world_size_;
    int64_t recv_c = ((rank_ - t) % world_size_ + world_size_) % world_size_;
    auto [s_start, s_len] = chunk_range(count, world_size_, send_c);
    auto [r_start, r_len] = chunk_range(count, world_size_, recv_c);
    duplex(next_[s], prev_[s], bytes + s_start * esize, s_len * esize,
           bytes + r_start * esize, r_len * esize, deadline,
           &scratch_[s].pace);
  }
}

void HostCollectives::allreduce_stripe(int64_t s, char* bytes, size_t count,
                                       size_t esize, Dtype dtype, ReduceOp op,
                                       int64_t deadline) {
  rs_phase_stripe(s, bytes, count, esize, dtype, op, deadline);
  ag_phase_stripe(s, bytes, count, esize, deadline);
}

void HostCollectives::allreduce(void* data, size_t count, Dtype dtype,
                                ReduceOp op, int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // header exchanged even for count==0: an empty-vs-nonempty mismatch
    // must error, not hang the nonempty member
    check_op_header(0, count, static_cast<uint32_t>(dtype),
                    static_cast<uint32_t>(op), deadline);
    if (count == 0) return;
    char* bytes = static_cast<char*>(data);
    size_t esize = dtype_size(dtype);
    int64_t eff = effective_stripes(count * esize, stripes_);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      allreduce_stripe(s, bytes + start * esize, len, esize, dtype, op,
                       deadline);
    });
  });
}

namespace {

// One chunk on the q8 wire: 4-byte f32 scale, then `len` int8 codes.
void q8_encode(const float* src, size_t len, char* wire) {
  float absmax = 0.f;
  bool finite = true;
  for (size_t i = 0; i < len; i++) {
    float a = std::fabs(src[i]);
    if (!std::isfinite(a)) finite = false;
    absmax = std::max(absmax, a);
  }
  if (!finite) {
    // Non-finite gradients must poison the result the way the f32/bf16
    // wires do: std::max/min drop NaN (they return the other operand),
    // so a diverged model would otherwise be encoded as clamped finite
    // codes and the blow-up silently hidden. A NaN scale makes every
    // decoded element NaN on all ranks.
    float nan = std::numeric_limits<float>::quiet_NaN();
    memcpy(wire, &nan, sizeof(float));
    memset(wire + sizeof(float), 0, len);
    return;
  }
  float scale = absmax > 0.f ? absmax / 127.f : 1.f;
  memcpy(wire, &scale, sizeof(float));
  int8_t* q = reinterpret_cast<int8_t*>(wire + sizeof(float));
  for (size_t i = 0; i < len; i++) {
    float v = std::nearbyint(src[i] / scale);
    q[i] = static_cast<int8_t>(std::max(-127.f, std::min(127.f, v)));
  }
}

// dst[i] (+)= scale * q[i]
void q8_decode(const char* wire, size_t len, float* dst, bool accumulate) {
  float scale;
  memcpy(&scale, wire, sizeof(float));
  const int8_t* q = reinterpret_cast<const int8_t*>(wire + sizeof(float));
  if (accumulate) {
    for (size_t i = 0; i < len; i++) dst[i] += scale * static_cast<float>(q[i]);
  } else {
    for (size_t i = 0; i < len; i++) dst[i] = scale * static_cast<float>(q[i]);
  }
}

}  // namespace

void HostCollectives::rs_q8_phase_stripe(int64_t s, float* data, size_t count,
                                         int64_t deadline) {
  size_t max_chunk = count / world_size_ + 1;
  size_t max_wire = sizeof(float) + max_chunk;
  std::vector<char>& send_wire = scratch_[s].send;
  std::vector<char>& recv_wire = scratch_[s].recv;
  if (send_wire.size() < max_wire) send_wire.resize(max_wire);
  if (recv_wire.size() < max_wire) recv_wire.resize(max_wire);

  // Reduce-scatter: each hop quantizes its CURRENT partial sum of the
  // outgoing chunk and dequant-accumulates the incoming one in f32.
  for (int64_t t = 0; t < world_size_ - 1; t++) {
    int64_t send_c = ((rank_ - t) % world_size_ + world_size_) % world_size_;
    int64_t recv_c =
        ((rank_ - t - 1) % world_size_ + world_size_) % world_size_;
    auto [s_start, s_len] = chunk_range(count, world_size_, send_c);
    auto [r_start, r_len] = chunk_range(count, world_size_, recv_c);
    q8_encode(data + s_start, s_len, send_wire.data());
    duplex(next_[s], prev_[s], send_wire.data(), sizeof(float) + s_len,
           recv_wire.data(), sizeof(float) + r_len, deadline,
           &scratch_[s].pace);
    q8_decode(recv_wire.data(), r_len, data + r_start, /*accumulate=*/true);
  }
}

void HostCollectives::allreduce_q8_stripe(int64_t s, float* data, size_t count,
                                          int64_t deadline) {
  rs_q8_phase_stripe(s, data, count, deadline);
  // Allgather: the OWNER quantizes its fully-reduced chunk exactly once
  // (first send); every later hop forwards the received wire bytes
  // verbatim, so all members decode identical codes — the reduced
  // values stay bit-identical across ranks (the determinism oracle).
  std::vector<std::vector<char>>& stored = scratch_[s].stored;
  stored.resize(world_size_);
  {
    int64_t own_c = (rank_ + 1) % world_size_;
    auto [o_start, o_len] = chunk_range(count, world_size_, own_c);
    stored[own_c].resize(sizeof(float) + o_len);
    q8_encode(data + o_start, o_len, stored[own_c].data());
    // decode own chunk too: every member must hold the DECODED codes,
    // not its higher-precision f32 partial (bit-identity across ranks)
    q8_decode(stored[own_c].data(), o_len, data + o_start, false);
  }
  for (int64_t t = 0; t < world_size_ - 1; t++) {
    int64_t send_c =
        ((rank_ + 1 - t) % world_size_ + world_size_) % world_size_;
    int64_t recv_c = ((rank_ - t) % world_size_ + world_size_) % world_size_;
    auto [r_start, r_len] = chunk_range(count, world_size_, recv_c);
    stored[recv_c].resize(sizeof(float) + r_len);
    duplex(next_[s], prev_[s], stored[send_c].data(), stored[send_c].size(),
           stored[recv_c].data(), stored[recv_c].size(), deadline,
           &scratch_[s].pace);
    q8_decode(stored[recv_c].data(), r_len, data + r_start, false);
  }
}

void HostCollectives::allreduce_q8(float* data, size_t count,
                                   int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // distinct kind: a q8 op meeting a plain allreduce must error, not
    // desync (their wire framings differ even at equal counts)
    check_op_header(4, count, /*dtype=*/100, /*op=*/0, deadline);
    if (count == 0) return;
    // ~1 wire byte per f32 element (int8 codes + per-chunk scales)
    int64_t eff = effective_stripes(count, stripes_);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      allreduce_q8_stripe(s, data + start, len, deadline);
    });
  });
}

void HostCollectives::allgather(const void* in, void* out, size_t nbytes,
                                int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  char* slots = static_cast<char*>(out);
  memcpy(slots + rank_ * nbytes, in, nbytes);
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    check_op_header(1, nbytes, 0, 0, deadline);
    if (nbytes == 0) return;
    int64_t eff = effective_stripes(nbytes, stripes_);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t st) {
      auto [off, len] = stripe_range(nbytes, eff, st);
      if (len == 0) return;
      for (int64_t t = 0; t < world_size_ - 1; t++) {
        int64_t send_c = ((rank_ - t) % world_size_ + world_size_) % world_size_;
        int64_t recv_c =
            ((rank_ - t - 1) % world_size_ + world_size_) % world_size_;
        duplex(next_[st], prev_[st], slots + send_c * nbytes + off, len,
               slots + recv_c * nbytes + off, len, deadline,
               &scratch_[st].pace);
      }
    });
  });
}

std::vector<std::pair<size_t, size_t>> HostCollectives::shard_ranges(
    size_t count, size_t esize, int64_t r, int64_t layout_stripes) const {
  if (r < 0 || r >= world_size_) throw SocketError("bad shard rank");
  int64_t eff = layout_stripes > 0
                    ? std::min(layout_stripes, stripes_)
                    : effective_stripes(count * esize, stripes_);
  int64_t own_c = (r + 1) % world_size_;
  std::vector<std::pair<size_t, size_t>> out;
  for (int64_t s = 0; s < eff; s++) {
    auto [st, sl] = stripe_range(count, eff, s);
    if (sl == 0) continue;
    auto [cs, cl] = chunk_range(sl, world_size_, own_c);
    if (cl) out.emplace_back(st + cs, cl);
  }
  return out;
}

void HostCollectives::copy_shard(char* data, char* shard, size_t count,
                                 size_t esize, int64_t eff,
                                 bool to_shard) const {
  // One source of truth for the layout: walk the same ranges Python gets
  // from shard_ranges, so compaction can never disagree with them.
  size_t off = 0;
  for (auto [start, len] : shard_ranges(count, esize, rank_, eff)) {
    if (to_shard)
      memcpy(shard + off * esize, data + start * esize, len * esize);
    else
      memcpy(data + start * esize, shard + off * esize, len * esize);
    off += len;
  }
}

void HostCollectives::reduce_scatter(void* data, size_t count, Dtype dtype,
                                     ReduceOp op, void* shard_out,
                                     int64_t layout_stripes,
                                     int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  size_t esize = dtype_size(dtype);
  if (world_size_ == 1) {
    memcpy(shard_out, data, count * esize);
    return;
  }
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    int64_t eff = layout_stripes > 0
                      ? std::min(layout_stripes, stripes_)
                      : effective_stripes(count * esize, stripes_);
    // The layout rides the header's op slot: a reduce_scatter meeting a
    // differently-partitioned one must error, not scatter to the wrong
    // shard boundaries (ReduceOp fits in the low byte).
    check_op_header(5, count, static_cast<uint32_t>(dtype),
                    static_cast<uint32_t>(op) |
                        (static_cast<uint32_t>(eff) << 8),
                    deadline);
    if (count == 0) return;
    char* bytes = static_cast<char*>(data);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      rs_phase_stripe(s, bytes + start * esize, len, esize, dtype, op,
                      deadline);
    });
    copy_shard(bytes, static_cast<char*>(shard_out), count, esize, eff,
               /*to_shard=*/true);
  });
}

void HostCollectives::reduce_scatter_q8(float* data, size_t count,
                                        float* shard_out, bool grid_shard,
                                        int64_t layout_stripes,
                                        int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) {
    memcpy(shard_out, data, count * sizeof(float));
    return;
  }
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // ~1 wire byte per f32 element, like the fused q8 op
    int64_t eff = layout_stripes > 0
                      ? std::min(layout_stripes, stripes_)
                      : effective_stripes(count, stripes_);
    check_op_header(7, count, /*dtype=*/100,
                    static_cast<uint32_t>(eff) << 8, deadline);
    if (count == 0) return;
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      rs_q8_phase_stripe(s, data + start, len, deadline);
      if (grid_shard) {
        // Reproduce the fused op's phase-2 owner quantize+decode so the
        // shard sits on the same int8 grid the fused allreduce returns.
        int64_t own_c = (rank_ + 1) % world_size_;
        auto [cs, cl] = chunk_range(len, world_size_, own_c);
        if (cl) {
          std::vector<char>& wire = scratch_[s].send;
          if (wire.size() < sizeof(float) + cl)
            wire.resize(sizeof(float) + cl);
          q8_encode(data + start + cs, cl, wire.data());
          q8_decode(wire.data(), cl, data + start + cs, /*accumulate=*/false);
        }
      }
    });
    copy_shard(reinterpret_cast<char*>(data),
               reinterpret_cast<char*>(shard_out), count, sizeof(float), eff,
               /*to_shard=*/true);
  });
}

void HostCollectives::allgather_into(const void* shard, void* data,
                                     size_t count, Dtype dtype,
                                     int64_t layout_stripes,
                                     int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  size_t esize = dtype_size(dtype);
  if (world_size_ == 1) {
    memcpy(data, shard, count * esize);
    return;
  }
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    int64_t eff = layout_stripes > 0
                      ? std::min(layout_stripes, stripes_)
                      : effective_stripes(count * esize, stripes_);
    check_op_header(6, count, static_cast<uint32_t>(dtype),
                    static_cast<uint32_t>(eff) << 8, deadline);
    if (count == 0) return;
    char* bytes = static_cast<char*>(data);
    copy_shard(bytes, const_cast<char*>(static_cast<const char*>(shard)),
               count, esize, eff, /*to_shard=*/false);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      ag_phase_stripe(s, bytes + start * esize, len, esize, deadline);
    });
  });
}

void HostCollectives::broadcast(void* data, size_t nbytes, int64_t root,
                                int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  if (root < 0 || root >= world_size_) throw SocketError("bad broadcast root");
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    check_op_header(2, nbytes, static_cast<uint32_t>(root), 0, deadline);
    if (nbytes == 0) return;
    char* bytes = static_cast<char*>(data);
    int64_t eff = effective_stripes(nbytes, stripes_);
    last_stripe_ns_.assign(eff, 0);
    // Forward around the ring, root first; the last hop before root does not
    // send. recv-then-send per hop (latency is fine at control-plane sizes;
    // bulk weight transfer goes through the checkpoint transport instead).
    run_striped([&](int64_t st) {
      auto [off, len] = stripe_range(nbytes, eff, st);
      if (len == 0) return;
      if (rank_ == root) {
        duplex(next_[st], prev_[st], bytes + off, len, nullptr, 0, deadline,
               &scratch_[st].pace);
      } else {
        duplex(next_[st], prev_[st], nullptr, 0, bytes + off, len, deadline);
        if ((rank_ + 1) % world_size_ != root)
          duplex(next_[st], prev_[st], bytes + off, len, nullptr, 0,
                 deadline, &scratch_[st].pace);
      }
    });
  });
}

void HostCollectives::barrier(int64_t timeout_ms) {
  std::lock_guard<std::mutex> lock(op_mu_);
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    check_op_header(3, 0, 0, 0, deadline);
    // Two full ring passes on stripe 0: after the first, rank 0 knows
    // everyone arrived; the second releases everyone.
    char token = 1;
    for (int round = 0; round < 2; round++) {
      if (rank_ == 0) {
        duplex(next_[0], prev_[0], &token, 1, nullptr, 0, deadline);
        duplex(next_[0], prev_[0], nullptr, 0, &token, 1, deadline);
      } else {
        duplex(next_[0], prev_[0], nullptr, 0, &token, 1, deadline);
        duplex(next_[0], prev_[0], &token, 1, nullptr, 0, deadline);
      }
    }
  });
}

} // namespace tft
