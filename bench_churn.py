"""Churn benchmark: throughput under replica-group kills (the north star).

Measures the driver-set target from BASELINE.md: steps/sec with one
replica-group kill every ``--kill-every`` steps must stay >= 90% of
healthy-state steps/sec. The reference makes this claim qualitatively
("avoid stop the world training on errors", reference README.md:46-47) and
exercises the recovery flow in tests (reference torchft/manager.py:470-526);
this benchmark puts a number on it.

Topology: N replica groups as local processes (CPU JAX), one real
HostCollectives TCP ring between them, one lighthouse. Two phases with the
same model/config:

  healthy: all groups train ``--steps`` steps, no faults.
  churn:   a supervisor SIGKILLs one (rotating, never group 0) group each
           time group 0 commits ``--kill-every`` more steps, then restarts
           it; the restarted process heals from a live peer over HTTP.

Reported (CHURN_BENCH.json + one JSON line on stdout):
  steps_per_sec_healthy / steps_per_sec_churn  (group 0's committed steps)
  ratio  = churn / healthy       (north star: >= 0.90)
  heal_p50_s = median time from SIGKILL to the restarted group's first
               committed step (includes process restart + jit recompile —
               on real multi-host deployments each group has its own host,
               so single-host numbers are pessimistic: the restarting
               process competes for this machine's CPUs).

A separate ``--durable`` mode benches the durable checkpoint tier
(DURABLE_BENCH.json): per-checkpoint trainer stall of the async sharded
zero-copy snapshot vs the v1-shaped synchronous writer, per-member
durable bytes (~1/W), and the cold no-donor restore split into
manifest-read / shard-fetch / reshard / h2d / compile buckets.
``--durable --dryrun`` is the CI smoke: asserts one committed
async-snapshot record and one no-donor-restore record, writes no
artifact.

Usage::

    python bench_churn.py --groups 4 --steps 300 --kill-every 100
    python bench_churn.py --durable
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


# --------------------------------------------------------------------------
# worker: one replica group
# --------------------------------------------------------------------------


def worker() -> None:
    """Trains the flagship transformer (small config) with the full FT path,
    appending one JSONL record per attempted step (plus one "boot" record
    timestamping the restart->rejoin phases for the heal breakdown, and one
    "heal" record per live recovery carrying the streamed-fetch stats)."""
    t_enter = time.time()
    from torchft_tpu.platform import (
        apply_compilation_cache_env,
        apply_jax_platform_env,
        standby_gate,
        standby_should_warm,
    )

    apply_jax_platform_env()
    apply_compilation_cache_env()  # restarted workers reload jit executables

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from datetime import timedelta

    from torchft_tpu import (
        FTTrainState,
        HostCollectives,
        Manager,
        OptimizerWrapper,
    )
    from torchft_tpu.models import TransformerConfig, init_params, loss_fn

    group = int(os.environ["REPLICA_GROUP_ID"])
    num_steps = int(os.environ["NUM_STEPS"])
    log_path = os.environ["BENCH_LOG"]
    t_setup = time.time()  # library imports done

    # Backend acquisition timed on its own: on tunneled accelerator hosts
    # this is the phase that can eat tens of seconds (or hang), and the
    # old breakdown buried it inside one opaque "setup" bucket.
    jax.devices()
    t_backend = time.time()

    cfg = TransformerConfig(
        vocab_size=2048, d_model=128, n_heads=4, n_layers=2, d_ff=256,
        max_seq_len=64,
    )
    batch_size, seq_len = 4, 64
    rng = np.random.default_rng(group)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq_len), dtype=np.int32)
    )

    state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), optax.adamw(1e-3))
    grad_fn = jax.jit(jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b)))
    t_model = time.time()  # params + optimizer state live on device

    # Compile BEFORE joining the quorum, then hold at the start line until
    # every group is ready (parent touches the go file). Without this the
    # first group up forms a solo quorum and races at world-size-1 speed
    # while peers are still importing/compiling, polluting the measured
    # window. Restarted workers find the go file already present and rejoin
    # immediately through the normal heal path.
    _, grads0 = jax.block_until_ready(grad_fn(state.params, batch))
    # The collectives object exists BEFORE the gate (no network until
    # configure), so promotion pays neither its thread start nor — after
    # the AOT warm below — any packer/optimizer-update compile: promotion
    # is quorum join + weight fetch only.
    collectives = HostCollectives(timeout=timedelta(seconds=30))
    is_standby = bool(os.environ.get("TORCHFT_STANDBY_FILE"))
    if is_standby and standby_should_warm():
        # Truly-warm STANDBY discipline (TORCHFT_STANDBY_WARM): run the
        # optimizer update and the ring pack/unpack once AOT, so the jit
        # cache is hot for every executable the first post-promotion step
        # needs — not just the grad program. Cold restarts skip this on
        # purpose: for them every pre-gate second delays the rejoin, and
        # the apply/packer compiles are persistent-cache hits paid once
        # inside the (already short) first committed step.
        state.warm(grads0)
        collectives.prewarm(grads0)
    t_compiled = time.time()
    # Hot-spare standbys park HERE, fully warmed, until promoted; for
    # them activated_t is the promotion instant, for cold starts it
    # coincides with compile completion.
    standby_gate()
    t_activated = time.time()

    # Manager BEFORE the start line: heartbeats flow while the groups
    # gather at the go-gate, so the first quorum's join gate sees every
    # group as healthy and holds the door for all of them — otherwise the
    # first group to request forms an instant solo quorum (it is the only
    # HEARTBEATING replica at that moment) and membership flaps from
    # there.
    manager = Manager(
        collectives=collectives,
        load_state_dict=state.load_state_dict,
        state_dict=state.state_dict,
        min_replica_size=1,
        heartbeat_interval=timedelta(milliseconds=50),
        replica_id=f"bench_{group}",
    )
    optimizer = OptimizerWrapper(manager, state)
    transport = manager.checkpoint_transport()

    go_path = os.environ["BENCH_GO"]
    open(log_path + ".ready", "w").close()
    while not os.path.exists(go_path):
        time.sleep(0.05)

    with open(log_path, "a", buffering=1) as log:
        # Boot record first: the parent joins it with its kill/spawn
        # timestamps to break heal latency into respawn / import / setup /
        # backend_init / mesh / compile / rendezvous phases.
        log.write(
            json.dumps(
                {
                    "boot": {
                        "spawn_t": float(os.environ.get("BENCH_SPAWN_T", 0)),
                        "enter_t": t_enter,
                        "setup_t": t_setup,
                        "backend_t": t_backend,
                        "model_t": t_model,
                        "compiled_t": t_compiled,
                        "activated_t": t_activated,
                        "manager_t": time.time(),
                    }
                }
            )
            + "\n"
        )
        last_heal_stats = None
        while manager.current_step() < num_steps:
            t0 = time.perf_counter()
            optimizer.zero_grad()
            t1 = time.perf_counter()
            loss, grads = grad_fn(state.params, batch)
            jax.block_until_ready(grads)
            t2 = time.perf_counter()
            avg = manager.allreduce(grads).wait()
            t3 = time.perf_counter()
            committed = optimizer.step(avg)
            t4 = time.perf_counter()
            log.write(
                json.dumps(
                    {
                        "t": time.time(),
                        "step": manager.current_step(),
                        "committed": bool(committed),
                        "participants": manager.num_participants(),
                        "ms": {
                            "quorum_start": round((t1 - t0) * 1e3, 1),
                            "grad": round((t2 - t1) * 1e3, 1),
                            "allreduce": round((t3 - t2) * 1e3, 1),
                            "commit": round((t4 - t3) * 1e3, 1),
                        },
                    }
                )
                + "\n"
            )
            # One "heal" record per live recovery: the transport's fetch
            # stats (stream path, wire, fetch/h2d seconds) joined by the
            # parent into the heal breakdown.
            stats = getattr(transport, "last_fetch_stats", None)
            if stats is not None and stats is not last_heal_stats:
                last_heal_stats = stats
                log.write(
                    json.dumps({"heal": {"t": time.time(), **stats}}) + "\n"
                )
    manager.shutdown()
    collectives.shutdown()


# --------------------------------------------------------------------------
# zygote: import-warm respawn server
# --------------------------------------------------------------------------


def zygote() -> None:
    """Import-warm respawn server (``TORCHFT_ZYGOTE=0`` disables): pays
    the worker's Python import bill ONCE, then forks a ready-to-run
    worker per request. A cold restart's dominant cost on this bench is
    re-importing jax/optax/torchft under survivor contention (~10 s of
    the measured ~20 s heal at 4 groups on 2 CPUs — the breakdown's
    ``setup`` bucket); priority levers can't fix it where nice is
    unenforced (gVisor), but not re-doing the work can. The zygote stays
    SINGLE-THREADED and never initializes the jax backend (XLA clients
    spawn thread pools; forking a multithreaded process risks inherited
    lock state) — each forked child acquires its own backend, so the
    breakdown's backend_init / mesh / compile phases stay honest per
    restart and only the pure re-import cost disappears.

    Protocol (line-JSON): parent writes ``{"env": {...full child env},
    "nice": N}`` on stdin; zygote forks, answers ``{"pid": P}``, and
    reports reaped children as ``{"exit": P, "rc": RC}`` (kills surface
    as negative signal codes, matching subprocess semantics)."""
    import select

    from torchft_tpu.platform import apply_jax_platform_env

    apply_jax_platform_env()
    import jax  # noqa: F401
    import jax.numpy  # noqa: F401
    import numpy  # noqa: F401
    import optax  # noqa: F401

    import torchft_tpu  # noqa: F401
    import torchft_tpu.models  # noqa: F401

    assert threading.active_count() == 1, (
        "zygote must stay single-threaded to fork safely; an import "
        "started a thread"
    )
    print(json.dumps({"ready": True}), flush=True)
    children: Dict[int, bool] = {}
    while True:
        ready, _, _ = select.select([sys.stdin], [], [], 0.1)
        if ready:
            line = sys.stdin.readline()
            if not line:
                break  # parent is gone; any orphans are its to kill
            req = json.loads(line)
            pid = os.fork()
            if pid == 0:
                # -- child: become the worker --
                try:
                    devnull = os.open(os.devnull, os.O_RDONLY)
                    os.dup2(devnull, 0)  # stdin is the PROTOCOL pipe
                    os.dup2(2, 1)  # keep the protocol stdout clean too
                    os.environ.clear()
                    os.environ.update(req["env"])
                    if req.get("nice"):
                        try:
                            os.nice(int(req["nice"]))
                        except OSError:
                            pass
                    worker()
                    os._exit(0)
                except SystemExit as e:
                    os._exit(int(e.code or 0))
                except BaseException:
                    import traceback

                    traceback.print_exc()
                    os._exit(1)
            children[pid] = True
            print(json.dumps({"pid": pid}), flush=True)
        for pid in list(children):
            wpid, status = os.waitpid(pid, os.WNOHANG)
            if wpid:
                del children[pid]
                print(
                    json.dumps(
                        {"exit": wpid,
                         "rc": os.waitstatus_to_exitcode(status)}
                    ),
                    flush=True,
                )


class _ZygoteProc:
    """Popen-shaped handle for a zygote-forked worker (the supervisor
    signals it directly by pid; exit codes arrive via the zygote's
    reaper events)."""

    def __init__(self, zyg: "_Zygote", pid: int) -> None:
        self._zyg = zyg
        self.pid = pid

    def poll(self) -> Optional[int]:
        rc = self._zyg.exit_codes.get(self.pid)
        if rc is not None:
            return rc
        if not self._zyg.alive():
            # Zygote gone (phase teardown): fall back to a liveness
            # probe so the final wait loop can't spin on a dead child.
            try:
                os.kill(self.pid, 0)
            except ProcessLookupError:
                return -9
        return None

    def send_signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            pass

    def kill(self) -> None:
        self.send_signal(signal.SIGKILL)

    def terminate(self) -> None:
        self.send_signal(signal.SIGTERM)

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = time.time() + (timeout if timeout is not None else 3600)
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if time.time() >= deadline:
                raise subprocess.TimeoutExpired("zygote-child", timeout)
            time.sleep(0.05)


class _Zygote:
    """Parent-side handle: one import-warm respawn server per phase."""

    def __init__(self, base_env: Dict[str, str]) -> None:
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--zygote"],
            env=base_env,
            cwd=REPO,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            text=True,
            bufsize=1,
        )
        self.exit_codes: Dict[int, int] = {}
        self._responses: "queue.Queue[dict]" = queue.Queue()
        self._lock = threading.Lock()
        threading.Thread(
            target=self._read, daemon=True, name="zygote_reader"
        ).start()
        msg = self._responses.get(timeout=120)
        if not msg.get("ready"):
            raise RuntimeError(f"zygote failed to warm: {msg}")

    def _read(self) -> None:
        try:
            for line in self.proc.stdout:
                msg = json.loads(line)
                if "exit" in msg:
                    self.exit_codes[msg["exit"]] = msg["rc"]
                else:
                    if "pid" in msg:
                        # The kernel recycles pids: clear a stale exit
                        # code from a previous worker IN PIPE ORDER, so
                        # a fresh child never reads as already-dead (and
                        # its own exit, which can only arrive later on
                        # this pipe, is never erased).
                        self.exit_codes.pop(msg["pid"], None)
                    self._responses.put(msg)
        except Exception:
            pass  # zygote died; spawn() falls back to classic Popen

    def spawn(self, env: Dict[str, str], nice: int = 0) -> _ZygoteProc:
        with self._lock:
            self.proc.stdin.write(
                json.dumps({"env": env, "nice": nice}) + "\n"
            )
            self.proc.stdin.flush()
            msg = self._responses.get(timeout=60)
        return _ZygoteProc(self, msg["pid"])

    def alive(self) -> bool:
        return self.proc.poll() is None

    def shutdown(self) -> None:
        try:
            self.proc.kill()
        except Exception:
            pass


# --------------------------------------------------------------------------
# parent: orchestration + measurement
# --------------------------------------------------------------------------


class _Group:
    def __init__(
        self, gid: int, log_path: str, env: Dict[str, str],
        hot_spare: bool = False, heal_boost: int = 0,
        zygote: Optional[_Zygote] = None, lift_ok: bool = True,
    ) -> None:
        self.gid = gid
        self.log_path = log_path
        self.env = env
        self.hot_spare = hot_spare
        self.heal_boost = heal_boost
        self.zygote = zygote
        # launcher.py discipline: standbys only warm NICED when the
        # supervisor can lift them back — an unprivileged supervisor
        # warms un-niced (bounded contention) rather than parking spares
        # at a priority nobody can ever restore.
        self.lift_ok = lift_ok
        self.boost_active: Optional[float] = None
        self.proc: Optional[subprocess.Popen] = None
        self.standby: Optional[subprocess.Popen] = None
        self.standby_file: Optional[str] = None
        self.standby_armed_t = 0.0
        self.standby_lifted = False

    def _popen(
        self, extra_env: Dict[str, str], idle: bool = False
    ) -> subprocess.Popen:
        env = {**os.environ, "BENCH_SPAWN_T": str(time.time()), **extra_env}
        # In the GROUP SPEC only, an empty value means "unset" (e.g.
        # JAX_PLATFORMS="" lets the host's default accelerator platform
        # win for the TPU group); inherited empty-string env vars pass
        # through untouched — empty and unset differ for some vars.
        for k, v in self.env.items():
            if v == "":
                env.pop(k, None)
            else:
                env[k] = v
        # Import-warm respawn: fork from the phase zygote when the child
        # would run the same interpreter profile the zygote warmed (CPU
        # platform). The TPU group needs a REAL interpreter start (its
        # sitecustomize backend preload runs at interpreter start), so it
        # always takes the classic spawn.
        if (
            self.zygote is not None
            and self.zygote.alive()
            and env.get("JAX_PLATFORMS") == "cpu"
        ):
            try:
                return self.zygote.spawn(env, nice=19 if idle else 0)
            except Exception:
                pass  # zygote wedged/died: classic spawn still heals
        preexec = None
        if idle:

            def preexec() -> None:
                try:
                    os.nice(19)
                except OSError:
                    pass

        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            env=env,
            cwd=REPO,
            preexec_fn=preexec,
        )

    def spawn(self) -> None:
        self.proc = self._popen({})
        if self.hot_spare:
            self.arm_standby()

    def arm_standby(self) -> None:
        # Idle priority (launcher.py discipline): standby warm-up
        # (imports + jit) must not steal cycles from live training — the
        # round-3 hot-spare phase measured ratio 0.742 BECAUSE re-arming
        # contended with every group on the single shared CPU. The
        # idle-priority trade is bounded by the warm-deadline lift below:
        # a spare that is STILL warming when the grace expires gets its
        # priority restored so repeat kills find it parked at the gate
        # fully warmed, not mid-import (the round-5 16 s hot-spare p50:
        # on a saturated host an idle re-arm never finishes, so every
        # promotion paid the whole warm-up at heal time).
        self.standby_file = self.log_path + f".standby_{time.time():.3f}"
        self.standby = self._popen(
            {"TORCHFT_STANDBY_FILE": self.standby_file},
            idle=self.lift_ok,
        )
        self.standby_armed_t = time.monotonic()
        self.standby_lifted = False

    def standby_warm(self) -> bool:
        """Whether the parked standby finished warming (standby_gate
        touches ``<standby_file>.warm`` on arrival)."""
        return bool(
            self.standby_file and os.path.exists(self.standby_file + ".warm")
        )

    def lift_slow_warmup(self, deadline_s: float) -> None:
        """Restores a still-warming standby to normal priority once the
        grace window expires (torchft_tpu.launcher applies the same
        policy): bounded contention once per re-arm instead of a cold
        warm-up on every subsequent kill of this group."""
        if (
            not self.lift_ok  # standby was never niced; nothing to lift
            or self.standby is None
            or self.standby.poll() is not None
            or self.standby_lifted
            or self.standby_warm()
            or time.monotonic() - self.standby_armed_t < deadline_s
        ):
            return
        self.standby_lifted = True
        try:
            os.setpriority(os.PRIO_PROCESS, self.standby.pid, 0)
        except (OSError, AttributeError):
            pass

    def restart(self) -> None:
        """Cold respawn, or sub-second promotion of the warm standby
        (the launcher's --hot-spare policy, torchft_tpu.launcher)."""
        if self.standby is not None and self.standby.poll() is None:
            open(self.standby_file, "w").close()
            self.proc = self.standby
            self.standby = None
            try:  # lift the idle priority on promotion (root/CAP_SYS_NICE)
                os.setpriority(os.PRIO_PROCESS, self.proc.pid, 0)
            except (OSError, AttributeError):
                pass
            self.arm_standby()
        else:
            self.proc = self._popen({})
            if self.heal_boost:
                # Heal-priority boost (platform.heal_boost_nice): the
                # cold-restarting member is the cohort's degraded one —
                # lend it survivor CPU while it heals; maybe_deboost
                # returns it to parity at its first committed step.
                try:
                    os.setpriority(
                        os.PRIO_PROCESS, self.proc.pid, -self.heal_boost
                    )
                    self.boost_active = time.time()
                except (OSError, AttributeError):
                    pass
            if self.hot_spare:
                self.arm_standby()

    def maybe_deboost(self) -> None:
        """Ends an active heal boost once the restarted worker committed
        a step (healed — it is a peer again), or after a 60 s hard cap
        (a heal that slow has bigger problems than priority). Reads only
        the log's TAIL, at a 1 s cadence: re-parsing a 1200-record JSONL
        4×/s from the supervisor would load the very CPUs whose
        contention the heal numbers measure."""
        if self.boost_active is None or self.proc is None:
            return
        now = time.time()
        if now < getattr(self, "_deboost_next_check", 0):
            return
        self._deboost_next_check = now + 1.0
        healed = False
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                start = max(0, f.tell() - 8192)
                f.seek(start)
                tail = f.read().decode(errors="replace").splitlines()
            if start > 0:
                tail = tail[1:]  # first line torn by the mid-file seek
            for line in tail:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if r.get("committed") and r.get("t", 0) > self.boost_active:
                    healed = True
                    break
        except OSError:
            pass
        if healed or now - self.boost_active > 60:
            self.boost_active = None
            if self.proc.poll() is None:
                try:
                    os.setpriority(os.PRIO_PROCESS, self.proc.pid, 0)
                except (OSError, AttributeError):
                    pass

    def reap(self) -> None:
        if self.standby is not None and self.standby.poll() is None:
            self.standby.kill()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def _read_log(path: str) -> List[dict]:
    records = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn write
    except FileNotFoundError:
        pass
    return records


def _committed(records: List[dict]) -> List[dict]:
    return [r for r in records if r.get("committed")]


# Every heal-breakdown phase the artifact can carry, in pipeline order.
# Cold restarts populate all of them; promoted standbys only the ones a
# promotion actually pays (activation / rendezvous / fetch / h2d /
# first_commit) — the absent cold keys are the measurement that the warm
# path skipped that work.
HEAL_PHASES = (
    "activation", "respawn", "import", "setup", "backend_init", "mesh",
    "compile", "join", "rendezvous", "fetch", "h2d", "first_commit",
)


def compute_heal_stats(
    kills: List[dict], logs_by_gid: Dict[int, List[dict]]
) -> tuple:
    """Joins the supervisor's kill timestamps with each victim's log
    records into ``(heal_s, breakdowns)``.

    heal_s: seconds from each SIGKILL to the restarted group's first
    committed step (sorted). breakdowns: one dict of HEAL_PHASES seconds
    per attributable kill — boot-record deltas (respawn / import / setup
    / backend_init / mesh / compile for cold restarts; activation /
    rendezvous for both paths) plus the in-band "heal" record's streamed
    fetch / h2d split. Each kill's window is bounded at the SAME group's
    next kill: if the victim dies again before its restart commits, the
    later kill's commit/boot/heal records must not be attributed to this
    one (that would silently fold an extra kill cycle into the medians).
    Pure function of the logs — unit-testable without running a phase."""
    heal_s = []
    breakdowns = []
    for k in kills:
        next_kill_t = min(
            (
                k2["t"]
                for k2 in kills
                if k2["gid"] == k["gid"] and k2["t"] > k["t"]
            ),
            default=float("inf"),
        )
        log = logs_by_gid.get(k["gid"], [])
        after = [
            r["t"]
            for r in _committed(log)
            if k["t"] < r["t"] < next_kill_t
        ]
        if after:
            heal_s.append(after[0] - k["t"])
        # Match boots by ACTIVATION time: a promoted hot-spare standby was
        # spawned (and imported/compiled) long before the kill, so only
        # its activation falls in this kill's window.
        boots = [
            r["boot"]
            for r in log
            if "boot" in r
            and k["t"] < r["boot"].get("activated_t", r["boot"]["spawn_t"])
            < next_kill_t
        ]
        if boots and after:
            b = boots[0]
            entry = {
                # kill -> warmed process past its gate (cold: respawn +
                # import + setup + backend_init + mesh + compile;
                # promoted standby: just the supervisor poll + gate poll)
                "activation": b["activated_t"] - k["t"],
                # manager/store/quorum-client bring-up ("join" is the
                # same delta, kept for artifact continuity)
                "rendezvous": b["manager_t"] - b["activated_t"],
                "join": b["manager_t"] - b["activated_t"],
                "first_commit": after[0] - b["manager_t"],
            }
            if b["spawn_t"] > k["t"]:
                # Cold restart: the process-boot phases belong to this kill.
                entry.update(
                    {
                        "respawn": b["spawn_t"] - k["t"],
                        "import": b["enter_t"] - b["spawn_t"],
                        "setup": b["setup_t"] - b["enter_t"],
                    }
                )
                if "backend_t" in b and "model_t" in b:
                    entry.update(
                        {
                            "backend_init": b["backend_t"] - b["setup_t"],
                            "mesh": b["model_t"] - b["backend_t"],
                            "compile": b["compiled_t"] - b["model_t"],
                        }
                    )
                else:  # pre-split boot record: one opaque compile bucket
                    entry["compile"] = b["compiled_t"] - b["setup_t"]
            # The streamed-heal transfer split, recorded in-band by the
            # worker when its manager healed from a live peer.
            heals = [
                r["heal"]
                for r in log
                if "heal" in r and k["t"] < r["heal"]["t"] < next_kill_t
            ]
            if heals:
                entry["fetch"] = heals[0].get("fetch_s")
                entry["h2d"] = heals[0].get("h2d_s")
            breakdowns.append(
                {n: v for n, v in entry.items() if v is not None}
            )
    heal_s.sort()
    return heal_s, breakdowns


def _steps_per_sec(records: List[dict], skip: int = 5) -> float:
    """Committed steps/sec, excluding the first ``skip`` commits (compile +
    ramp)."""
    done = _committed(records)[skip:]
    if len(done) < 2:
        return 0.0
    return (len(done) - 1) / (done[-1]["t"] - done[0]["t"])


def _run_phase(
    name: str,
    groups: int,
    steps: int,
    kill_every: int,
    out_dir: str,
    lighthouse_addr: str,
    tpu_group0: bool = False,
    hot_spare: bool = False,
    deadline_s: Optional[float] = None,
) -> dict:
    go_path = os.path.join(out_dir, f"{name}.go")
    from torchft_tpu.launcher import _can_lift_priority
    from torchft_tpu.platform import heal_boost_nice

    # One capability probe gates every priority maneuver this phase: the
    # heal boost (needs a negative nice) and standby IDLE warming (only
    # safe when the lift back to 0 is possible — an unprivileged
    # supervisor warms spares un-niced, the launcher.py discipline, or
    # the warm-deadline fix would silently no-op and every repeat kill
    # would promote a half-warmed spare again).
    lift_ok = _can_lift_priority()
    heal_boost = heal_boost_nice() if lift_ok else 0
    # One import-warm respawn server per phase (see zygote()): restarts
    # of CPU groups fork from it instead of re-importing jax/optax under
    # survivor contention. Warmed with the CPU-worker interpreter
    # profile; failure to start is non-fatal (classic spawns still work).
    zyg: Optional[_Zygote] = None
    if os.environ.get("TORCHFT_ZYGOTE", "1") != "0":
        base_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        base_env.pop("PALLAS_AXON_POOL_IPS", None)
        try:
            zyg = _Zygote(base_env)
        except Exception as e:  # noqa: BLE001 - degraded, not broken
            print(f"zygote unavailable ({e!r}); classic spawns only",
                  file=sys.stderr)
            zyg = None
    gs: List[_Group] = []
    for g in range(groups):
        log_path = os.path.join(out_dir, f"{name}_g{g}.jsonl")
        gs.append(
            _Group(
                g,
                log_path,
                {
                    # --tpu-group0: the measurement group runs on the real
                    # chip (the platform the host pins by default); its CPU
                    # peers are the churn. Kills only ever hit CPU groups
                    # (victim rotates over 1..N-1), so this shows the
                    # TPU-RESIDENT process's throughput under cross-group
                    # churn — the axis virtual-device dryruns can't show.
                    "JAX_PLATFORMS": ""
                    if (tpu_group0 and g == 0)
                    else "cpu",
                    # CPU workers skip the sitecustomize TPU-backend
                    # preload (axon.register + PJRT init at INTERPRETER
                    # START — it can round-trip the device tunnel): pure
                    # dead weight on the cold-restart heal path, where
                    # the import bucket dominated round 3's 15.2 s p50.
                    # (empty value = "unset" per _popen's group-spec rule)
                    **(
                        {}
                        if (tpu_group0 and g == 0)
                        else {"PALLAS_AXON_POOL_IPS": ""}
                    ),
                    "TORCHFT_LIGHTHOUSE": lighthouse_addr,
                    "REPLICA_GROUP_ID": str(g),
                    "NUM_REPLICA_GROUPS": str(groups),
                    "NUM_STEPS": str(steps),
                    "BENCH_LOG": log_path,
                    "BENCH_GO": go_path,
                    # Shared persistent jit cache: restarted workers reload
                    # executables instead of recompiling (the dominant heal
                    # cost in round 2's 31 s p50).
                    "TORCHFT_COMPILE_CACHE": os.path.join(out_dir, "jax_cache"),
                },
                # Standbys only for killable groups: kills rotate over
                # 1..N-1, so a group-0 standby would be pure import+compile
                # contention against the measurement group (and on
                # --tpu-group0 it could not warm the primary-owned chip
                # anyway).
                hot_spare=hot_spare and g != 0,
                heal_boost=heal_boost,
                zygote=zyg,
                lift_ok=lift_ok,
            )
        )
    for g in gs:
        g.spawn()

    # Start line: release every group at once, after all have compiled.
    ready_deadline = time.time() + 300
    while time.time() < ready_deadline:
        if all(os.path.exists(g.log_path + ".ready") for g in gs):
            break
        time.sleep(0.25)
    open(go_path, "w").close()

    kills: List[dict] = []
    next_kill = kill_every if kill_every > 0 else None
    victim = 1  # rotate over groups 1..N-1; group 0 is the measurement group
    # Deadline scales with the step target (the default was raised to 1200
    # steps for kill-count power; a fixed 1200 s cap would silently
    # truncate slow runs back to the under-powered measurement). Truncation
    # is detected and reported either way.
    deadline = time.time() + (
        deadline_s if deadline_s is not None else max(1200, steps * 4)
    )
    timed_out = False
    from torchft_tpu.platform import standby_warm_deadline_s

    warm_deadline = standby_warm_deadline_s()
    try:
        while any(g.alive() for g in gs):
            if time.time() >= deadline:
                timed_out = True
                break
            time.sleep(0.25)
            # Restart any dead group (supervisor role, launcher semantics;
            # promotes the warm standby under --hot-spare). The warm-
            # deadline lift keeps re-armed standbys from starving at idle
            # priority past the next kill of their group.
            for g in gs:
                g.lift_slow_warmup(warm_deadline)
                g.maybe_deboost()
                if g.proc is not None and g.proc.poll() not in (None, 0):
                    g.restart()
            if next_kill is not None:
                lead = len(_committed(_read_log(gs[0].log_path)))
                if lead >= next_kill and lead < steps - 5:
                    v = gs[victim]
                    if v.alive():
                        v.proc.send_signal(signal.SIGKILL)
                        kills.append(
                            {"t": time.time(), "gid": v.gid, "at_step": lead}
                        )
                        victim = victim % (groups - 1) + 1
                    next_kill += kill_every
    finally:
        for g in gs:
            g.reap()  # parked standbys never exit on their own
            if g.alive():
                g.proc.terminate()
        for g in gs:
            if g.proc is not None:
                try:
                    g.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    g.proc.kill()
        if zyg is not None:
            zyg.shutdown()

    # Heal latency: kill -> first commit recorded by the restarted process,
    # broken into HEAL_PHASES via the worker's boot + heal records (see
    # compute_heal_stats).
    heal_s, breakdowns = compute_heal_stats(
        kills, {g.gid: _read_log(g.log_path) for g in gs}
    )

    def _phase_median(name: str) -> Optional[float]:
        vals = sorted(b[name] for b in breakdowns if name in b)
        return round(vals[len(vals) // 2], 2) if vals else None

    # Throughput spread: group 0's committed-step rate over time quarters —
    # the noise floor a churn ratio must be read against.
    g0 = _committed(_read_log(gs[0].log_path))[5:]
    quarter_sps = []
    for i in range(4):
        seg = g0[i * len(g0) // 4 : (i + 1) * len(g0) // 4]
        if len(seg) >= 2:
            quarter_sps.append(
                round((len(seg) - 1) / (seg[-1]["t"] - seg[0]["t"]), 3)
            )

    committed_g0 = len(_committed(_read_log(gs[0].log_path)))
    return {
        "steps_per_sec": round(_steps_per_sec(_read_log(gs[0].log_path)), 3),
        "steps_per_sec_quarters": quarter_sps,
        # Deadline truncation (the phase was cut off mid-run, so the
        # measurement is under-powered). A near-target committed count
        # without a timeout is normal: the first group to finish exits,
        # which can abort one in-flight step on the others.
        "truncated": bool(timed_out),
        "committed_vs_target": f"{committed_g0}/{steps}",
        "kills": len(kills),
        "heal_s": [round(h, 2) for h in heal_s],
        "heal_p50_s": round(heal_s[len(heal_s) // 2], 2) if heal_s else None,
        "heal_breakdown_median_s": {
            name: _phase_median(name) for name in HEAL_PHASES
        }
        if breakdowns
        else None,
        "committed_steps_g0": len(_committed(_read_log(gs[0].log_path))),
    }


# --------------------------------------------------------------------------
# durable phase: async sharded snapshot stall + no-donor restore
# --------------------------------------------------------------------------


def run_durable_phase(
    n_elems: int = 8_000_000,
    checkpoints: int = 4,
    world_old: int = 3,
    world_new: int = 2,
) -> dict:
    """Bench the durable tier in-process with a fake-manager fleet (the
    durable pipeline's only inputs are ``(step, quorum_id, rank, world)``
    at the commit boundary; the live-Manager integration is covered by
    the chaos ``fleet_loss`` config and tests/test_durable.py).

    Three measurements on an adam-shaped state (f32 params + 2x f32
    opt-state, bf16 wire):

      sync_baseline:  W=1 ``mode="sync"`` — the v1-shaped blocking
                      d2h + serialize + write + fsync pipeline on the
                      trainer thread, per checkpoint.
      async_sharded:  W=world_old ``mode="async"`` + ``zero_copy`` —
                      each member's trainer pays only the layout walk of
                      its ~1/W shard; cast/CRC/write/fsync ride the
                      background writer.
      durable_restore: a COLD fleet of W=world_new (no live donor, no
                      overlap with world_old) reassembles the newest
                      committed set, split into manifest-read /
                      shard-fetch / reshard / h2d / compile buckets.
    """
    import statistics
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchft_tpu.durable import DurableCheckpointer

    class _Mgr:
        def __init__(self, rank: int, world: int) -> None:
            self._rank, self._world = rank, world
            self._step, self._bc = 0, 0

        def current_step(self) -> int:
            return self._step

        def quorum_id(self) -> int:
            return 1

        def participating_rank(self) -> int:
            return self._rank

        def num_participants(self) -> int:
            return self._world

        def replica_id(self) -> str:
            return f"durable_bench_{self._rank}"

        def state_dict(self) -> dict:
            return {"step": self._step, "batches_committed": self._bc}

        def load_state_dict(self, sd: dict) -> None:
            self._step = sd["step"]
            self._bc = sd["batches_committed"]

        def add_commit_hook(self, fn) -> None:
            pass

    class _St:
        def __init__(self) -> None:
            z = jnp.zeros((n_elems,), jnp.float32)
            self.params = {"w": z + 0.5}
            self.opt_state = {"m": z, "v": z}

        def state_dict(self) -> dict:
            return {"params": self.params, "opt_state": self.opt_state}

        def load_state_dict(self, sd) -> None:
            self.params = sd["params"]
            self.opt_state = sd["opt_state"]

    # functional (non-donating) update — the regime TORCHFT_DURABLE_
    # ZEROCOPY is sound for
    update = jax.jit(
        lambda w, m, v, g: (
            w - 0.1 * (0.9 * m + 0.1 * g),
            0.9 * m + 0.1 * g,
            0.99 * v + 0.01 * g * g,
        )
    )

    def train_step(st: "_St", step: int) -> None:
        g = jnp.full((n_elems,), 0.001 * step, jnp.float32)
        w, m, v = update(
            st.params["w"], st.opt_state["m"], st.opt_state["v"], g
        )
        st.params = {"w": w}
        st.opt_state = {"m": m, "v": v}
        jax.block_until_ready(w)

    record: Dict[str, object] = {
        "phase": "durable",
        "config": {
            "n_elems": n_elems,
            "checkpoints": checkpoints,
            "world_old": world_old,
            "world_new": world_new,
            "wire": "bf16",
            "host_cpus": os.cpu_count(),
        },
    }

    with tempfile.TemporaryDirectory(prefix="durable_bench_") as tmp:
        # -- sync baseline (v1-shaped blocking writer, full state) --
        sync_dir = os.path.join(tmp, "sync")
        mgr = _Mgr(0, 1)
        st = _St()
        train_step(st, 0)  # warm jit; materialize state
        cp = DurableCheckpointer(
            sync_dir, mgr, st, every=1, keep=2, mode="sync",
            commit_timeout_s=60.0,
        )
        for step in range(1, checkpoints + 1):
            train_step(st, step)
            mgr._step, mgr._bc = step, step
            cp.maybe_save()
        cp.flush(120.0)
        cp.close()
        sync_stalls = [r["stall_s"] for r in cp.snapshots]
        total_bytes = int(cp.snapshots[0]["total_bytes"])
        record["config"]["total_bytes"] = total_bytes  # type: ignore[index]
        record["sync_baseline"] = {
            "mode": "sync",
            "world": 1,
            "stall_s": [round(s, 6) for s in sync_stalls],
            "stall_p50_s": round(statistics.median(sync_stalls), 6),
            "durable_bytes_per_member": total_bytes,
        }

        # -- async sharded zero-copy snapshots at W=world_old --
        async_dir = os.path.join(tmp, "async")
        mgrs = [_Mgr(r, world_old) for r in range(world_old)]
        sts = [_St() for _ in range(world_old)]
        for s in sts:
            train_step(s, 0)
        cps = [
            DurableCheckpointer(
                async_dir, m, s, every=1, keep=2, mode="async",
                zero_copy=True, commit_timeout_s=60.0,
            )
            for m, s in zip(mgrs, sts)
        ]
        for step in range(1, checkpoints + 1):
            for s in sts:  # deterministic: members stay replicated
                train_step(s, step)
            for m in mgrs:
                m._step, m._bc = step, step * world_old
            for c in cps:
                c.maybe_save()
        flushed = all(c.flush(120.0) for c in cps)
        for c in cps:
            c.close()
        committed_steps = cps[0].committed_steps()
        rows = [r for c in cps for r in c.snapshots]
        async_stalls = [r["stall_s"] for r in rows]
        shard_bytes = sorted({int(r["shard_bytes"]) for r in rows})
        record["async_sharded"] = {
            "mode": "async",
            "world": world_old,
            "zero_copy": True,
            "rows": [
                {
                    k: (round(v, 6) if k == "stall_s" else v)
                    for k, v in r.items()
                }
                for r in rows
            ],
            "stall_p50_s": round(statistics.median(async_stalls), 6),
            "stall_mean_s": round(
                sum(async_stalls) / len(async_stalls), 6
            ),
            "shard_bytes": shard_bytes,
            "committed_steps": committed_steps,
            "flushed": flushed,
        }
        sync_mean = sum(sync_stalls) / len(sync_stalls)
        async_mean = sum(async_stalls) / len(async_stalls)
        record["stall_ratio_vs_sync"] = round(
            async_mean / sync_mean, 4
        ) if sync_mean else None
        # per-member durable bytes ~ total/W (floor split slack < W)
        record["shard_scaling_ok"] = bool(
            max(int(r["shard_bytes"]) for r in rows)
            <= total_bytes // world_old + world_old
        )

        # -- no-donor cold restore at a DIFFERENT W --
        new_mgrs = [_Mgr(r, world_new) for r in range(world_new)]
        new_sts = [_St() for _ in range(world_new)]
        restores = []
        t_all = time.perf_counter()
        for m, s in zip(new_mgrs, new_sts):
            rcp = DurableCheckpointer(async_dir, m, s, every=1)
            t0 = time.perf_counter()
            step = rcp.restore_latest(device_put=True)
            wall = time.perf_counter() - t0
            stats = dict(rcp.last_restore_stats or {})
            stats["restored_step"] = step
            stats["wall_s"] = wall
            restores.append(stats)
            rcp.close()
        # compile bucket: first jitted step on the restored state — a
        # fresh function object so jax cannot reuse the warm executable
        restep = jax.jit(lambda w, g: w - 0.1 * g)
        t0 = time.perf_counter()
        jax.block_until_ready(
            restep(
                new_sts[0].params["w"],
                jnp.full((n_elems,), 0.001, jnp.float32),
            )
        )
        compile_s = time.perf_counter() - t0
        digests = {
            hash(np.asarray(s.params["w"]).tobytes()) for s in new_sts
        }
        r0 = restores[0]
        record["durable_restore"] = {
            "kind": "no_donor_cold_restore",
            "world_old": world_old,
            "world_new": world_new,
            "restored_step": r0.get("restored_step"),
            "bytes": r0.get("bytes"),
            "manifest_read_s": round(r0.get("manifest_read_s", 0.0), 6),
            "shard_fetch_s": round(r0.get("shard_fetch_s", 0.0), 6),
            "reshard_s": round(r0.get("reshard_s", 0.0), 6),
            "h2d_s": round(r0.get("h2d_s", 0.0), 6),
            "compile_s": round(compile_s, 6),
            "wall_s": round(r0.get("wall_s", 0.0), 6),
            "fleet_wall_s": round(time.perf_counter() - t_all, 6),
            "members_bit_identical": len(digests) == 1,
            "per_member": [
                {
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in r.items()
                }
                for r in restores
            ],
        }
    return record


def run_durable_main(dryrun: bool, out: str) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    record = run_durable_phase(
        n_elems=1_000_000 if dryrun else 8_000_000,
        checkpoints=2 if dryrun else 4,
    )
    snaps = record["async_sharded"]
    restore = record["durable_restore"]
    ratio = record["stall_ratio_vs_sync"]
    # one committed async-snapshot record + one no-donor-restore record
    # with every bucket present: the dryrun contract, asserted on full
    # runs too (a bench that can't produce its own headline rows should
    # fail, not publish an empty artifact)
    ok = (
        bool(snaps["committed_steps"])
        and bool(snaps["flushed"])
        and any(r["committed"] for r in snaps["rows"])
        and restore["restored_step"] == max(snaps["committed_steps"])
        and restore["members_bit_identical"]
        and all(
            restore[k] is not None
            for k in (
                "manifest_read_s", "shard_fetch_s", "reshard_s",
                "h2d_s", "compile_s",
            )
        )
        and record["shard_scaling_ok"]
    )
    record["measurement_ok"] = ok and ratio is not None and ratio <= 0.05
    print(
        json.dumps(
            {
                "metric": (
                    "durable_dryrun_ok" if dryrun else "durable_stall_ratio"
                ),
                "value": (1 if ok else 0) if dryrun else ratio,
                "unit": "bool" if dryrun else "ratio",
                "stall_ratio_vs_sync": ratio,
                "restored_step": restore["restored_step"],
                "restore_wall_s": restore["wall_s"],
            }
        )
    )
    if dryrun:
        return 0 if ok else 1  # smoke only, NO artifact
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
    return 0 if ok else 1


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--zygote", action="store_true")
    parser.add_argument("--groups", type=int, default=4)
    # >= 10 kills over >= 1000 steps: 2 kills over 300 steps (round 2)
    # left the effect smaller than the noise (ratio measured > 1).
    parser.add_argument("--steps", type=int, default=1200)
    parser.add_argument("--kill-every", type=int, default=100)
    parser.add_argument(
        "--tpu-group0",
        action="store_true",
        help="run group 0 on the host's default (TPU) platform; kills "
        "still only hit the CPU peer groups",
    )
    parser.add_argument(
        "--hot-spare",
        action="store_true",
        help="also run a churn phase where restarts promote a pre-warmed "
        "standby (the launcher's --hot-spare policy) instead of cold-"
        "restarting",
    )
    parser.add_argument(
        "--durable",
        action="store_true",
        help="bench the durable checkpoint tier instead of churn: async "
        "sharded snapshot stall vs the synchronous writer, 1/W shard "
        "bytes, and the cold no-donor restore breakdown "
        "(DURABLE_BENCH.json; with --dryrun: CI smoke, no artifact)",
    )
    parser.add_argument(
        "--dryrun",
        action="store_true",
        help="seconds-scale CI smoke: 2 groups, a few dozen steps, one "
        "kill per churn phase (cold + hot-spare), tight deadlines, NO "
        "artifact written — exercises the whole kill/heal/promotion "
        "path so it can't silently rot between perf rounds",
    )
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    if args.durable:
        sys.exit(
            run_durable_main(
                dryrun=args.dryrun,
                out=args.out or os.path.join(REPO, "DURABLE_BENCH.json"),
            )
        )
    if args.dryrun and not args.worker:
        # Kill early in a window long enough that the donor is still
        # alive and committing when the victim's restart comes up — a
        # kill near the end lets survivors finish and exit first, and
        # the restart then rejoins solo without a checkpoint heal.
        args.groups = 2
        args.steps = 48
        args.kill_every = 10
        args.hot_spare = True
    if args.out is None:
        args.out = os.path.join(
            REPO,
            "CHURN_BENCH_tpu.json" if args.tpu_group0 else "CHURN_BENCH.json",
        )

    if args.worker:
        worker()
        return
    if args.zygote:
        zygote()
        return

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from torchft_tpu import Lighthouse

    out_dir = os.path.join(REPO, ".bench_churn_logs")
    os.makedirs(out_dir, exist_ok=True)
    for f in os.listdir(out_dir):
        path = os.path.join(out_dir, f)
        if os.path.isdir(path):
            # Keep the persistent jit cache WARM across runs: restarted
            # workers (and whole re-runs) skip the compile.
            continue
        os.unlink(path)

    # Failure detection speed comes from heartbeat_timeout (a dead member
    # leaves the healthy set after 500 ms and the join gate does not apply
    # to it). join_timeout must exceed a STEP TIME: the gate holds quorum
    # formation for healthy-but-not-yet-requesting members, and members
    # re-request once per step — a 200 ms gate under >200 ms steps lets
    # sub-quorums form between paced requests, flapping membership and
    # starving a joiner (observed: the TPU group excluded for 43 s while
    # two CPU groups fast-quorumed as a stable pair).
    lighthouse = Lighthouse(
        bind="[::]:0",
        min_replicas=1,
        join_timeout_ms=2000,
        quorum_tick_ms=50,
        heartbeat_timeout_ms=500,
    )

    phase_deadline = 300.0 if args.dryrun else None
    healthy = _run_phase(
        "healthy", args.groups, args.steps, 0, out_dir, lighthouse.address(),
        tpu_group0=args.tpu_group0, deadline_s=phase_deadline,
    )
    churn = _run_phase(
        "churn", args.groups, args.steps, args.kill_every, out_dir,
        lighthouse.address(), tpu_group0=args.tpu_group0,
        deadline_s=phase_deadline,
    )
    churn_hot = None
    if args.hot_spare:
        # Third phase: same kill schedule, restarts by standby PROMOTION
        # (launcher --hot-spare). The cold phase above stays in the
        # artifact so both restart policies' heal latencies are on record.
        churn_hot = _run_phase(
            "churn_hot", args.groups, args.steps, args.kill_every, out_dir,
            lighthouse.address(), tpu_group0=args.tpu_group0, hot_spare=True,
            deadline_s=phase_deadline,
        )
    lighthouse.shutdown()

    ratio = (
        round(churn["steps_per_sec"] / healthy["steps_per_sec"], 3)
        if healthy["steps_per_sec"]
        else 0.0
    )
    # Noise gate: churn measuring FASTER than healthy by > 5% means the
    # run-to-run noise exceeds the effect under measurement — record the
    # run as too noisy instead of claiming an absurd ratio (a fault-
    # tolerance layer cannot beat the fault-free loop).
    quarters = healthy.get("steps_per_sec_quarters") or []
    spread = (
        round((max(quarters) - min(quarters)) / max(quarters), 3)
        if quarters
        else None
    )
    from torchft_tpu.chaos import bench_fault_stamp

    result = {
        "config": {
            "groups": args.groups,
            "steps": args.steps,
            "kill_every": args.kill_every,
            "host_cpus": os.cpu_count(),
            "tpu_group0": args.tpu_group0,
        },
        # The seeded schedule (env TORCHFT_CHAOS_SEED/_PLAN) plus this
        # bench's own fault knobs: any anomaly in this artifact replays
        # via scripts/chaos_run.py --seed.
        "fault_plan": bench_fault_stamp(
            bench="bench_churn", kill_every=args.kill_every,
            kill_kind="sigkill",
        ),
        "healthy": healthy,
        "churn": churn,
        "churn_hot_spare": churn_hot,
        "ratio": ratio,
        "ratio_hot_spare": (
            round(churn_hot["steps_per_sec"] / healthy["steps_per_sec"], 3)
            if churn_hot and healthy["steps_per_sec"]
            else None
        ),
        "healthy_quarter_spread": spread,
        "measurement_ok": bool(
            ratio <= 1.05
            and not healthy.get("truncated")
            and not churn.get("truncated")
        ),
        "target": 0.90,
        "note": "all host groups share this machine's CPUs, so heal "
        "numbers carry contention the target deployment (one host per "
        "group) does not have. Hot-spare policy: standbys re-arm at IDLE "
        "priority so warm-up never steals training cycles, with a "
        "bounded warm-deadline lift (TORCHFT_STANDBY_WARM_DEADLINE_S) "
        "restoring a still-warming spare to normal priority so repeat "
        "kills find it fully warmed — the fix for the round-3/5 "
        "half-warmed-promotion regression (ratio 0.742 warm-at-full-"
        "priority vs 16.85 s p50 warm-at-idle-forever). Promotion = "
        "quorum join + streamed weight fetch only: the spare parks with "
        "backend up, grad/optimizer-update/ring-packer executables "
        "AOT-compiled, and collectives pre-created. Heal transfer rides "
        "the streamed zero-copy checkpoint pipeline (fetch/h2d keys in "
        "heal_breakdown_median_s; TORCHFT_HEAL_WIRE/TORCHFT_HEAL_STREAMS "
        "tune it).",
    }
    if args.dryrun:
        # Smoke only: assert the paths ran (kills happened, heals
        # completed, breakdown keys exist, AND at least one heal rode
        # the zero-copy stream transport — a regression that silently
        # falls back to the pickled fetch must fail CI, not stay green
        # because heals still limp through), write NO artifact.
        stream_heals = 0
        for fname in os.listdir(out_dir):
            if fname.endswith(".jsonl") and "churn" in fname:
                stream_heals += sum(
                    1
                    for r in _read_log(os.path.join(out_dir, fname))
                    if r.get("heal", {}).get("path") == "stream"
                )
        ok = (
            churn["kills"] >= 1
            and churn["heal_p50_s"] is not None
            and churn_hot is not None
            and churn_hot["kills"] >= 1
            and churn_hot["heal_p50_s"] is not None
            and stream_heals >= 1
            # at least one KILL-window heal carried the streamed
            # fetch/h2d split into the artifact keys
            and any(
                (p.get("heal_breakdown_median_s") or {}).get("fetch")
                is not None
                for p in (churn, churn_hot)
            )
        )
        print(
            json.dumps(
                {
                    "metric": "churn_dryrun_ok",
                    "value": 1 if ok else 0,
                    "unit": "bool",
                    "heal_p50_s": churn["heal_p50_s"],
                    "heal_p50_hot_s": (
                        churn_hot["heal_p50_s"] if churn_hot else None
                    ),
                    "stream_heals": stream_heals,
                }
            )
        )
        sys.exit(0 if ok else 1)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(
        json.dumps(
            {
                "metric": "steps_per_sec_churn_ratio",
                "value": ratio,
                "unit": "ratio",
                "vs_baseline": round(ratio / 0.90, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
