"""Per-step ZeRO (ShardedDDP) tests.

The claims the engine makes, pinned as oracles:

- sharded-vs-unsharded BIT-identity on the f32 wire (W=2,3,5, striped
  plans): reduce-scatter + shard-local optimizer + allgather produces the
  very same bytes as the fused plan allreduce + full-size update, because
  the sharded plan reuses the fused plan's ring sums and f32 divide and
  the optimizer arithmetic is elementwise;
- on lossy wires (bf16/q8 grad leg, bf16 param leg) every member still
  holds IDENTICAL params (the cohort-determinism oracle) that track the
  exact trajectory closely;
- the memory claim: each member's optimizer state covers ~1/W of the
  model and the cohort's shards tile it exactly, with the resident bytes
  published through ``report_opt_state_bytes``;
- membership changes re-partition the optimizer state through the
  quorum-id-keyed mask-allgather — surviving members' momentum carries,
  a departed member's positions restart at zero (replayed against a full
  host-side oracle);
- a heal voids the shard meta so the restored member re-shards the
  donor's shard at its next step;
- ``ShardedOptimizerWrapper`` is the same transaction behind the
  OptimizerWrapper loop shape.

All over a REAL HostCollectives ring with the deterministic ring-manager
fake (fixed quorum, always-commit) — the join-timing nondeterminism a
live lighthouse adds would break bit-equality oracles.
"""

import threading
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import Any, Dict

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from torchft_tpu import FTTrainState, ShardedDDP, ShardedOptimizerWrapper
from torchft_tpu._native import Store
from torchft_tpu.collectives import HostCollectives, ReduceOp
from torchft_tpu.parallel import build_shard_apply_step


def _ring(store, world_size, prefix, stripes=1):
    cols = [
        HostCollectives(timeout=timedelta(seconds=15), stripes=stripes)
        for _ in range(world_size)
    ]
    addr = f"{store.address()}/{prefix}"
    with ThreadPoolExecutor(max_workers=world_size) as ex:
        for f in [
            ex.submit(cols[r].configure, addr, r, world_size)
            for r in range(world_size)
        ]:
            f.result()
    return cols


def _run_all(cols, fn):
    results = [None] * len(cols)
    errors = []

    def run(r):
        try:
            results[r] = fn(r, cols[r])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=run, args=(r,)) for r in range(len(cols))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


class _PlanRingManager:
    """Deterministic manager fake over a REAL HostCollectives ring with
    the sharded-plan surface: full participation, always-commit, fixed
    quorum id — removes join-timing nondeterminism so trajectory oracles
    can demand bit-equality (the test_local_sgd._RingManager pattern)."""

    def __init__(self, col, quorum_id: int = 1):
        self._col = col
        self.qid = quorum_id
        self.commit = True
        self.opt_bytes_reports: list = []

    def start_quorum(self, **kw):
        pass

    def _div(self, op):
        return float(self._col.size()) if op == ReduceOp.AVG else None

    def plan_reduce_scatter(self, tree, op=ReduceOp.AVG, wire=None,
                            ag_wire=None):
        return self._col.plan_reduce_scatter(
            tree, ReduceOp.SUM, divisor=self._div(op), wire=wire,
            ag_wire=ag_wire,
        )

    def plan_allgather_into(self, shard, wire=None):
        return self._col.plan_allgather_into(shard, wire=wire)

    def allgather(self, tree):
        return self._col.allgather(tree)

    def quorum_id(self):
        return self.qid

    def should_commit(self):
        return self.commit

    def report_error(self, e):
        raise e

    def report_opt_state_bytes(self, nbytes):
        self.opt_bytes_reports.append(int(nbytes))


# Model: two leaves whose total (5003 + 257 = 5260) is not divisible by
# any tested world size, so the stripe partition's remainder handling is
# in play. Dict keys sort under tree-flatten ("b" before "w").
_W_N, _B_N = 5003, 257
_TOTAL = _W_N + _B_N


def _params():
    return {
        "w": jnp.asarray(
            np.linspace(-1.0, 1.0, _W_N, dtype=np.float32)
        ),
        "b": jnp.asarray(
            np.linspace(0.5, 2.0, _B_N, dtype=np.float32)
        ),
    }


def _grads(r, s):
    rng = np.random.default_rng(1000 + 37 * r + s)
    return {
        "w": jnp.asarray(rng.standard_normal(_W_N).astype(np.float32)),
        "b": jnp.asarray(rng.standard_normal(_B_N).astype(np.float32)),
    }


def _flat(tree):
    return np.concatenate(
        [
            np.asarray(l).ravel()
            for l in jax.tree_util.tree_leaves(tree)
        ]
    )


def _run_sharded(store, world, prefix, tx, steps, stripes=1,
                 shard_wire=None, param_wire=None):
    cols = _ring(store, world, prefix, stripes)

    def member(r, col):
        st = FTTrainState(_params(), tx, opt_state=())
        m = _PlanRingManager(col)
        ddp = ShardedDDP(
            m, st, grad_fn=None, shard_wire=shard_wire,
            param_wire=param_wire,
        )
        for s in range(steps):
            assert ddp.apply_gradients(_grads(r, s))
        return st, ddp, m

    try:
        return _run_all(cols, member)
    finally:
        for c in cols:
            c.shutdown()


def _run_unsharded_oracle(store, world, prefix, tx, steps, stripes=1):
    """The fused path: plan allreduce (SUM + f32 divide — the identical
    arithmetic the sharded rs leg performs) and a full-size optimizer
    update through the SAME jitted shard-apply program (the full flat
    vector is just a shard of size total). Returns the flat params, one
    per member (all identical by the fused plan's own bit-identity)."""
    cols = _ring(store, world, prefix, stripes)

    def member(r, col):
        params = jnp.asarray(_flat(_params()))
        opt = tx.init(params)
        apply = build_shard_apply_step(tx)
        for s in range(steps):
            avg = col.plan_allreduce(
                _grads(r, s), ReduceOp.SUM, divisor=float(world)
            ).wait()
            params, opt = apply(params, opt, jnp.asarray(_flat(avg)))
        return np.asarray(params)

    try:
        return _run_all(cols, member)
    finally:
        for c in cols:
            c.shutdown()


class TestShardedStepBitIdentity:
    @pytest.mark.parametrize(
        "world,stripes", [(2, 1), (2, 4), (3, 1), (5, 2)]
    )
    def test_f32_matches_unsharded_bitwise(self, world, stripes):
        store = Store()
        tx = optax.adam(1e-2)
        try:
            oracle = _run_unsharded_oracle(
                store, world, f"or_{world}_{stripes}", tx, steps=3,
                stripes=stripes,
            )
            res = _run_sharded(
                store, world, f"sh_{world}_{stripes}", tx, steps=3,
                stripes=stripes,
            )
            for st, _, _ in res:
                got = _flat(st.params)
                assert got.tobytes() == oracle[0].tobytes(), (
                    "sharded step diverged bitwise from the fused path"
                )
        finally:
            store.shutdown()

    @pytest.mark.parametrize(
        "shard_wire,param_wire",
        [("bf16", None), ("q8", "bf16"), ("q8", None)],
    )
    def test_lossy_wires_cohort_identical_and_close(
        self, shard_wire, param_wire
    ):
        store = Store()
        tx = optax.adam(1e-2)
        try:
            oracle = _run_unsharded_oracle(
                store, 3, f"orl_{shard_wire}_{param_wire}", tx, steps=3
            )
            res = _run_sharded(
                store, 3, f"shl_{shard_wire}_{param_wire}", tx, steps=3,
                shard_wire=shard_wire, param_wire=param_wire,
            )
            flats = [_flat(st.params) for st, _, _ in res]
            # Determinism oracle: lossy wires, IDENTICAL params anyway
            # (every member adopts the same decoded words).
            for f in flats[1:]:
                assert f.tobytes() == flats[0].tobytes()
            # And they track the exact trajectory.
            np.testing.assert_allclose(
                flats[0], oracle[0], rtol=0.05, atol=0.05
            )
        finally:
            store.shutdown()

    def test_auto_param_wire_is_bf16_iff_q8(self):
        st = FTTrainState(_params(), optax.adam(1e-2), opt_state=())
        assert ShardedDDP(None, st, None, shard_wire="q8")._param_wire \
            == "bf16"
        assert ShardedDDP(None, st, None, shard_wire="bf16")._param_wire \
            is None
        assert ShardedDDP(None, st, None)._param_wire is None

    def test_rejects_non_f32_masters(self):
        st = FTTrainState(
            {"w": jnp.ones((4,), jnp.bfloat16)}, optax.sgd(0.1),
            opt_state=(),
        )
        with pytest.raises(ValueError, match="f32 master"):
            ShardedDDP(None, st, None)


class TestShardedOptimizerState:
    def test_state_is_sharded_and_tiles_the_model(self):
        store = Store()
        try:
            res = _run_sharded(
                store, 3, "mem", optax.adam(1e-2), steps=1
            )
            seen = np.zeros(_TOTAL, np.int32)
            for st, ddp, m in res:
                meta = ddp._shard_meta
                assert meta is not None and meta["quorum_id"] == 1
                ln = 0
                for s, l in meta["ranges"]["float32"]:
                    seen[s: s + l] += 1
                    ln += l
                assert ln < _TOTAL  # strictly smaller than the model
                # adam: mu and nu are shard-sized
                leaves = jax.tree_util.tree_leaves(ddp._opt_shard)
                assert (
                    sum(
                        1 for x in leaves if getattr(x, "size", 0) == ln
                    ) >= 2
                )
                # the resident footprint was published for the policy
                # engine's opt-memory signal
                assert m.opt_bytes_reports
                assert m.opt_bytes_reports[-1] == ddp.opt_state_bytes()
                assert ddp.opt_state_bytes() >= 2 * 4 * ln
            np.testing.assert_array_equal(
                seen, np.ones(_TOTAL, np.int32)
            )
        finally:
            store.shutdown()

    def test_opt_state_bytes_scale_inverse_with_world(self):
        store = Store()
        try:
            per_world = {}
            for world in (2, 3):
                res = _run_sharded(
                    store, world, f"scale{world}", optax.adam(1e-2),
                    steps=1,
                )
                per_world[world] = sum(
                    ddp.opt_state_bytes() for _, ddp, _ in res
                )
            # the cohort TOTAL stays ~constant (the model's 2 moments),
            # so per-member bytes scale ~1/W
            assert per_world[2] == pytest.approx(per_world[3], rel=0.05)
        finally:
            store.shutdown()


class TestReshardOnMembershipChange:
    OPT = dict(learning_rate=0.05, momentum=0.9, nesterov=True)

    def test_survivor_momentum_carries_departed_restarts_zero(self):
        tx = optax.sgd(**self.OPT)
        store = Store()
        try:
            cols3 = _ring(store, 3, "pre")
            states, ddps, mans = [], [], []

            def one_step(r):
                st = FTTrainState(_params(), tx, opt_state=())
                m = _PlanRingManager(cols3[r], quorum_id=1)
                ddp = ShardedDDP(m, st, grad_fn=None)
                assert ddp.apply_gradients(_grads(r, 0))
                return st, ddp, m

            for st, ddp, m in _run_all(
                cols3, lambda r, c: one_step(r)
            ):
                states.append(st)
                ddps.append(ddp)
                mans.append(m)
            params_after1 = _flat(states[0].params)
            # Reassemble the FULL momentum from the three shards (the
            # trace is the only model-sized state leaf of momentum-sgd).
            full_trace = np.zeros(_TOTAL, np.float32)
            for ddp in ddps:
                tr = next(
                    np.asarray(l)
                    for l in jax.tree_util.tree_leaves(ddp._opt_shard)
                    if getattr(l, "size", 0) > 1
                )
                off = 0
                for s, ln in ddp._shard_meta["ranges"]["float32"]:
                    full_trace[s: s + ln] = tr[off: off + ln]
                    off += ln
            # Positions only the departed member (2) owned restart at 0.
            carried = full_trace.copy()
            for s, ln in ddps[2]._shard_meta["ranges"]["float32"]:
                carried[s: s + ln] = 0.0
            for c in cols3:
                c.shutdown()

            # Member 2 departs; survivors re-form at quorum 2.
            cols2 = _ring(store, 2, "post")

            def resync(r, col):
                mans[r]._col = col
                mans[r].qid = 2
                assert ddps[r].apply_gradients(_grads(r, 1))
                return None

            _run_all(cols2, resync)
            for c in cols2:
                c.shutdown()

            # Survivors hold identical params.
            assert _flat(states[0].params).tobytes() == _flat(
                states[1].params
            ).tobytes()
            # Momentum oracle: replay the post-reshard step on the full
            # vector — init state, graft the carried trace, one update
            # through the SAME jitted apply.
            avg_g2 = (
                _flat(_grads(0, 1)) + _flat(_grads(1, 1))
            ) / 2.0
            oracle_opt = tx.init(jnp.asarray(params_after1))
            o_leaves, o_def = jax.tree_util.tree_flatten(oracle_opt)
            o_leaves = [
                jnp.asarray(carried)
                if getattr(l, "size", 0) == _TOTAL
                else l
                for l in o_leaves
            ]
            oracle_opt = jax.tree_util.tree_unflatten(o_def, o_leaves)
            apply = build_shard_apply_step(tx)
            new_full, new_opt = apply(
                jnp.asarray(params_after1), oracle_opt,
                jnp.asarray(avg_g2),
            )
            np.testing.assert_allclose(
                _flat(states[0].params), np.asarray(new_full),
                rtol=1e-6, atol=1e-6,
            )
            oracle_trace = next(
                np.asarray(l)
                for l in jax.tree_util.tree_leaves(new_opt)
                if getattr(l, "size", 0) == _TOTAL
            )
            for r in (0, 1):
                meta = ddps[r]._shard_meta
                assert meta["quorum_id"] == 2  # re-keyed to the new quorum
                tr = next(
                    np.asarray(l)
                    for l in jax.tree_util.tree_leaves(ddps[r]._opt_shard)
                    if getattr(l, "size", 0) > 1
                )
                expect = np.concatenate(
                    [
                        oracle_trace[s: s + ln]
                        for s, ln in meta["ranges"]["float32"]
                    ]
                )
                np.testing.assert_allclose(
                    tr, expect, rtol=1e-6, atol=1e-6
                )
                # the re-partition re-published the resident footprint
                assert len(mans[r].opt_bytes_reports) == 2
        finally:
            store.shutdown()


class TestHealAndCheckpoint:
    def test_state_dict_roundtrip_voids_meta_and_reshards(self):
        tx = optax.adam(1e-2)
        store = Store()
        try:
            # Uninterrupted solo run: 4 steps.
            (ref, _, _), = _run_sharded(store, 1, "ref", tx, steps=4)

            # Interrupted: 2 steps, checkpoint, restore into a FRESH
            # engine, 2 more steps.
            cols = _ring(store, 1, "ckpt")
            st = FTTrainState(_params(), tx, opt_state=())
            m = _PlanRingManager(cols[0])
            ddp = ShardedDDP(m, st, grad_fn=None)
            for s in range(2):
                assert ddp.apply_gradients(_grads(0, s))
            sd = ddp.state_dict()

            st2 = FTTrainState(_params(), tx, opt_state=())
            m2 = _PlanRingManager(cols[0])
            ddp2 = ShardedDDP(m2, st2, grad_fn=None)
            ddp2.load_state_dict(sd)
            # The heal discipline: meta is voided so the next step takes
            # the re-shard path instead of trusting the donor's quorum.
            assert ddp2._shard_meta["quorum_id"] == -1
            assert ddp2._opt_shard is not None
            for s in range(2, 4):
                assert ddp2.apply_gradients(_grads(0, s))
            assert ddp2._shard_meta["quorum_id"] == 1  # re-keyed
            assert m2.opt_bytes_reports  # reshard republished the bytes
            assert _flat(st2.params).tobytes() == _flat(
                ref.params
            ).tobytes()
            for c in cols:
                c.shutdown()
        finally:
            store.shutdown()

    def test_begin_fresh_shard_drops_state(self):
        tx = optax.adam(1e-2)
        store = Store()
        try:
            (st, ddp, _), = _run_sharded(store, 1, "fresh", tx, steps=1)
            assert ddp._opt_shard is not None
            ddp.begin_fresh_shard()
            assert ddp._opt_shard is None
            assert ddp._shard_meta is None
        finally:
            store.shutdown()


class TestShardedOptimizerWrapper:
    def test_wrapper_matches_engine_bitwise(self):
        tx = optax.adam(1e-2)
        store = Store()
        try:
            ref = _run_sharded(store, 2, "eng", tx, steps=3)
            cols = _ring(store, 2, "wrap")

            def member(r, col):
                st = FTTrainState(_params(), tx, opt_state=())
                m = _PlanRingManager(col)
                opt = ShardedOptimizerWrapper(m, st)
                for s in range(3):
                    opt.zero_grad()
                    assert opt.step(_grads(r, s))
                assert opt.last_commit is True
                assert opt.opt_state_bytes() > 0
                return st, opt

            res = _run_all(cols, member)
            for c in cols:
                c.shutdown()
            for (st, _), (ref_st, _, _) in zip(res, ref):
                assert _flat(st.params).tobytes() == _flat(
                    ref_st.params
                ).tobytes()
        finally:
            store.shutdown()

    def test_wrapper_state_dict_delegates(self):
        st = FTTrainState(_params(), optax.adam(1e-2), opt_state=())
        opt = ShardedOptimizerWrapper(None, st, shard_wire="q8")
        sd = opt.state_dict()
        assert set(sd) == {"state", "opt_shard", "shard_meta"}
        opt.load_state_dict(sd)
        assert opt._core._opt_shard is None


class TestAbortKeepsPreStepState:
    def test_failed_commit_rolls_back(self):
        tx = optax.adam(1e-2)
        store = Store()
        try:
            cols = _ring(store, 2, "abort")

            def member(r, col):
                st = FTTrainState(_params(), tx, opt_state=())
                m = _PlanRingManager(col)
                ddp = ShardedDDP(m, st, grad_fn=None)
                assert ddp.apply_gradients(_grads(r, 0))
                p1 = _flat(st.params)
                opt1 = jax.tree_util.tree_map(
                    np.asarray, ddp._opt_shard
                )
                m.commit = False  # the vote fails: discard the step
                assert not ddp.apply_gradients(_grads(r, 1))
                assert ddp.last_commit is False
                # params AND the optimizer shard keep pre-step values
                assert _flat(st.params).tobytes() == p1.tobytes()
                for a, b in zip(
                    jax.tree_util.tree_leaves(opt1),
                    jax.tree_util.tree_leaves(ddp._opt_shard),
                ):
                    assert np.asarray(a).tobytes() == np.asarray(
                        b
                    ).tobytes()
                return None

            _run_all(cols, member)
            for c in cols:
                c.shutdown()
        finally:
            store.shutdown()
