"""The chaos plane's injection-seam contract, checked end to end.

Four sub-checks:

- **Guarded call sites**: a DISARMED injection point costs exactly one
  relaxed atomic load and a branch — which holds only when every call
  site reaches ``tft_fault_maybe`` through the ``TFT_FAULT_CHECK`` macro
  (native/src/fault.h), never directly. A raw call would pay the
  decision mutex + hash on every frame of every ring op in production.
  Any literal ``tft_fault_maybe`` outside the engine's own files flags.
- **Seam-enum sync**: every seam in ``chaos.py``'s ``NATIVE_SEAMS``
  must have its ``kSeam<CamelCase>`` enumerator in ``fault.h``'s Seam
  enum (a plan arming an unknown seam is silently ignored by the native
  engine), and every enumerator must map back to a seam ``chaos.py``
  knows (native or reserved Python-side) — orphan enumerators are dead
  wiring the next seam author copies.
- **Armed-seam reachability**: every native seam's enumerator must
  appear at a call site outside the engine files — a seam with no
  ``TFT_FAULT_CHECK`` reaching it arms rules that can never fire, and
  every chaos sweep over it silently tests nothing (how the serving/
  durable seams of PRs 17-18 would rot).
- **Kind totality**: ``SEAMS`` and the ``SEAM_KINDS`` vocabulary must
  cover each other exactly (the random plan generator draws kinds per
  seam; a missing entry is a KeyError at fuzz time, an orphan entry is
  a vocabulary nothing can draw).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Optional, Sequence

from . import Violation, relpath

RULE = "fault_guard"

SCAN_DIR = Path("native/src")
# The engine's own files: declaration, definition, and the macro.
ENGINE_FILES = ("fault.h", "fault.cc")
CHAOS_PY = Path("torchft_tpu/chaos.py")
FAULT_H = Path("native/src/fault.h")

_CALL = re.compile(r"\btft_fault_maybe\b")
_ENUMERATOR = re.compile(r"\bkSeam([A-Z]\w*)\s*=")


def _camel(seam: str) -> str:
    return "".join(p.capitalize() for p in seam.split("_"))


def _snake(camel: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", camel).lower()


def _chaos_registry(text: str):
    """(NATIVE_SEAMS, PYTHON_SEAMS, SEAM_KINDS keys) literals from
    chaos.py, any of them None when not statically readable."""
    native = python = kinds = None
    for node in ast.parse(text).body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            targets = [node.target.id]
            value = node.value
        for name in targets:
            try:
                lit = ast.literal_eval(value) if value is not None else None
            except ValueError:
                continue
            if name == "NATIVE_SEAMS":
                native = tuple(lit)
            elif name == "PYTHON_SEAMS":
                python = tuple(lit)
            elif name == "SEAM_KINDS":
                kinds = dict(lit)
    return native, python, kinds


def check(
    root: Path, scan_dir: Optional[Path] = None,
    engine_files: Optional[Sequence[str]] = None,
    chaos_path: Optional[Path] = None,
    fault_h_path: Optional[Path] = None,
) -> List[Violation]:
    base = root / (scan_dir or SCAN_DIR)
    engine = tuple(engine_files or ENGINE_FILES)
    chaos_path = chaos_path or root / CHAOS_PY
    fault_h_path = fault_h_path or root / FAULT_H
    out: List[Violation] = []
    scanned_text: List[str] = []
    if base.exists():
        for path in sorted(base.rglob("*")):
            if path.suffix not in (".cc", ".h"):
                continue
            if path.name in engine:
                continue
            text = path.read_text()
            scanned_text.append(text)
            for m in _CALL.finditer(text):
                line_no = text[: m.start()].count("\n") + 1
                line = text.splitlines()[line_no - 1]
                # TFT_FAULT_CHECK expands to the guarded call; a call
                # site USING the macro never spells tft_fault_maybe
                # itself, so any literal appearance outside the engine
                # is a violation (comments included — a commented recipe
                # showing the raw call is how the next raw call gets
                # written).
                out.append(
                    Violation(
                        RULE,
                        relpath(root, path),
                        line_no,
                        "raw tft_fault_maybe call outside the "
                        "TFT_FAULT_CHECK guard (disarmed fast-path "
                        f"contract): {line.strip()[:80]!r} — route the "
                        "injection point through TFT_FAULT_CHECK "
                        "(native/src/fault.h)",
                    )
                )

    if not (chaos_path.exists() and fault_h_path.exists()):
        return out
    chaos_rel = relpath(root, chaos_path)
    fault_rel = relpath(root, fault_h_path)
    native, python, kinds = _chaos_registry(chaos_path.read_text())
    if native is None or python is None or kinds is None:
        out.append(
            Violation(
                RULE,
                chaos_rel,
                1,
                "NATIVE_SEAMS / PYTHON_SEAMS / SEAM_KINDS are not "
                "statically readable literals",
            )
        )
        return out

    fault_text = fault_h_path.read_text()
    enumerators = {}
    for m in _ENUMERATOR.finditer(fault_text):
        enumerators[m.group(1)] = fault_text[: m.start()].count("\n") + 1

    for seam in native:
        cam = _camel(seam)
        if cam not in enumerators:
            out.append(
                Violation(
                    RULE,
                    fault_rel,
                    1,
                    f"native seam {seam!r} (chaos.py NATIVE_SEAMS) has "
                    f"no kSeam{cam} enumerator in the fault engine: "
                    "plans arming it are silently ignored",
                )
            )
        elif not any(
            f"fault::kSeam{cam}" in t for t in scanned_text
        ):
            out.append(
                Violation(
                    RULE,
                    fault_rel,
                    enumerators[cam],
                    f"native seam {seam!r} has no TFT_FAULT_CHECK call "
                    f"site reaching fault::kSeam{cam}: armed rules can "
                    "never fire, chaos sweeps over it test nothing",
                )
            )
    all_seams = set(native) | set(python)
    for cam, line in enumerators.items():
        if _snake(cam) not in all_seams:
            out.append(
                Violation(
                    RULE,
                    fault_rel,
                    line,
                    f"kSeam{cam} maps to no seam in chaos.py "
                    "(NATIVE_SEAMS + PYTHON_SEAMS): orphan enumerator",
                )
            )
    for seam in all_seams:
        if seam not in kinds or not kinds[seam]:
            out.append(
                Violation(
                    RULE,
                    chaos_rel,
                    1,
                    f"seam {seam!r} has no SEAM_KINDS vocabulary: the "
                    "random plan generator KeyErrors drawing for it",
                )
            )
    for seam in kinds:
        if seam not in all_seams:
            out.append(
                Violation(
                    RULE,
                    chaos_rel,
                    1,
                    f"SEAM_KINDS entry {seam!r} is not a registered "
                    "seam: nothing can draw it",
                )
            )
    return out
