"""Weight-distribution serving plane: zero-copy pub/sub fan-out tree.

ROADMAP item 2 ("the millions-of-users tier"): the training fleet's
OUTPUT becomes a product surface. A trainer-side :class:`WeightPublisher`
publishes version-stamped weight SNAPSHOTS and DELTAS, each packed once
per version into the PR-17 :class:`~torchft_tpu.checkpointing._StreamStaging`
byte-stream layout and then served as CRC-guarded zero-copy byte ranges
over the PR-5 streamed wire contract (``X-TFT-Crc32c`` header per range,
400 on a torn republish — the nonce check). :class:`WeightRelay` nodes
form a fan-out tree below the publisher (the same host -> region -> fleet
shape the quorum map carries): each relay fetches a version ONCE,
CRC-verifies it, caches the verbatim wire bytes, and re-serves them as
byte ranges — never re-encoding, never re-pickling — so publisher egress
per version is independent of subscriber count. Thousands of
:class:`WeightSubscriber` clients hold lease-based sessions against their
serving node (a relay batches its whole downstream population into ONE
upstream lease entry — the PR-7 ``LeaseClient`` batched-renewal
discipline applied to the serving wire) and perform staleness-bounded
reads: every read carries ``(version, age_ms)`` like the region quorum
cache, where ``age_ms`` is computed from LOCAL monotonic time since the
last confirmed-fresh contact plus the upstream-reported age, so a
partitioned relay keeps serving with an HONESTLY growing age instead of
lying about freshness.

Wire formats (``TORCHFT_PS_WIRE``): ``q8`` (default) ships each float
leaf as ``{q: int8, s: f32 scale}`` with the :mod:`torchft_tpu.quantize`
numerics (scale = max|d|/127 floored at 1e-12, round-half-even), packed
device-side by the PR-6 Pallas kernels when the leaf lives on a TPU;
``bf16`` ships a round-to-nearest-even downcast; ``f32``/``none`` ships
raw. Error feedback lives at the PUBLISHER: the publisher tracks the
``served`` tree (the dequantized accumulation of everything it shipped),
deltas are encoded against it, and the served tree advances by the
DECODED delta — so a subscriber that applies every delta holds
byte-identical state to the publisher's served tree. The manifest's
``digest`` (CRC32C over the canonical f32 leaf bytes) proves it at
install time: a digest mismatch is a torn install AVERTED, the
subscriber keeps its previous version.

Late joiners catch up via snapshot+delta: the publisher emits a full
snapshot every ``TORCHFT_PS_SNAPSHOT_EVERY`` versions and retains the
latest snapshot plus everything after it (``TORCHFT_PS_KEEP`` bounds the
total), so a joiner fetches one snapshot and replays the delta chain.

Reference parity: none — upstream torchft's parameter_server.py is a
world-size-2 prototype; this module is the scaled replacement it is
rebuilt on (parameter_server.py keeps the old session API as a shim).
"""

from __future__ import annotations

import argparse
import http.client
import json
import logging
import os
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ._native import crc32c as _crc32c
from ._native import crc32c_update as _crc32c_update
from .checkpointing import (
    _StreamStaging,
    load_packed_meta,
    rebuild_from_packed,
)

logger: logging.Logger = logging.getLogger(__name__)

_DRIP_CHUNK = 1 << 16


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    return int(raw) if raw else default


def advertise_host() -> str:
    """The host peers should dial for this machine's serving endpoints:
    env ``TORCHFT_PS_HOST`` when set (the operator's routable name for a
    machine whose hostname peers may not resolve), else the hostname."""
    return os.environ.get("TORCHFT_PS_HOST", "").strip() or socket.gethostname()


def _url_host(host: str) -> str:
    # bare IPv6 literals need brackets in URLs
    if ":" in host and not host.startswith("["):
        return f"[{host}]"
    return host


def wire_from_env() -> str:
    wire = os.environ.get("TORCHFT_PS_WIRE", "q8").strip().lower() or "q8"
    if wire in ("none", "raw"):
        wire = "f32"
    if wire not in ("q8", "bf16", "f32"):
        raise ValueError(f"unsupported TORCHFT_PS_WIRE: {wire!r}")
    return wire


# -- wire encode / decode ----------------------------------------------------


def _use_device_kernels(leaf: Any) -> bool:
    import sys

    jax = sys.modules.get("jax")
    return (
        jax is not None
        and isinstance(leaf, jax.Array)
        and jax.default_backend() == "tpu"
    )


def _as_f32(leaf: Any) -> np.ndarray:
    arr = np.asarray(leaf)
    if not np.issubdtype(np.asarray(arr).dtype, np.floating):
        raise ValueError(
            "serving plane publishes FLOAT weight trees only; got leaf "
            f"dtype {arr.dtype}"
        )
    return np.ascontiguousarray(arr, dtype=np.float32)


def _q8_encode_leaf(leaf: Any) -> Dict[str, np.ndarray]:
    """Symmetric int8 with the quantize.py numerics. Device-packed by the
    Pallas kernel when the leaf is a TPU-resident jax Array (the PR-6
    path); the numpy oracle otherwise — the two are pinned bit-identical
    by tests/test_device_pack.py."""
    if _use_device_kernels(leaf):
        from .ops.quantize_kernels import quantize_q8

        q, s = quantize_q8(leaf)
        return {
            "q": np.asarray(q),
            "s": np.asarray(s, dtype=np.float32).reshape(()),
        }
    d = _as_f32(leaf)
    if d.size:
        scale = np.float32(max(float(np.max(np.abs(d))) / 127.0, 1e-12))
    else:
        scale = np.float32(1e-12)
    q = np.clip(np.rint(d / scale), -127, 127).astype(np.int8)
    return {"q": q, "s": np.asarray(scale, dtype=np.float32).reshape(())}


def _bf16_encode_leaf(leaf: Any) -> np.ndarray:
    if _use_device_kernels(leaf):
        from .ops.quantize_kernels import cast_bf16

        return np.asarray(cast_bf16(leaf))
    import ml_dtypes

    return _as_f32(leaf).astype(ml_dtypes.bfloat16)


def encode_tree(tree: Any, wire: str) -> Any:
    """Encode a float pytree for the serving wire. ``q8`` leaves become
    ``{"q": int8, "s": f32 scalar}`` sub-dicts; ``bf16`` leaves the
    half-width downcast; ``f32`` a contiguous f32 pull. The encoded tree
    is what :class:`~torchft_tpu.checkpointing._StreamStaging` packs —
    per-subscriber bytes are proportional to THIS tree's size, not the
    f32 size."""
    import jax

    if wire == "q8":
        return jax.tree_util.tree_map(_q8_encode_leaf, tree)
    if wire == "bf16":
        return jax.tree_util.tree_map(_bf16_encode_leaf, tree)
    if wire == "f32":
        return jax.tree_util.tree_map(_as_f32, tree)
    raise ValueError(f"unsupported serving wire: {wire!r}")


def _is_q8_leaf(x: Any) -> bool:
    return (
        isinstance(x, dict)
        and len(x) == 2
        and "q" in x
        and "s" in x
        and isinstance(x.get("q"), np.ndarray)
    )


def decode_tree(enc: Any, wire: str) -> Any:
    """Exact decode of :func:`encode_tree` output back to an f32 numpy
    tree (``q * s`` for q8 — the same arithmetic the ring's dequantize
    kernels pin)."""
    import jax

    if wire == "q8":
        return jax.tree_util.tree_map(
            lambda e: e["q"].astype(np.float32) * e["s"],
            enc,
            is_leaf=_is_q8_leaf,
        )
    if wire == "bf16":
        return jax.tree_util.tree_map(
            lambda a: np.asarray(a).astype(np.float32), enc
        )
    if wire == "f32":
        return jax.tree_util.tree_map(
            lambda a: np.asarray(a, dtype=np.float32), enc
        )
    raise ValueError(f"unsupported serving wire: {wire!r}")


def tree_digest(tree: Any) -> str:
    """CRC32C over the canonical f32 bytes of every leaf in flatten
    order — the install-time proof that a subscriber's accumulated state
    matches the publisher's served tree bit for bit."""
    import jax

    leaves = jax.tree_util.tree_flatten(tree)[0]
    state = _crc32c(b"")
    for leaf in leaves:
        arr = np.ascontiguousarray(leaf, dtype=np.float32)
        state = _crc32c_update(
            state, memoryview(arr.reshape(-1).view(np.uint8))
        )
    return f"{state:08x}"


def _tree_sub(a: Any, b: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(lambda x, y: x - y, a, b)


def _tree_add(a: Any, b: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(lambda x, y: x + y, a, b)


def _tree_f32(tree: Any) -> Any:
    import jax

    return jax.tree_util.tree_map(_as_f32, tree)


def _tree_nbytes(tree: Any) -> int:
    import jax

    return sum(
        int(np.asarray(leaf).nbytes)
        for leaf in jax.tree_util.tree_flatten(tree)[0]
    )


# -- version store -----------------------------------------------------------


class _BytesSource:
    """A relay-held version: the verbatim wire bytes as fetched from
    upstream (CRC already verified). Ranges are memoryview slices — the
    re-serve path never copies, never re-encodes."""

    def __init__(self, payload: bytes) -> None:
        self._view = memoryview(payload)
        self.total = len(payload)

    def write_range(self, wfile: Any, begin: int, end: int) -> None:
        wfile.write(self._view[begin:end])

    def range_crc32c(self, begin: int, end: int) -> int:
        return _crc32c(self._view[begin:end])


class _HeldVersion:
    """One servable version: manifest (JSON-safe dict), the packed-stream
    meta blob, and a range source (a live zero-copy staging on the
    publisher, verbatim cached bytes on a relay)."""

    def __init__(self, manifest: Dict[str, Any], meta: bytes, source: Any) -> None:
        self.manifest = manifest
        self.meta = meta
        self.source = source


class _VersionStore:
    """Versioned map of held versions with long-poll support. Eviction
    keeps the latest snapshot and everything after it (the late-joiner
    catch-up chain must stay intact); older versions are dropped oldest
    first once more than ``keep`` are held."""

    def __init__(self, keep: int) -> None:
        self._keep = max(int(keep), 1)
        self._versions: Dict[int, _HeldVersion] = {}
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._latest = -1
        self._latest_snapshot = -1

    def install(self, held: _HeldVersion) -> None:
        v = int(held.manifest["version"])
        with self._cv:
            self._versions[v] = held
            self._latest = max(self._latest, v)
            if held.manifest["kind"] == "snapshot":
                self._latest_snapshot = max(self._latest_snapshot, v)
            for old in sorted(self._versions):
                if len(self._versions) <= self._keep:
                    break
                if old >= self._latest_snapshot:
                    break
                del self._versions[old]
            self._cv.notify_all()

    def clear(self) -> None:
        """Forget everything (upstream republished from scratch — a
        restarted publisher); waiters wake and re-plan."""
        with self._cv:
            self._versions.clear()
            self._latest = -1
            self._latest_snapshot = -1
            self._cv.notify_all()

    def get(self, version: int) -> Optional[_HeldVersion]:
        with self._lock:
            return self._versions.get(version)

    def latest(self) -> int:
        with self._lock:
            return self._latest

    def latest_snapshot(self) -> int:
        with self._lock:
            return self._latest_snapshot

    def manifests(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                self._versions[v].manifest for v in sorted(self._versions)
            ]

    def wait_newer(self, after: int, timeout_s: float) -> int:
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._latest <= after:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            return self._latest


# -- serving node (the HTTP surface shared by publisher and relay) -----------


class _ServingNode:
    """Shared server-side state: the version store, lease table, egress
    accounting, and the freshness provider the ``age_ms`` fields come
    from. The publisher and every relay each own one."""

    def __init__(self, role: str, keep: int, lease_ttl_ms: int) -> None:
        self.role = role
        self.store = _VersionStore(keep=keep)
        self.lease_ttl_ms = lease_ttl_ms
        self._leases: Dict[str, Tuple[float, int]] = {}
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "egress_bytes": 0,
            "ranges_served": 0,
            "meta_served": 0,
            "status_served": 0,
            "nonce_rejects": 0,
            "lease_renews": 0,
            "publishes": 0,
            "syncs": 0,
            "upstream_errors": 0,
        }
        # Relays override this with their partition-honest computation;
        # a publisher IS the source of truth, so its view is never stale.
        self.age_ms: Callable[[], int] = (
            lambda: 0 if self.store.latest() >= 0 else -1
        )
        self.drip_ms = _env_int("TORCHFT_PS_DRIP_MS", 0)

    def incr(self, key: str, amount: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + amount

    def renew_lease(self, lease_id: str, ttl_ms: int, subs: int) -> int:
        """Register/renew one lease entry. ``subs`` is the BATCH weight:
        a relay covers its whole downstream population with one entry
        (the LeaseClient batched-renewal shape on the serving wire).
        Returns the fleet-wide subscriber total after pruning."""
        now = time.monotonic()
        with self._lock:
            self._leases[lease_id] = (
                now + max(ttl_ms, 1) / 1000.0,
                max(int(subs), 0),
            )
            self.counters["lease_renews"] += 1
            return self._prune_leases_locked(now)

    def drop_lease(self, lease_id: str) -> None:
        with self._lock:
            self._leases.pop(lease_id, None)

    def _prune_leases_locked(self, now: float) -> int:
        for lid in [l for l, (dl, _) in self._leases.items() if dl < now]:
            del self._leases[lid]
        return sum(subs for _, subs in self._leases.values())

    def lease_totals(self) -> Tuple[int, int]:
        """(live lease entries, fleet subscriber total) after pruning."""
        with self._lock:
            total = self._prune_leases_locked(time.monotonic())
            return len(self._leases), total

    def status(self) -> Dict[str, Any]:
        leases, subscribers = self.lease_totals()
        latest = self.store.latest()
        held = self.store.get(latest)
        with self._lock:
            counters = dict(self.counters)
        return {
            "role": self.role,
            "latest": latest,
            "latest_snapshot": self.store.latest_snapshot(),
            "latest_nonce": held.manifest["nonce"] if held else "",
            "age_ms": int(self.age_ms()),
            "leases": leases,
            "subscribers": subscribers,
            "counters": counters,
        }

    def listing(self) -> Dict[str, Any]:
        out = self.status()
        out["versions"] = self.store.manifests()
        return out


def _make_handler(
    node: _ServingNode,
    extra_get: Optional[Callable[[BaseHTTPRequestHandler, str], bool]],
) -> type:
    """The /ps/* GET router. ``extra_get`` lets a host server graft
    additional routes (the parameter-server compat shim) onto the same
    listener; it runs first and returns True when it consumed the
    request."""

    class RequestHandler(BaseHTTPRequestHandler):
        def _send_json(self, obj: Dict[str, Any]) -> None:
            data = (json.dumps(obj) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            node.incr("egress_bytes", len(data))

        def _held_for(
            self, version: int, nonce: str
        ) -> Optional[_HeldVersion]:
            held = node.store.get(version)
            if held is None:
                self.send_error(
                    404, f"unknown or evicted version {version}"
                )
                return None
            if nonce != held.manifest["nonce"]:
                # Torn republish: the version number was reused by a
                # different publish (publisher restart). Serving the
                # bytes would mix two payloads in one subscriber buffer
                # — fail loudly, the client re-plans (the PR-5
                # 400-on-stale-seq contract).
                node.incr("nonce_rejects")
                self.send_error(
                    400,
                    f"stale publish: version {version} serving nonce "
                    f"{held.manifest['nonce']}, range asked for {nonce}",
                )
                return None
            return held

        def do_GET(self) -> None:  # noqa: N802 - stdlib naming
            if extra_get is not None and extra_get(self, self.path):
                return
            parsed = urllib.parse.urlsplit(self.path)
            parts = [p for p in parsed.path.split("/") if p]
            query = urllib.parse.parse_qs(parsed.query)
            try:
                self._route(parts, query)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away mid-body; nothing to answer

        def _route(
            self, parts: List[str], query: Dict[str, List[str]]
        ) -> None:
            if not parts or parts[0] != "ps":
                self.send_error(404, f"invalid path: {self.path}")
                return
            if parts[1:] == ["status"]:
                node.incr("status_served")
                self._send_json(node.status())
                return
            if parts[1:] == ["versions"]:
                node.incr("status_served")
                self._send_json(node.listing())
                return
            if len(parts) == 3 and parts[1] == "wait":
                after = int(parts[2])
                timeout_ms = int(query.get("timeout_ms", ["1000"])[0])
                node.store.wait_newer(
                    after, min(max(timeout_ms, 0), 60_000) / 1000.0
                )
                node.incr("status_served")
                self._send_json(node.status())
                return
            if len(parts) == 3 and parts[1] == "manifest":
                held = node.store.get(int(parts[2]))
                if held is None:
                    self.send_error(404, f"unknown version {parts[2]}")
                    return
                self._send_json(held.manifest)
                return
            if len(parts) == 4 and parts[1] == "meta":
                held = self._held_for(int(parts[2]), parts[3])
                if held is None:
                    return
                self.send_response(200)
                self.send_header(
                    "Content-Type", "application/octet-stream"
                )
                self.send_header("Content-Length", str(len(held.meta)))
                self.end_headers()
                self.wfile.write(held.meta)
                node.incr("meta_served")
                node.incr("egress_bytes", len(held.meta))
                return
            if len(parts) == 6 and parts[1] == "range":
                self._serve_range(
                    int(parts[2]), int(parts[3]), int(parts[4]), parts[5]
                )
                return
            if len(parts) == 5 and parts[1] == "lease":
                lease_id, ttl_ms, subs = (
                    parts[2], int(parts[3]), int(parts[4])
                )
                total = node.renew_lease(lease_id, ttl_ms, subs)
                self._send_json(
                    {"ok": True, "ttl_ms": ttl_ms, "subscribers": total}
                )
                return
            self.send_error(404, f"invalid path: {self.path}")

        def _serve_range(
            self, version: int, i: int, n: int, nonce: str
        ) -> None:
            if n < 1 or not (0 <= i < n):
                self.send_error(404, f"bad range part {i}/{n}")
                return
            held = self._held_for(version, nonce)
            if held is None:
                return
            source = held.source
            begin = source.total * i // n
            end = source.total * (i + 1) // n
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(end - begin))
            # Per-range CRC32C, same polynomial as the ring frames and
            # the heal stream: the subscriber verifies BEFORE the bytes
            # can reach an install.
            self.send_header(
                "X-TFT-Crc32c", f"{source.range_crc32c(begin, end):08x}"
            )
            self.end_headers()
            if node.drip_ms > 0:
                # Chaos/bench throttle: stream the body in small chunks
                # so a publisher SIGKILL reliably lands MID-range.
                pos = begin
                while pos < end:
                    nxt = min(pos + _DRIP_CHUNK, end)
                    source.write_range(self.wfile, pos, nxt)
                    self.wfile.flush()
                    pos = nxt
                    time.sleep(node.drip_ms / 1000.0)
            else:
                source.write_range(self.wfile, begin, end)
            node.incr("ranges_served")
            node.incr("egress_bytes", end - begin)

        def log_message(self, format: str, *args: object) -> None:
            logger.debug(f"serving[{node.role}]: {format % args}")

    return RequestHandler


class ServingServer:
    """IPv6 threaded HTTP server bound to a :class:`_ServingNode`. The
    same listener shape as the checkpoint server (dual-stack ``::``,
    daemon handler threads, deep accept queue for subscriber stampedes)."""

    def __init__(
        self,
        node: _ServingNode,
        port: int = 0,
        extra_get: Optional[
            Callable[[BaseHTTPRequestHandler, str], bool]
        ] = None,
    ) -> None:
        class _Server(ThreadingHTTPServer):
            address_family = socket.AF_INET6
            request_queue_size = 1024
            daemon_threads = True

        self._server = _Server(("::", port), _make_handler(node, extra_get))
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            daemon=True,
            name=f"serving_{node.role}",
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return int(self._server.socket.getsockname()[1])

    def address(self) -> str:
        """Advertised base URL (``TORCHFT_PS_HOST`` honored)."""
        return f"http://{_url_host(advertise_host())}:{self.port}"

    def local_address(self) -> str:
        """Loopback base URL for same-host composition (tests, benches,
        the chaos harness)."""
        return f"http://[::1]:{self.port}"

    def shutdown(self) -> None:
        self._server.shutdown()
        self._thread.join()
        self._server.server_close()


# -- client-side wire --------------------------------------------------------


class WireDetection(Exception):
    """A fetch aborted by an integrity/consistency check BEFORE any
    state was touched: ``kind`` names the detector (``crc``, ``nonce``,
    ``short``, ``gone``, ``digest``, ``gap``). Zero torn installs is the
    plane's invariant; these are the detections that enforce it."""

    def __init__(self, kind: str, detail: str) -> None:
        super().__init__(f"{kind}: {detail}")
        self.kind = kind


def _http_json(url: str, timeout_s: float) -> Dict[str, Any]:
    with urllib.request.urlopen(url, timeout=timeout_s) as f:
        return json.load(f)


def _http_bytes(url: str, timeout_s: float, verify_crc: bool) -> bytes:
    """GET a body, enforcing Content-Length (a publisher killed mid-range
    yields a SHORT body, never a silently truncated install) and the
    per-range ``X-TFT-Crc32c`` header when asked. 400 means the serving
    side refused a stale nonce — surfaced as a ``nonce`` detection."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as f:
            expected = int(f.headers.get("Content-Length", "-1"))
            body = f.read()
            if expected >= 0 and len(body) != expected:
                raise WireDetection(
                    "short",
                    f"{url}: body {len(body)} of {expected} bytes",
                )
            if verify_crc:
                crc_hdr = f.headers.get("X-TFT-Crc32c")
                if crc_hdr is None or int(crc_hdr, 16) != _crc32c(body):
                    raise WireDetection(
                        "crc", f"{url}: range CRC mismatch"
                    )
            return body
    except urllib.error.HTTPError as e:
        if e.code == 400:
            raise WireDetection("nonce", f"{url}: {e.reason}") from e
        if e.code == 404:
            raise WireDetection("gone", f"{url}: {e.reason}") from e
        raise
    except (OSError, http.client.HTTPException) as e:
        # a connection torn mid-body (publisher SIGKILL) lands here;
        # URLError/timeout are OSError subclasses
        raise WireDetection("short", f"{url}: {e}") from e


def _fetch_version(
    base: str,
    manifest: Dict[str, Any],
    streams: int,
    timeout_s: float,
) -> Tuple[bytes, bytes]:
    """Fetch one version's (meta, payload) from a serving node, nonce-
    pinned, range-CRC-verified, then full-payload-CRC-verified against
    the manifest. Any failure raises :class:`WireDetection` with NOTHING
    partially applied."""
    v = int(manifest["version"])
    nonce = manifest["nonce"]
    meta = _http_bytes(
        f"{base}/ps/meta/{v}/{nonce}", timeout_s, verify_crc=False
    )
    if len(meta) != int(manifest["meta_len"]):
        raise WireDetection(
            "short", f"meta for v{v}: {len(meta)} of {manifest['meta_len']}"
        )
    total = int(manifest["total"])
    n = max(1, min(int(streams), 64))
    payload = bytearray(total)
    pos = 0
    for i in range(n):
        chunk = _http_bytes(
            f"{base}/ps/range/{v}/{i}/{n}/{nonce}",
            timeout_s,
            verify_crc=True,
        )
        payload[pos:pos + len(chunk)] = chunk
        pos += len(chunk)
    if pos != total:
        raise WireDetection("short", f"v{v}: {pos} of {total} bytes")
    if _crc32c(memoryview(payload)) != int(manifest["crc"], 16):
        raise WireDetection("crc", f"v{v}: full payload CRC mismatch")
    return meta, bytes(payload)


def _catch_up_plan(
    have: int, manifests: Dict[int, Dict[str, Any]]
) -> List[int]:
    """Versions to fetch, ascending, to go from ``have`` to the latest
    held version: the pure delta chain when every link is present, else
    latest snapshot + its delta suffix (the late-joiner path). Raises a
    ``gap`` detection when neither chain closes — the caller keeps its
    state and retries after the next publish/sync."""
    if not manifests:
        return []
    latest = max(manifests)
    if have >= latest:
        return []
    deltas = list(range(have + 1, latest + 1))
    if have >= 0 and all(
        v in manifests and manifests[v]["kind"] == "delta" for v in deltas
    ):
        return deltas
    snapshots = [
        v for v, m in manifests.items() if m["kind"] == "snapshot"
    ]
    if not snapshots:
        raise WireDetection(
            "gap", f"no snapshot held; have={have} latest={latest}"
        )
    s = max(snapshots)
    chain = list(range(s + 1, latest + 1))
    if not all(
        v in manifests and manifests[v]["kind"] == "delta" for v in chain
    ):
        raise WireDetection(
            "gap", f"broken delta chain after snapshot {s}"
        )
    return [s] + chain


# -- publisher ---------------------------------------------------------------


class WeightPublisher:
    """The root of the fan-out tree: packs each published version ONCE
    into a zero-copy staging and serves it to its direct children
    (relays, or subscribers in a flat deployment). Publish cost is
    amortized per VERSION, never per subscriber.

    Error-feedback delta discipline: ``_served`` is the f32 tree a
    subscriber holds after applying every shipped payload. A delta is
    encoded against it and it advances by the DECODED delta, so
    quantization error feeds back into the next delta instead of
    accumulating downstream — and the manifest ``digest`` of ``_served``
    is exactly what a correct install must hash to."""

    def __init__(
        self,
        port: int = 0,
        wire: Optional[str] = None,
        snapshot_every: Optional[int] = None,
        keep: Optional[int] = None,
        lease_ttl_ms: Optional[int] = None,
        extra_get: Optional[
            Callable[[BaseHTTPRequestHandler, str], bool]
        ] = None,
    ) -> None:
        self.wire = wire if wire is not None else wire_from_env()
        if self.wire not in ("q8", "bf16", "f32"):
            raise ValueError(f"unsupported serving wire: {self.wire!r}")
        self.snapshot_every = max(
            snapshot_every
            if snapshot_every is not None
            else _env_int("TORCHFT_PS_SNAPSHOT_EVERY", 8),
            1,
        )
        self.node = _ServingNode(
            role="publisher",
            keep=(
                keep if keep is not None else _env_int("TORCHFT_PS_KEEP", 16)
            ),
            lease_ttl_ms=(
                lease_ttl_ms
                if lease_ttl_ms is not None
                else _env_int("TORCHFT_PS_LEASE_TTL_MS", 10_000)
            ),
        )
        self.server = ServingServer(self.node, port=port, extra_get=extra_get)
        self._publish_lock = threading.Lock()
        self._served: Any = None
        self._next_version = 0
        logger.info(
            f"WeightPublisher serving on {self.server.address()} "
            f"(wire={self.wire}, snapshot_every={self.snapshot_every})"
        )

    def address(self) -> str:
        return self.server.address()

    def publish(self, params: Any, step: Optional[int] = None) -> Dict[str, Any]:
        """Publish one version of ``params`` (a float pytree; jax or
        numpy leaves). Device-side packing (PR-6 kernels) applies to
        TPU-resident snapshot leaves; everything else rides the numpy
        oracle — bit-identical numerics either way. Returns the
        manifest."""
        with self._publish_lock:
            version = self._next_version
            snapshot = (
                self._served is None or version % self.snapshot_every == 0
            )
            if snapshot:
                f32_nbytes = _tree_nbytes(params)
                enc = encode_tree(params, self.wire)
                self._served = decode_tree(enc, self.wire)
            else:
                current = _tree_f32(params)
                f32_nbytes = _tree_nbytes(current)
                enc = encode_tree(
                    _tree_sub(current, self._served), self.wire
                )
                self._served = _tree_add(
                    self._served, decode_tree(enc, self.wire)
                )
            staging = _StreamStaging(enc, wire=None, snapshot=True)
            manifest = {
                "version": version,
                "kind": "snapshot" if snapshot else "delta",
                "base": None if snapshot else version - 1,
                "wire": self.wire,
                "step": step,
                "total": staging.total,
                "meta_len": len(staging.meta),
                "f32_nbytes": f32_nbytes,
                "crc": f"{staging.range_crc32c(0, staging.total):08x}",
                "digest": tree_digest(self._served),
                "nonce": uuid.uuid4().hex[:16],
            }
            self.node.store.install(
                _HeldVersion(manifest, staging.meta, staging)
            )
            self._next_version = version + 1
            self.node.incr("publishes")
            return manifest

    def status(self) -> Dict[str, Any]:
        return self.node.status()

    def shutdown(self) -> None:
        self.server.shutdown()


def publish_on_commit(
    manager: Any,
    publisher: WeightPublisher,
    params_fn: Callable[[], Any],
    every: int = 1,
) -> None:
    """Wire publish-at-commit: rides ``Manager.add_commit_hook`` so every
    ``every``-th COMMITTED step publishes ``params_fn()`` stamped with
    the step. Commit hooks must not raise; a failed publish is logged by
    the manager and the trainer is unaffected."""
    every = max(int(every), 1)

    def _hook(step: int, quorum_id: int, committed: bool) -> None:
        if committed and step % every == 0:
            publisher.publish(params_fn(), step=step)

    manager.add_commit_hook(_hook)


# -- relay -------------------------------------------------------------------


class WeightRelay:
    """One interior node of the fan-out tree: syncs versions from its
    upstream (publisher or another relay) as VERBATIM wire bytes —
    CRC-verified on the way in, then re-served as zero-copy memoryview
    ranges; the payload is never decoded, re-encoded or re-pickled —
    and fronts its own subscriber population. It renews ONE batched
    lease upstream covering that whole population, so lease traffic at
    the publisher scales with the tree's fan-out, not the fleet size.

    Honest staleness: ``age_ms`` is local monotonic time since the last
    successful upstream sync PLUS the age the upstream reported then —
    no cross-host clocks involved. A partitioned relay (or a dead
    publisher) keeps serving its held versions while that age grows."""

    def __init__(
        self,
        upstream: str,
        port: int = 0,
        keep: Optional[int] = None,
        lease_ttl_ms: Optional[int] = None,
        streams: Optional[int] = None,
        poll_timeout_ms: int = 1000,
        timeout_s: float = 20.0,
        name: Optional[str] = None,
    ) -> None:
        self.upstream = upstream.rstrip("/")
        self.streams = (
            streams if streams is not None else _env_int("TORCHFT_PS_STREAMS", 2)
        )
        self._poll_timeout_ms = poll_timeout_ms
        self._timeout_s = timeout_s
        self.name = name or f"relay-{uuid.uuid4().hex[:8]}"
        self.node = _ServingNode(
            role="relay",
            keep=(
                keep if keep is not None else _env_int("TORCHFT_PS_KEEP", 16)
            ),
            lease_ttl_ms=(
                lease_ttl_ms
                if lease_ttl_ms is not None
                else _env_int("TORCHFT_PS_LEASE_TTL_MS", 10_000)
            ),
        )
        self.node.age_ms = self._age_ms
        self.server = ServingServer(self.node, port=port)
        self._fresh_lock = threading.Lock()
        self._fresh_mono: Optional[float] = None
        self._fresh_upstream_age = 0
        self._partitioned = False
        self._lease_due = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def address(self) -> str:
        return self.server.address()

    def _age_ms(self) -> int:
        with self._fresh_lock:
            if self._fresh_mono is None:
                return -1
            return int(
                (time.monotonic() - self._fresh_mono) * 1000.0
                + max(self._fresh_upstream_age, 0)
            )

    def set_partitioned(self, flag: bool) -> None:
        """Chaos seam: a partitioned relay stops reaching upstream (every
        sync attempt fails as if the link were cut) but keeps serving —
        its ``age_ms`` grows honestly until the partition lifts."""
        self._partitioned = flag

    def sync_once(self) -> bool:
        """One upstream sync: list versions, fetch what is missing
        (verbatim, integrity-checked), refresh the freshness clock.
        Returns True when anything new was installed. Raises
        :class:`WireDetection`/:class:`OSError` on an unreachable or
        torn upstream — the caller's loop counts and retries."""
        if self._partitioned:
            raise WireDetection("gone", f"{self.name}: partitioned")
        listing = _http_json(
            f"{self.upstream}/ps/versions", self._timeout_s
        )
        manifests = {
            int(m["version"]): m for m in listing.get("versions", [])
        }
        mine = self.node.store.latest()
        up_latest = int(listing.get("latest", -1))
        if manifests and mine >= 0:
            held = self.node.store.get(mine)
            stale_nonce = (
                mine in manifests
                and held is not None
                and manifests[mine]["nonce"] != held.manifest["nonce"]
            )
            if up_latest < mine or stale_nonce:
                # Upstream republished from scratch (publisher restart):
                # our chain no longer extends theirs. Drop and resync
                # from their snapshot; downstream subscribers re-plan
                # the same way off our listing.
                logger.info(
                    f"{self.name}: upstream regression "
                    f"(mine={mine}, upstream={up_latest}); resyncing"
                )
                self.node.store.clear()
                mine = -1
        progressed = False
        for v in _catch_up_plan(mine, manifests):
            m = manifests[v]
            meta, payload = _fetch_version(
                self.upstream, m, self.streams, self._timeout_s
            )
            self.node.store.install(
                _HeldVersion(dict(m), meta, _BytesSource(payload))
            )
            self.node.incr("syncs")
            progressed = True
        if self.node.store.latest() >= up_latest:
            with self._fresh_lock:
                self._fresh_mono = time.monotonic()
                self._fresh_upstream_age = int(listing.get("age_ms", 0))
        return progressed

    def _renew_upstream_lease(self) -> None:
        now = time.monotonic()
        if now < self._lease_due:
            return
        ttl = self.node.lease_ttl_ms
        _, subs = self.node.lease_totals()
        _http_json(
            f"{self.upstream}/ps/lease/{self.name}/{ttl}/{max(subs, 1)}",
            self._timeout_s,
        )
        self._lease_due = now + ttl / 3000.0

    def _run(self) -> None:
        backoff = 0.05
        while not self._stop.is_set():
            try:
                self.sync_once()
                self._renew_upstream_lease()
                backoff = 0.05
                # idle until upstream advances past what we hold
                _http_json(
                    f"{self.upstream}/ps/wait/{self.node.store.latest()}"
                    f"?timeout_ms={self._poll_timeout_ms}",
                    self._timeout_s + self._poll_timeout_ms / 1000.0,
                )
            except (WireDetection, OSError, ValueError) as e:
                self.node.incr("upstream_errors")
                logger.debug(f"{self.name}: upstream sync failed: {e}")
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 0.5)

    def start(self) -> "WeightRelay":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=self.name
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        self.server.shutdown()


# -- subscriber --------------------------------------------------------------


class StaleWeightsError(Exception):
    """A staleness-bounded read found weights older than the caller's
    ``max_age_ms`` bound (or no weights at all)."""


class WeightSubscriber:
    """One inference client: holds a lease with its serving node, polls
    for new versions, and installs them with the full integrity ladder
    (range CRC -> full payload CRC -> nonce pinning -> post-install tree
    digest). An install is all-or-nothing: every detection leaves the
    previously installed version untouched, so a publisher death
    mid-range can NEVER corrupt this subscriber.

    Reads are staleness-bounded: :meth:`current` returns
    ``(version, tree, age_ms)`` and raises :class:`StaleWeightsError`
    when the honest age exceeds the caller's bound."""

    def __init__(
        self,
        address: str,
        streams: Optional[int] = None,
        lease_ttl_ms: Optional[int] = None,
        max_age_ms: Optional[int] = None,
        timeout_s: float = 20.0,
        name: Optional[str] = None,
    ) -> None:
        self.base = address.rstrip("/")
        self.streams = (
            streams if streams is not None else _env_int("TORCHFT_PS_STREAMS", 2)
        )
        self.lease_ttl_ms = (
            lease_ttl_ms
            if lease_ttl_ms is not None
            else _env_int("TORCHFT_PS_LEASE_TTL_MS", 10_000)
        )
        self.max_age_ms = (
            max_age_ms
            if max_age_ms is not None
            else _env_int("TORCHFT_PS_MAX_AGE_MS", 0)
        )
        self._timeout_s = timeout_s
        self.name = name or f"sub-{uuid.uuid4().hex[:8]}"
        self._lock = threading.Lock()
        self._tree: Any = None
        self._version = -1
        self._fresh_mono: Optional[float] = None
        self._fresh_upstream_age = 0
        self._lease_due = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats: Dict[str, int] = {
            "bytes_fetched": 0,
            "installs": 0,
            "snapshot_installs": 0,
            "delta_installs": 0,
            "catch_up_deltas": 0,
            "torn_installs": 0,
            "detect_crc": 0,
            "detect_nonce": 0,
            "detect_short": 0,
            "detect_gone": 0,
            "detect_digest": 0,
            "detect_gap": 0,
        }

    # -- read side --

    def version(self) -> int:
        with self._lock:
            return self._version

    def age_ms(self) -> int:
        with self._lock:
            if self._fresh_mono is None:
                return -1
            return int(
                (time.monotonic() - self._fresh_mono) * 1000.0
                + max(self._fresh_upstream_age, 0)
            )

    def current(
        self, max_age_ms: Optional[int] = None
    ) -> Tuple[int, Any, int]:
        """``(version, f32 tree, age_ms)`` of the installed weights.
        ``max_age_ms`` (default the instance/env bound; 0 = unbounded)
        raises :class:`StaleWeightsError` on an over-age read."""
        bound = self.max_age_ms if max_age_ms is None else max_age_ms
        with self._lock:
            if self._tree is None:
                raise StaleWeightsError(f"{self.name}: no weights installed")
            age = (
                int(
                    (time.monotonic() - self._fresh_mono) * 1000.0
                    + max(self._fresh_upstream_age, 0)
                )
                if self._fresh_mono is not None
                else -1
            )
            if bound and (age < 0 or age > bound):
                raise StaleWeightsError(
                    f"{self.name}: weights age {age}ms exceeds bound "
                    f"{bound}ms (version {self._version})"
                )
            return self._version, self._tree, age

    # -- sync side --

    def _renew_lease(self) -> None:
        now = time.monotonic()
        if now < self._lease_due:
            return
        try:
            _http_json(
                f"{self.base}/ps/lease/{self.name}/{self.lease_ttl_ms}/1",
                self._timeout_s,
            )
        except (OSError, ValueError):
            pass  # advisory; the next poll retries
        self._lease_due = now + self.lease_ttl_ms / 3000.0

    def _detect(self, kind: str) -> None:
        self.stats[f"detect_{kind}"] = self.stats.get(f"detect_{kind}", 0) + 1

    def poll(self, wait_timeout_ms: int = 0) -> bool:
        """One sync step: renew the lease, check the serving node, catch
        up to its latest version. ``wait_timeout_ms`` long-polls when
        already current. Returns True when a new version was installed;
        False on no-news or on a DETECTED-and-averted failure (state
        untouched either way)."""
        self._renew_lease()
        have = self.version()
        try:
            if wait_timeout_ms > 0:
                listing = _http_json(
                    f"{self.base}/ps/wait/{have}"
                    f"?timeout_ms={wait_timeout_ms}",
                    self._timeout_s + wait_timeout_ms / 1000.0,
                )
                if int(listing.get("latest", -1)) > have:
                    listing = _http_json(
                        f"{self.base}/ps/versions", self._timeout_s
                    )
            else:
                listing = _http_json(
                    f"{self.base}/ps/versions", self._timeout_s
                )
        except (OSError, ValueError):
            self._detect("gone")
            return False
        manifests = {
            int(m["version"]): m for m in listing.get("versions", [])
        }
        up_latest = int(listing.get("latest", -1))
        if have >= 0 and up_latest < have:
            # publisher restarted below our version: our chain is dead;
            # restart from its snapshot (state stays until the new chain
            # fully verifies)
            have = -1
        try:
            plan = _catch_up_plan(have, manifests)
        except WireDetection as e:
            self._detect(e.kind)
            return False
        if not plan:
            if up_latest >= 0 and up_latest == self.version():
                with self._lock:
                    self._fresh_mono = time.monotonic()
                    self._fresh_upstream_age = int(
                        listing.get("age_ms", 0)
                    )
            return False
        # Build the candidate tree off to the side; swap only after the
        # WHOLE chain decodes and the final digest matches.
        if manifests[plan[0]]["kind"] == "snapshot":
            work = None
        else:
            with self._lock:
                work = self._tree
        fetched_bytes = 0
        try:
            for v in plan:
                m = manifests[v]
                meta_raw, payload = _fetch_version(
                    self.base, m, self.streams, self._timeout_s
                )
                fetched_bytes += len(meta_raw) + len(payload)
                enc = rebuild_from_packed(load_packed_meta(meta_raw), payload)
                dec = decode_tree(enc, m["wire"])
                if m["kind"] == "snapshot":
                    work = dec
                else:
                    if work is None:
                        raise WireDetection(
                            "gap", f"delta v{v} with no base installed"
                        )
                    work = _tree_add(work, dec)
        except WireDetection as e:
            self._detect(e.kind)
            return False
        final = manifests[plan[-1]]
        if tree_digest(work) != final["digest"]:
            # the ladder below caught nothing but the end state is wrong
            # — a torn install AVERTED at the last gate
            self._detect("digest")
            return False
        deltas = sum(1 for v in plan if manifests[v]["kind"] == "delta")
        with self._lock:
            self._tree = work
            self._version = int(final["version"])
            self._fresh_mono = time.monotonic()
            self._fresh_upstream_age = int(listing.get("age_ms", 0))
            self.stats["bytes_fetched"] += fetched_bytes
            self.stats["installs"] += 1
            if manifests[plan[0]]["kind"] == "snapshot":
                self.stats["snapshot_installs"] += 1
            if deltas:
                self.stats["delta_installs"] += 1
                self.stats["catch_up_deltas"] += deltas
        return True

    def wait_version(self, version: int, timeout_s: float) -> bool:
        """Polls until at least ``version`` is installed; True on
        success within the deadline."""
        deadline = time.monotonic() + timeout_s
        while self.version() < version:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            self.poll(wait_timeout_ms=int(min(remaining, 1.0) * 1000))
        return True

    def _run(self, poll_ms: int) -> None:
        while not self._stop.is_set():
            try:
                self.poll(wait_timeout_ms=poll_ms)
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                logger.debug(f"{self.name}: poll failed: {e}")
                self._stop.wait(0.05)

    def start(self, poll_ms: int = 1000) -> "WeightSubscriber":
        self._thread = threading.Thread(
            target=self._run, args=(poll_ms,), daemon=True, name=self.name
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        try:
            _http_json(
                f"{self.base}/ps/lease/{self.name}/1/0", self._timeout_s
            )
        except (OSError, ValueError):
            pass


# -- demo publisher process (chaos / bench harness) --------------------------


def demo_params(seed: int, leaves: int, elems: int, version: int) -> Any:
    """Deterministic weight tree for harness publishers: a seeded base
    walked by a seeded step, so any process at any time can recompute
    the exact tree version ``v`` published — a respawned publisher
    starts a fresh version history (new nonces) over the same weights,
    which is exactly the torn-republish case the nonce check guards."""
    base_rng = np.random.default_rng(seed)
    step_rng = np.random.default_rng(seed + 1)
    tree = {}
    for i in range(leaves):
        base = base_rng.standard_normal(elems).astype(np.float32)
        step = step_rng.standard_normal(elems).astype(np.float32)
        tree[f"layer{i}"] = base + np.float32(0.01 * version) * step
    return tree


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m torchft_tpu.serving``: a standalone demo publisher —
    the subprocess the chaos harness SIGKILLs mid-range and the bench's
    out-of-process root."""
    parser = argparse.ArgumentParser(description="torchft_tpu demo weight publisher")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--wire", default=None, choices=("q8", "bf16", "f32"))
    parser.add_argument("--leaves", type=int, default=4)
    parser.add_argument("--elems", type=int, default=16384)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--versions", type=int, default=0,
                        help="publishes before lingering (0 = forever)")
    parser.add_argument("--publish-every-ms", type=int, default=250)
    parser.add_argument("--snapshot-every", type=int, default=None)
    parser.add_argument("--keep", type=int, default=None)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    pub = WeightPublisher(
        port=args.port,
        wire=args.wire,
        snapshot_every=args.snapshot_every,
        keep=args.keep,
    )
    print(f"serving {pub.address()} port={pub.server.port}", flush=True)
    version = 0
    try:
        while True:
            if args.versions <= 0 or version < args.versions:
                pub.publish(
                    demo_params(args.seed, args.leaves, args.elems, version),
                    step=version,
                )
                version += 1
            time.sleep(args.publish_every_ms / 1000.0)
    except KeyboardInterrupt:
        pass
    finally:
        pub.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
