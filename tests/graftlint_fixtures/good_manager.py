# graftlint fixture: the latch discipline done right (clean-pass control).


class Manager:
    def __init__(self, collectives, iso_collectives=None):
        self._collectives = collectives
        self._iso_collectives = iso_collectives
        self._errored = None

    def allreduce(self, tree, op="avg"):
        if op not in ("avg", "sum"):
            # Eager static-usage error: allowed.
            raise ValueError(f"unsupported op: {op}")

        def dispatch(t):
            return self._collectives.allreduce(t)

        return self._managed_dispatch("allreduce", tree, dispatch)

    def iso_allreduce(self, tree):
        if tree is None:
            # Eager static-usage error: allowed.
            raise ValueError("tree required")

        def dispatch(t):
            if self._errored:
                # Runs under _managed_dispatch's try, so raising here IS
                # latching — the rule must not flag it.
                raise RuntimeError("isolated plane unusable this quorum")
            return self._iso_collectives.allreduce(t)

        return self._managed_dispatch("iso_allreduce", tree, dispatch)

    def _managed_dispatch(self, op_name, tree, dispatch):
        try:
            return dispatch(tree)
        except Exception as e:
            self.report_error(e)
            return None

    def report_error(self, e):
        self._errored = e
