// Rendezvous key-value store: the role c10d TCPStore plays in the reference
// (reference torchft/manager.py:170-211 wires one per replica group; the
// collectives layer namespaces keys per quorum like
// torchft/process_group.py:81-99). set / blocking get / atomic add.
#pragma once

#include <atomic>
#include <map>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "conn_pool.h"
#include "conn_tracker.h"
#include "net.h"
#include "thread_annotations.h"

namespace tft {

class StoreServer {
 public:
  explicit StoreServer(const std::string& bind_addr);
  ~StoreServer();

  uint16_t port() const;
  std::string address() const; // "host:port"
  void shutdown();

 private:
  void serve();
  void handle_conn(Socket& sock);

  std::unique_ptr<Listener> listener_;
  std::string hostname_;

  Mutex mu_;
  CondVar cv_;
  std::map<std::string, std::string> data_ TFT_GUARDED_BY(mu_);
  std::atomic<bool> shutting_down_{false};

  std::thread accept_thread_;
  ConnTracker conns_;
};

// Thread-safe client over pooled persistent connections (a blocking get on
// one thread must not stall sets from another).
class StoreClient {
 public:
  StoreClient(const std::string& addr, int64_t connect_timeout_ms);

  void set(const std::string& key, const std::string& value, int64_t timeout_ms);
  // Blocks until the key exists (timeout_ms < 0: forever). Throws
  // TimeoutError on deadline.
  std::string get(const std::string& key, int64_t timeout_ms);
  int64_t add(const std::string& key, int64_t delta, int64_t timeout_ms);

 private:
  template <typename Req, typename Resp>
  Resp roundtrip(uint8_t req_type, const Req& req, uint8_t resp_type,
                 int64_t timeout_ms);

  ConnPool pool_;
};

} // namespace tft
