"""Lease-based membership: pure semantics, backoff schedule, status view.

The lease layer generalizes heartbeats (a heartbeat is a lease of
``heartbeat_timeout_ms``), adds batched renewal + explicit departs, and
replaces the fixed-interval heartbeat hammer with jittered renewal +
exponential backoff. Pure functions are driven through the JSON C-API entry
points (torchft_tpu._native); live-server behavior through Lighthouse +
LeaseClient.
"""

import threading
import time
from datetime import timedelta

import pytest

from torchft_tpu import _native
from torchft_tpu._native import (
    LeaseClient,
    Lighthouse,
    backoff_ms,
    depart_apply,
    jittered_interval_ms,
    lease_apply,
    quorum_compute,
    quorum_step,
)
from torchft_tpu.lighthouse import fetch_status


def member(replica_id, step=1, **kw):
    m = {
        "replica_id": replica_id,
        "address": f"addr_{replica_id}",
        "store_address": f"store_{replica_id}",
        "step": step,
        "world_size": 1,
        "shrink_only": False,
        "force_reconfigure": False,
    }
    m.update(kw)
    return m


def entry(replica_id, ttl_ms=0, participating=False, **kw):
    return {
        "replica_id": replica_id,
        "ttl_ms": ttl_ms,
        "participating": participating,
        "member": member(replica_id, **kw),
    }


def opts(min_replicas=1, join_timeout_ms=0, heartbeat_timeout_ms=5000):
    return {
        "min_replicas": min_replicas,
        "join_timeout_ms": join_timeout_ms,
        "quorum_tick_ms": 10,
        "heartbeat_timeout_ms": heartbeat_timeout_ms,
    }


EMPTY = {
    "participants": {},
    "heartbeats": {},
    "lease_ttls": {},
    "prev_quorum": None,
    "quorum_id": 0,
}


class TestBackoffSchedule:
    def test_deterministic(self):
        for f in range(1, 8):
            assert backoff_ms(f, 100, 10000, 42) == backoff_ms(f, 100, 10000, 42)

    def test_zero_failures_no_delay(self):
        assert backoff_ms(0, 100, 10000, 1) == 0
        assert backoff_ms(-3, 100, 10000, 1) == 0

    def test_exponential_growth_and_cap(self):
        # Jitter is +-50%, so compare against the raw exponential envelope:
        # every delay for failure k lies in [0.5, 1.5) * min(base*2^(k-1), max)
        # and never exceeds max.
        base, cap = 100, 10000
        for seed in range(20):
            for f in range(1, 12):
                raw = min(base * 2 ** (f - 1), cap)
                d = backoff_ms(f, base, cap, seed)
                assert 0.5 * raw <= d <= cap, (seed, f, d, raw)
                assert d <= 1.5 * raw, (seed, f, d, raw)

    def test_overflow_immune(self):
        # 1000 consecutive failures must still yield a sane capped delay.
        d = backoff_ms(1000, 100, 10000, 7)
        assert 0 < d <= 10000

    def test_jitter_spreads_seeds(self):
        # The whole point: different groups (seeds) retry at different times.
        delays = {backoff_ms(3, 100, 10000, seed) for seed in range(50)}
        assert len(delays) > 25

    def test_interval_jitter_bounds(self):
        for seed in range(10):
            for tick in range(10):
                d = jittered_interval_ms(1000, seed, tick)
                assert 750 <= d < 1250
        # and it actually varies across ticks
        assert len({jittered_interval_ms(1000, 1, t) for t in range(20)}) > 5


class TestLeaseSemantics:
    def test_renewal_grants_ttl(self):
        s = lease_apply(EMPTY, [entry("a", ttl_ms=2000)], now_ms=1000)
        assert s["heartbeats"]["a"] == 1000
        assert s["lease_ttls"]["a"] == 2000
        o = opts()
        # alive until grant + ttl, not grant + heartbeat_timeout
        assert quorum_compute(2999, s, o)["reason"].count("[1 heartbeating]")
        assert "[0 heartbeating]" in quorum_compute(3000, s, o)["reason"]

    def test_default_ttl_is_heartbeat_timeout(self):
        s = lease_apply(EMPTY, [entry("a", ttl_ms=0)], now_ms=0)
        assert "a" not in s["lease_ttls"]
        o = opts(heartbeat_timeout_ms=5000)
        assert "[1 heartbeating]" in quorum_compute(4999, s, o)["reason"]
        assert "[0 heartbeating]" in quorum_compute(5000, s, o)["reason"]

    def test_participating_registers(self):
        s = lease_apply(EMPTY, [entry("a", ttl_ms=1000, participating=True)], 5)
        assert s["participants"]["a"]["joined_ms"] == 5
        r = quorum_step(10, 10, s, opts())
        assert r["quorum"] is not None
        assert [m["replica_id"] for m in r["quorum"]["participants"]] == ["a"]
        assert r["changed"] and r["quorum"]["quorum_id"] == 1

    def test_renewal_preserves_joined_ms(self):
        # The join-timeout clock must not be reset by every renewal, or a
        # straggler wait could never elapse under steady renewal traffic.
        s = lease_apply(EMPTY, [entry("a", ttl_ms=1000, participating=True)], 5)
        s = lease_apply(s, [entry("a", ttl_ms=1000, participating=True)], 500)
        assert s["participants"]["a"]["joined_ms"] == 5
        assert s["heartbeats"]["a"] == 500

    def test_expiry_vs_explicit_depart(self):
        # Lease expiry: the member stays healthy until its TTL runs out.
        # Explicit depart: gone immediately, including its participant slot.
        o = opts()
        s = lease_apply(
            EMPTY,
            [entry("a", 1000, True), entry("b", 1000, True)],
            now_ms=0,
        )
        r = quorum_step(10, 10, s, o)
        assert len(r["quorum"]["participants"]) == 2

        # b silently dies: still in quorums until t=1000
        s = lease_apply(r["state"], [entry("a", 1000, True), entry("b", 1000, True)], 20)
        r_mid = quorum_step(999, 999, dict(s), o)
        assert len(r_mid["quorum"]["participants"]) == 2
        # ... but a's renewals keep it alive past b's expiry
        s2 = lease_apply(dict(s), [entry("a", 1000, True)], 900)
        r_exp = quorum_step(1100, 1100, s2, o)
        assert [m["replica_id"] for m in r_exp["quorum"]["participants"]] == ["a"]
        assert r_exp["changed"]

        # explicit depart removes b IMMEDIATELY (no TTL wait)
        s3 = lease_apply(
            r["state"], [entry("a", 1000, True), entry("b", 1000, True)], 20
        )
        s3 = depart_apply(s3, "b")
        assert "b" not in s3["heartbeats"] and "b" not in s3["participants"]
        r_dep = quorum_step(30, 30, s3, o)
        assert [m["replica_id"] for m in r_dep["quorum"]["participants"]] == ["a"]

    def test_prune_keeps_output_invariant(self):
        # Members dead >= 10 TTLs are pruned from state, and pruning never
        # changes the quorum output (they were unhealthy either way).
        s = lease_apply(EMPTY, [entry("dead", 100), entry("live", 100, True)], 0)
        s = lease_apply(s, [entry("live", 100, True)], 2000)
        r = quorum_step(2050, 2050, s, opts())
        assert "dead" not in r["state"]["heartbeats"]
        assert [m["replica_id"] for m in r["quorum"]["participants"]] == ["live"]


class TestLiveLeases:
    def test_batch_renew_forms_quorum(self):
        with Lighthouse(min_replicas=1, join_timeout_ms=100) as lh:
            c = LeaseClient(lh.address())
            qid = c.renew(
                [entry("g0", 2000, True), entry("g1", 2000, True)],
                timeout=timedelta(seconds=10),
            )
            assert qid == 1
            st = lh.status_json()
            assert st["quorum_id"] == 1
            got = sorted(
                m["replica_id"] for m in st["quorum"]["participants"]
            )
            assert got == ["g0", "g1"]

    def test_status_json_fields(self):
        with Lighthouse(min_replicas=1, join_timeout_ms=100) as lh:
            c = LeaseClient(lh.address())
            c.renew([entry("g0", 3000, True)])
            st = lh.status_json()
            assert st["role"] == "flat"
            assert st["quorum_id"] == 1
            (m,) = st["members"]
            assert m["replica_id"] == "g0"
            assert m["ttl_ms"] == 3000
            assert 0 < m["lease_remaining_ms"] <= 3000
            assert {"total", "computed", "last_compute_us"} <= set(st["tick"])
            assert st["regions"] == []
            assert isinstance(st["open_conns"], int)

    def test_status_json_over_http_matches(self):
        # The satellite contract: the JSON view is served NEXT TO the HTML
        # dashboard and is what bench_lighthouse consumes.
        with Lighthouse(min_replicas=1, join_timeout_ms=100) as lh:
            c = LeaseClient(lh.address())
            c.renew([entry("g0", 3000, True)])
            st = fetch_status(lh.address())
            assert st["role"] == "flat" and st["quorum_id"] == 1
            assert st["members"][0]["replica_id"] == "g0"

    def test_depart_removes_immediately(self):
        with Lighthouse(min_replicas=1, join_timeout_ms=100) as lh:
            c = LeaseClient(lh.address())
            c.renew([entry("g0", 60000, True), entry("g1", 60000, True)])
            c.depart("g1")
            st = lh.status_json()
            assert [m["replica_id"] for m in st["members"]] == ["g0"]

    def test_idle_ticks_skip_compute(self):
        # Between quorum rounds (no registered participants) the tick loop
        # must not rescan membership — that is the lease replacement for the
        # O(groups)-per-tick heartbeat scan.
        with Lighthouse(
            min_replicas=1, join_timeout_ms=100, quorum_tick_ms=20
        ) as lh:
            c = LeaseClient(lh.address())
            c.renew([entry("g0", 60000, True)])  # quorum forms, participants clear
            deadline = time.monotonic() + 5
            while lh.status_json()["quorum_id"] != 1:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            t0 = lh.status_json()["tick"]
            time.sleep(0.5)
            t1 = lh.status_json()["tick"]
            assert t1["total"] - t0["total"] >= 10  # loop kept running
            assert t1["computed"] - t0["computed"] <= 1  # but did ~no scans

    def test_heartbeat_and_renew_share_connection(self):
        with Lighthouse(min_replicas=1, join_timeout_ms=100) as lh:
            c = LeaseClient(lh.address())
            c.heartbeat("hb-only")
            c.renew([entry("g0", 2000)])
            st = lh.status_json()
            ids = sorted(m["replica_id"] for m in st["members"])
            assert ids == ["g0", "hb-only"]
            # one persistent connection for all three verbs
            c.depart("g0")


class TestManagerBackoffIntegration:
    def test_dead_lighthouse_not_hammered(self):
        # A manager whose lighthouse dies must space its renewal attempts
        # out exponentially. We can't intercept the native loop directly, so
        # assert the schedule contract the loop is built on plus the
        # manager's survival: it keeps serving while renewals back off.
        lh = Lighthouse(min_replicas=1, join_timeout_ms=100)
        addr = lh.address()
        m = _native.Manager(
            "bk", addr, "localhost", "[::]:0", "127.0.0.1:1", 1,
            heartbeat_interval=timedelta(milliseconds=50),
            connect_timeout=timedelta(seconds=5),
        )
        lh.shutdown()
        time.sleep(0.6)  # several failed renewals' worth
        # still alive and shut down cleanly (no wedge in the backoff path)
        m.shutdown()
