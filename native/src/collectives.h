// Host-side collective communication over TCP: the role Gloo plays in the
// reference (reference torchft/process_group.py:282-296 ProcessGroupGloo and
// the reconfigure discipline of process_group.py:238-254).
//
// Design for the TPU build: cross-replica-group traffic stays OUTSIDE XLA
// (host-side sockets), so a dead peer surfaces as a socket error on an
// abortable fd instead of a wedged ICI collective — the property the
// reference gets from subprocess-isolated NCCL ("Baby" PGs,
// process_group.py:551-1064). Intra-group collectives are XLA's job (pjit
// over the slice mesh); this class only ever spans replica groups.
//
// Topology: a ring, STRIPED over N parallel TCP connections per neighbor
// edge. configure() rendezvouses through the Store (the caller passes
// "host:port/prefix" where prefix is unique per quorum, mirroring
// manager.py:470-477), each rank listens on an ephemeral port, dials rank+1
// `stripes` times and accepts `stripes` connections from rank-1 (the hello
// carries the stripe index, so accept order never matters). Every bulk op
// splits its payload into `stripes` contiguous sub-ranges; stripe s runs the
// full ring schedule over its own sub-range on its own connection pair, on
// its own thread. A single TCP connection is window-limited on
// high-bandwidth-delay paths (the DCN/tunneled links these collectives
// actually cross), so striping multiplies achievable throughput the way
// NCCL channels or multi-stream object fetches do.
//
// HIERARCHICAL TOPOLOGY (configure with a region and/or host map): on a
// fleet spanning regions, the flat ring makes every member push
// 2*(W-1)/W*N bytes across whatever link its neighbor happens to sit
// behind — on a topology-oblivious placement that is the slow inter-region
// (DCN) path for every edge. With a region label per rank, configure()
// additionally builds
//   - an INTRA ring per region (the member's region peers, rank order), and
//   - an INTER ring among one deterministic LEADER per region (the lowest
//     rank — i.e. lowest replica-id, since ranks sort by replica-id — with
//     regions ordered by their leader's rank),
// and allreduce_hier() runs the hierarchical schedule
//   intra reduce-scatter -> intra allgather (delivers the full region sum to
//   the leader; on a ring, gather-to-one costs the same edges as
//   gather-to-all) -> inter ring allreduce among leaders (the only bytes on
//   the slow links: (L-1)/L*N sent per leader per phase, L = region count)
//   -> chunk-pipelined intra broadcast of the leader's result.
// Every phase reuses the SAME rs/ag stripe bodies as the flat ring, so the
// schedule is composed from proven pieces; all members of a region adopt the
// leader's bytes verbatim and leaders are bit-identical by ring determinism,
// so results are bit-identical across ALL members and across runs. The sum
// ORDER differs from the flat ring (documented; tolerance-class equal).
//
// THIRD TIER — the HOST ring (configure with a host map): members sharing
// a (region, host) label pair are co-resident processes; pushing their
// ring bytes through loopback TCP costs two kernel copies plus syscalls
// per chunk. configure() groups them into a HOST ring below the intra
// tier, carried over POSIX shared-memory ring buffers (one SPSC ring per
// directed edge per stripe, tft_shm_* segments, futex doorbells) — a
// single memcpy per hop instead of a socket round trip. The schedule
// grows to
//   host reduce-scatter -> host allgather (the HOST leader — lowest rank
//   on the host — holds the host sum) -> intra rs/ag among HOST leaders
//   of a region -> inter ring among region leaders (wire applied there,
//   unchanged) -> intra broadcast to host leaders -> host broadcast.
// The intra tier therefore spans host LEADERS only; the region leader is
// the lowest rank of its region, which is by construction also a host
// leader. Segments are owned by the configure generation (created by the
// producing member, torn down — unlinked — on reconfigure/destruction);
// abort() poisons the ring magic and futex-wakes every waiter, so a
// failure propagates across the shm tier the way a socket FIN does on
// TCP. TORCHFT_HC_SHM=0 falls the host tier back to loopback TCP (same
// geometry, kTierHost hello) — the honest control the shm bench row is
// measured against; with no host map (or no (region,host) group of >= 2)
// the host tier is absent and the schedule is exactly the two-tier one.
// Shared-memory hops hand NOTHING to the kernel: their tx_bytes stay 0
// (wire accounting is honest) and the bytes moved are reported
// separately as shm_bytes.
//
// Ring allreduce = reduce-scatter + allgather; within each stripe every
// chunk is reduced in the same rank order on every participant, and stripe
// boundaries depend only on (count, stripes, world_size) — all negotiated —
// so results are bit-identical across ranks and across runs: the
// determinism oracle the reference tests demand
// (manager_integ_test.py:279-282).
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "net.h"
#include "thread_annotations.h"

namespace tft {

enum class ReduceOp : int {
  kSum = 0,
  kProduct = 1,
  kMin = 2,
  kMax = 3,
};

enum class Dtype : int {
  kF32 = 0,
  kF64 = 1,
  kI32 = 2,
  kI64 = 3,
  // bfloat16 ships natively (2 bytes on the wire — half the DCN traffic of
  // an f32 upcast); reduction arithmetic is f32 per hop with
  // round-to-nearest-even back to bf16.
  kBF16 = 4,
};

size_t dtype_size(Dtype d);

// Upper bound on ring stripes (sockets + threads per neighbor edge); far
// above the knee of any measured sweep, low enough that a bad config can't
// fork-bomb the host.
constexpr int64_t kMaxStripes = 64;

// Wire format of a CommPlan (see CommPlan below). Mirrored by the Python
// layer's `wire=` strings: None -> kNative, "bf16" -> kBF16, "q8" -> kQ8,
// "q8ef" -> kQ8EF.
enum class PlanWire : int {
  // Each leaf rides the ring in its own native dtype (f32/f64/i32/i64/
  // bf16 groups) — the legacy managed path's accumulation-dtype grouping.
  kNative = 0,
  // f32 leaves are rounded (nearest-even) to bf16 at pack and ride a
  // bf16 group; other dtypes group natively. Halves the f32 wire bytes,
  // matching ddp's compress="bf16" (jax downcast + bf16 ring) exactly.
  kBF16 = 1,
  // Whole tree packs into ONE f32 group and rides the quantized ring
  // (int8 chunks + per-chunk scales) — the legacy wire="q8" schedule.
  kQ8 = 2,
  // kQ8 plus per-leaf symmetric int8 quantization with ERROR FEEDBACK
  // executed natively at pack time: d = leaf + residual; scale =
  // max(|d|)/127 (floored 1e-12); dq = round(d/scale)*scale ships;
  // residual = d - dq persists in the plan. The native mirror of
  // quantize.quantize_with_feedback so the q8 DDP mode needs no jitted
  // quantize program on the per-step hot path.
  kQ8EF = 3,
};

// Wire of the hierarchical op's INTER hop (allreduce_hier / hier plans).
// The intra tier always rides native dtypes — quantization noise is paid
// exactly once, on the slow link that needs it.
enum class HierWire : int {
  kNone = 0,   // native dtype across regions too
  kBF16 = 1,   // leaders ring in bf16 (f32 payloads, SUM only)
  kQ8 = 2,     // leaders ride the quantized ring (f32 payloads, SUM only)
};

// Token bucket for per-connection send pacing (TORCHFT_HC_WIRE_CAP_MBPS /
// TORCHFT_HC_WIRE_CAP_INTRA_MBPS). Two uses: QoS — cap the gradient ring's
// per-connection rate so it cannot starve heal/checkpoint traffic on a
// shared NIC — and transport validation, emulating a per-connection-limited
// path (TCP window / BDP cap, tunnel throttling, a wide-area inter-region
// hop) on loopback so the stripe and hierarchy sweeps can measure where the
// real win lives. Pure pacing: no wire-format or schedule effect, so
// members need NOT agree on it.
struct PaceState {
  double tokens = 0;  // bytes available to send now
  std::chrono::steady_clock::time_point last{};
  bool init = false;
};

// Per-stripe persistent staging (grow-only, reused across ops): per-op
// allocation of a world-size chunk — up to payload/world_size bytes —
// costs an mmap + demand-zero page faults EVERY op at gradient scale.
// Also carries the connection's pacing state and the per-op tx counter
// (bytes actually handed to the kernel by duplex) the hierarchical
// accounting sums per tier — measured traffic, not a model.
struct StripeScratch {
  std::vector<char> recv;           // allreduce recv / q8 recv wire
  std::vector<char> send;           // q8 send wire
  std::vector<std::vector<char>> stored;  // q8 phase-2 circulating codes
  PaceState pace;                   // this connection's send pacing
  int64_t cap_bps = 0;              // tier's per-connection send cap
  int64_t tx_bytes = 0;             // bytes sent since the op reset it
  // Bytes moved through this stripe's SHARED-MEMORY rings since the op
  // reset it (frame headers included). Kept apart from tx_bytes on
  // purpose: shm hops hand nothing to the kernel, so the wire bill
  // stays honest while the movement is still measurable.
  int64_t shm_bytes = 0;
  // Diagnostic tag ("tier=... stripe=... prev=host:port") baked at
  // configure: wire-integrity and desync errors carry it so a W=8 fleet
  // log names the guilty edge instead of an anonymous socket.
  std::string tag;
};

class ShmSegment;

// One directed shared-memory edge pair of the host ring, per stripe: the
// TX ring this member CREATES and produces into (toward its next host
// neighbor) and the RX ring it ATTACHES and consumes from (fed by its
// prev neighbor). Creator-owned segments: dropping the handle unlinks
// the name — the configure-generation ownership contract.
struct ShmEdge {
  std::unique_ptr<ShmSegment> tx;
  std::unique_ptr<ShmSegment> rx;
  uint64_t fseq_tx = 0;  // frames produced (stale-payload detection)
  uint64_t fseq_rx = 0;  // frames consumed
  // Chaos: op index whose sends this edge swallows (drop-doorbell /
  // partition faults persist for the whole op — the injected failure is
  // the peer's stall, not a detectable frame skip).
  int64_t drop_op = -1;
};

// One ring a member participates in: the FLAT ring over all W members, the
// INTRA ring over its region peers, or the INTER ring over region leaders.
// `rank`/`world` are tier-local (flat: the global rank/world). `conns` is
// the tier's parallel-connection count per neighbor edge, `cap_bps` the
// tier's per-connection send pacing (0 = unpaced) — a hierarchical fleet
// paces its slow inter links without throttling the fast intra ones.
struct RingTier {
  int64_t rank = -1;
  int64_t world = 0;
  int64_t conns = 0;
  int64_t cap_bps = 0;
  // Diagnostics: tier name ("flat"/"intra"/"inter"/"host") and the
  // neighbor addresses wired at configure — protocol-desync and CRC
  // errors name the edge they fired on.
  std::string name;
  std::string peer_next_addr;
  std::string peer_prev_addr;
  std::vector<Socket> next;   // one per stripe
  std::vector<Socket> prev;   // one per stripe
  // Shared-memory transport (host tier only, TORCHFT_HC_SHM on): one
  // edge pair per stripe instead of sockets. When non-empty, every ring
  // body routes its duplex through the shm rings.
  bool use_shm = false;
  std::vector<ShmEdge> shm;
  // Persistent per-stripe staging + pacing + per-op tx accounting
  // (grow-only, reused across ops).
  std::vector<StripeScratch> scratch;
  void clear() {
    rank = -1;
    world = 0;
    next.clear();
    prev.clear();
    use_shm = false;
    shm.clear();
  }
};

// Per-op phase/byte breakdown of the last hierarchical op (allreduce_hier
// or one hier plan execute): wall seconds per schedule phase and MEASURED
// bytes sent on each tier's connections (summed from the per-connection tx
// counters duplex maintains — what actually hit the kernel, headers
// included). inter_rs/inter_ag split the leader's slow-link bill per ring
// phase: each is (L-1)/L of the payload, the number the topology buys.
struct HierStats {
  int64_t intra_rs_ns = 0;
  int64_t intra_ag_ns = 0;
  int64_t inter_ring_ns = 0;
  int64_t intra_bcast_ns = 0;
  int64_t intra_tx_bytes = 0;
  int64_t inter_tx_bytes = 0;
  int64_t inter_rs_tx_bytes = 0;
  int64_t inter_ag_tx_bytes = 0;
  // Host (third) tier: phase walls of the shm (or loopback-TCP
  // fallback) ring, its MEASURED socket tx (0 under shm — the honest
  // zero-tx contract) and the bytes moved through the shm rings.
  int64_t shm_rs_ns = 0;
  int64_t shm_ag_ns = 0;
  int64_t shm_bcast_ns = 0;
  int64_t host_tx_bytes = 0;
  int64_t shm_bytes = 0;
  int64_t payload_bytes = 0;
  int64_t eff_intra = 0;
  int64_t eff_inter = 0;
  int64_t eff_host = 0;
  int64_t intra_world = 0;
  int64_t inter_world = 0;
  int64_t host_world = 0;
  bool leader = false;       // region leader
  bool host_leader = false;
  bool host_shm = false;     // host tier transport: shm (else TCP)
  int wire = 0;  // HierWire of the inter hop
};

// A persistent, precompiled description of one pytree's gradient sync:
// leaf -> dtype-group assignment with per-leaf element offsets, the wire
// format, the stripe partition (the plan's "buckets" — each stripe
// sub-range is packed, ridden, and unpacked as one pipeline unit), and
// persistent staging buffers sized once at build. Built once per
// (signature, wire) by HostCollectives::plan_build and executed each step
// as a single native call; Python's only per-step work is collecting leaf
// pointers. Executing the ring over the IDENTICAL per-group stripe
// partition the legacy single-op path uses (and through the same
// *_stripe bodies) makes plan-vs-legacy bit-identity structural, not
// coincidental. Plans are invalidated by configure(): the layout bakes in
// (world_size, stripes) and a new ring means new geometry.
struct CommPlan {
  struct Leaf {
    size_t count;   // flat elements
    Dtype dtype;    // source (and result) dtype
  };
  // One contiguous staging buffer per ring dtype; leaves are packed at
  // fixed offsets in signature order (the legacy concatenation layout).
  struct Group {
    Dtype dtype;                     // ring/staging dtype
    std::vector<int64_t> leaf_idx;   // leaves packed into this group
    std::vector<size_t> leaf_off;    // element offset of each leaf
    size_t count = 0;                // total flat elements
    int64_t eff = 1;                 // stripe partition (fixed at build)
    std::vector<char> staging;       // persistent, count * esize bytes
  };
  // Per-bucket (= per stripe sub-range) phase timings of the last
  // execute; the plan-path analog of the bulk path's bucket stats.
  // `leg` distinguishes a sharded plan's two halves (1 = reduce-scatter
  // grad leg, 2 = allgather param leg; 0 = fused execute) so the
  // accounting layer can bill each leg's wire separately.
  struct BucketStat {
    int64_t group = 0;
    int64_t stripe = 0;
    int64_t leg = 0;
    int64_t bytes = 0;
    int64_t pack_ns = 0, ring_ns = 0, unpack_ns = 0;
  };

  PlanWire wire = PlanWire::kNative;
  // Pre-packed leaves: the caller (a device-side Pallas pack) already
  // emitted the WIRE encoding — one contiguous payload per group in the
  // group's staging dtype (int8 codes for q8 wires, with a per-leaf f32
  // scale sidecar), so execute's pack stage collapses to a straight
  // decode/memcpy into staging. The ring and unpack phases are the
  // host-pack plan's own, and `prepacked` is deliberately EXCLUDED from
  // the signature hash: a device-packing member and a host-packing member
  // produce bit-identical staging (the device kernels mirror the native
  // EF/cast arithmetic), so mixed rings interoperate — pack placement is
  // a local choice, not a wire-contract change.
  bool prepacked = false;
  // Hierarchical plan: execute runs the two-tier schedule (intra rs/ag,
  // inter ring at `wire` among leaders, intra bcast) instead of the flat
  // ring. Groups keep their NATIVE dtypes — the plan wire applies at the
  // inter hop only (kBF16: leaders cast f32 staging to bf16 for the slow
  // link; kQ8/kQ8EF: leaders ride the quantized ring, kQ8EF with the
  // per-leaf error-feedback carry applied to the REGION sum at the
  // leader, so the residual refines each region's own contribution).
  // Baked into the signature hash: a hier plan meeting a flat plan must
  // error, not desync.
  bool hier = false;
  // Sharded plan (per-step ZeRO): the fused schedule split at the
  // reduce-scatter boundary into two first-class executes. `wire` is the
  // GRAD reduce-scatter leg's encoding; `ag_wire` the PARAM allgather
  // leg's (native or bf16). One flat f32 group; the rank-owned shard —
  // shard_ranges over the group's eff — always lands in FULL f32
  // precision (a lossy wire only ever paid to ship bytes the owner never
  // ships). Both legs share the group's eff, so the two partitions can
  // never disagree. Runs on the FLAT ring regardless of topology (the
  // flat ring always exists; the shard layout is its layout).
  bool sharded = false;
  PlanWire ag_wire = PlanWire::kNative;
  // Persistent bf16 wire staging for a sharded plan's bf16 leg(s)
  // (grow-only, the hier_wire_buf_ discipline, but per plan: sized once
  // at build).
  std::vector<char> wirebuf;
  std::vector<Leaf> leaves;
  std::vector<Group> groups;
  // kQ8EF: persistent error-feedback carry, laid out exactly like the
  // single f32 group's staging (per-leaf offsets shared). Prepacked q8
  // plans leave it empty — the carry lives device-side in the packer.
  // Hier kQ8EF plans allocate it everywhere but only the region LEADER
  // advances it (the EF quantize happens at the inter hop); a leader
  // change rebuilds plans (configure invalidates), so a new leader
  // starts from a zero carry — the standard reset discipline.
  std::vector<float> residual;
  uint64_t sig = 0;      // structure hash, exchanged in the op header
  int64_t execs = 0;     // executes since build (0 = cold)
  std::vector<BucketStat> stats;  // last execute, one entry per bucket
};

class HostCollectives {
 public:
  HostCollectives();  // wire-CRC default snapshotted from TORCHFT_WIRE_CRC
  ~HostCollectives();

  // Rebuilds the ring(s) for a (possibly new) membership. store_addr is
  // "host:port/prefix"; the prefix must be unique per quorum — stale members
  // of an old quorum never see the new keys, so they cannot cross-talk
  // (reference manager.py:470-477 store-prefix discipline). Aborts any
  // in-flight op first. `stripes` is the parallel-connection count per
  // neighbor edge; every member must pass the same value (the hello
  // handshake rejects mismatches, and the Python layer additionally
  // negotiates it through the store so mismatched ranks fail fast with a
  // descriptive error before any socket work).
  //
  // `regions` (optional): one region label per rank, identical on every
  // member (it comes from the quorum, which already agrees). When given
  // with >= 2 distinct labels, the TWO-TIER topology is built alongside
  // the flat ring (see the file comment) and allreduce_hier()/hier plans
  // become available; `stripes_inter` (0 = `stripes`) is the inter
  // (leader) ring's connection count — the slow wide-area hop is where
  // striping pays, so it gets its own knob.
  //
  // `hosts` (optional): one host label per rank (quorum-agreed, like
  // regions). Whenever a (region, host) pair groups >= 2 ranks, the
  // HOST tier is built below the intra one (see the file comment) —
  // shared-memory rings by default, loopback TCP under TORCHFT_HC_SHM=0
  // — and the hierarchical schedule becomes available even on a
  // single-region cohort (host rings + a leader ring are two real
  // tiers). Ring-buffer bytes per edge per stripe:
  // TORCHFT_HC_SHM_RING_BYTES (default 1 MiB).
  void configure(const std::string& store_addr, int64_t rank, int64_t world_size,
                 int64_t timeout_ms, int64_t stripes = 1,
                 const std::vector<std::string>& regions = {},
                 int64_t stripes_inter = 0,
                 const std::vector<std::string>& hosts = {});

  // Whether the last configure() built a hierarchical topology: a region
  // map with >= 2 distinct labels, a host map grouping >= 2 co-hosted
  // ranks, or both.
  bool hier_capable() const { return hier_; }

  // Host-tier transport of the last configure: 0 = no host tier,
  // 1 = loopback TCP (TORCHFT_HC_SHM off), 2 = shared-memory rings.
  int host_tier_transport() const {
    if (!hier_ || host_.world <= 1) return 0;
    return host_.use_shm ? 2 : 1;
  }

  // Requests per-frame CRC32C on every ring/stripe payload frame of the
  // NEXT configure() (and thereafter, until changed). Every member must
  // agree — the hello magic carries the frame format, so a mismatch
  // fails at connect with a descriptive error, and the Python layer
  // additionally negotiates the knob through the store. Default comes
  // from TORCHFT_WIRE_CRC at construction. A CRC mismatch on a frame
  // raises WireCorruptionError ("wire corruption: ..."), which rides
  // the normal latch -> vote-discard -> reconfigure machinery. Disabled
  // (the default), the wire format is byte-identical to the pre-CRC
  // protocol and duplex pays a single branch.
  void set_wire_crc(bool on) { crc_req_ = on; }
  bool wire_crc() const { return crc_; }

  // In-place ring allreduce over `count` elements of `data`.
  void allreduce(void* data, size_t count, Dtype dtype, ReduceOp op,
                 int64_t timeout_ms);

  // In-place TWO-TIER allreduce (requires a hier configure):
  //   intra reduce-scatter -> intra allgather -> inter ring among leaders
  //   -> chunk-pipelined intra broadcast.
  // `wire` selects the INTER hop's encoding (HierWire; bf16/q8 take f32
  // payloads and kSum only — intra stays native/full precision either
  // way). Results are bit-identical across members and runs; the sum
  // order differs from the flat ring (two-tier reduction tree).
  // Phase/byte breakdown of the last call: last_hier_json().
  void allreduce_hier(void* data, size_t count, Dtype dtype, ReduceOp op,
                      HierWire wire, int64_t timeout_ms);

  // In-place QUANTIZED ring SUM over `count` f32 elements: every hop
  // ships each chunk as [f32 absmax/127 scale][int8 payload] and the
  // receiver dequantize-accumulates into its f32 buffer (the same
  // f32-accumulator discipline the bf16 path uses). Phase 2 circulates
  // the owner-quantized reduced chunks verbatim, so wire bytes per
  // member are ~2x the int8 payload REGARDLESS of world size — unlike a
  // quantized allgather, whose traffic grows O(world). Per-hop
  // requantization of partial sums keeps relative error at the int8
  // quantization class (~1/127 of each chunk's absmax).
  void allreduce_q8(float* data, size_t count, int64_t timeout_ms);

  // ---- sharded (split) collectives ----
  //
  // Ring allreduce is reduce-scatter + allgather; these expose the two
  // phases as first-class ops so a caller can stop at the reduce-scatter
  // boundary, update only the shard it owns, and allgather the *updated*
  // values — the weight-update sharding of "Automatic Cross-Replica
  // Sharding of Weight Update in Data-Parallel Training" (Xu et al.).
  //
  // Shard layout: payload striping partitions `count` elements into
  // `layout_stripes` contiguous sub-ranges (stripe_range); within each
  // sub-range the ring schedule leaves chunk (rank+1) % world_size fully
  // reduced at this rank (the same chunk the fused op starts phase 2
  // from). Rank r's SHARD is the union of those per-stripe owned chunks,
  // compacted in stripe order. `layout_stripes` <= 0 means "derive from
  // the payload size like the fused op" (effective_stripes over
  // count * esize bytes — esize 1 for the q8 wire); a caller composing a
  // reduce-scatter with a later allgather_into of a DIFFERENT element
  // size (e.g. q8 reduce, bf16 gather) must pin the same explicit value
  // on both ops or the two partitions disagree. The layout is pure
  // arithmetic on (count, layout_stripes, world_size) — identical on
  // every member — and the per-op header carries it, so a mismatch
  // errors instead of desyncing.

  // Element (start, len) ranges of rank r's shard for a `count`-element
  // payload of `esize`-byte elements. Valid after configure().
  std::vector<std::pair<size_t, size_t>> shard_ranges(
      size_t count, size_t esize, int64_t r, int64_t layout_stripes = 0) const;

  // Ring reduce-scatter: phase 1 of the fused allreduce (bit-identical
  // arithmetic order), stopping at the reduce-scatter boundary. `data`
  // (count elements, clobbered: non-owned regions hold partial sums on
  // return) is reduced in place; the rank-owned shard is compacted into
  // `shard_out` (shard_ranges-many elements).
  void reduce_scatter(void* data, size_t count, Dtype dtype, ReduceOp op,
                      void* shard_out, int64_t layout_stripes,
                      int64_t timeout_ms);

  // Quantized-wire reduce-scatter: phase 1 of allreduce_q8 (int8 chunks,
  // per-hop dequant-accumulate in f32). The owned shard lands in FULL
  // f32 precision — the fused op's lossy phase-2 owner quantization only
  // existed to ship the chunk, and here it never ships. `grid_shard`
  // true applies that owner quantize+decode anyway, reproducing the
  // fused allreduce_q8's bits exactly (the determinism oracle for
  // decomposed-vs-fused tests).
  void reduce_scatter_q8(float* data, size_t count, float* shard_out,
                         bool grid_shard, int64_t layout_stripes,
                         int64_t timeout_ms);

  // Ring allgather of per-rank shards into the full buffer: phase 2 of
  // the fused allreduce. `shard` is this rank's shard (shard_ranges
  // layout); `data` (count elements) is filled with every rank's shard
  // at its owned positions. Composing reduce_scatter + allgather_into at
  // the same (dtype, layout_stripes) is bit-identical to the fused
  // allreduce on every rank.
  void allgather_into(const void* shard, void* data, size_t count,
                      Dtype dtype, int64_t layout_stripes,
                      int64_t timeout_ms);

  // ---- persistent comm plans ----
  //
  // plan_build compiles a CommPlan for a leaf signature (counts[i],
  // dtypes[i]) and wire format; returns a plan id valid until the next
  // configure() (which invalidates every plan — the layout bakes in the
  // ring geometry) or plan_free. Build is pure layout arithmetic — no
  // sockets touched — so ranks may build at different times; the id is
  // local. All members of a ring must build plans from identical
  // signatures (the execute header hashes the signature and errors on
  // mismatch, like every other op). `prepacked` builds a plan whose
  // execute takes pre-packed per-GROUP wire buffers (plan_execute_pre)
  // instead of per-leaf source pointers; it does not change the wire
  // contract (see CommPlan::prepacked), so prepacked and plain plans of
  // the same signature interoperate in one ring. `hier` builds a
  // HIERARCHICAL plan (see CommPlan::hier; requires a hier configure at
  // execute time): groups stay native-dtype and `wire` applies at the
  // inter hop only.
  int64_t plan_build(const int64_t* counts, const int32_t* dtypes,
                     int64_t n_leaves, PlanWire wire, bool prepacked = false,
                     bool hier = false);

  // Executes one gradient sync over the plan: packs/casts leaf_in[i]
  // into the persistent staging (kQ8EF additionally runs the native
  // error-feedback quantization against the plan's residual), rides the
  // ring, and unpacks (divisor applied, AVG-style) into leaf_out[i].
  // Each stripe sub-range is one pipeline bucket running
  // pack -> ring -> unpack on its own pool worker, so bucket i+1
  // packs/casts while bucket i rides the ring and bucket i-1 unpacks.
  // The ring arithmetic per group is bit-identical to the legacy
  // single-op path (same stripe partition, same *_stripe bodies).
  // Aborts/peer death wake every stripe exactly like the bulk ops.
  // Hier plans run the two-tier schedule instead: pack streams into the
  // intra reduce-scatter phase and unpack out of the broadcast phase, so
  // the per-bucket triple pipeline survives the extra tiers.
  void plan_execute(int64_t plan_id, const void* const* leaf_in,
                    void* const* leaf_out, double divisor, bool has_divisor,
                    int64_t timeout_ms);

  // Executes a PREPACKED plan: group_in[g] points at group g's wire
  // payload (g.count elements of the group's staging dtype — int8 codes
  // for q8 wires, bf16/native words otherwise) and group_aux[g] at its
  // per-leaf f32 scale sidecar (q8 wires only; ignored — may be null —
  // for other groups). The pack stage per stripe bucket is a straight
  // decode (q8: staging[i] = q[i] * scale; else memcpy) streamed
  // per bucket like any other phase; ring and unpack are plan_execute's
  // own, so device-packed results are bit-identical to host-packed ones
  // whenever the device pack mirrors the native pack arithmetic (the
  // Pallas kernels' tested contract). A NaN scale poisons its whole leaf
  // (0 * NaN), reproducing the host EF's non-finite propagation.
  void plan_execute_pre(int64_t plan_id, const void* const* group_in,
                        const void* const* group_aux, void* const* leaf_out,
                        double divisor, bool has_divisor, int64_t timeout_ms);

  // ---- sharded comm plans (per-step ZeRO weight-update sharding) ----
  //
  // plan_build_sharded compiles a SHARDED CommPlan: the fused allreduce
  // schedule split at the reduce-scatter boundary so a caller can update
  // only the 1/W shard it owns (optimizer state sharded with it) and
  // allgather the *updated* params — "Automatic Cross-Replica Sharding
  // of Weight Update in Data-Parallel Training" (Xu et al.) on the
  // per-step path. f32 leaves only (they pack one flat f32 group whose
  // shard_ranges over the group eff IS the shard layout); `rs_wire`
  // encodes the grad leg (native/bf16/q8 — the owner's shard stays full
  // f32 either way), `ag_wire` the param leg (native/bf16). Like every
  // plan: valid until the next configure(), signature exchanged in the
  // op headers (kinds 11/12) so mismatched plans error, not desync.
  int64_t plan_build_sharded(const int64_t* counts, const int32_t* dtypes,
                             int64_t n_leaves, PlanWire rs_wire,
                             PlanWire ag_wire);

  // Grad leg: packs leaf_in into the f32 staging, runs the rs phase per
  // stripe bucket (the fused op's own body at the plan's partition),
  // compacts the rank-owned chunks into `shard_out` (plan_sharded_meta's
  // shard_count f32 elements) and applies the divisor to the SHARD only
  // — the owner's slice of the fused unpack arithmetic (f32 / f32).
  void plan_execute_rs(int64_t plan_id, const void* const* leaf_in,
                       float* shard_out, double divisor, bool has_divisor,
                       int64_t timeout_ms);

  // Param leg: scatters `shard_in` (the UPDATED shard, same layout) back
  // into staging, rides the ag phase at `ag_wire` (bf16: every member
  // decodes the identical wire words, so gathered params are
  // bit-identical across the cohort) and unpacks into leaf_out, no
  // divisor.
  void plan_execute_ag(int64_t plan_id, const float* shard_in,
                       void* const* leaf_out, int64_t timeout_ms);

  // out[0] = this rank's shard element count, out[1] = the plan's stripe
  // partition (the layout_stripes to pass shard_ranges), out[2] = total
  // flat element count.
  void plan_sharded_meta(int64_t plan_id, int64_t* out);

  void plan_free(int64_t plan_id);
  // Zeroes a kQ8EF plan's error-feedback carry (no-op otherwise): the
  // caller's heal/abort discipline — a recovered member must not carry a
  // residual from its abandoned trajectory.
  void plan_reset_feedback(int64_t plan_id);
  // Per-bucket phase stats of the plan's last execute, as JSON:
  // {"execs": n, "buckets": [{"group", "stripe", "bytes", "pack_s",
  // "ring_s", "unpack_s"}, ...]}.
  std::string plan_stats_json(int64_t plan_id);

  // Phase/byte breakdown of the LAST hierarchical op (allreduce_hier or
  // hier plan execute; hier plans accumulate across their groups), as
  // JSON: {"intra_rs_s", "intra_ag_s", "inter_ring_s", "intra_bcast_s",
  // "intra_tx_bytes", "inter_tx_bytes", "inter_rs_tx_bytes",
  // "inter_ag_tx_bytes", "payload_bytes", "eff_intra", "eff_inter",
  // "intra_world", "inter_world", "leader", "wire"}. tx bytes are
  // MEASURED (summed from the per-connection counters duplex maintains),
  // not modeled. Same read discipline as last_stripe_ns: call from the
  // thread that issued the op.
  std::string last_hier_json() const;

  // Gathers `nbytes` from every rank into `out` (world_size * nbytes), in
  // rank order.
  void allgather(const void* in, void* out, size_t nbytes, int64_t timeout_ms);
  // Broadcasts `nbytes` of `data` from `root` to all ranks, in place.
  void broadcast(void* data, size_t nbytes, int64_t root, int64_t timeout_ms);
  void barrier(int64_t timeout_ms);

  int64_t rank() const { return rank_; }
  int64_t world_size() const { return world_size_; }
  int64_t stripes() const { return stripes_; }

  // Wall-clock nanoseconds each stripe spent inside the last bulk op
  // (index = stripe). Written under op_mu_; callers read it from the same
  // thread that issued the op (the Python executor), so no extra locking.
  const std::vector<int64_t>& last_stripe_ns() const { return last_stripe_ns_; }

  // Wakes any thread blocked inside an op with a SocketError; the instance
  // stays usable via a subsequent configure(). Safe to call from any thread.
  void abort();

  // abort() plus deterministic release of every ring resource — sockets,
  // listener and the host tier's shm segments (creator unlink) — without
  // destroying the instance. The shutdown() counterpart of configure's
  // generation ownership: callers that keep the object alive (pending
  // GC, caches) must not keep kernel-named segments alive with it. A
  // later configure() rebuilds everything.
  void release_rings();

 private:
  // Sends send_len bytes to next while concurrently receiving recv_len
  // bytes from prev (full-duplex pump; one-directional blocking would
  // deadlock once kernel buffers fill on a large ring step). `sc`
  // (nullable) carries the connection's send pacing (cap_bps token
  // bucket) and accumulates sent bytes into its tx counter; receives are
  // never paced, and a token-dry sender keeps draining its receive side.
  void duplex(Socket& next, Socket& prev, const char* send_buf,
              size_t send_len, char* recv_buf, size_t recv_len,
              int64_t deadline_ms, StripeScratch* sc = nullptr,
              bool header_frame = false);

  // The shared-memory analog of duplex for one host-tier edge pair:
  // produces one frame ([len, fseq] header + payload) into the stripe's
  // TX ring while consuming one from its RX ring, futex-blocking (with
  // the op deadline) when a ring is full/empty. Frame sequence numbers
  // and lengths are checked on consume — a stale or desynced frame
  // errors instead of reducing wrong bytes; a poisoned ring magic (peer
  // abort/death, torn segment) errors like a socket FIN. Accounts moved
  // bytes into scratch.shm_bytes, never tx_bytes.
  void shm_duplex(RingTier& T, int64_t s, const char* send_buf,
                  size_t send_len, char* recv_buf, size_t recv_len,
                  int64_t deadline_ms, bool header_frame);

  // Routes one edge exchange of tier T / stripe s through the tier's
  // transport: shm rings when T.use_shm, else the TCP duplex. Every ring
  // body goes through here, so the host tier reuses the proven phase
  // bodies unchanged.
  void edge_duplex(RingTier& T, int64_t s, const char* send_buf,
                   size_t send_len, char* recv_buf, size_t recv_len,
                   int64_t deadline_ms, bool header_frame = false);

  // Exchanges a tiny (kind, count, dtype, op) header with both neighbors
  // of tier `T` on stripe 0 before a collective and throws on mismatch — a
  // size/dtype-mismatched op would otherwise deadlock silently once kernel
  // buffers fill.
  void check_op_header(RingTier& T, uint32_t kind, uint64_t count,
                       uint32_t dtype, uint32_t op, int64_t deadline_ms);

  // Runs fn(stripe) for every stripe concurrently: stripe 0 on the calling
  // thread, the rest on PERSISTENT pool workers. The FIRST failing stripe
  // shuts down every stripe's sockets (waking its siblings within
  // milliseconds — the same abort-propagation discipline run_op applies
  // ring-wide), the job is fully drained, and the lowest-stripe error is
  // rethrown. Also records per-stripe wall time into last_stripe_ns_.
  void run_striped(const std::function<void(int64_t)>& fn);

  // Grows the stripe worker pool to at least `workers` threads (grow-only;
  // workers outlive reconfigures and die with the instance). Spawning a
  // thread per stripe per native op costs ~0.1 ms each under sandboxed
  // runtimes, and one chunk-pipelined gradient allreduce issues hundreds
  // of native ring ops — the pool turns each op's fan-out into a condvar
  // wake. Between jobs workers block on pool_cv_, never inside socket IO,
  // so abort() needs no extra wakeup path for an idle pool.
  void ensure_pool(int64_t workers);
  void pool_main(int64_t idx, int64_t start_gen);

  // Per-stripe ring bodies over an element/byte sub-range of tier `T`'s
  // ring. Parameterized by tier so the flat, intra and inter rings all
  // run the SAME proven bodies — the two-tier schedule is composed from
  // them, never reimplemented.
  void allreduce_stripe(RingTier& T, int64_t s, char* bytes, size_t count,
                        size_t esize, Dtype dtype, ReduceOp op,
                        int64_t deadline);
  void allreduce_q8_stripe(RingTier& T, int64_t s, float* data, size_t count,
                           int64_t deadline);
  // The two phases of the ring schedule, shared verbatim by the fused
  // allreduce, the first-class reduce_scatter / allgather_into, and the
  // two-tier schedule's intra/inter hops (the sharing is what makes
  // decomposed-vs-fused and hier-vs-oracle bit-identity structural
  // rather than coincidental).
  void rs_phase_stripe(RingTier& T, int64_t s, char* bytes, size_t count,
                       size_t esize, Dtype dtype, ReduceOp op,
                       int64_t deadline);
  void ag_phase_stripe(RingTier& T, int64_t s, char* bytes, size_t count,
                       size_t esize, int64_t deadline);
  void rs_q8_phase_stripe(RingTier& T, int64_t s, float* data, size_t count,
                          int64_t deadline);
  // The allgather phase of the quantized ring (owner-quantize + circulate
  // codes verbatim); allreduce_q8_stripe = rs_q8_phase + this.
  void ag_q8_phase_stripe(RingTier& T, int64_t s, float* data, size_t count,
                          int64_t deadline);
  // Chunk-pipelined store-and-forward broadcast of a byte sub-range from
  // tier rank `root` around tier T's ring: member d forwards chunk k-1
  // while receiving chunk k (duplex), so the wall is ~bytes/bw + a chunk
  // of fill per hop instead of hops * bytes/bw. The two-tier schedule's
  // distribution phase.
  void bcast_pipe_stripe(RingTier& T, int64_t s, char* bytes, size_t nbytes,
                         int64_t root, int64_t deadline);
  // One hierarchical schedule over `count` elements of `data` (already
  // under op_mu_/run_op): the shared body of allreduce_hier and the hier
  // plan execute. Runs the host (shm) phases when the host tier exists,
  // the intra/inter phases on host leaders, and accumulates phase/byte
  // stats into last_hier_.
  void hier_schedule(char* bytes, size_t count, size_t esize, Dtype dtype,
                     ReduceOp op, HierWire wire, int64_t eff_intra,
                     int64_t eff_inter, int64_t deadline);
  // The leader's inter hop — rs then ag among region leaders over `buf`,
  // re-striped at eff_inter, with the wire encoding applied (bf16: cast
  // through hier_wire_buf_; q8: the quantized ring bodies). ONE
  // implementation serves the bulk op and the hier plan, so a wire or
  // accounting change can never desync the two. `*rs_tx` receives the
  // rs phase's measured slow-link tx (delta of the tier counter).
  void inter_ring_phase(HierWire wire, char* buf, size_t count, size_t esize,
                        Dtype dtype, ReduceOp op, int64_t eff_inter,
                        int64_t deadline, int64_t* rs_tx);
  // Copies the rank-owned chunk of every stripe between the full buffer
  // and the compacted shard (to_shard=true: gather out of `data` into
  // `shard`; false: scatter back).
  void copy_shard(char* data, char* shard, size_t count, size_t esize,
                  int64_t eff, bool to_shard) const;
  // Sum of the per-connection tx counters of a tier's scratch; resetting
  // them is the per-op accounting boundary. tier_shm sums the bytes
  // moved through the tier's shared-memory rings (0 on TCP tiers).
  static int64_t tier_tx(const RingTier& T);
  static int64_t tier_shm(const RingTier& T);
  static void reset_tier_tx(RingTier& T);

  // Builds the host tier's shared-memory edges (create TX, attach RX
  // with retry until `deadline`) for the freshly computed geometry;
  // called from configure's phase 2 with no locks held.
  void wire_shm_edges(std::vector<ShmEdge>& edges, int64_t conns,
                      const std::string& base, int64_t next_rank,
                      int64_t prev_rank, int64_t deadline);
  // Poisons every shm ring magic and futex-wakes all waiters (local and
  // peer) — the shm analog of a socket shutdown; part of the abort/
  // failure-propagation path.
  void shm_poison_wake_locked() TFT_REQUIRES(cfg_mu_);

  // Plan internals: pack/unpack one element range of a group (casts per
  // the plan wire; unpack applies the divisor), and the kQ8EF per-leaf
  // error-feedback quantization (whole group — the per-leaf absmax spans
  // stripe boundaries, so it cannot run per stripe).
  void plan_pack_range(CommPlan& p, CommPlan::Group& g,
                       const void* const* leaf_in, size_t start,
                       size_t len) const;
  void plan_unpack_range(const CommPlan& p, const CommPlan::Group& g,
                         void* const* leaf_out, size_t start, size_t len,
                         double divisor, bool has_divisor) const;
  void plan_pack_ef(CommPlan& p, CommPlan::Group& g,
                    const void* const* leaf_in) const;
  // Hier kQ8EF: the same per-leaf EF quantization applied IN PLACE to the
  // group's staging (which holds the REGION sum at the leader before the
  // inter hop): d = staging + residual; quantize; staging = dq;
  // residual = d - dq. Leader-only by construction.
  void plan_ef_inplace(CommPlan& p, CommPlan::Group& g) const;
  // Prepacked decode of one element range: q8 groups dequantize the int8
  // codes against the per-leaf scale sidecar, everything else memcpys the
  // already-wire-encoded words into staging.
  void plan_pack_pre_range(const CommPlan& p, CommPlan::Group& g,
                           const void* group_in, const void* group_aux,
                           size_t start, size_t len) const;
  // The hier plan execute body for one group (under run_op): pack fused
  // into the intra_rs phase, unpack fused into the bcast phase.
  void plan_execute_hier_group(CommPlan& p, size_t gi,
                               const void* const* leaf_in,
                               void* const* leaf_out, double divisor,
                               bool has_divisor, int64_t deadline);
  CommPlan& plan_get(int64_t plan_id);

  // Shuts down every ring socket (all tiers, all stripes); cfg_mu_ must
  // NOT be held.
  void shutdown_sockets();
  void shutdown_sockets_locked() TFT_REQUIRES(cfg_mu_);

  // Runs an op body; on ANY failure shuts down all ring sockets before
  // rethrowing. The FIN propagates the failure around the ring — and, for
  // hierarchical ops, ACROSS TIERS: a dead region leader kills its inter
  // peers' op, whose intra members then fail on their own tier's sockets,
  // so every member of every region errors within one op deadline instead
  // of blocking while a majority of survivors can't reach the next quorum
  // — the distributed analog of NCCL's abort-on-error. The dead ring stays
  // dead (ops throw immediately) until the next configure().
  template <typename Fn>
  void run_op(Fn&& fn) {
    try {
      fn();
    } catch (...) {
      {
        MutexLock lock(cfg_mu_);
        shutdown_sockets_locked();
        aborted_ = true;
      }
      throw;
    }
  }

  // Element range [start, len) of stripe `s` when `count` elements are
  // split into `n` near-equal contiguous stripes.
  static std::pair<size_t, size_t> stripe_range(size_t count, int64_t n,
                                                int64_t s);

  // Guards socket object identity (swap/close) against concurrent abort.
  // Never held across blocking IO, so abort() always runs promptly.
  Mutex cfg_mu_;
  // Serializes collective ops (they share the ring sockets and must issue in
  // the same order on every rank anyway).
  Mutex op_mu_;

  // Ring geometry and per-stripe/tier state below ride a DUAL protocol no
  // single capability can express (so no GUARDED_BY): identity writers
  // (configure) hold op_mu_ AND cfg_mu_; the op thread reads under op_mu_;
  // pool workers read with NO lock, synchronized by the pool_mu_ job
  // handoff (the op thread publishes the job under pool_mu_ while itself
  // holding op_mu_, so no write can overlap a worker's read). abort()/
  // run_op touch only the sockets' fds, under cfg_mu_.
  int64_t rank_ = -1;
  int64_t world_size_ = 0;
  int64_t stripes_ = 1;
  int64_t stripes_inter_ = 1;
  bool hier_ = false;
  // Canonical hash of the (region, host) topology map of the last
  // configure — mixed into hier plan signatures so plans built against
  // different topologies error at the header instead of desyncing.
  uint64_t topo_hash_ = 0;
  // Shared-memory ring-buffer bytes per edge per stripe, snapshotted at
  // configure (TORCHFT_HC_SHM_RING_BYTES).
  size_t shm_ring_bytes_ = 1 << 20;
  // Wire CRC: crc_req_ is the caller's request (env default at
  // construction, settable until configure); crc_ is the ACTIVE frame
  // format, snapshotted by configure so it is stable for the life of a
  // ring (same dual protocol as rank_/stripes_).
  bool crc_req_ = false;
  bool crc_ = false;
  // Monotonic per-member collective-op counter (bumped under op_mu_ at
  // every public op): the op_index axis of the seeded fault schedule and
  // the index desync/corruption errors report.
  int64_t op_seq_ = 0;
  std::unique_ptr<Listener> listener_;
  // The four rings a member can participate in. flat_ always exists
  // after a multi-member configure; intra_/inter_/host_ only under a
  // hier configure (intra_.world == 1 for a one-member region,
  // inter_.world only meaningful on the region leader, host_.world <= 1
  // when this member is alone on its host).
  RingTier flat_;
  RingTier intra_;
  RingTier inter_;
  RingTier host_;
  HierStats last_hier_;
  // Leader-side inter-hop wire staging for allreduce_hier's bf16 wire
  // (grow-only, reused across ops).
  std::vector<char> hier_wire_buf_;
  std::vector<int64_t> last_stripe_ns_;    // per-stripe time of the last op
  std::atomic<bool> aborted_{true}; // not configured yet
  // Bumped by every abort(); configure() uses it to detect an abort that
  // raced with its (lock-free) rendezvous phase.
  std::atomic<int64_t> abort_epoch_{0};

  // Stripe worker pool state (all under pool_mu_). Worker `idx` runs stripe
  // `idx + 1` of the current job when that stripe exists (ops can use fewer
  // effective stripes than configured); stripe 0 always runs on the op
  // thread. op_mu_ guarantees at most one job is in flight. The job BODY is
  // invoked by workers after dropping pool_mu_ (it blocks in socket IO);
  // its lifetime is the run_striped stack frame, pinned until the
  // pool_pending_ drain completes.
  Mutex pool_mu_;
  CondVar pool_cv_;       // workers: wait for a new job
  CondVar pool_done_cv_;  // run_striped: wait for drain
  const std::function<void(int64_t)>* pool_body_ TFT_GUARDED_BY(pool_mu_) =
      nullptr;
  int64_t pool_gen_ TFT_GUARDED_BY(pool_mu_) = 0;  // bumped once per job
  int64_t pool_n_ TFT_GUARDED_BY(pool_mu_) = 0;  // stripe count of the job
  int64_t pool_pending_ TFT_GUARDED_BY(pool_mu_) = 0;  // workers not yet done
  bool pool_stop_ TFT_GUARDED_BY(pool_mu_) = false;
  std::vector<std::thread> pool_ TFT_GUARDED_BY(pool_mu_);

  // Comm plans (guarded by plan_mu_ for map identity; a plan's buffers
  // are only ever touched under op_mu_ during execute). Cleared by
  // configure() — ids from an old ring error instead of running with a
  // stale layout.
  Mutex plan_mu_;
  std::map<int64_t, std::unique_ptr<CommPlan>> plans_ TFT_GUARDED_BY(plan_mu_);
  int64_t next_plan_id_ TFT_GUARDED_BY(plan_mu_) = 1;
};

} // namespace tft
