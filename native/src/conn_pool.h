// Small connection pool used by the RPC clients. Persistent connections keep
// per-step RPCs (should_commit runs every training step) off the TCP
// handshake path, while allowing concurrent blocking calls from multiple
// threads — a single shared connection would serialize them, and a barrier
// RPC (quorum, should_commit vote) held by one thread would deadlock another.
#pragma once

#include <string>
#include <utility>
#include <vector>

#include "net.h"
#include "thread_annotations.h"

namespace tft {

class ConnPool {
 public:
  ConnPool(std::string addr, int64_t connect_timeout_ms, size_t max_idle = 4)
      : addr_(std::move(addr)),
        connect_timeout_ms_(connect_timeout_ms),
        max_idle_(max_idle) {}

  // Returns an idle connection or dials a new one.
  Socket acquire() {
    {
      MutexLock lock(mu_);
      if (!idle_.empty()) {
        Socket s = std::move(idle_.back());
        idle_.pop_back();
        return s;
      }
    }
    return connect_with_retry(addr_, connect_timeout_ms_);
  }

  // Hand back a connection that is still in a clean request/response state.
  // Connections that desynchronized (timeout mid-response, socket error) must
  // simply be dropped by the caller instead.
  void release(Socket s) {
    if (!s.valid()) return;
    MutexLock lock(mu_);
    if (idle_.size() < max_idle_) idle_.push_back(std::move(s));
  }

  const std::string& addr() const { return addr_; }
  int64_t connect_timeout_ms() const { return connect_timeout_ms_; }

 private:
  std::string addr_;
  int64_t connect_timeout_ms_;
  size_t max_idle_;
  Mutex mu_;
  std::vector<Socket> idle_ TFT_GUARDED_BY(mu_);
};

} // namespace tft
