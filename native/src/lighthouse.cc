#include "lighthouse.h"

#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <chrono>
#include <sstream>

#include "fault.h"
#include "http_util.h"
#include "log.h"
#include "manager.h"
#include "wire.h"

namespace tft {

using torchft_tpu::ErrorResponse;
using torchft_tpu::Quorum;
using torchft_tpu::QuorumMember;

namespace {

// One RootSync round trip on a fresh connection; false on any failure
// (the peer being down is the normal case this exists to tolerate).
bool root_sync_call(const std::string& addr, int64_t my_epoch,
                    int64_t timeout_ms, torchft_tpu::RootSyncResponse* out) {
  try {
    torchft_tpu::RootSyncRequest req;
    req.set_root_epoch(my_epoch);
    *out = call<torchft_tpu::RootSyncRequest, torchft_tpu::RootSyncResponse>(
        addr, MsgType::kRootSyncReq, req, MsgType::kRootSyncResp, timeout_ms,
        timeout_ms);
    return true;
  } catch (const std::exception&) {
    return false;
  }
}

constexpr char kStandbyMsg[] =
    "standby root (passive; retry another root endpoint)";

// Per-activation tie-break nonce: distinct across processes and across
// claims within one process (pid ^ wall clock ^ a counter, mixed; 0 is
// reserved for "no claim"). Collisions would need two claims mixing to
// the same 64-bit value — and even then the tie merely persists until
// the next epoch bump, never corrupts state.
uint64_t fresh_claim_nonce() {
  static std::atomic<uint64_t> counter{0};
  uint64_t n = fault::mix64(static_cast<uint64_t>(::getpid()) ^
                            (static_cast<uint64_t>(unix_ms()) << 16) ^
                            (counter.fetch_add(1) << 56));
  return n == 0 ? 1 : n;
}

} // namespace

Lighthouse::Lighthouse(const std::string& bind_addr, const LighthouseOpt& opt)
    : opt_(opt),
      listener_(std::make_unique<Listener>(bind_addr)),
      hostname_(local_hostname()) {
  peers_ = split_addr_list(opt_.peers);
  takeover_ms_ = opt_.takeover_ms > 0 ? opt_.takeover_ms : 3000;

  int64_t recovered_epoch = 0;
  if (!opt_.wal_dir.empty()) {
    int64_t t0 = now_ms();
    WalRecovery rec = DurableLog::recover(opt_.wal_dir, now_ms(), unix_ms());
    wal_replay_ms_ = now_ms() - t0;
    wal_replayed_ = rec.replayed;
    wal_records_replayed_ = rec.records_replayed;
    wal_dropped_tail_bytes_ = rec.dropped_tail_bytes;
    {
      MutexLock lock(mu_);  // no sibling threads yet; for the analysis
      state_ = std::move(rec.state);
      quorum_gen_ = rec.quorum_gen;
      root_epoch_ = rec.root_epoch;
      wal_quorum_logged_ = state_.quorum_id;
      recovered_epoch = rec.root_epoch;
    }
    wal_ = std::make_unique<DurableLog>(opt_.wal_dir, opt_.snapshot_every);
    if (rec.replayed) {
      LOG_INFO("lighthouse WAL replayed: quorum_id="
               << rec.state.quorum_id << " quorum_gen=" << rec.quorum_gen
               << " root_epoch=" << rec.root_epoch << " records="
               << rec.records_replayed << " dropped_tail_bytes="
               << rec.dropped_tail_bytes << " in " << wal_replay_ms_ << " ms");
    }
  }

  // Role election. A root started with standby=true is passive by fiat;
  // an unflagged root with peers probes them first — finding an ACTIVE
  // peer at a strictly higher epoch means we are the deposed incarnation
  // and must fence (tail the winner) instead of forking quorum history.
  bool start_active = !opt_.standby;
  if (start_active && !peers_.empty()) {
    for (const auto& peer : peers_) {
      torchft_tpu::RootSyncResponse resp;
      if (!root_sync_call(peer, recovered_epoch, 1000, &resp)) continue;
      MutexLock lock(mu_);
      seen_peer_epoch_ = std::max(seen_peer_epoch_, resp.root_epoch());
      if (resp.active() && resp.root_epoch() > recovered_epoch) {
        LOG_WARN("peer " << peer << " is ACTIVE at root epoch "
                         << resp.root_epoch() << " > recovered "
                         << recovered_epoch
                         << "; starting as a fenced standby");
        start_active = false;
      }
    }
  }
  {
    MutexLock lock(mu_);
    active_ = start_active;
    last_sync_ok_ms_ = now_ms();
    last_tick_ms_ = now_ms();
    if (start_active) {
      // Every active claim bumps the root epoch, WAL-fenced when a log
      // is configured — the monotone counter split-brain detection and
      // the chaos harness's cross-restart invariant key off.
      root_epoch_ = std::max(root_epoch_, seen_peer_epoch_) + 1;
      claim_nonce_ = fresh_claim_nonce();
      if (wal_) {
        try {
          wal_->log_epoch(root_epoch_);
        } catch (const std::exception& e) {
          wal_dead_logged_ = true;
          LOG_ERROR("root-epoch WAL append failed at startup ("
                    << e.what() << "); refusing new quorum promises");
        }
      }
    }
  }

  accept_thread_ = std::thread([this] { accept_loop(); });
  tick_thread_ = std::thread([this] { tick_loop(); });
  if (!peers_.empty() || opt_.standby) {
    peer_thread_ = std::thread([this] { peer_loop(); });
  }
  LOG_INFO("Lighthouse listening on: "
           << address() << (start_active ? "" : " (standby)"));
}

Lighthouse::~Lighthouse() { shutdown(); }

std::string Lighthouse::address() const {
  return "http://" + hostname_ + ":" + std::to_string(listener_->port());
}

uint16_t Lighthouse::port() const { return listener_->port(); }

void Lighthouse::shutdown() {
  {
    // Flag + notify under the cv's mutex so waiters can't miss the wakeup.
    MutexLock lock(mu_);
    if (shutting_down_.exchange(true)) return;
    quorum_cv_.notify_all();
  }
  listener_->close();
  if (accept_thread_.joinable()) accept_thread_.join();
  if (tick_thread_.joinable()) tick_thread_.join();
  if (peer_thread_.joinable()) peer_thread_.join();
  conns_.shutdown_all();
}

bool Lighthouse::active() {
  MutexLock lock(mu_);
  return active_;
}

int64_t Lighthouse::root_epoch() {
  MutexLock lock(mu_);
  return root_epoch_;
}

bool Lighthouse::reject_if_standby(Socket& sock) {
  {
    MutexLock lock(mu_);
    if (active_) return false;
  }
  send_error(sock, ErrorResponse::UNAVAILABLE, kStandbyMsg);
  return true;
}

void Lighthouse::accept_loop() {
  while (!shutting_down_) {
    Socket sock = listener_->accept();
    if (!sock.valid()) return;
    conns_.spawn(std::move(sock), [this](Socket& s) { handle_conn(s); });
  }
}

void Lighthouse::tick_loop() {
  while (!shutting_down_) {
    bool stalled = false;
    {
      MutexLock lock(mu_);
      int64_t now = now_ms();
      stalled = !peers_.empty() && active_ && last_tick_ms_ > 0 &&
                now - last_tick_ms_ > takeover_ms_;
      last_tick_ms_ = now;
    }
    if (stalled) {
      // Resumed from a stall longer than the standby takeover bound
      // (SIGSTOP, scheduler starvation, VM pause): a peer may hold a
      // higher epoch by now. Probe BEFORE making any further promise —
      // this is what bounds the deposed-primary split-brain window to
      // the stall itself, not to the next scheduled fence probe.
      LOG_WARN("quorum tick stalled past the takeover bound ("
               << takeover_ms_ << " ms); probing peers before serving");
      probe_peers_fence();
    }
    {
      MutexLock lock(mu_);
      if (active_) quorum_tick_locked();
    }
    struct timespec ts;
    ts.tv_sec = opt_.quorum_tick_ms / 1000;
    ts.tv_nsec = (opt_.quorum_tick_ms % 1000) * 1000000;
    nanosleep(&ts, nullptr);
  }
}

bool Lighthouse::wal_commit_quorum_locked(const Quorum& quorum) {
  if (!wal_) return true;
  try {
    wal_->log_quorum(quorum, quorum_gen_ + 1, root_epoch_);
    wal_quorum_logged_ = quorum.quorum_id();
  } catch (const std::exception& e) {
    if (!wal_dead_logged_) {
      wal_dead_logged_ = true;
      LOG_ERROR("quorum WAL append failed ("
                << e.what()
                << "); refusing new quorum promises until restart — a "
                   "promise that outruns the log would regress on replay");
    }
    return false;
  }
  try {
    wal_->maybe_snapshot(state_, quorum_gen_ + 1, root_epoch_, now_ms(),
                         unix_ms());
  } catch (const std::exception& e) {
    // The record above is already fsync'd — the promise IS durable, so
    // publish it (rolling back would re-form and re-append the same
    // quorum every tick forever). Compaction is what degraded: the log
    // grows until an operator fixes the directory.
    if (!wal_dead_logged_) {
      wal_dead_logged_ = true;
      LOG_ERROR("WAL snapshot compaction failed ("
                << e.what() << "); serving continues, log growth UNBOUNDED "
                               "until the WAL directory recovers");
    }
  }
  return true;
}

void Lighthouse::wal_log_members_locked(const std::vector<std::string>& ids) {
  if (!wal_ || ids.empty()) return;
  try {
    wal_->log_lease(wal_entries_from_state(state_, ids, now_ms()), unix_ms());
    wal_->maybe_snapshot(state_, quorum_gen_, root_epoch_, now_ms(),
                         unix_ms());
  } catch (const std::exception& e) {
    if (!wal_dead_logged_) {
      wal_dead_logged_ = true;
      LOG_ERROR("lease WAL append failed (" << e.what()
                                            << "); durability degraded");
    }
  }
}

void Lighthouse::quorum_tick_locked() {
  ticks_total_ += 1;
  // Idle skip: with no registered participant no quorum can form (a lease
  // expiring can only shrink the healthy set), so the O(groups) membership
  // scan is pure waste. This is what keeps root CPU flat between quorum
  // rounds at thousands-of-groups scale.
  if (state_.participants.empty() && opt_.min_replicas > 0) return;
  // A dead WAL (torn append) freezes NEW promises entirely: a quorum the
  // log cannot remember would regress on replay. Frozen beats regressed.
  if (wal_ && wal_->dead()) return;

  // Rollback savepoint: quorum_step mutates the state (id bump, prev
  // quorum, participant clear) BEFORE we know the WAL accepted the
  // promise — if the append tears, the state must roll back so status
  // and later ticks never advertise an unpublished quorum_id.
  int64_t saved_qid = state_.quorum_id;
  std::optional<Quorum> saved_prev;
  std::map<std::string, ParticipantDetails> saved_parts;
  if (wal_) {
    saved_prev = state_.prev_quorum;
    saved_parts = state_.participants;
  }

  auto t0 = std::chrono::steady_clock::now();
  QuorumStepResult res = quorum_step(now_ms(), unix_ms(), state_, opt_);
  last_compute_us_ = std::chrono::duration_cast<std::chrono::microseconds>(
                         std::chrono::steady_clock::now() - t0)
                         .count();
  ticks_computed_ += 1;
  total_compute_us_ += last_compute_us_;
  LOG_DEBUG("Next quorum status: " << res.reason);

  if (!res.quorum.has_value()) return;
  const Quorum& quorum = *res.quorum;

  // Durability gate: a CHANGED quorum (id bump / membership commit) is a
  // new external promise — it must hit the WAL (and, best-effort, the
  // standby peers) before anyone sees it. An unchanged re-formation
  // republishes an already-durable promise.
  if (res.changed) {
    if (!wal_commit_quorum_locked(quorum)) {
      state_.quorum_id = saved_qid;
      state_.prev_quorum = std::move(saved_prev);
      state_.participants = std::move(saved_parts);
      return;
    }
    push_quorum_to_peers_locked(quorum);
  }

  if (res.changed) {
    LOG_INFO("Detected quorum change, bumping quorum_id to " << state_.quorum_id);

    // Event log entry: membership + who is healing (step behind max).
    int64_t max_step = -1;
    for (const auto& p : quorum.participants())
      max_step = std::max(max_step, p.step());
    std::ostringstream ev;
    ev << "[" << format_unix_ms(unix_ms()) << "] quorum " << state_.quorum_id
       << ": " << quorum.participants_size() << " member"
       << (quorum.participants_size() == 1 ? "" : "s");
    std::string healing;
    for (const auto& p : quorum.participants()) {
      if (p.step() != max_step) {
        if (!healing.empty()) healing += ", ";
        healing += p.replica_id();
      }
    }
    if (!healing.empty())
      ev << "; healing to step " << max_step << ": " << healing;
    state_.events.push_front(ev.str());
    while (state_.events.size() > 20) state_.events.pop_back();
  }

  LOG_INFO("Quorum! id=" << quorum.quorum_id()
                         << " participants=" << quorum.participants_size());

  latest_quorum_ = quorum;
  quorum_gen_ += 1;
  quorum_cv_.notify_all();
}

void Lighthouse::handle_conn(Socket& sock) {
  try {
    std::string req_head;
    if (sniff_http(sock, req_head)) {
      handle_http(sock, req_head);
      return;
    }

    while (true) {
      auto [type, payload] = recv_frame(sock);
      switch (type) {
        case MsgType::kLighthouseQuorumReq:
          handle_quorum_req(sock, payload);
          break;
        case MsgType::kLighthouseHeartbeatReq: {
          if (reject_if_standby(sock)) return;
          torchft_tpu::LighthouseHeartbeatRequest req;
          req.ParseFromString(payload);
          {
            MutexLock lock(mu_);
            state_.heartbeats[req.replica_id()] = now_ms();
            wal_log_members_locked({req.replica_id()});
          }
          send_msg(sock, MsgType::kLighthouseHeartbeatResp,
                   torchft_tpu::LighthouseHeartbeatResponse());
          break;
        }
        case MsgType::kLeaseRenewReq:
          handle_lease_renew(sock, payload);
          break;
        case MsgType::kDepartReq:
          handle_depart(sock, payload);
          break;
        case MsgType::kRegionDigestReq:
          handle_region_digest(sock, payload);
          break;
        case MsgType::kRegionPollReq:
          handle_region_poll(sock, payload);
          break;
        case MsgType::kRootSyncReq:
          // Served in EVERY role: the standby's state tail, and the
          // epoch-fencing probe a restarted/deposed root keys off.
          handle_root_sync(sock, payload);
          break;
        default:
          send_error(sock, ErrorResponse::INVALID_ARGUMENT,
                     "unexpected message type");
          return;
      }
    }
  } catch (const std::exception&) {
    // peer went away
  }
}

void Lighthouse::handle_quorum_req(Socket& sock, const std::string& payload) {
  if (reject_if_standby(sock)) return;
  torchft_tpu::LighthouseQuorumRequest req;
  if (!req.ParseFromString(payload) || !req.has_requester()) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "missing requester");
    return;
  }
  const QuorumMember& requester = req.requester();
  LOG_INFO("got quorum request for replica " << requester.replica_id());

  int64_t deadline = req.timeout_ms() <= 0 ? -1 : now_ms() + req.timeout_ms();

  UniqueMutexLock lock(mu_);
  // Joining the quorum is an implicit heartbeat.
  state_.heartbeats[requester.replica_id()] = now_ms();
  state_.participants[requester.replica_id()] =
      ParticipantDetails{now_ms(), requester};
  wal_log_members_locked({requester.replica_id()});
  int64_t gen = quorum_gen_;
  // Proactive tick so a now-complete quorum resolves without waiting a tick.
  quorum_tick_locked();

  while (true) {
    // Wait for a quorum newer than our subscription point (bailing out if
    // a fencing demotion made this root a standby mid-poll).
    while (quorum_gen_ == gen && !shutting_down_ && active_) {
      if (deadline < 0) {
        quorum_cv_.wait(lock);
      } else {
        int64_t remain = deadline - now_ms();
        if (remain <= 0) {
          lock.unlock();
          send_error(sock, ErrorResponse::DEADLINE_EXCEEDED,
                     "lighthouse quorum timed out");
          return;
        }
        quorum_cv_.wait_for(lock, std::chrono::milliseconds(remain));
      }
    }
    if (shutting_down_) {
      lock.unlock();
      send_error(sock, ErrorResponse::CANCELLED, "lighthouse shutting down");
      return;
    }
    if (!active_) {
      lock.unlock();
      send_error(sock, ErrorResponse::UNAVAILABLE, kStandbyMsg);
      return;
    }
    gen = quorum_gen_;
    bool in_quorum = false;
    for (const auto& p : latest_quorum_.participants()) {
      if (p.replica_id() == requester.replica_id()) {
        in_quorum = true;
        break;
      }
    }
    if (in_quorum) {
      torchft_tpu::LighthouseQuorumResponse resp;
      *resp.mutable_quorum() = latest_quorum_;
      lock.unlock();
      send_msg(sock, MsgType::kLighthouseQuorumResp, resp);
      return;
    }
    // A quorum formed without us (e.g. it was computed just before we joined);
    // re-register and keep waiting.
    LOG_INFO("Replica " << requester.replica_id() << " not in quorum, retrying");
    state_.participants[requester.replica_id()] =
        ParticipantDetails{now_ms(), requester};
    wal_log_members_locked({requester.replica_id()});
  }
}

void Lighthouse::handle_lease_renew(Socket& sock, const std::string& payload) {
  if (reject_if_standby(sock)) return;
  torchft_tpu::LeaseRenewRequest req;
  if (!req.ParseFromString(payload)) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "bad lease renew request");
    return;
  }
  std::vector<LeaseEntry> entries = lease_entries_from_pb(req);
  torchft_tpu::LeaseRenewResponse resp;
  {
    MutexLock lock(mu_);
    // A NEW registration is quorum intent worth resolving eagerly, the way
    // a long-poll join does. Re-renewals of existing participants change
    // nothing the periodic tick won't see — ticking for those would be
    // O(groups) per renewal, O(groups^2)/interval aggregate while a join
    // window holds the quorum open.
    bool fresh = apply_lease_batch(state_, entries, now_ms());
    std::vector<std::string> ids;
    ids.reserve(entries.size());
    for (const auto& e : entries) ids.push_back(e.replica_id);
    wal_log_members_locked(ids);
    if (fresh) quorum_tick_locked();
    resp.set_quorum_id(state_.quorum_id);
  }
  send_msg(sock, MsgType::kLeaseRenewResp, resp);
}

void Lighthouse::handle_depart(Socket& sock, const std::string& payload) {
  if (reject_if_standby(sock)) return;
  torchft_tpu::DepartRequest req;
  if (!req.ParseFromString(payload) || req.replica_id().empty()) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "missing replica_id");
    return;
  }
  {
    UniqueMutexLock lock(mu_);
    apply_depart(state_, req.replica_id());
    // The depart ACK is a durable promise: "this member stays departed
    // across a root restart". Log it BEFORE the response (and before the
    // tick that may commit a quorum excluding the member), so a torn
    // append can only lose an un-acked depart.
    if (wal_) {
      try {
        wal_->log_depart(req.replica_id());
      } catch (const std::exception& e) {
        if (!wal_dead_logged_) {
          wal_dead_logged_ = true;
          LOG_ERROR("depart WAL append failed (" << e.what() << ")");
        }
        lock.unlock();
        send_error(sock, ErrorResponse::UNAVAILABLE,
                   "wal append failed; depart not durable");
        return;
      }
    }
    // An explicit depart may complete a pending quorum (the departed member
    // no longer counts against the straggler hold-the-door wait).
    quorum_tick_locked();
  }
  LOG_INFO("replica " << req.replica_id() << " departed");
  send_msg(sock, MsgType::kDepartResp, torchft_tpu::DepartResponse());
}

void Lighthouse::handle_region_digest(Socket& sock, const std::string& payload) {
  if (reject_if_standby(sock)) return;
  torchft_tpu::RegionDigestRequest req;
  if (!req.ParseFromString(payload) || req.region_id().empty()) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "missing region_id");
    return;
  }
  std::vector<DigestEntry> entries = digest_from_pb(req);
  torchft_tpu::RegionDigestResponse resp;
  {
    MutexLock lock(mu_);
    // Departs FIRST: a re-queued depart (failed push) may be older than a
    // rejoin carried in this digest's entries — entries must win.
    for (const auto& d : req.departed()) apply_depart(state_, d);
    apply_digest(state_, entries, now_ms());
    // WAL, mirroring apply order: departs, then the POST-APPLY member
    // slices (so the freshness gate's outcome — not its input — is what
    // replays; a region redigest after a failed push re-logs harmlessly).
    if (wal_) {
      try {
        for (const auto& d : req.departed()) wal_->log_depart(d);
      } catch (const std::exception& e) {
        if (!wal_dead_logged_) {
          wal_dead_logged_ = true;
          LOG_ERROR("digest depart WAL append failed (" << e.what() << ")");
        }
      }
      std::vector<std::string> ids;
      ids.reserve(entries.size());
      for (const auto& e : entries) ids.push_back(e.replica_id);
      wal_log_members_locked(ids);
    }
    regions_[req.region_id()] =
        RegionInfo{now_ms(), static_cast<int64_t>(entries.size())};
    // A digest can both register participants and remove stragglers.
    quorum_tick_locked();
    resp.set_quorum_gen(quorum_gen_);
  }
  send_msg(sock, MsgType::kRegionDigestResp, resp);
}

void Lighthouse::handle_region_poll(Socket& sock, const std::string& payload) {
  if (reject_if_standby(sock)) return;
  torchft_tpu::RegionPollRequest req;
  if (!req.ParseFromString(payload)) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "bad region poll request");
    return;
  }
  int64_t deadline = req.timeout_ms() <= 0 ? -1 : now_ms() + req.timeout_ms();

  UniqueMutexLock lock(mu_);
  while (quorum_gen_ <= req.min_gen() && !shutting_down_ && active_) {
    if (deadline < 0) {
      quorum_cv_.wait(lock);
    } else {
      int64_t remain = deadline - now_ms();
      if (remain <= 0) {
        lock.unlock();
        send_error(sock, ErrorResponse::DEADLINE_EXCEEDED,
                   "region poll timed out");
        return;
      }
      quorum_cv_.wait_for(lock, std::chrono::milliseconds(remain));
    }
  }
  if (shutting_down_) {
    lock.unlock();
    send_error(sock, ErrorResponse::CANCELLED, "lighthouse shutting down");
    return;
  }
  if (!active_) {
    lock.unlock();
    send_error(sock, ErrorResponse::UNAVAILABLE, kStandbyMsg);
    return;
  }
  torchft_tpu::RegionPollResponse resp;
  *resp.mutable_quorum() = latest_quorum_;
  resp.set_gen(quorum_gen_);
  lock.unlock();
  send_msg(sock, MsgType::kRegionPollResp, resp);
}

void Lighthouse::push_quorum_to_peers_locked(const torchft_tpu::Quorum& q) {
  if (peers_.empty()) return;
  // Held-lock network IO, deliberately: the promise must reach the
  // standby's WAL before ANY waiter can observe it, and commits are rare
  // (membership changes only). The deadline is short — a dead peer costs
  // one bounded stall per commit, never an unbounded one.
  int64_t timeout = std::min<int64_t>(250, std::max<int64_t>(50, takeover_ms_ / 4));
  for (const auto& peer : peers_) {
    try {
      torchft_tpu::RootSyncRequest req;
      req.set_root_epoch(root_epoch_);
      req.set_quorum_gen(quorum_gen_ + 1);
      *req.mutable_quorum() = q;
      auto resp =
          call<torchft_tpu::RootSyncRequest, torchft_tpu::RootSyncResponse>(
              peer, MsgType::kRootSyncReq, req, MsgType::kRootSyncResp,
              timeout, timeout);
      seen_peer_epoch_ = std::max(seen_peer_epoch_, resp.root_epoch());
    } catch (const std::exception&) {
      // Best-effort: an unreachable standby resyncs via its pull loop.
    }
  }
}

void Lighthouse::handle_root_sync(Socket& sock, const std::string& payload) {
  torchft_tpu::RootSyncRequest req;
  req.ParseFromString(payload);  // empty/garbage payload: epoch 0, harmless
  torchft_tpu::RootSyncResponse resp;
  {
    MutexLock lock(mu_);
    seen_peer_epoch_ = std::max(seen_peer_epoch_, req.root_epoch());
    if (req.has_quorum() && req.root_epoch() >= root_epoch_) {
      // PUSH form: the active peer replicates a fresh commit. Apply the
      // watermark (never regress), make it durable BEFORE acking, and
      // treat the push as proof of an alive active root. An active root
      // receiving a higher-epoch push has been deposed — fence.
      if (active_ && req.root_epoch() > root_epoch_) {
        active_ = false;
        LOG_WARN("deposed by a root-sync push at epoch "
                 << req.root_epoch() << " > ours " << root_epoch_
                 << "; demoting to standby");
        quorum_cv_.notify_all();
      }
      if (!active_) {
        const Quorum& q = req.quorum();
        if (q.quorum_id() >= state_.quorum_id) {
          state_.quorum_id = q.quorum_id();
          state_.prev_quorum = q;
          latest_quorum_ = q;
          quorum_gen_ = std::max(quorum_gen_, req.quorum_gen());
          if (wal_ && q.quorum_id() > wal_quorum_logged_) {
            try {
              wal_->log_quorum(q, quorum_gen_, req.root_epoch());
              wal_quorum_logged_ = q.quorum_id();
            } catch (const std::exception& e) {
              if (!wal_dead_logged_) {
                wal_dead_logged_ = true;
                LOG_ERROR("standby push WAL append failed (" << e.what()
                                                             << ")");
              }
            }
          }
        }
        last_sync_ok_ms_ = now_ms();
      }
    }
    resp.set_root_epoch(root_epoch_);
    resp.set_active(active_);
    resp.set_claim_nonce(claim_nonce_);
    resp.set_quorum_id(state_.quorum_id);
    resp.set_quorum_gen(quorum_gen_);
    if (active_) {
      // Full membership as age-relative digest entries — the exact wire
      // form the region tier pushes, so the standby's mirror rides the
      // same clock-skew-free reconstruction.
      digest_to_pb(make_digest(state_, now_ms(), opt_), &resp);
      if (state_.prev_quorum.has_value())
        *resp.mutable_quorum() = *state_.prev_quorum;
    }
  }
  send_msg(sock, MsgType::kRootSyncResp, resp);
}

void Lighthouse::probe_peers_fence() {
  int64_t my_epoch;
  {
    MutexLock lock(mu_);
    if (!active_) return;
    my_epoch = root_epoch_;
  }
  for (const auto& peer : peers_) {
    torchft_tpu::RootSyncResponse resp;
    if (!root_sync_call(peer, my_epoch, 1000, &resp)) continue;
    MutexLock lock(mu_);
    seen_peer_epoch_ = std::max(seen_peer_epoch_, resp.root_epoch());
    // Deposed: a peer claimed a higher epoch while we were down or
    // stalled — or the SAME epoch (a collided claim: our startup probe
    // missed it), broken by claim-nonce order so exactly one side
    // demotes. Fence — become its standby instead of forking history.
    bool deposed =
        resp.active() &&
        (resp.root_epoch() > root_epoch_ ||
         (resp.root_epoch() == root_epoch_ &&
          resp.claim_nonce() > claim_nonce_));
    if (active_ && deposed) {
      active_ = false;
      last_sync_ok_ms_ = now_ms();
      LOG_WARN("deposed: peer " << peer << " is ACTIVE at root epoch "
                                << resp.root_epoch() << " (ours "
                                << root_epoch_ << "); demoting to standby");
      // Wake parked long-polls so they bail out with the standby error
      // instead of stalling to their deadlines.
      quorum_cv_.notify_all();
    }
  }
}

bool Lighthouse::sync_from_peers() {
  int64_t my_epoch;
  {
    MutexLock lock(mu_);
    my_epoch = root_epoch_;
  }
  for (const auto& peer : peers_) {
    torchft_tpu::RootSyncResponse resp;
    if (!root_sync_call(peer, my_epoch,
                        std::min<int64_t>(takeover_ms_ / 2 + 1, 2000), &resp))
      continue;
    MutexLock lock(mu_);
    seen_peer_epoch_ = std::max(seen_peer_epoch_, resp.root_epoch());
    if (!resp.active()) continue;  // a fellow standby: epoch info only
    // Full-replace the mirror from the active root's digest: members the
    // primary departed/pruned simply stop appearing, so no tombstone
    // protocol is needed.
    LighthouseState fresh;
    fresh.quorum_id = resp.quorum_id();
    if (resp.has_quorum()) fresh.prev_quorum = resp.quorum();
    int64_t now = now_ms();
    for (const auto& e : digest_from_pb(resp)) {
      if (e.replica_id.empty()) continue;
      fresh.heartbeats[e.replica_id] = now - e.lease_age_ms;
      if (e.ttl_ms > 0) fresh.lease_ttls[e.replica_id] = e.ttl_ms;
      if (!e.status_json.empty())
        fresh.member_status[e.replica_id] = e.status_json;
      if (e.participating) {
        fresh.participants[e.replica_id] =
            ParticipantDetails{now - e.joined_age_ms, e.member};
      }
    }
    bool advanced = fresh.quorum_id > wal_quorum_logged_;
    state_ = std::move(fresh);
    if (resp.has_quorum()) latest_quorum_ = resp.quorum();
    quorum_gen_ = std::max(quorum_gen_, resp.quorum_gen());
    last_sync_ok_ms_ = now;
    // Standby-side durability: the mirrored watermark must survive OUR
    // crash too, or a restart-then-takeover could regress below what the
    // fleet already saw. A full snapshot per advance also keeps the
    // mirrored leases warm on disk.
    if (wal_ && advanced) {
      try {
        wal_->snapshot(state_, quorum_gen_, root_epoch_, now_ms(), unix_ms());
        wal_quorum_logged_ = state_.quorum_id;
      } catch (const std::exception& e) {
        if (!wal_dead_logged_) {
          wal_dead_logged_ = true;
          LOG_ERROR("standby snapshot failed (" << e.what() << ")");
        }
      }
    }
    return true;
  }
  return false;
}

void Lighthouse::do_takeover() {
  MutexLock lock(mu_);
  if (active_ || shutting_down_) return;
  int64_t epoch = std::max(root_epoch_, seen_peer_epoch_) + 1;
  if (wal_) {
    // The epoch claim must be durable BEFORE serving: a takeover that
    // crashes and restarts must still outrank the primary it deposed.
    try {
      wal_->log_epoch(epoch);
    } catch (const std::exception& e) {
      if (!wal_dead_logged_) {
        wal_dead_logged_ = true;
        LOG_ERROR("takeover epoch WAL append failed (" << e.what()
                                                       << "); staying standby");
      }
      return;
    }
  }
  root_epoch_ = epoch;
  claim_nonce_ = fresh_claim_nonce();
  active_ = true;
  last_tick_ms_ = now_ms();
  LOG_WARN("standby TAKEOVER: no active-root sync within "
           << takeover_ms_ << " ms; serving as root epoch " << root_epoch_
           << " (quorum_id watermark " << state_.quorum_id << ")");
}

void Lighthouse::peer_loop() {
  while (!shutting_down_) {
    bool active;
    {
      MutexLock lock(mu_);
      active = active_;
    }
    int64_t nap;
    if (active) {
      probe_peers_fence();
      nap = std::max<int64_t>(500, takeover_ms_ / 2);
    } else {
      bool ok = sync_from_peers();
      int64_t starving_ms;
      {
        MutexLock lock(mu_);
        starving_ms = now_ms() - last_sync_ok_ms_;
      }
      if (!ok && starving_ms > takeover_ms_) do_takeover();
      nap = std::max<int64_t>(50, std::min<int64_t>(takeover_ms_ / 4, 1000));
    }
    while (nap > 0 && !shutting_down_) {
      int64_t chunk = nap < 50 ? nap : 50;
      struct timespec ts;
      ts.tv_sec = chunk / 1000;
      ts.tv_nsec = (chunk % 1000) * 1000000;
      nanosleep(&ts, nullptr);
      nap -= chunk;
    }
  }
}

namespace {

const char kIndexHtml[] = R"html(<!DOCTYPE html>
<html>
<head>
<title>torchft_tpu lighthouse</title>
<style>
body { font-family: system-ui, sans-serif; margin: 2em; background: #10141a; color: #e6e6e6; }
h1 { font-size: 1.4em; }
.card { border: 1px solid #2c3442; border-radius: 8px; padding: 0.8em 1.2em; margin: 0.6em 0; background: #161c26; }
.recovering { border-color: #e0912f; }
.muted { color: #8b96a8; font-size: 0.9em; }
button { background: #933; color: #fff; border: none; border-radius: 4px; padding: 0.3em 0.8em; cursor: pointer; }
table { border-collapse: collapse; }
td, th { padding: 0.2em 0.8em; text-align: left; }
</style>
</head>
<body>
<h1>torchft_tpu lighthouse</h1>
<div id="status">loading...</div>
<script>
async function refresh() {
  try {
    const r = await fetch('/status');
    document.getElementById('status').innerHTML = await r.text();
  } catch (e) {}
}
async function kill(id) {
  await fetch('/replica/' + encodeURIComponent(id) + '/kill', {method: 'POST'});
}
refresh();
setInterval(refresh, 1000);
</script>
</body>
</html>
)html";

} // namespace

std::string Lighthouse::render_status_locked() {
  auto [_, quorum_status] = quorum_compute(now_ms(), state_, opt_);

  int64_t max_step = -1;
  int64_t num_participants = -1;
  if (state_.prev_quorum.has_value()) {
    num_participants = state_.prev_quorum->participants_size();
    for (const auto& p : state_.prev_quorum->participants())
      max_step = std::max(max_step, p.step());
  }

  std::ostringstream os;
  os << "<div class=card><b>Quorum " << state_.quorum_id << "</b> &mdash; "
     << num_participants << " participants, max step " << max_step;
  if (state_.quorum_formed_ms >= 0) {
    int64_t age_s = (now_ms() - state_.quorum_formed_ms) / 1000;
    os << ", age " << age_s << " s";
  }
  os << "<div class=muted>" << html_escape(quorum_status) << "</div></div>";

  if (state_.prev_quorum.has_value()) {
    for (const auto& p : state_.prev_quorum->participants()) {
      bool recovering = p.step() != max_step;
      os << "<div class='card" << (recovering ? " recovering" : "") << "'><b>"
         << html_escape(p.replica_id()) << "</b>"
         << (recovering ? " <span class=muted>(recovering)</span>" : "")
         << "<table>"
         << "<tr><td>step</td><td>" << p.step() << "</td></tr>"
         << "<tr><td>manager</td><td>" << html_escape(p.address()) << "</td></tr>"
         << "<tr><td>store</td><td>" << html_escape(p.store_address()) << "</td></tr>"
         << "<tr><td>world size</td><td>" << p.world_size() << "</td></tr>"
         << "</table>"
         // replica_id reaches JS only via dataset (never inlined in code),
         // so a hostile id can't escape into script.
         << "<button data-rid=\"" << html_escape(p.replica_id())
         << "\" onclick=\"kill(this.dataset.rid)\">Kill</button></div>";
    }
  }

  os << "<div class=card><b>Heartbeats</b><table>";
  int64_t now = now_ms();
  for (const auto& [replica_id, last] : state_.heartbeats) {
    bool old = now - last >= opt_.heartbeat_timeout_ms;
    os << "<tr><td>" << html_escape(replica_id) << "</td><td"
       << (old ? " style='color:#e0912f'" : "") << ">" << (now - last)
       << " ms ago</td></tr>";
  }
  os << "</table></div>";

  if (!state_.events.empty()) {
    os << "<div class=card><b>Events</b>";
    for (const auto& ev : state_.events)
      os << "<div class=muted>" << html_escape(ev) << "</div>";
    os << "</div>";
  }
  return os.str();
}

Json Lighthouse::status_json_locked() {
  int64_t now = now_ms();
  JsonObject o;
  o["role"] = std::string(!active_ ? "standby"
                                   : (regions_.empty() ? "flat" : "root"));
  o["active"] = active_;
  // Durability stamps: a COLD root (nothing to remember) and an AMNESIAC
  // one (had state, lost it) look identical in the member list — the
  // root_epoch + wal_replayed pair tells them apart: a restarted durable
  // root shows wal_replayed=true and root_epoch >= 2.
  o["root_epoch"] = root_epoch_;
  o["wal_enabled"] = wal_ != nullptr;
  o["wal_replayed"] = wal_replayed_;
  if (wal_) {
    JsonObject w;
    w["records_replayed"] = wal_records_replayed_;
    w["dropped_tail_bytes"] = wal_dropped_tail_bytes_;
    w["replay_ms"] = wal_replay_ms_;
    w["records_appended"] = wal_->records_appended();
    w["snapshots_written"] = wal_->snapshots_written();
    w["dead"] = wal_->dead();
    o["wal"] = Json(std::move(w));
  }
  if (!peers_.empty() || opt_.standby) {
    JsonArray ps;
    for (const auto& p : peers_) ps.push_back(Json(p));
    o["peers"] = Json(std::move(ps));
    o["seen_peer_epoch"] = seen_peer_epoch_;
    if (!active_) o["last_sync_age_ms"] = now - last_sync_ok_ms_;
  }
  o["quorum_id"] = state_.quorum_id;
  o["quorum_gen"] = quorum_gen_;
  if (state_.quorum_formed_ms >= 0) {
    o["quorum_age_ms"] = now - state_.quorum_formed_ms;
  } else {
    o["quorum_age_ms"] = Json();
  }
  if (state_.prev_quorum.has_value()) {
    o["quorum"] = quorum_to_json(*state_.prev_quorum);
  } else {
    o["quorum"] = Json();
  }

  JsonArray members;
  for (const auto& [replica_id, last] : state_.heartbeats) {
    JsonObject m;
    m["replica_id"] = replica_id;
    int64_t ttl = lease_ttl_for(state_, replica_id, opt_);
    m["ttl_ms"] = ttl;
    m["lease_remaining_ms"] = last + ttl - now;
    m["participating"] = state_.participants.count(replica_id) > 0;
    auto st = state_.member_status.find(replica_id);
    if (st != state_.member_status.end()) {
      try {
        m["status"] = Json::parse(st->second);
      } catch (const std::exception&) {
        m["status"] = st->second; // unparseable digest: surface raw
      }
    }
    members.push_back(Json(std::move(m)));
  }
  o["members"] = Json(std::move(members));

  JsonArray parts;
  for (const auto& [replica_id, _] : state_.participants)
    parts.push_back(Json(replica_id));
  o["participants"] = Json(std::move(parts));

  JsonObject tick;
  tick["total"] = ticks_total_;
  tick["computed"] = ticks_computed_;
  tick["last_compute_us"] = last_compute_us_;
  tick["total_compute_us"] = total_compute_us_;
  o["tick"] = Json(std::move(tick));

  JsonArray regions;
  for (const auto& [region_id, info] : regions_) {
    JsonObject r;
    r["region_id"] = region_id;
    r["last_digest_age_ms"] = now - info.last_digest_ms;
    r["entries"] = info.entries;
    regions.push_back(Json(std::move(r)));
  }
  o["regions"] = Json(std::move(regions));

  JsonArray events;
  for (const auto& ev : state_.events) events.push_back(Json(ev));
  o["events"] = Json(std::move(events));
  return Json(std::move(o));
}

std::string Lighthouse::status_json() {
  Json j;
  {
    MutexLock lock(mu_);
    j = status_json_locked();
  }
  JsonObject& o = j.as_object();
  o["open_conns"] = static_cast<int64_t>(conns_.size());
  o["address"] = address();
  return j.dump();
}

void Lighthouse::handle_http(Socket& sock, const std::string& head) {
  std::istringstream is(head);
  std::string method, path;
  is >> method >> path;

  if (method == "GET" && (path == "/" || path.empty())) {
    http_respond(sock, 200, "text/html", kIndexHtml);
  } else if (method == "GET" && path == "/status.json") {
    http_respond(sock, 200, "application/json", status_json());
  } else if (method == "GET" && path == "/status") {
    std::string body;
    {
      MutexLock lock(mu_);
      body = render_status_locked();
    }
    http_respond(sock, 200, "text/html", body);
  } else if (method == "POST" && path.rfind("/replica/", 0) == 0 &&
             path.size() > 14 && path.compare(path.size() - 5, 5, "/kill") == 0) {
    std::string replica_id = path.substr(9, path.size() - 9 - 5);
    std::string addr;
    {
      MutexLock lock(mu_);
      if (state_.prev_quorum.has_value()) {
        for (const auto& p : state_.prev_quorum->participants()) {
          if (p.replica_id() == replica_id) {
            addr = p.address();
            break;
          }
        }
      }
    }
    if (addr.empty()) {
      http_respond(sock, 404, "text/plain", "failed to find replica");
      return;
    }
    try {
      ManagerClient client(addr, /*connect_timeout_ms=*/10000);
      client.kill("killed from dashboard");
      http_respond(sock, 200, "text/plain", "ok");
    } catch (const std::exception& e) {
      http_respond(sock, 500, "text/plain", e.what());
    }
  } else {
    http_respond(sock, 404, "text/plain", "not found");
  }
}

} // namespace tft
