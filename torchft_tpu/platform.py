"""Backend-selection helper for entry scripts.

On hosts where a sitecustomize registers and pins an accelerator backend
via ``jax.config`` at interpreter start, the ``JAX_PLATFORMS`` env var
alone loses that race — subprocesses that must run on CPU (tests, local
replica-group simulation, bench peers) silently land on the accelerator
and pay a device round-trip per collective. Entry points call
:func:`apply_jax_platform_env` right after ``import jax`` to make the env
var authoritative again.
"""

from __future__ import annotations

import os


def apply_jax_platform_env() -> None:
    """Re-applies ``JAX_PLATFORMS`` through ``jax.config`` (no-op when the
    env var is unset or jax is already initialized on the right backend)."""
    platforms = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if not platforms:
        return
    import jax

    jax.config.update("jax_platforms", platforms)
