"""TPU-native fused ops (pallas kernels).

The reference framework has no custom kernels (its hot ops live inside
PyTorch/NCCL); on TPU the hot op of the flagship training loop is
attention, implemented here as a fused pallas flash-attention kernel so
the O(S²) score matrix never round-trips HBM. The wire-compression
kernels (quantize/dequantize/cast) move gradient-sync packing onto the
accelerator so d2h bytes scale with the wire size, not the f32 size.
"""

from .quantize_kernels import (
    cast_bf16,
    dequantize_q8,
    quantize_q8,
    quantize_q8_ef,
)

try:
    from .flash_attention import flash_attention
except ImportError as _e:  # old jax without jax.shard_map: the flash
    # kernel's sharded entry is unimportable there, but the wire-
    # compression kernels above have no mesh dependency and must keep
    # serving the device-pack path. Callers get the original error.
    _flash_import_error = _e

    def flash_attention(*args, **kwargs):  # type: ignore[misc]
        raise ImportError(
            "torchft_tpu.ops.flash_attention is unavailable: "
            f"{_flash_import_error}"
        )

__all__ = [
    "flash_attention",
    "cast_bf16",
    "dequantize_q8",
    "quantize_q8",
    "quantize_q8_ef",
]
