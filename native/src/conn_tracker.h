// Per-connection thread bookkeeping shared by the three servers. Handler
// threads are detached and self-reap (remove their fd and wake shutdown), so
// long-lived servers don't accumulate zombie threads or stale fd numbers.
#pragma once

#include <cstdint>
#include <map>
#include <sys/socket.h>
#include <thread>

#include "net.h"
#include "thread_annotations.h"

namespace tft {

class ConnTracker {
 public:
  // Spawns a detached handler thread for sock. Returns false (dropping the
  // connection) if shutdown already started.
  template <typename Fn>
  bool spawn(Socket sock, Fn fn) {
    uint64_t id;
    {
      MutexLock lock(mu_);
      if (shutting_down_) return false;
      id = next_id_++;
      fds_[id] = sock.fd();
      active_++;
    }
    std::thread([this, id, s = std::move(sock), fn = std::move(fn)]() mutable {
      fn(s);
      MutexLock lock(mu_);
      fds_.erase(id);
      active_--;
      cv_.notify_all();
    }).detach();
    return true;
  }

  // Currently-open handler count (the control-plane fan-in metric the
  // lighthouse status view reports).
  size_t size() {
    MutexLock lock(mu_);
    return active_;
  }

  // Wakes all handlers blocked in socket IO and waits until every handler
  // thread has finished. Callers must first unblock handlers waiting on
  // their own condition variables.
  void shutdown_all() {
    UniqueMutexLock lock(mu_);
    shutting_down_ = true;
    for (const auto& [id, fd] : fds_) ::shutdown(fd, SHUT_RDWR);
    while (active_ != 0) cv_.wait(lock);
  }

 private:
  Mutex mu_;
  CondVar cv_;
  std::map<uint64_t, int> fds_ TFT_GUARDED_BY(mu_);
  uint64_t next_id_ TFT_GUARDED_BY(mu_) = 0;
  size_t active_ TFT_GUARDED_BY(mu_) = 0;
  bool shutting_down_ TFT_GUARDED_BY(mu_) = false;
};

} // namespace tft
