"""graftcheck's own tests: the BFS explorer, the replay contract, every
protocol model's clean sweep (budget-capped for CI; the committed full
budget is the slow tier + the CI ``graftcheck`` job), and the seeded
regressions — every deliberately-broken variant must produce a
counterexample with a replay line, or the checker has stopped seeing
the bug its fence exists to prevent.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT / "tools"))

import graftcheck  # noqa: E402
from graftcheck.core import (  # noqa: E402
    Model,
    ReplayError,
    explore,
    replay,
)

# (model, broken-variant) -> the property the counterexample must hit.
EXPECTED_REGRESSIONS = {
    ("step_txn", "stale_votes"): "silent_commit",
    ("lease", "stale_digest"): "hb_monotonic",
    ("lease", "no_prune"): "no_expired_in_quorum",
    ("wal", "publish_before_log"): "promise_durable",
    ("wal", "no_fence_probe"): "qid_monotone",
    ("durable", "commit_without_fence"): "commit_complete",
    ("durable", "delete_before_retire"): "commit_complete",
    ("durable", "use_torn_tail"): "torn_manifest_wins",
    ("decision", "leader_broadcast"): "uniform_data_step",
    ("decision", "argmin_all_sentinel"): "adopt_sentinel",
    ("serving", "no_integrity"): "no_torn_install",
}


class _Counter(Model):
    """Tiny reference system: a counter that may inc or (once) skip, with
    the property that it never reaches 4 via a skip."""

    name = "counter"
    properties = ("no_skip_to_4",)

    def initial(self):
        return (0, 0)  # (value, skipped)

    def actions(self, state):
        v, skipped = state
        acts = []
        if v < 4:
            acts.append(("inc", (v + 1, skipped)))
        if not skipped and v < 3:
            acts.append(("skip", (v + 2, 1)))
        return acts

    def check(self, state):
        v, skipped = state
        return ["no_skip_to_4"] if (v == 4 and skipped) else []


class TestCore:
    def test_bfs_finds_shortest_violation(self):
        result = explore(_Counter())
        assert result.violation is not None
        assert result.violation.prop == "no_skip_to_4"
        # BFS: the 3-action witness (skip, inc, inc), not a longer one.
        assert len(result.violation.trace) == 3
        assert result.violation.trace.count("skip") == 1

    def test_exploration_complete_and_deduped(self):
        class Clean(_Counter):
            def check(self, state):
                return []

        result = explore(Clean())
        assert result.complete and not result.truncated_by
        # states (v, s): (0,0) (1,0) (2,0) (3,0) (4,0) (2,1) (3,1) (4,1)
        assert result.states == 8
        assert result.ok

    def test_budget_truncation_flagged(self):
        class Clean(_Counter):
            def check(self, state):
                return []

        result = explore(Clean(), max_states=3)
        assert not result.complete and result.truncated_by == "max_states"

    def test_replay_follows_labels_and_rejects_unknown(self):
        model = _Counter()
        states = replay(model, ["skip", "inc", "inc"])
        assert states[0] == (0, 0) and states[-1] == (4, 1)
        assert model.check(states[-1]) == ["no_skip_to_4"]
        with pytest.raises(ReplayError):
            replay(model, ["warp"])

    def test_replay_line_format(self):
        result = explore(_Counter())
        line = result.violation.replay_line()
        assert line.startswith("replay: --model counter --trace '")
        labels = json.loads(line.split("--trace ", 1)[1].strip("'"))
        assert tuple(labels) == tuple(result.violation.trace)


class TestModelsClean:
    """Every model's correct variant is violation-free. CI-tier budget is
    capped (seconds); the committed full budget runs in the slow tier
    and the dedicated CI job."""

    @pytest.mark.parametrize("name", graftcheck.MODEL_NAMES)
    def test_capped_sweep_clean(self, name):
        result = explore(graftcheck.make(name), max_states=60_000)
        assert result.violation is None, result.violation.replay_line()
        # a model this small would assert nothing worth checking
        assert result.states > 1_000

    @pytest.mark.slow
    @pytest.mark.parametrize("name", graftcheck.MODEL_NAMES)
    def test_full_budget_sweep_clean(self, name):
        result = explore(graftcheck.make(name))
        assert result.violation is None, result.violation.replay_line()

    def test_nontrivial_state_spaces(self):
        # The --dryrun CI smoke's contract: >=1 model clears 10k distinct
        # states even under a 20k cap (here: wal completes under it).
        result = explore(graftcheck.make("wal"))
        assert result.complete and result.states > 10_000


class TestRegressions:
    """The acceptance-criteria seeded regressions: a deliberately broken
    protocol variant (e.g. a manifest commit without the WAL fence) must
    yield a counterexample whose replay reaches the violated property."""

    def test_registry_matches_expectations(self):
        have = {
            (name, b)
            for name in graftcheck.MODEL_NAMES
            for b in graftcheck.broken_variants(name)
        }
        assert have == set(EXPECTED_REGRESSIONS)

    @pytest.mark.parametrize(
        "name,broken", sorted(EXPECTED_REGRESSIONS), ids="/".join
    )
    def test_broken_variant_produces_counterexample(self, name, broken):
        model = graftcheck.make(name, broken)
        result = explore(model)
        assert result.violation is not None, (
            f"{name}/{broken}: no counterexample — the checker no longer "
            "sees the bug this fence exists to prevent"
        )
        assert result.violation.prop == EXPECTED_REGRESSIONS[(name, broken)]
        # the counterexample replays: same labels, same violating state
        states = replay(model, result.violation.trace)
        assert states[-1] == result.violation.state
        assert result.violation.prop in model.check(states[-1])

    def test_commit_without_fence_replay_line(self):
        # The ISSUE's canonical regression, pinned end to end: manifest
        # commit without the all-writers marker fence -> an incomplete
        # set wins restore.
        result = explore(graftcheck.make("durable", "commit_without_fence"))
        line = result.violation.replay_line()
        assert "--model durable_commit_without_fence" in line
        assert "commit" in "".join(result.violation.trace)


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, str(REPO_ROOT / "scripts/graftcheck.py"), *args],
            capture_output=True,
            text=True,
        )

    def test_dryrun_smoke(self):
        proc = self._run("--dryrun")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ok (max" in proc.stdout

    def test_broken_variant_exits_zero_only_when_found(self):
        proc = self._run(
            "--model", "durable", "--broken", "commit_without_fence"
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "replay: --model durable_commit_without_fence" in proc.stdout

    def test_trace_replay_reports_violation(self):
        find = self._run(
            "--model", "wal", "--broken", "publish_before_log"
        )
        trace_line = next(
            ln for ln in find.stdout.splitlines() if "replay:" in ln
        )
        trace = trace_line.split("--trace ", 1)[1].strip().strip("'")
        proc = self._run(
            "--model", "wal", "--broken", "publish_before_log",
            "--trace", trace,
        )
        assert proc.returncode == 1
        assert "violates: promise_durable" in proc.stdout

    def test_unknown_model_usage_error(self):
        assert self._run("--model", "nope").returncode == 2

    @pytest.mark.slow
    def test_full_sweep_clean(self):
        proc = self._run()
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "all models clean" in proc.stdout
