import os
import subprocess
import sys

# JAX on a virtual 8-device CPU mesh: multi-chip sharding paths are tested
# without TPU hardware (the driver's dryrun uses the same trick). Must be set
# before the first `import jax` anywhere in the test session.
# Force CPU even when a real TPU is tunneled in: the unit suite needs 8
# virtual devices (and TPU jit compiles are 20-40s each); the driver runs
# bench.py / dryrun on real hardware separately. The axon sitecustomize
# pins the TPU backend via jax.config at startup, so the env var alone is
# not enough — override the config after import too.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

_LIB = os.path.join(REPO_ROOT, "torchft_tpu", "_libtorchft.so")
if not os.path.exists(_LIB):
    subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "native")], check=True)

def pytest_configure(config):
    # tier-1 filters with -m 'not slow'; register the marker so it is a
    # contract, not a typo-prone string.
    config.addinivalue_line(
        "markers",
        "slow: long-running chaos/fleet schedules excluded from tier-1",
    )


# -- environment capability gates ------------------------------------------
# Tier-1 runs on heterogeneous boxes; these two capabilities are absent on
# some of them and their absence is an ENVIRONMENT property, not a code
# defect — the affected tests skip with a precise reason instead of
# failing, so an unexpected failure always means a real regression.

# New-style top-level `jax.shard_map` (varying-manual-axes typing, jax
# >= 0.6). context_parallel / pipeline / flash_attention import it
# directly; older jax only ships jax.experimental.shard_map, whose typing
# semantics those modules do not target.
HAS_SHARD_MAP = hasattr(jax, "shard_map")
SHARD_MAP_SKIP = (
    "this jax lacks top-level jax.shard_map (new-style shard_map with "
    "varying-manual-axes typing) required by the sharded model-parallel "
    "modules"
)

# Cross-process collectives on the CPU backend. jaxlib only wires a CPU
# collectives implementation (gloo, selected via the
# `jax_cpu_collectives_implementation` config / env) into the CPU client
# from jax ~0.5 on; older builds raise "Multiprocess computations aren't
# implemented on the CPU backend" at first cross-process dispatch, so the
# config's absence is the capability probe.
HAS_CPU_MULTIPROCESS = hasattr(
    jax.config, "jax_cpu_collectives_implementation"
)
CPU_MULTIPROCESS_SKIP = (
    "this jax/jaxlib has no CPU multiprocess collectives backend (no "
    "jax_cpu_collectives_implementation config): cross-process CPU "
    "computations raise at dispatch"
)
