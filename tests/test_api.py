"""Unit tests for the integration API layer: OptimizerWrapper, FTTrainState,
DistributedDataParallel, DistributedSampler. Mirrors reference optim_test.py,
ddp_test.py, data_test.py (autospec'd Manager pattern)."""

from unittest.mock import MagicMock, create_autospec

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import FTTrainState, OptimizerWrapper
from torchft_tpu.collectives import _completed
from torchft_tpu.data import DistributedSampler, StatefulDataLoader
from torchft_tpu.ddp import DistributedDataParallel
from torchft_tpu.manager import Manager


def _state():
    params = {"w": jnp.ones((3,), jnp.float32)}
    return FTTrainState(params, optax.sgd(0.5))


class TestOptimizerWrapper:
    def test_zero_grad_starts_quorum(self):
        manager = create_autospec(Manager, instance=True)
        opt = OptimizerWrapper(manager, _state())
        opt.zero_grad()
        manager.start_quorum.assert_called_once()

    def test_step_applies_on_commit(self):
        manager = create_autospec(Manager, instance=True)
        manager.should_commit.return_value = True
        state = _state()
        opt = OptimizerWrapper(manager, state)
        assert opt.step({"w": jnp.full((3,), 2.0)})
        np.testing.assert_allclose(np.asarray(state.params["w"]), 0.0)

    def test_step_skips_on_abort(self):
        manager = create_autospec(Manager, instance=True)
        manager.should_commit.return_value = False
        state = _state()
        before = np.asarray(state.params["w"]).copy()
        opt = OptimizerWrapper(manager, state)
        assert not opt.step({"w": jnp.full((3,), 2.0)})
        np.testing.assert_array_equal(np.asarray(state.params["w"]), before)


class TestFTTrainState:
    def test_load_restores_jax_arrays(self):
        state = _state()
        state.apply_gradients({"w": jnp.ones((3,))})
        snapshot = state.state_dict()
        host = {
            "params": {"w": np.asarray(snapshot["params"]["w"])},
            "opt_state": snapshot["opt_state"],
        }
        fresh = _state()
        fresh.load_state_dict(host)
        import jax

        assert isinstance(fresh.params["w"], jax.Array)
        np.testing.assert_array_equal(
            np.asarray(fresh.params["w"]), np.asarray(snapshot["params"]["w"])
        )

    def test_heal_then_apply_uses_healed_params(self):
        # The divergence regression: a heal applied via load_state_dict must
        # be what apply_gradients operates on.
        state = _state()
        state.load_state_dict({"params": {"w": np.full(3, 10.0, np.float32)},
                               "opt_state": state.opt_state})
        state.apply_gradients({"w": jnp.full((3,), 2.0)})
        np.testing.assert_allclose(np.asarray(state.params["w"]), 9.0)


class TestDDP:
    def test_allreduce_routes_through_manager(self):
        manager = create_autospec(Manager, instance=True)
        manager.allreduce.side_effect = lambda g: _completed(g)
        ddp = DistributedDataParallel(manager)
        grads = {"w": np.ones(2)}
        out = ddp.allreduce_grads(grads).wait()
        np.testing.assert_array_equal(out["w"], grads["w"])
        manager.allreduce.assert_called_once()

    def test_wrap_grad_fn(self):
        manager = create_autospec(Manager, instance=True)
        manager.allreduce.side_effect = lambda g: _completed(
            {k: v * 0.5 for k, v in g.items()}
        )
        ddp = DistributedDataParallel(manager)
        fn = ddp.wrap_grad_fn(lambda p: (1.25, {"g": np.full(2, 4.0)}))
        value, grads = fn({"unused": 0})
        assert value == 1.25
        np.testing.assert_array_equal(grads["g"], np.full(2, 2.0))


class TestDistributedSampler:
    def test_shards_partition_dataset(self):
        # Reference data_test.py:26-39 arithmetic.
        n, groups, ranks = 100, 2, 2
        seen = []
        for g in range(groups):
            for r in range(ranks):
                s = DistributedSampler(
                    n, replica_group=g, num_replica_groups=groups,
                    rank=r, num_replicas=ranks, shuffle=False,
                )
                idxs = list(s)
                assert len(idxs) == 25
                assert s.global_rank == r + ranks * g
                assert s.global_world_size == 4
                seen.extend(idxs)
        assert sorted(seen) == list(range(100))

    def test_shuffle_deterministic_per_epoch(self):
        a = DistributedSampler(50, 0, 2, seed=7)
        b = DistributedSampler(50, 0, 2, seed=7)
        assert list(a) == list(b)
        a.set_epoch(1)
        assert list(a) != list(b)

    def test_padding_when_uneven(self):
        s = DistributedSampler(10, 0, 3, shuffle=False)
        assert len(list(s)) == len(s) == 4  # ceil(10/3)

    def test_drop_last(self):
        s = DistributedSampler(10, 0, 3, shuffle=False, drop_last=True)
        assert len(list(s)) == 3


class TestStatefulDataLoader:
    """Dataloader-position recovery (reference train_ddp.py:57-61,141-148)."""

    def _loader(self, n=20, batch=4, shuffle=True, **kw):
        s = DistributedSampler(n, 0, 2, shuffle=shuffle, seed=3)
        return StatefulDataLoader(s, batch, **kw)

    def test_batches_cover_shard_then_roll_epoch(self):
        loader = self._loader(n=16, batch=4, shuffle=False)  # shard = 8 idxs
        b1, b2 = next(loader), next(loader)
        assert sorted(b1 + b2) == list(range(0, 16, 2))
        assert loader.epoch == 0 and loader.position == 8
        b3 = next(loader)  # epoch rolls: shard exhausted
        assert loader.epoch == 1 and loader.position == 4
        assert len(b3) == 4

    def test_resume_mid_epoch_bit_identical(self):
        # The oracle: a restored loader replays the EXACT remaining stream.
        a = self._loader()
        for _ in range(3):
            next(a)
        saved = a.state_dict()
        expected = [next(a) for _ in range(7)]

        b = self._loader()
        b.load_state_dict(saved)
        assert [next(b) for _ in range(7)] == expected

    def test_step_derived_offset_is_wrong_after_epoch_boundary(self):
        # The failure mode VERDICT #6 calls out: position-from-step ignores
        # the reshuffle at epoch boundaries.
        loader = self._loader(n=16, batch=4)  # 2 batches per epoch-shard
        stream = [next(loader) for _ in range(4)]  # crosses into epoch 1
        naive = self._loader(n=16, batch=4)
        flat = naive._sampler.indices_for_epoch(0) * 2
        naive_batches = [flat[i * 4 : (i + 1) * 4] for i in range(4)]
        assert stream[:2] == naive_batches[:2]
        assert stream[2:] != naive_batches[2:]  # epoch-1 reshuffle matters

    def test_drop_last_keeps_batch_shape_static(self):
        loader = self._loader(n=18, batch=4, shuffle=False)  # shard = 9
        sizes = [len(next(loader)) for _ in range(6)]
        assert sizes == [4, 4, 4, 4, 4, 4]  # tail of 1 dropped each epoch

    def test_keep_last_partial_batch(self):
        loader = self._loader(n=18, batch=4, shuffle=False, drop_last=False)
        sizes = [len(next(loader)) for _ in range(3)]
        assert sizes == [4, 4, 1]

    def test_batch_size_exceeding_shard_rejected(self):
        with pytest.raises(ValueError, match="exceeds the shard size"):
            self._loader(n=16, batch=16)  # shard is only 8

    def test_uncommitted_step_replay(self):
        # The train-loop discipline: save state before drawing, restore on
        # an uncommitted step, so the retry trains the same batch.
        loader = self._loader()
        ckpt = loader.state_dict()
        first = next(loader)
        loader.load_state_dict(ckpt)
        assert next(loader) == first

    def test_roundtrip_through_checkpoint_serialization(self):
        from torchft_tpu.checkpointing import (
            deserialize_state_dict,
            serialize_state_dict,
        )

        loader = self._loader()
        next(loader)
        sd = deserialize_state_dict(serialize_state_dict(loader.state_dict()))
        fresh = self._loader()
        fresh.load_state_dict(sd)
        assert fresh.state_dict() == loader.state_dict()
