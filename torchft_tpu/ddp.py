"""Fault-tolerant data parallelism across replica groups.

Reference: torchft/ddp.py — there, a comm-hook routes each gradient bucket
through ``Manager.allreduce`` during backward. JAX has no backward hooks;
gradients materialize as one pytree from ``jax.grad``, which is *better* for
this transport: the whole tree is packed into one ring pass per dtype by the
collectives layer (the bucketing DDP's reducer approximates).

Intra-replica-group sharding (FSDP/TP-style) stays in user pjit code over
the slice mesh — this wrapper only averages across groups, mirroring the
reference's division of labor (torchft owns the replicate dim only,
process_group.py:1067-1341).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

from .collectives import Work
from .manager import Manager


class DistributedDataParallel:
    """Averages gradient pytrees across replica groups, fault-tolerantly.

    Usage::

        ddp = DistributedDataParallel(manager)
        grads = grad_fn(params, batch)
        grads = ddp.allreduce_grads(grads).wait()   # async; overlap-friendly

    or wrap a grad function so the average happens on call::

        value_and_avg_grads = ddp.wrap_grad_fn(jax.value_and_grad(loss_fn))
    """

    def __init__(self, manager: Manager) -> None:
        self._manager = manager

    def allreduce_grads(self, grads: Any) -> Work:
        """Starts the async cross-group average of ``grads``; the Work
        resolves to the averaged pytree (input unchanged on error, with the
        error latched for ``should_commit`` — reference ddp.py:67-71)."""
        return self._manager.allreduce(grads)

    def wrap_grad_fn(
        self, grad_fn: Callable[..., Tuple[Any, Any]]
    ) -> Callable[..., Tuple[Any, Any]]:
        """Wraps a ``jax.value_and_grad``-style fn so returned grads are
        already averaged across replica groups (blocking)."""

        def wrapped(*args: Any, **kwargs: Any) -> Tuple[Any, Any]:
            value, grads = grad_fn(*args, **kwargs)
            return value, self.allreduce_grads(grads).wait()

        return wrapped
