"""Numerics of the pallas flash-attention kernel vs dense reference.

Runs in interpret mode on the CPU test mesh (conftest pins JAX_PLATFORMS=cpu
with 8 virtual devices); on real TPU the same code compiles to Mosaic.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import HAS_SHARD_MAP, SHARD_MAP_SKIP

if not HAS_SHARD_MAP:
    # the flash kernel's sharded entry imports jax.shard_map at module
    # load, so the guard must run before the import or collection errors
    pytest.skip(SHARD_MAP_SKIP, allow_module_level=True)

from torchft_tpu.ops import flash_attention


def dense_attention(q, k, v, causal=True, sm_scale=None, window=None):
    B, S, H, D = q.shape
    if sm_scale is None:
        sm_scale = D ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    qpos = jnp.arange(S)[:, None]
    kpos = jnp.arange(S)[None, :]
    mask = (qpos >= kpos) if causal else jnp.ones((S, S), jnp.bool_)
    if window is not None:
        mask = mask & (qpos - kpos < window)
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )


def rand_qkv(key, shape, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("S,blocks", [(128, (64, 64)), (96, (32, 32))])
def test_forward_matches_dense(causal, S, blocks):
    q, k, v = rand_qkv(jax.random.PRNGKey(0), (2, S, 2, 32))
    out = flash_attention(
        q, k, v, causal=causal, block_q=blocks[0], block_k=blocks[1]
    )
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_grads_match_dense():
    q, k, v = rand_qkv(jax.random.PRNGKey(1), (1, 64, 2, 16))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=32, block_k=32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            a, b, atol=1e-4, rtol=1e-4, err_msg=f"d{name}"
        )


def test_noncausal_grads_match_dense():
    q, k, v = rand_qkv(jax.random.PRNGKey(5), (1, 64, 1, 16))
    gf = jax.grad(
        lambda q: jnp.sum(
            flash_attention(q, k, v, causal=False, block_q=32, block_k=32)
        )
    )(q)
    gd = jax.grad(
        lambda q: jnp.sum(dense_attention(q, k, v, causal=False))
    )(q)
    np.testing.assert_allclose(gf, gd, atol=1e-4, rtol=1e-4)


def test_under_jit_bf16():
    q, k, v = rand_qkv(jax.random.PRNGKey(2), (2, 128, 4, 16), jnp.bfloat16)
    out = jax.jit(
        functools.partial(flash_attention, block_q=64, block_k=64)
    )(q, k, v)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32), atol=3e-2, rtol=3e-2
    )


def test_sharded_over_mesh_matches_dense():
    from torchft_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 2, "model": 4})
    q, k, v = rand_qkv(jax.random.PRNGKey(3), (2, 64, 4, 16))
    out = flash_attention(
        q, k, v, mesh=mesh, batch_axis="data", head_axis="model",
        block_q=32, block_k=32,
    )
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_transformer_flash_matches_dense_path():
    import dataclasses

    from torchft_tpu.models import init_params, loss_fn, tiny_config

    cfg = tiny_config()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 65)),
        jnp.int32,
    )
    cfg_flash = dataclasses.replace(cfg, use_flash=True)
    l_dense = loss_fn(cfg, params, tokens)
    l_flash = loss_fn(cfg_flash, params, tokens)
    np.testing.assert_allclose(l_flash, l_dense, atol=1e-4, rtol=1e-4)

    g_dense = jax.grad(lambda p: loss_fn(cfg, p, tokens))(params)
    g_flash = jax.grad(lambda p: loss_fn(cfg_flash, p, tokens))(params)
    leaves_d = jax.tree_util.tree_leaves(g_dense)
    leaves_f = jax.tree_util.tree_leaves(g_flash)
    for a, b in zip(leaves_f, leaves_d):
        np.testing.assert_allclose(a, b, atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_nondivisible_seq_is_padded_exactly(causal):
    # S=100 with 64-blocks: padded keys masked, padded query cotangents
    # zero — forward AND grads must match dense exactly
    q, k, v = rand_qkv(jax.random.PRNGKey(4), (1, 100, 2, 8))
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
            ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda q, k, v: jnp.sum(dense_attention(q, k, v, causal=causal) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            a, b, atol=1e-4, rtol=1e-4, err_msg=f"d{name}"
        )


def dense_windowed(q, k, v, window):
    return dense_attention(q, k, v, causal=True, window=window)


@pytest.mark.parametrize("window", [1, 16, 40, 200])
def test_sliding_window_matches_dense(window):
    # windows smaller than / straddling / larger than the 32-blocks
    q, k, v = rand_qkv(jax.random.PRNGKey(7), (1, 128, 2, 16))
    out = flash_attention(
        q, k, v, window=window, block_q=32, block_k=32
    )
    ref = dense_windowed(q, k, v, window)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_sliding_window_grads_match_dense():
    q, k, v = rand_qkv(jax.random.PRNGKey(8), (1, 96, 2, 8))
    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, window=24, block_q=32, block_k=32) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda q, k, v: jnp.sum(dense_windowed(q, k, v, 24) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            a, b, atol=1e-4, rtol=1e-4, err_msg=f"d{name}"
        )


def test_window_requires_causal():
    q, k, v = rand_qkv(jax.random.PRNGKey(9), (1, 64, 1, 8))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=8)


def test_transformer_attn_window():
    import dataclasses

    from torchft_tpu.models import init_params, loss_fn, tiny_config

    cfg = dataclasses.replace(tiny_config(), use_flash=True, attn_window=16)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 65)),
        jnp.int32,
    )
    l_win = float(loss_fn(cfg, params, tokens))
    l_full = float(
        loss_fn(dataclasses.replace(cfg, attn_window=None), params, tokens)
    )
    assert np.isfinite(l_win) and abs(l_win - l_full) > 1e-6  # window bites

    with pytest.raises(ValueError, match="use_flash"):
        dataclasses.replace(tiny_config(), attn_window=16)
    # windowing is not implemented on the CP paths: must refuse, not
    # silently train full-attention
    with pytest.raises(ValueError, match="context-parallel"):
        dataclasses.replace(
            tiny_config(), use_flash=True, attn_window=16,
            cp_seq_axis="seq",
        )
