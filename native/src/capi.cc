// C API consumed by torchft_tpu/_native.py via ctypes. Strings cross the
// boundary as malloc'd char* (caller frees with tft_string_free); structured
// values as JSON. Status codes: 0 ok, 1 timeout (Python raises TimeoutError,
// mirroring the reference's gRPC-status mapping in src/lib.rs:321-333),
// 2 other error (Python raises RuntimeError with tft_last_error()).
#include <cstring>
#include <string>

#include "collectives.h"
#include "fault.h"
#include "json.h"
#include "lighthouse.h"
#include "manager.h"
#include "net.h"
#include "quorum.h"
#include "region.h"
#include "shm.h"
#include "store.h"
#include "wal.h"
#include "wire.h"

using namespace tft;

namespace {

thread_local std::string g_last_error;

constexpr int kOk = 0;
constexpr int kTimeout = 1;
constexpr int kError = 2;

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.data(), s.size());
  out[s.size()] = '\0';
  return out;
}

char* dup_bytes(const std::string& s, size_t* len_out) {
  char* out = static_cast<char*>(malloc(s.size() ? s.size() : 1));
  memcpy(out, s.data(), s.size());
  *len_out = s.size();
  return out;
}

bool is_timeout(const torchft_tpu::ErrorResponse::Code code) {
  return code == torchft_tpu::ErrorResponse::DEADLINE_EXCEEDED ||
         code == torchft_tpu::ErrorResponse::CANCELLED;
}

// Runs fn, translating exceptions to status codes.
template <typename Fn>
int guarded(Fn&& fn) {
  try {
    fn();
    return kOk;
  } catch (const TimeoutError& e) {
    g_last_error = e.what();
    return kTimeout;
  } catch (const RpcError& e) {
    g_last_error = e.what();
    return is_timeout(e.code) ? kTimeout : kError;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return kError;
  } catch (...) {
    g_last_error = "unknown error";
    return kError;
  }
}

} // namespace

extern "C" {

const char* tft_last_error() { return g_last_error.c_str(); }

void tft_string_free(char* s) { free(s); }

// ---- Lighthouse ----

// wal_dir ("" = no durability), peers ("" = no failover set; comma-
// separated other root endpoints), standby (1 = start passive) and
// takeover_ms (0 = default) are the durable-control-plane knobs — see
// native/src/lighthouse.h and docs/OPERATIONS.md "control-plane
// durability & failover".
void* tft_lighthouse_create(const char* bind, uint64_t min_replicas,
                            int64_t join_timeout_ms, int64_t quorum_tick_ms,
                            int64_t heartbeat_timeout_ms, const char* wal_dir,
                            int64_t snapshot_every, const char* peers,
                            int standby, int64_t takeover_ms) {
  Lighthouse* lh = nullptr;
  int rc = guarded([&] {
    LighthouseOpt opt;
    opt.min_replicas = min_replicas;
    opt.join_timeout_ms = join_timeout_ms;
    opt.quorum_tick_ms = quorum_tick_ms;
    opt.heartbeat_timeout_ms = heartbeat_timeout_ms;
    opt.wal_dir = wal_dir ? wal_dir : "";
    opt.snapshot_every = snapshot_every;
    opt.peers = peers ? peers : "";
    opt.standby = standby != 0;
    opt.takeover_ms = takeover_ms;
    lh = new Lighthouse(bind, opt);
  });
  return rc == kOk ? lh : nullptr;
}

// Whether this root is ACTIVE (serving) vs a passive warm standby.
int tft_lighthouse_active(void* handle) {
  return static_cast<Lighthouse*>(handle)->active() ? 1 : 0;
}

// Monotonic root epoch (0 = never active; fenced through the WAL).
int64_t tft_lighthouse_root_epoch(void* handle) {
  return static_cast<Lighthouse*>(handle)->root_epoch();
}

char* tft_lighthouse_address(void* handle) {
  return dup_string(static_cast<Lighthouse*>(handle)->address());
}

void tft_lighthouse_shutdown(void* handle) {
  static_cast<Lighthouse*>(handle)->shutdown();
}

void tft_lighthouse_destroy(void* handle) {
  delete static_cast<Lighthouse*>(handle);
}

int tft_lighthouse_heartbeat(const char* addr, const char* replica_id,
                             int64_t timeout_ms) {
  return guarded([&] {
    LighthouseClient client(addr, timeout_ms);
    client.heartbeat(replica_id, timeout_ms);
  });
}

int tft_lighthouse_status_json(void* handle, char** out) {
  return guarded(
      [&] { *out = dup_string(static_cast<Lighthouse*>(handle)->status_json()); });
}

// ---- RegionLighthouse ----

void* tft_region_create(const char* bind, const char* root_addr,
                        const char* region_id, int64_t digest_interval_ms,
                        int64_t heartbeat_timeout_ms, int64_t connect_timeout_ms) {
  RegionLighthouse* r = nullptr;
  int rc = guarded([&] {
    RegionOpt opt;
    if (digest_interval_ms > 0) opt.digest_interval_ms = digest_interval_ms;
    if (heartbeat_timeout_ms > 0) opt.heartbeat_timeout_ms = heartbeat_timeout_ms;
    if (connect_timeout_ms > 0) opt.connect_timeout_ms = connect_timeout_ms;
    r = new RegionLighthouse(bind, root_addr, region_id, opt);
  });
  return rc == kOk ? r : nullptr;
}

char* tft_region_address(void* handle) {
  return dup_string(static_cast<RegionLighthouse*>(handle)->address());
}

void tft_region_shutdown(void* handle) {
  static_cast<RegionLighthouse*>(handle)->shutdown();
}

void tft_region_destroy(void* handle) {
  delete static_cast<RegionLighthouse*>(handle);
}

int tft_region_status_json(void* handle, char** out) {
  return guarded([&] {
    *out = dup_string(static_cast<RegionLighthouse*>(handle)->status_json());
  });
}

// The region-side quorum cache: the last root quorum served locally with
// its refresh age (no root round trip per read).
int tft_region_quorum_json(void* handle, char** out) {
  return guarded([&] {
    *out = dup_string(static_cast<RegionLighthouse*>(handle)->quorum_json());
  });
}

// ---- LeaseClient (persistent lighthouse-protocol client) ----

// A LighthouseClient handle for batch lease renewal / heartbeat / depart
// over ONE persistent connection — the wire surface bench_lighthouse's
// simulated groups and host-level renewal batchers ride.

void* tft_lease_client_create(const char* addr, int64_t connect_timeout_ms) {
  return new LighthouseClient(addr, connect_timeout_ms);
}

void tft_lease_client_destroy(void* handle) {
  delete static_cast<LighthouseClient*>(handle);
}

// entries_json: [{replica_id, ttl_ms, participating, member: {...}}, ...].
// Writes the lighthouse's current quorum_id to *quorum_id_out.
int tft_lease_client_renew(void* handle, const char* entries_json,
                           int64_t timeout_ms, int64_t* quorum_id_out) {
  return guarded([&] {
    std::vector<LeaseEntry> entries =
        lease_entries_from_json(Json::parse(entries_json));
    *quorum_id_out =
        static_cast<LighthouseClient*>(handle)->lease_renew(entries, timeout_ms);
  });
}

int tft_lease_client_heartbeat(void* handle, const char* replica_id,
                               int64_t timeout_ms) {
  return guarded([&] {
    static_cast<LighthouseClient*>(handle)->heartbeat(replica_id, timeout_ms);
  });
}

int tft_lease_client_depart(void* handle, const char* replica_id,
                            int64_t timeout_ms) {
  return guarded([&] {
    static_cast<LighthouseClient*>(handle)->depart(replica_id, timeout_ms);
  });
}

// ---- ManagerServer ----

// lighthouse_addr and root_addr may be COMMA-SEPARATED endpoint lists
// (root failover sets); region_probe_max bounds the demoted manager's
// region re-probes (0 = probe forever, the pre-durability behavior).
void* tft_manager_create(const char* replica_id, const char* lighthouse_addr,
                         const char* hostname, const char* bind,
                         const char* store_addr, uint64_t world_size,
                         int64_t heartbeat_interval_ms, int64_t connect_timeout_ms,
                         const char* root_addr, int64_t lease_ttl_ms,
                         const char* region, const char* host,
                         int64_t region_probe_max) {
  ManagerServer* m = nullptr;
  int rc = guarded([&] {
    m = new ManagerServer(replica_id, lighthouse_addr, hostname, bind, store_addr,
                          world_size, heartbeat_interval_ms, connect_timeout_ms,
                          root_addr ? root_addr : "", lease_ttl_ms,
                          region ? region : "", host ? host : "",
                          region_probe_max);
  });
  return rc == kOk ? m : nullptr;
}

// Whether the manager is currently demoted to direct-root registration
// (region failover active).
int tft_manager_using_root(void* handle) {
  return static_cast<ManagerServer*>(handle)->using_root_fallback() ? 1 : 0;
}

// Whether the bounded region re-probe gave up (region_probe_max
// consecutive failures while demoted) — the manager stays on the root.
int tft_manager_probe_given_up(void* handle) {
  return static_cast<ManagerServer*>(handle)->region_probe_given_up() ? 1 : 0;
}

// Publishes a member-health digest (JSON) carried on subsequent lease
// renewals into the lighthouse's per-member /status.json view.
int tft_manager_set_status(void* handle, const char* status_json) {
  return guarded([&] {
    static_cast<ManagerServer*>(handle)->set_status_json(
        status_json ? status_json : "");
  });
}

char* tft_manager_address(void* handle) {
  return dup_string(static_cast<ManagerServer*>(handle)->address());
}

void tft_manager_shutdown(void* handle) {
  static_cast<ManagerServer*>(handle)->shutdown();
}

void tft_manager_destroy(void* handle) {
  delete static_cast<ManagerServer*>(handle);
}

// ---- ManagerClient ----

void* tft_client_create(const char* addr, int64_t connect_timeout_ms) {
  return new ManagerClient(addr, connect_timeout_ms);
}

void tft_client_destroy(void* handle) {
  delete static_cast<ManagerClient*>(handle);
}

int tft_client_quorum(void* handle, int64_t rank, int64_t step,
                      const char* checkpoint_metadata, int shrink_only,
                      int force_reconfigure, int64_t timeout_ms,
                      char** result_json) {
  return guarded([&] {
    auto resp = static_cast<ManagerClient*>(handle)->quorum(
        rank, step, checkpoint_metadata, shrink_only != 0,
        force_reconfigure != 0, timeout_ms);
    *result_json = dup_string(quorum_response_to_json(resp).dump());
  });
}

int tft_client_checkpoint_metadata(void* handle, int64_t rank, int64_t timeout_ms,
                                   char** metadata_out) {
  return guarded([&] {
    *metadata_out = dup_string(
        static_cast<ManagerClient*>(handle)->checkpoint_metadata(rank, timeout_ms));
  });
}

int tft_client_should_commit(void* handle, int64_t rank, int64_t step,
                             int should_commit, int64_t timeout_ms, int* result) {
  return guarded([&] {
    *result = static_cast<ManagerClient*>(handle)->should_commit(
                  rank, step, should_commit != 0, timeout_ms)
                  ? 1
                  : 0;
  });
}

int tft_client_kill(void* handle, const char* msg) {
  return guarded([&] { static_cast<ManagerClient*>(handle)->kill(msg); });
}

// ---- Store ----

void* tft_store_create(const char* bind) {
  StoreServer* s = nullptr;
  int rc = guarded([&] { s = new StoreServer(bind); });
  return rc == kOk ? s : nullptr;
}

char* tft_store_address(void* handle) {
  return dup_string(static_cast<StoreServer*>(handle)->address());
}

int tft_store_port(void* handle) {
  return static_cast<StoreServer*>(handle)->port();
}

void tft_store_shutdown(void* handle) {
  static_cast<StoreServer*>(handle)->shutdown();
}

void tft_store_destroy(void* handle) {
  delete static_cast<StoreServer*>(handle);
}

void* tft_store_client_create(const char* addr, int64_t connect_timeout_ms) {
  StoreClient* c = nullptr;
  int rc = guarded([&] { c = new StoreClient(addr, connect_timeout_ms); });
  return rc == kOk ? c : nullptr;
}

void tft_store_client_destroy(void* handle) {
  delete static_cast<StoreClient*>(handle);
}

int tft_store_client_set(void* handle, const char* key, const char* value,
                         size_t value_len, int64_t timeout_ms) {
  return guarded([&] {
    static_cast<StoreClient*>(handle)->set(key, std::string(value, value_len),
                                           timeout_ms);
  });
}

int tft_store_client_get(void* handle, const char* key, int64_t timeout_ms,
                         char** value_out, size_t* value_len_out) {
  return guarded([&] {
    std::string v = static_cast<StoreClient*>(handle)->get(key, timeout_ms);
    *value_out = dup_bytes(v, value_len_out);
  });
}

int tft_store_client_add(void* handle, const char* key, int64_t delta,
                         int64_t timeout_ms, int64_t* value_out) {
  return guarded([&] {
    *value_out = static_cast<StoreClient*>(handle)->add(key, delta, timeout_ms);
  });
}

// ---- HostCollectives ----

void* tft_hc_create() { return new HostCollectives(); }

void tft_hc_destroy(void* handle) { delete static_cast<HostCollectives*>(handle); }

int tft_hc_configure(void* handle, const char* store_addr, int64_t rank,
                     int64_t world_size, int64_t timeout_ms, int64_t stripes) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->configure(store_addr, rank, world_size,
                                                     timeout_ms, stripes);
  });
}

// Configure with a REGION and/or HOST MAP: each *_json is a JSON array
// of one label per rank ("" = unlabeled; null/empty string = no map).
// With >= 2 distinct region labels the intra/inter tiers are built
// alongside the flat ring; with a host map grouping >= 2 co-hosted
// ranks the shared-memory HOST tier is built below them
// (TORCHFT_HC_SHM=0 falls it back to loopback TCP). stripes_inter
// (<= 0: = stripes) is the inter (leader) ring's connection count.
int tft_hc_configure_hier(void* handle, const char* store_addr, int64_t rank,
                          int64_t world_size, int64_t timeout_ms,
                          int64_t stripes, int64_t stripes_inter,
                          const char* regions_json, const char* hosts_json) {
  return guarded([&] {
    auto parse_labels = [](const char* js) {
      std::vector<std::string> out;
      if (js != nullptr && js[0] != '\0') {
        // Bound to a local: `Json::parse(...).as_array()` in the
        // range-for would destroy the temporary before the loop body
        // runs (the classic pre-C++23 range-for dangling reference).
        Json parsed = Json::parse(js);
        for (const auto& r : parsed.as_array()) out.push_back(r.as_string());
      }
      return out;
    };
    static_cast<HostCollectives*>(handle)->configure(
        store_addr, rank, world_size, timeout_ms, stripes,
        parse_labels(regions_json), stripes_inter, parse_labels(hosts_json));
  });
}

// Whether the last configure built a hierarchical topology (region
// and/or host tiers).
int64_t tft_hc_hier_capable(void* handle) {
  return static_cast<HostCollectives*>(handle)->hier_capable() ? 1 : 0;
}

// Host-tier transport of the last configure: 0 = no host tier, 1 =
// loopback TCP (TORCHFT_HC_SHM=0), 2 = shared-memory rings.
int64_t tft_hc_host_tier_transport(void* handle) {
  return static_cast<HostCollectives*>(handle)->host_tier_transport();
}

// abort() + deterministic release of every ring resource (sockets,
// listener, shm segments) without destroying the handle; a later
// configure rebuilds. The Python shutdown() path — segment lifetime must
// not ride garbage-collection timing.
int tft_hc_release(void* handle) {
  return guarded(
      [&] { static_cast<HostCollectives*>(handle)->release_rings(); });
}

// In-place two-tier allreduce (see HostCollectives::allreduce_hier).
// wire: 0 native across regions, 1 bf16 inter hop, 2 q8 inter hop.
int tft_hc_allreduce_hier(void* handle, void* data, size_t count, int dtype,
                          int op, int wire, int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->allreduce_hier(
        data, count, static_cast<Dtype>(dtype), static_cast<ReduceOp>(op),
        static_cast<HierWire>(wire), timeout_ms);
  });
}

// Phase/byte breakdown of the last hierarchical op as JSON (measured
// per-tier tx bytes; see HostCollectives::last_hier_json). Caller frees
// via tft_string_free.
int tft_hc_last_hier_json(void* handle, char** out) {
  return guarded([&] {
    *out = dup_string(static_cast<HostCollectives*>(handle)->last_hier_json());
  });
}

int tft_hc_allreduce(void* handle, void* data, size_t count, int dtype, int op,
                     int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->allreduce(
        data, count, static_cast<Dtype>(dtype), static_cast<ReduceOp>(op),
        timeout_ms);
  });
}

int tft_hc_allreduce_q8(void* handle, float* data, size_t count,
                        int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->allreduce_q8(data, count,
                                                        timeout_ms);
  });
}

int tft_hc_reduce_scatter(void* handle, void* data, size_t count, int dtype,
                          int op, void* shard_out, int64_t layout_stripes,
                          int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->reduce_scatter(
        data, count, static_cast<Dtype>(dtype), static_cast<ReduceOp>(op),
        shard_out, layout_stripes, timeout_ms);
  });
}

int tft_hc_reduce_scatter_q8(void* handle, float* data, size_t count,
                             float* shard_out, int grid_shard,
                             int64_t layout_stripes, int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->reduce_scatter_q8(
        data, count, shard_out, grid_shard != 0, layout_stripes, timeout_ms);
  });
}

int tft_hc_allgather_into(void* handle, const void* shard, void* data,
                          size_t count, int dtype, int64_t layout_stripes,
                          int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->allgather_into(
        shard, data, count, static_cast<Dtype>(dtype), layout_stripes,
        timeout_ms);
  });
}

// Writes up to `cap` (start, len) element pairs of rank `rank`'s shard into
// `out` (flattened pairs); returns the number of pairs, or -1 on error
// (tft_last_error set). Pure layout arithmetic once configured.
int64_t tft_hc_shard_ranges(void* handle, size_t count, size_t esize,
                            int64_t rank, int64_t layout_stripes, int64_t* out,
                            int64_t cap) {
  std::vector<std::pair<size_t, size_t>> ranges;
  int rc = guarded([&] {
    ranges = static_cast<HostCollectives*>(handle)->shard_ranges(
        count, esize, rank, layout_stripes);
  });
  if (rc != kOk) return -1;
  int64_t n = static_cast<int64_t>(ranges.size());
  for (int64_t i = 0; i < n && i < cap; i++) {
    out[2 * i] = static_cast<int64_t>(ranges[i].first);
    out[2 * i + 1] = static_cast<int64_t>(ranges[i].second);
  }
  return n;
}

// ---- persistent comm plans ----

// Builds a CommPlan for a leaf signature; returns the plan id (> 0) or -1
// with tft_last_error set. wire: 0 native dtypes, 1 bf16, 2 q8, 3 q8+EF.
int64_t tft_plan_build(void* handle, const int64_t* counts,
                       const int32_t* dtypes, int64_t n_leaves, int wire) {
  int64_t id = -1;
  int rc = guarded([&] {
    id = static_cast<HostCollectives*>(handle)->plan_build(
        counts, dtypes, n_leaves, static_cast<PlanWire>(wire));
  });
  return rc == kOk ? id : -1;
}

// One gradient sync over the plan: a single GIL-released call that packs
// leaf_in, rides the striped ring, and unpacks (dividing when
// has_divisor) into leaf_out. Both pointer arrays are n_leaves long, in
// signature order.
int tft_plan_execute(void* handle, int64_t plan_id, const void* const* leaf_in,
                     void* const* leaf_out, double divisor, int has_divisor,
                     int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->plan_execute(
        plan_id, leaf_in, leaf_out, divisor, has_divisor != 0, timeout_ms);
  });
}

// Builds a PREPACKED CommPlan: execute takes per-GROUP wire buffers the
// caller (the device-side Pallas pack) already encoded, so the pack stage
// is a straight decode. Same wire contract as tft_plan_build — prepacked
// and plain plans of one signature interoperate in one ring.
int64_t tft_plan_build_pre(void* handle, const int64_t* counts,
                           const int32_t* dtypes, int64_t n_leaves, int wire) {
  int64_t id = -1;
  int rc = guarded([&] {
    id = static_cast<HostCollectives*>(handle)->plan_build(
        counts, dtypes, n_leaves, static_cast<PlanWire>(wire),
        /*prepacked=*/true);
  });
  return rc == kOk ? id : -1;
}

// Builds a HIERARCHICAL CommPlan: execute (tft_plan_execute) runs the
// two-tier schedule — intra reduce-scatter/allgather, inter ring among
// region leaders at `wire` (bf16/q8/q8+EF applied at the slow hop ONLY;
// staging and the intra tier stay native width), chunk-pipelined intra
// broadcast. Requires a region-map configure (tft_hc_configure_hier) at
// execute time; the signature hash bakes the hier geometry in, so a hier
// plan meeting a flat plan errors instead of desyncing.
int64_t tft_plan_build_hier(void* handle, const int64_t* counts,
                            const int32_t* dtypes, int64_t n_leaves,
                            int wire) {
  int64_t id = -1;
  int rc = guarded([&] {
    id = static_cast<HostCollectives*>(handle)->plan_build(
        counts, dtypes, n_leaves, static_cast<PlanWire>(wire),
        /*prepacked=*/false, /*hier=*/true);
  });
  return rc == kOk ? id : -1;
}

// One gradient sync over a prepacked plan: group_in[g] is group g's wire
// payload (g.count staging-dtype elements — int8 codes for q8 wires),
// group_aux[g] its per-leaf f32 scale sidecar (q8 only; may be null
// otherwise). Both arrays are n_groups long in plan group order;
// leaf_out is n_leaves long in signature order.
int tft_plan_execute_pre(void* handle, int64_t plan_id,
                         const void* const* group_in,
                         const void* const* group_aux, void* const* leaf_out,
                         double divisor, int has_divisor, int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->plan_execute_pre(
        plan_id, group_in, group_aux, leaf_out, divisor, has_divisor != 0,
        timeout_ms);
  });
}

// ---- sharded comm plans (per-step ZeRO) ----

// Builds a SHARDED CommPlan: the fused allreduce split at the
// reduce-scatter boundary so the caller can update only the 1/W shard it
// owns and allgather the updated params. f32 leaves only; rs_wire
// (0 native, 1 bf16, 2 q8) encodes the grad leg — the owner's shard
// lands full f32 regardless — and ag_wire (0 native, 1 bf16) the param
// leg. Returns the plan id (> 0) or -1 with tft_last_error set.
int64_t tft_plan_build_sharded(void* handle, const int64_t* counts,
                               const int32_t* dtypes, int64_t n_leaves,
                               int rs_wire, int ag_wire) {
  int64_t id = -1;
  int rc = guarded([&] {
    id = static_cast<HostCollectives*>(handle)->plan_build_sharded(
        counts, dtypes, n_leaves, static_cast<PlanWire>(rs_wire),
        static_cast<PlanWire>(ag_wire));
  });
  return rc == kOk ? id : -1;
}

// Grad leg of a sharded plan: packs leaf_in (n_leaves, signature order),
// rides the reduce-scatter phase, compacts the rank-owned shard into
// shard_out (tft_plan_sharded_meta's shard_count f32 elements) with the
// divisor applied to the shard only.
int tft_plan_execute_rs(void* handle, int64_t plan_id,
                        const void* const* leaf_in, float* shard_out,
                        double divisor, int has_divisor, int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->plan_execute_rs(
        plan_id, leaf_in, shard_out, divisor, has_divisor != 0, timeout_ms);
  });
}

// Param leg of a sharded plan: scatters shard_in (the updated shard,
// same layout) back, rides the allgather phase at the plan's ag wire and
// unpacks into leaf_out (n_leaves, signature order), no divisor.
int tft_plan_execute_ag(void* handle, int64_t plan_id, const float* shard_in,
                        void* const* leaf_out, int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->plan_execute_ag(
        plan_id, shard_in, leaf_out, timeout_ms);
  });
}

// out3[0] = this rank's shard element count, out3[1] = the plan's stripe
// partition (pass it to tft_hc_shard_ranges as layout_stripes), out3[2]
// = total flat element count.
int tft_plan_sharded_meta(void* handle, int64_t plan_id, int64_t* out3) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->plan_sharded_meta(plan_id, out3);
  });
}

int tft_plan_free(void* handle, int64_t plan_id) {
  return guarded(
      [&] { static_cast<HostCollectives*>(handle)->plan_free(plan_id); });
}

int tft_plan_reset_feedback(void* handle, int64_t plan_id) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->plan_reset_feedback(plan_id);
  });
}

// Per-bucket phase timings of the plan's last execute, as JSON.
int tft_plan_stats_json(void* handle, int64_t plan_id, char** out) {
  return guarded([&] {
    *out = dup_string(
        static_cast<HostCollectives*>(handle)->plan_stats_json(plan_id));
  });
}

int tft_hc_allgather(void* handle, const void* in, void* out, size_t nbytes,
                     int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->allgather(in, out, nbytes, timeout_ms);
  });
}

int tft_hc_broadcast(void* handle, void* data, size_t nbytes, int64_t root,
                     int64_t timeout_ms) {
  return guarded([&] {
    static_cast<HostCollectives*>(handle)->broadcast(data, nbytes, root,
                                                     timeout_ms);
  });
}

int tft_hc_barrier(void* handle, int64_t timeout_ms) {
  return guarded(
      [&] { static_cast<HostCollectives*>(handle)->barrier(timeout_ms); });
}

void tft_hc_abort(void* handle) { static_cast<HostCollectives*>(handle)->abort(); }

// Requests per-frame CRC32C on the ring wire for the NEXT configure
// (default: TORCHFT_WIRE_CRC). All members must agree — the hello magic
// carries the frame format, and the Python layer negotiates the knob
// through the store like stripes.
void tft_hc_set_wire_crc(void* handle, int on) {
  static_cast<HostCollectives*>(handle)->set_wire_crc(on != 0);
}

// Whether the ACTIVE ring (last configure) runs the CRC-guarded frames.
int tft_hc_wire_crc(void* handle) {
  return static_cast<HostCollectives*>(handle)->wire_crc() ? 1 : 0;
}

// ---- chaos plane (deterministic fault injection) ----
// The seeded fault schedule is process-global: rules match on (seam,
// member, op_index) so one armed plan drives every member hosted by the
// process (thread fleets included). See native/src/fault.h.

// Arms (replaces) the fault plan: {"seed": u64, "rules": [{"seam":
// "ring_send"|"net_send"|..., "kind": "drop"|"delay"|"truncate"|
// "duplicate"|"bit_flip"|"partition", "member": -1|rank, "min_op",
// "max_op", "permille", "max_fires", "param"}]}. Stats persist across
// re-arms (the harness re-arms per step); tft_fault_disarm resets them.
int tft_fault_arm(const char* plan_json) {
  return guarded([&] { fault::arm_from_json(plan_json ? plan_json : "{}"); });
}

void tft_fault_disarm(void) { fault::disarm(); }

int tft_fault_armed(void) { return fault::armed() ? 1 : 0; }

// Injection stats: {"armed", "fired_total", "fired": {"seam:kind": n}}.
int tft_fault_stats_json(char** out) {
  return guarded([&] { *out = dup_string(fault::stats_json()); });
}

// CRC32C (Castagnoli) over a buffer — the same polynomial the ring
// frames ride; exposed so the Python heal stream and tests share one
// implementation.
uint32_t tft_crc32c(const void* data, uint64_t len) {
  return fault::crc32c(data, static_cast<size_t>(len));
}

// Incremental form for non-contiguous payloads (the heal staging's
// per-leaf segments): seed with 0xFFFFFFFF, chain updates, invert at the
// end — exactly what tft_crc32c does for one buffer.
uint32_t tft_crc32c_update(uint32_t state, const void* data, uint64_t len) {
  return fault::crc32c_update(state, data, static_cast<size_t>(len));
}

int64_t tft_hc_world_size(void* handle) {
  return static_cast<HostCollectives*>(handle)->world_size();
}

int64_t tft_hc_stripes(void* handle) {
  return static_cast<HostCollectives*>(handle)->stripes();
}

// Copies up to `cap` per-stripe wall times (ns) of the last bulk op into
// `out`; returns how many stripes the op actually ran. Must be called from
// the thread that issued the op (the Python single-op executor), which is
// the only thread that reads these between ops.
int64_t tft_hc_last_stripe_ns(void* handle, int64_t* out, int64_t cap) {
  const auto& ns = static_cast<HostCollectives*>(handle)->last_stripe_ns();
  int64_t n = static_cast<int64_t>(ns.size());
  for (int64_t i = 0; i < n && i < cap; i++) out[i] = ns[i];
  return n;
}

// ---- shared-memory segments (isolated accelerator data plane) ----
// Lifecycle for the POSIX shm staging buffers the isolated XLA backend
// feeds its disposable child through (see native/src/shm.h for the
// ownership contract: the creator unlinks, attachments never do, and a
// SIGKILLed child's mapping vanishes with it while the parent's survives).

void* tft_shm_create(const char* name, int64_t bytes) {
  try {
    return ShmSegment::Create(name, static_cast<size_t>(bytes));
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

void* tft_shm_attach(const char* name, int64_t bytes) {
  try {
    return ShmSegment::Attach(name, static_cast<size_t>(bytes));
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

void* tft_shm_data(void* handle) {
  return static_cast<ShmSegment*>(handle)->data();
}

int64_t tft_shm_size(void* handle) {
  return static_cast<int64_t>(static_cast<ShmSegment*>(handle)->size());
}

void tft_shm_close(void* handle) { delete static_cast<ShmSegment*>(handle); }

int tft_shm_unlink(const char* name) {
  return guarded([&] { ShmSegment::Unlink(name); });
}

int64_t tft_shm_live_count() { return ShmSegment::live_count(); }

// The CommPlan leaf->offset layout both sides of the shm boundary lay
// payloads out with (the authority the Python mirror is pinned against).
// wire: 0 native dtypes, 1 bf16, 2 q8, 3 q8+EF — plan_build's codes.
int tft_shm_layout_json(const int64_t* counts, const int32_t* dtypes,
                        int64_t n_leaves, int wire, char** out) {
  return guarded([&] {
    *out = dup_string(shm_layout_json(counts, dtypes, n_leaves, wire));
  });
}

// ---- pure functions (test entry points) ----

// state_json: {participants: {id: {joined_ms, member: {...}}}, heartbeats:
// {id: ms}, prev_quorum: {...}|null, quorum_id: int}; opt_json: LighthouseOpt
// fields. Returns {"quorum": [members]|null, "reason": str}.
int tft_quorum_compute(int64_t now, const char* state_json, const char* opt_json,
                       char** result_json) {
  return guarded([&] {
    LighthouseState state = lighthouse_state_from_json(Json::parse(state_json));
    LighthouseOpt opt = lighthouse_opt_from_json(Json::parse(opt_json));
    auto [quorum, reason] = quorum_compute(now, state, opt);
    JsonObject out;
    if (quorum.has_value()) {
      JsonArray arr;
      for (const auto& m : *quorum) arr.push_back(member_to_json(m));
      out["quorum"] = Json(std::move(arr));
    } else {
      out["quorum"] = Json();
    }
    out["reason"] = reason;
    *result_json = dup_string(Json(std::move(out)).dump());
  });
}

int tft_compute_quorum_results(const char* replica_id, int64_t rank,
                               const char* quorum_json, char** result_json) {
  return guarded([&] {
    torchft_tpu::Quorum quorum = quorum_from_json(Json::parse(quorum_json));
    auto resp = compute_quorum_results(replica_id, rank, quorum);
    *result_json = dup_string(quorum_response_to_json(resp).dump());
  });
}

// One full quorum tick as a pure state transition (the exact function both
// the flat lighthouse and the hierarchical root run per tick). Returns
// {"state": ..., "quorum": {...}|null, "changed": bool, "reason": str} —
// the entry point of the flat-vs-hierarchical equivalence property suite.
int tft_quorum_step(int64_t now, int64_t unix_now, const char* state_json,
                    const char* opt_json, char** result_json) {
  return guarded([&] {
    LighthouseState state = lighthouse_state_from_json(Json::parse(state_json));
    LighthouseOpt opt = lighthouse_opt_from_json(Json::parse(opt_json));
    QuorumStepResult res = quorum_step(now, unix_now, state, opt);
    JsonObject out;
    out["state"] = lighthouse_state_to_json(state);
    out["quorum"] = res.quorum.has_value() ? quorum_to_json(*res.quorum) : Json();
    out["changed"] = res.changed;
    out["reason"] = res.reason;
    *result_json = dup_string(Json(std::move(out)).dump());
  });
}

// Applies a batched lease renewal to a state; returns the new state JSON.
int tft_lease_apply(const char* state_json, const char* entries_json, int64_t now,
                    char** result_json) {
  return guarded([&] {
    LighthouseState state = lighthouse_state_from_json(Json::parse(state_json));
    apply_lease_batch(state, lease_entries_from_json(Json::parse(entries_json)),
                      now);
    *result_json = dup_string(lighthouse_state_to_json(state).dump());
  });
}

// Explicit depart; returns the new state JSON.
int tft_depart_apply(const char* state_json, const char* replica_id,
                     char** result_json) {
  return guarded([&] {
    LighthouseState state = lighthouse_state_from_json(Json::parse(state_json));
    apply_depart(state, replica_id);
    *result_json = dup_string(lighthouse_state_to_json(state).dump());
  });
}

// Region side of the digest protocol: compresses a region state to
// age-relative entries at `now` on the region clock.
int tft_digest_make(const char* state_json, int64_t now, const char* opt_json,
                    char** result_json) {
  return guarded([&] {
    LighthouseState state = lighthouse_state_from_json(Json::parse(state_json));
    LighthouseOpt opt = lighthouse_opt_from_json(Json::parse(opt_json));
    *result_json = dup_string(digest_to_json(make_digest(state, now, opt)).dump());
  });
}

// Root side: merges a digest into a state at `now` on the root clock;
// returns the new state JSON.
int tft_digest_apply(const char* state_json, const char* digest_json, int64_t now,
                     char** result_json) {
  return guarded([&] {
    LighthouseState state = lighthouse_state_from_json(Json::parse(state_json));
    apply_digest(state, digest_from_json(Json::parse(digest_json)), now);
    *result_json = dup_string(lighthouse_state_to_json(state).dump());
  });
}

// ---- write-ahead quorum log (pure entry points) ----
// The scripted kill-at-every-record property suites drive the EXACT
// DurableLog encoder/decoder the live root runs, with caller-supplied
// clocks (mono == unix == scripted t makes the rebase an identity).

void* tft_wal_open(const char* dir, int64_t snapshot_every) {
  DurableLog* wal = nullptr;
  int rc = guarded([&] { wal = new DurableLog(dir, snapshot_every); });
  return rc == kOk ? wal : nullptr;
}

void tft_wal_close(void* handle) { delete static_cast<DurableLog*>(handle); }

// entries_json: [{replica_id, age_ms, ttl_ms, participating,
// joined_age_ms, member}] — the POST-APPLY state slices (ages relative
// to unix_ms).
int tft_wal_log_lease(void* handle, const char* entries_json, int64_t unix_ms) {
  return guarded([&] {
    static_cast<DurableLog*>(handle)->log_lease(
        wal_lease_entries_from_json(Json::parse(entries_json)), unix_ms);
  });
}

int tft_wal_log_depart(void* handle, const char* replica_id) {
  return guarded(
      [&] { static_cast<DurableLog*>(handle)->log_depart(replica_id); });
}

int tft_wal_log_quorum(void* handle, const char* quorum_json,
                       int64_t quorum_gen, int64_t root_epoch) {
  return guarded([&] {
    static_cast<DurableLog*>(handle)->log_quorum(
        quorum_from_json(Json::parse(quorum_json)), quorum_gen, root_epoch);
  });
}

int tft_wal_log_epoch(void* handle, int64_t epoch) {
  return guarded([&] { static_cast<DurableLog*>(handle)->log_epoch(epoch); });
}

// state_json uses the lighthouse_state_to_json schema with MONOTONIC
// times at mono_now (the scripted suites pass mono_now == unix_now == t).
int tft_wal_snapshot(void* handle, const char* state_json, int64_t quorum_gen,
                     int64_t root_epoch, int64_t mono_now, int64_t unix_now) {
  return guarded([&] {
    static_cast<DurableLog*>(handle)->snapshot(
        lighthouse_state_from_json(Json::parse(state_json)), quorum_gen,
        root_epoch, mono_now, unix_now);
  });
}

// Replays snapshot + log; returns {"state": <lighthouse state JSON>,
// "quorum_gen", "root_epoch", "replayed", "records_replayed",
// "dropped_tail_bytes"} with times re-based onto mono_now.
int tft_wal_recover(const char* dir, int64_t mono_now, int64_t unix_now,
                    char** result_json) {
  return guarded([&] {
    WalRecovery rec = DurableLog::recover(dir, mono_now, unix_now);
    JsonObject out;
    out["state"] = lighthouse_state_to_json(rec.state);
    out["quorum_gen"] = rec.quorum_gen;
    out["root_epoch"] = rec.root_epoch;
    out["replayed"] = rec.replayed;
    out["records_replayed"] = rec.records_replayed;
    out["dropped_tail_bytes"] = rec.dropped_tail_bytes;
    *result_json = dup_string(Json(std::move(out)).dump());
  });
}

// Deterministic jittered exponential backoff schedule (the manager renewal
// loop's retry delays), exposed for the backoff-schedule unit tests.
int64_t tft_backoff_ms(int failures, int64_t base_ms, int64_t max_ms,
                       uint64_t seed) {
  return backoff_ms(failures, base_ms, max_ms, seed);
}

// Deterministic jittered renewal interval (the healthy-path herd spread).
int64_t tft_jittered_interval_ms(int64_t interval_ms, uint64_t seed,
                                 uint64_t tick) {
  return jittered_interval_ms(interval_ms, seed, tick);
}

} // extern "C"
