"""Unit coverage for the bench machinery that keeps losing rounds
(VERDICT r05 #6): the wall-clock window helpers (budget exhaustion must be
a recorded result, not a wedge), the child-process backend probe (the
un-loseable step zero), and bench_churn's heal-phase breakdown join (the
artifact keys the heal work is judged by). Pure-Python: no ring, no
training processes."""

import os
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402
import bench_churn  # noqa: E402


class TestTimedWindow:
    def test_budget_exhaustion_returns_instead_of_wedging(self):
        """A run_step that slows to a crawl must end the window at the
        next drain boundary with the completed steps recorded — the
        whole un-loseable design rests on this helper stopping."""
        calls = {"n": 0}

        def run_step():
            calls["n"] += 1
            time.sleep(0.002)

        t0 = time.perf_counter()
        n, el = bench._timed_window(
            run_step, drain=lambda: None, budget_s=0.15, rate_hint=100
        )
        assert n == calls["n"] > 0
        assert el >= 0.15
        # The clock is checked at drain boundaries: with a sane interval
        # the overshoot stays bounded (seconds, not the supervisor budget)
        assert time.perf_counter() - t0 < 10

    def test_max_steps_caps_the_window(self):
        n, el = bench._timed_window(
            lambda: None, drain=lambda: None, budget_s=60, max_steps=7,
            rate_hint=1000,
        )
        assert n == 7
        assert el < 10

    def test_degrading_rate_shortens_interval(self):
        """The interval adapts to the OBSERVED rate: a slowdown mid-window
        must not leave a start-of-run-sized burst running past budget."""
        state = {"n": 0}

        def run_step():
            state["n"] += 1
            time.sleep(0.0001 if state["n"] < 50 else 0.01)

        t0 = time.perf_counter()
        bench._timed_window(
            run_step, drain=lambda: None, budget_s=0.3, rate_hint=10000
        )
        assert time.perf_counter() - t0 < 10


class TestBackendProbe:
    def test_probe_success_reports_platform(self):
        plat = bench._probe_backend_child(
            deadline_s=60,
            _cmd=[sys.executable, "-c", "print('cpu')"],
        )
        assert plat == "cpu"

    def test_probe_hang_times_out_fast(self):
        t0 = time.monotonic()
        plat = bench._probe_backend_child(
            deadline_s=0.3,
            tries=2,
            _cmd=[sys.executable, "-c", "import time; time.sleep(60)"],
        )
        assert plat is None
        assert time.monotonic() - t0 < 10  # 2 tries x 0.3 s + spawn slop

    def test_probe_crash_is_failure_not_exception(self):
        plat = bench._probe_backend_child(
            deadline_s=30,
            _cmd=[sys.executable, "-c", "raise SystemExit(2)"],
        )
        assert plat is None


def _boot(kill_t, cold=True, heal_gap=2.0):
    """Synthetic boot record ``heal_gap`` seconds of pipeline after a
    kill at ``kill_t``."""
    if cold:
        spawn = kill_t + 0.25
        return {
            "spawn_t": spawn,
            "enter_t": spawn + 1.0,
            "setup_t": spawn + 2.0,
            "backend_t": spawn + 2.5,
            "model_t": spawn + 2.8,
            "compiled_t": spawn + 3.3,
            "activated_t": spawn + 3.3,
            "manager_t": spawn + 3.5,
        }
    # promoted standby: spawned long before the kill, activated just after
    spawn = kill_t - 60.0
    return {
        "spawn_t": spawn,
        "enter_t": spawn + 1.0,
        "setup_t": spawn + 2.0,
        "backend_t": spawn + 2.5,
        "model_t": spawn + 2.8,
        "compiled_t": spawn + 3.3,
        "activated_t": kill_t + 0.3,
        "manager_t": kill_t + 0.5,
    }


class TestHealBreakdowns:
    def test_cold_restart_full_phase_split(self):
        """A cold kill yields every interior key the round-5 verdict asked
        for: backend_init / mesh / compile split out of the old opaque
        setup bucket, plus the streamed fetch/h2d from the heal record."""
        kill = {"t": 100.0, "gid": 1, "at_step": 10}
        b = _boot(100.0, cold=True)
        log = [
            {"boot": b},
            {"heal": {"t": 104.0, "path": "stream", "fetch_s": 0.4,
                      "h2d_s": 0.05, "wire": None, "streams": 4}},
            {"t": 104.5, "committed": True},
        ]
        heal_s, breakdowns = bench_churn.compute_heal_stats(
            [kill], {1: log}
        )
        assert heal_s == [pytest.approx(4.5)]
        (bd,) = breakdowns
        assert bd["respawn"] == pytest.approx(0.25)
        assert bd["import"] == pytest.approx(1.0)
        assert bd["setup"] == pytest.approx(1.0)
        assert bd["backend_init"] == pytest.approx(0.5)
        assert bd["mesh"] == pytest.approx(0.3)
        assert bd["compile"] == pytest.approx(0.5)
        assert bd["rendezvous"] == pytest.approx(0.2)
        assert bd["fetch"] == pytest.approx(0.4)
        assert bd["h2d"] == pytest.approx(0.05)
        assert bd["first_commit"] == pytest.approx(104.5 - b["manager_t"])
        # every emitted key is a declared artifact phase
        assert set(bd) <= set(bench_churn.HEAL_PHASES)

    def test_promoted_standby_has_no_cold_phases(self):
        """A warm promotion's breakdown must NOT carry the process-boot
        phases (they happened long before the kill): their absence is the
        measurement that promotion skipped that work."""
        kill = {"t": 200.0, "gid": 2, "at_step": 20}
        log = [
            {"boot": _boot(200.0, cold=False)},
            {"t": 201.2, "committed": True},
        ]
        heal_s, breakdowns = bench_churn.compute_heal_stats(
            [kill], {2: log}
        )
        assert heal_s == [pytest.approx(1.2)]
        (bd,) = breakdowns
        assert bd["activation"] == pytest.approx(0.3)
        for cold_key in ("respawn", "import", "setup", "backend_init",
                         "mesh", "compile"):
            assert cold_key not in bd

    def test_repeat_kill_window_bounding(self):
        """If the same group dies again before its restart commits, the
        later kill's boot/commit must not be attributed to the earlier
        one (VERDICT r04 #6: an extra kill cycle silently folded into the
        medians)."""
        k1 = {"t": 100.0, "gid": 1, "at_step": 10}
        k2 = {"t": 102.0, "gid": 1, "at_step": 10}
        # only the SECOND kill's restart ever commits
        log = [
            {"boot": _boot(102.0, cold=True)},
            {"t": 106.1, "committed": True},
        ]
        heal_s, breakdowns = bench_churn.compute_heal_stats(
            [k1, k2], {1: log}
        )
        # k1's window [100, 102) contains no commit: no heal sample, no
        # breakdown. k2 owns the commit at 106.1.
        assert heal_s == [pytest.approx(4.1)]
        assert len(breakdowns) == 1
        assert breakdowns[0]["respawn"] == pytest.approx(0.25)

    def test_old_boot_records_still_break_down(self):
        """Pre-split boot records (no backend_t/model_t) fold the interior
        phases into one compile bucket instead of crashing."""
        kill = {"t": 50.0, "gid": 3, "at_step": 5}
        b = _boot(50.0, cold=True)
        del b["backend_t"], b["model_t"]
        log = [{"boot": b}, {"t": 55.0, "committed": True}]
        _, breakdowns = bench_churn.compute_heal_stats([kill], {3: log})
        (bd,) = breakdowns
        assert bd["compile"] == pytest.approx(b["compiled_t"] - b["setup_t"])
        assert "backend_init" not in bd and "mesh" not in bd


class TestStandbyWarmKnobs:
    def test_standby_gate_touches_warm_marker(self, tmp_path, monkeypatch):
        """Reaching the gate = warm-up complete: the marker the
        warm-deadline re-arm policy and promotion logging key off."""
        from torchft_tpu.platform import standby_gate

        gate = tmp_path / "gate"
        monkeypatch.setenv("TORCHFT_STANDBY_FILE", str(gate))
        gate.write_text("")  # pre-activated: gate returns immediately
        standby_gate()
        assert (tmp_path / "gate.warm").exists()

    def test_standby_should_warm_default_and_off(self, monkeypatch):
        from torchft_tpu.platform import standby_should_warm

        monkeypatch.delenv("TORCHFT_STANDBY_WARM", raising=False)
        assert standby_should_warm() is True
        monkeypatch.setenv("TORCHFT_STANDBY_WARM", "0")
        assert standby_should_warm() is False

    def test_warm_deadline_parse_and_fallback(self, monkeypatch):
        from torchft_tpu.platform import standby_warm_deadline_s

        monkeypatch.setenv("TORCHFT_STANDBY_WARM_DEADLINE_S", "7.5")
        assert standby_warm_deadline_s() == 7.5
        monkeypatch.setenv("TORCHFT_STANDBY_WARM_DEADLINE_S", "bogus")
        assert standby_warm_deadline_s() == 20.0
