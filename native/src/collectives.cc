#include "collectives.h"

#include <linux/futex.h>
#include <netdb.h>
#include <poll.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <cstdlib>
#include <chrono>
#include <cmath>
#include <exception>
#include <limits>
#include <set>
#include <thread>
#include <vector>

#include "fault.h"
#include "json.h"
#include "log.h"
#include "shm.h"
#include "store.h"

namespace tft {

size_t dtype_size(Dtype d) {
  switch (d) {
    case Dtype::kF32:
    case Dtype::kI32:
      return 4;
    case Dtype::kF64:
    case Dtype::kI64:
      return 8;
    case Dtype::kBF16:
      return 2;
  }
  throw SocketError("bad dtype");
}

namespace {

// Hello magic, versioned: the low byte is the ring wire-protocol revision.
// History: the original "tftc" magic (0x74667463) spanned BOTH the
// pre-op-header wire and the build that added check_op_header, so the
// magic alone could not distinguish them; a ring mixing those desyncs
// mid-op (the old side consumes the 24-byte op header as payload). This
// versioned magic makes any mix of revisions — including byte-compatible
// "tftc" builds that already spoke op headers — fail AT CONNECT with a
// clear error; that over-rejection is the price of screening out the
// truly incompatible older builds sharing the old magic. Bump the low
// byte on any future wire change.
// rev 3: hello grew from {magic, rank} to {magic, rank, stripe, nstripes}
// for the striped multi-connection ring.
// rev 4: hello grew a TIER word ({magic, rank, stripe, nstripes, tier})
// for the two-tier topology — one listener serves the flat, intra-region
// and inter-region (leader) rings, and the hello names which ring a
// connection belongs to.
constexpr uint32_t kHelloMagic = 0x74667404; // "tft" + proto rev 4
// rev 5: the CRC-guarded frame format — every ring/stripe payload frame
// carries a 4-byte CRC32C trailer (TORCHFT_WIRE_CRC, store-negotiated
// like stripes). The rev-5 magic is used ONLY when CRC is on, so a
// CRC-off fleet keeps speaking the byte-identical rev-4 format and
// interops with un-upgraded peers; a mixed on/off pair fails AT CONNECT
// with a CRC-specific error instead of a frame desync.
constexpr uint32_t kHelloMagicCrc = 0x74667405;
// "tftp": per-op header magic (part of the wire protocol).
constexpr uint32_t kOpMagic = 0x74667470;

// Connection tiers named in the hello (and indexing RingTier members).
constexpr uint32_t kTierFlat = 0;
constexpr uint32_t kTierIntra = 1;
constexpr uint32_t kTierInter = 2;
// Host (intra-host) tier: shared-memory rings by default, so the hello
// tier word only appears on the wire under the TORCHFT_HC_SHM=0
// loopback-TCP fallback.
constexpr uint32_t kTierHost = 3;

// ---- shared-memory ring buffers (the host tier's transport) ----
//
// One SPSC byte ring per directed edge per stripe, living in a POSIX shm
// segment (ShmSegment, creator = the producing member). Layout: a
// 64-byte header, then `capacity` data bytes. head/tail are free-running
// byte counters (the ring is full when head - tail == capacity); db_w /
// db_r are futex doorbells bumped after every publish/consume. SHARED
// futexes (no PRIVATE flag): producer and consumer are different
// processes mapping the same page. The magic doubles as the liveness
// word — abort/teardown/torn-segment faults poison it, and both sides
// treat a poisoned ring exactly like a socket FIN.

struct ShmRingHdr {
  std::atomic<uint32_t> magic;
  uint32_t capacity;
  std::atomic<uint64_t> head;   // bytes produced (free-running)
  std::atomic<uint64_t> tail;   // bytes consumed
  std::atomic<uint32_t> db_w;   // producer doorbell
  std::atomic<uint32_t> db_r;   // consumer doorbell
  // Liveness: the producer (creator) and consumer (attacher) publish
  // their pids. A SIGKILLed co-hosted process closes no socket and
  // poisons no magic — the kernel tells us nothing — so a blocked
  // waiter probes the counterpart's pid (kill(pid, 0), ESRCH = gone)
  // once per futex slice and surfaces the death in ~100 ms instead of
  // waiting out the whole op deadline.
  std::atomic<uint32_t> owner_pid;  // producer, set at create
  std::atomic<uint32_t> peer_pid;   // consumer, set at attach
};
static_assert(sizeof(ShmRingHdr) <= 64, "shm ring header outgrew its slot");
static_assert(std::atomic<uint64_t>::is_always_lock_free,
              "shm ring counters must be lock-free (they cross processes)");

constexpr uint32_t kShmRingMagic = 0x74667368;   // "tfsh"
constexpr uint32_t kShmRingPoison = 0xDEADD00Du;
constexpr size_t kShmHdrBytes = 64;

// Every shm_duplex call moves exactly one frame per direction: a 16-byte
// in-stream header (monotonic per-edge sequence + payload length), then
// the payload. The sequence is the stale-payload oracle (a replayed
// frame mismatches), the length the desync oracle (a mismatched op would
// otherwise reduce the wrong bytes).
struct ShmFrame {
  uint64_t fseq;
  uint32_t len;
  uint32_t pad;
};
static_assert(sizeof(ShmFrame) == 16, "shm frame header must be 16 bytes");

inline ShmRingHdr* shm_ring_hdr(void* seg) {
  return static_cast<ShmRingHdr*>(seg);
}
inline char* shm_ring_data(void* seg) {
  return static_cast<char*>(seg) + kShmHdrBytes;
}

// True when `pid` names a process that can never feed its ring again:
// gone entirely (ESRCH), or a ZOMBIE — a SIGKILLed bench/training child
// whose parent has not reaped it yet still *exists* for kill(pid, 0),
// but will never produce another byte (the /proc state disambiguates,
// exactly like the isolated plane's stall monitor). pid 0 = not yet
// published — indeterminate, not dead. Co-hosted by construction, so
// the pid is always probeable.
bool shm_pid_gone(uint32_t pid) {
  if (pid == 0) return false;
  if (kill(static_cast<pid_t>(pid), 0) != 0) return errno == ESRCH;
  char path[64];
  snprintf(path, sizeof(path), "/proc/%u/stat", pid);
  FILE* f = fopen(path, "r");
  if (f == nullptr) return false;  // no /proc: fall back to the deadline
  char buf[256];
  size_t n = fread(buf, 1, sizeof(buf) - 1, f);
  fclose(f);
  buf[n] = '\0';
  // State is the field after the parenthesized comm (which may itself
  // contain spaces and parens — scan from the LAST ')').
  const char* rp = strrchr(buf, ')');
  if (rp == nullptr) return false;
  for (rp++; *rp == ' '; rp++) {
  }
  return *rp == 'Z' || *rp == 'X';
}

// Deadline-sliced futex wait on a doorbell: `expect` must be the value
// read BEFORE the caller re-checked its condition (the lost-wakeup
// protocol); the slice cap bounds the worst case even if a wake is
// missed entirely.
void shm_futex_wait(std::atomic<uint32_t>* addr, uint32_t expect,
                    int64_t max_ms) {
  if (max_ms <= 0) return;
  if (max_ms > 100) max_ms = 100;
  struct timespec ts;
  ts.tv_sec = max_ms / 1000;
  ts.tv_nsec = (max_ms % 1000) * 1000000;
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAIT, expect,
          &ts, nullptr, 0);
}

void shm_futex_wake(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE,
          std::numeric_limits<int>::max(), nullptr, nullptr, 0);
}

// Wrap-aware copy of `n` bytes into/out of a ring at free-running
// position `pos`.
void shm_ring_write(char* data, uint32_t cap, uint64_t pos, const char* src,
                    size_t n) {
  size_t off = static_cast<size_t>(pos % cap);
  size_t first = std::min<size_t>(n, cap - off);
  memcpy(data + off, src, first);
  if (n > first) memcpy(data, src + first, n - first);
}

void shm_ring_read(const char* data, uint32_t cap, uint64_t pos, char* dst,
                   size_t n) {
  size_t off = static_cast<size_t>(pos % cap);
  size_t first = std::min<size_t>(n, cap - off);
  memcpy(dst, data + off, first);
  if (n > first) memcpy(dst + first, data, n - first);
}

// FNV-1a over a string — the shm segment namespace and the topology-map
// hash mixed into hier plan signatures.
uint64_t fnv64(const std::string& s) {
  uint64_t h = 1469598103934665603ull;
  for (unsigned char c : s) {
    h ^= c;
    h *= 1099511628211ull;
  }
  return h;
}

// Floor on bytes a stripe must carry before an extra connection/thread is
// worth waking: below this, per-op thread dispatch costs more than the
// wire. The effective stripe count derived from it depends only on
// (payload, configured stripes) — identical on every member, preserving
// the schedule agreement.
constexpr size_t kMinStripeBytes = 64 << 10;

int64_t effective_stripes(size_t payload_bytes, int64_t configured) {
  int64_t by_size = static_cast<int64_t>(payload_bytes / kMinStripeBytes);
  return std::max<int64_t>(1, std::min(configured, std::max<int64_t>(by_size, 1)));
}

template <typename T>
void reduce_typed(T* dst, const T* src, size_t n, ReduceOp op) {
  switch (op) {
    case ReduceOp::kSum:
      for (size_t i = 0; i < n; i++) dst[i] += src[i];
      return;
    case ReduceOp::kProduct:
      for (size_t i = 0; i < n; i++) dst[i] *= src[i];
      return;
    case ReduceOp::kMin:
      for (size_t i = 0; i < n; i++) dst[i] = std::min(dst[i], src[i]);
      return;
    case ReduceOp::kMax:
      for (size_t i = 0; i < n; i++) dst[i] = std::max(dst[i], src[i]);
      return;
  }
  throw SocketError("bad reduce op");
}

inline float bf16_to_f32(uint16_t h) {
  uint32_t bits = static_cast<uint32_t>(h) << 16;
  float f;
  memcpy(&f, &bits, sizeof(f));
  return f;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  memcpy(&bits, &f, sizeof(bits));
  // Round to nearest even (NaN payloads preserved by the +0x7FFF carry-free
  // path since NaN mantissas survive truncation of the low half).
  uint32_t lsb = (bits >> 16) & 1;
  bits += 0x7FFF + lsb;
  return static_cast<uint16_t>(bits >> 16);
}

void reduce_bf16(uint16_t* dst, const uint16_t* src, size_t n, ReduceOp op) {
  for (size_t i = 0; i < n; i++) {
    float a = bf16_to_f32(dst[i]);
    float b = bf16_to_f32(src[i]);
    float r;
    switch (op) {
      case ReduceOp::kSum: r = a + b; break;
      case ReduceOp::kProduct: r = a * b; break;
      case ReduceOp::kMin: r = std::min(a, b); break;
      case ReduceOp::kMax: r = std::max(a, b); break;
      default: throw SocketError("bad reduce op");
    }
    dst[i] = f32_to_bf16(r);
  }
}

void reduce_into(void* dst, const void* src, size_t n, Dtype dtype, ReduceOp op) {
  switch (dtype) {
    case Dtype::kF32:
      reduce_typed(static_cast<float*>(dst), static_cast<const float*>(src), n, op);
      return;
    case Dtype::kF64:
      reduce_typed(static_cast<double*>(dst), static_cast<const double*>(src), n,
                   op);
      return;
    case Dtype::kI32:
      reduce_typed(static_cast<int32_t*>(dst), static_cast<const int32_t*>(src), n,
                   op);
      return;
    case Dtype::kI64:
      reduce_typed(static_cast<int64_t*>(dst), static_cast<const int64_t*>(src), n,
                   op);
      return;
    case Dtype::kBF16:
      reduce_bf16(static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(src),
                  n, op);
      return;
  }
  throw SocketError("bad dtype");
}

// Element range of ring chunk `c` when `count` elements are split into `ws`
// near-equal chunks (first `count % ws` chunks get one extra element).
std::pair<size_t, size_t> chunk_range(size_t count, int64_t ws, int64_t c) {
  size_t q = count / ws;
  size_t r = count % ws;
  size_t start = c * q + std::min<size_t>(c, r);
  size_t len = q + (static_cast<size_t>(c) < r ? 1 : 0);
  return {start, len};
}

int64_t ns_between(std::chrono::steady_clock::time_point a,
                   std::chrono::steady_clock::time_point b) {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(b - a).count();
}

int64_t cap_to_bps(const char* cap) {
  return cap ? static_cast<int64_t>(std::atof(cap) * (1 << 20)) : 0;
}

}  // namespace

std::pair<size_t, size_t> HostCollectives::stripe_range(size_t count,
                                                        int64_t n, int64_t s) {
  return chunk_range(count, n, s);
}

namespace {

bool env_wire_crc() {
  const char* e = std::getenv("TORCHFT_WIRE_CRC");
  if (e == nullptr) return false;
  std::string v(e);
  return v == "1" || v == "on" || v == "true";
}

// "host:port" of a connected socket's peer, for edge diagnostics.
std::string peer_addr_str(int fd) {
  struct sockaddr_storage ss;
  socklen_t slen = sizeof(ss);
  if (getpeername(fd, reinterpret_cast<struct sockaddr*>(&ss), &slen) != 0)
    return "?";
  char host[NI_MAXHOST];
  char port[NI_MAXSERV];
  if (getnameinfo(reinterpret_cast<struct sockaddr*>(&ss), slen, host,
                  sizeof(host), port, sizeof(port),
                  NI_NUMERICHOST | NI_NUMERICSERV) != 0)
    return "?";
  return std::string(host) + ":" + port;
}

}  // namespace

HostCollectives::HostCollectives() : crc_req_(env_wire_crc()) {}

HostCollectives::~HostCollectives() {
  abort();
  std::vector<std::thread> workers;
  {
    MutexLock lock(pool_mu_);
    pool_stop_ = true;
    workers.swap(pool_);
  }
  pool_cv_.notify_all();
  for (auto& w : workers) w.join();
}

void HostCollectives::abort() {
  MutexLock lock(cfg_mu_);
  aborted_ = true;
  abort_epoch_++;
  if (listener_) listener_->close();
  shutdown_sockets_locked();
}

void HostCollectives::shutdown_sockets_locked() {
  for (RingTier* T : {&flat_, &intra_, &inter_, &host_}) {
    for (auto& s : T->next) s.shutdown_rdwr();
    for (auto& s : T->prev) s.shutdown_rdwr();
  }
  shm_poison_wake_locked();
}

void HostCollectives::shm_poison_wake_locked() {
  // The shm analog of the socket FIN sweep: poison every ring magic this
  // member produces into (its TX rings) so the consumer errors instead
  // of waiting out its deadline, and wake every doorbell — local waiters
  // re-check aborted_/magic, the peer's waiter sees the poison.
  for (auto& e : host_.shm) {
    if (e.tx) {
      ShmRingHdr* h = shm_ring_hdr(e.tx->data());
      h->magic.store(kShmRingPoison, std::memory_order_release);
      shm_futex_wake(&h->db_w);
      shm_futex_wake(&h->db_r);
    }
    if (e.rx) {
      ShmRingHdr* h = shm_ring_hdr(e.rx->data());
      shm_futex_wake(&h->db_w);
      shm_futex_wake(&h->db_r);
    }
  }
}

void HostCollectives::shutdown_sockets() {
  MutexLock lock(cfg_mu_);
  shutdown_sockets_locked();
}

void HostCollectives::release_rings() {
  abort();                    // poison + wake every waiter
  MutexLock op_lock(op_mu_);  // wait for in-flight ops to drain
  MutexLock lock(cfg_mu_);
  flat_.clear();
  intra_.clear();
  inter_.clear();
  host_.clear();  // unlinks this member's shm segments (creator-owned)
  listener_.reset();
}

int64_t HostCollectives::tier_tx(const RingTier& T) {
  int64_t t = 0;
  for (const auto& sc : T.scratch) t += sc.tx_bytes;
  return t;
}

int64_t HostCollectives::tier_shm(const RingTier& T) {
  int64_t t = 0;
  for (const auto& sc : T.scratch) t += sc.shm_bytes;
  return t;
}

void HostCollectives::reset_tier_tx(RingTier& T) {
  for (auto& sc : T.scratch) {
    sc.tx_bytes = 0;
    sc.shm_bytes = 0;
  }
}

namespace {

// Remaining budget before `deadline`; throws once it is exhausted (a
// non-positive timeout must never leak into a blocking call, where some
// callees read <0 as "wait forever").
int64_t remain_or_throw(int64_t deadline) {
  int64_t r = deadline - now_ms();
  if (r <= 0) throw TimeoutError("configure timed out");
  return r;
}

} // namespace

namespace {

// TORCHFT_HC_SHM: the host tier's transport. Default on — the whole
// point of the tier is replacing loopback TCP; 0/off/false falls back to
// a TCP host ring with identical geometry (the bench's honest control).
bool env_shm_on() {
  const char* e = std::getenv("TORCHFT_HC_SHM");
  if (e == nullptr) return true;
  std::string v(e);
  // Case-insensitive, matching the Python layer's parse exactly: the
  // negotiated fingerprint is computed from Python's reading, so any
  // divergence here would pass the mismatch guard and then wedge
  // configure (one member wiring shm, the other TCP).
  for (auto& c : v) c = static_cast<char>(tolower(c));
  return !(v == "0" || v == "off" || v == "false");
}

size_t env_shm_ring_bytes() {
  const char* e = std::getenv("TORCHFT_HC_SHM_RING_BYTES");
  size_t v = e ? static_cast<size_t>(std::atoll(e)) : (1u << 20);
  // Floor keeps the frame pump making progress at sane chunk sizes; the
  // ring handles frames larger than itself, but a degenerate capacity
  // would turn every hop into a futex ping-pong.
  return std::max<size_t>(v, 4096);
}

}  // namespace

void HostCollectives::configure(const std::string& store_addr, int64_t rank,
                                int64_t world_size, int64_t timeout_ms,
                                int64_t stripes,
                                const std::vector<std::string>& regions,
                                int64_t stripes_inter,
                                const std::vector<std::string>& hosts) {
  if (rank < 0 || world_size <= 0 || rank >= world_size)
    throw SocketError("bad rank/world_size");
  if (stripes < 1 || stripes > kMaxStripes)
    throw SocketError("bad stripe count (want 1.." +
                      std::to_string(kMaxStripes) + ")");
  if (stripes_inter <= 0) stripes_inter = stripes;
  if (stripes_inter > kMaxStripes)
    throw SocketError("bad inter stripe count (want 1.." +
                      std::to_string(kMaxStripes) + ")");
  if (!regions.empty() &&
      static_cast<int64_t>(regions.size()) != world_size)
    throw SocketError("region map must carry one label per rank");
  if (!hosts.empty() && static_cast<int64_t>(hosts.size()) != world_size)
    throw SocketError("host map must carry one label per rank");
  abort(); // unblock any op stuck on the old ring
  MutexLock op_lock(op_mu_); // wait for it to drain

  {
    // Comm plans bake in (world_size, stripes) layout arithmetic and
    // persistent staging sized for the old ring: every one of them is
    // stale the moment membership changes. Dropping them here (no
    // execute can be in flight — op_mu_ is held) turns a stale plan id
    // into a descriptive error instead of a desynced wire schedule.
    MutexLock plan_lock(plan_mu_);
    plans_.clear();
  }

  // Hierarchical topology from the (region, host) maps: pure arithmetic
  // on (labels, rank order), identical on every member. The region
  // LEADER is the lowest rank of the region (ranks sort by replica-id,
  // so this is the lowest replica-id); the inter ring orders regions by
  // their leader's rank. HOST groups are keyed by the (region, host)
  // PAIR — a host label that leaks across region boundaries can never
  // stitch two regions together — and the host leader is the lowest
  // rank of the group, so the region leader is always a host leader.
  // The intra ring spans the HOST LEADERS of a region (with no host
  // grouping every member is its own host leader, which is exactly the
  // two-tier topology).
  const bool regions_labeled = [&] {
    if (regions.empty() || world_size <= 1) return false;
    for (const auto& r : regions)
      if (r.empty()) return false;
    return true;
  }();
  const bool hosts_labeled = [&] {
    if (hosts.empty() || world_size <= 1) return false;
    for (const auto& h : hosts)
      if (h.empty()) return false;
    return true;
  }();
  auto region_of = [&](int64_t r) {
    return regions_labeled ? regions[r] : std::string();
  };
  auto hkey = [&](int64_t r) {
    return region_of(r) + '\x1f' + hosts[r];
  };

  bool multi_region = false;
  if (regions_labeled) {
    std::set<std::string> distinct(regions.begin(), regions.end());
    multi_region = distinct.size() >= 2;
  }
  bool host_grouped = false;
  if (hosts_labeled) {
    std::map<std::string, int64_t> sizes;
    for (int64_t r = 0; r < world_size; r++)
      if (++sizes[hkey(r)] >= 2) host_grouped = true;
  }
  const bool hier = multi_region || host_grouped;

  std::vector<int64_t> host_members;   // my (region, host) group
  int64_t host_rank = -1;
  std::vector<int64_t> intra_members;  // host leaders of my region
  int64_t intra_rank = -1;
  std::vector<int64_t> leaders;        // region leaders
  int64_t inter_rank = -1;
  bool is_host_leader = true;
  if (hier) {
    if (hosts_labeled) {
      for (int64_t r = 0; r < world_size; r++) {
        if (hkey(r) == hkey(rank)) {
          if (r == rank)
            host_rank = static_cast<int64_t>(host_members.size());
          host_members.push_back(r);
        }
      }
    } else {
      host_members = {rank};
      host_rank = 0;
    }
    is_host_leader = host_members[0] == rank;
    // Host leaders of my region, rank order — the intra tier's members.
    std::set<std::string> seen_hosts;
    for (int64_t r = 0; r < world_size; r++) {
      if (region_of(r) != region_of(rank)) continue;
      std::string k = hosts_labeled ? hkey(r) : std::to_string(r);
      if (!seen_hosts.insert(k).second) continue;  // not the host leader
      if (r == rank) intra_rank = static_cast<int64_t>(intra_members.size());
      intra_members.push_back(r);
    }
    std::map<std::string, int64_t> leader_of;
    for (int64_t r = 0; r < world_size; r++)
      if (!leader_of.count(region_of(r))) leader_of[region_of(r)] = r;
    for (const auto& [_, l] : leader_of) leaders.push_back(l);
    std::sort(leaders.begin(), leaders.end());
    for (size_t i = 0; i < leaders.size(); i++)
      if (leaders[i] == rank) inter_rank = static_cast<int64_t>(i);
  }
  const int64_t host_world =
      hier ? static_cast<int64_t>(host_members.size()) : 0;
  const int64_t intra_world = hier ? static_cast<int64_t>(intra_members.size()) : 0;
  const int64_t inter_world = hier ? static_cast<int64_t>(leaders.size()) : 0;
  const bool is_leader = hier && inter_rank >= 0;
  const bool shm_on = env_shm_on();
  // Canonical topology hash (mixed into hier plan signatures): identical
  // maps hash identically on every member.
  uint64_t topo = 1469598103934665603ull;
  {
    std::string all;
    for (int64_t r = 0; r < world_size; r++) {
      all += region_of(r);
      all += '\x1f';
      all += hosts_labeled ? hosts[r] : std::string();
      all += '\x1e';
    }
    topo = fnv64(all);
  }

  // Phase 1 (under cfg_mu_, non-blocking): retire the old ring, stand up the
  // new listener so a concurrent abort() can close it and wake phase 2.
  int64_t epoch;
  {
    MutexLock lock(cfg_mu_);
    flat_.clear();
    intra_.clear();
    inter_.clear();
    // Dropping the host tier's edges unlinks every segment this member
    // created — shm segments are owned by the configure generation.
    host_.clear();
    listener_.reset();
    rank_ = rank;
    world_size_ = world_size;
    stripes_ = stripes;
    stripes_inter_ = stripes_inter;
    hier_ = hier;
    topo_hash_ = topo;
    shm_ring_bytes_ = env_shm_ring_bytes();
    // Per-connection send caps, per tier: the main knob paces the
    // slow/wide-area links (the flat ring's edges, the inter hop), the
    // intra knob optionally paces the fast in-region links (0 = unpaced
    // — the default, and what the fast-intra/slow-inter emulation in
    // bench_overlap --hier-sweep relies on). Snapshotted here so the
    // knobs are stable for the lifetime of a ring.
    const int64_t cap_main =
        cap_to_bps(std::getenv("TORCHFT_HC_WIRE_CAP_MBPS"));
    const int64_t cap_intra =
        cap_to_bps(std::getenv("TORCHFT_HC_WIRE_CAP_INTRA_MBPS"));
    auto init_tier = [](RingTier& T, const char* name, int64_t trank,
                        int64_t tworld, int64_t conns, int64_t cap) {
      T.rank = trank;
      T.world = tworld;
      T.conns = conns;
      T.cap_bps = cap;
      T.name = name;
      T.peer_next_addr.clear();
      T.peer_prev_addr.clear();
      T.scratch.assign(conns, StripeScratch{});
      for (auto& sc : T.scratch) sc.cap_bps = cap;
    };
    init_tier(flat_, "flat", rank, world_size, stripes, cap_main);
    if (hier) {
      // Only HOST LEADERS participate in the intra (and inter) rings;
      // world stays 0 for everyone else so op bodies branch uniformly.
      init_tier(intra_, "intra", intra_rank,
                is_host_leader ? intra_world : 0, stripes, cap_intra);
      init_tier(inter_, "inter", inter_rank, is_leader ? inter_world : 0,
                stripes_inter, cap_main);
      // The host ring is intra-host by construction: never paced (there
      // is no NIC to protect), shm-backed unless TORCHFT_HC_SHM=0.
      init_tier(host_, "host", host_rank, host_world > 1 ? host_world : 0,
                stripes, /*cap=*/0);
    }
    // The frame format is fixed for the life of the ring: snapshot the
    // CRC request here, under the same publication protocol as the
    // geometry.
    crc_ = crc_req_;
    aborted_ = true;
    epoch = abort_epoch_;
    if (world_size == 1) {
      aborted_ = false;
      return;
    }
    listener_ = std::make_unique<Listener>("[::]:0");
  }

  // Phase 2 (no locks held, every step deadline-bounded): rendezvous through
  // the store and wire the rings. All neighbors dial concurrently; connect()
  // lands in the peer's listen backlog, so no accept ordering is needed.
  int64_t deadline = now_ms() + timeout_ms;
  auto [kv_addr, prefix] = split_store_addr(store_addr);
  StoreClient store(kv_addr, remain_or_throw(deadline));

  std::string my_addr =
      local_hostname() + ":" + std::to_string(listener_->port());
  store.set(prefix + "/hc_addr_" + std::to_string(rank), my_addr,
            remain_or_throw(deadline));

  // (tier, next global rank, prev global rank, connection count) of every
  // ring this member participates in.
  struct TierPlanEntry {
    uint32_t tier;
    int64_t next_rank;
    int64_t prev_rank;
    int64_t conns;
    std::vector<Socket> next;
    std::vector<Socket> prev;
    std::string next_addr;  // diagnostics: where this tier's edges lead
    std::string prev_addr;
  };
  std::vector<TierPlanEntry> tiers;
  tiers.push_back({kTierFlat, (rank + 1) % world_size,
                   (rank - 1 + world_size) % world_size, stripes, {}, {},
                   {}, {}});
  if (hier && is_host_leader && intra_world > 1) {
    tiers.push_back(
        {kTierIntra, intra_members[(intra_rank + 1) % intra_world],
         intra_members[(intra_rank - 1 + intra_world) % intra_world],
         stripes, {}, {}, {}, {}});
  }
  if (is_leader && inter_world > 1) {
    tiers.push_back({kTierInter, leaders[(inter_rank + 1) % inter_world],
                     leaders[(inter_rank - 1 + inter_world) % inter_world],
                     stripes_inter, {}, {}, {}, {}});
  }
  const int64_t host_next =
      host_world > 1 ? host_members[(host_rank + 1) % host_world] : -1;
  const int64_t host_prev =
      host_world > 1 ? host_members[(host_rank - 1 + host_world) % host_world]
                     : -1;
  if (host_world > 1 && !shm_on) {
    // TORCHFT_HC_SHM=0: the host ring rides loopback TCP with identical
    // geometry — the honest control the shm bench row is measured
    // against, and the fallback where /dev/shm is unavailable.
    tiers.push_back({kTierHost, host_next, host_prev, stripes, {}, {}, {},
                     {}});
  }

  // Dial every tier's next member once per stripe; the hello names the
  // (tier, stripe) slot so the peer can place accepted connections
  // regardless of arrival order, and carries the stripe COUNT so a config
  // mismatch that slipped past the store-level negotiation still fails at
  // connect, not mid-op.
  // The hello magic names the FRAME FORMAT (rev 4 raw, rev 5 CRC-guarded):
  // a pair that disagrees on TORCHFT_WIRE_CRC fails right here instead of
  // desyncing 4 bytes into the first payload frame.
  const uint32_t hello_magic = crc_ ? kHelloMagicCrc : kHelloMagic;
  for (auto& tp : tiers) {
    tp.next_addr =
        store.get(prefix + "/hc_addr_" + std::to_string(tp.next_rank),
                  remain_or_throw(deadline));
    tp.next.resize(tp.conns);
    for (int64_t s = 0; s < tp.conns; s++) {
      tp.next[s] = connect_with_retry(tp.next_addr, remain_or_throw(deadline));
      uint32_t hello[5] = {hello_magic, static_cast<uint32_t>(rank),
                           static_cast<uint32_t>(s),
                           static_cast<uint32_t>(tp.conns), tp.tier};
      tp.next[s].send_all(hello, sizeof(hello), deadline);
    }
    tp.prev.resize(tp.conns);
  }

  int64_t expected = 0;
  for (auto& tp : tiers) expected += tp.conns;
  for (int64_t i = 0; i < expected; i++) {
    Socket sock = listener_->accept(deadline);
    if (!sock.valid()) throw SocketError("listener closed during configure");
    uint32_t peer_hello[5];
    sock.recv_all(peer_hello, sizeof(peer_hello), deadline);
    if (peer_hello[0] != hello_magic) {
      if (peer_hello[0] == kHelloMagic || peer_hello[0] == kHelloMagicCrc)
        throw SocketError(
            "ring handshake: wire-CRC mismatch (this rank has "
            "TORCHFT_WIRE_CRC " + std::string(crc_ ? "on" : "off") +
            ", peer has the opposite — all members must agree; the store "
            "negotiation should have caught this first)");
      throw SocketError(
          "ring handshake: wire-protocol mismatch (peer binary speaks a "
          "different ring protocol revision)");
    }
    TierPlanEntry* tp = nullptr;
    for (auto& cand : tiers)
      if (cand.tier == peer_hello[4]) { tp = &cand; break; }
    if (tp == nullptr)
      throw SocketError(
          "ring handshake: connection for a tier this rank does not "
          "participate in (mismatched region maps?)");
    if (peer_hello[1] != static_cast<uint32_t>(tp->prev_rank))
      throw SocketError("ring handshake: unexpected peer rank");
    if (peer_hello[3] != static_cast<uint32_t>(tp->conns))
      throw SocketError(
          "ring handshake: stripe-count mismatch (this rank " +
          std::to_string(tp->conns) + ", prev rank " +
          std::to_string(peer_hello[3]) +
          " — all members must configure the same stripes)");
    uint32_t slot = peer_hello[2];
    if (slot >= static_cast<uint32_t>(tp->conns) || tp->prev[slot].valid())
      throw SocketError("ring handshake: bad or duplicate stripe index");
    if (tp->prev_addr.empty()) tp->prev_addr = peer_addr_str(sock.fd());
    tp->prev[slot] = std::move(sock);
  }

  // Shared-memory host edges: created/attached AFTER the TCP rendezvous
  // (the store round already ordered everyone into this generation), one
  // edge pair per stripe. Deadline-bounded like every phase-2 step.
  std::vector<ShmEdge> shm_edges;
  if (host_world > 1 && shm_on) {
    // Segment namespace: the store prefix is unique per quorum, so its
    // hash scopes the names to this generation; ranks scope the edge.
    std::string base = "tft_hc_" + [&] {
      char buf[20];
      snprintf(buf, sizeof(buf), "%016llx",
               static_cast<unsigned long long>(fnv64(store_addr)));
      return std::string(buf);
    }();
    wire_shm_edges(shm_edges, stripes, base, host_next, host_prev, deadline);
  }

  // Phase 3: publish the new rings unless an abort raced in.
  MutexLock lock(cfg_mu_);
  if (abort_epoch_ != epoch) throw SocketError("aborted during configure");
  for (auto& tp : tiers) {
    RingTier& T = tp.tier == kTierFlat ? flat_
                  : tp.tier == kTierIntra ? intra_
                  : tp.tier == kTierInter ? inter_
                                          : host_;
    T.next = std::move(tp.next);
    T.prev = std::move(tp.prev);
    T.peer_next_addr = tp.next_addr;
    T.peer_prev_addr = tp.prev_addr;
    for (size_t s = 0; s < T.scratch.size(); s++)
      T.scratch[s].tag = "tier=" + T.name + " stripe=" + std::to_string(s) +
                         " prev_peer=" + T.peer_prev_addr;
  }
  if (!shm_edges.empty()) {
    host_.use_shm = true;
    host_.shm = std::move(shm_edges);
    host_.peer_next_addr = "shm:rank" + std::to_string(host_next);
    host_.peer_prev_addr = "shm:rank" + std::to_string(host_prev);
    for (size_t s = 0; s < host_.scratch.size(); s++)
      host_.scratch[s].tag = "tier=host stripe=" + std::to_string(s) +
                             " prev_peer=" + host_.peer_prev_addr;
  }
  aborted_ = false;
}

void HostCollectives::wire_shm_edges(std::vector<ShmEdge>& edges,
                                     int64_t conns, const std::string& base,
                                     int64_t next_rank, int64_t prev_rank,
                                     int64_t deadline) {
  const size_t seg_bytes = kShmHdrBytes + shm_ring_bytes_;
  for (int64_t s = 0; s < conns; s++) {
    ShmEdge e;
    std::string txname = base + "_" + std::to_string(rank_) + "_" +
                         std::to_string(next_rank) + "_s" + std::to_string(s);
    // Defensive unlink: a SIGKILLed predecessor of a crashed run may have
    // leaked the name (same idempotent discipline as the iso plane).
    ShmSegment::Unlink(txname);
    e.tx.reset(ShmSegment::Create(txname, seg_bytes));
    // Fresh segments are zero-filled (ftruncate): head/tail/doorbells
    // start at 0; publish capacity, then the magic with release so an
    // attacher that sees the magic sees the capacity too.
    ShmRingHdr* h = shm_ring_hdr(e.tx->data());
    h->capacity = static_cast<uint32_t>(shm_ring_bytes_);
    h->owner_pid.store(static_cast<uint32_t>(getpid()),
                       std::memory_order_relaxed);
    h->magic.store(kShmRingMagic, std::memory_order_release);

    std::string rxname = base + "_" + std::to_string(prev_rank) + "_" +
                         std::to_string(rank_) + "_s" + std::to_string(s);
    for (;;) {
      remain_or_throw(deadline);
      try {
        e.rx.reset(ShmSegment::Attach(rxname, seg_bytes));
        break;
      } catch (const SocketError&) {
        // Not created yet (or still the wrong generation's size): the
        // peer is inside its own configure. Retry until the deadline.
        struct timespec ts{0, 5 * 1000000};
        nanosleep(&ts, nullptr);
      }
    }
    ShmRingHdr* rh = shm_ring_hdr(e.rx->data());
    while (rh->magic.load(std::memory_order_acquire) != kShmRingMagic) {
      remain_or_throw(deadline);
      struct timespec ts{0, 1 * 1000000};
      nanosleep(&ts, nullptr);
    }
    if (rh->capacity != shm_ring_bytes_)
      throw SocketError(
          "shm ring capacity mismatch (TORCHFT_HC_SHM_RING_BYTES drifted "
          "across co-hosted members: mine " +
          std::to_string(shm_ring_bytes_) + ", peer " +
          std::to_string(rh->capacity) + ")");
    rh->peer_pid.store(static_cast<uint32_t>(getpid()),
                       std::memory_order_relaxed);
    edges.push_back(std::move(e));
  }
}

void HostCollectives::duplex(Socket& next, Socket& prev, const char* send_buf,
                             size_t send_len, char* recv_buf, size_t recv_len,
                             int64_t deadline_ms, StripeScratch* sc,
                             bool header_frame) {
  const double bps = sc ? static_cast<double>(sc->cap_bps) : 0.0;
  PaceState* pace = sc ? &sc->pace : nullptr;
  // Burst = 20 ms of credit (floor 64 KB): small enough that the realized
  // rate tracks the cap within any measurement window, large enough that a
  // chunk-sized write needs one send call.
  const double burst = std::max(65536.0, bps / 50.0);

  // Chaos seam: the ring frame send path. Disarmed, this is one relaxed
  // atomic load; armed, the seeded schedule decides per (member,
  // op_index) — and at most one frame of the op is hit (the harness arms
  // one-shot rules), on whichever stripe claims the firing first.
  bool flip_pending = false;
  bool partitioned = false;
  fault::Decision fd =
      send_len > 0
          ? TFT_FAULT_CHECK(header_frame ? fault::kSeamRingHdr
                                         : fault::kSeamRingSend,
                            rank_, op_seq_)
          : fault::Decision{};
  if (fd.kind != fault::kNone) {
    // Deadline-bounded raw send of a fault's own bytes (the sockets are
    // non-blocking).
    auto raw_send = [&](const char* buf, size_t n) {
      size_t done = 0;
      while (done < n) {
        ssize_t w =
            ::send(next.fd(), buf + done, n - done, MSG_NOSIGNAL | MSG_DONTWAIT);
        if (w > 0) {
          done += static_cast<size_t>(w);
          if (sc) sc->tx_bytes += w;
          continue;
        }
        if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
          struct pollfd pfd{next.fd(), POLLOUT, 0};
          int timeout =
              poll_timeout_or_throw(deadline_ms, "collective timed out");
          if (::poll(&pfd, 1, timeout) < 0 && errno != EINTR)
            throw SocketError(std::string("poll: ") + strerror(errno));
          continue;
        }
        if (w < 0 && errno == EINTR) continue;
        throw SocketError(std::string("ring send: ") + strerror(errno));
      }
    };
    switch (fd.kind) {
      case fault::kDrop:
        next.shutdown_rdwr();
        prev.shutdown_rdwr();
        throw SocketError("chaos injected: ring send dropped (" +
                          (sc ? sc->tag : std::string("?")) + ")");
      case fault::kDelay: {
        // Bounded by the op deadline (the fault.h contract): a delay
        // fault stalls the op, it must never stall PAST the op.
        int64_t ms = fd.param;
        if (deadline_ms >= 0) {
          int64_t remain = deadline_ms - now_ms();
          if (remain < 0) remain = 0;
          if (ms > remain) ms = remain;
        }
        struct timespec ts;
        ts.tv_sec = ms / 1000;
        ts.tv_nsec = (ms % 1000) * 1000000;
        nanosleep(&ts, nullptr);
        break;
      }
      case fault::kTruncate:
        // A torn write then death: the peer sees a partial frame + EOF.
        raw_send(send_buf, send_len / 2);
        next.shutdown_rdwr();
        prev.shutdown_rdwr();
        throw SocketError("chaos injected: ring send truncated (" +
                          (sc ? sc->tag : std::string("?")) + ")");
      case fault::kDuplicate:
        // Repeat a prefix: every later byte of the stream lands at the
        // wrong offset. With CRC on, THIS frame's trailer check catches
        // it; off, the desync surfaces at the next op header.
        raw_send(send_buf, send_len < 16 ? send_len : 16);
        break;
      case fault::kBitFlip:
        // Applied to the first chunk actually sent below: the caller's
        // buffer (and the CRC, computed over the ORIGINAL bytes) stay
        // clean — only the wire is poisoned.
        flip_pending = true;
        break;
      case fault::kPartition:
        // Asymmetric partition: our sends silently vanish while our
        // receives keep draining — the peer stalls until ITS op
        // deadline (a stall, not an error, is the injected failure).
        partitioned = true;
        break;
      default:
        break;
    }
  }

  // CRC-guarded framing (negotiated at configure): each direction with a
  // payload carries a 4-byte CRC32C trailer after its last payload byte.
  // The CRC state updates incrementally per kernel chunk, so the payload
  // is walked exactly once either way; with crc_ off the totals collapse
  // to the raw lengths and no CRC code runs — the single-branch contract.
  const bool crc = crc_;
  const size_t send_total = send_len + ((crc && send_len > 0) ? 4 : 0);
  const size_t recv_total = recv_len + ((crc && recv_len > 0) ? 4 : 0);
  uint32_t scrc = 0xFFFFFFFFu;
  uint32_t rcrc = 0xFFFFFFFFu;
  char strail[4];
  char rtrail[4];
  size_t sent = partitioned ? send_total : 0;
  size_t got = 0;
  while (sent < send_total || got < recv_total) {
    // Refill the token bucket and decide whether this pass may send; when
    // token-dry, the send fd leaves the poll set and the poll timeout
    // shrinks to the refill time, so receives still drain at full speed.
    // Pacing covers payload bytes only (the 4-byte trailer is noise).
    int64_t pace_wait_ms = -1;
    bool may_send = sent < send_total;
    if (may_send && sent < send_len && pace && bps > 0) {
      auto now = std::chrono::steady_clock::now();
      if (!pace->init) {
        pace->init = true;
        pace->tokens = burst;
      } else {
        pace->tokens +=
            std::chrono::duration<double>(now - pace->last).count() * bps;
        if (pace->tokens > burst) pace->tokens = burst;
      }
      pace->last = now;
      if (pace->tokens < 1.0) {
        may_send = false;
        pace_wait_ms =
            static_cast<int64_t>((1.0 - pace->tokens) / bps * 1000.0) + 1;
      }
    }
    struct pollfd pfds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    if (may_send) {
      send_idx = n;
      pfds[n].fd = next.fd();
      pfds[n].events = POLLOUT;
      n++;
    }
    if (got < recv_total) {
      recv_idx = n;
      pfds[n].fd = prev.fd();
      pfds[n].events = POLLIN;
      n++;
    }
    int timeout = poll_timeout_or_throw(deadline_ms, "collective timed out");
    if (pace_wait_ms >= 0 && (timeout < 0 || pace_wait_ms < timeout))
      timeout = static_cast<int>(pace_wait_ms);
    int prc = ::poll(pfds, n, timeout);
    if (prc == 0) {
      if (pace_wait_ms >= 0) continue;  // token refill elapsed, not a stall
      throw TimeoutError("collective timed out");
    }
    if (prc < 0) {
      if (errno == EINTR) continue;
      throw SocketError(std::string("poll: ") + strerror(errno));
    }
    if (send_idx >= 0 && (pfds[send_idx].revents & (POLLOUT | POLLERR | POLLHUP))) {
      if (sent < send_len) {
        size_t allow = send_len - sent;
        if (pace && bps > 0 && static_cast<double>(allow) > pace->tokens)
          allow = static_cast<size_t>(pace->tokens);
        const char* src = send_buf + sent;
        char flipbuf[4096];
        if (flip_pending && allow > 0) {
          // Poison exactly one bit of the first byte of this chunk on
          // its way to the wire; the sender's CRC (below) covers the
          // ORIGINAL bytes, so the receiver's trailer check must fire.
          size_t n = allow < sizeof(flipbuf) ? allow : sizeof(flipbuf);
          memcpy(flipbuf, src, n);
          flipbuf[0] ^= static_cast<char>(1u << ((fd.h >> 8) % 8));
          src = flipbuf;
          allow = n;
        }
        ssize_t w = ::send(next.fd(), src, allow,
                           MSG_NOSIGNAL | MSG_DONTWAIT);
        if (w > 0) {
          if (flip_pending) flip_pending = false;  // byte 0 is out
          if (crc) scrc = fault::crc32c_update(scrc, send_buf + sent, w);
          sent += static_cast<size_t>(w);
          if (pace && bps > 0) pace->tokens -= static_cast<double>(w);
          // Per-connection tx accounting (the hierarchical per-tier byte
          // bill sums these): bytes actually handed to the kernel.
          if (sc) sc->tx_bytes += w;
          if (crc && sent == send_len) {
            uint32_t fin = ~scrc;
            memcpy(strail, &fin, sizeof(fin));
          }
        } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                   errno != EINTR) {
          throw SocketError(std::string("ring send: ") + strerror(errno));
        }
      } else {
        // CRC trailer (4 bytes, unpaced).
        ssize_t w = ::send(next.fd(), strail + (sent - send_len),
                           send_total - sent, MSG_NOSIGNAL | MSG_DONTWAIT);
        if (w > 0) {
          sent += static_cast<size_t>(w);
          if (sc) sc->tx_bytes += w;
        } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                   errno != EINTR) {
          throw SocketError(std::string("ring send: ") + strerror(errno));
        }
      }
    }
    if (recv_idx >= 0 &&
        (pfds[recv_idx].revents & (POLLIN | POLLERR | POLLHUP))) {
      if (got < recv_len) {
        ssize_t r =
            ::recv(prev.fd(), recv_buf + got, recv_len - got, MSG_DONTWAIT);
        if (r > 0) {
          if (crc) rcrc = fault::crc32c_update(rcrc, recv_buf + got, r);
          got += static_cast<size_t>(r);
        } else if (r == 0) {
          throw SocketError("ring peer closed connection");
        } else if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
          throw SocketError(std::string("ring recv: ") + strerror(errno));
        }
      } else {
        ssize_t r = ::recv(prev.fd(), rtrail + (got - recv_len),
                           recv_total - got, MSG_DONTWAIT);
        if (r > 0) {
          got += static_cast<size_t>(r);
          if (got == recv_total) {
            uint32_t want;
            memcpy(&want, rtrail, sizeof(want));
            if (want != ~rcrc)
              // The typed integrity error: rides the caller's latch ->
              // vote-discard -> reconfigure machinery instead of
              // committing poisoned bytes.
              throw WireCorruptionError(
                  "ring frame CRC32C mismatch (" +
                  (sc ? sc->tag : std::string("?")) + ", rank " +
                  std::to_string(rank_) + ", op_index " +
                  std::to_string(op_seq_) + ", frame " +
                  std::to_string(recv_len) + " bytes)");
          }
        } else if (r == 0) {
          throw SocketError("ring peer closed connection");
        } else if (errno != EAGAIN && errno != EWOULDBLOCK && errno != EINTR) {
          throw SocketError(std::string("ring recv: ") + strerror(errno));
        }
      }
    }
  }
}

void HostCollectives::edge_duplex(RingTier& T, int64_t s, const char* send_buf,
                                  size_t send_len, char* recv_buf,
                                  size_t recv_len, int64_t deadline_ms,
                                  bool header_frame) {
  if (T.use_shm)
    shm_duplex(T, s, send_buf, send_len, recv_buf, recv_len, deadline_ms,
               header_frame);
  else
    duplex(T.next[s], T.prev[s], send_buf, send_len, recv_buf, recv_len,
           deadline_ms, &T.scratch[s], header_frame);
}

void HostCollectives::shm_duplex(RingTier& T, int64_t s, const char* send_buf,
                                 size_t send_len, char* recv_buf,
                                 size_t recv_len, int64_t deadline_ms,
                                 bool header_frame) {
  ShmEdge& e = T.shm[s];
  StripeScratch& sc = T.scratch[s];
  ShmRingHdr* txh = shm_ring_hdr(e.tx->data());
  ShmRingHdr* rxh = shm_ring_hdr(e.rx->data());
  char* txd = shm_ring_data(e.tx->data());
  char* rxd = shm_ring_data(e.rx->data());
  const uint32_t tx_cap = txh->capacity;
  const uint32_t rx_cap = rxh->capacity;

  // Chaos seam: the shm ring frame path (payload frames only — like
  // ring_hdr/ring_send, a "mid-ring corruption" plan must not be
  // satisfiable by the op header). Disarmed: one relaxed atomic load.
  bool swallow = false;  // drop-doorbell: the publish silently vanishes
  bool stale = false;    // stale-payload: replay the previous frame seq
  bool torn = false;     // torn-segment: half a frame, then poison + die
  fault::Decision fd =
      (send_len > 0 && !header_frame)
          ? TFT_FAULT_CHECK(fault::kSeamShmRing, rank_, op_seq_)
          : fault::Decision{};
  switch (fd.kind) {
    case fault::kDrop:
    case fault::kPartition:
      // The doorbell (and the bytes behind it) never land: the consumer
      // stalls until ITS op deadline — the stall, not an error, is the
      // injected failure (the co-hosted analog of an asymmetric
      // partition / SIGKILLed producer).
      swallow = true;
      break;
    case fault::kBitFlip:
      stale = true;
      break;
    case fault::kTruncate:
      torn = true;
      break;
    case fault::kDelay: {
      int64_t ms = fd.param;
      if (deadline_ms >= 0) {
        int64_t remain = deadline_ms - now_ms();
        if (remain < 0) remain = 0;
        if (ms > remain) ms = remain;
      }
      struct timespec ts;
      ts.tv_sec = ms / 1000;
      ts.tv_nsec = (ms % 1000) * 1000000;
      nanosleep(&ts, nullptr);
      break;
    }
    default:
      break;
  }

  ShmFrame shdr{};
  // A swallowed (dropped/partitioned) frame never ships: its sequence
  // must not advance either, or a later frame would read as a skip.
  if (send_len > 0 && !swallow) e.fseq_tx++;
  shdr.fseq = stale ? e.fseq_tx - 1 : e.fseq_tx;
  shdr.len = static_cast<uint32_t>(send_len);
  const char* shdr_bytes = reinterpret_cast<const char*>(&shdr);
  const size_t send_total = send_len > 0 ? sizeof(ShmFrame) + send_len : 0;
  // Torn-segment fault: stop mid-frame, poison, die (the consumer's
  // magic check is the detection).
  const size_t send_stop =
      torn ? sizeof(ShmFrame) + send_len / 2 : send_total;
  const size_t recv_total = recv_len > 0 ? sizeof(ShmFrame) + recv_len : 0;

  size_t sent = swallow ? send_total : 0;
  size_t got = 0;
  char rhdr_buf[sizeof(ShmFrame)];
  bool rhdr_checked = recv_total == 0;

  while (sent < send_total || got < recv_total) {
    if (aborted_.load(std::memory_order_relaxed))
      throw SocketError("collective aborted (" + sc.tag + ")");
    // Doorbell values read BEFORE the condition re-check: the standard
    // futex lost-wakeup protocol (a publish between our check and the
    // wait makes the wait return immediately).
    uint32_t v_w = rxh->db_w.load(std::memory_order_acquire);
    uint32_t v_r = txh->db_r.load(std::memory_order_acquire);
    bool progress = false;

    if (sent < send_stop) {
      if (txh->magic.load(std::memory_order_relaxed) != kShmRingMagic)
        throw SocketError("shm ring torn (aborted or reconfigured): " +
                          sc.tag);
      uint64_t head = txh->head.load(std::memory_order_relaxed);
      uint64_t tail = txh->tail.load(std::memory_order_acquire);
      size_t space = tx_cap - static_cast<size_t>(head - tail);
      if (space > 0) {
        size_t n = std::min(space, send_stop - sent);
        // The logical stream: 16 header bytes, then the payload.
        size_t done = 0;
        while (done < n) {
          size_t off = sent + done;
          const char* src;
          size_t avail;
          if (off < sizeof(ShmFrame)) {
            src = shdr_bytes + off;
            avail = sizeof(ShmFrame) - off;
          } else {
            src = send_buf + (off - sizeof(ShmFrame));
            avail = send_total - off;
          }
          size_t chunk = std::min(n - done, avail);
          shm_ring_write(txd, tx_cap, head + done, src, chunk);
          done += chunk;
        }
        txh->head.store(head + n, std::memory_order_release);
        txh->db_w.fetch_add(1, std::memory_order_release);
        shm_futex_wake(&txh->db_w);
        sc.shm_bytes += static_cast<int64_t>(n);
        sent += n;
        progress = true;
      }
      if (torn && sent >= send_stop) {
        {
          MutexLock lock(cfg_mu_);
          shm_poison_wake_locked();
        }
        throw SocketError("chaos injected: shm segment torn (" + sc.tag +
                          ")");
      }
    }

    if (got < recv_total) {
      uint64_t head = rxh->head.load(std::memory_order_acquire);
      uint64_t tail = rxh->tail.load(std::memory_order_relaxed);
      size_t avail = static_cast<size_t>(head - tail);
      if (avail == 0 &&
          rxh->magic.load(std::memory_order_acquire) != kShmRingMagic)
        throw SocketError("shm ring torn by peer (abort or death): " +
                          sc.tag);
      if (avail > 0) {
        size_t n = std::min(avail, recv_total - got);
        size_t done = 0;
        while (done < n) {
          size_t off = got + done;
          char* dst;
          size_t room;
          if (off < sizeof(ShmFrame)) {
            dst = rhdr_buf + off;
            room = sizeof(ShmFrame) - off;
          } else {
            dst = recv_buf + (off - sizeof(ShmFrame));
            room = recv_total - off;
          }
          size_t chunk = std::min(n - done, room);
          shm_ring_read(rxd, rx_cap, tail + done, dst, chunk);
          done += chunk;
        }
        rxh->tail.store(tail + n, std::memory_order_release);
        rxh->db_r.fetch_add(1, std::memory_order_release);
        shm_futex_wake(&rxh->db_r);
        got += n;
        progress = true;
        if (!rhdr_checked && got >= sizeof(ShmFrame)) {
          ShmFrame rhdr;
          memcpy(&rhdr, rhdr_buf, sizeof(rhdr));
          e.fseq_rx++;
          if (rhdr.fseq != e.fseq_rx)
            // The typed integrity verdict: a replayed (stale) frame must
            // ride the latch -> vote-discard -> reconfigure machinery,
            // not silently reduce yesterday's bytes.
            throw WireCorruptionError(
                "shm ring stale frame (" + sc.tag + ", rank " +
                std::to_string(rank_) + ", op_index " +
                std::to_string(op_seq_) + ": expected frame " +
                std::to_string(e.fseq_rx) + ", got " +
                std::to_string(rhdr.fseq) + ")");
          if (rhdr.len != recv_len)
            throw SocketError(
                "shm ring frame desync (" + sc.tag + "): expected " +
                std::to_string(recv_len) + " bytes, peer framed " +
                std::to_string(rhdr.len) +
                " (members must run identical ops)");
          rhdr_checked = true;
        }
      }
    }

    if (!progress) {
      int64_t remain = deadline_ms < 0 ? 100 : deadline_ms - now_ms();
      if (remain <= 0) throw TimeoutError("collective timed out");
      // Liveness probe before sleeping: a SIGKILLed co-hosted peer
      // leaves no FIN and no poison — its pid vanishing is the only
      // signal, checked once per slice (~100 ms surfacing).
      if (got < recv_total &&
          shm_pid_gone(rxh->owner_pid.load(std::memory_order_relaxed)))
        throw SocketError("shm ring peer died (producer pid gone): " +
                          sc.tag);
      if (sent < send_stop &&
          shm_pid_gone(txh->peer_pid.load(std::memory_order_relaxed)))
        throw SocketError("shm ring peer died (consumer pid gone): " +
                          sc.tag);
      // Wait on whichever side is blocking us; receives take priority
      // (they are what unblocks a full TX ring on the far side).
      if (got < recv_total)
        shm_futex_wait(&rxh->db_w, v_w, remain);
      else
        shm_futex_wait(&txh->db_r, v_r, remain);
    }
  }
}

void HostCollectives::check_op_header(RingTier& T, uint32_t kind,
                                      uint64_t count, uint32_t dtype,
                                      uint32_t op, int64_t deadline_ms) {
  // One tiny duplex exchange describing the op each neighbor is about to
  // run. A mismatched op (different tree sizes, dtypes, or op kinds on
  // different members) otherwise DEADLOCKS silently: the small member
  // finishes, stops reading, and the large member blocks forever once
  // kernel buffers fill. ~20 bytes per collective — noise next to any
  // payload — converts that into an immediate, descriptive error. Runs on
  // stripe 0 of the tier (the stripe COUNT is already pinned at connect
  // time by the hello, so one stripe's agreement covers the schedule);
  // hierarchical ops run it once per tier they touch.
  struct Header {
    uint32_t magic, kind;
    uint64_t count;
    uint32_t dtype, op;
  } mine{kOpMagic, kind, count, dtype, op}, theirs{};
  edge_duplex(T, 0, reinterpret_cast<const char*>(&mine), sizeof(mine),
              reinterpret_cast<char*>(&theirs), sizeof(theirs), deadline_ms,
              /*header_frame=*/true);
  if (theirs.magic != kOpMagic)
    // Keep the historic prefix (operators and tests grep for it); the
    // context after it is what makes the error actionable in a W=8
    // fleet log — which edge, which tier, which op.
    throw SocketError(
        "ring op header corrupt (protocol desync): tier=" + T.name +
        " prev_peer=" + T.peer_prev_addr + " op_kind=" +
        std::to_string(kind) + " op_index=" + std::to_string(op_seq_) +
        " rank=" + std::to_string(rank_) + " got_magic=0x" + [&] {
          char buf[16];
          snprintf(buf, sizeof(buf), "%08x", theirs.magic);
          return std::string(buf);
        }());
  if (theirs.kind != mine.kind || theirs.count != mine.count ||
      theirs.dtype != mine.dtype || theirs.op != mine.op)
    throw SocketError(
        "ring op mismatch: this rank kind=" + std::to_string(kind) +
        " count=" + std::to_string(count) + " dtype=" +
        std::to_string(dtype) + " op=" + std::to_string(op) +
        ", prev rank kind=" + std::to_string(theirs.kind) + " count=" +
        std::to_string(theirs.count) + " dtype=" +
        std::to_string(theirs.dtype) + " op=" + std::to_string(theirs.op) +
        " (members must reduce identical trees)");
}

void HostCollectives::run_striped(const std::function<void(int64_t)>& fn) {
  int64_t n = static_cast<int64_t>(last_stripe_ns_.size());
  std::vector<std::exception_ptr> errs(n);

  auto body = [&](int64_t s) {
    auto t0 = std::chrono::steady_clock::now();
    try {
      fn(s);
    } catch (...) {
      errs[s] = std::current_exception();
      // Wake every sibling stripe immediately: they share the op's fate,
      // and letting them block until their timeout would stall the abort
      // path the whole design exists to keep fast.
      shutdown_sockets();
    }
    last_stripe_ns_[s] =
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - t0)
            .count();
  };

  if (n <= 1) {
    body(0);
  } else {
    // Publish the job to the persistent workers (a thread per stripe per
    // native op would cost more than the stripe's transport at pipelined
    // chunk sizes), run stripe 0 here, then wait for the drain. The drain
    // wait is unconditional-bounded: failing stripes shut down every
    // socket, so no sibling can block past its IO wakeup.
    std::function<void(int64_t)> body_fn = body;
    ensure_pool(n - 1);
    {
      MutexLock lock(pool_mu_);
      pool_body_ = &body_fn;
      pool_n_ = n;
      pool_pending_ = n - 1;
      pool_gen_++;
    }
    pool_cv_.notify_all();
    body(0);
    {
      UniqueMutexLock lock(pool_mu_);
      while (pool_pending_ != 0) pool_done_cv_.wait(lock);
      pool_body_ = nullptr;
    }
  }
  // ONE error is rethrown. A typed WireCorruptionError beats its
  // siblings regardless of stripe index: the failing stripe's shutdown
  // makes every other stripe die with a GENERIC socket error, and
  // rethrowing one of those would erase the integrity verdict the
  // cross-language "wire corruption:" contract (and the chaos harness's
  // detection ledger) depends on. Otherwise: lowest stripe wins.
  std::exception_ptr chosen;
  for (auto& e : errs) {
    if (!e) continue;
    if (!chosen) chosen = e;
    try {
      std::rethrow_exception(e);
    } catch (const WireCorruptionError&) {
      chosen = e;
      break;
    } catch (...) {
    }
  }
  if (chosen) std::rethrow_exception(chosen);
}

void HostCollectives::ensure_pool(int64_t workers) {
  MutexLock lock(pool_mu_);
  while (static_cast<int64_t>(pool_.size()) < workers) {
    // Seed each worker with the CURRENT generation (stable under pool_mu_):
    // a fresh thread must not mistake an already-running or past job for
    // its first wakeup.
    pool_.emplace_back(&HostCollectives::pool_main, this,
                       static_cast<int64_t>(pool_.size()), pool_gen_);
  }
}

void HostCollectives::pool_main(int64_t idx, int64_t start_gen) {
  int64_t seen_gen = start_gen;
  for (;;) {
    const std::function<void(int64_t)>* body;
    int64_t n;
    {
      UniqueMutexLock lock(pool_mu_);
      while (!pool_stop_ && pool_gen_ == seen_gen) pool_cv_.wait(lock);
      if (pool_stop_) return;
      seen_gen = pool_gen_;
      body = pool_body_;
      n = pool_n_;
    }
    // Worker idx owns stripe idx+1; jobs narrower than the pool (fewer
    // effective stripes) don't count the spare workers in pool_pending_.
    if (idx + 1 < n) {
      (*body)(idx + 1);
      MutexLock lock(pool_mu_);
      if (--pool_pending_ == 0) pool_done_cv_.notify_all();
    }
  }
}

void HostCollectives::rs_phase_stripe(RingTier& T, int64_t s, char* bytes,
                                      size_t count, size_t esize, Dtype dtype,
                                      ReduceOp op, int64_t deadline) {
  size_t max_chunk = count / T.world + 1;
  std::vector<char>& recv_tmp = T.scratch[s].recv;
  if (recv_tmp.size() < max_chunk * esize) recv_tmp.resize(max_chunk * esize);

  // Reduce-scatter: after step t, chunk (rank - t) has accumulated the
  // values of ranks rank-t..rank. After ws-1 steps chunk (rank+1) holds the
  // full reduction at this rank — computed in the identical rank order
  // everywhere.
  for (int64_t t = 0; t < T.world - 1; t++) {
    int64_t send_c = ((T.rank - t) % T.world + T.world) % T.world;
    int64_t recv_c = ((T.rank - t - 1) % T.world + T.world) % T.world;
    auto [s_start, s_len] = chunk_range(count, T.world, send_c);
    auto [r_start, r_len] = chunk_range(count, T.world, recv_c);
    edge_duplex(T, s, bytes + s_start * esize, s_len * esize,
                recv_tmp.data(), r_len * esize, deadline);
    reduce_into(bytes + r_start * esize, recv_tmp.data(), r_len, dtype, op);
  }
}

void HostCollectives::ag_phase_stripe(RingTier& T, int64_t s, char* bytes,
                                      size_t count, size_t esize,
                                      int64_t deadline) {
  // Allgather: circulate the owned chunks, starting from (rank + 1) —
  // the chunk the reduce-scatter phase leaves fully reduced here.
  for (int64_t t = 0; t < T.world - 1; t++) {
    int64_t send_c = ((T.rank + 1 - t) % T.world + T.world) % T.world;
    int64_t recv_c = ((T.rank - t) % T.world + T.world) % T.world;
    auto [s_start, s_len] = chunk_range(count, T.world, send_c);
    auto [r_start, r_len] = chunk_range(count, T.world, recv_c);
    edge_duplex(T, s, bytes + s_start * esize, s_len * esize,
                bytes + r_start * esize, r_len * esize, deadline);
  }
}

void HostCollectives::allreduce_stripe(RingTier& T, int64_t s, char* bytes,
                                       size_t count, size_t esize, Dtype dtype,
                                       ReduceOp op, int64_t deadline) {
  rs_phase_stripe(T, s, bytes, count, esize, dtype, op, deadline);
  ag_phase_stripe(T, s, bytes, count, esize, deadline);
}

void HostCollectives::allreduce(void* data, size_t count, Dtype dtype,
                                ReduceOp op, int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // header exchanged even for count==0: an empty-vs-nonempty mismatch
    // must error, not hang the nonempty member
    check_op_header(flat_, 0, count, static_cast<uint32_t>(dtype),
                    static_cast<uint32_t>(op), deadline);
    if (count == 0) return;
    char* bytes = static_cast<char*>(data);
    size_t esize = dtype_size(dtype);
    int64_t eff = effective_stripes(count * esize, stripes_);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      allreduce_stripe(flat_, s, bytes + start * esize, len, esize, dtype, op,
                       deadline);
    });
  });
}

namespace {

// One chunk on the q8 wire: 4-byte f32 scale, then `len` int8 codes.
void q8_encode(const float* src, size_t len, char* wire) {
  float absmax = 0.f;
  bool finite = true;
  for (size_t i = 0; i < len; i++) {
    float a = std::fabs(src[i]);
    if (!std::isfinite(a)) finite = false;
    absmax = std::max(absmax, a);
  }
  if (!finite) {
    // Non-finite gradients must poison the result the way the f32/bf16
    // wires do: std::max/min drop NaN (they return the other operand),
    // so a diverged model would otherwise be encoded as clamped finite
    // codes and the blow-up silently hidden. A NaN scale makes every
    // decoded element NaN on all ranks.
    float nan = std::numeric_limits<float>::quiet_NaN();
    memcpy(wire, &nan, sizeof(float));
    memset(wire + sizeof(float), 0, len);
    return;
  }
  float scale = absmax > 0.f ? absmax / 127.f : 1.f;
  memcpy(wire, &scale, sizeof(float));
  int8_t* q = reinterpret_cast<int8_t*>(wire + sizeof(float));
  for (size_t i = 0; i < len; i++) {
    float v = std::nearbyint(src[i] / scale);
    q[i] = static_cast<int8_t>(std::max(-127.f, std::min(127.f, v)));
  }
}

// dst[i] (+)= scale * q[i]
void q8_decode(const char* wire, size_t len, float* dst, bool accumulate) {
  float scale;
  memcpy(&scale, wire, sizeof(float));
  const int8_t* q = reinterpret_cast<const int8_t*>(wire + sizeof(float));
  if (accumulate) {
    for (size_t i = 0; i < len; i++) dst[i] += scale * static_cast<float>(q[i]);
  } else {
    for (size_t i = 0; i < len; i++) dst[i] = scale * static_cast<float>(q[i]);
  }
}

}  // namespace

void HostCollectives::rs_q8_phase_stripe(RingTier& T, int64_t s, float* data,
                                         size_t count, int64_t deadline) {
  size_t max_chunk = count / T.world + 1;
  size_t max_wire = sizeof(float) + max_chunk;
  std::vector<char>& send_wire = T.scratch[s].send;
  std::vector<char>& recv_wire = T.scratch[s].recv;
  if (send_wire.size() < max_wire) send_wire.resize(max_wire);
  if (recv_wire.size() < max_wire) recv_wire.resize(max_wire);

  // Reduce-scatter: each hop quantizes its CURRENT partial sum of the
  // outgoing chunk and dequant-accumulates the incoming one in f32.
  for (int64_t t = 0; t < T.world - 1; t++) {
    int64_t send_c = ((T.rank - t) % T.world + T.world) % T.world;
    int64_t recv_c = ((T.rank - t - 1) % T.world + T.world) % T.world;
    auto [s_start, s_len] = chunk_range(count, T.world, send_c);
    auto [r_start, r_len] = chunk_range(count, T.world, recv_c);
    q8_encode(data + s_start, s_len, send_wire.data());
    edge_duplex(T, s, send_wire.data(), sizeof(float) + s_len,
                recv_wire.data(), sizeof(float) + r_len, deadline);
    q8_decode(recv_wire.data(), r_len, data + r_start, /*accumulate=*/true);
  }
}

void HostCollectives::ag_q8_phase_stripe(RingTier& T, int64_t s, float* data,
                                         size_t count, int64_t deadline) {
  // Allgather: the OWNER quantizes its fully-reduced chunk exactly once
  // (first send); every later hop forwards the received wire bytes
  // verbatim, so all members decode identical codes — the reduced
  // values stay bit-identical across ranks (the determinism oracle).
  std::vector<std::vector<char>>& stored = T.scratch[s].stored;
  stored.resize(T.world);
  {
    int64_t own_c = (T.rank + 1) % T.world;
    auto [o_start, o_len] = chunk_range(count, T.world, own_c);
    stored[own_c].resize(sizeof(float) + o_len);
    q8_encode(data + o_start, o_len, stored[own_c].data());
    // decode own chunk too: every member must hold the DECODED codes,
    // not its higher-precision f32 partial (bit-identity across ranks)
    q8_decode(stored[own_c].data(), o_len, data + o_start, false);
  }
  for (int64_t t = 0; t < T.world - 1; t++) {
    int64_t send_c = ((T.rank + 1 - t) % T.world + T.world) % T.world;
    int64_t recv_c = ((T.rank - t) % T.world + T.world) % T.world;
    auto [r_start, r_len] = chunk_range(count, T.world, recv_c);
    stored[recv_c].resize(sizeof(float) + r_len);
    edge_duplex(T, s, stored[send_c].data(), stored[send_c].size(),
                stored[recv_c].data(), stored[recv_c].size(), deadline);
    q8_decode(stored[recv_c].data(), r_len, data + r_start, false);
  }
}

void HostCollectives::allreduce_q8_stripe(RingTier& T, int64_t s, float* data,
                                          size_t count, int64_t deadline) {
  rs_q8_phase_stripe(T, s, data, count, deadline);
  ag_q8_phase_stripe(T, s, data, count, deadline);
}

void HostCollectives::allreduce_q8(float* data, size_t count,
                                   int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // distinct kind: a q8 op meeting a plain allreduce must error, not
    // desync (their wire framings differ even at equal counts)
    check_op_header(flat_, 4, count, /*dtype=*/100, /*op=*/0, deadline);
    if (count == 0) return;
    // ~1 wire byte per f32 element (int8 codes + per-chunk scales)
    int64_t eff = effective_stripes(count, stripes_);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      allreduce_q8_stripe(flat_, s, data + start, len, deadline);
    });
  });
}

void HostCollectives::allgather(const void* in, void* out, size_t nbytes,
                                int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  if (aborted_) throw SocketError("collectives not configured");
  char* slots = static_cast<char*>(out);
  memcpy(slots + rank_ * nbytes, in, nbytes);
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    check_op_header(flat_, 1, nbytes, 0, 0, deadline);
    if (nbytes == 0) return;
    int64_t eff = effective_stripes(nbytes, stripes_);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t st) {
      auto [off, len] = stripe_range(nbytes, eff, st);
      if (len == 0) return;
      for (int64_t t = 0; t < world_size_ - 1; t++) {
        int64_t send_c = ((rank_ - t) % world_size_ + world_size_) % world_size_;
        int64_t recv_c =
            ((rank_ - t - 1) % world_size_ + world_size_) % world_size_;
        duplex(flat_.next[st], flat_.prev[st], slots + send_c * nbytes + off,
               len, slots + recv_c * nbytes + off, len, deadline,
               &flat_.scratch[st]);
      }
    });
  });
}

std::vector<std::pair<size_t, size_t>> HostCollectives::shard_ranges(
    size_t count, size_t esize, int64_t r, int64_t layout_stripes) const {
  if (r < 0 || r >= world_size_) throw SocketError("bad shard rank");
  int64_t eff = layout_stripes > 0
                    ? std::min(layout_stripes, stripes_)
                    : effective_stripes(count * esize, stripes_);
  int64_t own_c = (r + 1) % world_size_;
  std::vector<std::pair<size_t, size_t>> out;
  for (int64_t s = 0; s < eff; s++) {
    auto [st, sl] = stripe_range(count, eff, s);
    if (sl == 0) continue;
    auto [cs, cl] = chunk_range(sl, world_size_, own_c);
    if (cl) out.emplace_back(st + cs, cl);
  }
  return out;
}

void HostCollectives::copy_shard(char* data, char* shard, size_t count,
                                 size_t esize, int64_t eff,
                                 bool to_shard) const {
  // One source of truth for the layout: walk the same ranges Python gets
  // from shard_ranges, so compaction can never disagree with them.
  size_t off = 0;
  for (auto [start, len] : shard_ranges(count, esize, rank_, eff)) {
    if (to_shard)
      memcpy(shard + off * esize, data + start * esize, len * esize);
    else
      memcpy(data + start * esize, shard + off * esize, len * esize);
    off += len;
  }
}

void HostCollectives::reduce_scatter(void* data, size_t count, Dtype dtype,
                                     ReduceOp op, void* shard_out,
                                     int64_t layout_stripes,
                                     int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  if (aborted_) throw SocketError("collectives not configured");
  size_t esize = dtype_size(dtype);
  if (world_size_ == 1) {
    memcpy(shard_out, data, count * esize);
    return;
  }
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    int64_t eff = layout_stripes > 0
                      ? std::min(layout_stripes, stripes_)
                      : effective_stripes(count * esize, stripes_);
    // The layout rides the header's op slot: a reduce_scatter meeting a
    // differently-partitioned one must error, not scatter to the wrong
    // shard boundaries (ReduceOp fits in the low byte).
    check_op_header(flat_, 5, count, static_cast<uint32_t>(dtype),
                    static_cast<uint32_t>(op) |
                        (static_cast<uint32_t>(eff) << 8),
                    deadline);
    if (count == 0) return;
    char* bytes = static_cast<char*>(data);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      rs_phase_stripe(flat_, s, bytes + start * esize, len, esize, dtype, op,
                      deadline);
    });
    copy_shard(bytes, static_cast<char*>(shard_out), count, esize, eff,
               /*to_shard=*/true);
  });
}

void HostCollectives::reduce_scatter_q8(float* data, size_t count,
                                        float* shard_out, bool grid_shard,
                                        int64_t layout_stripes,
                                        int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) {
    memcpy(shard_out, data, count * sizeof(float));
    return;
  }
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // ~1 wire byte per f32 element, like the fused q8 op
    int64_t eff = layout_stripes > 0
                      ? std::min(layout_stripes, stripes_)
                      : effective_stripes(count, stripes_);
    check_op_header(flat_, 7, count, /*dtype=*/100,
                    static_cast<uint32_t>(eff) << 8, deadline);
    if (count == 0) return;
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      rs_q8_phase_stripe(flat_, s, data + start, len, deadline);
      if (grid_shard) {
        // Reproduce the fused op's phase-2 owner quantize+decode so the
        // shard sits on the same int8 grid the fused allreduce returns.
        int64_t own_c = (rank_ + 1) % world_size_;
        auto [cs, cl] = chunk_range(len, world_size_, own_c);
        if (cl) {
          std::vector<char>& wire = flat_.scratch[s].send;
          if (wire.size() < sizeof(float) + cl)
            wire.resize(sizeof(float) + cl);
          q8_encode(data + start + cs, cl, wire.data());
          q8_decode(wire.data(), cl, data + start + cs, /*accumulate=*/false);
        }
      }
    });
    copy_shard(reinterpret_cast<char*>(data),
               reinterpret_cast<char*>(shard_out), count, sizeof(float), eff,
               /*to_shard=*/true);
  });
}

void HostCollectives::allgather_into(const void* shard, void* data,
                                     size_t count, Dtype dtype,
                                     int64_t layout_stripes,
                                     int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  if (aborted_) throw SocketError("collectives not configured");
  size_t esize = dtype_size(dtype);
  if (world_size_ == 1) {
    memcpy(data, shard, count * esize);
    return;
  }
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    int64_t eff = layout_stripes > 0
                      ? std::min(layout_stripes, stripes_)
                      : effective_stripes(count * esize, stripes_);
    check_op_header(flat_, 6, count, static_cast<uint32_t>(dtype),
                    static_cast<uint32_t>(eff) << 8, deadline);
    if (count == 0) return;
    char* bytes = static_cast<char*>(data);
    copy_shard(bytes, const_cast<char*>(static_cast<const char*>(shard)),
               count, esize, eff, /*to_shard=*/false);
    last_stripe_ns_.assign(eff, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff, s);
      if (len == 0) return;
      ag_phase_stripe(flat_, s, bytes + start * esize, len, esize, deadline);
    });
  });
}

// ---- hierarchical (two-tier) schedule ----

void HostCollectives::bcast_pipe_stripe(RingTier& T, int64_t s, char* bytes,
                                        size_t nbytes, int64_t root,
                                        int64_t deadline) {
  if (T.world <= 1 || nbytes == 0) return;
  int64_t d = ((T.rank - root) % T.world + T.world) % T.world;
  // Chunk-pipelined store-and-forward: member d forwards chunk c-1 while
  // receiving chunk c (duplex pumps both directions), so the wall is
  // ~bytes/bw + (world-1) chunk fills instead of (world-1) * bytes/bw.
  // The chunk count is a pure function of nbytes — identical everywhere.
  int64_t k = std::min<int64_t>(16, std::max<int64_t>(
                                        1, static_cast<int64_t>(
                                               nbytes / (256 << 10))));
  const bool fwd = d + 1 < T.world;  // the last member's next IS the root
  for (int64_t c = 0; c < k; c++) {
    auto [cs, cl] = chunk_range(nbytes, k, c);
    if (d == 0) {
      edge_duplex(T, s, bytes + cs, cl, nullptr, 0, deadline);
    } else {
      const char* sbuf = nullptr;
      size_t slen = 0;
      if (fwd && c > 0) {
        auto [ps, pl] = chunk_range(nbytes, k, c - 1);
        sbuf = bytes + ps;
        slen = pl;
      }
      edge_duplex(T, s, sbuf, slen, bytes + cs, cl, deadline);
    }
  }
  if (d > 0 && fwd) {
    auto [ps, pl] = chunk_range(nbytes, k, k - 1);
    edge_duplex(T, s, bytes + ps, pl, nullptr, 0, deadline);
  }
}

void HostCollectives::inter_ring_phase(HierWire wire, char* buf, size_t count,
                                       size_t esize, Dtype dtype, ReduceOp op,
                                       int64_t eff_inter, int64_t deadline,
                                       int64_t* rs_tx) {
  // Two explicit ring phases (the same rs/ag bodies the flat ring uses)
  // so the per-phase slow-link bill — (L-1)/L of the wire payload each
  // way — is measured separately.
  const int64_t tx0 = tier_tx(inter_);
  if (wire == HierWire::kQ8) {
    float* f = reinterpret_cast<float*>(buf);
    last_stripe_ns_.assign(eff_inter, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_inter, s);
      if (len == 0) return;
      rs_q8_phase_stripe(inter_, s, f + start, len, deadline);
    });
    *rs_tx = tier_tx(inter_) - tx0;
    last_stripe_ns_.assign(eff_inter, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_inter, s);
      if (len == 0) return;
      ag_q8_phase_stripe(inter_, s, f + start, len, deadline);
    });
  } else if (wire == HierWire::kBF16) {
    // Leaders round the f32 payload to bf16 ONCE, ride the slow hop at
    // half width (per-hop f32 math, RNE back — the native bf16 ring
    // body), and decode; quantization noise is paid exactly once, on
    // the link that needs it, and all leaders decode identical words.
    if (hier_wire_buf_.size() < count * 2) hier_wire_buf_.resize(count * 2);
    uint16_t* w = reinterpret_cast<uint16_t*>(hier_wire_buf_.data());
    const float* f = reinterpret_cast<const float*>(buf);
    for (size_t i = 0; i < count; i++) w[i] = f32_to_bf16(f[i]);
    char* wb = hier_wire_buf_.data();
    last_stripe_ns_.assign(eff_inter, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_inter, s);
      if (len == 0) return;
      rs_phase_stripe(inter_, s, wb + start * 2, len, 2, Dtype::kBF16,
                      ReduceOp::kSum, deadline);
    });
    *rs_tx = tier_tx(inter_) - tx0;
    last_stripe_ns_.assign(eff_inter, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_inter, s);
      if (len == 0) return;
      ag_phase_stripe(inter_, s, wb + start * 2, len, 2, deadline);
    });
    float* out = reinterpret_cast<float*>(buf);
    for (size_t i = 0; i < count; i++) out[i] = bf16_to_f32(w[i]);
  } else {
    last_stripe_ns_.assign(eff_inter, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_inter, s);
      if (len == 0) return;
      rs_phase_stripe(inter_, s, buf + start * esize, len, esize, dtype, op,
                      deadline);
    });
    *rs_tx = tier_tx(inter_) - tx0;
    last_stripe_ns_.assign(eff_inter, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_inter, s);
      if (len == 0) return;
      ag_phase_stripe(inter_, s, buf + start * esize, len, esize, deadline);
    });
  }
}

void HostCollectives::hier_schedule(char* bytes, size_t count, size_t esize,
                                    Dtype dtype, ReduceOp op, HierWire wire,
                                    int64_t eff_intra, int64_t eff_inter,
                                    int64_t deadline) {
  using clock = std::chrono::steady_clock;
  const bool host_leader = host_.world <= 1 || host_.rank == 0;
  const bool leader =
      host_leader && (intra_.world <= 1 || intra_.rank == 0);
  // The host tier partitions exactly like the intra one (full-width
  // bytes over the main stripe knob) — the two tiers hand the same
  // buckets to the same phase bodies.
  const int64_t eff_host = eff_intra;

  // Phase 0a/0b — host reduce-scatter + allgather over the shm rings
  // (or the loopback-TCP fallback): the HOST leader ends with the host
  // sum, at memcpy speed, before any socket is touched. Non-leaders
  // rejoin at the host broadcast.
  auto h0 = clock::now();
  if (host_.world > 1) {
    last_stripe_ns_.assign(eff_host, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_host, s);
      if (len == 0) return;
      rs_phase_stripe(host_, s, bytes + start * esize, len, esize, dtype,
                      op, deadline);
    });
  }
  auto h1 = clock::now();
  if (host_.world > 1) {
    last_stripe_ns_.assign(eff_host, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_host, s);
      if (len == 0) return;
      ag_phase_stripe(host_, s, bytes + start * esize, len, esize, deadline);
    });
  }
  auto h2 = clock::now();
  last_hier_.shm_rs_ns += ns_between(h0, h1);
  last_hier_.shm_ag_ns += ns_between(h1, h2);

  // Phase 1 — intra reduce-scatter: HOST-LEADER shards of the REGION
  // sum, on the fast links, spreading reduction bandwidth and compute.
  // (intra_.world is 0 on non-host-leaders — they skip straight to the
  // host broadcast below.)
  auto t0 = clock::now();
  if (intra_.world > 1) {
    last_stripe_ns_.assign(eff_intra, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_intra, s);
      if (len == 0) return;
      rs_phase_stripe(intra_, s, bytes + start * esize, len, esize, dtype,
                      op, deadline);
    });
  }
  // Phase 2 — intra allgather: delivers the full region sum to the LEADER
  // (on a ring, gather-to-one costs the same edges as gather-to-all).
  auto t1 = clock::now();
  if (intra_.world > 1) {
    last_stripe_ns_.assign(eff_intra, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_intra, s);
      if (len == 0) return;
      ag_phase_stripe(intra_, s, bytes + start * esize, len, esize, deadline);
    });
  }
  // Phase 3 — inter ring among leaders: the ONLY bytes on the slow links
  // ((L-1)/L of the wire payload per phase, measured into rs_tx/the
  // counter delta by the shared inter_ring_phase body).
  auto t2 = clock::now();
  const int64_t inter_tx0 = tier_tx(inter_);
  int64_t inter_rs_tx = 0;
  if (leader && inter_.world > 1)
    inter_ring_phase(wire, bytes, count, esize, dtype, op, eff_inter,
                     deadline, &inter_rs_tx);
  // Phase 4 — chunk-pipelined intra broadcast of the leader's result:
  // every member adopts the leader's bytes VERBATIM, and leaders are
  // bit-identical across regions (ring determinism), so the global
  // result is bit-identical on every member.
  auto t3 = clock::now();
  if (intra_.world > 1) {
    last_stripe_ns_.assign(eff_intra, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_intra, s);
      if (len == 0) return;
      bcast_pipe_stripe(intra_, s, bytes + start * esize, len * esize, 0,
                        deadline);
    });
  }
  auto t4 = clock::now();
  // Phase 5 — host broadcast of the host leader's (now-global) bytes:
  // every co-hosted member adopts them verbatim, completing the
  // bit-identity chain host member -> host leader -> region leader.
  if (host_.world > 1) {
    last_stripe_ns_.assign(eff_host, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(count, eff_host, s);
      if (len == 0) return;
      bcast_pipe_stripe(host_, s, bytes + start * esize, len * esize, 0,
                        deadline);
    });
  }
  auto h3 = clock::now();
  last_hier_.intra_rs_ns += ns_between(t0, t1);
  last_hier_.intra_ag_ns += ns_between(t1, t2);
  last_hier_.inter_ring_ns += ns_between(t2, t3);
  last_hier_.intra_bcast_ns += ns_between(t3, t4);
  last_hier_.shm_bcast_ns += ns_between(t4, h3);
  last_hier_.inter_rs_tx_bytes += inter_rs_tx;
  last_hier_.inter_ag_tx_bytes += tier_tx(inter_) - inter_tx0 - inter_rs_tx;
}

void HostCollectives::allreduce_hier(void* data, size_t count, Dtype dtype,
                                     ReduceOp op, HierWire wire,
                                     int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  if (aborted_) throw SocketError("collectives not configured");
  last_hier_ = HierStats{};
  last_hier_.wire = static_cast<int>(wire);
  if (world_size_ == 1) return;
  if (!hier_)
    throw SocketError(
        "hierarchical schedule unavailable: configure() saw neither a "
        "region map with >= 2 distinct labels nor a host map grouping "
        ">= 2 co-hosted ranks (the cohort rides the flat ring)");
  if (wire != HierWire::kNone &&
      (dtype != Dtype::kF32 || op != ReduceOp::kSum))
    throw SocketError("hier wire bf16/q8 takes f32 payloads and SUM only");
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    size_t esize = dtype_size(dtype);
    size_t inter_esize = wire == HierWire::kQ8 ? 1
                         : wire == HierWire::kBF16 ? 2
                                                   : esize;
    int64_t eff_intra = effective_stripes(count * esize, stripes_);
    int64_t eff_inter = effective_stripes(count * inter_esize, stripes_inter_);
    reset_tier_tx(intra_);
    reset_tier_tx(inter_);
    reset_tier_tx(host_);
    // Both effective stripe counts and the wire ride the header's op slot:
    // every member derives them from negotiated inputs, but a drifted knob
    // must error, not desync two tiers' schedules. The host tier shares
    // eff_intra by construction.
    uint32_t opword = static_cast<uint32_t>(op) |
                      (static_cast<uint32_t>(wire) << 4) |
                      (static_cast<uint32_t>(eff_intra) << 8) |
                      (static_cast<uint32_t>(eff_inter) << 16);
    if (host_.world > 1)
      check_op_header(host_, 9, count, static_cast<uint32_t>(dtype), opword,
                      deadline);
    if (intra_.world > 1)
      check_op_header(intra_, 9, count, static_cast<uint32_t>(dtype), opword,
                      deadline);
    const bool host_leader = host_.world <= 1 || host_.rank == 0;
    const bool leader =
        host_leader && (intra_.world <= 1 || intra_.rank == 0);
    if (leader && inter_.world > 1)
      check_op_header(inter_, 9, count, static_cast<uint32_t>(dtype), opword,
                      deadline);
    if (count == 0) return;
    last_hier_.payload_bytes = static_cast<int64_t>(count * esize);
    last_hier_.eff_intra = eff_intra;
    last_hier_.eff_inter = eff_inter;
    last_hier_.eff_host = host_.world > 1 ? eff_intra : 0;
    last_hier_.intra_world = intra_.world;
    last_hier_.inter_world = leader ? inter_.world : 0;
    last_hier_.host_world = host_.world;
    last_hier_.leader = leader;
    last_hier_.host_leader = host_leader;
    last_hier_.host_shm = host_.use_shm;
    hier_schedule(static_cast<char*>(data), count, esize, dtype, op, wire,
                  eff_intra, eff_inter, deadline);
    last_hier_.intra_tx_bytes = tier_tx(intra_);
    last_hier_.inter_tx_bytes = tier_tx(inter_);
    last_hier_.host_tx_bytes = tier_tx(host_);
    last_hier_.shm_bytes = tier_shm(host_);
  });
}

std::string HostCollectives::last_hier_json() const {
  JsonObject o;
  o["intra_rs_s"] = Json(last_hier_.intra_rs_ns / 1e9);
  o["intra_ag_s"] = Json(last_hier_.intra_ag_ns / 1e9);
  o["inter_ring_s"] = Json(last_hier_.inter_ring_ns / 1e9);
  o["intra_bcast_s"] = Json(last_hier_.intra_bcast_ns / 1e9);
  o["intra_tx_bytes"] = Json(last_hier_.intra_tx_bytes);
  o["inter_tx_bytes"] = Json(last_hier_.inter_tx_bytes);
  o["inter_rs_tx_bytes"] = Json(last_hier_.inter_rs_tx_bytes);
  o["inter_ag_tx_bytes"] = Json(last_hier_.inter_ag_tx_bytes);
  o["shm_rs_s"] = Json(last_hier_.shm_rs_ns / 1e9);
  o["shm_ag_s"] = Json(last_hier_.shm_ag_ns / 1e9);
  o["shm_bcast_s"] = Json(last_hier_.shm_bcast_ns / 1e9);
  o["host_tx_bytes"] = Json(last_hier_.host_tx_bytes);
  o["shm_bytes"] = Json(last_hier_.shm_bytes);
  o["payload_bytes"] = Json(last_hier_.payload_bytes);
  o["eff_intra"] = Json(last_hier_.eff_intra);
  o["eff_inter"] = Json(last_hier_.eff_inter);
  o["eff_host"] = Json(last_hier_.eff_host);
  o["intra_world"] = Json(last_hier_.intra_world);
  o["inter_world"] = Json(last_hier_.inter_world);
  o["host_world"] = Json(last_hier_.host_world);
  o["leader"] = Json(last_hier_.leader);
  o["host_leader"] = Json(last_hier_.host_leader);
  o["host_shm"] = Json(last_hier_.host_shm);
  o["wire"] = Json(static_cast<int64_t>(last_hier_.wire));
  return Json(std::move(o)).dump();
}

// ---- persistent comm plans ----

namespace {

// Python-floor integer division (numpy's // semantics): C++ / truncates
// toward zero, which would disagree with the legacy host path on
// negative sums.
template <typename T>
T floor_div(T a, T d) {
  T q = a / d;
  if ((a % d != 0) && ((a < 0) != (d < 0))) q--;
  return q;
}

}  // namespace

int64_t HostCollectives::plan_build(const int64_t* counts,
                                    const int32_t* dtypes, int64_t n_leaves,
                                    PlanWire wire, bool prepacked, bool hier) {
  if (world_size_ <= 0)
    throw SocketError("plan_build before configure (layout needs the ring)");
  if (n_leaves <= 0) throw SocketError("plan_build of an empty signature");
  if (hier && prepacked)
    throw SocketError(
        "hier plans take no pre-packed leaves (the wire encoding happens at "
        "the leader's inter hop, not at pack)");
  auto p = std::make_unique<CommPlan>();
  p->wire = wire;
  p->prepacked = prepacked;
  p->hier = hier;
  p->leaves.resize(n_leaves);
  // FNV-1a over (wire, geometry, signature): exchanged in the execute
  // header so mismatched plans error instead of desyncing the ring.
  uint64_t h = 1469598103934665603ull;
  auto mix = [&h](uint64_t v) {
    for (int i = 0; i < 8; i++) {
      h ^= (v >> (8 * i)) & 0xff;
      h *= 1099511628211ull;
    }
  };
  mix(static_cast<uint64_t>(wire));
  mix(static_cast<uint64_t>(world_size_));
  mix(static_cast<uint64_t>(stripes_));
  if (hier) {
    // Hier plans bake in the hierarchical geometry as well: a hier plan
    // meeting a flat plan — or one built against a different inter
    // stripe knob or a drifted (region, host) topology map — must error
    // at the header, not desync mid-payload.
    mix(0x48494552ull /*"HIER"*/);
    mix(static_cast<uint64_t>(stripes_inter_));
    mix(topo_hash_);
  }
  const bool q8 = wire == PlanWire::kQ8 || wire == PlanWire::kQ8EF;
  for (int64_t i = 0; i < n_leaves; i++) {
    if (counts[i] < 0) throw SocketError("plan_build: negative leaf count");
    Dtype dt = static_cast<Dtype>(dtypes[i]);
    dtype_size(dt);  // validates the code
    p->leaves[i] = {static_cast<size_t>(counts[i]), dt};
    mix(static_cast<uint64_t>(counts[i]));
    mix(static_cast<uint64_t>(dtypes[i]));
    Dtype gdt;
    if (q8) {
      if (dt != Dtype::kF32 && dt != Dtype::kBF16)
        throw SocketError(
            "comm plan: q8 wires take f32/bf16 leaves only (callers fall "
            "back to the legacy path for other dtypes)");
      gdt = Dtype::kF32;
    } else if (wire == PlanWire::kBF16) {
      // Hier: the wire applies at the INTER hop only — staging (and the
      // intra ring) stays full-width native, the leader casts for the
      // slow link. Flat: the whole ring rides the bf16 group.
      gdt = (!hier && dt == Dtype::kF32) ? Dtype::kBF16 : dt;
    } else {
      gdt = dt;
    }
    // First-appearance group order — the legacy host path's dict order.
    CommPlan::Group* g = nullptr;
    for (auto& cand : p->groups)
      if (cand.dtype == gdt) { g = &cand; break; }
    if (g == nullptr) {
      p->groups.emplace_back();
      g = &p->groups.back();
      g->dtype = gdt;
    }
    g->leaf_idx.push_back(i);
    g->leaf_off.push_back(g->count);
    g->count += static_cast<size_t>(counts[i]);
  }
  size_t total_f32 = 0;
  for (auto& g : p->groups) {
    size_t esize = dtype_size(g.dtype);
    // The stripe partition IS the plan's bucket list, derived exactly
    // like the fused op derives it (q8 wires: ~1 byte/element) so the
    // ring arithmetic — chunk boundaries, q8 scales — matches the
    // legacy single-op path bit for bit. Hier plans partition by the
    // INTRA tier's full-width bytes (the intra ring is what streams per
    // bucket; the inter hop re-stripes per phase at execute).
    g.eff = effective_stripes(
        g.count * (q8 && !hier ? 1 : esize), stripes_);
    g.staging.resize(g.count * esize);
    total_f32 += g.count;
  }
  // Prepacked kQ8EF: the error-feedback carry lives device-side in the
  // packer (that is the point — the full-f32 residual never crosses the
  // device link), so the plan allocates none.
  if (wire == PlanWire::kQ8EF && !prepacked) p->residual.assign(total_f32, 0.f);
  // NOTE: `prepacked` is NOT mixed into the hash — pack placement is a
  // local choice, and a device-packing member must interoperate with a
  // host-packing one (the device kernels mirror the native arithmetic
  // bit for bit; tests/test_device_pack.py pins the mixed-ring case).
  p->sig = h;
  MutexLock lock(plan_mu_);
  plans_[next_plan_id_] = std::move(p);
  return next_plan_id_++;
}

int64_t HostCollectives::plan_build_sharded(const int64_t* counts,
                                            const int32_t* dtypes,
                                            int64_t n_leaves, PlanWire rs_wire,
                                            PlanWire ag_wire) {
  if (world_size_ <= 0)
    throw SocketError("plan_build before configure (layout needs the ring)");
  if (n_leaves <= 0) throw SocketError("plan_build of an empty signature");
  if (rs_wire == PlanWire::kQ8EF)
    throw SocketError(
        "sharded plans take no q8ef grad wire (error feedback corrects a "
        "FUSED lossy result; the shard owner keeps full f32 here, so there "
        "is no owner-side loss to feed back)");
  if (ag_wire != PlanWire::kNative && ag_wire != PlanWire::kBF16)
    throw SocketError(
        "sharded plans allgather params at native or bf16 wires only (a "
        "quantized param broadcast would drift the cohort's weights)");
  auto p = std::make_unique<CommPlan>();
  p->wire = rs_wire;
  p->ag_wire = ag_wire;
  p->sharded = true;
  p->leaves.resize(n_leaves);
  uint64_t h = 1469598103934665603ull;
  auto mix = [&h](uint64_t v) {
    for (int i = 0; i < 8; i++) {
      h ^= (v >> (8 * i)) & 0xff;
      h *= 1099511628211ull;
    }
  };
  mix(static_cast<uint64_t>(rs_wire));
  mix(static_cast<uint64_t>(world_size_));
  mix(static_cast<uint64_t>(stripes_));
  // The sharded schedule (and its second wire) is part of the contract a
  // peer must share: a sharded plan meeting a fused plan of the same
  // signature — or one gathering at a different param wire — must error
  // at the header, not desync.
  mix(0x53485244ull /*"SHRD"*/);
  mix(static_cast<uint64_t>(ag_wire));
  p->groups.emplace_back();
  CommPlan::Group& g = p->groups.back();
  g.dtype = Dtype::kF32;
  for (int64_t i = 0; i < n_leaves; i++) {
    if (counts[i] < 0) throw SocketError("plan_build: negative leaf count");
    if (static_cast<Dtype>(dtypes[i]) != Dtype::kF32)
      throw SocketError(
          "sharded plans take f32 leaves only (the shard layout is one flat "
          "f32 group; callers keep f32 master weights or use a fused plan)");
    p->leaves[i] = {static_cast<size_t>(counts[i]), Dtype::kF32};
    mix(static_cast<uint64_t>(counts[i]));
    mix(static_cast<uint64_t>(dtypes[i]));
    g.leaf_idx.push_back(i);
    g.leaf_off.push_back(g.count);
    g.count += static_cast<size_t>(counts[i]);
  }
  // The stripe partition derives from the GRAD leg's wire bytes (the
  // fused op's own rule: q8 ~1 byte, bf16 2, f32 4 per element) and is
  // shared by both legs — shard boundaries must be one arithmetic fact.
  const size_t rs_esize = rs_wire == PlanWire::kQ8     ? 1
                          : rs_wire == PlanWire::kBF16 ? 2
                                                       : 4;
  g.eff = effective_stripes(g.count * rs_esize, stripes_);
  g.staging.resize(g.count * sizeof(float));
  if (rs_wire == PlanWire::kBF16 || ag_wire == PlanWire::kBF16)
    p->wirebuf.resize(g.count * 2);
  p->sig = h;
  MutexLock lock(plan_mu_);
  plans_[next_plan_id_] = std::move(p);
  return next_plan_id_++;
}

void HostCollectives::plan_sharded_meta(int64_t plan_id, int64_t* out) {
  MutexLock op_lock(op_mu_);
  CommPlan& p = plan_get(plan_id);
  if (!p.sharded)
    throw SocketError("plan_sharded_meta on a non-sharded plan");
  const CommPlan::Group& g = p.groups[0];
  size_t shard_count = 0;
  for (auto [start, len] :
       shard_ranges(g.count, sizeof(float), rank_, g.eff))
    shard_count += len;
  out[0] = static_cast<int64_t>(shard_count);
  out[1] = g.eff;
  out[2] = static_cast<int64_t>(g.count);
}

CommPlan& HostCollectives::plan_get(int64_t plan_id) {
  MutexLock lock(plan_mu_);
  auto it = plans_.find(plan_id);
  if (it == plans_.end())
    throw SocketError(
        "unknown or invalidated comm plan (plans do not survive "
        "reconfigure; rebuild after every quorum change)");
  return *it->second;
}

void HostCollectives::plan_free(int64_t plan_id) {
  MutexLock op_lock(op_mu_);  // no execute in flight
  MutexLock lock(plan_mu_);
  plans_.erase(plan_id);
}

void HostCollectives::plan_reset_feedback(int64_t plan_id) {
  MutexLock op_lock(op_mu_);
  CommPlan& p = plan_get(plan_id);
  std::fill(p.residual.begin(), p.residual.end(), 0.f);
}

std::string HostCollectives::plan_stats_json(int64_t plan_id) {
  MutexLock op_lock(op_mu_);
  CommPlan& p = plan_get(plan_id);
  JsonObject out;
  out["execs"] = Json(p.execs);
  out["wire"] = Json(static_cast<int64_t>(p.wire));
  out["prepacked"] = Json(static_cast<int64_t>(p.prepacked ? 1 : 0));
  out["hier"] = Json(static_cast<int64_t>(p.hier ? 1 : 0));
  JsonArray buckets;
  for (const auto& st : p.stats) {
    JsonObject b;
    b["group"] = Json(st.group);
    b["stripe"] = Json(st.stripe);
    b["leg"] = Json(st.leg);
    b["bytes"] = Json(st.bytes);
    b["pack_s"] = Json(st.pack_ns / 1e9);
    b["ring_s"] = Json(st.ring_ns / 1e9);
    b["unpack_s"] = Json(st.unpack_ns / 1e9);
    buckets.push_back(Json(std::move(b)));
  }
  out["buckets"] = Json(std::move(buckets));
  return Json(std::move(out)).dump();
}

void HostCollectives::plan_pack_range(CommPlan& p, CommPlan::Group& g,
                                      const void* const* leaf_in,
                                      size_t start, size_t len) const {
  size_t end = start + len;
  size_t gesize = dtype_size(g.dtype);
  for (size_t k = 0; k < g.leaf_idx.size(); k++) {
    int64_t li = g.leaf_idx[k];
    const CommPlan::Leaf& leaf = p.leaves[li];
    size_t off = g.leaf_off[k];
    size_t lend = off + leaf.count;
    if (lend <= start || off >= end) continue;
    size_t a = std::max(off, start);
    size_t b = std::min(lend, end);
    size_t n = b - a;
    const char* src = static_cast<const char*>(leaf_in[li]) +
                      (a - off) * dtype_size(leaf.dtype);
    char* dst = g.staging.data() + a * gesize;
    if (leaf.dtype == g.dtype) {
      memcpy(dst, src, n * gesize);
    } else if (leaf.dtype == Dtype::kF32 && g.dtype == Dtype::kBF16) {
      const float* s = reinterpret_cast<const float*>(src);
      uint16_t* d = reinterpret_cast<uint16_t*>(dst);
      for (size_t i = 0; i < n; i++) d[i] = f32_to_bf16(s[i]);
    } else if (leaf.dtype == Dtype::kBF16 && g.dtype == Dtype::kF32) {
      const uint16_t* s = reinterpret_cast<const uint16_t*>(src);
      float* d = reinterpret_cast<float*>(dst);
      for (size_t i = 0; i < n; i++) d[i] = bf16_to_f32(s[i]);
    } else {
      throw SocketError("comm plan: unsupported pack cast");
    }
  }
}

void HostCollectives::plan_unpack_range(const CommPlan& p,
                                        const CommPlan::Group& g,
                                        void* const* leaf_out, size_t start,
                                        size_t len, double divisor,
                                        bool has_divisor) const {
  size_t end = start + len;
  size_t gesize = dtype_size(g.dtype);
  // Divisor semantics mirror the legacy host path exactly: f32 groups
  // divide in f32 (numpy 2's in-place weak-scalar rule), f64 in f64,
  // bf16 via f32 with round-to-nearest-even back (_apply_divisor), ints
  // floor-divide.
  const float div32 = static_cast<float>(divisor);
  for (size_t k = 0; k < g.leaf_idx.size(); k++) {
    int64_t li = g.leaf_idx[k];
    const CommPlan::Leaf& leaf = p.leaves[li];
    size_t off = g.leaf_off[k];
    size_t lend = off + leaf.count;
    if (lend <= start || off >= end) continue;
    size_t a = std::max(off, start);
    size_t b = std::min(lend, end);
    size_t n = b - a;
    const char* src = g.staging.data() + a * gesize;
    char* dst = static_cast<char*>(leaf_out[li]) +
                (a - off) * dtype_size(leaf.dtype);
    switch (g.dtype) {
      case Dtype::kF32: {
        const float* s = reinterpret_cast<const float*>(src);
        if (leaf.dtype == Dtype::kF32) {
          float* d = reinterpret_cast<float*>(dst);
          for (size_t i = 0; i < n; i++)
            d[i] = has_divisor ? s[i] / div32 : s[i];
        } else if (leaf.dtype == Dtype::kBF16) {
          uint16_t* d = reinterpret_cast<uint16_t*>(dst);
          for (size_t i = 0; i < n; i++)
            d[i] = f32_to_bf16(has_divisor ? s[i] / div32 : s[i]);
        } else {
          throw SocketError("comm plan: unsupported unpack cast");
        }
        break;
      }
      case Dtype::kBF16: {
        const uint16_t* s = reinterpret_cast<const uint16_t*>(src);
        if (leaf.dtype == Dtype::kBF16 || leaf.dtype == Dtype::kF32) {
          for (size_t i = 0; i < n; i++) {
            uint16_t w = s[i];
            if (has_divisor) w = f32_to_bf16(bf16_to_f32(w) / div32);
            if (leaf.dtype == Dtype::kBF16)
              reinterpret_cast<uint16_t*>(dst)[i] = w;
            else
              reinterpret_cast<float*>(dst)[i] = bf16_to_f32(w);
          }
        } else {
          throw SocketError("comm plan: unsupported unpack cast");
        }
        break;
      }
      case Dtype::kF64: {
        const double* s = reinterpret_cast<const double*>(src);
        double* d = reinterpret_cast<double*>(dst);
        for (size_t i = 0; i < n; i++)
          d[i] = has_divisor ? s[i] / divisor : s[i];
        break;
      }
      case Dtype::kI32: {
        const int32_t* s = reinterpret_cast<const int32_t*>(src);
        int32_t* d = reinterpret_cast<int32_t*>(dst);
        int32_t dv = static_cast<int32_t>(divisor);
        for (size_t i = 0; i < n; i++)
          d[i] = has_divisor ? floor_div(s[i], dv) : s[i];
        break;
      }
      case Dtype::kI64: {
        const int64_t* s = reinterpret_cast<const int64_t*>(src);
        int64_t* d = reinterpret_cast<int64_t*>(dst);
        int64_t dv = static_cast<int64_t>(divisor);
        for (size_t i = 0; i < n; i++)
          d[i] = has_divisor ? floor_div(s[i], dv) : s[i];
        break;
      }
    }
  }
}

void HostCollectives::plan_pack_ef(CommPlan& p, CommPlan::Group& g,
                                   const void* const* leaf_in) const {
  // The native mirror of quantize.quantize_with_feedback, leaf by leaf:
  // the per-leaf absmax spans stripe boundaries, so EF packs the whole
  // group before the striped ring starts (the only plan phase that
  // cannot stream per bucket). Arithmetic matches the jitted original
  // op for op: f32 adds, absmax/127 in f32 floored at 1e-12,
  // round-to-nearest-even, clip to [-127, 127], dq = q * scale,
  // residual = d - dq.
  float* stg = reinterpret_cast<float*>(g.staging.data());
  for (size_t k = 0; k < g.leaf_idx.size(); k++) {
    int64_t li = g.leaf_idx[k];
    const CommPlan::Leaf& leaf = p.leaves[li];
    size_t off = g.leaf_off[k];
    size_t n = leaf.count;
    float* d = stg + off;
    float* res = p.residual.data() + off;
    if (leaf.dtype == Dtype::kF32) {
      const float* s = static_cast<const float*>(leaf_in[li]);
      for (size_t i = 0; i < n; i++) d[i] = s[i] + res[i];
    } else {  // kBF16, enforced at build
      const uint16_t* s = static_cast<const uint16_t*>(leaf_in[li]);
      for (size_t i = 0; i < n; i++) d[i] = bf16_to_f32(s[i]) + res[i];
    }
    float absmax = 0.f;
    bool finite = true;
    for (size_t i = 0; i < n; i++) {
      float a = std::fabs(d[i]);
      if (!std::isfinite(a)) finite = false;
      absmax = std::max(absmax, a);
    }
    if (!finite) {
      // A diverged leaf poisons its own payload AND its carry — the
      // same NaN propagation the jitted path produces — and the q8
      // wire's NaN-scale encode then poisons every member.
      float nan = std::numeric_limits<float>::quiet_NaN();
      for (size_t i = 0; i < n; i++) {
        res[i] = nan;
        d[i] = nan;
      }
      continue;
    }
    float scale = std::max(absmax / 127.0f, 1e-12f);
    for (size_t i = 0; i < n; i++) {
      float q = std::nearbyint(d[i] / scale);
      q = std::max(-127.f, std::min(127.f, q));
      float dq = q * scale;
      res[i] = d[i] - dq;
      d[i] = dq;
    }
  }
}

void HostCollectives::plan_ef_inplace(CommPlan& p, CommPlan::Group& g) const {
  // The hier kQ8EF step: identical arithmetic to plan_pack_ef, applied to
  // the REGION SUM already sitting in staging (d = staging + residual).
  // Runs at the LEADER only, just before the quantized inter hop — the
  // carry refines this region's contribution window over window, and the
  // expensive residual never rides the fast intra links at all.
  float* stg = reinterpret_cast<float*>(g.staging.data());
  for (size_t k = 0; k < g.leaf_idx.size(); k++) {
    size_t off = g.leaf_off[k];
    size_t n = p.leaves[g.leaf_idx[k]].count;
    float* d = stg + off;
    float* res = p.residual.data() + off;
    for (size_t i = 0; i < n; i++) d[i] = d[i] + res[i];
    float absmax = 0.f;
    bool finite = true;
    for (size_t i = 0; i < n; i++) {
      float a = std::fabs(d[i]);
      if (!std::isfinite(a)) finite = false;
      absmax = std::max(absmax, a);
    }
    if (!finite) {
      float nan = std::numeric_limits<float>::quiet_NaN();
      for (size_t i = 0; i < n; i++) {
        res[i] = nan;
        d[i] = nan;
      }
      continue;
    }
    float scale = std::max(absmax / 127.0f, 1e-12f);
    for (size_t i = 0; i < n; i++) {
      float q = std::nearbyint(d[i] / scale);
      q = std::max(-127.f, std::min(127.f, q));
      float dq = q * scale;
      res[i] = d[i] - dq;
      d[i] = dq;
    }
  }
}

void HostCollectives::plan_pack_pre_range(const CommPlan& p,
                                          CommPlan::Group& g,
                                          const void* group_in,
                                          const void* group_aux, size_t start,
                                          size_t len) const {
  size_t gesize = dtype_size(g.dtype);
  const bool q8 = p.wire == PlanWire::kQ8 || p.wire == PlanWire::kQ8EF;
  if (!q8) {
    // The payload already IS the staging encoding (bf16/native words,
    // cast on device): a straight copy into the ring's in-place buffer.
    memcpy(g.staging.data() + start * gesize,
           static_cast<const char*>(group_in) + start * gesize, len * gesize);
    return;
  }
  // q8 wires: int8 codes + one f32 scale per leaf. dq = q * scale is the
  // exact product the host EF writes into staging (same q, same scale —
  // the device kernel's tested contract), so the ring sees identical
  // bits. A NaN scale (the device kernel's non-finite signal) poisons
  // every element of its leaf: 0 * NaN = NaN, the host EF's whole-leaf
  // propagation.
  if (group_aux == nullptr)
    throw SocketError("prepacked q8 plan: missing per-leaf scale sidecar");
  const int8_t* q = static_cast<const int8_t*>(group_in);
  const float* scales = static_cast<const float*>(group_aux);
  float* stg = reinterpret_cast<float*>(g.staging.data());
  size_t end = start + len;
  for (size_t k = 0; k < g.leaf_idx.size(); k++) {
    const CommPlan::Leaf& leaf = p.leaves[g.leaf_idx[k]];
    size_t off = g.leaf_off[k];
    size_t lend = off + leaf.count;
    if (lend <= start || off >= end) continue;
    size_t a = std::max(off, start);
    size_t b = std::min(lend, end);
    float scale = scales[k];
    for (size_t i = a; i < b; i++)
      stg[i] = static_cast<float>(q[i]) * scale;
  }
}

void HostCollectives::plan_execute_pre(int64_t plan_id,
                                       const void* const* group_in,
                                       const void* const* group_aux,
                                       void* const* leaf_out, double divisor,
                                       bool has_divisor, int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  CommPlan& p = plan_get(plan_id);
  if (!p.prepacked)
    throw SocketError(
        "plan_execute_pre on a plan built without prepacked leaves");
  p.stats.clear();
  const bool q8 = p.wire == PlanWire::kQ8 || p.wire == PlanWire::kQ8EF;
  if (world_size_ == 1) {
    for (size_t gi = 0; gi < p.groups.size(); gi++) {
      CommPlan::Group& g = p.groups[gi];
      plan_pack_pre_range(p, g, group_in[gi], group_aux[gi], 0, g.count);
      plan_unpack_range(p, g, leaf_out, 0, g.count, divisor, has_divisor);
    }
    p.execs++;
    return;
  }
  if (aborted_) throw SocketError("collectives not configured");
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // Same header as the host-pack execute (the hash excludes
    // `prepacked`): a device-packing member and a host-packing member of
    // one ring agree here and produce identical staging.
    check_op_header(flat_, 8, p.sig, static_cast<uint32_t>(p.wire), 0,
                    deadline);
    for (size_t gi = 0; gi < p.groups.size(); gi++) {
      CommPlan::Group& g = p.groups[gi];
      if (g.count == 0) continue;
      size_t esize = dtype_size(g.dtype);
      size_t stat_base = p.stats.size();
      p.stats.resize(stat_base + g.eff);
      last_stripe_ns_.assign(g.eff, 0);
      // Unlike the host EF (whole-group absmax before any stripe may
      // start), the prepacked decode is per-element and streams per
      // bucket — the triple pipeline covers the q8 wires too.
      run_striped([&](int64_t s) {
        auto [start, len] = stripe_range(g.count, g.eff, s);
        CommPlan::BucketStat& st = p.stats[stat_base + s];
        st.group = static_cast<int64_t>(gi);
        st.stripe = s;
        st.bytes = static_cast<int64_t>(len * esize);
        if (len == 0) return;
        auto t0 = std::chrono::steady_clock::now();
        plan_pack_pre_range(p, g, group_in[gi], group_aux[gi], start, len);
        auto t1 = std::chrono::steady_clock::now();
        if (q8) {
          allreduce_q8_stripe(
              flat_, s, reinterpret_cast<float*>(g.staging.data()) + start,
              len, deadline);
        } else {
          allreduce_stripe(flat_, s, g.staging.data() + start * esize, len,
                           esize, g.dtype, ReduceOp::kSum, deadline);
        }
        auto t2 = std::chrono::steady_clock::now();
        plan_unpack_range(p, g, leaf_out, start, len, divisor, has_divisor);
        auto t3 = std::chrono::steady_clock::now();
        st.pack_ns = ns_between(t0, t1);
        st.ring_ns = ns_between(t1, t2);
        st.unpack_ns = ns_between(t2, t3);
      });
    }
  });
  p.execs++;
}

void HostCollectives::plan_execute_hier_group(CommPlan& p, size_t gi,
                                              const void* const* leaf_in,
                                              void* const* leaf_out,
                                              double divisor, bool has_divisor,
                                              int64_t deadline) {
  CommPlan::Group& g = p.groups[gi];
  if (g.count == 0) return;
  size_t esize = dtype_size(g.dtype);
  const bool q8 = p.wire == PlanWire::kQ8 || p.wire == PlanWire::kQ8EF;
  // The plan wire applies at the inter hop, and only where it means
  // something: q8 plans have a single f32 group; a bf16 plan's non-f32
  // groups (ints, f64, native bf16) ride the inter ring at native width.
  HierWire wire = HierWire::kNone;
  if (g.dtype == Dtype::kF32) {
    if (q8) wire = HierWire::kQ8;
    else if (p.wire == PlanWire::kBF16) wire = HierWire::kBF16;
  }
  const int64_t eff_intra = g.eff;
  const size_t inter_esize = wire == HierWire::kQ8 ? 1
                             : wire == HierWire::kBF16 ? 2
                                                       : esize;
  const int64_t eff_inter =
      effective_stripes(g.count * inter_esize, stripes_inter_);
  const bool host_leader = host_.world <= 1 || host_.rank == 0;
  const bool leader =
      host_leader && (intra_.world <= 1 || intra_.rank == 0);
  char* stg = g.staging.data();

  size_t stat_base = p.stats.size();
  p.stats.resize(stat_base + eff_intra);
  for (int64_t s = 0; s < eff_intra; s++) {
    auto [start, len] = stripe_range(g.count, eff_intra, s);
    p.stats[stat_base + s].group = static_cast<int64_t>(gi);
    p.stats[stat_base + s].stripe = s;
    p.stats[stat_base + s].bytes = static_cast<int64_t>(len * esize);
  }

  using clock = std::chrono::steady_clock;
  auto h0 = clock::now();
  // Phase 0 — pack fused into the HOST reduce-scatter when the host tier
  // exists (bucket i+1 packs while bucket i rides its shm ring), then
  // the host allgather: the host leader ends with the host sum without
  // a socket in sight. With no host tier the pack fuses into the intra
  // reduce-scatter exactly as before.
  const bool host_active = host_.world > 1;
  if (host_active) {
    last_stripe_ns_.assign(eff_intra, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(g.count, eff_intra, s);
      if (len == 0) return;
      auto p0 = clock::now();
      plan_pack_range(p, g, leaf_in, start, len);
      auto p1 = clock::now();
      rs_phase_stripe(host_, s, stg + start * esize, len, esize, g.dtype,
                      ReduceOp::kSum, deadline);
      auto p2 = clock::now();
      CommPlan::BucketStat& st = p.stats[stat_base + s];
      st.pack_ns = ns_between(p0, p1);
      st.ring_ns += ns_between(p1, p2);
    });
  }
  auto h1 = clock::now();
  if (host_active) {
    last_stripe_ns_.assign(eff_intra, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(g.count, eff_intra, s);
      if (len == 0) return;
      auto p0 = clock::now();
      ag_phase_stripe(host_, s, stg + start * esize, len, esize, deadline);
      p.stats[stat_base + s].ring_ns += ns_between(p0, clock::now());
    });
  }
  auto h2 = clock::now();
  last_hier_.shm_rs_ns += ns_between(h0, h1);
  last_hier_.shm_ag_ns += ns_between(h1, h2);

  auto t0 = clock::now();
  // Phase 1 — pack fused into the intra reduce-scatter, per stripe bucket
  // (bucket i+1 packs while bucket i rides its intra connection: the
  // triple pipeline survives the extra tier). Under an active host tier
  // the payload is already packed and host-summed; intra_.world is 0 on
  // non-host-leaders, so only host leaders run these phases.
  if (intra_.world > 1) {
    last_stripe_ns_.assign(eff_intra, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(g.count, eff_intra, s);
      if (len == 0) return;
      auto p0 = clock::now();
      if (!host_active) plan_pack_range(p, g, leaf_in, start, len);
      auto p1 = clock::now();
      rs_phase_stripe(intra_, s, stg + start * esize, len, esize, g.dtype,
                      ReduceOp::kSum, deadline);
      auto p2 = clock::now();
      CommPlan::BucketStat& st = p.stats[stat_base + s];
      st.pack_ns += ns_between(p0, p1);
      st.ring_ns += ns_between(p1, p2);
    });
  } else if (!host_active) {
    plan_pack_range(p, g, leaf_in, 0, g.count);
  }
  auto t1 = clock::now();
  // Phase 2 — intra allgather: the leader ends with the full region sum.
  if (intra_.world > 1) {
    last_stripe_ns_.assign(eff_intra, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(g.count, eff_intra, s);
      if (len == 0) return;
      auto p0 = clock::now();
      ag_phase_stripe(intra_, s, stg + start * esize, len, esize, deadline);
      p.stats[stat_base + s].ring_ns += ns_between(p0, clock::now());
    });
  }
  auto t2 = clock::now();
  const int64_t inter_tx0 = tier_tx(inter_);
  int64_t inter_rs_tx = 0;
  // Phase 3 — the leader's inter hop at the plan wire. kQ8EF first runs
  // the per-leaf error-feedback quantization against the plan's residual
  // — on the REGION SUM, at the leader, so the carry refines this
  // region's contribution and quantization noise is paid exactly once.
  if (leader && inter_.world > 1) {
    if (p.wire == PlanWire::kQ8EF && wire == HierWire::kQ8)
      plan_ef_inplace(p, g);
    // The SAME inter-ring body the bulk op runs — a wire or accounting
    // change can never desync the plan path from allreduce_hier.
    inter_ring_phase(wire, stg, g.count, esize, g.dtype, ReduceOp::kSum,
                     eff_inter, deadline, &inter_rs_tx);
  }
  auto t3 = clock::now();
  // Phase 4 — broadcast the leader's result down the tiers. With a host
  // tier the unpack fuses into the HOST broadcast (the last phase every
  // member runs); otherwise into the intra broadcast as before.
  if (intra_.world > 1) {
    last_stripe_ns_.assign(eff_intra, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(g.count, eff_intra, s);
      if (len == 0) return;
      auto p0 = clock::now();
      bcast_pipe_stripe(intra_, s, stg + start * esize, len * esize, 0,
                        deadline);
      auto p1 = clock::now();
      if (!host_active)
        plan_unpack_range(p, g, leaf_out, start, len, divisor, has_divisor);
      auto p2 = clock::now();
      CommPlan::BucketStat& st = p.stats[stat_base + s];
      st.ring_ns += ns_between(p0, p1);
      st.unpack_ns += ns_between(p1, p2);
    });
  } else if (!host_active) {
    plan_unpack_range(p, g, leaf_out, 0, g.count, divisor, has_divisor);
  }
  auto t4 = clock::now();
  if (host_active) {
    last_stripe_ns_.assign(eff_intra, 0);
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(g.count, eff_intra, s);
      if (len == 0) return;
      auto p0 = clock::now();
      bcast_pipe_stripe(host_, s, stg + start * esize, len * esize, 0,
                        deadline);
      auto p1 = clock::now();
      plan_unpack_range(p, g, leaf_out, start, len, divisor, has_divisor);
      auto p2 = clock::now();
      CommPlan::BucketStat& st = p.stats[stat_base + s];
      st.ring_ns += ns_between(p0, p1);
      st.unpack_ns += ns_between(p1, p2);
    });
  }
  auto h3 = clock::now();
  last_hier_.intra_rs_ns += ns_between(t0, t1);
  last_hier_.intra_ag_ns += ns_between(t1, t2);
  last_hier_.inter_ring_ns += ns_between(t2, t3);
  last_hier_.intra_bcast_ns += ns_between(t3, t4);
  last_hier_.shm_bcast_ns += ns_between(t4, h3);
  last_hier_.inter_rs_tx_bytes += inter_rs_tx;
  last_hier_.inter_ag_tx_bytes += tier_tx(inter_) - inter_tx0 - inter_rs_tx;
  last_hier_.payload_bytes += static_cast<int64_t>(g.count * esize);
  last_hier_.eff_intra = eff_intra;
  last_hier_.eff_inter = eff_inter;
  last_hier_.eff_host = host_active ? eff_intra : 0;
}

void HostCollectives::plan_execute(int64_t plan_id,
                                   const void* const* leaf_in,
                                   void* const* leaf_out, double divisor,
                                   bool has_divisor, int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  CommPlan& p = plan_get(plan_id);
  if (p.prepacked)
    throw SocketError(
        "plan_execute on a prepacked plan (use plan_execute_pre)");
  p.stats.clear();
  const bool q8 = p.wire == PlanWire::kQ8 || p.wire == PlanWire::kQ8EF;
  if (world_size_ == 1) {
    // Solo: pack -> identity -> unpack. Flat kQ8EF advances the
    // error-feedback state exactly as it would in a ring (a member that
    // later joins a cohort carries coherent state); a HIER plan's EF
    // belongs to the inter hop, which does not exist solo, so the carry
    // stays untouched (the wire only ever applies on the slow link).
    for (auto& g : p.groups) {
      if (p.wire == PlanWire::kQ8EF && !p.hier)
        plan_pack_ef(p, g, leaf_in);
      else
        plan_pack_range(p, g, leaf_in, 0, g.count);
      plan_unpack_range(p, g, leaf_out, 0, g.count, divisor, has_divisor);
    }
    p.execs++;
    return;
  }
  if (aborted_) throw SocketError("collectives not configured");
  if (p.hier) {
    if (!hier_)
      throw SocketError(
          "hier plan on a flat ring: configure() was not given a region map "
          "with >= 2 distinct labels");
    run_op([&] {
      int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
      last_hier_ = HierStats{};
      last_hier_.wire = static_cast<int>(
          p.wire == PlanWire::kBF16 ? HierWire::kBF16
          : q8 ? HierWire::kQ8
               : HierWire::kNone);
      reset_tier_tx(intra_);
      reset_tier_tx(inter_);
      reset_tier_tx(host_);
      const bool host_leader = host_.world <= 1 || host_.rank == 0;
      const bool leader =
          host_leader && (intra_.world <= 1 || intra_.rank == 0);
      // kind 10 = hier plan: a hier plan meeting a flat plan (kind 8) or
      // a bulk hier op (kind 9) must error at the header.
      if (host_.world > 1)
        check_op_header(host_, 10, p.sig, static_cast<uint32_t>(p.wire), 0,
                        deadline);
      if (intra_.world > 1)
        check_op_header(intra_, 10, p.sig, static_cast<uint32_t>(p.wire), 0,
                        deadline);
      if (leader && inter_.world > 1)
        check_op_header(inter_, 10, p.sig, static_cast<uint32_t>(p.wire), 0,
                        deadline);
      last_hier_.intra_world = intra_.world;
      last_hier_.inter_world = leader ? inter_.world : 0;
      last_hier_.host_world = host_.world;
      last_hier_.leader = leader;
      last_hier_.host_leader = host_leader;
      last_hier_.host_shm = host_.use_shm;
      for (size_t gi = 0; gi < p.groups.size(); gi++)
        plan_execute_hier_group(p, gi, leaf_in, leaf_out, divisor,
                                has_divisor, deadline);
      last_hier_.intra_tx_bytes = tier_tx(intra_);
      last_hier_.inter_tx_bytes = tier_tx(inter_);
      last_hier_.host_tx_bytes = tier_tx(host_);
      last_hier_.shm_bytes = tier_shm(host_);
    });
    p.execs++;
    return;
  }
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // The signature hash covers (wire, geometry, leaf counts, dtypes):
    // two members executing different plans error here instead of
    // deadlocking mid-payload.
    check_op_header(flat_, 8, p.sig, static_cast<uint32_t>(p.wire), 0,
                    deadline);
    for (size_t gi = 0; gi < p.groups.size(); gi++) {
      CommPlan::Group& g = p.groups[gi];
      if (g.count == 0) continue;
      if (p.wire == PlanWire::kQ8EF) plan_pack_ef(p, g, leaf_in);
      size_t esize = dtype_size(g.dtype);
      size_t stat_base = p.stats.size();
      p.stats.resize(stat_base + g.eff);
      last_stripe_ns_.assign(g.eff, 0);
      // The triple pipeline: every stripe sub-range is one bucket whose
      // pack -> ring -> unpack runs end-to-end on its own pool worker,
      // so bucket i+1 packs/casts while bucket i rides its connection
      // and bucket i-1 unpacks — with NO cross-bucket barrier and no
      // Python between phases. The ring body and stripe partition are
      // the fused op's own, so results are bit-identical to the legacy
      // path by construction.
      run_striped([&](int64_t s) {
        auto [start, len] = stripe_range(g.count, g.eff, s);
        CommPlan::BucketStat& st = p.stats[stat_base + s];
        st.group = static_cast<int64_t>(gi);
        st.stripe = s;
        st.bytes = static_cast<int64_t>(len * esize);
        if (len == 0) return;
        auto t0 = std::chrono::steady_clock::now();
        if (p.wire != PlanWire::kQ8EF)
          plan_pack_range(p, g, leaf_in, start, len);
        auto t1 = std::chrono::steady_clock::now();
        if (q8) {
          allreduce_q8_stripe(
              flat_, s, reinterpret_cast<float*>(g.staging.data()) + start,
              len, deadline);
        } else {
          allreduce_stripe(flat_, s, g.staging.data() + start * esize, len,
                           esize, g.dtype, ReduceOp::kSum, deadline);
        }
        auto t2 = std::chrono::steady_clock::now();
        plan_unpack_range(p, g, leaf_out, start, len, divisor, has_divisor);
        auto t3 = std::chrono::steady_clock::now();
        st.pack_ns = ns_between(t0, t1);
        st.ring_ns = ns_between(t1, t2);
        st.unpack_ns = ns_between(t2, t3);
      });
    }
  });
  p.execs++;
}

void HostCollectives::plan_execute_rs(int64_t plan_id,
                                      const void* const* leaf_in,
                                      float* shard_out, double divisor,
                                      bool has_divisor, int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  CommPlan& p = plan_get(plan_id);
  if (!p.sharded)
    throw SocketError("plan_execute_rs on a non-sharded plan");
  p.stats.clear();
  CommPlan::Group& g = p.groups[0];
  float* stg = reinterpret_cast<float*>(g.staging.data());
  const float div32 = static_cast<float>(divisor);
  if (world_size_ == 1) {
    // Solo: the shard IS the whole payload — pack, divide, done.
    plan_pack_range(p, g, leaf_in, 0, g.count);
    for (size_t i = 0; i < g.count; i++)
      shard_out[i] = has_divisor ? stg[i] / div32 : stg[i];
    p.execs++;
    return;
  }
  if (aborted_) throw SocketError("collectives not configured");
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // kind 11 = sharded grad leg: a sharded rs meeting a fused plan
    // execute (kind 8) or the param leg (kind 12) errors at the header.
    check_op_header(flat_, 11, p.sig, static_cast<uint32_t>(p.wire), 0,
                    deadline);
    const size_t wesize = p.wire == PlanWire::kQ8     ? 1
                          : p.wire == PlanWire::kBF16 ? 2
                                                      : 4;
    p.stats.resize(g.eff);
    last_stripe_ns_.assign(g.eff, 0);
    const int64_t own_c = (rank_ + 1) % world_size_;
    // Each stripe bucket runs pack -> rs phase end-to-end on its own
    // pool worker — the fused plan's triple pipeline, minus the phase
    // the schedule exists to drop.
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(g.count, g.eff, s);
      CommPlan::BucketStat& st = p.stats[s];
      st.group = 0;
      st.stripe = s;
      st.leg = 1;
      st.bytes = static_cast<int64_t>(len * wesize);
      if (len == 0) return;
      auto t0 = std::chrono::steady_clock::now();
      plan_pack_range(p, g, leaf_in, start, len);
      auto t1 = std::chrono::steady_clock::now();
      if (p.wire == PlanWire::kQ8) {
        // Per-hop dequant-accumulate in f32: the owner's chunk ends as
        // the FULL f32 running sum — the fused op's phase-2 owner
        // quantization only existed to ship the chunk, and here it
        // never ships (the PR-2 reduce_scatter_q8 discipline).
        rs_q8_phase_stripe(flat_, s, stg + start, len, deadline);
      } else if (p.wire == PlanWire::kBF16) {
        // Cast the stripe to bf16 wire words, ride the rs phase at half
        // width (per-hop f32 math, RNE back — the native bf16 body),
        // then decode only the OWNER chunk back into f32 staging: the
        // non-owned chunks' partial sums never leave the wire buffer.
        uint16_t* w = reinterpret_cast<uint16_t*>(p.wirebuf.data()) + start;
        for (size_t i = 0; i < len; i++) w[i] = f32_to_bf16(stg[start + i]);
        rs_phase_stripe(flat_, s, reinterpret_cast<char*>(w), len, 2,
                        Dtype::kBF16, ReduceOp::kSum, deadline);
        auto [cs, cl] = chunk_range(len, world_size_, own_c);
        for (size_t i = 0; i < cl; i++)
          stg[start + cs + i] = bf16_to_f32(w[cs + i]);
      } else {
        rs_phase_stripe(flat_, s, reinterpret_cast<char*>(stg + start), len,
                        sizeof(float), Dtype::kF32, ReduceOp::kSum, deadline);
      }
      auto t2 = std::chrono::steady_clock::now();
      st.pack_ns = ns_between(t0, t1);
      st.ring_ns = ns_between(t1, t2);
    });
    auto u0 = std::chrono::steady_clock::now();
    copy_shard(reinterpret_cast<char*>(stg),
               reinterpret_cast<char*>(shard_out), g.count, sizeof(float),
               g.eff, /*to_shard=*/true);
    if (has_divisor) {
      size_t sn = 0;
      for (auto [start, len] :
           shard_ranges(g.count, sizeof(float), rank_, g.eff))
        sn += len;
      // The owner's slice of the fused unpack arithmetic: f32 / f32.
      for (size_t i = 0; i < sn; i++) shard_out[i] /= div32;
    }
    if (!p.stats.empty())
      p.stats[0].unpack_ns = ns_between(u0, std::chrono::steady_clock::now());
  });
  p.execs++;
}

void HostCollectives::plan_execute_ag(int64_t plan_id, const float* shard_in,
                                      void* const* leaf_out,
                                      int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  CommPlan& p = plan_get(plan_id);
  if (!p.sharded)
    throw SocketError("plan_execute_ag on a non-sharded plan");
  CommPlan::Group& g = p.groups[0];
  float* stg = reinterpret_cast<float*>(g.staging.data());
  if (world_size_ == 1) {
    memcpy(stg, shard_in, g.count * sizeof(float));
    plan_unpack_range(p, g, leaf_out, 0, g.count, 1.0, /*has_divisor=*/false);
    p.execs++;
    return;
  }
  if (aborted_) throw SocketError("collectives not configured");
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    // kind 12 = sharded param leg; the header carries the AG wire so a
    // native-gathering member and a bf16-gathering one error apart.
    check_op_header(flat_, 12, p.sig, static_cast<uint32_t>(p.ag_wire), 0,
                    deadline);
    copy_shard(reinterpret_cast<char*>(stg),
               const_cast<char*>(reinterpret_cast<const char*>(shard_in)),
               g.count, sizeof(float), g.eff, /*to_shard=*/false);
    const size_t wesize = p.ag_wire == PlanWire::kBF16 ? 2 : 4;
    const size_t stat_base = p.stats.size();  // append after the rs leg
    p.stats.resize(stat_base + g.eff);
    last_stripe_ns_.assign(g.eff, 0);
    const int64_t own_c = (rank_ + 1) % world_size_;
    run_striped([&](int64_t s) {
      auto [start, len] = stripe_range(g.count, g.eff, s);
      CommPlan::BucketStat& st = p.stats[stat_base + s];
      st.group = 0;
      st.stripe = s;
      st.leg = 2;
      st.bytes = static_cast<int64_t>(len * wesize);
      if (len == 0) return;
      auto t0 = std::chrono::steady_clock::now();
      auto t1 = t0;
      if (p.ag_wire == PlanWire::kBF16) {
        // Encode only the OWNED chunk (the rest arrives over the ring),
        // circulate the bf16 words, then decode the WHOLE stripe: every
        // member adopts the identical decoded words, so the gathered
        // params are bit-identical across the cohort — the property the
        // commit vote's determinism oracle rests on.
        uint16_t* w = reinterpret_cast<uint16_t*>(p.wirebuf.data()) + start;
        auto [cs, cl] = chunk_range(len, world_size_, own_c);
        for (size_t i = 0; i < cl; i++)
          w[cs + i] = f32_to_bf16(stg[start + cs + i]);
        t1 = std::chrono::steady_clock::now();
        ag_phase_stripe(flat_, s, reinterpret_cast<char*>(w), len, 2,
                        deadline);
        for (size_t i = 0; i < len; i++) stg[start + i] = bf16_to_f32(w[i]);
      } else {
        ag_phase_stripe(flat_, s, reinterpret_cast<char*>(stg + start), len,
                        sizeof(float), deadline);
      }
      auto t2 = std::chrono::steady_clock::now();
      plan_unpack_range(p, g, leaf_out, start, len, 1.0,
                        /*has_divisor=*/false);
      auto t3 = std::chrono::steady_clock::now();
      st.pack_ns = ns_between(t0, t1);
      st.ring_ns = ns_between(t1, t2);
      st.unpack_ns = ns_between(t2, t3);
    });
  });
  p.execs++;
}

void HostCollectives::broadcast(void* data, size_t nbytes, int64_t root,
                                int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  if (root < 0 || root >= world_size_) throw SocketError("bad broadcast root");
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    check_op_header(flat_, 2, nbytes, static_cast<uint32_t>(root), 0,
                    deadline);
    if (nbytes == 0) return;
    char* bytes = static_cast<char*>(data);
    int64_t eff = effective_stripes(nbytes, stripes_);
    last_stripe_ns_.assign(eff, 0);
    // Forward around the ring, root first; the last hop before root does not
    // send. recv-then-send per hop (latency is fine at control-plane sizes;
    // bulk weight transfer goes through the checkpoint transport instead).
    run_striped([&](int64_t st) {
      auto [off, len] = stripe_range(nbytes, eff, st);
      if (len == 0) return;
      if (rank_ == root) {
        duplex(flat_.next[st], flat_.prev[st], bytes + off, len, nullptr, 0,
               deadline, &flat_.scratch[st]);
      } else {
        duplex(flat_.next[st], flat_.prev[st], nullptr, 0, bytes + off, len,
               deadline, &flat_.scratch[st]);
        if ((rank_ + 1) % world_size_ != root)
          duplex(flat_.next[st], flat_.prev[st], bytes + off, len, nullptr, 0,
                 deadline, &flat_.scratch[st]);
      }
    });
  });
}

void HostCollectives::barrier(int64_t timeout_ms) {
  MutexLock lock(op_mu_);
  op_seq_++;
  if (aborted_) throw SocketError("collectives not configured");
  if (world_size_ == 1) return;
  run_op([&] {
    int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
    check_op_header(flat_, 3, 0, 0, 0, deadline);
    // Two full ring passes on stripe 0: after the first, rank 0 knows
    // everyone arrived; the second releases everyone.
    char token = 1;
    for (int round = 0; round < 2; round++) {
      if (rank_ == 0) {
        duplex(flat_.next[0], flat_.prev[0], &token, 1, nullptr, 0, deadline,
               &flat_.scratch[0]);
        duplex(flat_.next[0], flat_.prev[0], nullptr, 0, &token, 1, deadline,
               &flat_.scratch[0]);
      } else {
        duplex(flat_.next[0], flat_.prev[0], nullptr, 0, &token, 1, deadline,
               &flat_.scratch[0]);
        duplex(flat_.next[0], flat_.prev[0], &token, 1, nullptr, 0, deadline,
               &flat_.scratch[0]);
      }
    }
  });
}

} // namespace tft
