"""Pallas wire-compression kernel numerics vs the FMA-free numpy oracle.

Runs under JAX_PLATFORMS=cpu in interpret mode (conftest pins the
platform), so tier-1 exercises the identical kernel bodies that compile
to Mosaic on TPU. The contract is BIT identity: the device quantize must
reproduce the numpy EF reference — and therefore the native plan_pack_ef
— exactly, or a device-packing ring member would drift from a
host-packing one (see torchft_tpu/ops/quantize_kernels.py).

Skip discipline: a module-level PROBE actually runs a tiny interpret-mode
kernel and skips with the precise failure when Pallas cannot execute here
— not a blanket platform check.
"""

import numpy as np
import pytest

from test_comm_plan import _np_quantize_ef


def _pallas_probe():
    try:
        import jax.numpy as jnp

        from torchft_tpu.ops.quantize_kernels import cast_bf16

        out = cast_bf16(jnp.ones((5,), jnp.float32), interpret=True)
        assert out.shape == (5,)
        return None
    except Exception as e:  # noqa: BLE001 - the probe IS the skip reason
        return f"pallas interpret mode unavailable here: {e!r}"


_SKIP = _pallas_probe()
if _SKIP is not None:
    pytest.skip(_SKIP, allow_module_level=True)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from torchft_tpu.ops.quantize_kernels import (  # noqa: E402
    _SCALE_FLOOR,
    _absmax,
    cast_bf16,
    dequantize_q8,
    quantize_q8,
    quantize_q8_ef,
)


def _np_scale(d):
    absmax = np.max(np.abs(d)) if d.size else np.float32(0)
    if not np.isfinite(absmax):
        return np.float32(np.nan)
    return np.maximum(
        np.float32(absmax) / np.float32(127.0), np.float32(1e-12)
    )


class TestQuantizeOracle:
    @pytest.mark.parametrize(
        "shape", [(1,), (33,), (128,), (257,), (13, 7), (70001,), (300000,)]
    )
    def test_ef_matches_numpy_oracle_bitwise(self, shape):
        rng = np.random.default_rng(hash(shape) % 2**32)
        x = rng.standard_normal(shape).astype(np.float32)
        res = np.zeros(shape, np.float32)
        q, s, r = quantize_q8_ef(jnp.asarray(x), jnp.asarray(res))
        dq_np, res_np = _np_quantize_ef(x, res)
        assert np.asarray(s).tobytes() == _np_scale(x).tobytes()
        # the decoded payload q*scale == the oracle's dq (+0.0 normalizes
        # the -0.0 an int8 code cannot carry; the q8 ring's own encode
        # kills the zero sign identically)
        dq_dev = (
            np.asarray(q, np.float32) * np.asarray(s) + np.float32(0.0)
        ).astype(np.float32)
        want = (dq_np + np.float32(0.0)).astype(np.float32)
        assert dq_dev.tobytes() == want.tobytes()
        # the carry is EXACT — this is the multi-step stability contract
        assert np.asarray(r).tobytes() == res_np.tobytes()

    def test_multi_step_carry_stays_bitwise(self):
        rng = np.random.default_rng(3)
        res_np = np.zeros(70001, np.float32)
        res_dev = jnp.asarray(res_np)
        fn = jax.jit(quantize_q8_ef)
        for step in range(6):
            x = rng.standard_normal(70001).astype(np.float32) * (step + 1)
            q, s, res_dev = fn(jnp.asarray(x), res_dev)
            _, res_np = _np_quantize_ef(x, res_np)
            assert np.asarray(res_dev).tobytes() == res_np.tobytes(), (
                f"carry diverged at step {step} — the EF recurrence must "
                "stay FMA-free (see _round32_mul)"
            )

    def test_round_half_to_even(self):
        # values landing exactly on .5 of the quantization grid must
        # round to even like nearbyint/np.round, not half-away
        scale = np.float32(1.0)
        x = np.array([0.5, 1.5, 2.5, -0.5, -1.5, 127.0], np.float32)
        q, s, _ = quantize_q8_ef(
            jnp.asarray(x * np.float32(127.0 / 127.0)),
            jnp.zeros(6, jnp.float32),
        )
        # scale = 127/127 = 1 exactly, so codes are round(x)
        assert np.asarray(s) == scale
        np.testing.assert_array_equal(
            np.asarray(q), np.array([0, 2, 2, 0, -2, 127], np.int8)
        )

    def test_all_zero_leaf_uses_scale_floor(self):
        q, s, r = quantize_q8_ef(
            jnp.zeros(1000, jnp.float32), jnp.zeros(1000, jnp.float32)
        )
        assert float(np.asarray(s)) == np.float32(_SCALE_FLOOR)
        assert not np.asarray(q).any()
        assert not np.asarray(r).any()

    @pytest.mark.parametrize("bad", [np.nan, np.inf, -np.inf])
    def test_nonfinite_poisons_whole_leaf(self, bad):
        x = np.zeros(517, np.float32)
        x[3] = 1.0
        x[400] = bad
        q, s, r = quantize_q8_ef(
            jnp.asarray(x), jnp.zeros(517, jnp.float32)
        )
        # NaN scale carries the poison (int8 codes cannot); the decode
        # 0 * NaN then NaNs EVERY element — the host EF's whole-leaf
        # propagation — and the carry is dead too
        assert np.isnan(np.asarray(s))
        assert not np.asarray(q).any()
        assert np.all(np.isnan(np.asarray(r)))
        assert np.all(np.isnan(np.asarray(dequantize_q8(q, s))))

    def test_quantize_q8_is_ef_with_zero_carry(self):
        rng = np.random.default_rng(11)
        x = rng.standard_normal(4097).astype(np.float32)
        q, s = quantize_q8(jnp.asarray(x))
        qe, se, _ = quantize_q8_ef(
            jnp.asarray(x), jnp.zeros(4097, jnp.float32)
        )
        assert np.asarray(q).tobytes() == np.asarray(qe).tobytes()
        assert np.asarray(s).tobytes() == np.asarray(se).tobytes()

    def test_dequantize_is_exact_decode(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal(1025).astype(np.float32)
        q, s = quantize_q8(jnp.asarray(x))
        out = np.asarray(dequantize_q8(q, s))
        want = (
            np.asarray(q, np.float32) * np.asarray(s)
        ).astype(np.float32)
        assert out.tobytes() == want.tobytes()


class TestCastBf16:
    def test_matches_numpy_round_to_nearest_even(self):
        import ml_dtypes

        rng = np.random.default_rng(5)
        x = np.concatenate([
            rng.standard_normal(70001).astype(np.float32),
            np.array([0.0, -0.0, np.inf, -np.inf, np.nan, 1e-40,
                      3.389531389251535e38], np.float32),
        ])
        got = np.asarray(cast_bf16(jnp.asarray(x)))
        want = x.astype(ml_dtypes.bfloat16)
        assert got.tobytes() == want.tobytes()

    def test_2d_shape_preserved(self):
        x = jnp.ones((13, 9), jnp.float32) * 1.7
        out = cast_bf16(x)
        assert out.shape == (13, 9) and out.dtype == jnp.bfloat16


class TestGridAccumulation:
    def test_multi_block_absmax_matches_single(self):
        # The TPU path splits big payloads into _BLOCK_ROWS grids whose
        # revisited (1,1) accumulator the interpret single-block path
        # never exercises — drive the multi-block grid explicitly.
        rng = np.random.default_rng(9)
        tiles = jnp.asarray(rng.standard_normal((64, 128)).astype(np.float32))
        multi = np.asarray(_absmax(tiles, 16, True))[0, 0]
        single = np.asarray(_absmax(tiles, 64, True))[0, 0]
        want = np.max(np.abs(np.asarray(tiles)))
        assert multi == want == single

    def test_multi_block_absmax_max_in_late_block(self):
        x = np.zeros((64, 128), np.float32)
        x[60, 5] = -7.5  # lives in the LAST block: accumulate must see it
        assert np.asarray(_absmax(jnp.asarray(x), 16, True))[0, 0] == 7.5
