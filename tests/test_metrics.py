"""Direct suite for torchft_tpu.metrics: the windowed rollups the policy
engine consumes (_Timer reservoirs, _TimedBlock, event-rate windows) had
no coverage of their own — they were only exercised incidentally through
Manager integration paths.
"""

import threading
import time

from torchft_tpu.metrics import Metrics, _EventWindow, _TimedBlock, _Timer


class TestTimer:
    def test_empty_snapshot(self):
        assert _Timer().snapshot() == {"n": 0}

    def test_percentiles_and_totals(self):
        t = _Timer()
        for v in [0.1, 0.2, 0.3, 0.4, 0.5]:
            t.record(v)
        snap = t.snapshot()
        assert snap["n"] == 5
        assert abs(snap["total_s"] - 1.5) < 1e-9
        assert snap["p50"] == 0.3
        assert snap["max"] == 0.5
        # p90 of 5 samples indexes int(0.9*5)=4 -> the largest
        assert snap["p90"] == 0.5

    def test_reservoir_is_bounded_but_totals_are_not(self):
        t = _Timer(maxlen=8)
        for i in range(100):
            t.record(float(i))
        snap = t.snapshot()
        # count/total keep the full history; percentiles see the window
        assert snap["n"] == 100
        assert abs(snap["total_s"] - sum(range(100))) < 1e-6
        assert snap["p50"] >= 92.0  # only the last 8 samples remain
        assert snap["max"] == 99.0

    def test_single_sample_percentiles_clamp(self):
        t = _Timer()
        t.record(0.25)
        snap = t.snapshot()
        assert snap["p50"] == 0.25
        assert snap["p90"] == 0.25
        assert snap["max"] == 0.25


class TestTimedBlock:
    def test_records_elapsed_wall(self):
        m = Metrics()
        with m.timed("op"):
            time.sleep(0.01)
        snap = m.snapshot()["timers_s"]["op"]
        assert snap["n"] == 1
        assert snap["max"] >= 0.009

    def test_records_even_when_body_raises(self):
        m = Metrics()
        try:
            with m.timed("op"):
                raise RuntimeError("boom")
        except RuntimeError:
            pass
        assert m.snapshot()["timers_s"]["op"]["n"] == 1

    def test_returns_self_as_context(self):
        block = Metrics().timed("x")
        assert isinstance(block, _TimedBlock)
        with block as entered:
            assert entered is block


class TestEventWindow:
    def test_unmarked_rate_is_zero(self):
        m = Metrics()
        assert m.rate_per_min("never") == 0.0

    def test_rate_uses_observed_window_when_young(self):
        # A process 0.1 s old that saw 2 events is running at ~1200/min,
        # not 2/600s=0.2/min: the divisor is observed time, not the
        # nominal window.
        w = _EventWindow()
        w.mark()
        w.mark()
        time.sleep(0.05)
        rate = w.rate_per_min(window_s=600.0)
        assert rate > 100.0

    def test_old_events_age_out_of_the_window(self):
        w = _EventWindow()
        w.mark()
        time.sleep(0.12)
        # a 0.05 s trailing window no longer contains the event
        assert w.rate_per_min(window_s=0.05) == 0.0

    def test_rollover_shrinks_observed_window(self):
        # When the reservoir rolled over, time before the oldest retained
        # stamp is unaccountable and must not dilute the rate.
        w = _EventWindow(maxlen=4)
        for _ in range(10):
            w.mark()
        assert w.count == 10
        rate = w.rate_per_min(window_s=600.0)
        assert rate > 0.0

    def test_snapshot_shape(self):
        m = Metrics()
        m.mark("churn")
        snap = m.snapshot()["events"]["churn"]
        assert snap["n"] == 1
        assert snap["rate_per_min"] > 0.0


class TestMetricsThreading:
    def test_concurrent_mixed_writes(self):
        m = Metrics()

        def writer():
            for _ in range(200):
                m.incr("c")
                m.record("t", 0.001)
                m.mark("e")

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = m.snapshot()
        assert snap["counters"]["c"] == 800
        assert snap["timers_s"]["t"]["n"] == 800
        assert snap["events"]["e"]["n"] == 800
