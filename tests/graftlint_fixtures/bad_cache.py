# graftlint fixture: plan-cache mutations outside the invalidation
# entry points.


class HostCollectives:
    def __init__(self):
        self._plans = {}  # allowed

    def configure(self, store_addr, rank, world_size):
        self._plans = {}  # allowed (the invalidation entry point)

    def _plan_for(self, key):
        if key not in self._plans:
            self._plans[key] = object()  # allowed (build-and-memoize)
        return self._plans[key]

    def sneaky_drop(self, key):
        self._plans.pop(key, None)  # violation: mutating method call

    def sneaky_insert(self, key, plan):
        self._plans[key] = plan  # violation: item assignment

    def sneaky_rebind(self):
        self._plans = {}  # violation: rebound outside entry points

    def read_only(self, key):
        return self._plans.get(key)  # clean: reads are fine anywhere
