#include "lighthouse.h"

#include <sys/socket.h>

#include <chrono>
#include <sstream>

#include "http_util.h"
#include "log.h"
#include "manager.h"
#include "wire.h"

namespace tft {

using torchft_tpu::ErrorResponse;
using torchft_tpu::Quorum;
using torchft_tpu::QuorumMember;

Lighthouse::Lighthouse(const std::string& bind_addr, const LighthouseOpt& opt)
    : opt_(opt),
      listener_(std::make_unique<Listener>(bind_addr)),
      hostname_(local_hostname()) {
  accept_thread_ = std::thread([this] { accept_loop(); });
  tick_thread_ = std::thread([this] { tick_loop(); });
  LOG_INFO("Lighthouse listening on: " << address());
}

Lighthouse::~Lighthouse() { shutdown(); }

std::string Lighthouse::address() const {
  return "http://" + hostname_ + ":" + std::to_string(listener_->port());
}

uint16_t Lighthouse::port() const { return listener_->port(); }

void Lighthouse::shutdown() {
  {
    // Flag + notify under the cv's mutex so waiters can't miss the wakeup.
    MutexLock lock(mu_);
    if (shutting_down_.exchange(true)) return;
    quorum_cv_.notify_all();
  }
  listener_->close();
  if (accept_thread_.joinable()) accept_thread_.join();
  if (tick_thread_.joinable()) tick_thread_.join();
  conns_.shutdown_all();
}

void Lighthouse::accept_loop() {
  while (!shutting_down_) {
    Socket sock = listener_->accept();
    if (!sock.valid()) return;
    conns_.spawn(std::move(sock), [this](Socket& s) { handle_conn(s); });
  }
}

void Lighthouse::tick_loop() {
  while (!shutting_down_) {
    {
      MutexLock lock(mu_);
      quorum_tick_locked();
    }
    struct timespec ts;
    ts.tv_sec = opt_.quorum_tick_ms / 1000;
    ts.tv_nsec = (opt_.quorum_tick_ms % 1000) * 1000000;
    nanosleep(&ts, nullptr);
  }
}

void Lighthouse::quorum_tick_locked() {
  ticks_total_ += 1;
  // Idle skip: with no registered participant no quorum can form (a lease
  // expiring can only shrink the healthy set), so the O(groups) membership
  // scan is pure waste. This is what keeps root CPU flat between quorum
  // rounds at thousands-of-groups scale.
  if (state_.participants.empty() && opt_.min_replicas > 0) return;

  auto t0 = std::chrono::steady_clock::now();
  QuorumStepResult res = quorum_step(now_ms(), unix_ms(), state_, opt_);
  last_compute_us_ = std::chrono::duration_cast<std::chrono::microseconds>(
                         std::chrono::steady_clock::now() - t0)
                         .count();
  ticks_computed_ += 1;
  total_compute_us_ += last_compute_us_;
  LOG_DEBUG("Next quorum status: " << res.reason);

  if (!res.quorum.has_value()) return;
  const Quorum& quorum = *res.quorum;

  if (res.changed) {
    LOG_INFO("Detected quorum change, bumping quorum_id to " << state_.quorum_id);

    // Event log entry: membership + who is healing (step behind max).
    int64_t max_step = -1;
    for (const auto& p : quorum.participants())
      max_step = std::max(max_step, p.step());
    std::ostringstream ev;
    ev << "[" << format_unix_ms(unix_ms()) << "] quorum " << state_.quorum_id
       << ": " << quorum.participants_size() << " member"
       << (quorum.participants_size() == 1 ? "" : "s");
    std::string healing;
    for (const auto& p : quorum.participants()) {
      if (p.step() != max_step) {
        if (!healing.empty()) healing += ", ";
        healing += p.replica_id();
      }
    }
    if (!healing.empty())
      ev << "; healing to step " << max_step << ": " << healing;
    state_.events.push_front(ev.str());
    while (state_.events.size() > 20) state_.events.pop_back();
  }

  LOG_INFO("Quorum! id=" << quorum.quorum_id()
                         << " participants=" << quorum.participants_size());

  latest_quorum_ = quorum;
  quorum_gen_ += 1;
  quorum_cv_.notify_all();
}

void Lighthouse::handle_conn(Socket& sock) {
  try {
    std::string req_head;
    if (sniff_http(sock, req_head)) {
      handle_http(sock, req_head);
      return;
    }

    while (true) {
      auto [type, payload] = recv_frame(sock);
      switch (type) {
        case MsgType::kLighthouseQuorumReq:
          handle_quorum_req(sock, payload);
          break;
        case MsgType::kLighthouseHeartbeatReq: {
          torchft_tpu::LighthouseHeartbeatRequest req;
          req.ParseFromString(payload);
          {
            MutexLock lock(mu_);
            state_.heartbeats[req.replica_id()] = now_ms();
          }
          send_msg(sock, MsgType::kLighthouseHeartbeatResp,
                   torchft_tpu::LighthouseHeartbeatResponse());
          break;
        }
        case MsgType::kLeaseRenewReq:
          handle_lease_renew(sock, payload);
          break;
        case MsgType::kDepartReq:
          handle_depart(sock, payload);
          break;
        case MsgType::kRegionDigestReq:
          handle_region_digest(sock, payload);
          break;
        case MsgType::kRegionPollReq:
          handle_region_poll(sock, payload);
          break;
        default:
          send_error(sock, ErrorResponse::INVALID_ARGUMENT,
                     "unexpected message type");
          return;
      }
    }
  } catch (const std::exception&) {
    // peer went away
  }
}

void Lighthouse::handle_quorum_req(Socket& sock, const std::string& payload) {
  torchft_tpu::LighthouseQuorumRequest req;
  if (!req.ParseFromString(payload) || !req.has_requester()) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "missing requester");
    return;
  }
  const QuorumMember& requester = req.requester();
  LOG_INFO("got quorum request for replica " << requester.replica_id());

  int64_t deadline = req.timeout_ms() <= 0 ? -1 : now_ms() + req.timeout_ms();

  UniqueMutexLock lock(mu_);
  // Joining the quorum is an implicit heartbeat.
  state_.heartbeats[requester.replica_id()] = now_ms();
  state_.participants[requester.replica_id()] =
      ParticipantDetails{now_ms(), requester};
  int64_t gen = quorum_gen_;
  // Proactive tick so a now-complete quorum resolves without waiting a tick.
  quorum_tick_locked();

  while (true) {
    // Wait for a quorum newer than our subscription point.
    while (quorum_gen_ == gen && !shutting_down_) {
      if (deadline < 0) {
        quorum_cv_.wait(lock);
      } else {
        int64_t remain = deadline - now_ms();
        if (remain <= 0) {
          lock.unlock();
          send_error(sock, ErrorResponse::DEADLINE_EXCEEDED,
                     "lighthouse quorum timed out");
          return;
        }
        quorum_cv_.wait_for(lock, std::chrono::milliseconds(remain));
      }
    }
    if (shutting_down_) {
      lock.unlock();
      send_error(sock, ErrorResponse::CANCELLED, "lighthouse shutting down");
      return;
    }
    gen = quorum_gen_;
    bool in_quorum = false;
    for (const auto& p : latest_quorum_.participants()) {
      if (p.replica_id() == requester.replica_id()) {
        in_quorum = true;
        break;
      }
    }
    if (in_quorum) {
      torchft_tpu::LighthouseQuorumResponse resp;
      *resp.mutable_quorum() = latest_quorum_;
      lock.unlock();
      send_msg(sock, MsgType::kLighthouseQuorumResp, resp);
      return;
    }
    // A quorum formed without us (e.g. it was computed just before we joined);
    // re-register and keep waiting.
    LOG_INFO("Replica " << requester.replica_id() << " not in quorum, retrying");
    state_.participants[requester.replica_id()] =
        ParticipantDetails{now_ms(), requester};
  }
}

void Lighthouse::handle_lease_renew(Socket& sock, const std::string& payload) {
  torchft_tpu::LeaseRenewRequest req;
  if (!req.ParseFromString(payload)) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "bad lease renew request");
    return;
  }
  std::vector<LeaseEntry> entries = lease_entries_from_pb(req);
  torchft_tpu::LeaseRenewResponse resp;
  {
    MutexLock lock(mu_);
    // A NEW registration is quorum intent worth resolving eagerly, the way
    // a long-poll join does. Re-renewals of existing participants change
    // nothing the periodic tick won't see — ticking for those would be
    // O(groups) per renewal, O(groups^2)/interval aggregate while a join
    // window holds the quorum open.
    if (apply_lease_batch(state_, entries, now_ms())) quorum_tick_locked();
    resp.set_quorum_id(state_.quorum_id);
  }
  send_msg(sock, MsgType::kLeaseRenewResp, resp);
}

void Lighthouse::handle_depart(Socket& sock, const std::string& payload) {
  torchft_tpu::DepartRequest req;
  if (!req.ParseFromString(payload) || req.replica_id().empty()) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "missing replica_id");
    return;
  }
  {
    MutexLock lock(mu_);
    apply_depart(state_, req.replica_id());
    // An explicit depart may complete a pending quorum (the departed member
    // no longer counts against the straggler hold-the-door wait).
    quorum_tick_locked();
  }
  LOG_INFO("replica " << req.replica_id() << " departed");
  send_msg(sock, MsgType::kDepartResp, torchft_tpu::DepartResponse());
}

void Lighthouse::handle_region_digest(Socket& sock, const std::string& payload) {
  torchft_tpu::RegionDigestRequest req;
  if (!req.ParseFromString(payload) || req.region_id().empty()) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "missing region_id");
    return;
  }
  std::vector<DigestEntry> entries = digest_from_pb(req);
  torchft_tpu::RegionDigestResponse resp;
  {
    MutexLock lock(mu_);
    // Departs FIRST: a re-queued depart (failed push) may be older than a
    // rejoin carried in this digest's entries — entries must win.
    for (const auto& d : req.departed()) apply_depart(state_, d);
    apply_digest(state_, entries, now_ms());
    regions_[req.region_id()] =
        RegionInfo{now_ms(), static_cast<int64_t>(entries.size())};
    // A digest can both register participants and remove stragglers.
    quorum_tick_locked();
    resp.set_quorum_gen(quorum_gen_);
  }
  send_msg(sock, MsgType::kRegionDigestResp, resp);
}

void Lighthouse::handle_region_poll(Socket& sock, const std::string& payload) {
  torchft_tpu::RegionPollRequest req;
  if (!req.ParseFromString(payload)) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "bad region poll request");
    return;
  }
  int64_t deadline = req.timeout_ms() <= 0 ? -1 : now_ms() + req.timeout_ms();

  UniqueMutexLock lock(mu_);
  while (quorum_gen_ <= req.min_gen() && !shutting_down_) {
    if (deadline < 0) {
      quorum_cv_.wait(lock);
    } else {
      int64_t remain = deadline - now_ms();
      if (remain <= 0) {
        lock.unlock();
        send_error(sock, ErrorResponse::DEADLINE_EXCEEDED,
                   "region poll timed out");
        return;
      }
      quorum_cv_.wait_for(lock, std::chrono::milliseconds(remain));
    }
  }
  if (shutting_down_) {
    lock.unlock();
    send_error(sock, ErrorResponse::CANCELLED, "lighthouse shutting down");
    return;
  }
  torchft_tpu::RegionPollResponse resp;
  *resp.mutable_quorum() = latest_quorum_;
  resp.set_gen(quorum_gen_);
  lock.unlock();
  send_msg(sock, MsgType::kRegionPollResp, resp);
}

namespace {

const char kIndexHtml[] = R"html(<!DOCTYPE html>
<html>
<head>
<title>torchft_tpu lighthouse</title>
<style>
body { font-family: system-ui, sans-serif; margin: 2em; background: #10141a; color: #e6e6e6; }
h1 { font-size: 1.4em; }
.card { border: 1px solid #2c3442; border-radius: 8px; padding: 0.8em 1.2em; margin: 0.6em 0; background: #161c26; }
.recovering { border-color: #e0912f; }
.muted { color: #8b96a8; font-size: 0.9em; }
button { background: #933; color: #fff; border: none; border-radius: 4px; padding: 0.3em 0.8em; cursor: pointer; }
table { border-collapse: collapse; }
td, th { padding: 0.2em 0.8em; text-align: left; }
</style>
</head>
<body>
<h1>torchft_tpu lighthouse</h1>
<div id="status">loading...</div>
<script>
async function refresh() {
  try {
    const r = await fetch('/status');
    document.getElementById('status').innerHTML = await r.text();
  } catch (e) {}
}
async function kill(id) {
  await fetch('/replica/' + encodeURIComponent(id) + '/kill', {method: 'POST'});
}
refresh();
setInterval(refresh, 1000);
</script>
</body>
</html>
)html";

} // namespace

std::string Lighthouse::render_status_locked() {
  auto [_, quorum_status] = quorum_compute(now_ms(), state_, opt_);

  int64_t max_step = -1;
  int64_t num_participants = -1;
  if (state_.prev_quorum.has_value()) {
    num_participants = state_.prev_quorum->participants_size();
    for (const auto& p : state_.prev_quorum->participants())
      max_step = std::max(max_step, p.step());
  }

  std::ostringstream os;
  os << "<div class=card><b>Quorum " << state_.quorum_id << "</b> &mdash; "
     << num_participants << " participants, max step " << max_step;
  if (state_.quorum_formed_ms >= 0) {
    int64_t age_s = (now_ms() - state_.quorum_formed_ms) / 1000;
    os << ", age " << age_s << " s";
  }
  os << "<div class=muted>" << html_escape(quorum_status) << "</div></div>";

  if (state_.prev_quorum.has_value()) {
    for (const auto& p : state_.prev_quorum->participants()) {
      bool recovering = p.step() != max_step;
      os << "<div class='card" << (recovering ? " recovering" : "") << "'><b>"
         << html_escape(p.replica_id()) << "</b>"
         << (recovering ? " <span class=muted>(recovering)</span>" : "")
         << "<table>"
         << "<tr><td>step</td><td>" << p.step() << "</td></tr>"
         << "<tr><td>manager</td><td>" << html_escape(p.address()) << "</td></tr>"
         << "<tr><td>store</td><td>" << html_escape(p.store_address()) << "</td></tr>"
         << "<tr><td>world size</td><td>" << p.world_size() << "</td></tr>"
         << "</table>"
         // replica_id reaches JS only via dataset (never inlined in code),
         // so a hostile id can't escape into script.
         << "<button data-rid=\"" << html_escape(p.replica_id())
         << "\" onclick=\"kill(this.dataset.rid)\">Kill</button></div>";
    }
  }

  os << "<div class=card><b>Heartbeats</b><table>";
  int64_t now = now_ms();
  for (const auto& [replica_id, last] : state_.heartbeats) {
    bool old = now - last >= opt_.heartbeat_timeout_ms;
    os << "<tr><td>" << html_escape(replica_id) << "</td><td"
       << (old ? " style='color:#e0912f'" : "") << ">" << (now - last)
       << " ms ago</td></tr>";
  }
  os << "</table></div>";

  if (!state_.events.empty()) {
    os << "<div class=card><b>Events</b>";
    for (const auto& ev : state_.events)
      os << "<div class=muted>" << html_escape(ev) << "</div>";
    os << "</div>";
  }
  return os.str();
}

Json Lighthouse::status_json_locked() {
  int64_t now = now_ms();
  JsonObject o;
  o["role"] = std::string(regions_.empty() ? "flat" : "root");
  o["quorum_id"] = state_.quorum_id;
  o["quorum_gen"] = quorum_gen_;
  if (state_.quorum_formed_ms >= 0) {
    o["quorum_age_ms"] = now - state_.quorum_formed_ms;
  } else {
    o["quorum_age_ms"] = Json();
  }
  if (state_.prev_quorum.has_value()) {
    o["quorum"] = quorum_to_json(*state_.prev_quorum);
  } else {
    o["quorum"] = Json();
  }

  JsonArray members;
  for (const auto& [replica_id, last] : state_.heartbeats) {
    JsonObject m;
    m["replica_id"] = replica_id;
    int64_t ttl = lease_ttl_for(state_, replica_id, opt_);
    m["ttl_ms"] = ttl;
    m["lease_remaining_ms"] = last + ttl - now;
    m["participating"] = state_.participants.count(replica_id) > 0;
    auto st = state_.member_status.find(replica_id);
    if (st != state_.member_status.end()) {
      try {
        m["status"] = Json::parse(st->second);
      } catch (const std::exception&) {
        m["status"] = st->second; // unparseable digest: surface raw
      }
    }
    members.push_back(Json(std::move(m)));
  }
  o["members"] = Json(std::move(members));

  JsonArray parts;
  for (const auto& [replica_id, _] : state_.participants)
    parts.push_back(Json(replica_id));
  o["participants"] = Json(std::move(parts));

  JsonObject tick;
  tick["total"] = ticks_total_;
  tick["computed"] = ticks_computed_;
  tick["last_compute_us"] = last_compute_us_;
  tick["total_compute_us"] = total_compute_us_;
  o["tick"] = Json(std::move(tick));

  JsonArray regions;
  for (const auto& [region_id, info] : regions_) {
    JsonObject r;
    r["region_id"] = region_id;
    r["last_digest_age_ms"] = now - info.last_digest_ms;
    r["entries"] = info.entries;
    regions.push_back(Json(std::move(r)));
  }
  o["regions"] = Json(std::move(regions));

  JsonArray events;
  for (const auto& ev : state_.events) events.push_back(Json(ev));
  o["events"] = Json(std::move(events));
  return Json(std::move(o));
}

std::string Lighthouse::status_json() {
  Json j;
  {
    MutexLock lock(mu_);
    j = status_json_locked();
  }
  JsonObject& o = j.as_object();
  o["open_conns"] = static_cast<int64_t>(conns_.size());
  o["address"] = address();
  return j.dump();
}

void Lighthouse::handle_http(Socket& sock, const std::string& head) {
  std::istringstream is(head);
  std::string method, path;
  is >> method >> path;

  if (method == "GET" && (path == "/" || path.empty())) {
    http_respond(sock, 200, "text/html", kIndexHtml);
  } else if (method == "GET" && path == "/status.json") {
    http_respond(sock, 200, "application/json", status_json());
  } else if (method == "GET" && path == "/status") {
    std::string body;
    {
      MutexLock lock(mu_);
      body = render_status_locked();
    }
    http_respond(sock, 200, "text/html", body);
  } else if (method == "POST" && path.rfind("/replica/", 0) == 0 &&
             path.size() > 14 && path.compare(path.size() - 5, 5, "/kill") == 0) {
    std::string replica_id = path.substr(9, path.size() - 9 - 5);
    std::string addr;
    {
      MutexLock lock(mu_);
      if (state_.prev_quorum.has_value()) {
        for (const auto& p : state_.prev_quorum->participants()) {
          if (p.replica_id() == replica_id) {
            addr = p.address();
            break;
          }
        }
      }
    }
    if (addr.empty()) {
      http_respond(sock, 404, "text/plain", "failed to find replica");
      return;
    }
    try {
      ManagerClient client(addr, /*connect_timeout_ms=*/10000);
      client.kill("killed from dashboard");
      http_respond(sock, 200, "text/plain", "ok");
    } catch (const std::exception& e) {
      http_respond(sock, 500, "text/plain", e.what());
    }
  } else {
    http_respond(sock, 404, "text/plain", "not found");
  }
}

} // namespace tft
