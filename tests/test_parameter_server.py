"""Parameter server end-to-end in one process.
Mirrors reference parameter_server_test.py:33-47."""

import threading
from datetime import timedelta

import numpy as np

from torchft_tpu.collectives import Collectives, HostCollectives, ReduceOp
from torchft_tpu.parameter_server import ParameterServer


class EchoAverageServer(ParameterServer):
    """Server that averages one tree with the client, twice."""

    @classmethod
    def new_collectives(cls) -> Collectives:
        return HostCollectives(timeout=timedelta(seconds=10))

    def forward(self, session_id: str, collectives: Collectives) -> None:
        for _ in range(2):
            collectives.allreduce(
                {"w": np.full(4, 2.0, np.float32)}, ReduceOp.AVG
            ).wait()
        collectives.shutdown()


def test_parameter_server_session_roundtrip():
    server = EchoAverageServer()
    try:
        client = EchoAverageServer.new_session(server.address())
        for _ in range(2):
            out = client.allreduce(
                {"w": np.full(4, 4.0, np.float32)}, ReduceOp.AVG
            ).wait()
            np.testing.assert_array_equal(out["w"], np.full(4, 3.0))
        client.shutdown()
    finally:
        server.shutdown()


def test_multiple_sessions():
    server = EchoAverageServer()
    try:
        for _ in range(2):
            client = EchoAverageServer.new_session(server.address())
            out = client.allreduce(
                {"w": np.zeros(4, np.float32)}, ReduceOp.AVG
            ).wait()
            np.testing.assert_array_equal(out["w"], np.full(4, 1.0))
            # finish the session protocol so the server thread completes
            client.allreduce({"w": np.zeros(4, np.float32)}, ReduceOp.AVG).wait()
            client.shutdown()
    finally:
        server.shutdown()

# -- addressing (TORCHFT_PS_HOST) --------------------------------------------


def test_address_honors_env_host(monkeypatch):
    monkeypatch.setenv("TORCHFT_PS_HOST", "ps.example.internal")
    server = EchoAverageServer()
    try:
        addr = server.address()
        assert addr.startswith("http://ps.example.internal:")
        assert addr.endswith("/new_session")
        assert server.serving_address().startswith(
            "http://ps.example.internal:"
        )
    finally:
        server.shutdown()


def test_address_falls_back_to_hostname(monkeypatch):
    import socket

    monkeypatch.delenv("TORCHFT_PS_HOST", raising=False)
    server = EchoAverageServer()
    try:
        assert server.address() == (
            f"http://{socket.gethostname()}:"
            f"{server.publisher.server.port}/new_session"
        )
    finally:
        server.shutdown()


def test_address_brackets_ipv6_literal(monkeypatch):
    monkeypatch.setenv("TORCHFT_PS_HOST", "fd00::1234")
    server = EchoAverageServer()
    try:
        assert server.address().startswith("http://[fd00::1234]:")
    finally:
        server.shutdown()


def test_listener_is_dual_stack_ipv6():
    import socket

    server = EchoAverageServer()
    try:
        assert (
            server.publisher.server._server.address_family
            == socket.AF_INET6
        )
    finally:
        server.shutdown()


# -- session lifecycle -------------------------------------------------------


class RecordingServer(ParameterServer):
    """Tracks every collectives it hands to sessions so tests can assert
    they were freed; ``fail_first`` makes the first forward() raise
    mid-session."""

    # Recording is routed through a thread-local sink: each handler
    # thread tags itself in _handle_session, so overlapping sessions
    # (and the client-side new_collectives calls on the test thread)
    # never clobber each other the way a temporary classmethod swap
    # would.
    _local = threading.local()

    def __init__(self, fail_first: bool = False) -> None:
        self.handed_out = []
        self.fail_first = fail_first
        self._sessions = 0
        super().__init__()

    @classmethod
    def new_collectives(cls) -> Collectives:
        c = HostCollectives(timeout=timedelta(seconds=10))
        sink = getattr(cls._local, "sink", None)
        if sink is not None:
            sink.append(c)
        return c

    def _handle_session(self, session_id, store_addr):
        type(self)._local.sink = self.handed_out
        try:
            super()._handle_session(session_id, store_addr)
        finally:
            type(self)._local.sink = None

    def forward(self, session_id, collectives):
        self._sessions += 1
        if self.fail_first and self._sessions == 1:
            collectives.allreduce(
                {"w": np.full(4, 2.0, np.float32)}, ReduceOp.AVG
            ).wait()
            raise RuntimeError("mid-session failure")
        for _ in range(2):
            collectives.allreduce(
                {"w": np.full(4, 2.0, np.float32)}, ReduceOp.AVG
            ).wait()


def _drain(client):
    out = client.allreduce(
        {"w": np.full(4, 4.0, np.float32)}, ReduceOp.AVG
    ).wait()
    return out["w"]


def _wait_until(pred, timeout_s=10.0):
    import time

    deadline = time.monotonic() + timeout_s
    while not pred():
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.02)
    return True


def test_session_error_frees_collectives():
    server = RecordingServer(fail_first=True)
    try:
        client = RecordingServer.new_session(server.address())
        np.testing.assert_array_equal(_drain(client), np.full(4, 3.0))
        # the server's forward raises after the first op; its collectives
        # must be shut down by the session wrapper, not left to GC
        assert _wait_until(
            lambda: len(server.handed_out) == 1
            and server.handed_out[0]._shutdown
        )
        client.shutdown()
    finally:
        server.shutdown()


def test_client_reconnects_after_session_failure():
    server = RecordingServer(fail_first=True)
    try:
        first = RecordingServer.new_session(server.address())
        np.testing.assert_array_equal(_drain(first), np.full(4, 3.0))
        first.shutdown()
        assert _wait_until(
            lambda: server.handed_out
            and server.handed_out[0]._shutdown
        )
        # reconnect: a fresh session works end to end
        second = RecordingServer.new_session(server.address())
        np.testing.assert_array_equal(_drain(second), np.full(4, 3.0))
        np.testing.assert_array_equal(_drain(second), np.full(4, 3.0))
        second.shutdown()
        assert _wait_until(
            lambda: len(server.handed_out) == 2
            and all(c._shutdown for c in server.handed_out)
        )
    finally:
        server.shutdown()


def test_concurrent_sessions():
    import threading

    server = EchoAverageServer()
    results = []
    try:

        def run_one():
            client = EchoAverageServer.new_session(server.address())
            for _ in range(2):
                out = client.allreduce(
                    {"w": np.full(4, 4.0, np.float32)}, ReduceOp.AVG
                ).wait()
                results.append(out["w"].copy())
            client.shutdown()

        threads = [threading.Thread(target=run_one) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert len(results) == 6
        for r in results:
            np.testing.assert_array_equal(r, np.full(4, 3.0))
    finally:
        server.shutdown()


# -- serving surface on the same listener ------------------------------------


def test_ps_surface_rides_session_port():
    from torchft_tpu.serving import WeightSubscriber, _http_json

    server = EchoAverageServer(wire="f32")
    try:
        base = f"http://[::1]:{server.publisher.server.port}"
        st = _http_json(f"{base}/ps/status", 5.0)
        assert st["role"] == "publisher"
        assert st["latest"] == -1  # nothing published yet
        server.publish({"w": np.arange(8, dtype=np.float32)}, step=3)
        sub = WeightSubscriber(base, name="ps-sub")
        assert sub.poll() is True
        version, tree, _age = sub.current()
        assert version == 0
        np.testing.assert_array_equal(
            tree["w"], np.arange(8, dtype=np.float32)
        )
        # ...while the legacy session API still answers on the same port
        client = EchoAverageServer.new_session(server.address())
        np.testing.assert_array_equal(_drain(client), np.full(4, 3.0))
        np.testing.assert_array_equal(_drain(client), np.full(4, 3.0))
        client.shutdown()
    finally:
        server.shutdown()
