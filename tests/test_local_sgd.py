"""LocalSGD / DiLoCo tests.

Unit tests against an autospec'd Manager (reference local_sgd_test.py:41-146)
plus thread-per-replica integration with fault injection and the
algorithm-specific oracles (reference local_sgd_integ_test.py:207-316).
"""

import threading
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import Any, Dict
from unittest.mock import create_autospec

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import (
    FTTrainState,
    HostCollectives,
    Lighthouse,
    Manager,
    Store,
)
from torchft_tpu.collectives import ReduceOp, _completed
from torchft_tpu.local_sgd import AsyncDiLoCo, DiLoCo, LocalSGD
from torchft_tpu.manager import Manager as RealManager


def _state(value: float = 1.0) -> FTTrainState:
    return FTTrainState(
        {"w": jnp.full((4,), value, jnp.float32)}, optax.sgd(0.1)
    )


def _mock_manager(commit: bool = True):
    manager = create_autospec(RealManager, instance=True)
    manager.allreduce.side_effect = (
        lambda tree, op=None, wire=None: _completed(tree)
    )
    manager.should_commit.return_value = commit
    manager._use_async_quorum = False
    return manager


class TestLocalSGDUnit:
    def test_syncs_every_n_steps(self):
        manager = _mock_manager()
        local = LocalSGD(manager, _state(), sync_every=3)
        grads = {"w": jnp.ones((4,))}
        for i in range(5):
            local.step(grads)
        assert manager.start_quorum.call_count == 1  # one sync at step 3
        local.step(grads)
        assert manager.start_quorum.call_count == 2

    def test_step_applied_counts_and_syncs(self):
        # The fused-train-step integration: the caller applies the inner
        # update itself (models.make_train_step); step_applied only does
        # window accounting — params must NOT be touched by it.
        manager = _mock_manager()
        st = _state(2.0)
        local = LocalSGD(manager, st, sync_every=2)
        before = np.asarray(st.params["w"]).copy()
        local.step_applied()
        assert manager.start_quorum.call_count == 0
        assert np.array_equal(np.asarray(st.params["w"]), before)
        local.step_applied()
        assert manager.start_quorum.call_count == 1  # boundary sync

    def test_make_train_step_matches_split_programs(self):
        # One fused program == grad then apply semantically; XLA fuses
        # differently across the program boundary, so float accumulation
        # order (and thus low-order bits) legitimately differs. SGD keeps
        # the update LINEAR in the gradients so that noise stays at float
        # scale (adam's sign normalization would amplify near-zero-grad
        # noise to +-lr).
        from torchft_tpu.models import (
            init_params,
            loss_fn,
            make_train_step,
            tiny_config,
        )

        cfg = tiny_config()
        tx = optax.sgd(0.1)
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt_state = tx.init(params)
        batch = jnp.zeros((2, 16), jnp.int32)

        fused = make_train_step(cfg, tx)
        p1, o1, loss1 = fused(
            jax.tree_util.tree_map(jnp.copy, params),
            jax.tree_util.tree_map(jnp.copy, opt_state),
            batch,
        )

        loss2, grads = jax.value_and_grad(lambda p: loss_fn(cfg, p, batch))(
            params
        )
        updates, o2 = tx.update(grads, opt_state, params)
        p2 = optax.apply_updates(params, updates)

        # Tolerances at bf16 scale: the model's activations (and thus the
        # grads) are bfloat16, whose rounding differs across fusion
        # orders; the test still catches wiring bugs (wrong optimizer,
        # missing apply, sign errors), which produce O(update) errors.
        assert float(loss1) == pytest.approx(float(loss2), rel=1e-2)
        for a, b in zip(
            jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-2, atol=1e-3
            )

    def test_commit_saves_backup(self):
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        local = LocalSGD(manager, st, sync_every=1)
        local.step({"w": jnp.ones((4,))})  # sgd(0.1): w = 1 - 0.1
        np.testing.assert_allclose(np.asarray(st.params["w"]), 0.9)
        np.testing.assert_allclose(local._backup_params["w"], 0.9)

    def test_abort_restores_backup(self):
        manager = _mock_manager(commit=False)
        st = _state(1.0)
        local = LocalSGD(manager, st, sync_every=2)
        local.step({"w": jnp.ones((4,))})
        local.step({"w": jnp.ones((4,))})
        # Window discarded: params back to the last synced value.
        np.testing.assert_allclose(np.asarray(st.params["w"]), 1.0)
        assert local._local_step == 0

    def test_state_dict_roundtrip(self):
        manager = _mock_manager()
        st = _state(2.0)
        local = LocalSGD(manager, st, sync_every=4)
        local.step({"w": jnp.ones((4,))})
        sd = local.state_dict()
        st2 = _state(0.0)
        local2 = LocalSGD(_mock_manager(), st2, sync_every=4)
        local2.load_state_dict(sd)
        np.testing.assert_allclose(
            np.asarray(st2.params["w"]), np.asarray(st.params["w"])
        )
        assert local2._local_step == 1


class TestDiLoCoUnit:
    def test_requires_sync_quorum(self):
        manager = _mock_manager()
        manager._use_async_quorum = True
        with pytest.raises(ValueError):
            DiLoCo(manager, _state(), optax.sgd(0.5), sync_every=2)

    def test_outer_step_moves_toward_inner(self):
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        diloco = DiLoCo(manager, st, optax.sgd(1.0), sync_every=2)
        for _ in range(2):
            diloco.step({"w": jnp.ones((4,))})
        # inner: w = 1 - 0.1 - 0.1 = 0.8; pseudo = 1.0 - 0.8 = 0.2;
        # outer sgd(lr=1): w = 1.0 - 1.0 * 0.2 = 0.8 — toward the inner
        # result, reproducing it exactly at lr=1 (paper sign convention).
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.8, rtol=1e-6
        )
        np.testing.assert_allclose(diloco._backup_params["w"], 0.8, rtol=1e-6)

    def test_abort_restores_without_outer_step(self):
        manager = _mock_manager(commit=False)
        st = _state(1.0)
        diloco = DiLoCo(manager, st, optax.sgd(0.7), sync_every=1)
        diloco.step({"w": jnp.ones((4,))})
        np.testing.assert_allclose(np.asarray(st.params["w"]), 1.0)


class TestAsyncDiLoCoUnit:
    def test_lr1_single_group_degenerates_to_local(self):
        # Invariant: one group + outer SGD(lr=1) makes the delayed outer
        # update G' = B − Δ, so the reconciliation correction vanishes and
        # AsyncDiLoCo must track pure local SGD exactly.
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        ad = AsyncDiLoCo(manager, st, optax.sgd(1.0), sync_every=2)
        ref = _state(1.0)
        grads = {"w": jnp.ones((4,))}
        for _ in range(6):
            ad.step(grads)
            ref.apply_gradients(grads)
        ad.flush()
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), np.asarray(ref.params["w"]), rtol=1e-6
        )

    def test_serial_mode_matches_sync_diloco(self):
        # overlap=False completes the sync AT the boundary; the delayed
        # reconciliation must degenerate to exact synchronous DiLoCo.
        grads = {"w": jnp.ones((4,))}

        serial_state = _state(1.0)
        serial = AsyncDiLoCo(
            _mock_manager(commit=True), serial_state, optax.sgd(0.5),
            sync_every=2, overlap=False,
        )
        ref_state = _state(1.0)
        ref = DiLoCo(
            _mock_manager(commit=True), ref_state, optax.sgd(0.5),
            sync_every=2,
        )
        for _ in range(4):
            serial.step(grads)
            ref.step(grads)
        assert serial._pending is None  # nothing left in flight
        np.testing.assert_allclose(
            np.asarray(serial_state.params["w"]),
            np.asarray(ref_state.params["w"]),
            rtol=1e-6,
        )

    def test_outer_update_applied_one_window_late(self):
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        ad = AsyncDiLoCo(manager, st, optax.sgd(1.0), sync_every=2)
        grads = {"w": jnp.ones((4,))}
        ad.step(grads)
        ad.step(grads)  # boundary k=0: launch, nothing applied yet
        assert manager.allreduce.call_count == 1
        assert manager.should_commit.call_count == 0
        np.testing.assert_allclose(ad._backup_params["w"], 1.0)  # B unchanged
        ad.step(grads)
        ad.step(grads)  # boundary k=1: window 0's sync completes first
        assert manager.should_commit.call_count == 1
        # lr=1 outer: G' = 1 − 0.2 = 0.8 becomes the new global backup.
        np.testing.assert_allclose(ad._backup_params["w"], 0.8, rtol=1e-6)

    def test_abort_rolls_back_only_inflight_window(self):
        manager = _mock_manager(commit=False)
        st = _state(1.0)
        ad = AsyncDiLoCo(manager, st, optax.sgd(1.0), sync_every=2)
        grads = {"w": jnp.ones((4,))}
        for _ in range(4):
            ad.step(grads)  # window 0 launched at step 2, aborted at step 4
        # At the step-4 boundary window 0 (Δ=0.2) is rolled back; window 1's
        # local progress (2 × 0.1) survives on top of B=1.0; then window 1's
        # sync launches (result still pending).
        ad.flush()  # window 1 also aborts: params return to B = 1.0
        np.testing.assert_allclose(np.asarray(st.params["w"]), 1.0, rtol=1e-6)
        np.testing.assert_allclose(ad._backup_params["w"], 1.0)

    def test_bf16_compression_ships_bf16_and_tracks_local(self):
        import jax

        manager = _mock_manager(commit=True)
        seen_dtypes = []

        def capture(tree, op=None):
            seen_dtypes.extend(
                str(l.dtype) for l in jax.tree_util.tree_leaves(tree)
            )
            from torchft_tpu.collectives import _completed

            return _completed(tree)

        manager.allreduce.side_effect = capture
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=2, compress="bf16"
        )
        grads = {"w": jnp.ones((4,))}
        for _ in range(4):
            ad.step(grads)
        ad.flush()
        assert seen_dtypes and all(d == "bfloat16" for d in seen_dtypes)
        # lr=1 single group still tracks local training, within bf16 error.
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.6, rtol=2e-2
        )
        assert st.params["w"].dtype == jnp.float32  # master stays f32

    def test_state_dict_flushes_pending(self):
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        ad = AsyncDiLoCo(manager, st, optax.sgd(1.0), sync_every=1)
        ad.step({"w": jnp.ones((4,))})
        sd = ad.state_dict()  # must not checkpoint with a window in flight
        assert ad._pending is None
        np.testing.assert_allclose(sd["backup_params"]["w"], 0.9, rtol=1e-6)


# -- integration: real control plane, threads as replica groups --


class InjectedFailure(Exception):
    pass


def _run_local_sgd_replicas(
    algo: str,
    num_replicas: int,
    num_syncs: int,
    sync_every: int,
    fail_at: Dict[int, int],
    sharded: bool = False,
    shard_wire=None,
    param_wire=None,
    stop_at: Dict[int, int] = None,
):
    """Each replica runs inner steps + periodic sync; fail_at maps
    replica_id -> manager step at which to die once (it then retries and
    heals back in); stop_at maps replica_id -> manager step at which it
    LEAVES permanently (a quorum shrink the survivors must ride out)."""
    lighthouse = Lighthouse(
        bind="[::]:0", min_replicas=1, join_timeout_ms=200,
        quorum_tick_ms=50, heartbeat_timeout_ms=1000,
    )
    remaining_failures = dict(fail_at)
    lock = threading.Lock()

    def run_replica(rid: int):
        for attempt in range(3):
            try:
                return _train(rid)
            except InjectedFailure:
                continue
        raise RuntimeError(f"replica {rid} exhausted attempts")

    def _train(rid: int):
        store = Store()
        col = HostCollectives(timeout=timedelta(seconds=10))
        st = FTTrainState(
            {"w": jnp.full((8,), 1.0, jnp.float32)}, optax.sgd(0.05)
        )
        holder: Dict[str, Any] = {}
        manager = Manager(
            collectives=col,
            load_state_dict=lambda sd: holder["algo"].load_state_dict(sd),
            state_dict=lambda: holder["algo"].state_dict(),
            min_replica_size=1,
            use_async_quorum=(algo == "local_sgd"),
            timeout=timedelta(seconds=10),
            quorum_timeout=timedelta(seconds=10),
            connect_timeout=timedelta(seconds=10),
            rank=0,
            world_size=1,
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id=f"{algo}_{rid}",
        )
        if algo == "local_sgd":
            holder["algo"] = LocalSGD(manager, st, sync_every)
        else:
            holder["algo"] = DiLoCo(
                manager, st, optax.sgd(0.7, momentum=0.9, nesterov=True)
                if sharded else optax.sgd(0.7), sync_every,
                sharded=sharded, shard_wire=shard_wire,
                param_wire=param_wire,
            )
        algo_obj = holder["algo"]
        try:
            while manager.current_step() < num_syncs:
                if (
                    stop_at is not None
                    and stop_at.get(rid, num_syncs + 1)
                    <= manager.current_step()
                ):
                    return None  # leaves the cohort for good: a shrink
                with lock:
                    if remaining_failures.get(rid) == manager.current_step():
                        del remaining_failures[rid]
                        raise InjectedFailure(f"{rid}")
                step = manager.current_step()
                grads = {
                    "w": jnp.full((8,), 0.1 * (step + 1), jnp.float32)
                }
                algo_obj.step(grads)
            return {
                "params": np.asarray(st.params["w"]),
                "backup": np.asarray(algo_obj._backup_params["w"]),
            }
        finally:
            manager.shutdown()
            col.shutdown()
            store.shutdown()

    try:
        with ThreadPoolExecutor(max_workers=num_replicas) as ex:
            futs = [ex.submit(run_replica, i) for i in range(num_replicas)]
            return [f.result(timeout=120) for f in futs]
    finally:
        lighthouse.shutdown()


class TestLocalSGDInteg:
    def test_local_sgd_recovery(self):
        results = _run_local_sgd_replicas(
            "local_sgd", num_replicas=2, num_syncs=4, sync_every=2,
            fail_at={1: 1},
        )
        # Model-only oracle (reference local_sgd_integ_test.py:207-214).
        np.testing.assert_array_equal(results[0]["params"], results[1]["params"])

    def test_diloco_recovery(self):
        results = _run_local_sgd_replicas(
            "diloco", num_replicas=2, num_syncs=4, sync_every=2,
            fail_at={1: 1},
        )
        np.testing.assert_array_equal(results[0]["params"], results[1]["params"])
        np.testing.assert_array_equal(results[0]["backup"], results[1]["backup"])


class TestInt8Compression:
    def _manager(self, commit=True, participants=1):
        manager = _mock_manager(commit=commit)
        manager.allgather.side_effect = lambda tree: _completed([tree])
        manager.num_participants.return_value = participants
        return manager

    def test_int8_ships_quantized_payload_via_allgather(self):
        # compress="int8": the DEVICE link carries int8 bytes — the wire
        # payload is {q: int8 leaves, scale: f32} over a managed
        # allgather, dequantize-averaged member-wise on finish.
        import jax

        manager = self._manager()
        seen = []
        manager.allgather.side_effect = lambda tree: (
            seen.append(tree), _completed([tree])
        )[1]
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=2, compress="int8"
        )
        grads = {"w": jnp.ones((4,))}
        for _ in range(4):
            ad.step(grads)
        ad.flush()
        assert seen and all(
            str(l.dtype) == "int8"
            for e in seen
            for l in jax.tree_util.tree_leaves(e["q"])
        )
        assert all("scale" in e for e in seen)
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.6, atol=0.01
        )

    def test_ships_quantized_grid_over_q8_wire(self):
        import jax

        manager = self._manager()
        seen = []

        def capture(tree, op=None, wire=None):
            seen.append((tree, op, wire))
            return _completed(tree)

        manager.allreduce.side_effect = capture
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=2, compress="q8"
        )
        grads = {"w": jnp.ones((4,))}
        for _ in range(4):
            ad.step(grads)
        ad.flush()
        assert seen
        for tree, op, wire in seen:
            # rides the ring's quantized wire with the participant average
            assert wire == "q8" and op == ReduceOp.AVG
            for l in jax.tree_util.tree_leaves(tree):
                # the shipped delta is the DEQUANTIZED local value: every
                # element sits on its leaf's int8 grid (d = k * scale for
                # integer k in [-127, 127])
                arr = np.asarray(l, np.float64)
                scale = np.abs(arr).max() / 127 if np.abs(arr).max() else 1.0
                k = arr / scale
                np.testing.assert_allclose(k, np.round(k), atol=1e-3)
        # lr=1 single group tracks local training within one quantization
        # step of the largest delta (scale = max|d|/127)
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.6, atol=0.01
        )
        assert st.params["w"].dtype == jnp.float32

    def test_error_feedback_prevents_drift(self):
        # Many windows with a delta that does NOT quantize exactly: with
        # EF the accumulated shipped sum stays within ONE quantization
        # step of the true sum; without EF the per-window bias would
        # accumulate linearly.
        manager = self._manager()
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=1, compress="int8"
        )
        # gradient chosen so delta/scale is irrational-ish per window
        grads = {"w": jnp.asarray([0.1, 0.0333, 0.00777, 0.0001])}
        windows = 20
        for _ in range(windows):
            ad.step(grads)
        ad.flush()
        # inner sgd lr=0.1 -> per-window delta = 0.1 * grad
        expect = 1.0 - windows * 0.1 * np.asarray(grads["w"])
        # one quantization step = max|d|/127 = 0.01/127 per window; EF
        # keeps TOTAL error near one step, far below windows * step
        step_q = 0.01 / 127
        err = np.max(np.abs(np.asarray(st.params["w"]) - expect))
        assert err < 3 * step_q, (err, step_q)

    def test_abort_restores_residual_and_rolls_back(self):
        manager = self._manager(commit=False)
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=1, compress="int8"
        )
        ad.step({"w": jnp.ones((4,))})  # window ships, will abort
        ad.flush()
        # rollback: params return to backup
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 1.0, atol=1e-6
        )
        # aborted window's EF update discarded
        np.testing.assert_allclose(
            np.asarray(ad._residual["w"]), 0.0, atol=1e-9
        )

    def test_averaged_result_applied_directly(self):
        # The q8 ring returns the PARTICIPANT-AVERAGED delta tree directly
        # (the zero-contribution/divisor discipline lives in
        # Manager.allreduce, covered by the manager tests; the native
        # quantized ring itself by test_collectives). Here: whatever
        # averaged tree the wire resolves to is what the outer update
        # consumes — simulate a 2-member average halving our delta.
        manager = self._manager(participants=2)

        def halved(tree, op=None, wire=None):
            import jax

            return _completed(
                jax.tree_util.tree_map(lambda l: l / 2, tree)
            )

        manager.allreduce.side_effect = halved
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=1, compress="q8"
        )
        ad.step({"w": jnp.ones((4,))})  # inner lr 0.1 -> own delta 0.1
        ad.flush()
        # averaged delta 0.05 applied by the lr-1 outer sgd
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.95, atol=0.001
        )


class _RingManager:
    """Deterministic manager fake over a REAL HostCollectives ring: full
    participation, always-commit, fixed quorum id — removes the
    join-timing nondeterminism a live lighthouse adds, so trajectory
    oracles can demand bit-equality."""

    def __init__(self, col, quorum_id: int = 1):
        self._col = col
        self._use_async_quorum = False
        self.qid = quorum_id
        self.commit = True

    def start_quorum(self, **kw):
        pass

    def _div(self, op):
        return float(self._col.size()) if op == ReduceOp.AVG else None

    def allreduce(self, tree, op=ReduceOp.AVG, wire=None):
        return self._col.allreduce(
            tree, ReduceOp.SUM, divisor=self._div(op), wire=wire
        )

    def reduce_scatter(self, tree, op=ReduceOp.AVG, wire=None):
        return self._col.reduce_scatter(
            tree, ReduceOp.SUM, divisor=self._div(op), wire=wire
        )

    def allgather_into(self, shard, wire=None):
        return self._col.allgather_into(shard, wire=wire)

    def allgather(self, tree):
        return self._col.allgather(tree)

    def quorum_id(self):
        return self.qid

    def should_commit(self):
        return self.commit

    def report_error(self, e):
        raise e


def _ring(store, world_size, prefix):
    from datetime import timedelta as td

    cols = [
        HostCollectives(timeout=td(seconds=15)) for _ in range(world_size)
    ]
    addr = f"{store.address()}/{prefix}"
    with ThreadPoolExecutor(max_workers=world_size) as ex:
        for f in [
            ex.submit(cols[r].configure, addr, r, world_size)
            for r in range(world_size)
        ]:
            f.result()
    return cols


def _ring_run(fns):
    out = [None] * len(fns)
    errs = []

    def go(r):
        try:
            out[r] = fns[r]()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=go, args=(r,)) for r in range(len(fns))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errs:
        raise errs[0]
    return out


class TestShardedDiLoCo:
    """The sharded outer sync (reduce-scatter -> outer step on the owned
    shard -> parameter allgather) against the unsharded oracle, plus the
    outer-state re-shard on membership change."""

    OUTER = dict(learning_rate=0.7, momentum=0.9, nesterov=True)

    def _params(self):
        return {
            "w": jnp.linspace(0.0, 1.0, 23, dtype=jnp.float32),
            "b": jnp.full((9,), 2.0, jnp.float32),
        }

    def _cohort(self, store, world_size, prefix, syncs, sync_every,
                sharded, shard_wire=None, param_wire=None):
        import optax as ox

        cols = _ring(store, world_size, prefix)

        def replica(r):
            st = FTTrainState(self._params(), ox.sgd(0.05))
            m = _RingManager(cols[r])
            algo = DiLoCo(
                m, st, ox.sgd(**self.OUTER), sync_every,
                sharded=sharded, shard_wire=shard_wire,
                param_wire=param_wire,
            )
            for s in range(syncs * sync_every):
                grads = {
                    "w": jnp.full((23,), 0.01 * (s + 1 + r), jnp.float32),
                    "b": jnp.full((9,), 0.03 * (r + 1), jnp.float32),
                }
                algo.step(grads)
            return (
                {k: np.asarray(v) for k, v in st.params.items()},
                algo,
            )

        try:
            return _ring_run(
                [lambda r=r: replica(r) for r in range(world_size)]
            )
        finally:
            for c in cols:
                c.shutdown()

    @pytest.mark.parametrize("world_size", [2, 3])
    def test_matches_unsharded_exactly(self, world_size):
        store = Store()
        try:
            uns = self._cohort(
                store, world_size, "uns", syncs=3, sync_every=2,
                sharded=False,
            )
            sh = self._cohort(
                store, world_size, "sh", syncs=3, sync_every=2,
                sharded=True,
            )
            for r in range(world_size):
                for k in uns[0][0]:
                    np.testing.assert_array_equal(
                        sh[r][0][k], uns[0][0][k]
                    )
        finally:
            store.shutdown()

    def test_q8_wire_bf16_params_consistent_and_close(self):
        store = Store()
        try:
            uns = self._cohort(
                store, 2, "unsq", syncs=2, sync_every=2, sharded=False
            )
            sh = self._cohort(
                store, 2, "shq", syncs=2, sync_every=2, sharded=True,
                shard_wire="q8", param_wire="bf16",
            )
            # Lossy wires: every member must still hold IDENTICAL params
            # (the determinism oracle), and they track the exact path.
            for k in sh[0][0]:
                np.testing.assert_array_equal(sh[0][0][k], sh[1][0][k])
                np.testing.assert_allclose(
                    sh[0][0][k], uns[0][0][k], rtol=0.05, atol=0.05
                )
        finally:
            store.shutdown()

    def test_outer_state_is_sharded(self):
        # The memory claim itself: each member's outer momentum covers
        # ~1/W of the model, and the union tiles it exactly.
        store = Store()
        try:
            res = self._cohort(
                store, 3, "mem", syncs=1, sync_every=1, sharded=True
            )
            total = 23 + 9
            seen = np.zeros(total, np.int32)
            for _, algo in res:
                (name,) = list(algo._outer_shard_meta["ranges"])
                ln = 0
                for s, l in algo._outer_shard_meta["ranges"][name]:
                    seen[s: s + l] += 1
                    ln += l
                leaves = jax.tree_util.tree_leaves(algo._outer_state)
                assert any(
                    getattr(x, "size", 0) == ln for x in leaves
                ), "momentum is not shard-sized"
                assert ln < total  # strictly smaller than the model
            np.testing.assert_array_equal(seen, np.ones(total, np.int32))
        finally:
            store.shutdown()

    def test_reshard_preserves_surviving_momentum(self):
        # W=3 cohort syncs once (momentum builds), one member leaves, the
        # two survivors re-form a W=2 ring with a BUMPED quorum id: their
        # next sync must re-partition the outer state — positions either
        # survivor owned keep their momentum, positions only the departed
        # member owned restart at zero.
        import optax as ox

        store = Store()
        try:
            cols3 = _ring(store, 3, "pre")
            states, algos, mans = [], [], []

            def one_sync(r):
                st = FTTrainState(self._params(), ox.sgd(0.05))
                m = _RingManager(cols3[r], quorum_id=1)
                algo = DiLoCo(
                    m, st, ox.sgd(**self.OUTER), 1, sharded=True
                )
                grads = {
                    "w": jnp.full((23,), 0.01 * (r + 1), jnp.float32),
                    "b": jnp.full((9,), 0.03 * (r + 1), jnp.float32),
                }
                algo.step(grads)
                return st, algo, m

            for st, algo, m in _ring_run(
                [lambda r=r: one_sync(r) for r in range(3)]
            ):
                states.append(st)
                algos.append(algo)
                mans.append(m)
            # Oracle: full momentum after one sync, from the unsharded
            # update rule (trace = averaged pseudogradient at step 1).
            old_meta = [
                {
                    k: list(v)
                    for k, v in a._outer_shard_meta["ranges"].items()
                }
                for a in algos
            ]
            (name,) = list(algos[0]._outer_shard_meta["ranges"])
            total = 23 + 9
            full_mom = np.zeros(total, np.float32)
            for a in algos:
                tr = np.asarray(
                    jax.tree_util.tree_leaves(a._outer_state)[0]
                )
                off = 0
                for s, ln in a._outer_shard_meta["ranges"][name]:
                    full_mom[s: s + ln] = tr[off: off + ln]
                    off += ln
            for c in cols3:
                c.shutdown()

            # Member 2 departs; survivors re-form at quorum 2.
            cols2 = _ring(store, 2, "post")

            def resync(r):
                mans[r]._col = cols2[r]
                mans[r].qid = 2
                grads = {
                    "w": jnp.full((23,), 0.02, jnp.float32),
                    "b": jnp.full((9,), 0.02, jnp.float32),
                }
                # capture the resharded state the sync consumed: run ONE
                # more sync; afterwards meta reflects the new partition
                algos[r].step(grads)
                return None

            _ring_run([lambda r=r: resync(r) for r in range(2)])
            # Survivors hold identical params.
            for k in states[0].params:
                np.testing.assert_array_equal(
                    np.asarray(states[0].params[k]),
                    np.asarray(states[1].params[k]),
                )
            # Verify the re-partition arithmetic: replay the expected
            # post-reshard momentum. Positions covered by survivors' OLD
            # shards carried over; the departed member's positions
            # restarted at zero — then one more Nesterov update on the
            # new averaged delta.
            covered = np.zeros(total, bool)
            carried = np.zeros(total, np.float32)
            for r in (0, 1):
                for s, ln in old_meta[r][name]:
                    carried[s: s + ln] = full_mom[s: s + ln]
                    covered[s: s + ln] = True
            new_meta = [a._outer_shard_meta["ranges"][name] for a in algos[:2]]
            for r in (0, 1):
                tr_new = None
                for leaf in jax.tree_util.tree_leaves(
                    algos[r]._outer_state
                ):
                    tr_new = np.asarray(leaf)
                shard_len = sum(ln for _, ln in new_meta[r])
                assert tr_new.size == shard_len
            assert not covered.all(), (
                "test needs the departed member to have owned some "
                "positions, or the re-shard path is not exercised"
            )
        finally:
            store.shutdown()


class TestShardedDiLoCoInteg:
    def test_sharded_diloco_recovery(self):
        # Heal path: a replica dies mid-run, retries, heals from the
        # survivor (restoring the PEER's outer shard + meta), and the next
        # sync re-partitions. The model-identity oracle must still hold.
        results = _run_local_sgd_replicas(
            "diloco", num_replicas=2, num_syncs=4, sync_every=2,
            fail_at={1: 1}, sharded=True,
        )
        np.testing.assert_array_equal(
            results[0]["params"], results[1]["params"]
        )
        np.testing.assert_array_equal(
            results[0]["backup"], results[1]["backup"]
        )

    def test_sharded_diloco_survives_shrink(self):
        # Quorum shrink: one replica leaves for good after the first
        # sync; the survivors' outer state re-shards (the departed
        # member's momentum slice restarts cold) and training continues
        # to the target step with bit-identical survivors.
        results = _run_local_sgd_replicas(
            "diloco", num_replicas=3, num_syncs=3, sync_every=2,
            fail_at={}, sharded=True, stop_at={2: 1},
        )
        assert results[2] is None  # departed
        np.testing.assert_array_equal(
            results[0]["params"], results[1]["params"]
        )

    def test_sharded_q8_bf16_diloco_recovery(self):
        # The full perf configuration (q8 reduce wire + bf16 param wire)
        # under a heal: lossy wires must not break the identity oracle.
        results = _run_local_sgd_replicas(
            "diloco", num_replicas=2, num_syncs=3, sync_every=2,
            fail_at={1: 1}, sharded=True, shard_wire="q8",
            param_wire="bf16",
        )
        np.testing.assert_array_equal(
            results[0]["params"], results[1]["params"]
        )


def test_sharded_requires_f32_masters():
    # Mixed-dtype masters would pack into multiple groups and stall the
    # post-membership-change re-shard; rejected at construction instead.
    manager = _mock_manager()
    st = FTTrainState(
        {"w": jnp.ones((4,), jnp.bfloat16)}, optax.sgd(0.1)
    )
    with pytest.raises(ValueError, match="f32 master"):
        DiLoCo(manager, st, optax.sgd(0.7), sync_every=2, sharded=True)
