"""Adaptive fault-tolerance policy engine: runtime strategy selection one
level above the data plane.

Chameleon (arXiv:2508.21613, PAPERS.md) argues the fault-tolerance
*strategy* — not just its schedule — should be selected at runtime from
observed conditions: churn rate and effective bandwidth swing by orders of
magnitude over a long run (the 100k-GPU HSDP report, arXiv:2602.00277),
and no fixed strategy is right for all of it. This repo already ships
every strategy (per-step DDP over the plan/iso transports, LocalSGD,
DiLoCo with sharded outer sync, q8/bf16 wires) and every signal
(``Manager.signals()``: rolling churn rate from quorum-id bumps, effective
wire bandwidth from ``pop_op_stats``, heal-cost breakdowns). The
:class:`PolicyEngine` closes the loop: it watches the measured signals and
switches **strategy × wire × sync-interval** at outer-window boundaries.

Decision discipline (the same failure-hardened lockstep vote AdaptiveDDP
proves for schedule selection, one level up):

- every ``decide_every`` attempted steps, at a window boundary, the cohort
  runs ONE decision transaction: each member allgathers its signal vector
  through the manager, aggregates deterministically (slowest compute,
  bottleneck bandwidth, worst churn), prices every candidate with the same
  pure cost model, and takes the same argmin from identical data — no
  leader;
- an errored or structurally-unrunnable candidate carries a sentinel and
  can never win; ties (and anything within the hysteresis margin) fall to
  the CURRENT strategy, so the engine can never lose to standing still;
- the switch is itself a voted, latched step, split-brain-free by two
  stacked mechanisms. First, the decision rides ONE managed collective:
  a member failure mid-gather (died process, aborted ring, corrupted
  payload) propagates ring-wide through the native fail-fast discipline,
  every member's error latches, every member's commit vote fails, and the
  whole cohort aborts the transition together. Second, the narrow residue
  — a member that received the gather but failed before acting on it —
  discards the step locally, falls behind the cohort's committed step,
  and HEALS from a switched peer at the next quorum, adopting the donor's
  active strategy through the ``state_dict`` surface; and because
  mismatched ops fail fast cohort-wide, no data transaction can ever
  COMMIT under mixed strategies in between.

State carry across a switch reuses the engines' own machinery: entering a
windowed strategy re-anchors its window at the live params
(``begin_fresh_window``), DiLoCo outer-optimizer state persists across
tenures (and re-shards itself via the quorum-id-keyed partition check when
membership moved meanwhile), and error-feedback carries are dropped at the
tenure boundary (they belong to the superseded trajectory).
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .ddp import PipelinedDDP, ShardedDDP
from .local_sgd import AsyncDiLoCo, DiLoCo, LocalSGD
from .manager import Manager
from .train_state import FTTrainState

logger: logging.Logger = logging.getLogger(__name__)

# Cost recorded for a candidate that cannot run (structurally unavailable
# anywhere in the cohort, or marked failed after erroring): large enough to
# never win an argmin, finite so gathered arithmetic stays clean — the same
# sentinel discipline as AdaptiveDDP's probe.
SENTINEL_COST_S = 1e9

_WIRE_FACTOR = {None: 1.0, "bf16": 0.5, "q8": 0.25, "int8": 0.25}


def choose_target(costs: List[float], current: int, hysteresis: float) -> int:
    """Deterministic choice from cohort-identical costs: the argmin, but a
    challenger must beat the incumbent by the hysteresis margin — ties and
    near-ties stand still. A sentineled incumbent always loses (it cannot
    be run), unless everything is sentineled, in which case standing still
    is all that's left.

    Pure (PR-7 extraction pattern): every member feeds identical gathered
    costs through this and must reach the identical index — the property
    graftcheck's ``decision`` model exhaustively verifies, and the
    conformance suite pins this exact function to that model.
    """
    best = int(np.argmin(costs))
    if costs[best] >= SENTINEL_COST_S:
        # Everything is sentineled (a cohort-wide misconfiguration):
        # standing still is all that's left.
        return current
    cur = costs[current]
    if cur >= SENTINEL_COST_S:
        return best
    if costs[best] < cur * (1.0 - hysteresis):
        return best
    return current


@dataclass(frozen=True)
class StrategySpec:
    """One candidate point in the strategy × wire × sync-interval space.

    ``kind``: ``"ddp"`` (per-step, blocking transaction), ``"localsgd"``
    (windowed parameter averaging) or ``"diloco"`` (windowed outer
    optimizer on pseudogradients). ``sync_every`` is the outer window in
    inner steps (1 for ddp). ``wire`` compresses the sync payload
    (``None`` f32 | ``"bf16"`` | ``"q8"``). ``transport`` (ddp only)
    selects the data path: ``"legacy"`` managed ring, ``"plan"``
    persistent native comm plan, ``"iso"`` the isolated-child XLA plane.
    ``sharded``: for diloco, the weight-update-sharded outer sync
    (requires f32 masters and an elementwise outer optimizer); for ddp,
    the per-step ZeRO engine (:class:`~torchft_tpu.ddp.ShardedDDP` —
    reduce-scatter grads, ~1/W optimizer shard, bf16 param allgather;
    requires f32 masters and rides the sharded comm plan, so
    ``transport="plan"`` and the flat ring only). ``hier``
    (ddp/plan or diloco) runs the sync over the topology-aware
    hierarchical schedule (shm host rings -> intra-region rings -> the
    inter-region leader ring); such candidates are priced on the
    BOTTLENECK tier's measured bandwidth, not the folded flat average,
    and an un-hierarchical cohort latches them into the failure
    sentinel at runtime."""

    name: str
    kind: str
    sync_every: int = 1
    wire: Optional[str] = None
    transport: str = "legacy"
    sharded: bool = False
    hier: bool = False

    def __post_init__(self) -> None:
        if self.kind not in ("ddp", "localsgd", "diloco"):
            raise ValueError(f"unsupported strategy kind: {self.kind!r}")
        if self.kind == "ddp" and self.sync_every != 1:
            raise ValueError("ddp strategies are per-step (sync_every=1)")
        if self.kind != "ddp" and self.sync_every < 2:
            raise ValueError("windowed strategies need sync_every >= 2")
        if self.wire not in (None, "bf16", "q8"):
            raise ValueError(f"unsupported wire: {self.wire!r}")
        if self.transport not in ("legacy", "plan", "iso"):
            raise ValueError(f"unsupported transport: {self.transport!r}")
        if self.hier and self.kind == "localsgd":
            raise ValueError("localsgd has no hier schedule")
        if self.hier and self.kind == "ddp" and self.transport != "plan":
            raise ValueError("hier ddp rides the plan transport")
        if self.sharded and self.kind == "localsgd":
            raise ValueError("localsgd has no sharded form")
        if self.sharded and self.kind == "ddp":
            if self.transport != "plan":
                raise ValueError(
                    "sharded ddp rides the plan transport (the sharded "
                    "schedule IS a comm-plan form)"
                )
            if self.hier:
                raise ValueError(
                    "sharded ddp rides the flat ring (no hierarchical "
                    "reduce-scatter schedule is composed)"
                )

    def wire_factor(self) -> float:
        """Sync payload bytes relative to f32."""
        return _WIRE_FACTOR[self.wire]


def default_candidates(
    f32_masters: bool = True, topology_labeled: bool = False
) -> Tuple[StrategySpec, ...]:
    """The default ladder, ordered from tightest to loosest sync: per-step
    DDP (legacy and plan transports; plus ``ddp_sharded`` — the per-step
    ZeRO engine with q8 grad reduce-scatter, ~1/W optimizer shards and a
    bf16 param allgather — when the masters are f32), LocalSGD, and two
    DiLoCo(q8) window lengths — sharded outer sync when the masters are
    f32 (the ISSUE's ``DiLoCo(sharded, q8)`` point), plain q8 otherwise.
    Availability is still checked per cohort at construction (a diloco
    candidate without an outer optimizer or under an async-quorum manager
    simply can't win).

    ``topology_labeled`` (the AdaptiveDDP construction gate: this member
    carries TORCHFT_REGION or an explicit TORCHFT_HOST) adds the
    ``ddp_plan_hier`` candidate — the plan transport over the
    hierarchical (shm host tier / region tier) schedule, priced on the
    bottleneck tier's measured bandwidth. Unlabeled fleets keep the
    exact pre-hier ladder."""
    sharded = bool(f32_masters)
    ladder = [
        StrategySpec("ddp", "ddp"),
        StrategySpec("ddp_plan", "ddp", transport="plan"),
    ]
    if topology_labeled:
        ladder.append(
            StrategySpec("ddp_plan_hier", "ddp", transport="plan", hier=True)
        )
    if sharded:
        # Per-step ZeRO: q8 grad reduce-scatter + bf16 param allgather
        # through the sharded comm plan, optimizer state ~1/W. Wins
        # memory and update FLOPs; its wire term (q8 rs + bf16 ag) still
        # beats the f32 per-step candidates, though not fused q8 — the
        # cost model prices exactly that trade.
        ladder.append(
            StrategySpec(
                "ddp_sharded", "ddp", transport="plan", wire="q8",
                sharded=True,
            )
        )
    ladder += [
        StrategySpec("localsgd_h16", "localsgd", sync_every=16),
        StrategySpec(
            "diloco_q8_h16", "diloco", sync_every=16, wire="q8",
            sharded=sharded,
        ),
        StrategySpec(
            "diloco_q8_h64", "diloco", sync_every=64, wire="q8",
            sharded=sharded,
        ),
    ]
    return tuple(ladder)


@dataclass(frozen=True)
class CostKnobs:
    """Tunable weights of the cost model (env ``TORCHFT_POLICY_*``).

    ``staleness_weight``: convergence discount per inner step of window
    length — models that H-step-stale outer updates buy less progress per
    step than exact per-step sync, the term that makes per-step DDP win
    quiet fat links (0 optimizes raw step throughput only).
    ``sync_fixed_s``: per-sync fixed cost (packing, d2h, dispatch) added
    on top of the bytes/bandwidth wire term.
    ``surface_s``: how long a fault keeps poisoning the data plane before
    membership converges around it (≈ the failure-detection/lease window)
    — a fault inside this horizon of a transaction fails THAT transaction
    and discards the window, so windows shorter than the horizon are hit
    by essentially every fault while windows much longer than it absorb
    most faults in local compute.
    ``opt_mem_weight`` (env ``TORCHFT_POLICY_OPT_MEM``, default 0 =
    off): seconds of modeled cost per GiB of RESIDENT optimizer state —
    the memory-pressure term that lets ``ddp_sharded``'s ~1/W shard win
    against byte-equivalent unsharded candidates on memory-bound hosts.
    Pricing uses the adam-class estimate (2 f32 moments per master
    weight, / world for sharded-ddp candidates) rather than the measured
    ``opt_state_bytes`` signal: the measurement describes the ACTIVE
    strategy's residency, while every candidate must be priced by what
    it WOULD hold — the signal stays exported for observability and for
    validating the estimate."""

    staleness_weight: float = 0.05
    sync_fixed_s: float = 0.002
    hysteresis: float = 0.1
    surface_s: float = 1.0
    opt_mem_weight: float = 0.0

    @classmethod
    def from_env(cls) -> "CostKnobs":
        return cls(
            staleness_weight=float(
                os.environ.get("TORCHFT_POLICY_STALENESS", "0.05")
            ),
            sync_fixed_s=float(
                os.environ.get("TORCHFT_POLICY_SYNC_FIXED_S", "0.002")
            ),
            hysteresis=float(
                os.environ.get("TORCHFT_POLICY_HYSTERESIS", "0.1")
            ),
            surface_s=float(
                os.environ.get("TORCHFT_POLICY_SURFACE_S", "1.0")
            ),
            opt_mem_weight=float(
                os.environ.get("TORCHFT_POLICY_OPT_MEM", "0.0")
            ),
        )


def strategy_cost(
    spec: StrategySpec, signals: Dict[str, float], knobs: CostKnobs
) -> float:
    """Modeled seconds per EFFECTIVE inner step under ``signals`` — the
    pure function every member evaluates over identical aggregated data,
    so the argmin is cohort-identical by construction.

    Terms (all measured, none assumed):

    - inner compute: ``compute_s`` per step;
    - amortized sync: wire bytes (model bytes × wire factor) over the
      measured effective bandwidth, plus control cost (quorum + commit
      vote), divided by the window length;
    - churn: at measured fault rate λ, each fault costs a reconfigure,
      the UNHIDDEN part of a heal (a window of local steps hides up to
      (H-1)·compute of heal latency behind inner compute — the "longer
      windows as churn rises" effect), the expected cohort-wide discard
      when the fault lands mid-transaction, and the victim's lost half
      window (cohort-normalized) — the term that caps window growth;
    - staleness: a (1 + w·(H-1)) effective-progress discount, the term
      that keeps per-step DDP optimal on quiet fat links;
    - optimizer memory (off unless ``opt_mem_weight`` > 0): the modeled
      adam-class resident state (2 f32 moments per master weight),
      ~1/world for the sharded per-step engine — the term that lets
      ``ddp_sharded`` win on memory-bound hosts even though its wire
      (q8 rs + bf16 ag, factor 0.375) loses to fused q8 (0.25).
    """
    c = max(float(signals["compute_s"]), 1e-6)
    bw_mbps = float(signals.get("wire_eff_MBps") or 0.0)
    model_bytes = float(signals["model_bytes"])
    intra_bw = float(signals.get("tier_intra_MBps") or 0.0)
    inter_bw = float(signals.get("tier_inter_MBps") or 0.0)
    if spec.hier and (intra_bw > 0.0 or inter_bw > 0.0):
        # Hierarchical candidates are priced on the BOTTLENECK tier, not
        # the folded flat average: the schedule's phases are sequential,
        # so the wall is bounded below by its worst leg — the wire-
        # compressed inter hop at the measured inter bandwidth vs the
        # full-width intra/host legs (~2N per member: rs + ag) at the
        # measured intra bandwidth. An shm host tier simply makes the
        # host leg's measured bandwidth enormous, so it never bounds.
        legs = []
        if inter_bw > 0.0:
            legs.append(
                model_bytes * spec.wire_factor() / (inter_bw * (1 << 20))
            )
        if intra_bw > 0.0:
            legs.append(2.0 * model_bytes / (intra_bw * (1 << 20)))
        wire_s = max(legs)
    elif bw_mbps <= 0.0:
        # Unmeasured bandwidth: price syncs at the fixed cost only; the
        # first windows' op stats fill this in.
        wire_s = 0.0
    elif spec.kind == "ddp" and spec.sharded:
        # Two sequential legs over the same bottleneck link, each moving
        # ~half an allreduce's bytes: grad reduce-scatter at the shard
        # wire + the param allgather (bf16 when the shard wire is q8 —
        # ShardedDDP's "auto" default — else full f32). For the q8
        # default this folds to factor (0.25 + 0.5)/2 = 0.375: the
        # honest "wins memory/FLOPs, not bytes" accounting.
        ag_factor = 0.5 if spec.wire == "q8" else 1.0
        wire_s = (
            model_bytes * (spec.wire_factor() + ag_factor) / 2.0
            / (bw_mbps * (1 << 20))
        )
    else:
        wire_s = (
            model_bytes * spec.wire_factor() / (bw_mbps * (1 << 20))
        )
    sync_s = wire_s + knobs.sync_fixed_s
    ctrl_s = max(float(signals.get("ctrl_s") or 0.0), 0.0)
    h = float(spec.sync_every)
    t = c + (sync_s + ctrl_s) / h

    lam = max(float(signals.get("churn_per_min") or 0.0), 0.0) / 60.0
    if lam > 0.0:
        reconf_s = max(float(signals.get("reconf_s") or 0.0), 0.0)
        heal_s = max(float(signals.get("heal_s") or 0.0), 0.0)
        world = max(float(signals.get("world") or 1.0), 1.0)
        txn_s = sync_s + ctrl_s
        window_s = h * c + txn_s
        # A fault fails the transaction it lands in — and also the NEXT
        # one when it strikes within the surfacing horizon (the dead
        # member still holds its lease, so the ring forms around the
        # corpse and the op fails). Short windows are therefore hit by
        # essentially every fault; long windows absorb most faults in
        # local compute.
        p_txn = (
            min(1.0, (txn_s + knobs.surface_s) / window_s)
            if window_s > 0
            else 1.0
        )
        # A discarded transaction takes its whole window of inner work
        # with it (commit-or-rollback is window-granular).
        discard_s = p_txn * (h * c + sync_s)
        victim_s = (h * c / 2.0) / world
        exposed_heal_s = max(0.0, heal_s - (h - 1.0) * c)
        per_fault_s = reconf_s + exposed_heal_s + discard_s + victim_s
        # λ · per_fault is the fraction of wall time lost to faults;
        # goodput scales by (1 - loss), so cost scales by its inverse —
        # the saturating form matters exactly where strategies collapse
        # (a window longer than the fault interval almost never commits).
        t = t / max(1.0 - lam * per_fault_s, 0.05)

    cost = t * (1.0 + knobs.staleness_weight * (h - 1.0))
    if knobs.opt_mem_weight > 0.0:
        # Modeled resident optimizer state, NOT the measured
        # opt_state_bytes signal: every candidate is priced by what it
        # WOULD hold, and the pure model keeps the argmin
        # cohort-identical (see CostKnobs).
        mem_world = max(float(signals.get("world") or 1.0), 1.0)
        share = (
            1.0 / mem_world
            if (spec.kind == "ddp" and spec.sharded)
            else 1.0
        )
        cost += (
            knobs.opt_mem_weight * 2.0 * model_bytes * share / float(1 << 30)
        )
    return cost


class PolicyEngine:
    """Runtime strategy selection over per-step DDP, LocalSGD and DiLoCo.

    Usage (identical train-loop surface to AdaptiveDDP)::

        policy = PolicyEngine(manager, state, grad_fn, outer_tx=outer_sgd)
        for batch in batches:
            loss = policy.step(batch)
        policy.flush()

    Wire the manager's state callbacks to :meth:`state_dict` /
    :meth:`load_state_dict` so recovering replicas adopt the donor's
    ACTIVE strategy and window bookkeeping along with the weights.

    ``grad_fn(params, *batch) -> (loss, grads)`` — the same contract as
    PipelinedDDP. ``outer_tx`` is the DiLoCo outer optimizer (elementwise,
    e.g. SGD+Nesterov); without one, diloco candidates are structurally
    unavailable and record sentinels. DiLoCo candidates also require a
    sync-quorum manager (``use_async_quorum=False``), like DiLoCo itself.

    Knobs (env, all documented in docs/OPERATIONS.md):
    ``TORCHFT_POLICY_DECIDE_EVERY`` (attempted steps between decision
    transactions, default 32), ``TORCHFT_POLICY_HYSTERESIS`` (relative
    margin a challenger must beat the incumbent by, default 0.1),
    ``TORCHFT_POLICY_STALENESS``, ``TORCHFT_POLICY_SYNC_FIXED_S`` (cost
    model, see :class:`CostKnobs`), ``TORCHFT_POLICY_CHURN_WINDOW_S``
    (trailing window of the churn-rate signal, default 600).
    """

    def __init__(
        self,
        manager: Manager,
        state: FTTrainState,
        grad_fn: Callable[..., Tuple[Any, Any]],
        outer_tx: Any = None,
        candidates: Optional[Sequence[StrategySpec]] = None,
        decide_every: Optional[int] = None,
        knobs: Optional[CostKnobs] = None,
        initial: Optional[str] = None,
    ) -> None:
        self._manager = manager
        self._state = state
        self._grad_fn = grad_fn
        self._outer_tx = outer_tx
        if candidates is None:
            candidates = default_candidates(
                f32_masters=self._masters_are_f32(),
                topology_labeled=bool(
                    getattr(manager, "_region", "")
                    or os.environ.get("TORCHFT_REGION", "")
                    or os.environ.get("TORCHFT_HOST", "")
                ),
            )
        self._candidates: List[StrategySpec] = list(candidates)
        if not self._candidates:
            raise ValueError("need at least one candidate strategy")
        names = [c.name for c in self._candidates]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate candidate names: {names}")
        self._avail = [self._structurally_available(c) for c in self._candidates]
        if not any(self._avail):
            raise ValueError(
                "no candidate strategy is runnable here (diloco needs "
                "outer_tx and a sync-quorum manager; iso needs an iso "
                "plane)"
            )
        # Runtime failure latch, cleared when membership changes (a new
        # cohort deserves a fresh verdict — AdaptiveDDP's re-probe rule).
        self._failed = [False] * len(self._candidates)
        if initial is None:
            self._current = next(
                i for i, ok in enumerate(self._avail) if ok
            )
        else:
            self._current = names.index(initial)
            if not self._avail[self._current]:
                raise ValueError(f"initial strategy {initial!r} unavailable")
        if decide_every is None:
            decide_every = int(
                os.environ.get("TORCHFT_POLICY_DECIDE_EVERY", "32")
            )
        self._decide_every = max(int(decide_every), 1)
        self._knobs = knobs if knobs is not None else CostKnobs.from_env()
        self._churn_window_s = float(
            os.environ.get("TORCHFT_POLICY_CHURN_WINDOW_S", "600")
        )
        self._model_bytes = self._count_model_bytes()
        self._engines: Dict[str, Any] = {}
        # Lockstep clocks: ticks advance once per step() on every member;
        # the decision epoch counts decision transactions. Both ride the
        # state_dict so healed members resume aligned.
        self._ticks = 0
        self._last_decide_tick = 0
        self._decide_epoch = 0
        self._decide_qid: Optional[int] = None
        # Measured-signal accumulators (local; cohort-aggregated at
        # decision time through the gather).
        self._compute_samples: deque = deque(maxlen=64)
        self._consec_errors = 0
        self._error_backstop = 8
        self.last_commit: Optional[bool] = None
        self.decisions: List[dict] = []

    # -- construction-time capability checks --

    def _masters_are_f32(self) -> bool:
        import jax

        leaves = jax.tree_util.tree_leaves(self._state.params)
        return bool(leaves) and all(
            np.dtype(getattr(l, "dtype", np.float32)) == np.float32
            for l in leaves
        )

    def _structurally_available(self, spec: StrategySpec) -> bool:
        """Whether this member can run ``spec`` at all. Structural gates
        only — runtime failures are the sentinel latch's business. The
        verdict is still cohort-ANDed through the decision gather, so a
        heterogeneous cohort converges on the common subset."""
        if spec.kind == "ddp":
            if spec.transport == "iso":
                return bool(
                    getattr(self._manager, "has_iso_plane", lambda: False)()
                )
            if spec.sharded and not self._masters_are_f32():
                # ShardedDDP's shard/gather arithmetic is defined on f32
                # masters (the sharded plan carries one flat f32 group).
                return False
            return True
        if spec.kind == "localsgd":
            return True
        # diloco: needs the outer optimizer and an eager-heal manager
        if self._outer_tx is None:
            return False
        if getattr(self._manager, "_use_async_quorum", False):
            return False
        if spec.sharded and not self._masters_are_f32():
            return False
        if spec.sharded and spec.wire == "bf16":
            # the sharded outer sync has no bf16 REDUCE wire (bf16 serves
            # its param allgather leg, a different knob)
            return False
        return True

    def _count_model_bytes(self) -> int:
        import jax

        return int(
            sum(
                int(np.prod(getattr(l, "shape", ()) or (1,))) * 4
                for l in jax.tree_util.tree_leaves(self._state.params)
            )
        )

    # -- engines --

    @property
    def strategy(self) -> StrategySpec:
        """The active strategy."""
        return self._candidates[self._current]

    def _engine(self, spec: StrategySpec) -> Any:
        eng = self._engines.get(spec.name)
        if eng is not None:
            return eng
        if spec.kind == "ddp" and spec.sharded:
            eng = ShardedDDP(
                self._manager, self._state, self._grad_fn,
                shard_wire=spec.wire,
            )
        elif spec.kind == "ddp":
            eng = PipelinedDDP(
                self._manager, self._state, self._grad_fn,
                compress=spec.wire, transport=spec.transport,
                hier=spec.hier,
            )
        elif spec.kind == "localsgd":
            eng = LocalSGD(self._manager, self._state, spec.sync_every)
        elif spec.sharded:
            eng = DiLoCo(
                self._manager, self._state, self._outer_tx,
                spec.sync_every, sharded=True, shard_wire=spec.wire,
            )
        else:
            # Unsharded DiLoCo over a compressed wire: AsyncDiLoCo with
            # overlap=False IS synchronous DiLoCo through the same jitted
            # ops, and carries the q8/bf16 pseudogradient pipeline.
            eng = AsyncDiLoCo(
                self._manager, self._state, self._outer_tx,
                spec.sync_every, compress=spec.wire, overlap=False,
            )
        self._engines[spec.name] = eng
        return eng

    # -- train-loop surface --

    def step(self, *batch: Any) -> Any:
        """One training step under the active strategy; runs the decision
        transaction at window boundaries every ``decide_every`` attempted
        steps. Returns the loss."""
        spec = self._candidates[self._current]
        eng = self._engine(spec)
        t0 = time.perf_counter()
        synced = True
        if spec.kind == "ddp":
            loss = eng.blocking_step(*batch)
            self.last_commit = eng.last_commit
        else:
            loss, grads = self._grad_fn(self._state.params, *batch)
            eng.step(grads)
            synced = eng._local_step == 0
            if synced:
                self.last_commit = eng.last_sync_commit
        wall = time.perf_counter() - t0
        self._ticks += 1
        self._observe(spec, wall, synced)

        errored = synced and self.last_commit is False
        # The consecutive-error run counts TRANSACTIONS: inner (non-sync)
        # steps of a windowed strategy carry no verdict and must not
        # reset the counter, or the backstop could never trip for any
        # windowed strategy.
        tripped = self._note_errored(errored) if synced else False
        if not tripped:
            # Errored boundaries still decide: a data-plane error is
            # cohort-visible (ring failures fail fast everywhere), so the
            # cadence stays lockstep — and a strategy whose windows keep
            # dying under a fault storm must not get to postpone the
            # decision that would replace it until the storm ends.
            at_boundary = spec.kind == "ddp" or eng._local_step == 0
            if (
                at_boundary
                and self._ticks - self._last_decide_tick >= self._decide_every
            ):
                self._last_decide_tick = self._ticks
                self._decide_and_maybe_switch()
        return loss

    def flush(self) -> bool:
        """Settles anything the active strategy left in flight (call once
        after the loop); returns the last transaction's outcome."""
        eng = self._engines.get(self._candidates[self._current].name)
        if eng is None:
            return bool(self.last_commit)
        if isinstance(eng, (PipelinedDDP, ShardedDDP)):
            return eng.flush()
        if isinstance(eng, AsyncDiLoCo):
            eng.flush()
        return bool(self.last_commit)

    # -- measurement --

    def _observe(self, spec: StrategySpec, wall: float, synced: bool) -> None:
        """Folds one step's wall time into the local signal accumulators
        and routes the data plane's op stats through the manager (which
        maintains the rolling bandwidth estimate)."""
        entries = self._manager.observe_op_stats()
        if spec.kind != "ddp" and not synced:
            # A pure inner step: compute, no transaction — the cleanest
            # compute_s sample there is.
            self._compute_samples.append(wall)
        elif spec.kind == "ddp":
            # Subtract the measured op phases from the step wall; quorum
            # overlaps compute, so what remains approximates compute.
            op_s = sum(
                st.get("pack", 0.0) + st.get("d2h", 0.0)
                + st.get("ring", 0.0) + st.get("h2d", 0.0)
                for st in entries
            )
            self._compute_samples.append(max(wall - op_s, 1e-5))

    def _signal_vector(self) -> np.ndarray:
        """This member's measured conditions + per-candidate availability,
        as the fixed-length float64 vector the decision gather ships."""
        sig = self._manager.signals(self._churn_window_s)
        snap = self._manager.metrics().snapshot()
        timers = snap["timers_s"]

        def _p50(name: str) -> float:
            t = timers.get(name) or {}
            return float(t.get("p50") or 0.0)

        heal = sig.get("heal") or {}
        heal_fetch = (heal.get("fetch_s") or {}).get("p50") or 0.0
        heal_apply = (heal.get("apply_s") or {}).get("p50") or 0.0
        # Weight the heal cost by how often churn ACTUALLY heals: a
        # cohort-wide transaction abort bumps the churn clock but heals
        # nobody (everyone rolled back together), while a real kill heals
        # its victim every time. Charging a full heal per churn event
        # would otherwise let a single early heal dominate the fault term
        # at high abort rates.
        churn_n = (snap["events"].get("churn") or {}).get("n", 0)
        heal_frac = min(
            1.0, snap["counters"].get("heals", 0) / max(churn_n, 1)
        )
        compute_s = (
            float(np.median(self._compute_samples))
            if self._compute_samples
            else 0.0
        )
        tiers = sig.get("tier_eff_MBps") or {}
        head = [
            1.0,  # ok marker: a zeroed (non-participating) entry drops out
            compute_s,
            float(sig.get("wire_eff_MBps") or 0.0),
            float(sig.get("churn_per_min") or 0.0),
            _p50("quorum") + _p50("commit_vote"),
            _p50("reconfigure"),
            (float(heal_fetch) + float(heal_apply)) * heal_frac,
            # Per-tier measured bandwidth of the hierarchical schedule
            # (0 = unmeasured): what prices hier/shm candidates on the
            # bottleneck tier instead of the folded flat average.
            float(tiers.get("intra") or 0.0),
            float(tiers.get("inter") or 0.0),
            # Measured resident optimizer-state bytes (0 until a sharded
            # engine reports): observability + model validation — the
            # cost model prices candidates by the pure estimate instead.
            float(sig.get("opt_state_bytes") or 0.0),
        ]
        avail = [1.0 if a else 0.0 for a in self._avail]
        failed = [1.0 if f else 0.0 for f in self._failed]
        return np.asarray(head + avail + failed, np.float64)

    def _aggregate(self, entries: List[np.ndarray]) -> Dict[str, Any]:
        """Cohort-aggregates gathered signal vectors into ONE deterministic
        signal dict: slowest compute paces the cohort, the bottleneck
        connection bounds every sync, the worst churn is everyone's churn.
        Zeroed entries (healing/spare members) and non-finite residue are
        excluded."""
        k = len(self._candidates)
        live = [
            e for e in entries
            if e.shape == (10 + 2 * k,) and np.isfinite(e).all() and e[0] > 0.5
        ]
        if not live:
            raise RuntimeError("no live signal entries in decision gather")
        mat = np.stack(live)
        bws = mat[:, 2]
        bws = bws[bws > 0.0]

        def _tier_min(col: int) -> float:
            # Bottleneck across members, like the flat bandwidth: the
            # slowest member's measured tier bounds every phase.
            v = mat[:, col]
            v = v[v > 0.0]
            return float(v.min()) if v.size else 0.0

        avail = mat[:, 10:10 + k].min(axis=0)  # AND across members
        failed = mat[:, 10 + k:].max(axis=0)  # OR across members
        return {
            "compute_s": float(mat[:, 1].max()),
            "wire_eff_MBps": float(bws.min()) if bws.size else 0.0,
            "churn_per_min": float(mat[:, 3].max()),
            "ctrl_s": float(mat[:, 4].max()),
            "reconf_s": float(mat[:, 5].max()),
            "heal_s": float(mat[:, 6].max()),
            "tier_intra_MBps": _tier_min(7),
            "tier_inter_MBps": _tier_min(8),
            "opt_state_bytes": float(mat[:, 9].max()),
            "world": float(len(live)),
            "model_bytes": float(self._model_bytes),
            "avail": avail,
            "failed": failed,
        }

    def _costs(self, agg: Dict[str, Any]) -> List[float]:
        costs = []
        for i, spec in enumerate(self._candidates):
            if agg["avail"][i] < 0.5 or agg["failed"][i] > 0.5:
                costs.append(SENTINEL_COST_S)
            else:
                costs.append(strategy_cost(spec, agg, self._knobs))
        return costs

    def _choose(self, costs: List[float]) -> int:
        return choose_target(costs, self._current, self._knobs.hysteresis)

    # -- the decision transaction --

    def _decide_and_maybe_switch(self) -> None:
        """ONE voted, latched transaction: gather signals, compute the
        cohort-agreed target, vote. A failed gather latches EVERY member
        (ring failures propagate), so the cohort aborts together; a
        member that fails after the gather discards locally, lags, and
        heals into the cohort's choice (see the module docstring's
        split-brain analysis). Identical gathered data + a pure choice
        function = identical targets everywhere the gather succeeded."""
        m = self._manager
        m.start_quorum()
        qid: Optional[int] = None
        target = self._current
        agg: Optional[Dict[str, Any]] = None
        costs: Optional[List[float]] = None
        try:
            qid = m.quorum_id()
            if qid != self._decide_qid:
                # Membership changed since the last decision: failure
                # verdicts belong to the old cohort. Reset BEFORE building
                # the signal vector, so the fresh cohort's very first
                # decision doesn't gather the stale sentinels.
                self._failed = [False] * len(self._candidates)
            gathered = m.allgather(
                {"policy_sig": self._signal_vector()}
            ).wait()
            if m.errored() is None:
                agg = self._aggregate(
                    [
                        np.asarray(e["policy_sig"], np.float64)
                        for e in gathered
                    ]
                )
                costs = self._costs(agg)
                target = self._choose(costs)
        except Exception as e:  # noqa: BLE001 - latch, vote, stand still
            logger.exception("policy decision failed: %s", e)
            m.report_error(e)
            target = self._current
        # Control transaction: the committed-step counter must advance (it
        # is the cohort's transaction clock) but no batch was trained, so
        # batches_committed must not inflate.
        committed = m.should_commit(count_batches=False)
        switched = committed and target != self._current
        decision = {
            "epoch": self._decide_epoch,
            "tick": self._ticks,
            "from": self._candidates[self._current].name,
            "to": self._candidates[target].name,
            "committed": bool(committed),
            "switched": bool(switched),
            "signals": {
                k: v
                for k, v in (agg or {}).items()
                if k not in ("avail", "failed")
            },
            "costs": {
                spec.name: round(float(c), 6)
                for spec, c in zip(self._candidates, costs or [])
            },
        }
        self._decide_epoch += 1
        self._decide_qid = qid if qid is not None else self._decide_qid
        self.decisions.append(decision)
        metrics = m.metrics()
        metrics.incr("policy_decisions")
        if switched:
            self._adopt(target)
            metrics.incr("policy_switches")
            logger.info(
                "policy switch %s -> %s (signals=%s)",
                decision["from"], decision["to"], decision["signals"],
            )
        elif committed:
            metrics.incr(f"policy_mode_{self._candidates[self._current].name}")
        else:
            metrics.incr("policy_decision_aborts")
        m.push_status(
            {
                "policy": {
                    "strategy": self._candidates[self._current].name,
                    "epoch": self._decide_epoch,
                    "decisions": len(self.decisions),
                }
            }
        )

    def _adopt(self, target: int) -> None:
        """Hands control to ``target``'s engine at the (boundary) switch
        point: windowed engines re-anchor their window at the live params
        (keeping DiLoCo outer state — momentum survives a round trip);
        DDP engines drop stale per-trajectory carries."""
        self._current = target
        spec = self._candidates[target]
        eng = self._engine(spec)
        if spec.kind == "ddp":
            eng.last_commit = None
            if isinstance(eng, ShardedDDP):
                # Tenure boundary for the sharded engine: void the
                # quorum-keyed shard meta so the first step under the new
                # tenure re-partitions against the live cohort, and let
                # the optimizer restart from a deterministic fresh init —
                # every member computes it from cohort-identical params,
                # so cross-member identity holds through the switch.
                eng.begin_fresh_shard()
            else:
                eng._residual = None
                eng._prev_residual = None
            if spec.transport == "plan" and spec.wire == "q8":
                # the NATIVE q8ef carry lives in the comm plan, not in
                # eng._residual — same tenure-boundary reset discipline
                self._manager.reset_plan_feedback()
        else:
            eng.begin_fresh_window()
        self._manager.metrics().incr(f"policy_mode_{spec.name}")

    def _note_errored(self, errored: bool) -> bool:
        """Sustained-failure backstop: a run of consecutive errored
        transactions marks the CURRENT strategy failed (sentinel — it can
        never win again this cohort) and falls back to the base strategy
        immediately. Errors are cohort-visible (the commit vote fails for
        everyone), so every member trips this at the same step."""
        if not errored:
            self._consec_errors = 0
            return False
        self._consec_errors += 1
        if self._consec_errors < self._error_backstop:
            return False
        self._consec_errors = 0
        base = next(i for i, ok in enumerate(self._avail) if ok)
        if self._current != base:
            self._failed[self._current] = True
            self._manager.metrics().incr("policy_backstops")
            logger.warning(
                "policy backstop: %s errored %d consecutive transactions; "
                "falling back to %s",
                self._candidates[self._current].name, self._error_backstop,
                self._candidates[base].name,
            )
            self._adopt(base)
        return True

    # -- checkpoint plumbing (manager state callbacks) --

    def state_dict(self) -> Dict[str, Any]:
        spec = self._candidates[self._current]
        if spec.kind == "ddp" and spec.sharded:
            # The sharded engine's own surface: ships the donor's opt
            # shard + quorum-keyed meta; the recipient voids the meta on
            # load so its first step re-partitions under the live cohort.
            inner: Dict[str, Any] = self._engine(spec).state_dict()
        elif spec.kind == "ddp":
            inner = {"state": self._state.state_dict()}
        else:
            inner = self._engine(spec).state_dict()
        return {
            "inner": inner,
            "policy": {
                "current": self._current,
                "ticks": self._ticks,
                "last_decide_tick": self._last_decide_tick,
                "decide_epoch": self._decide_epoch,
                "failed": list(self._failed),
            },
        }

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        pol = sd["policy"]
        self._current = int(pol["current"])
        self._ticks = int(pol["ticks"])
        self._last_decide_tick = int(pol["last_decide_tick"])
        self._decide_epoch = int(pol["decide_epoch"])
        self._failed = [bool(f) for f in pol["failed"]]
        spec = self._candidates[self._current]
        if spec.kind == "ddp" and spec.sharded:
            self._engine(spec).load_state_dict(sd["inner"])
        elif spec.kind == "ddp":
            self._state.load_state_dict(sd["inner"]["state"])
        else:
            self._engine(spec).load_state_dict(sd["inner"])
        self._consec_errors = 0
