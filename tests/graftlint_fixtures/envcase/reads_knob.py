# graftlint fixture: reads a TORCHFT_* knob the fixture docs don't
# mention (and one they do, as the clean control).
import os

UNDOCUMENTED = os.environ.get("TORCHFT_FIXTURE_UNDOCUMENTED", "0")
DOCUMENTED = os.getenv("TORCHFT_FIXTURE_DOCUMENTED")
