"""Measures the data-plane overlap pipeline (VERDICT round 1 item 3).

Times a gradient-sized allreduce through a real 2-member host ring with the
chunked pipeline ON (d2h DMA / TCP ring / h2d upload overlapped) vs OFF
(sequential single-shot per dtype group), from this host's accelerator.
The payload is sized at ~10x the flagship bench model's gradients, where
the transfer+ring cost is the dominant fault-tolerance overhead.

Writes OVERLAP_BENCH.json and prints one summary line per config.

Usage: python bench_overlap.py [--peer <store_addr>]
"""

import json
import os
import subprocess
import sys
import time
from datetime import timedelta

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

N_LEAVES = 64
TOTAL_MB = 256  # ~64M f32 elements ~= 10x the bench model's ~25M params
ITERS = 3


def _tree(fill: float):
    import jax.numpy as jnp

    n = TOTAL_MB * (1 << 20) // 4 // N_LEAVES
    return {f"g{i}": jnp.full((n,), fill, jnp.float32) for i in range(N_LEAVES)}


PHASES = (("single_shot", 1), ("pipelined", 8))


def peer(store_addr: str) -> None:
    from torchft_tpu.platform import apply_jax_platform_env

    apply_jax_platform_env()
    from torchft_tpu.collectives import HostCollectives, ReduceOp

    zeros = _tree(0.0)
    for phase, (_, chunks) in enumerate(PHASES):
        # One ring + one HostCollectives per phase, chunk config matching
        # the main side exactly — the chunk schedule is part of the wire
        # contract (configure() validates it).
        hc = HostCollectives(timeout=timedelta(seconds=600),
                             connect_timeout=timedelta(seconds=600),
                             pipeline_chunks=chunks)
        hc.configure(f"{store_addr}/overlap{phase}", 1, 2)
        for _ in range(1 + ITERS):  # warm + timed
            hc.allreduce(zeros, ReduceOp.SUM).wait()
        hc.shutdown()


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--peer":
        peer(sys.argv[2])
        return

    import jax

    from torchft_tpu import Store
    from torchft_tpu.collectives import HostCollectives, ReduceOp

    store = Store()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    peer_proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--peer", store.address()],
        env=env,
    )

    tree = _tree(1.0)
    jax.block_until_ready(tree)
    report = {
        "platform": jax.devices()[0].platform,
        "payload_MB": TOTAL_MB,
        "leaves": N_LEAVES,
        "iters": ITERS,
    }
    try:
        for phase, (name, chunks) in enumerate(PHASES):
            hc = HostCollectives(
                timeout=timedelta(seconds=600),
                connect_timeout=timedelta(seconds=600),
                pipeline_chunks=chunks,
            )
            hc.configure(f"{store.address()}/overlap{phase}", 0, 2)
            out = hc.allreduce(tree, ReduceOp.SUM).wait()  # warm (jit pack)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(ITERS):
                out = hc.allreduce(tree, ReduceOp.SUM).wait()
                jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / ITERS
            report[name] = {"s": round(dt, 3),
                            "MBps": round(TOTAL_MB / dt, 1)}
            print(f"{name} (chunks={chunks}): {dt:.3f}s "
                  f"{TOTAL_MB / dt:.1f} MB/s", flush=True)
            hc.shutdown()
        report["speedup"] = round(
            report["single_shot"]["s"] / report["pipelined"]["s"], 3
        )
        assert peer_proc.wait(timeout=600) == 0
    finally:
        if peer_proc.poll() is None:
            peer_proc.kill()
        store.shutdown()

    with open(os.path.join(REPO, "OVERLAP_BENCH.json"), "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps({"overlap_speedup": report["speedup"]}))


if __name__ == "__main__":
    main()
