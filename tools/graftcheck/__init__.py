"""graftcheck: exhaustive protocol model checking for torchft_tpu.

Six protocol cores are extracted as pure transition systems and swept
exhaustively (bounded depth, state-hash dedup) against the chaos-plane
invariants:

- ``step_txn``  -- per-step AND-vote commit (epoch purity, no silent
  commit over a latched error)
- ``lease``     -- lease membership + hierarchy digests (heartbeat
  monotonicity, no expired member in a formed quorum)
- ``wal``       -- WAL-fenced root promises + epoch-fenced takeover
  (promise durability, quorum_id monotonicity, single publisher)
- ``durable``   -- durable manifest ladder (a commit record implies a
  complete restorable set; a torn tail never wins)
- ``decision``  -- policy decision transaction (identical argmin or
  cohort-wide abort; never adopt a sentineled strategy)
- ``serving``   -- serving install ladder (no torn install past the
  nonce/CRC/digest gates)

Every model ships deliberately *broken* variants (``BROKEN``) proving
the checker finds the bug each fence exists to prevent; violations
print a replay line in the established ``chaos_run.py`` format.

Use ``make(name, broken)`` to build a model and ``core.explore`` /
``core.replay`` to drive it; ``scripts/graftcheck.py`` is the CLI.
"""

from __future__ import annotations

from . import decision, durable, lease, serving, step_txn, wal
from .core import (  # noqa: F401  (re-exported API)
    Counterexample,
    Exploration,
    Model,
    ReplayError,
    explore,
    replay,
)

_MODULES = {
    "step_txn": step_txn,
    "lease": lease,
    "wal": wal,
    "durable": durable,
    "decision": decision,
    "serving": serving,
}

MODEL_NAMES = tuple(_MODULES)


def make(name: str, broken: str = "") -> Model:
    """Build a registered model (optionally one of its broken variants)."""
    try:
        mod = _MODULES[name]
    except KeyError:
        raise KeyError(
            "unknown model %r (have: %s)" % (name, ", ".join(_MODULES))
        )
    return mod.make(broken)


def broken_variants(name: str) -> tuple:
    """The deliberately-broken variant names a model ships."""
    return tuple(_MODULES[name].BROKEN)
