"""Benchmark: fault-tolerant training throughput on the flagship model.

Measures the FULL fault-tolerance path against a raw jitted train loop on
the same model and hardware — with a REAL cross-replica-group data plane: a
second replica group (peer process on host CPU) joins the quorum and the
host TCP ring, so every cross-group byte is actually packed, shipped, and
unpacked (no world-size-1 identity shortcut).

Three configurations are measured (details in BENCH_DETAIL.json):

  raw         jitted loss/grad/apply loop, no FT machinery.
  ft_ddp      per-step gradient allreduce through the ring (the reference
              train_ddp mode). On this host the device<->host tunnel runs at
              ~50 MB/s (vs ~10 GB/s PCIe on production TPU hosts), so
              per-step shipping of full f32 gradients is tunnel-bound; it is
              measured over a few steps and reported for completeness.
  ft_diloco   AsyncDiLoCo — the bandwidth-appropriate cross-group mode this
              framework ships for DCN-class links: inner steps stay on-chip,
              the pseudogradient sync runs through the ring asynchronously,
              overlapped with the next window's compute, and the outer
              update lands one window late. Full FT machinery (quorum +
              commit vote) every window. THIS is the headline metric.

The reference publishes no absolute numbers (BASELINE.md); the driver-set
north star is >= 90% of healthy-state throughput. The printed line reports
``vs_baseline = (ft_diloco_steps_per_sec / raw_steps_per_sec) / 0.90`` — 1.0
means exactly the 90% bar, > 1.0 beats it. Throughput *under churn* is
measured separately by bench_churn.py (CHURN_BENCH.json).

Prints ONE JSON line, e.g.:
{"metric": "steps_per_sec_ft", "value": 42.1, "unit": "steps/s", "vs_baseline": 1.01}
"""

import argparse
import json
import os
import subprocess
import sys
import time
from datetime import timedelta

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

SYNC_EVERY = 128  # AsyncDiLoCo window (inner steps per cross-group sync)


def _model_setup():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from torchft_tpu.models import TransformerConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = TransformerConfig(
        vocab_size=8192,
        d_model=512,
        n_heads=8,
        n_layers=6 if on_tpu else 2,
        d_ff=2048,
        max_seq_len=512,
    )
    batch_size = 16 if on_tpu else 4
    seq_len = 512 if on_tpu else 128
    rng = np.random.default_rng(0)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq_len), dtype=np.int32)
    )
    return cfg, batch, on_tpu


def _barrier(tree) -> None:
    # Readback barrier: on the tunneled TPU, block_until_ready returns
    # before remote execution drains, so force a tiny device read.
    import jax
    import numpy as np

    jax.block_until_ready(tree)
    leaf = jax.tree_util.tree_leaves(tree)[0]
    np.asarray(leaf.ravel()[0:1])


def peer() -> None:
    """CPU ring peer: a second replica group that paces the quorum and the
    ring (contributing zeros) so the main process's data plane is real."""
    from torchft_tpu.platform import apply_jax_platform_env

    apply_jax_platform_env()

    import jax
    import jax.numpy as jnp

    from torchft_tpu import HostCollectives, Manager
    from torchft_tpu.models import init_params

    cfg, _, _ = _model_setup()
    params = init_params(cfg, jax.random.PRNGKey(0))
    wire_dtype = (
        jnp.bfloat16 if os.environ.get("BENCH_PEER_DTYPE") == "bf16" else None
    )
    zeros = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, wire_dtype or l.dtype), params
    )

    state = {"params": params}
    collectives = HostCollectives(timeout=timedelta(seconds=300))
    manager = Manager(
        collectives=collectives,
        load_state_dict=state.update,
        state_dict=lambda: dict(state),
        min_replica_size=1,
        timeout=timedelta(seconds=300),  # rides out main-side jit compiles
        quorum_timeout=timedelta(seconds=300),
        rank=0,
        world_size=1,
        lighthouse_addr=os.environ["TORCHFT_LIGHTHOUSE"],
        replica_id="bench_peer",
    )
    # Signal readiness: heartbeats are flowing, so the main side's quorum
    # holds the door (join timeout) until our first quorum request lands.
    open(os.environ["BENCH_PEER_READY"], "w").close()
    # Hold until the main side joins: committing a solo quorum here would
    # advance our step and make the zero-contributing peer the recovery
    # primary for the main process. A quorum containing both sides can only
    # have formed from simultaneous requests, so the barrier's final quorum
    # IS the main side's round-0 quorum — reuse it (starting another here
    # would leave this peer one quorum ahead and deadlock the ring).
    # allow_heal=False throughout: the synthetic peer must never trigger
    # recovery transfers (a step-0 init sync would push the full state dict
    # through the device tunnel mid-compile on the main side).
    manager.start_quorum(allow_heal=False)
    manager.wait_quorum()
    while manager.num_participants() < 2:
        time.sleep(0.1)
        manager.start_quorum(allow_heal=False)
        manager.wait_quorum()
    print(f"peer: joined ring, participants={manager.num_participants()}",
          flush=True)
    # The peer never votes/commits: its step stays 0, so it can never
    # out-step a (transiently failing) main side and become its recovery
    # source, and it drops out of the max-step cohort after round 0 — the
    # main side's gradient divisor reflects real contributors only.
    rounds = int(os.environ["BENCH_PEER_ROUNDS"])
    for i in range(rounds):
        if i > 0:
            manager.start_quorum(allow_heal=False)
        manager.allreduce(zeros).wait()  # paced by the main side's ring op
        print(f"peer: round {i} done participants="
              f"{manager.num_participants()}", flush=True)
    manager.shutdown()
    collectives.shutdown()


def _spawn_peer(lighthouse_addr: str, rounds: int, dtype: str) -> subprocess.Popen:
    ready = os.path.join(REPO, f".bench_peer_ready_{os.getpid()}_{dtype}")
    if os.path.exists(ready):
        os.unlink(ready)
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "TORCHFT_LIGHTHOUSE": lighthouse_addr,
        "BENCH_PEER_ROUNDS": str(rounds),
        "BENCH_PEER_DTYPE": dtype,
        "BENCH_PEER_READY": ready,
        "TORCHFT_TPU_LOG": "info",
    }
    log = open(os.path.join(REPO, f".bench_peer_{dtype}.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--peer"],
        env=env,
        cwd=REPO,
        stdout=log,
        stderr=subprocess.STDOUT,
    )
    deadline = time.time() + 300
    while not os.path.exists(ready) and time.time() < deadline:
        time.sleep(0.2)
    os.unlink(ready)
    return proc


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--peer", action="store_true")
    args = parser.parse_args()
    if args.peer:
        peer()
        return

    import jax
    import numpy as np
    import optax

    from torchft_tpu import (
        AsyncDiLoCo,
        FTTrainState,
        HostCollectives,
        Lighthouse,
        Manager,
        OptimizerWrapper,
    )
    from torchft_tpu.models import init_params, loss_fn

    cfg, batch, on_tpu = _model_setup()
    warmup, steps = 5, 30 if on_tpu else 15
    tx = optax.adamw(1e-3)
    grad_fn = jax.jit(jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b)))

    def apply_fn_raw(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    apply_jit = jax.jit(apply_fn_raw, donate_argnums=(0, 1))

    detail = {"host": {"cpus": os.cpu_count(), "platform": jax.devices()[0].platform}}

    # -- raw loop --
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt_state = tx.init(params)
    for _ in range(warmup):
        loss, grads = grad_fn(params, batch)
        params, opt_state = apply_jit(params, opt_state, grads)
    _barrier(params)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, grads = grad_fn(params, batch)
        params, opt_state = apply_jit(params, opt_state, grads)
    _barrier(params)
    raw_sps = steps / (time.perf_counter() - t0)
    detail["raw"] = {"steps_per_sec": round(raw_sps, 3)}
    del params, opt_state

    # Device<->host bandwidth of the gradient-sized payload: the number that
    # decides whether per-step DDP or windowed DiLoCo fits this host.
    import jax.numpy as jnp

    probe = jnp.ones((16 << 20,), jnp.float32) + 0  # 64 MB
    jax.block_until_ready(probe)
    t0 = time.perf_counter()
    host_probe = np.asarray(probe)
    d2h_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    jax.block_until_ready(jnp.asarray(host_probe))
    h2d_s = time.perf_counter() - t0
    detail["transfer"] = {
        "d2h_MBps": round(64 / d2h_s, 1),
        "h2d_MBps": round(64 / h2d_s, 1),
    }
    del probe, host_probe

    lighthouse = Lighthouse(
        bind="[::]:0", min_replicas=1, join_timeout_ms=5000, quorum_tick_ms=50
    )

    # -- ft_ddp: per-step gradient allreduce over a real 2-group ring --
    ddp_warmup, ddp_steps = 1, 4 if on_tpu else 6
    peer_proc = _spawn_peer(
        lighthouse.address(), ddp_warmup + ddp_steps, "f32"
    )
    state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), tx)
    collectives = HostCollectives(timeout=timedelta(seconds=300))
    manager = Manager(
        collectives=collectives,
        load_state_dict=state.load_state_dict,
        state_dict=state.state_dict,
        min_replica_size=1,
        timeout=timedelta(seconds=300),  # first step rides a jit compile
        quorum_timeout=timedelta(seconds=300),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse.address(),
        replica_id="bench_main",
    )
    optimizer = OptimizerWrapper(manager, state)

    def ft_step():
        optimizer.zero_grad()
        loss, grads = grad_fn(state.params, batch)
        avg = manager.allreduce(grads).wait()
        optimizer.step(avg)

    for _ in range(ddp_warmup):
        ft_step()
    _barrier(state.params)
    t0 = time.perf_counter()
    for _ in range(ddp_steps):
        ft_step()
    _barrier(state.params)
    ddp_sps = ddp_steps / (time.perf_counter() - t0)
    # The claim being enforced: a real 2-member ring carried every byte (no
    # world-size-1 identity shortcut).
    assert collectives.size() == 2, "peer did not join the ring"
    detail["ft_ddp"] = {
        "steps_per_sec": round(ddp_sps, 3),
        "ratio_vs_raw": round(ddp_sps / raw_sps, 3),
        "note": "per-step full-gradient shipping; tunnel-bound on this host",
    }
    peer_proc.wait(timeout=120)
    manager.shutdown()
    collectives.shutdown()

    # -- ft_diloco: AsyncDiLoCo over the same real ring (headline) --
    diloco_windows = 3
    total_steps = SYNC_EVERY * diloco_windows
    peer_proc = _spawn_peer(lighthouse.address(), diloco_windows + 1, "bf16")
    state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), tx)
    collectives = HostCollectives(timeout=timedelta(seconds=300))
    manager = Manager(
        collectives=collectives,
        load_state_dict=None,  # set below via diloco
        state_dict=None,
        min_replica_size=1,
        use_async_quorum=False,
        timeout=timedelta(seconds=300),
        quorum_timeout=timedelta(seconds=300),
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse.address(),
        replica_id="bench_main_diloco",
    )
    diloco = AsyncDiLoCo(
        manager,
        state,
        optax.sgd(0.7, momentum=0.9, nesterov=True),
        SYNC_EVERY,
        compress="bf16",
    )
    manager._load_state_dict = diloco.load_state_dict
    manager._user_state_dict = diloco.state_dict

    # Warmup: one full window (compile + first sync launch).
    for _ in range(SYNC_EVERY):
        loss, grads = grad_fn(state.params, batch)
        diloco.step(grads)
    _barrier(state.params)
    t0 = time.perf_counter()
    for _ in range(total_steps):
        loss, grads = grad_fn(state.params, batch)
        diloco.step(grads)
    diloco.flush()
    _barrier(state.params)
    ft_sps = total_steps / (time.perf_counter() - t0)
    detail["ft_diloco"] = {
        "steps_per_sec": round(ft_sps, 3),
        "ratio_vs_raw": round(ft_sps / raw_sps, 3),
        "sync_every": SYNC_EVERY,
        "note": "bf16 pseudogradient sync overlapped with inner compute, "
        "outer update one window late (AsyncDiLoCo)",
    }
    peer_proc.wait(timeout=300)
    manager.shutdown()
    collectives.shutdown()
    lighthouse.shutdown()

    with open(os.path.join(REPO, "BENCH_DETAIL.json"), "w") as f:
        json.dump(detail, f, indent=2)

    print(
        json.dumps(
            {
                "metric": "steps_per_sec_ft",
                "value": round(ft_sps, 3),
                "unit": "steps/s",
                "vs_baseline": round((ft_sps / raw_sps) / 0.90, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
