"""Shared int8 wire-format kernels for the quantized compression modes.

ONE implementation of the per-leaf symmetric int8 quantization with error
feedback and of the member-wise dequantize-average, shared by
:class:`~torchft_tpu.ddp.PipelinedDDP` (``compress="int8"/"q8"``) and
:class:`~torchft_tpu.local_sgd.AsyncDiLoCo` (same modes): the two classes
must stay WIRE-COMPATIBLE (a DDP member and a DiLoCo member never share a
ring op, but the {q, scale} payload convention, the scale floor, and the
participant-divisor discipline are one protocol), so the numerics live in
one place.

Reference parity: none — the reference ships gradients uncompressed
(torch DDP's compressed comm hooks are the upstream analog).

Hot-path siblings: the native comm plan executes this same arithmetic in
C++ (``plan_pack_ef``, collectives.cc), and
:mod:`torchft_tpu.ops.quantize_kernels` executes it as Pallas kernels ON
DEVICE with a device-resident carry — so on the plan transport this
jitted host implementation is off the per-step path entirely (it remains
the wire contract's executable spec, and the int8 allgather transport
still runs it). All three are pinned bit-identical to the FMA-free numpy
oracle in tests/test_comm_plan.py and tests/test_device_pack.py.
"""

from __future__ import annotations

from typing import Any, Dict


def quantize_with_feedback(tree: Any, residual: Any) -> Dict[str, Any]:
    """Per-leaf symmetric int8 quantization with error feedback.

    For each leaf: ``d = leaf(f32) + residual``; ``scale = max(|d|)/127``
    (floored at 1e-12 so an all-zero leaf stays representable);
    ``q = clip(round(d/scale))`` int8; ``dq = q*scale`` (what is actually
    shipped, leaf-wise); ``res = d - dq`` (the carry the CALLER owns —
    restore it on aborted steps, reset it on heals).

    Traceable (callers jit it). Returns ``{"q", "scale", "dq", "res"}``,
    each a tree shaped like ``tree`` (dict-keyed ``tree_transpose``, so
    input pytrees containing tuples can never be mis-split).
    """
    import jax
    import jax.numpy as jnp

    def leaf(l: Any, r: Any) -> Dict[str, Any]:
        d = l.astype(jnp.float32) + r
        scale = jnp.maximum(jnp.max(jnp.abs(d)) / 127.0, 1e-12)
        q = jnp.clip(jnp.round(d / scale), -127, 127).astype(jnp.int8)
        dq = q.astype(jnp.float32) * scale
        return {"q": q, "scale": scale, "dq": dq, "res": d - dq}

    packed = jax.tree_util.tree_map(leaf, tree, residual)
    return jax.tree_util.tree_transpose(
        jax.tree_util.tree_structure(tree),
        jax.tree_util.tree_structure(
            {"q": 0, "scale": 0, "dq": 0, "res": 0}
        ),
        packed,
    )


def make_dequant_average() -> Any:
    """Jitted member-wise dequantize-then-average for gathered
    ``{"q", "scale"}`` entries: ``avg = sum_i(q_i * scale_i) / n``.

    ``n`` must be the PARTICIPANT count, not the cohort size —
    non-participating (healing/spare) entries arrive zeroed from
    ``Manager.allgather`` and must not dilute the divisor. Callers cache
    one jitted fn per cohort size (the entry-list length is part of the
    trace).
    """
    import jax
    import jax.numpy as jnp

    def combine(entries: Any, n: Any) -> Any:
        acc = None
        for e in entries:
            dq = jax.tree_util.tree_map(
                lambda q, s: q.astype(jnp.float32) * s, e["q"], e["scale"]
            )
            acc = (
                dq if acc is None
                else jax.tree_util.tree_map(jnp.add, acc, dq)
            )
        return jax.tree_util.tree_map(lambda a: a / n, acc)

    return jax.jit(combine)
