"""Per-phase breakdown of the link-sized per-step DDP loop on the REAL TPU.

Round-4 verdict #2: ft_ddp_small measured 0.288 steps/s vs 6.586 raw
(ratio 0.044) with ~3.3 s/step of unexplained overhead against a 0.246 s
ring estimate — and only 4 timed steps, no breakdown. This experiment runs
the SAME setup (2-member ring, int8 wire, CPU zero-peer) two ways:

  A. serialized: every phase drained (`_barrier`) so each timer isolates
     one phase — grad / quant / quorum / dispatch / ring_wait (split
     further by HostCollectives.pop_op_stats into pack/d2h/ring/h2d) /
     vote / combine / apply. Inflated total (each drain costs a tunnel
     RTT) but the DISTRIBUTION is the diagnosis.
  B. pipelined: PipelinedDDP steady state, >=20 steps, no intermediate
     drains — the honest rate, with the per-op collectives stats
     aggregated alongside.

Usage (serialize against any other TPU work — one chip):
    python experiments/ddp_small_tpu_breakdown.py
Env: BENCH_DDP_SMALL_BATCH (default 256, the round-4 artifact's point).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from torchft_tpu.platform import (  # noqa: E402
    apply_compilation_cache_env,
    apply_jax_platform_env,
)

apply_jax_platform_env()
apply_compilation_cache_env(os.path.join(REPO, ".bench_jax_cache"))

import bench  # noqa: E402

import jax  # noqa: E402
import optax  # noqa: E402

from torchft_tpu import FTTrainState, PipelinedDDP  # noqa: E402
from torchft_tpu.models import init_params, loss_fn  # noqa: E402
from torchft_tpu.quantize import (  # noqa: E402
    make_dequant_average,
    quantize_with_feedback,
)

WARM, FINE, PIPE = 2, 6, 20


def _round(v):
    return round(v, 4) if isinstance(v, float) else v


def run(state, manager, collectives, cfg, batch) -> None:
    import jax.numpy as jnp

    grad_fn = jax.jit(jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b)))
    quant = jax.jit(quantize_with_feedback)
    combine = make_dequant_average()
    residual = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, jnp.float32), state.params
    )

    def one(rec=None):
        nonlocal residual
        t0 = time.perf_counter()
        loss, grads = grad_fn(state.params, batch)
        bench._barrier(grads)
        t1 = time.perf_counter()
        out = quant(grads, residual)
        residual = out["res"]
        payload = {"q": out["q"], "scale": out["scale"]}
        bench._barrier(payload)
        t2 = time.perf_counter()
        manager.start_quorum()
        manager.wait_quorum()
        t3 = time.perf_counter()
        work = manager.allgather(payload)
        t4 = time.perf_counter()
        res = work.wait()
        t5 = time.perf_counter()
        committed = manager.should_commit()
        t6 = time.perf_counter()
        avg = combine(res, float(max(manager.num_participants(), 1)))
        bench._barrier(avg)
        t7 = time.perf_counter()
        if committed:
            state.apply_gradients(avg)
        bench._barrier(state.params)
        t8 = time.perf_counter()
        if rec is not None:
            rec.append({
                "grad": t1 - t0, "quant": t2 - t1, "quorum": t3 - t2,
                "dispatch": t4 - t3, "ring_wait": t5 - t4, "vote": t6 - t5,
                "combine": t7 - t6, "apply": t8 - t7, "total": t8 - t0,
            })

    print("== A: serialized phases ==", flush=True)
    for _ in range(WARM):
        one()
    collectives.pop_op_stats()
    recs = []
    for i in range(FINE):
        one(recs)
        print(f"  fine step {i}: {recs[-1]['total']:.3f}s", flush=True)
    med = {
        k: round(sorted(r[k] for r in recs)[len(recs) // 2], 4)
        for k in recs[0]
    }
    fine_ops = collectives.pop_op_stats()
    print("median s/phase:", json.dumps(med), flush=True)
    print("op stats:", json.dumps(
        [{k: _round(v) for k, v in s.items()} for s in fine_ops]), flush=True)

    print("== B: pipelined steady state ==", flush=True)
    ddp = PipelinedDDP(
        manager, state, lambda p, b: grad_fn(p, b), compress="int8"
    )
    ddp.step(batch)  # warm
    bench._barrier(state.params)
    t0 = time.perf_counter()
    step_times = []
    for i in range(PIPE):
        ts = time.perf_counter()
        ddp.step(batch)
        step_times.append(time.perf_counter() - ts)
    t_end = time.perf_counter()
    ddp.flush()
    bench._barrier(state.params)
    # The warm step's allgather may settle after the pop above (it is
    # only waited inside the first timed step) — keep the LAST ``PIPE``
    # entries so a late warm-round stat can't bias the medians.
    pipe_ops = collectives.pop_op_stats()[-PIPE:]
    sps = PIPE / (t_end - t0)
    agg = {}
    for s in pipe_ops:
        for k in ("pack", "d2h", "ring", "h2d"):
            if k in s:
                agg.setdefault(k, []).append(s[k])
    print("pipelined steps/s:", round(sps, 3), flush=True)
    print("per-step host time: median",
          round(sorted(step_times)[len(step_times) // 2], 4),
          "max", round(max(step_times), 4), flush=True)
    print("op medians:", json.dumps({
        k: round(sorted(v)[len(v) // 2], 4) for k, v in agg.items()}),
        flush=True)
    print("metrics:", json.dumps(manager.metrics().snapshot(), default=str),
          flush=True)
    assert collectives.size() == 2


def main() -> None:
    os.environ["BENCH_MODEL"] = "ddp_small"
    os.environ.setdefault("BENCH_DDP_SMALL_BATCH", "256")
    os.environ.setdefault("TORCHFT_HC_PIPELINE_CHUNKS", "1")

    cfg, batch, _ = bench._model_setup("ddp_small")
    print(f"platform={jax.devices()[0].platform} batch={batch.shape}",
          flush=True)
    tx = optax.adamw(1e-3)
    state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), tx)
    # bench's shared lifecycle: paced peer (rounds=0), reaped on exit.
    with bench._ring_session("ddp_probe", "int8", state) as (
        manager, collectives,
    ):
        run(state, manager, collectives, cfg, batch)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
