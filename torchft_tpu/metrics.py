"""Lightweight step-level metrics for the fault-tolerance runtime.

The reference's only progress metric is ``batches_committed``
(reference torchft/manager.py:642-653); observability is otherwise logs +
the dashboard. This module closes the SURVEY.md §5 tracing gap with
in-process counters/timers the Manager feeds at the transaction's
boundaries — no external dependencies, negligible overhead (a deque append
per event), and a one-call JSON-able snapshot for progress loops,
dashboards, or tests::

    manager.metrics().snapshot()
    # {"counters": {"commits": 98, "aborts": 2, "heals": 1, ...},
    #  "timers_s": {"quorum": {"n":100,"p50":0.0012,"p90":0.003,...}, ...}}
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict


class _Timer:
    """Bounded reservoir of durations with percentile snapshots."""

    def __init__(self, maxlen: int = 512) -> None:
        self._samples: deque = deque(maxlen=maxlen)
        self.count = 0
        self.total_s = 0.0

    def record(self, seconds: float) -> None:
        self._samples.append(seconds)
        self.count += 1
        self.total_s += seconds

    def snapshot(self) -> Dict[str, float]:
        samples = sorted(self._samples)
        if not samples:
            return {"n": 0}

        def pct(p: float) -> float:
            return samples[min(int(p * len(samples)), len(samples) - 1)]

        return {
            "n": self.count,
            "total_s": round(self.total_s, 6),
            "p50": round(pct(0.50), 6),
            "p90": round(pct(0.90), 6),
            "max": round(samples[-1], 6),
        }


class _EventWindow:
    """Bounded reservoir of event timestamps with a trailing-window rate.

    The rolling-rate primitive behind signals like the churn estimate
    (reconfigures per minute): ``mark()`` appends a monotonic timestamp,
    ``rate_per_min(window_s)`` counts events inside the trailing window
    and divides by the window actually OBSERVED — a process younger than
    the window divides by its own age, so early-life rates aren't
    diluted toward zero by time that never happened."""

    def __init__(self, maxlen: int = 512) -> None:
        self._stamps: deque = deque(maxlen=maxlen)
        self._born = time.monotonic()
        self.count = 0

    def mark(self) -> None:
        self._stamps.append(time.monotonic())
        self.count += 1

    def rate_per_min(self, window_s: float = 600.0) -> float:
        now = time.monotonic()
        cutoff = now - window_s
        n = sum(1 for t in self._stamps if t >= cutoff)
        observed = min(window_s, now - self._born)
        if self._stamps and len(self._stamps) == self._stamps.maxlen:
            # Reservoir rolled over: the window may predate the oldest
            # retained stamp; never divide by time we can't account for.
            observed = min(observed, now - self._stamps[0])
        return 0.0 if observed <= 0 else n * 60.0 / observed

    def snapshot(self, window_s: float = 600.0) -> Dict[str, float]:
        return {
            "n": self.count,
            "rate_per_min": round(self.rate_per_min(window_s), 6),
        }


class Metrics:
    """Thread-safe counters + timers + event windows. All methods are
    cheap enough for the hot path; reading is lock-held but O(window)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._timers: Dict[str, _Timer] = {}
        self._events: Dict[str, _EventWindow] = {}

    def incr(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] += by

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            timer = self._timers.get(name)
            if timer is None:
                timer = self._timers[name] = _Timer()
            timer.record(seconds)

    def mark(self, name: str) -> None:
        """Records one occurrence of a timestamped event (for rolling
        rates — counters answer "how many ever", this answers "how often
        lately")."""
        with self._lock:
            window = self._events.get(name)
            if window is None:
                window = self._events[name] = _EventWindow()
            window.mark()

    def rate_per_min(self, name: str, window_s: float = 600.0) -> float:
        """Trailing-window rate (events/min) of a ``mark``ed event; 0.0
        for a name never marked."""
        with self._lock:
            window = self._events.get(name)
            return 0.0 if window is None else window.rate_per_min(window_s)

    def timed(self, name: str) -> "_TimedBlock":
        return _TimedBlock(self, name)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "timers_s": {
                    name: t.snapshot() for name, t in self._timers.items()
                },
                "events": {
                    name: w.snapshot() for name, w in self._events.items()
                },
            }


class _TimedBlock:
    def __init__(self, metrics: Metrics, name: str) -> None:
        self._metrics = metrics
        self._name = name

    def __enter__(self) -> "_TimedBlock":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: object) -> None:
        self._metrics.record(self._name, time.perf_counter() - self._t0)
