"""Lighthouse HTTP dashboard + launcher tests.
Dashboard parity with reference templates/ + src/lighthouse.rs:320-437."""

import sys
import urllib.request
from datetime import timedelta

import pytest

from torchft_tpu._native import (
    Lighthouse,
    Manager,
    ManagerClient,
    Store,
)
from torchft_tpu.launcher import launch, replica_group_spec


def _get(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as f:
        return f.read().decode()


class TestDashboard:
    def test_index_and_status(self):
        lh = Lighthouse(bind="[::]:0", min_replicas=1)
        try:
            base = lh.address()
            index = _get(base + "/")
            assert "lighthouse" in index
            status = _get(base + "/status")
            assert "Quorum" in status

            # With a live member, status shows its card and heartbeat age.
            store = Store()
            m = Manager(
                "dash_rep", lh.address(), "localhost", "[::]:0",
                store.address(), 1,
            )
            client = ManagerClient(m.address())
            client.quorum(0, 3, "md", timeout=timedelta(seconds=10))
            status = _get(base + "/status")
            assert "dash_rep" in status
            assert "Kill" in status
            assert "Heartbeats" in status
            # Quorum age + event log (reference templates/status.html shows
            # the quorum's live state; heal/membership transitions logged).
            assert ", age " in status
            assert "Events" in status
            assert "quorum 1: 1 member" in status
            m.shutdown()
            store.shutdown()
        finally:
            lh.shutdown()

    def test_kill_unknown_replica_404(self):
        lh = Lighthouse(bind="[::]:0", min_replicas=1)
        try:
            req = urllib.request.Request(
                lh.address() + "/replica/nope/kill", method="POST"
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 404
        finally:
            lh.shutdown()

    def test_unknown_path_404(self):
        lh = Lighthouse(bind="[::]:0", min_replicas=1)
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(lh.address() + "/bogus", timeout=10)
            assert e.value.code == 404
        finally:
            lh.shutdown()


class TestLauncher:
    def test_spec_env_plumbing(self):
        spec = replica_group_spec(
            ["python", "x.py"], 1, 4, "http://lh:1", env={"EXTRA": "1"}
        )
        assert spec["env"]["REPLICA_GROUP_ID"] == "1"
        assert spec["env"]["NUM_REPLICA_GROUPS"] == "4"
        assert spec["env"]["TORCHFT_LIGHTHOUSE"] == "http://lh:1"
        assert spec["env"]["EXTRA"] == "1"
        assert spec["max_restarts"] == 10

    def test_launch_restarts_failed_group(self, tmp_path):
        # Each group fails once (marker file), then succeeds: the supervisor
        # must restart it (the reference's torchelastic max_restarts role).
        script = tmp_path / "flaky.py"
        script.write_text(
            "import os, sys\n"
            "marker = os.path.join(\n"
            "    os.path.dirname(os.path.abspath(__file__)),\n"
            "    'marker_' + os.environ['REPLICA_GROUP_ID'],\n"
            ")\n"
            "if not os.path.exists(marker):\n"
            "    open(marker, 'w').close()\n"
            "    sys.exit(1)\n"
            "sys.exit(0)\n"
        )
        rc = launch(
            [sys.executable, str(script)],
            num_replica_groups=2,
            lighthouse_addr="http://unused:1",
            max_restarts=2,
        )
        assert rc == 0
        assert (tmp_path / "marker_0").exists()
        assert (tmp_path / "marker_1").exists()

    def test_launch_hot_spare_promotion(self, tmp_path):
        # --hot-spare policy: the dead primary is replaced by PROMOTING
        # the pre-warmed standby (which was parked in standby_gate), not
        # by a cold restart. The promoted process proves it came through
        # the gate by writing a marker only standbys write.
        import os

        script = tmp_path / "spare.py"
        script.write_text(
            "import os, sys\n"
            f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
            "from torchft_tpu.platform import standby_gate\n"
            "d = os.path.dirname(os.path.abspath(__file__))\n"
            "if os.environ.get('TORCHFT_STANDBY_FILE'):\n"
            "    standby_gate()\n"
            "    open(os.path.join(d, 'promoted'), 'w').close()\n"
            "    sys.exit(0)\n"
            "if not os.path.exists(os.path.join(d, 'died')):\n"
            "    open(os.path.join(d, 'died'), 'w').close()\n"
            "    sys.exit(1)\n"
            "sys.exit(0)\n"
        )
        rc = launch(
            [sys.executable, str(script)],
            num_replica_groups=1,
            lighthouse_addr="http://unused:1",
            max_restarts=2,
            hot_spare=True,
        )
        assert rc == 0
        assert (tmp_path / "died").exists()
        assert (tmp_path / "promoted").exists()

    def test_supervised_standby_warm_marker(self, tmp_path):
        # standby_warm keys off the <standby_file>.warm marker that
        # standby_gate touches on arrival — the signal the warm-deadline
        # re-arm policy (lift a starving warm-up back to normal priority)
        # and promotion logging both read.
        from torchft_tpu.launcher import _Supervised

        s = _Supervised(spec={"name": "g0"})
        assert s.standby_warm() is False  # no standby file yet
        s.standby_file = str(tmp_path / "gate")
        assert s.standby_warm() is False  # armed but still warming
        (tmp_path / "gate.warm").write_text("")
        assert s.standby_warm() is True

    def test_launch_gives_up_after_max_restarts(self, tmp_path):
        script = tmp_path / "fail.py"
        script.write_text("import sys; sys.exit(3)\n")
        rc = launch(
            [sys.executable, str(script)],
            num_replica_groups=1,
            lighthouse_addr="http://unused:1",
            max_restarts=1,
        )
        assert rc == 1


class TestRenicePriorityProbe:
    """Spawn-time setpriority capability probe (VERDICT item 4): standbys
    only warm at nice 19 when the supervisor can lift a promoted one back
    to 0 — never leave a promoted worker training at idle priority."""

    def test_cap_sys_nice_in_capeff_allows(self):
        from torchft_tpu.launcher import _can_lift_priority

        # CAP_SYS_NICE is bit 23
        assert _can_lift_priority(
            status_text="Name:\tx\nCapEff:\t0000000000800000\n",
            rlimit_nice=0,
        )

    def test_no_cap_no_rlimit_denies(self):
        from torchft_tpu.launcher import _can_lift_priority

        assert not _can_lift_priority(
            status_text="Name:\tx\nCapEff:\t0000000000000000\n",
            rlimit_nice=0,
        )

    def test_root_without_cap_sys_nice_denies(self, monkeypatch):
        # The kernel's can_nice() is capability-based: root in a
        # --cap-drop SYS_NICE container cannot lift a niced child, and
        # euid 0 must NOT short-circuit the CapEff verdict.
        import torchft_tpu.launcher as launcher_mod

        monkeypatch.setattr(launcher_mod.os, "geteuid", lambda: 0)
        assert not launcher_mod._can_lift_priority(
            status_text="Name:\tx\nCapEff:\t0000000000000000\n",
            rlimit_nice=0,
        )
        # euid 0 only decides when no capability info exists at all
        assert launcher_mod._can_lift_priority(
            status_text="Name:\tx\n", rlimit_nice=0
        )

    def test_rlimit_nice_allowance_allows(self):
        from torchft_tpu.launcher import _can_lift_priority

        # soft RLIMIT_NICE of 20 admits raising priority to nice 0
        assert _can_lift_priority(
            status_text="Name:\tx\nCapEff:\t0000000000000000\n",
            rlimit_nice=20,
        )
        assert not _can_lift_priority(
            status_text="Name:\tx\nCapEff:\t0000000000000000\n",
            rlimit_nice=19,
        )
        # RLIM_INFINITY reads as -1: unlimited allowance, must allow
        assert _can_lift_priority(
            status_text="Name:\tx\nCapEff:\t0000000000000000\n",
            rlimit_nice=-1,
        )

    def test_unprivileged_supervisor_never_nices_standby(
        self, tmp_path, monkeypatch
    ):
        # With the probe forced to "cannot lift", the standby must warm
        # at the supervisor's own niceness (NOT 19) so a promotion never
        # yields a permanently-deprioritized primary.
        import os

        import torchft_tpu.launcher as launcher_mod

        monkeypatch.setattr(launcher_mod, "_can_lift_priority", lambda: False)
        script = tmp_path / "spare_nice.py"
        script.write_text(
            "import os, sys\n"
            f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
            "from torchft_tpu.platform import standby_gate\n"
            "d = os.path.dirname(os.path.abspath(__file__))\n"
            "if os.environ.get('TORCHFT_STANDBY_FILE'):\n"
            "    nice = os.nice(0)\n"
            "    standby_gate()\n"
            "    with open(os.path.join(d, 'promoted_nice'), 'w') as f:\n"
            "        f.write(str(nice))\n"
            "    sys.exit(0)\n"
            "if not os.path.exists(os.path.join(d, 'died')):\n"
            "    open(os.path.join(d, 'died'), 'w').close()\n"
            "    sys.exit(1)\n"
            "sys.exit(0)\n"
        )
        rc = launcher_mod.launch(
            [sys.executable, str(script)],
            num_replica_groups=1,
            lighthouse_addr="http://unused:1",
            max_restarts=2,
            hot_spare=True,
        )
        assert rc == 0
        base_nice = os.nice(0)
        promoted_nice = int((tmp_path / "promoted_nice").read_text())
        assert promoted_nice == base_nice, (
            f"promoted standby ran at nice {promoted_nice} (supervisor "
            f"{base_nice}): an unliftable supervisor must not warm "
            "standbys at idle priority"
        )
