# graftlint fixture: a chaos seam registry with deliberate drift against
# bad_fault.h (see TestFaultGuard for the violation each entry seeds).
NATIVE_SEAMS = ("ring_send", "wal_write", "ghost_seam")
PYTHON_SEAMS = ("store", "serving")

SEAM_KINDS = {
    "ring_send": ("drop", "bit_flip"),
    "wal_write": ("truncate",),
    "ghost_seam": ("drop",),
    "store": ("drop",),
    # "serving" missing -> kind-totality violation
    # not a registered seam -> orphan-vocabulary violation
    "orphan_kind": ("drop",),
}
