// Global quorum service. One per job; replica-group managers heartbeat into it
// and long-poll Quorum requests against it. Also serves an HTML dashboard on
// the same port (HTTP requests are sniffed apart from protocol frames).
// Reference: src/lighthouse.rs.
#pragma once

#include <atomic>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "conn_tracker.h"
#include "net.h"
#include "quorum.h"
#include "thread_annotations.h"

namespace tft {

class Lighthouse {
 public:
  Lighthouse(const std::string& bind_addr, const LighthouseOpt& opt);
  ~Lighthouse();

  // "http://host:port" (dashboard is literally served over HTTP here).
  std::string address() const;
  uint16_t port() const;
  void shutdown();

 private:
  void accept_loop();
  void tick_loop();
  void handle_conn(Socket& sock);
  void handle_http(Socket& sock, const std::string& head);
  void handle_quorum_req(Socket& sock, const std::string& payload);

  // Runs one quorum check; called with mu_ held. On success publishes the new
  // quorum (bumping quorum_id only when membership changed) and wakes waiters.
  void quorum_tick_locked() TFT_REQUIRES(mu_);

  std::string render_status_locked() TFT_REQUIRES(mu_);

  LighthouseOpt opt_;
  std::unique_ptr<Listener> listener_;
  std::string hostname_;

  Mutex mu_;
  CondVar quorum_cv_;
  LighthouseState state_ TFT_GUARDED_BY(mu_);
  // Broadcast channel equivalent: monotone generation + latest value.
  int64_t quorum_gen_ TFT_GUARDED_BY(mu_) = 0;
  torchft_tpu::Quorum latest_quorum_ TFT_GUARDED_BY(mu_);

  std::atomic<bool> shutting_down_{false};
  std::thread accept_thread_;
  std::thread tick_thread_;
  ConnTracker conns_;
};

} // namespace tft
