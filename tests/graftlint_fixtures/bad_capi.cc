// graftlint fixture: a C API with every flavor of bridge drift.
#include <cstdint>

extern "C" {

// OK everywhere (control: must NOT be flagged).
int tft_fix_ok(void* handle, int64_t a) { return 0; }

// Declared in bad_native.py with the wrong argtypes length.
int tft_fix_argcount(void* handle, int64_t a, int64_t b) { return 0; }

// int64 return with no restype declaration (default c_int truncates).
int64_t tft_fix_ret64(void* handle) { return 0; }

// Never declared in bad_native.py at all.
int tft_fix_undeclared(void* handle) { return 0; }

// Missing from the pyi _NativeLib block.
int tft_fix_unstubbed(void* handle) { return 0; }

// Shared-memory surface drift: tft_shm_* symbols ride the same
// three-file rule as every other export (the isolated-data-plane
// satellite pinned this — a handle-returning shm export with no restype
// would hand Python a truncated pointer).
void* tft_shm_fix_noresty(const char* name, int64_t bytes) { return 0; }

} // extern "C"
