"""Serving-plane tests: wire encodings, the publisher's error-feedback
delta discipline, the zero-copy relay re-serve, lease batching, and the
integrity ladder (range CRC -> payload CRC -> nonce -> digest) that
makes torn installs impossible."""

import threading
import time

import numpy as np
import pytest

from torchft_tpu import serving
from torchft_tpu.serving import (
    StaleWeightsError,
    WeightPublisher,
    WeightRelay,
    WeightSubscriber,
    WireDetection,
    _BytesSource,
    _catch_up_plan,
    _fetch_version,
    _http_json,
    decode_tree,
    demo_params,
    encode_tree,
    tree_digest,
)


def _tree(seed=0, leaves=3, elems=2048, version=0):
    return demo_params(seed, leaves, elems, version)


def _wait_until(pred, timeout_s=10.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while not pred():
        if time.monotonic() >= deadline:
            return False
        time.sleep(interval_s)
    return True


# -- wire encodings ----------------------------------------------------------


class TestWire:
    @pytest.mark.parametrize("wire", ["q8", "bf16", "f32"])
    def test_roundtrip_shapes_and_error(self, wire):
        tree = _tree()
        dec = decode_tree(encode_tree(tree, wire), wire)
        for k in tree:
            assert dec[k].dtype == np.float32
            assert dec[k].shape == tree[k].shape
        err = max(
            float(np.max(np.abs(dec[k] - tree[k]))) for k in tree
        )
        if wire == "f32":
            assert err == 0.0
        elif wire == "bf16":
            assert err < 0.05
        else:  # q8: bounded by scale/2 = max|d|/254
            bound = max(
                float(np.max(np.abs(tree[k]))) / 254.0 * 1.01 for k in tree
            )
            assert err <= bound

    def test_q8_matches_quantize_oracle(self):
        # One wire contract: serving's q8 must produce the exact
        # quantize.py numerics (scale floor, round-half-even).
        from torchft_tpu.quantize import quantize_with_feedback

        leaf = np.linspace(-3.0, 3.0, 1000, dtype=np.float32)
        enc = serving._q8_encode_leaf(leaf)
        import jax

        ref = quantize_with_feedback(
            {"x": jax.numpy.asarray(leaf)},
            {"x": jax.numpy.zeros_like(leaf)},
        )
        np.testing.assert_array_equal(enc["q"], np.asarray(ref["q"]["x"]))
        np.testing.assert_allclose(
            float(enc["s"]), float(ref["scale"]["x"]), rtol=1e-6
        )

    def test_q8_zero_leaf_scale_floor(self):
        enc = serving._q8_encode_leaf(np.zeros(64, np.float32))
        assert float(enc["s"]) == pytest.approx(1e-12)
        assert not enc["q"].any()

    def test_non_float_leaf_rejected(self):
        with pytest.raises(ValueError, match="FLOAT weight trees"):
            encode_tree({"ids": np.arange(4)}, "q8")

    def test_wire_sizes(self):
        # the measured per-subscriber bytes story starts here: wire
        # payloads must hit the q8<=0.3x / bf16<=0.55x targets
        pub = {}
        tree = _tree(elems=4096)
        f32 = sum(v.nbytes for v in tree.values())
        for wire in ("q8", "bf16", "f32"):
            p = WeightPublisher(wire=wire)
            try:
                m = p.publish(tree)
                pub[wire] = m["total"] + m["meta_len"]
                assert m["f32_nbytes"] == f32
            finally:
                p.shutdown()
        assert pub["q8"] <= 0.3 * pub["f32"]
        assert pub["bf16"] <= 0.55 * pub["f32"]

    def test_tree_digest_sensitive(self):
        t = _tree()
        d1 = tree_digest(t)
        t2 = {k: v.copy() for k, v in t.items()}
        t2["layer0"][3] += 1e-3
        assert tree_digest(t2) != d1
        assert tree_digest(t) == d1


# -- catch-up planning -------------------------------------------------------


class TestCatchUpPlan:
    def _manifests(self, kinds):
        return {
            v: {"version": v, "kind": k} for v, k in kinds.items()
        }

    def test_pure_delta_chain(self):
        ms = self._manifests({3: "delta", 4: "delta", 5: "delta"})
        assert _catch_up_plan(2, ms) == [3, 4, 5]

    def test_late_joiner_snapshot_path(self):
        ms = self._manifests({4: "snapshot", 5: "delta", 6: "delta"})
        assert _catch_up_plan(-1, ms) == [4, 5, 6]

    def test_gap_raises(self):
        ms = self._manifests({5: "delta", 6: "delta"})
        with pytest.raises(WireDetection, match="gap"):
            _catch_up_plan(-1, ms)

    def test_current_is_noop(self):
        ms = self._manifests({4: "snapshot", 5: "delta"})
        assert _catch_up_plan(5, ms) == []

    def test_missing_delta_falls_back_to_snapshot(self):
        ms = self._manifests({2: "snapshot", 3: "delta", 4: "delta"})
        # have=0 but v1 evicted: the pure chain is broken, replan from
        # the snapshot
        assert _catch_up_plan(0, ms) == [2, 3, 4]


# -- publisher ---------------------------------------------------------------


class TestPublisher:
    def test_snapshot_cadence_and_eviction(self):
        pub = WeightPublisher(wire="q8", snapshot_every=4, keep=5)
        try:
            for v in range(10):
                pub.publish(_tree(version=v))
            ms = {m["version"]: m for m in pub.node.store.manifests()}
            assert ms[8]["kind"] == "snapshot"
            assert ms[9]["kind"] == "delta"
            # keep=5 with latest snapshot at 8: everything below the
            # snapshot beyond the budget is gone, the chain 8..9 stays
            assert 8 in ms and 9 in ms
            assert len(ms) <= 5
            assert _catch_up_plan(-1, ms)[0] == 8
        finally:
            pub.shutdown()

    def test_delta_error_feedback_bounds_drift(self):
        # EF at the publisher: a subscriber applying every delta matches
        # the served tree exactly, and the served tree tracks the true
        # params within one quantization step (error does not grow with
        # the number of deltas).
        pub = WeightPublisher(wire="q8", snapshot_every=100)
        try:
            acc = None
            for v in range(12):
                true = _tree(version=v)
                m = pub.publish(true)
                meta, payload = _fetch_version(
                    pub.server.local_address(), m, 2, 10.0
                )
                dec = decode_tree(
                    serving.rebuild_from_packed(
                        serving.load_packed_meta(meta), payload
                    ),
                    m["wire"],
                )
                acc = dec if m["kind"] == "snapshot" else serving._tree_add(acc, dec)
                assert tree_digest(acc) == m["digest"]
            err = max(
                float(np.max(np.abs(acc[k] - true[k]))) for k in true
            )
            scale_bound = max(
                float(np.max(np.abs(true[k]))) for k in true
            ) / 127.0
            assert err <= 2.5 * scale_bound
        finally:
            pub.shutdown()

    def test_publish_on_commit_hook(self):
        class _Mgr:
            def __init__(self):
                self.hooks = []

            def add_commit_hook(self, h):
                self.hooks.append(h)

        pub = WeightPublisher(wire="f32")
        try:
            mgr = _Mgr()
            serving.publish_on_commit(mgr, pub, lambda: _tree(), every=2)
            (hook,) = mgr.hooks
            hook(1, 1, True)   # not an every-boundary
            hook(2, 1, False)  # aborted step: no publish
            hook(2, 1, True)
            hook(4, 1, True)
            assert pub.node.store.latest() == 1  # two publishes: v0, v1
            assert pub.node.store.get(0).manifest["step"] == 2
        finally:
            pub.shutdown()


# -- integrity ladder --------------------------------------------------------


class TestIntegrity:
    def test_nonce_mismatch_is_400(self):
        pub = WeightPublisher(wire="f32")
        try:
            m = dict(pub.publish(_tree()))
            m["nonce"] = "deadbeef00000000"
            with pytest.raises(WireDetection, match="nonce"):
                _fetch_version(pub.server.local_address(), m, 1, 10.0)
            assert pub.node.counters["nonce_rejects"] >= 1
        finally:
            pub.shutdown()

    def test_evicted_version_is_gone(self):
        pub = WeightPublisher(wire="f32")
        try:
            m = pub.publish(_tree())
            fake = dict(m)
            fake["version"] = 99
            with pytest.raises(WireDetection, match="gone"):
                _fetch_version(pub.server.local_address(), fake, 1, 10.0)
        finally:
            pub.shutdown()

    def test_corrupt_relay_cache_detected_by_payload_crc(self):
        # A relay re-signs range CRCs off its own buffer, so in-memory
        # corruption at the relay passes the RANGE check — the manifest's
        # full-payload CRC (minted by the publisher) is what catches it
        # end-to-end.
        pub = WeightPublisher(wire="f32")
        relay = WeightRelay(pub.server.local_address(), name="rx")
        try:
            m = pub.publish(_tree())
            relay.sync_once()
            held = relay.node.store.get(0)
            corrupted = bytearray(held.source._view.tobytes())
            corrupted[7] ^= 0xFF
            held.source = _BytesSource(bytes(corrupted))
            sub = WeightSubscriber(
                relay.server.local_address(), name="s-crc"
            )
            assert sub.poll() is False
            assert sub.stats["detect_crc"] == 1
            assert sub.version() == -1  # nothing installed
        finally:
            relay.shutdown()
            pub.shutdown()

    def test_truncated_meta_detected(self):
        pub = WeightPublisher(wire="f32")
        try:
            pub.publish(_tree())
            held = pub.node.store.get(0)
            held.meta = held.meta[:-10]
            sub = WeightSubscriber(
                pub.server.local_address(), name="s-meta"
            )
            assert sub.poll() is False
            assert sub.stats["detect_short"] == 1
        finally:
            pub.shutdown()

    def test_digest_gate_catches_wrong_end_state(self):
        # Everything on the wire verifies but the advertised end-state
        # digest disagrees: the install must be averted at the last gate.
        pub = WeightPublisher(wire="f32")
        try:
            pub.publish(_tree())
            pub.node.store.get(0).manifest["digest"] = "0" * 8
            sub = WeightSubscriber(
                pub.server.local_address(), name="s-dig"
            )
            assert sub.poll() is False
            assert sub.stats["detect_digest"] == 1
            assert sub.version() == -1
        finally:
            pub.shutdown()


# -- relay tree --------------------------------------------------------------


class TestRelay:
    def test_verbatim_reserve_bit_identity(self):
        pub = WeightPublisher(wire="q8")
        relay = WeightRelay(pub.server.local_address(), name="rv")
        try:
            m = pub.publish(_tree())
            relay.sync_once()
            up_meta, up_payload = _fetch_version(
                pub.server.local_address(), m, 3, 10.0
            )
            dn_meta, dn_payload = _fetch_version(
                relay.server.local_address(), m, 3, 10.0
            )
            assert up_meta == dn_meta
            assert up_payload == dn_payload
        finally:
            relay.shutdown()
            pub.shutdown()

    def test_publisher_egress_independent_of_subscribers(self):
        # The fan-out story by accounting: adding subscribers behind the
        # relay moves ZERO additional bytes out of the publisher.
        pub = WeightPublisher(wire="q8")
        relay = WeightRelay(pub.server.local_address(), name="re").start()
        try:
            pub.publish(_tree())
            subs = [
                WeightSubscriber(
                    relay.server.local_address(), name=f"se{i}"
                )
                for i in range(4)
            ]
            assert _wait_until(
                lambda: relay.node.store.latest() == 0, 10.0
            )
            before_ranges = pub.node.counters["ranges_served"]
            before_meta = pub.node.counters["meta_served"]
            for s in subs:
                assert s.wait_version(0, 10.0)
            # payload bytes left the publisher exactly once (the relay's
            # sync); subscribers fetching through the relay moved ZERO
            # additional ranges or metas out of the root
            assert pub.node.counters["ranges_served"] == before_ranges
            assert pub.node.counters["meta_served"] == before_meta
            assert relay.node.counters["ranges_served"] >= 4
            for s in subs:
                s.close()
        finally:
            relay.shutdown()
            pub.shutdown()

    def test_partitioned_relay_serves_with_honest_age(self):
        pub = WeightPublisher(wire="f32")
        relay = WeightRelay(pub.server.local_address(), name="rp")
        try:
            pub.publish(_tree())
            relay.sync_once()
            age0 = relay._age_ms()
            assert 0 <= age0 < 5_000
            relay.set_partitioned(True)
            with pytest.raises(WireDetection):
                relay.sync_once()
            time.sleep(0.15)
            st = _http_json(
                f"{relay.server.local_address()}/ps/status", 5.0
            )
            assert st["latest"] == 0  # still serving
            assert st["age_ms"] >= 150  # and honest about staleness
            relay.set_partitioned(False)
            relay.sync_once()
            assert relay._age_ms() < st["age_ms"]
        finally:
            relay.shutdown()
            pub.shutdown()

    def test_upstream_regression_resyncs(self):
        # A publisher that died and restarted publishes version numbers
        # from scratch under fresh nonces: the relay must drop its stale
        # chain and resync rather than serve a mixed history.
        pub1 = WeightPublisher(wire="f32", snapshot_every=1)
        relay = WeightRelay(pub1.server.local_address(), name="rr")
        try:
            for v in range(3):
                pub1.publish(_tree(version=v))
            relay.sync_once()
            assert relay.node.store.latest() == 2
            pub2 = WeightPublisher(wire="f32", snapshot_every=1)
            try:
                pub2.publish(_tree(seed=9))
                relay.upstream = pub2.server.local_address()
                relay.sync_once()
                assert relay.node.store.latest() == 0
                held = relay.node.store.get(0)
                assert held.manifest["digest"] == tree_digest(
                    decode_tree(
                        serving.rebuild_from_packed(
                            serving.load_packed_meta(held.meta),
                            held.source._view.tobytes(),
                        ),
                        "f32",
                    )
                )
            finally:
                pub2.shutdown()
        finally:
            relay.shutdown()
            pub1.shutdown()


# -- subscriber sessions -----------------------------------------------------


class TestSubscriber:
    def test_late_joiner_snapshot_plus_delta(self):
        pub = WeightPublisher(wire="q8", snapshot_every=4)
        try:
            for v in range(7):
                pub.publish(_tree(version=v))
            sub = WeightSubscriber(pub.server.local_address(), name="lj")
            assert sub.poll() is True
            assert sub.version() == 6
            # one install: snapshot v4 + deltas v5, v6
            assert sub.stats["installs"] == 1
            assert sub.stats["snapshot_installs"] == 1
            assert sub.stats["catch_up_deltas"] == 2
            v, tree, age = sub.current()
            assert v == 6 and age >= 0
            assert tree_digest(tree) == pub.node.store.get(6).manifest["digest"]
        finally:
            pub.shutdown()

    def test_staleness_bounded_read(self):
        pub = WeightPublisher(wire="f32")
        try:
            sub = WeightSubscriber(pub.server.local_address(), name="sb")
            with pytest.raises(StaleWeightsError, match="no weights"):
                sub.current()
            pub.publish(_tree())
            assert sub.poll() is True
            v, _, age = sub.current(max_age_ms=60_000)
            assert v == 0
            time.sleep(0.12)
            with pytest.raises(StaleWeightsError, match="exceeds bound"):
                sub.current(max_age_ms=100)
            # a fresh poll against a live node resets the age
            sub.poll()
            sub.current(max_age_ms=60_000)
        finally:
            pub.shutdown()

    def test_background_thread_follows_publishes(self):
        pub = WeightPublisher(wire="q8", snapshot_every=4)
        try:
            sub = WeightSubscriber(
                pub.server.local_address(), name="bg"
            ).start(poll_ms=200)
            for v in range(5):
                pub.publish(_tree(version=v))
            assert _wait_until(lambda: sub.version() == 4, 15.0)
            sub.close()
            assert sub.stats["torn_installs"] == 0
        finally:
            pub.shutdown()

    def test_publisher_restart_regression_recovers(self):
        pub1 = WeightPublisher(wire="f32", snapshot_every=1)
        sub = None
        try:
            for v in range(4):
                pub1.publish(_tree(version=v))
            sub = WeightSubscriber(pub1.server.local_address(), name="rg")
            assert sub.poll() is True and sub.version() == 3
            pub2 = WeightPublisher(wire="f32", snapshot_every=1)
            try:
                pub2.publish(_tree(seed=5))
                sub.base = pub2.server.local_address()
                assert sub.poll() is True
                assert sub.version() == 0  # new history accepted
                _, tree, _ = sub.current()
                assert tree_digest(tree) == pub2.node.store.get(0).manifest[
                    "digest"
                ]
            finally:
                pub2.shutdown()
        finally:
            pub1.shutdown()


# -- leases ------------------------------------------------------------------


class TestLeases:
    def test_lease_expiry_prunes(self):
        pub = WeightPublisher(wire="f32", lease_ttl_ms=100)
        try:
            pub.node.renew_lease("a", 100, 1)
            pub.node.renew_lease("b", 10_000, 2)
            leases, subs = pub.node.lease_totals()
            assert (leases, subs) == (2, 3)
            assert _wait_until(
                lambda: pub.node.lease_totals() == (1, 2), 5.0
            )
        finally:
            pub.shutdown()

    def test_relay_batches_downstream_population_upstream(self):
        # 3 subscriber leases at the relay become ONE upstream lease
        # entry whose weight is the whole population.
        pub = WeightPublisher(wire="f32")
        relay = WeightRelay(pub.server.local_address(), name="rl")
        try:
            pub.publish(_tree())
            relay.sync_once()
            for i in range(3):
                relay.node.renew_lease(f"s{i}", 10_000, 1)
            relay._lease_due = 0.0
            relay._renew_upstream_lease()
            st = pub.node.status()
            assert st["leases"] == 1
            assert st["subscribers"] == 3
        finally:
            relay.shutdown()
            pub.shutdown()

    def test_subscriber_renews_and_drops_lease(self):
        pub = WeightPublisher(wire="f32")
        try:
            pub.publish(_tree())
            sub = WeightSubscriber(
                pub.server.local_address(), name="ld", lease_ttl_ms=10_000
            )
            sub.poll()
            assert pub.node.lease_totals() == (1, 1)
            sub.close()  # releases via a 1ms renewal with weight 0
            assert _wait_until(
                lambda: pub.node.lease_totals() == (0, 0), 5.0
            )
        finally:
            pub.shutdown()


# -- two-tier end-to-end -----------------------------------------------------


def test_two_tier_fanout_end_to_end():
    pub = WeightPublisher(wire="q8", snapshot_every=4)
    r1 = WeightRelay(pub.server.local_address(), name="t1").start()
    r2 = WeightRelay(r1.server.local_address(), name="t2").start()
    subs = []
    try:
        subs = [
            WeightSubscriber(r2.server.local_address(), name=f"e{i}").start(
                poll_ms=150
            )
            for i in range(3)
        ]
        for v in range(6):
            pub.publish(_tree(version=v))
        assert _wait_until(
            lambda: all(s.version() == 5 for s in subs), 20.0
        )
        want = pub.node.store.get(5).manifest["digest"]
        for s in subs:
            _, tree, _ = s.current()
            assert tree_digest(tree) == want
            assert s.stats["torn_installs"] == 0
    finally:
        for s in subs:
            s.close()
        r2.shutdown()
        r1.shutdown()
        pub.shutdown()
