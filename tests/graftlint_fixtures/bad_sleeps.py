# graftlint fixture: deadline-less sleep-poll loops (and bounded
# controls that must NOT be flagged).
import time


def wait_forever(server):
    # Violation: no visible deadline.
    while not server.ready():
        time.sleep(0.1)


def wait_bounded_by_clock(server):
    # Clean: compares against time.monotonic().
    deadline = time.monotonic() + 5.0
    while not server.ready():
        if time.monotonic() > deadline:
            raise TimeoutError("server never became ready")
        time.sleep(0.05)


def wait_bounded_by_range(server):
    # Clean: for-range loops are inherently bounded.
    for _ in range(100):
        if server.ready():
            return
        time.sleep(0.05)
    raise TimeoutError("server never became ready")
