"""Lightweight step-level metrics for the fault-tolerance runtime.

The reference's only progress metric is ``batches_committed``
(reference torchft/manager.py:642-653); observability is otherwise logs +
the dashboard. This module closes the SURVEY.md §5 tracing gap with
in-process counters/timers the Manager feeds at the transaction's
boundaries — no external dependencies, negligible overhead (a deque append
per event), and a one-call JSON-able snapshot for progress loops,
dashboards, or tests::

    manager.metrics().snapshot()
    # {"counters": {"commits": 98, "aborts": 2, "heals": 1, ...},
    #  "timers_s": {"quorum": {"n":100,"p50":0.0012,"p90":0.003,...}, ...}}
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict


class _Timer:
    """Bounded reservoir of durations with percentile snapshots."""

    def __init__(self, maxlen: int = 512) -> None:
        self._samples: deque = deque(maxlen=maxlen)
        self.count = 0
        self.total_s = 0.0

    def record(self, seconds: float) -> None:
        self._samples.append(seconds)
        self.count += 1
        self.total_s += seconds

    def snapshot(self) -> Dict[str, float]:
        samples = sorted(self._samples)
        if not samples:
            return {"n": 0}

        def pct(p: float) -> float:
            return samples[min(int(p * len(samples)), len(samples) - 1)]

        return {
            "n": self.count,
            "total_s": round(self.total_s, 6),
            "p50": round(pct(0.50), 6),
            "p90": round(pct(0.90), 6),
            "max": round(samples[-1], 6),
        }


class Metrics:
    """Thread-safe counters + timers. All methods are cheap enough for the
    hot path; reading is lock-held but O(window)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._timers: Dict[str, _Timer] = {}

    def incr(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] += by

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            timer = self._timers.get(name)
            if timer is None:
                timer = self._timers[name] = _Timer()
            timer.record(seconds)

    def timed(self, name: str) -> "_TimedBlock":
        return _TimedBlock(self, name)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "timers_s": {
                    name: t.snapshot() for name, t in self._timers.items()
                },
            }


class _TimedBlock:
    def __init__(self, metrics: Metrics, name: str) -> None:
        self._metrics = metrics
        self._name = name

    def __enter__(self) -> "_TimedBlock":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: object) -> None:
        self._metrics.record(self._name, time.perf_counter() - self._t0)
