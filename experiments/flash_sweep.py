"""TPU experiment: attention fwd+bwd at the big bench shape — dense vs our
flash (block sweep) vs jax's built-in pallas flash; then whole-model check.
Run ALONE on the chip (memory: concurrent TPU work wrecks timings)."""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

B, S, H, D = 4, 2048, 16, 64
MODE = os.environ.get("EXP_MODE", "attn")  # attn | model


def drain(x):
    jax.block_until_ready(x)
    np.asarray(jax.tree_util.tree_leaves(x)[0].ravel()[0:1])


def bench(fn, args, warm=2, iters=8, label=""):
    for _ in range(warm):
        out = fn(*args)
    drain(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    drain(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{label:40s} {dt*1000:8.2f} ms", flush=True)
    return dt


def main():
    assert jax.devices()[0].platform == "tpu", "needs the real chip"
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, S, H, D), jnp.bfloat16)

    if MODE == "attn":
        def dense(q, k, v):
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
            causal = jnp.tril(jnp.ones((S, S), jnp.bool_))
            scores = jnp.where(causal, scores, jnp.finfo(scores.dtype).min)
            probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

        def fwdbwd(attn_fn):
            def loss(q, k, v):
                return jnp.sum(attn_fn(q, k, v).astype(jnp.float32)) / (B * S * H * D)
            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        bench(fwdbwd(dense), (q, k, v), label="dense")

        from torchft_tpu.ops import flash_attention
        for bq, bk in [(128, 128), (256, 256), (512, 512), (256, 512),
                       (512, 256), (128, 512), (512, 128), (1024, 512),
                       (512, 1024), (1024, 1024)]:
            fn = functools.partial(
                flash_attention, causal=True, block_q=bq, block_k=bk
            )
            try:
                bench(fwdbwd(fn), (q, k, v), label=f"ours bq={bq} bk={bk}")
            except Exception as e:
                print(f"ours bq={bq} bk={bk}: FAIL {type(e).__name__}: {str(e)[:120]}",
                      flush=True)

        # builtin flash wants (B, H, S, D)
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jflash,
        )
        qt = q.transpose(0, 2, 1, 3)
        kt = k.transpose(0, 2, 1, 3)
        vt = v.transpose(0, 2, 1, 3)

        def builtin(qt, kt, vt):
            return jflash(qt, kt, vt, causal=True, sm_scale=D ** -0.5)

        def fwdbwd_t(fn):
            def loss(a, b, c):
                return jnp.sum(fn(a, b, c).astype(jnp.float32)) / (B * S * H * D)
            return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        try:
            bench(fwdbwd_t(builtin), (qt, kt, vt), label="jax builtin flash")
        except Exception as e:
            print(f"builtin: FAIL {type(e).__name__}: {str(e)[:200]}", flush=True)
    else:
        # whole-model comparison at the big config: batch x attention sweep
        import optax
        from torchft_tpu.models import TransformerConfig, init_params, loss_fn

        rng = np.random.default_rng(0)
        tx = optax.adamw(1e-3)
        variants = [
            ("dense_B4", 4, {}),
            ("flash_B4", 4, {"use_flash": True}),
            ("dense_B8", 8, {}),
            ("flash_B8", 8, {"use_flash": True}),
            ("dense_B16", 16, {}),
            ("flash_B16", 16, {"use_flash": True}),
        ]
        only = os.environ.get("EXP_ONLY")
        for name, bsz, kw in variants:
            if only and only not in name:
                continue
            batch = jnp.asarray(
                rng.integers(0, 8192, size=(bsz, 2048), dtype=np.int32)
            )
            cfg = TransformerConfig(
                vocab_size=8192, d_model=1024, n_heads=16, n_layers=8,
                d_ff=4096, max_seq_len=2048, **kw,
            )
            n_params = None
            try:
                params = init_params(cfg, jax.random.PRNGKey(0))
                n_params = sum(
                    int(np.prod(l.shape))
                    for l in jax.tree_util.tree_leaves(params)
                )
                opt_state = tx.init(params)
                grad_fn = jax.jit(
                    jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b))
                )
                apply_jit = jax.jit(
                    lambda p, o, g: (
                        lambda u, no: (optax.apply_updates(p, u), no)
                    )(*tx.update(g, o, p)),
                    donate_argnums=(0, 1),
                )

                def step(params, opt_state):
                    loss, grads = grad_fn(params, batch)
                    return apply_jit(params, opt_state, grads)

                for _ in range(2):
                    params, opt_state = step(params, opt_state)
                drain(params)
                t0 = time.perf_counter()
                N = 8
                for _ in range(N):
                    params, opt_state = step(params, opt_state)
                drain(params)
                sps = N / (time.perf_counter() - t0)
                tflops = 6 * n_params * batch.size * sps / 1e12
                print(
                    f"model {name:12s} {sps:6.3f} steps/s "
                    f"{tflops:6.1f} param-TFLOP/s",
                    flush=True,
                )
                del params, opt_state
            except Exception as e:
                print(f"model {name}: FAIL {type(e).__name__}: {str(e)[:150]}",
                      flush=True)


if __name__ == "__main__":
    main()
