"""Benchmark: fault-tolerant training throughput on the flagship model.

Measures steps/sec of the FULL fault-tolerance path (async quorum +
fault-tolerant gradient allreduce + distributed commit vote, every step)
against a raw jitted train loop on the same model and hardware.

The reference publishes no absolute numbers (BASELINE.md); the driver-set
north star is >= 90% of healthy-state throughput under churn. This bench
reports the no-churn FT overhead — the upper bound of that ratio:
``vs_baseline = (ft_steps_per_sec / raw_steps_per_sec) / 0.90``, so 1.0
means exactly the 90% target and > 1.0 beats it.

Prints ONE JSON line, e.g.:
{"metric": "steps_per_sec_ft", "value": 12.3, "unit": "steps/s", "vs_baseline": 1.07}
"""

import json
import os
import sys
import time
from datetime import timedelta

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from torchft_tpu import (
        FTTrainState,
        HostCollectives,
        Lighthouse,
        Manager,
        OptimizerWrapper,
    )
    from torchft_tpu.models import TransformerConfig, init_params, loss_fn

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = TransformerConfig(
        vocab_size=8192,
        d_model=512,
        n_heads=8,
        n_layers=6 if on_tpu else 2,
        d_ff=2048,
        max_seq_len=512,
    )
    batch_size = 16 if on_tpu else 4
    seq_len = 512 if on_tpu else 128
    warmup, steps = 5, 30 if on_tpu else 15

    rng = np.random.default_rng(0)
    batch = jnp.asarray(
        rng.integers(0, cfg.vocab_size, size=(batch_size, seq_len), dtype=np.int32)
    )

    def barrier(tree) -> None:
        # Readback barrier: on the axon-tunneled TPU, block_until_ready
        # returns before remote execution drains, so force a (tiny) device
        # read to fence the timing.
        jax.block_until_ready(tree)
        leaf = jax.tree_util.tree_leaves(tree)[0]
        np.asarray(leaf.ravel()[0:1])
    tx = optax.adamw(1e-3)
    grad_fn = jax.jit(jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b)))

    def apply_fn_raw(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    apply_jit = jax.jit(apply_fn_raw, donate_argnums=(0, 1))

    # -- raw loop --
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt_state = tx.init(params)
    for _ in range(warmup):
        loss, grads = grad_fn(params, batch)
        params, opt_state = apply_jit(params, opt_state, grads)
    barrier(params)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, grads = grad_fn(params, batch)
        params, opt_state = apply_jit(params, opt_state, grads)
    barrier(params)
    raw_sps = steps / (time.perf_counter() - t0)

    # -- fault-tolerant loop (full machinery, single replica group) --
    lighthouse = Lighthouse(bind="[::]:0", min_replicas=1, join_timeout_ms=100)
    state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), tx)
    collectives = HostCollectives(timeout=timedelta(seconds=30))
    manager = Manager(
        collectives=collectives,
        load_state_dict=state.load_state_dict,
        state_dict=state.state_dict,
        min_replica_size=1,
        rank=0,
        world_size=1,
        lighthouse_addr=lighthouse.address(),
        replica_id="bench",
    )
    optimizer = OptimizerWrapper(manager, state)

    def ft_step():
        optimizer.zero_grad()
        loss, grads = grad_fn(state.params, batch)
        avg = manager.allreduce(grads).wait()
        optimizer.step(avg)

    for _ in range(warmup):
        ft_step()
    barrier(state.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        ft_step()
    barrier(state.params)
    ft_sps = steps / (time.perf_counter() - t0)

    manager.shutdown()
    collectives.shutdown()
    lighthouse.shutdown()

    print(
        json.dumps(
            {
                "metric": "steps_per_sec_ft",
                "value": round(ft_sps, 3),
                "unit": "steps/s",
                "vs_baseline": round((ft_sps / raw_sps) / 0.90, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
