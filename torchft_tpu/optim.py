"""Optimizer wrapper binding optax updates to the commit protocol.

Reference: torchft/optim.py — ``zero_grad()`` starts the quorum,
``step()`` applies the update only if the distributed commit vote passes.
State lives in an :class:`~torchft_tpu.train_state.FTTrainState` so a heal
applied at the ``should_commit`` safe point is visible to the very update
that follows it (the reference gets this from torch's in-place
``load_state_dict``; immutable jax pytrees need the holder).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .manager import Manager
from .train_state import FTTrainState


class OptimizerWrapper:
    """Quorum + commit gating around an optax optimizer.

    Canonical loop (reference train_ddp.py:119-152 shape)::

        state = FTTrainState(params, optax.adamw(1e-3))
        manager = Manager(..., state_dict=state.state_dict,
                          load_state_dict=state.load_state_dict)
        optimizer = OptimizerWrapper(manager, state)
        for step in ...:
            optimizer.zero_grad()                  # starts async quorum
            grads = grad_fn(state.params, batch)
            avg = manager.allreduce(grads).wait()  # fault-tolerant average
            optimizer.step(avg)                    # applies iff committed
    """

    def __init__(self, manager: Manager, state: FTTrainState) -> None:
        self.manager = manager
        self.state = state

    def zero_grad(self) -> None:
        """Starts the (async) quorum for this step. Name kept for parity
        with the reference API (optim.py:48-50)."""
        self.manager.start_quorum()

    def step(self, grads: Any) -> bool:
        """Votes, then applies ``grads`` iff every rank committed (reference
        optim.py:52-54). ``should_commit`` applies any pending recovery
        checkpoint into ``self.state`` first, so the update always starts
        from the healed weights. Returns whether the step committed."""
        if not self.manager.should_commit():
            return False
        self.state.apply_gradients(grads)
        return True


class ShardedOptimizerWrapper:
    """The :class:`OptimizerWrapper` loop shape over the per-step ZeRO
    engine: ``zero_grad()`` starts the quorum, ``step(grads)`` runs the
    whole sharded transaction — reduce-scatter, ~1/W shard-local
    optimizer update, param allgather, commit vote — instead of the
    fused allreduce + full-size update. Drop-in where the canonical loop
    computes raw (un-averaged) gradients::

        state = FTTrainState(params, optax.adamw(1e-3), opt_state=())
        optimizer = ShardedOptimizerWrapper(manager, state,
                                            shard_wire="q8")
        for step in ...:
            optimizer.zero_grad()                 # starts async quorum
            loss, grads = grad_fn(state.params, batch)
            optimizer.step(grads)                 # rs -> update -> ag

    Note the contract difference from :class:`OptimizerWrapper`: pass
    RAW gradients (the reduce-scatter averages them); there is no
    separate ``manager.allreduce`` call. Construct the train state with
    ``opt_state=()`` so no full-size optimizer state is ever allocated,
    and wire the manager's state callbacks to :meth:`state_dict` /
    :meth:`load_state_dict` so heals carry the optimizer shard."""

    def __init__(
        self,
        manager: Manager,
        state: FTTrainState,
        shard_wire: Optional[str] = None,
        param_wire: Optional[str] = "auto",
    ) -> None:
        from .ddp import ShardedDDP

        self.manager = manager
        self.state = state
        self._core = ShardedDDP(
            manager, state, grad_fn=None,
            shard_wire=shard_wire, param_wire=param_wire,
        )

    def zero_grad(self) -> None:
        """Starts the (async) quorum for this step."""
        self.manager.start_quorum()

    def step(self, grads: Any) -> bool:
        """Runs the sharded transaction for ``grads``; applies iff the
        cohort committed. Returns whether it did."""
        return self._core.apply_gradients(grads)

    @property
    def last_commit(self) -> Optional[bool]:
        return self._core.last_commit

    def opt_state_bytes(self) -> int:
        """Resident bytes of this replica's optimizer-state shard."""
        return self._core.opt_state_bytes()

    def state_dict(self) -> Dict[str, Any]:
        return self._core.state_dict()

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self._core.load_state_dict(sd)
